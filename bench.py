"""Benchmark driver: prints ONE JSON line with the headline metric.

Current benchmark: training throughput (images/sec) of the flagship image
model on the available device(s).  vs_baseline compares against the
reference's story: it publishes no absolute numbers (BASELINE.md), so
vs_baseline is reported as 1.0 when we complete the run at all, scaled by
nothing — the real comparison lands once ResNet-50/ImageNet is wired.
"""

import json
import time

import numpy as np


def main():
    import jax

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Convolution2D,
        Dense,
        Flatten,
        MaxPooling2D,
    )

    ctx = init_zoo_context(seed=0)
    model = Sequential()
    model.add(Convolution2D(32, 3, 3, activation="relu",
                            input_shape=(28, 28, 1)))
    model.add(MaxPooling2D())
    model.add(Convolution2D(64, 3, 3, activation="relu"))
    model.add(MaxPooling2D())
    model.add(Flatten())
    model.add(Dense(128, activation="relu"))
    model.add(Dense(10, activation="softmax"))
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")

    batch = 256 * max(ctx.data_parallel_size, 1)
    n = batch * 8
    x = np.random.default_rng(0).normal(size=(n, 28, 28, 1)).astype(
        np.float32)
    y = np.random.default_rng(1).integers(0, 10, size=(n,)).astype(np.int32)

    # warmup (compile)
    model.fit(x[:batch * 2], y[:batch * 2], batch_size=batch, nb_epoch=1)
    t0 = time.perf_counter()
    model.fit(x, y, batch_size=batch, nb_epoch=2)
    dt = time.perf_counter() - t0
    images = 2 * n
    ips = images / dt
    print(json.dumps({
        "metric": "mnist_convnet_train_images_per_sec",
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    main()
