"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline (BASELINE.md): ResNet-50 ImageNet training throughput,
images/sec/chip.  The reference publishes no absolute numbers (its story is
scaling factors on Xeon clusters, docs/docs/wp-bigdl.md); the BASELINE.json
north star is ">= A100-class images/sec/chip".  vs_baseline is therefore
reported against a 2500 img/s A100 figure (public MLPerf-era ResNet-50
mixed-precision single-A100 training throughput ballpark).

TPU backend init in this image is flaky (the axon plugin can hang or raise
UNAVAILABLE — BENCH_r01.json).  The harness therefore probes backend init in
a SUBPROCESS with a hard timeout, retries with backoff, and only then
initialises jax in-process on the platform the probe proved alive.  On final
TPU failure it falls back to a CPU run so a number always lands, with the
failure diagnostics embedded in the JSON line.
"""

import json
import os
import subprocess
import sys
import time

A100_IMAGES_PER_SEC = 2500.0

# ResNet-50 training FLOPs per image at 224x224: ~4.09 GFLOP forward,
# ~3x forward for fwd+bwd (standard accounting).
RESNET50_TRAIN_FLOPS_PER_IMAGE = 3 * 4.09e9

# Peak bf16 matmul FLOP/s per chip by device_kind substring (public specs).
TPU_PEAK_FLOPS = {
    "v6": 918e12,  # Trillium
    "v5p": 459e12,
    "v5e": 197e12,
    "v5": 459e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}

PROBE_CODE = "import jax; d = jax.devices(); print(d[0].platform, len(d))"


def probe_backend(timeout: float) -> tuple[bool, str]:
    """Try `jax.devices()` in a subprocess with a hard timeout.

    Returns (ok, detail).  A subprocess is the only reliable guard: the axon
    plugin can hang inside C++ without releasing the GIL, so an in-process
    watchdog thread could detect but never cancel it.
    """
    try:
        r = subprocess.run(
            [sys.executable, "-c", PROBE_CODE],
            capture_output=True, text=True, timeout=timeout,
            env=dict(os.environ),
        )
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout:.0f}s"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return False, (tail[-1] if tail else f"probe rc={r.returncode}")
    return True, r.stdout.strip()


def resolve_platform(attempts: int = 3, timeout: float = 150.0):
    """Probe TPU init with retry+backoff; fall back to CPU.

    Returns (platform, diagnostics list).
    """
    diags = []
    for i in range(attempts):
        ok, detail = probe_backend(timeout)
        if ok:
            diags.append(f"attempt {i + 1}: ok ({detail})")
            return detail.split()[0], diags
        diags.append(f"attempt {i + 1}: {detail}")
        time.sleep(min(10.0 * (2 ** i), 60.0))
    return "cpu", diags


def peak_flops_for(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for key, val in TPU_PEAK_FLOPS.items():
        if key in kind:
            return val
    return None


def main():
    platform, diags = resolve_platform()
    fell_back = platform == "cpu"
    if fell_back:
        # Force-CPU the same way the test harness does; the axon plugin
        # ignores JAX_PLATFORMS, only the config knob is honored.
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if fell_back:
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.resnet import ResNet

    ctx = init_zoo_context(seed=0)
    on_tpu = ctx.platform == "tpu"
    # CPU fallback: shrink so a diagnostic number lands in minutes.
    img = 224 if on_tpu else 64
    per_chip_batch = 256 if on_tpu else 16
    steps = 30 if on_tpu else 5
    model = ResNet.image_net(50, classes=1000, input_shape=(img, img, 3))
    model.compile(
        optimizer=ResNet.imagenet_optimizer(
            batch_size=per_chip_batch, steps_per_epoch=100),
        loss="sparse_categorical_crossentropy",
    )

    batch = per_chip_batch * max(ctx.data_parallel_size, 1)
    n = batch * steps
    x = np.random.default_rng(0).normal(size=(n, img, img, 3)).astype(
        np.float32)
    y = np.random.default_rng(1).integers(0, 1000, size=(n,)).astype(
        np.int32)

    # warmup (includes compile)
    model.fit(x[:batch * 2], y[:batch * 2], batch_size=batch, nb_epoch=1)
    t0 = time.perf_counter()
    model.fit(x, y, batch_size=batch, nb_epoch=1)
    dt = time.perf_counter() - t0
    ips = n / dt
    per_chip = ips / max(ctx.data_parallel_size, 1)

    out = {
        "metric": "resnet50_imagenet_train_images_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / A100_IMAGES_PER_SEC, 3),
        "platform": ctx.platform,
        "devices": ctx.num_devices,
        "per_chip_batch": per_chip_batch,
        "image_size": img,
        "steps_timed": steps,
    }
    if on_tpu:
        peak = peak_flops_for(jax.devices()[0].device_kind)
        if peak:
            out["mfu"] = round(
                per_chip * RESNET50_TRAIN_FLOPS_PER_IMAGE / peak, 4)
            out["device_kind"] = jax.devices()[0].device_kind
    if fell_back:
        out["note"] = "TPU backend unavailable; CPU fallback at reduced size"
        out["tpu_init_diagnostics"] = diags
    print(json.dumps(out))


if __name__ == "__main__":
    main()
