"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline (BASELINE.md): ResNet-50 ImageNet training throughput,
images/sec/chip.  The reference publishes no absolute numbers (its story is
scaling factors on Xeon clusters, docs/docs/wp-bigdl.md); the BASELINE.json
north star is ">= A100-class images/sec/chip", so vs_baseline is reported
against a 2500 img/s A100 figure.

The measurement itself lives in examples/resnet/train_imagenet.run() — the
example IS the bench (the role of the reference's Perf.scala harness,
examples/vnni/bigdl/Perf.scala:53-66).  It reports the end-to-end number
AND the decomposition the end-to-end number hides:

- value / *_e2e: wall-clock fit() throughput (host batch assembly + uint8
  H2D infeed + compiled step);
- pure_step_*: the jitted train step on a device-resident batch — the
  framework's compute celling;
- infeed_fraction: how much of e2e the infeed fails to hide.  On this
  harness's tunneled TPU the host→device link measures ~27-35 MB/s (vs tens
  of GB/s on a real TPU VM; see PROFILE_r03/ANALYSIS.md), so infeed
  dominates e2e here; pure_step is the portable number.
- compiles_timed: XLA compilations during the timed epoch (0 = no
  per-step retracing).

TPU backend init in this image is flaky (the axon plugin can hang or raise
UNAVAILABLE — BENCH_r01.json).  The harness probes backend init in a
SUBPROCESS with a hard timeout, retries with backoff, and only then
initialises jax in-process.  On final TPU failure it falls back to a CPU run
so a number always lands, with the diagnostics embedded in the JSON line.
"""

import json
import os
import subprocess
import sys
import time

A100_IMAGES_PER_SEC = 2500.0

# ResNet-50 training FLOPs per image at 224x224: ~4.09 GFLOP forward,
# ~3x forward for fwd+bwd (standard accounting).
RESNET50_TRAIN_FLOPS_PER_IMAGE = 3 * 4.09e9

# Peak bf16 matmul FLOP/s per chip by device_kind substring (public specs).
# Ordered most-specific first: "TPU v5 lite" (the v5e device_kind string)
# must match the 197 TF v5e entry, never the 459 TF v5p one.
TPU_PEAK_FLOPS = (
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6", 918e12),  # Trillium
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

PROBE_CODE = "import jax; d = jax.devices(); print(d[0].platform, len(d))"


# ---------------------------------------------------------------------------
# --data-pipeline: host data-plane bench (feature/prefetch.py).  No jax —
# it measures the HOST side: serial FeatureSet.batches() vs the parallel
# prefetch pipeline on a synthetic loader/transform whose cost is pure
# sleep (IO-shaped: releases the GIL, like real file reads and cv2).
# Emits BENCH_DATA_*.json so the gain is pinned, not asserted.
# ---------------------------------------------------------------------------

def _sleepy_loader(load_sleep_s: float, shard_records: int, feat: int = 16):
    import numpy as np

    def load(path: str) -> dict:
        i = int(path.rsplit("-", 1)[-1])
        time.sleep(load_sleep_s)
        rng = np.random.default_rng(1234 + i)
        return {
            "x": rng.standard_normal((shard_records, feat))
                    .astype("float32"),
            "y": rng.integers(0, 10, size=(shard_records,))
                    .astype("int32"),
        }

    return load


def data_pipeline_bench(workers: int = 4, depth: int = 8,
                        n_shards: int = 6, shard_records: int = 64,
                        batch_size: int = 16,
                        load_sleep_ms: float = 40.0,
                        transform_sleep_ms: float = 2.0,
                        seed: int = 7, out_path: str | None = None) -> dict:
    """Serial vs prefetched host-pipeline throughput + wait breakdown.

    The synthetic loader sleeps per shard (disk/decode IO) and the
    per-record transform sleeps per record (host preprocessing), so the
    measured speedup isolates the pipeline machinery from numpy noise.
    Also verifies the determinism contract: the prefetched stream must be
    byte-identical to the serial one for the same seed/epoch.
    """
    import numpy as np

    from analytics_zoo_tpu.feature.common import FnPreprocessing
    from analytics_zoo_tpu.feature.dataset import ShardedFeatureSet
    from analytics_zoo_tpu.feature.prefetch import PrefetchFeatureSet
    from analytics_zoo_tpu.metrics import (
        DataPipelineMetrics,
        MetricsRegistry,
        snapshot,
    )

    t_sleep = transform_sleep_ms / 1e3
    paths = [f"synth://shard-{i}" for i in range(n_shards)]
    base = ShardedFeatureSet(
        paths, n_slices=n_shards,
        loader=_sleepy_loader(load_sleep_ms / 1e3, shard_records),
        sizer=lambda p: shard_records)

    def slow_identity(record):
        time.sleep(t_sleep)
        return record

    fs = base.transform(FnPreprocessing(slow_identity))

    def drain(feature_set):
        """Iterate one epoch; returns (batches, wall_s, waits list)."""
        out, waits = [], []
        it = feature_set.batches(batch_size, shuffle=True, seed=seed,
                                 epoch=0)
        t_start = time.perf_counter()
        while True:
            t0 = time.perf_counter()
            batch = next(it, None)
            if batch is None:
                break
            waits.append(time.perf_counter() - t0)
            out.append(batch)
        return out, time.perf_counter() - t_start, waits

    def pcts(waits):
        return {"p50": round(float(np.percentile(waits, 50)), 6),
                "p99": round(float(np.percentile(waits, 99)), 6)}

    serial_batches, serial_s, serial_waits = drain(fs)
    # fresh registry so the artifact's zoo_data_prefetch_* series cover
    # exactly this run (the process-global one may hold training noise)
    reg = MetricsRegistry(enabled=True)
    pre = PrefetchFeatureSet(fs, depth=depth, workers=workers,
                             metrics=DataPipelineMetrics(registry=reg))
    pre_batches, pre_s, pre_waits = drain(pre)

    def batch_equal(a, b):
        if set(a) != set(b):
            return False
        return all(np.array_equal(a[k], b[k]) for k in a)

    deterministic = len(serial_batches) == len(pre_batches) and all(
        batch_equal(a, b) for a, b in zip(serial_batches, pre_batches))

    n_batches = len(serial_batches)
    prefetch_series = {}
    for s in snapshot(reg)["samples"]:
        if s["name"].startswith("zoo_data_prefetch") \
                and s.get("kind") == "histogram":
            prefetch_series[s["name"]] = {
                k: round(float(s[k]), 6)
                for k in ("count", "p50", "p99") if k in s}
    doc = {
        "metric": "data_pipeline_host_throughput",
        "unit": "batches/sec",
        "serial_batches_per_sec": round(n_batches / max(serial_s, 1e-9), 2),
        "prefetched_batches_per_sec": round(
            n_batches / max(pre_s, 1e-9), 2),
        "speedup": round(serial_s / max(pre_s, 1e-9), 3),
        "deterministic": bool(deterministic),
        "batches": n_batches,
        "workers": workers, "depth": depth, "batch_size": batch_size,
        "n_shards": n_shards, "shard_records": shard_records,
        "load_sleep_ms": load_sleep_ms,
        "transform_sleep_ms": transform_sleep_ms,
        # the fit-loop data_wait analogue: time the consumer blocked per
        # next() — what zoo_train_data_wait_seconds would see
        "consumer_wait_s": {"serial": pcts(serial_waits),
                            "prefetched": pcts(pre_waits)},
        "prefetch_metrics": prefetch_series,
    }
    doc["host_fingerprint"] = host_fingerprint()
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_DATA_r06.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    doc["artifact"] = out_path
    return doc


# ---------------------------------------------------------------------------
# --autotune: closed-loop autotuning bench (feature/autotune.py).  Both
# synthetics start from the WORST-CASE defaults (workers=1, depth=1, K=1)
# and must converge to >= 0.9x the best hand-tuned throughput from
# BENCH_DATA_r06 (workers=4, depth=8) / BENCH_DISPATCH_r07 (K=16), with
# the stream byte-identical under resizing and the loss trajectory
# bit-identical to the fixed-K run.  Emits BENCH_AUTOTUNE_r08.json.
# ---------------------------------------------------------------------------

def autotune_data_plane_bench(quick: bool = False) -> dict:
    """Sleep-bound host-pipeline synthetic (the BENCH_DATA_r06 shape):
    serial vs untuned-default (1,1) vs hand-tuned (4,8) vs the
    controller starting at (1,1).  Returns the data_plane section."""
    import numpy as np

    from analytics_zoo_tpu.feature.autotune import AutotuneController
    from analytics_zoo_tpu.feature.common import FnPreprocessing
    from analytics_zoo_tpu.feature.dataset import ShardedFeatureSet
    from analytics_zoo_tpu.feature.prefetch import PrefetchFeatureSet

    if quick:
        cfg = dict(n_shards=4, shard_records=32, batch_size=8,
                   load_sleep_ms=15.0, transform_sleep_ms=1.0)
        epochs, interval = 5, 0.04
    else:
        cfg = dict(n_shards=6, shard_records=64, batch_size=16,
                   load_sleep_ms=40.0, transform_sleep_ms=2.0)
        epochs, interval = 6, 0.1
    seed = 7
    t_sleep = cfg["transform_sleep_ms"] / 1e3
    base = ShardedFeatureSet(
        [f"synth://shard-{i}" for i in range(cfg["n_shards"])],
        n_slices=cfg["n_shards"],
        loader=_sleepy_loader(cfg["load_sleep_ms"] / 1e3,
                              cfg["shard_records"]),
        sizer=lambda p: cfg["shard_records"])

    def slow_identity(record):
        time.sleep(t_sleep)
        return record

    fs = base.transform(FnPreprocessing(slow_identity))

    def drain(feature_set, epoch):
        t0 = time.perf_counter()
        out = list(feature_set.batches(cfg["batch_size"], shuffle=True,
                                       seed=seed, epoch=epoch))
        return out, time.perf_counter() - t0

    def bps(n, s):
        return round(n / max(s, 1e-9), 2)

    serial = [drain(fs, e)[0] for e in range(epochs)]
    n_batches = len(serial[0])
    _, untuned_s = drain(PrefetchFeatureSet(fs, depth=1, workers=1), 0)
    _, hand_s = drain(PrefetchFeatureSet(fs, depth=8, workers=4), 0)

    ctrl = AutotuneController(interval=interval, min_window=4)
    pre = PrefetchFeatureSet(fs, depth=1, workers=1, controller=ctrl)
    epoch_bps, deterministic = [], True
    for e in range(epochs):
        got, dt = drain(pre, e)
        epoch_bps.append(bps(len(got), dt))
        deterministic = deterministic and len(got) == len(serial[e]) \
            and all(set(a) == set(b)
                    and all(np.array_equal(a[k], b[k]) for k in a)
                    for a, b in zip(serial[e], got))
    ctrl.stop()
    final_bps = epoch_bps[-1]
    cur = ctrl.current()
    return {
        "synthetic": cfg,
        "epochs": epochs,
        "batches_per_epoch": n_batches,
        "untuned_default_batches_per_sec": bps(n_batches, untuned_s),
        "hand_tuned_batches_per_sec": bps(n_batches, hand_s),
        "autotuned_epoch_batches_per_sec": epoch_bps,
        "autotuned_final_batches_per_sec": final_bps,
        "vs_hand_tuned": round(final_bps * hand_s / n_batches, 3),
        "vs_untuned_default": round(final_bps * untuned_s / n_batches, 3),
        "deterministic_under_resizing": bool(deterministic),
        "converged": {k: cur[k] for k in
                      ("workers", "depth", "read_ahead")},
        "hand_tuned_config": {"workers": 4, "depth": 8},
        "decisions": [
            {k: d[k] for k in ("knob", "old", "new", "reason")}
            for d in ctrl.decision_log()],
    }


def autotune_dispatch_bench(quick: bool = False) -> dict:
    """Dispatch-bound synthetic (the BENCH_DISPATCH_r07 shape): fixed
    K=1 (untuned default) and K=16 (hand-tuned) vs the controller's
    hill-climb starting at K=1.  Returns the dispatch section."""
    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.common.engine import ZooConfig
    from analytics_zoo_tpu.feature.autotune import AutotuneController

    n_batches = 192 if quick else 384
    batch_size = 16
    warm_epochs = 2  # the climb's ladder (~250-350 steps) lives here
    x, y = _dispatch_data(n_batches * batch_size)

    def fixed(k):
        zoo.init_zoo_context(ZooConfig(seed=11, steps_per_dispatch=k))
        m = _dispatch_model()
        # warm epochs match the autotuned leg so the trajectory
        # comparison covers the same step count
        m.fit(x, y, batch_size=batch_size, nb_epoch=warm_epochs)
        t0 = time.perf_counter()
        m.fit(x, y, batch_size=batch_size, nb_epoch=1)
        dt = time.perf_counter() - t0
        return (round(n_batches / dt, 1),
                [h["loss"] for h in m._estimator.history])

    k1_sps, k1_losses = fixed(1)
    k16_sps, _ = fixed(16)

    zoo.init_zoo_context(ZooConfig(seed=11))
    ctrl = AutotuneController()
    m = _dispatch_model()
    # warm epochs host the hill-climb (each K's first dispatch pays its
    # compile); the final epoch is the timed steady state at settled K
    m.fit(x, y, batch_size=batch_size, nb_epoch=warm_epochs,
          autotune=ctrl)
    t0 = time.perf_counter()
    m.fit(x, y, batch_size=batch_size, nb_epoch=1, autotune=ctrl)
    dt = time.perf_counter() - t0
    ctrl.stop()
    auto_losses = [h["loss"] for h in m._estimator.history]
    auto_sps = round(n_batches / dt, 1)
    cur = ctrl.current()
    return {
        "steps_per_epoch": n_batches,
        "batch_size": batch_size,
        "untuned_default_steps_per_sec": k1_sps,
        "hand_tuned_k16_steps_per_sec": k16_sps,
        "autotuned_steady_steps_per_sec": auto_sps,
        "vs_hand_tuned": round(auto_sps / max(k16_sps, 1e-9), 3),
        "vs_untuned_default": round(auto_sps / max(k1_sps, 1e-9), 3),
        "converged_k": cur["k"],
        "k_settled": cur["k_settled"],
        "k_cost_per_step_s": cur["k_cost_per_step_s"],
        "dispatches_to_converge": cur["k_settle_dispatch"],
        "loss_trajectory_bitwise_equal_to_k1": auto_losses == k1_losses,
        "decisions": [
            {k: d[k] for k in ("knob", "old", "new", "reason")}
            for d in ctrl.decision_log()],
    }


def autotune_bench(quick: bool = False, out_path: str | None = None) -> dict:
    """Both autotune synthetics; writes BENCH_AUTOTUNE_r08.json."""
    doc = {
        "metric": "autotune_convergence_vs_hand_tuned",
        "unit": "throughput ratio",
        "platform": "cpu",
        "quick": bool(quick),
        "data_plane": autotune_data_plane_bench(quick=quick),
        "dispatch": autotune_dispatch_bench(quick=quick),
    }
    doc["value"] = min(doc["data_plane"]["vs_hand_tuned"],
                       doc["dispatch"]["vs_hand_tuned"])
    doc["host_fingerprint"] = host_fingerprint()
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_AUTOTUNE_r08.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    doc["artifact"] = out_path
    return doc


def _autotune_main(argv):
    # host/dispatch overhead bench: the CPU backend is the point
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    kwargs = {}
    if "--quick" in argv:
        kwargs["quick"] = True
    if "--out" in argv:
        kwargs["out_path"] = argv[argv.index("--out") + 1]
    print(json.dumps(autotune_bench(**kwargs)))


# ---------------------------------------------------------------------------
# --partition: unified-partitioner bench (parallel/plan.py).  Replicated
# data parallelism vs the fsdp plan (params + optimizer state sharded
# over `data`) on the 8-device CPU mesh: per-chip param+opt-state bytes
# measured from the LIVE arrays (one device's resident shards), HLO
# bytes_accessed from the compile plane's zoo_hlo_* features, steps/sec,
# and the trajectory-equality flag — the fsdp memory win must be free
# (placement changes bytes and collectives, never the math).  Emits
# BENCH_PARTITION_r10.json.
# ---------------------------------------------------------------------------


def _partition_model():
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    m = Sequential()
    m.add(Dense(256, activation="relu", input_shape=(32,)))
    m.add(Dense(256, activation="relu"))
    m.add(Dense(10, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    return m


def _partition_data(n=512, feat=32, classes=10, seed=7):
    import numpy as np

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, feat)).astype(np.float32)
    w = rng.normal(size=(feat, classes))
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return x, y


def _partition_leg(plan_name, epochs, batch_size=64):
    """One training leg under a named plan; returns losses, per-chip
    bytes (live arrays), steps/sec and the plan's HLO features."""
    import jax

    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.metrics import get_registry, snapshot
    from analytics_zoo_tpu.parallel.plan import per_chip_bytes

    zoo.init_zoo_context(seed=11, mesh_shape={"data": 8}, platform="cpu")
    x, y = _partition_data()
    m = _partition_model()
    t0 = time.perf_counter()
    m.fit(x, y, batch_size=batch_size, nb_epoch=epochs, plan=plan_name)
    dt = time.perf_counter() - t0
    est = m._estimator
    steps = est.global_step
    params, opt_state = m.params, est._opt_state
    chip_bytes = per_chip_bytes((params, opt_state))
    total_bytes = sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(
            (params, opt_state)) if hasattr(leaf, "nbytes"))
    label = "train_step" if plan_name in (None, "dp") \
        else f"train_step_{plan_name}"
    hlo = {}
    for s in snapshot(get_registry())["samples"]:
        if s["name"].startswith("zoo_hlo_") \
                and s.get("labels", {}).get("label") == label:
            hlo[s["name"]] = s["value"]
    spec0 = jax.tree_util.tree_leaves(params)[0].sharding.spec
    return {
        "plan": plan_name or "dp",
        "losses": [h["loss"] for h in est.history],
        "per_chip_param_opt_bytes": int(chip_bytes),
        "global_param_opt_bytes": int(total_bytes),
        "steps": int(steps),
        "steps_per_sec": round(steps / max(dt, 1e-9), 2),
        "param0_spec": str(spec0),
        "hlo": hlo,
    }


def partition_bench(quick: bool = False,
                    out_path: str | None = None) -> dict:
    """Replicated DP vs the fsdp (and zero1) plans: memory ratio at
    trajectory equality; writes BENCH_PARTITION_r10.json."""
    epochs = 2 if quick else 4
    legs = {name: _partition_leg(name, epochs)
            for name in ("dp", "fsdp", "zero1")}
    repl, fs = legs["dp"], legs["fsdp"]
    ratio = fs["per_chip_param_opt_bytes"] \
        / max(repl["per_chip_param_opt_bytes"], 1)
    doc = {
        "metric": "fsdp_per_chip_param_opt_bytes_vs_replicated",
        "unit": "ratio (lower is better; target <= 0.6)",
        "value": round(ratio, 4),
        "zero1_ratio": round(
            legs["zero1"]["per_chip_param_opt_bytes"]
            / max(repl["per_chip_param_opt_bytes"], 1), 4),
        # the acceptance flag: fsdp must be FREE — the gather-on-use /
        # reduce-scatter program computes the same sums in the same
        # order, so the trajectory is bitwise dp's.  zero1's sharded-
        # moment program groups the gradient reduction differently
        # (reduce-scatter into moments, all-gather of updates) — ulp
        # drift, reported as max|Δ| rather than pretending bitwise.
        "trajectory_bitwise_equal": repl["losses"] == fs["losses"],
        "zero1_trajectory_max_abs_diff": max(
            abs(a - b) for a, b in zip(repl["losses"],
                                       legs["zero1"]["losses"])),
        "devices": 8,
        "platform": "cpu",
        "quick": bool(quick),
        "legs": legs,
        "note": ("per_chip bytes counted from live arrays (one device's "
                 "resident shards); hlo features from the compile "
                 "plane's zoo_hlo_* extraction at the choke point"),
    }
    doc["host_fingerprint"] = host_fingerprint()
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_PARTITION_r10.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    doc["artifact"] = out_path
    return doc


def _partition_main(argv):
    # the 8-device CPU mesh is the point (memory layout, not FLOPs)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")
    kwargs = {}
    if "--quick" in argv:
        kwargs["quick"] = True
    if "--out" in argv:
        kwargs["out_path"] = argv[argv.index("--out") + 1]
    print(json.dumps(partition_bench(**kwargs)))


# ---------------------------------------------------------------------------
# --memory: the complete memory plan (parallel/plan.py) — per-chip
# param+opt bytes under dp/zero1/zero2/zero3/fsdp on the 8-device CPU
# mesh, each leg closing the predicted-vs-measured loop through the
# estimator's zoo_mem_* gauges, plus transformer-GPipe legs where the
# remat policy arrives as a PLAN rule (with_remat → resolve_remat at
# trace time), not a layer flag.  Emits BENCH_MEMORY_r12.json.  The
# quick tier is the acceptance guard (tests/test_memory_plan.py):
# zero3 <= 0.25x dp per-chip state at a bit-identical (or recorded-ulp)
# loss trajectory, and the remat leg reproduces the un-remated grads.
# ---------------------------------------------------------------------------

_MEMORY_PLANS = ("dp", "zero1", "zero2", "zero3", "fsdp")


def _memory_leg(plan_name, epochs):
    """:func:`_partition_leg` plus the closed loop: the estimator's
    ``zoo_mem_*`` gauges (cost-model prediction vs measured placement)
    harvested for the leg's compile label."""
    from analytics_zoo_tpu.metrics import get_registry, snapshot

    leg = _partition_leg(plan_name, epochs)
    label = "train_step" if plan_name in (None, "dp") \
        else f"train_step_{plan_name}"
    mem = {}
    for s in snapshot(get_registry())["samples"]:
        if s["name"].startswith("zoo_mem_") \
                and s.get("labels", {}).get("label") == label:
            mem[s["name"]] = s["value"]
    leg["mem_gauges"] = mem
    if "zoo_mem_predicted_bytes" in mem:
        leg["predicted_chip_bytes"] = int(mem["zoo_mem_predicted_bytes"])
        leg["predicted_rel_error"] = round(
            float(mem.get("zoo_mem_rel_error", 0.0)), 4)
    return leg


def _memory_pipeline_leg(remat_policy):
    """One grad step of a 4-block transformer GPipe'd over ``pipe=4``,
    compiled through ``compile_step`` under a plan whose ``remat_rules``
    carry ``remat_policy`` — the policy reaches ``apply_remat`` via
    ``resolve_remat`` inside the stage body at trace time, overriding
    the layer's own flag.  Returns (doc, loss, grads) so the caller can
    pin remat == no-remat numerics."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.metrics import get_registry, snapshot
    from analytics_zoo_tpu.parallel.pipeline import transformer_gpipe
    from analytics_zoo_tpu.parallel.plan import (
        live_bytes,
        resolve_plan,
        with_remat,
        compile_step,
    )
    from analytics_zoo_tpu.pipeline.api.keras.layers import TransformerLayer

    zoo.init_zoo_context(seed=3, mesh_shape={"data": 2, "pipe": 4},
                         mesh_axes=("data", "pipe"), platform="cpu")
    layer = TransformerLayer(vocab=64, seq_len=8, n_block=4, n_head=2,
                             hidden_size=16, embedding_drop=0.0,
                             hidden_drop=0.0, attn_drop=0.0)
    params = layer.init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.normal(size=(8, 8, 16)).astype(np.float32))

    plan = resolve_plan("dp")
    label = "pipeline_gpipe_noremat"
    if remat_policy:
        plan = with_remat(plan, remat_policy)
        label = f"pipeline_gpipe_remat_{remat_policy}"

    def loss_fn(p, a):
        return jnp.mean(transformer_gpipe(layer, p, a,
                                          n_microbatch=4) ** 2)

    step = compile_step(jax.value_and_grad(loss_fn), plan, label=label)
    t0 = time.perf_counter()
    loss, grads = step(params, h)
    loss = float(loss)
    dt = time.perf_counter() - t0
    hlo = {}
    for s in snapshot(get_registry())["samples"]:
        if s["name"].startswith("zoo_hlo_") \
                and s.get("labels", {}).get("label") == label:
            hlo[s["name"]] = s["value"]
    doc = {
        "remat": remat_policy,
        "label": label,
        "loss": loss,
        "compile_plus_step_s": round(dt, 3),
        "live": live_bytes(),
        "hlo": hlo,
    }
    return doc, loss, grads


def memory_bench(quick: bool = False, out_path: str | None = None) -> dict:
    """The full sharding×remat memory plan: per-chip state ratios vs
    replicated DP with predicted-vs-measured closure, and plan-rule
    remat equivalence on the pipelined transformer; writes
    BENCH_MEMORY_r12.json."""
    import jax
    import numpy as np

    epochs = 2 if quick else 4
    legs = {name: _memory_leg(name, epochs) for name in _MEMORY_PLANS}
    dp = legs["dp"]

    def ratio(name):
        return round(legs[name]["per_chip_param_opt_bytes"]
                     / max(dp["per_chip_param_opt_bytes"], 1), 4)

    def traj_max_diff(name):
        return max(abs(a - b) for a, b in zip(dp["losses"],
                                              legs[name]["losses"]))

    pipe_none, loss_none, g_none = _memory_pipeline_leg(None)
    pipe_full, loss_full, g_full = _memory_pipeline_leg("full")
    grad_diffs = jax.tree_util.tree_map(
        lambda a, b: float(np.max(np.abs(np.asarray(a) - np.asarray(b)))),
        g_none, g_full)
    grad_max_diff = max(jax.tree_util.tree_leaves(grad_diffs) or [0.0])

    doc = {
        "metric": "zero3_per_chip_param_opt_bytes_vs_replicated",
        "unit": "ratio (lower is better; acceptance <= 0.25)",
        "value": ratio("zero3"),
        "ratios": {name: ratio(name) for name in _MEMORY_PLANS},
        # zero3/fsdp keep the gather-on-use program's reduction order,
        # so the trajectory is bitwise dp's; zero1/zero2 group the
        # moment update differently — ulp drift recorded, not hidden
        "zero3_trajectory_bitwise_equal":
            dp["losses"] == legs["zero3"]["losses"],
        "zero3_trajectory_max_abs_diff": traj_max_diff("zero3"),
        "zero2_trajectory_max_abs_diff": traj_max_diff("zero2"),
        "zero1_trajectory_max_abs_diff": traj_max_diff("zero1"),
        "pipeline_remat": {
            "legs": [pipe_none, pipe_full],
            "loss_abs_diff": abs(loss_none - loss_full),
            "grad_max_abs_diff": grad_max_diff,
        },
        "devices": 8,
        "platform": "cpu",
        "quick": bool(quick),
        "legs": legs,
        "note": ("per_chip bytes counted from live placed arrays; "
                 "predicted bytes from analysis/costmodel.py "
                 "predict_chip_bytes via the estimator's zoo_mem_* "
                 "gauges; remat legs compile through compile_step with "
                 "the policy as a plan rule (with_remat), resolved by "
                 "resolve_remat at trace time"),
    }
    doc["host_fingerprint"] = host_fingerprint()
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_MEMORY_r12.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    doc["artifact"] = out_path
    return doc


def _memory_main(argv):
    # the 8-device CPU mesh is the point (memory layout, not FLOPs)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")
    kwargs = {}
    if "--quick" in argv:
        kwargs["quick"] = True
    if "--out" in argv:
        kwargs["out_path"] = argv[argv.index("--out") + 1]
    print(json.dumps(memory_bench(**kwargs)))


# ---------------------------------------------------------------------------
# --precision: the precision plane (parallel/plan.py dtype_rules — the
# FOURTH rule table).  f32 vs mixed_precision() training legs on the
# 8-device CPU mesh: bf16 loss trajectory pinned within tolerance of
# f32, the per-leg zoo_hlo_* features plus the zoo-hlo-report dtype
# histogram showing the MEASURED bf16 shift, predicted-vs-measured
# steps/sec per dtype (DTYPE_PEAK_FACTORS closing the loop), the
# predicted fsdp param-gather collective-bytes reduction (grad
# collectives stay f32 per the accumulation contract), and the int8
# serving leg's bytes ratio + predict parity.  CPU has no bf16 MXU, so
# throughput wins are RECORDED, not required — the byte/feature deltas
# are the asserted invariants (tests/test_precision.py).  Emits
# BENCH_PRECISION_r16.json.
# ---------------------------------------------------------------------------


def _precision_leg(plan, epochs, report_dir, batch_size=64):
    """One training leg under ``plan`` (a ShardingPlan or name); returns
    losses, steps/sec, the compile plane's zoo_hlo_* features, the
    leg's zoo-hlo-report row (dtype histogram + declared policy) and
    the roofline's predicted steps/sec at the leg's compute dtype."""
    import jax
    import numpy as np

    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.analysis.costmodel import (
        histogram_compute_dtype,
        load_report_rows,
        predict_steps_per_sec,
    )
    from analytics_zoo_tpu.metrics import get_registry, snapshot
    from analytics_zoo_tpu.parallel.plan import resolve_plan

    os.environ["ZOO_HLO_REPORT_DIR"] = report_dir
    try:
        zoo.init_zoo_context(seed=11, mesh_shape={"data": 8},
                             platform="cpu")
        plan = resolve_plan(plan)
        x, y = _partition_data()
        m = _partition_model()
        t0 = time.perf_counter()
        m.fit(x, y, batch_size=batch_size, nb_epoch=epochs, plan=plan)
        dt = time.perf_counter() - t0
    finally:
        os.environ.pop("ZOO_HLO_REPORT_DIR", None)
    est = m._estimator
    steps = est.global_step
    label = "train_step" if plan.name == "dp" \
        else f"train_step_{plan.name}"
    hlo = {}
    for s in snapshot(get_registry())["samples"]:
        if s["name"].startswith("zoo_hlo_") \
                and s.get("labels", {}).get("label") == label:
            hlo[s["name"]] = s["value"]
    row = next((r for r in load_report_rows(report_dir)
                if r["label"] == label), None)
    hist = (row or {}).get("dtype_histogram") or {}
    dtype = plan.compute_cast_dtype()
    dtype_name = {"bfloat16": "bf16", "float16": "f16"}.get(
        str(np.dtype(dtype)) if dtype is not None else "", None)
    predicted = None
    if row and row["features"]:
        predicted = predict_steps_per_sec(
            row["features"], k=1, plan=plan.name,
            dtype_histogram=hist or None)
    measured = steps / max(dt, 1e-9)
    return {
        "plan": plan.name,
        "dtype": dtype_name or "f32",
        "dtype_policy": plan.dtype_policy_str(),
        "losses": [h["loss"] for h in est.history],
        "steps": int(steps),
        "steps_per_sec": round(measured, 2),
        "predicted_steps_per_sec": (round(predicted, 2)
                                    if predicted else None),
        "hlo": hlo,
        "dtype_histogram": hist,
        "measured_compute_dtype": histogram_compute_dtype(hist),
        "model": m,
    }


def precision_bench(quick: bool = False,
                    out_path: str | None = None) -> dict:
    """f32 vs mixed_precision() vs int8 serving; writes
    BENCH_PRECISION_r16.json."""
    import tempfile

    import numpy as np

    from analytics_zoo_tpu.analysis.costmodel import plan_collective_bytes
    from analytics_zoo_tpu.parallel.plan import int8_serving, mixed_precision
    from analytics_zoo_tpu.pipeline.inference.quantize import (
        dequantize_params,
        quantize_params_for_plan,
        quantized_bytes_ratio,
    )

    epochs = 2 if quick else 4
    legs = {}
    with tempfile.TemporaryDirectory() as rd:
        legs["f32"] = _precision_leg("dp", epochs, os.path.join(rd, "f32"))
        legs["bf16"] = _precision_leg(mixed_precision(), epochs,
                                      os.path.join(rd, "bf16"))
    f32, bf16 = legs["f32"], legs["bf16"]
    max_rel = max(
        abs(a - b) / max(abs(a), 1e-9)
        for a, b in zip(f32["losses"], bf16["losses"]))

    # int8 serving: quantize the f32 leg's trained weights under the
    # plan's int8 role, compare predict outputs and weight bytes
    m = f32.pop("model")
    bf16.pop("model")
    x, _ = _partition_data()
    params = m.params
    qparams = quantize_params_for_plan(params, int8_serving())
    base = np.asarray(m.predict(x[:64]))
    m._estimator.model.params = dequantize_params(qparams)
    served = np.asarray(m.predict(x[:64]))
    m._estimator.model.params = params
    int8_leg = {
        "plan": "dp+int8",
        "bytes_ratio": round(quantized_bytes_ratio(params, qparams), 4),
        "predict_max_abs_diff": float(np.max(np.abs(base - served))),
    }
    legs["int8_serving"] = int8_leg

    # predicted collective reduction: only the fsdp param-GATHER
    # traffic shrinks at bf16 — grad collectives are charged f32 per
    # the accumulation contract, so the predicted ratio is 2/3, the
    # number a real-TPU profile should reproduce
    pb = 4 * 1024 * 1024
    coll_f32 = plan_collective_bytes(pb, "fsdp", 8)
    coll_bf16 = plan_collective_bytes(pb, "fsdp", 8, dtype="bf16")
    doc = {
        "metric": "bf16_mixed_loss_trajectory_max_rel_diff_vs_f32",
        "unit": "ratio (lower is better; target <= 0.05)",
        "value": round(max_rel, 6),
        "bf16_hlo_shift": {
            "f32_leg_bf16_ops": int(f32["dtype_histogram"].get("bf16", 0)),
            "bf16_leg_bf16_ops": int(
                bf16["dtype_histogram"].get("bf16", 0)),
            "bf16_leg_compute_dtype": bf16["measured_compute_dtype"],
        },
        "predicted_fsdp_collective_bytes": {
            "f32": int(coll_f32), "bf16": int(coll_bf16),
            "ratio": round(coll_bf16 / max(coll_f32, 1), 4),
        },
        "int8_serving_bytes_ratio": int8_leg["bytes_ratio"],
        "devices": 8,
        "platform": "cpu",
        "quick": bool(quick),
        "legs": legs,
        "note": ("CPU mesh: no bf16 MXU, so steps/sec parity is "
                 "recorded (predicted-vs-measured per dtype), not "
                 "gated; the asserted invariants are the trajectory "
                 "tolerance, the measured bf16 histogram shift, the "
                 "f32 masters, and the int8 bytes/parity numbers"),
    }
    doc["host_fingerprint"] = host_fingerprint()
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_PRECISION_r16.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    doc["artifact"] = out_path
    return doc


def _precision_main(argv):
    # the 8-device CPU mesh: dtype layout and lowering, not FLOPs
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")
    kwargs = {}
    if "--quick" in argv:
        kwargs["quick"] = True
    if "--out" in argv:
        kwargs["out_path"] = argv[argv.index("--out") + 1]
    print(json.dumps(precision_bench(**kwargs)))


# ---------------------------------------------------------------------------
# --kernels: the Pallas kernel plane (ops/pallas/ behind kernel_rules —
# the FIFTH rule table).  Per kernel: (a) PARITY — the jnp fallback is
# the oracle; fused_adam's fallback is BITWISE optax.adam, and the
# interpret-mode Pallas path (ZOO_KERNEL_INTERPRET=1) is compared
# against it fwd and bwd; (b) BYTES — the kernel is cross-lowered for
# TPU with no chip (trace + lower(platforms=("tpu",))), hlo.py
# attributes the tpu_custom_call's operand+result bytes, and the
# measured number must sit within rel_error <= 0.05 of
# costmodel.kernel_bytes' analytic prediction; (c) the fallback leg
# compiles under its kernel_* label through compile_step/timed_compile
# (persistent cache + compile metering), and its CPU steps/sec is
# recorded; (d) VERDICTS — ConfigOracle.choose_kernels per platform:
# the CPU tier must DECLINE every kernel ("xla" — Pallas lowers via
# Mosaic), the tpu-v4 peaks pick by the byte model.  Emits
# BENCH_KERNEL_r17.json (tests/test_kernels.py pins the invariants).
# ---------------------------------------------------------------------------


def _kernel_lowered_bytes(name, fn, args, predicted):
    """Cross-lower the Pallas variant for TPU (no chip needed), run the
    HLO lint pipe on it, and return measured-vs-predicted custom-call
    bytes.  ``predicted`` is costmodel.kernel_bytes' "kernel" term."""
    import jax

    from analytics_zoo_tpu.analysis.hlo import lint_lowered
    from analytics_zoo_tpu.ops.pallas import record_kernel_bytes

    lowered = jax.jit(fn).trace(*args).lower(
        lowering_platforms=("tpu",))
    rpt = lint_lowered(lowered, label=f"kernel_{name}_tpu")
    measured = int(rpt.custom_kernel_bytes)
    doc = record_kernel_bytes(f"kernel_{name}", measured,
                              predicted_bytes=int(predicted))
    doc["custom_kernel_count"] = int(rpt.custom_kernel_count)
    return doc


def _kernel_timed_leg(name, fn, args, iters):
    """Compile ``fn`` under the ``kernel_<name>`` label through the
    choke point (kernel_step -> compile_step -> timed_compile: the
    persistent cache and zoo_compile_seconds see it) and time the
    compiled fallback on CPU."""
    import jax

    from analytics_zoo_tpu.ops.pallas import kernel_step

    step = kernel_step(name, fn)
    out = step(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(*args)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    return {"label": f"kernel_{name}",
            "steps_per_sec": round(iters / max(dt, 1e-9), 2)}


def kernels_bench(quick: bool = False,
                  out_path: str | None = None) -> dict:
    """Kernel-plane A/B: parity, cross-lowered bytes, verdicts; writes
    BENCH_KERNEL_r17.json."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from analytics_zoo_tpu.analysis.costmodel import (
        kernel_bytes,
        resolve_peaks,
    )
    from analytics_zoo_tpu.analysis.oracle import ConfigOracle
    from analytics_zoo_tpu.ops.pallas import fused_adam as fa
    from analytics_zoo_tpu.ops.pallas import fused_softmax_xent as fx
    from analytics_zoo_tpu.ops.pallas import int8_matmul as im
    from analytics_zoo_tpu.ops.pallas import kernel_invocation_counts

    iters = 10 if quick else 50
    steps = 2 if quick else 3
    rng = np.random.default_rng(11)
    kernels = {}

    # -- fused_adam: fallback bitwise vs optax, interpret vs optax -----
    params = {"w": jnp.asarray(rng.normal(size=(256, 128)), jnp.float32),
              "b": jnp.asarray(rng.normal(size=(7,)), jnp.float32)}
    grads = jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.normal(size=p.shape), jnp.float32),
        params)

    def run(tx, n):
        state = tx.init(params)
        p = params
        for _ in range(n):
            upd, state = tx.update(grads, state, p)
            p = optax.apply_updates(p, upd)
        return p

    p_ref = run(optax.adam(1e-3), steps)
    p_fb = run(fa.fused_adam(1e-3), steps)
    bitwise = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(p_ref),
                        jax.tree_util.tree_leaves(p_fb)))
    os.environ["ZOO_KERNEL_INTERPRET"] = "1"
    try:
        p_int = run(fa.fused_adam(1e-3), steps)
    finally:
        os.environ.pop("ZOO_KERNEL_INTERPRET", None)
    interp_err = max(
        float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        for a, b in zip(jax.tree_util.tree_leaves(p_ref),
                        jax.tree_util.tree_leaves(p_int)))
    n_adam = 4096
    g1 = jnp.asarray(rng.normal(size=(n_adam,)), jnp.float32)
    scal = jnp.asarray([1e-3, 0.9, 0.999, 1e-8, 0.1, 0.001], jnp.float32)
    kernels["fused_adam"] = {
        "parity": {"fallback_bitwise_vs_optax": bool(bitwise),
                   "interpret_max_abs_err": interp_err,
                   "tolerance": 1e-5},
        "bytes": _kernel_lowered_bytes(
            "fused_adam",
            lambda g, m, n, s: fa._adam_leaf_pallas(g, m, n, s, False),
            (g1, g1 * 0, g1 * 0 + 1e-4, scal),
            kernel_bytes("fused_adam", n=n_adam)["kernel"]),
        "timing": _kernel_timed_leg(
            "fused_adam", fa._adam_leaf_reference,
            (g1, g1 * 0, g1 * 0 + 1e-4, scal), iters),
    }

    # -- fused_softmax_xent: interpret fwd+grad vs the jnp oracle ------
    bsz, vocab = 128, 2048
    logits = jnp.asarray(rng.normal(size=(bsz, vocab)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, vocab, size=(bsz,)), jnp.int32)

    def loss_mean(x):
        return fx.softmax_xent(x, labels).mean()

    ref_loss, ref_lse = fx._reference_fwd(logits, labels)
    ref_dx = fx._reference_bwd(logits, labels, ref_lse,
                               jnp.full((bsz,), 1.0 / bsz))
    os.environ["ZOO_KERNEL_INTERPRET"] = "1"
    try:
        int_loss = fx.softmax_xent(logits, labels)
        int_dx = jax.grad(loss_mean)(logits)
    finally:
        os.environ.pop("ZOO_KERNEL_INTERPRET", None)
    kernels["fused_softmax_xent"] = {
        "parity": {
            "interpret_fwd_max_abs_err": float(
                np.max(np.abs(np.asarray(int_loss - ref_loss)))),
            "interpret_bwd_max_abs_err": float(
                np.max(np.abs(np.asarray(int_dx - ref_dx)))),
            "tolerance": 1e-4},
        "bytes": _kernel_lowered_bytes(
            "fused_softmax_xent",
            lambda x, l: fx._fwd_pallas(x, l, False),
            (logits, labels),
            kernel_bytes("fused_softmax_xent", batch=bsz,
                         vocab=vocab)["kernel"]),
        "timing": _kernel_timed_leg(
            "fused_softmax_xent",
            lambda x, l: fx._reference_fwd(x, l)[0],
            (logits, labels), iters),
    }

    # -- int8_matmul: interpret vs dequantize-then-dot -----------------
    m_, k_, n_ = 128, 256, 128
    x8 = jnp.asarray(rng.normal(size=(m_, k_)), jnp.float32)
    w8 = jnp.asarray(rng.integers(-127, 128, size=(k_, n_)), jnp.int8)
    s8 = jnp.asarray(rng.uniform(0.01, 0.1, size=(n_,)), jnp.float32)
    ref_mm = im._reference(x8, w8, s8)
    os.environ["ZOO_KERNEL_INTERPRET"] = "1"
    try:
        int_mm = im.int8_matmul(x8, w8, s8)
    finally:
        os.environ.pop("ZOO_KERNEL_INTERPRET", None)
    denom = float(np.max(np.abs(np.asarray(ref_mm)))) or 1.0
    kernels["int8_matmul"] = {
        "parity": {
            "interpret_max_rel_err": float(
                np.max(np.abs(np.asarray(int_mm - ref_mm)))) / denom,
            "tolerance": 1e-4},
        "bytes": _kernel_lowered_bytes(
            "int8_matmul",
            lambda x, w, s: im._matmul_pallas(x, w, s, False),
            (x8, w8, s8),
            kernel_bytes("int8_matmul", m=m_, k=k_, n=n_)["kernel"]),
        "timing": _kernel_timed_leg(
            "int8_matmul", im._reference, (x8, w8, s8), iters),
    }

    # -- per-platform verdicts: CPU declines, TPU picks by bytes -------
    sizes = {
        "fused_adam": {"n": n_adam},
        "fused_softmax_xent": {"batch": bsz, "vocab": vocab},
        "int8_matmul": {"m": m_, "k": k_, "n": n_},
        "flash": {"batch": 8, "heads": 12, "seq": 512, "head_dim": 64},
    }
    verdicts = {}
    for platform in ("cpu", "tpu-v4"):
        oracle = ConfigOracle(peaks=resolve_peaks(platform))
        verdicts[platform] = {
            name: {"choice": v["choice"], "reason": v["reason"],
                   "predicted_bytes": v["predicted_bytes"]}
            for name, v in oracle.choose_kernels(
                sizes, platform=platform).items()}
    cpu_declines = sum(1 for v in verdicts["cpu"].values()
                      if v["choice"] == "xla")

    max_bytes_rel = max(
        kernels[k]["bytes"].get("rel_error", 1.0)
        for k in ("fused_adam", "fused_softmax_xent"))
    doc = {
        "metric": "cross_lowered_custom_call_bytes_max_rel_error",
        "unit": "ratio (lower is better; target <= 0.05)",
        "value": round(max_bytes_rel, 6),
        "kernels": kernels,
        "verdicts": verdicts,
        "cpu_xla_picks": int(cpu_declines),
        "invocation_counts": kernel_invocation_counts(),
        "platform": "cpu",
        "quick": bool(quick),
        "note": ("CPU tier: parity runs the Pallas kernels in interpret "
                 "mode against the jnp fallback oracle; bytes are "
                 "MEASURED from genuine Mosaic cross-lowering "
                 "(lower(platforms=('tpu',)), no chip) and must match "
                 "costmodel.kernel_bytes; throughput A/B on real TPU "
                 "HBM is future work — the verdicts record what the "
                 "oracle would pick there"),
    }
    doc["host_fingerprint"] = host_fingerprint()
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_KERNEL_r17.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    doc["artifact"] = out_path
    return doc


def _kernels_main(argv):
    # single-process CPU: interpret-mode parity + cross-lowering need no
    # mesh, and the kernel_* labels must land in one compile cache
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    kwargs = {}
    if "--quick" in argv:
        kwargs["quick"] = True
    if "--out" in argv:
        kwargs["out_path"] = argv[argv.index("--out") + 1]
    print(json.dumps(kernels_bench(**kwargs)))


# ---------------------------------------------------------------------------
# --fleet: multi-replica serving fleet bench (serving/fleet.py).  No real
# model — the replicas serve the synthetic sleep model (per-RECORD
# GIL-releasing service time, like device inference), so the bench
# measures the CONTROL PLANE: the exactly-once claim protocol's
# scaling efficiency and the SLO autoscaler's response to a load step.
# Emits BENCH_FLEET_r09.json so the gains are pinned, not asserted.
# ---------------------------------------------------------------------------


def _fleet_controller(broker, replicas: int, service_ms: float,
                      batch_size: int = 8, budget_ms: float = 5.0,
                      scaler=None, interval: float = 0.5,
                      slo_p99_ms: float = 500.0):
    from analytics_zoo_tpu.serving import ClusterServingHelper
    from analytics_zoo_tpu.serving.fleet import (
        FleetController,
        _SyntheticModel,
    )
    from analytics_zoo_tpu.serving.scaler import SloScaler

    helper = ClusterServingHelper(
        model_path=None, batch_size=batch_size, batch_budget_ms=budget_ms,
        lease_ms=5_000, log_dir=os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "zoo-fleet-bench"))
    if scaler is None:  # fixed-size fleet: min == max pins the count
        scaler = SloScaler(slo_p99_ms=slo_p99_ms, min_replicas=replicas,
                           max_replicas=replicas)
    return FleetController(
        helper, broker, model_factory=lambda: _SyntheticModel(service_ms),
        scaler=scaler, interval=interval)


def fleet_scaling_bench(quick: bool = False) -> dict:
    """Saturated-backlog drain: wall-clock throughput of a 2-replica
    fleet vs 1 replica over ONE shared broker.  The claim protocol is
    the only coordination; >= 1.8x means leases + continuous batching
    cost < 10% of the doubled service capacity."""
    import numpy as np

    from analytics_zoo_tpu.serving import InMemoryBroker, InputQueue, \
        OutputQueue

    service_ms = 2.0
    n_records = 300 if quick else 1200
    out = {"service_ms_per_record": service_ms, "records": n_records,
           "throughput_rps": {}}
    for replicas in (1, 2):
        broker = InMemoryBroker()
        inq = InputQueue(broker=broker)
        rec = np.zeros((8,), np.float32)
        for i in range(n_records):
            inq.enqueue(f"u{i}", rec)
        ctrl = _fleet_controller(broker, replicas, service_ms)
        outq = OutputQueue(broker=broker)
        got = 0
        t0 = time.perf_counter()
        ctrl.start()
        deadline = t0 + 300.0
        while got < n_records and time.perf_counter() < deadline:
            got += len(outq.dequeue())
            time.sleep(0.005)
        wall = time.perf_counter() - t0
        ctrl.stop()
        if got != n_records:
            raise RuntimeError(
                f"fleet of {replicas} served {got}/{n_records}")
        out["throughput_rps"][str(replicas)] = round(n_records / wall, 1)
    out["scaling_2x_vs_1x"] = round(
        out["throughput_rps"]["2"] / out["throughput_rps"]["1"], 3)
    return out


def fleet_slo_bench(quick: bool = False) -> dict:
    """Offered-load step through the AUTOSCALING fleet: light traffic →
    overload (≈2.5x one replica's capacity) → light again.  Reports the
    client-observed p99 per load phase, the replica-count timeline, and
    the scaler's decision log — the acceptance story is p99 back under
    the SLO after scale-up, and replicas back at min after the load
    drops."""
    import threading

    import numpy as np

    from analytics_zoo_tpu.serving import InMemoryBroker, InputQueue, \
        OutputQueue
    from analytics_zoo_tpu.serving.scaler import SloScaler

    service_ms = 8.0  # one replica saturates at ~125 rec/s
    slo_p99_ms = 400.0
    interval = 0.25 if quick else 0.5
    phases = [("light", 2.0 if quick else 4.0, 30.0),
              ("overload", 6.0 if quick else 12.0, 300.0),
              ("light_again", 4.0 if quick else 8.0, 30.0)]
    # down_windows is the scale-down STABILIZATION window (the HPA
    # convention: minutes in production, seconds here): once the scaled-
    # up fleet drains the burst it reads slack, and the window must
    # outlast the rest of the overload phase or the fleet flaps down
    # into a marginal capacity that rebuilds the backlog
    scaler = SloScaler(slo_p99_ms=slo_p99_ms, min_replicas=1,
                       max_replicas=4, up_windows=2,
                       down_windows=18 if quick else 22)
    broker = InMemoryBroker()
    ctrl = _fleet_controller(broker, 1, service_ms, scaler=scaler,
                             interval=interval, slo_p99_ms=slo_p99_ms)
    inq = InputQueue(broker=broker)
    outq = OutputQueue(broker=broker)
    enq_ts: dict = {}
    lat: dict = {}  # uri -> (phase, latency_s)
    phase_of: dict = {}
    timeline = []
    stop = threading.Event()

    def collector():
        while not stop.is_set():
            now = time.perf_counter()
            for uri in outq.dequeue():
                t0 = enq_ts.get(uri)
                if t0 is not None:
                    lat[uri] = (phase_of[uri], now - t0)
            time.sleep(0.004)

    def sampler():
        t_start = time.perf_counter()
        while not stop.is_set():
            timeline.append({
                "t_s": round(time.perf_counter() - t_start, 2),
                "replicas": ctrl.replica_count(),
                "backlog": broker.unclaimed("image_stream"),
            })
            time.sleep(interval)

    ctrl.start()
    ct = threading.Thread(target=collector, daemon=True)
    st = threading.Thread(target=sampler, daemon=True)
    ct.start()
    st.start()
    rec = np.zeros((8,), np.float32)
    seq = 0
    phase_windows = {}
    for phase, duration, rate in phases:
        t_phase = time.perf_counter()
        phase_windows[phase] = [t_phase, t_phase + duration]
        while time.perf_counter() - t_phase < duration:
            uri = f"q{seq}"
            seq += 1
            phase_of[uri] = phase
            enq_ts[uri] = time.perf_counter()
            inq.enqueue(uri, rec)
            # paced offered load (sleep-based, so the achieved rate is
            # slightly under `rate` — the backlog signal is what counts)
            time.sleep(1.0 / rate)
    # drain: everything enqueued must come back before the report
    deadline = time.perf_counter() + 120.0
    while len(lat) < seq and time.perf_counter() < deadline:
        time.sleep(0.05)
    # let the scaler see the slack windows and come back down
    down_deadline = time.perf_counter() + (15.0 if quick else 30.0)
    while ctrl.replica_count() > 1 and time.perf_counter() < down_deadline:
        time.sleep(0.1)
    final_replicas = ctrl.replica_count()
    decisions = ctrl.decision_log()
    max_replicas_seen = max(
        [t["replicas"] for t in timeline] +
        [d["new"] for d in decisions if d["action"] == "up"] + [1])
    stop.set()
    ct.join(timeout=5)
    st.join(timeout=5)
    ctrl.stop()

    def p99(vals):
        if not vals:
            return None
        vals = sorted(vals)
        return round(vals[min(len(vals) - 1,
                              int(0.99 * len(vals)))] * 1e3, 1)

    by_phase = {}
    for phase, _, rate in phases:
        vals = [v for p, v in lat.values() if p == phase]
        by_phase[phase] = {"offered_rps": rate, "requests": len(vals),
                           "client_p99_ms": p99(vals)}
    # the SLO story: requests arriving in the LAST third of the overload
    # phase (post scale-up) vs the first third (pre scale-up)
    t0o, t1o = phase_windows["overload"]
    third = (t1o - t0o) / 3.0
    early, late = [], []
    for uri, (p, v) in lat.items():
        if p != "overload":
            continue
        ts = enq_ts[uri]
        if ts < t0o + third:
            early.append(v)
        elif ts > t1o - third:
            late.append(v)
    return {
        "service_ms_per_record": service_ms,
        "slo_p99_ms": slo_p99_ms,
        "phases": by_phase,
        "overload_early_p99_ms": p99(early),
        "overload_late_p99_ms": p99(late),
        "slo_held_after_scaleup": (p99(late) or 1e9) <= slo_p99_ms,
        "scaled_up": max_replicas_seen > 1,
        "scaled_down_after": final_replicas == 1,
        "max_replicas_seen": max_replicas_seen,
        "final_replicas": final_replicas,
        "replica_timeline": timeline,
        "decisions": [
            {k: d[k] for k in ("action", "old", "new", "reason",
                               "est_p99_ms", "queue_depth")}
            for d in decisions],
    }


def fleet_bench(quick: bool = False, out_path: str | None = None) -> dict:
    """Both fleet benches; writes BENCH_FLEET_r09.json."""
    doc = {
        "metric": "fleet_throughput_scaling_and_slo_step",
        "unit": "2-replica/1-replica throughput ratio",
        "platform": "cpu",
        "quick": bool(quick),
        "scaling": fleet_scaling_bench(quick=quick),
        "slo_step": fleet_slo_bench(quick=quick),
    }
    doc["value"] = doc["scaling"]["scaling_2x_vs_1x"]
    doc["host_fingerprint"] = host_fingerprint()
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_FLEET_r09.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    doc["artifact"] = out_path
    return doc


def _fleet_main(argv):
    # control-plane bench: host threads + sleep models, CPU is the point
    os.environ["JAX_PLATFORMS"] = "cpu"
    kwargs = {}
    if "--quick" in argv:
        kwargs["quick"] = True
    if "--out" in argv:
        kwargs["out_path"] = argv[argv.index("--out") + 1]
    print(json.dumps(fleet_bench(**kwargs)))


# ---------------------------------------------------------------------------
# --dispatch: fused multi-step dispatch + compile plane bench
# (ZOO_STEPS_PER_DISPATCH / ZOO_COMPILE_CACHE; docs/performance.md).
# Two measurements on a deliberately dispatch-bound synthetic model (tiny
# Dense net, small batch — per-step compute is microseconds, so the
# Python→device round-trip dominates exactly like the tunneled harness):
#   1. steps/sec for K ∈ {1, 4, 16}: how much lax.scan fusion amortizes
#      the per-step host overhead, plus a bitwise trajectory-equality
#      check (the K>1 contract);
#   2. cold vs warm time-to-first-step in SUBPROCESSES sharing a
#      ZOO_COMPILE_CACHE dir (cold populates, warm deserializes), plus a
#      post-`estimator.warmup()` fit.
# Emits BENCH_DISPATCH_r07.json so the gain is pinned, not asserted.
# Forced to the CPU backend: this bench measures HOST dispatch overhead
# and compile persistence, not device compute.
# ---------------------------------------------------------------------------

DISPATCH_FEAT = 32
DISPATCH_CLASSES = 10


def _dispatch_model(width: int = 64, depth: int = 1):
    """The K-sweep uses the tiny default (dispatch-bound: per-step
    compute ≪ per-step host overhead).  The compile probe uses a DEEP
    stack (width 256 × 30) instead: there XLA compile is ~4× the
    trace+lower cost, which is the regime the persistent cache exists
    for — on a tiny model time-to-first-step is tracing-bound and no
    disk cache can help it."""
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    m = Sequential()
    m.add(Dense(width, activation="relu", input_shape=(DISPATCH_FEAT,)))
    for _ in range(depth - 1):
        m.add(Dense(width, activation="relu"))
    m.add(Dense(DISPATCH_CLASSES, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    return m


def _dispatch_data(n: int, seed: int = 5):
    import numpy as np

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, DISPATCH_FEAT)).astype("float32")
    y = rng.integers(0, DISPATCH_CLASSES, size=(n,)).astype("int32")
    return x, y


def dispatch_bench(ks=(1, 4, 16), n_batches: int = 384,
                   batch_size: int = 16, quick: bool = False,
                   compile_probe: bool = True,
                   out_path: str | None = None) -> dict:
    """K-sweep steps/sec + cold/warm compile seconds; writes the artifact.

    ``quick``: CI-sized run (fewer batches; also exercised by
    tests/test_dispatch.py so a fusion regression fails loudly).
    ``compile_probe=False`` skips the two compile-cache subprocesses
    (each pays a full jax import) — the quick-tier test does.
    """
    import tempfile

    import numpy as np

    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.common.engine import ZooConfig

    if quick:
        n_batches = 128
    n_batches = max(ks) * (n_batches // max(ks))  # full chunks for every K
    x, y = _dispatch_data(n_batches * batch_size)

    results, trajectories = {}, {}
    for k in ks:
        zoo.init_zoo_context(ZooConfig(seed=11, steps_per_dispatch=k))
        m = _dispatch_model()
        # epoch 1 warms (trace + compile); epoch 2 is the timed
        # steady-state epoch (Keras continuation semantics)
        m.fit(x, y, batch_size=batch_size, nb_epoch=1)
        t0 = time.perf_counter()
        m.fit(x, y, batch_size=batch_size, nb_epoch=1)
        dt = time.perf_counter() - t0
        results[k] = {
            "steps_per_sec": round(n_batches / dt, 1),
            "dispatches_per_epoch": -(-n_batches // k),
            "epoch_s": round(dt, 4),
        }
        trajectories[k] = [h["loss"] for h in m._estimator.history]
    base = results[ks[0]]["steps_per_sec"]
    for k in ks:
        results[k]["speedup_vs_k1"] = round(
            results[k]["steps_per_sec"] / base, 3)

    doc = {
        "metric": "fused_dispatch_train_steps_per_sec",
        "unit": "steps/sec",
        "platform": "cpu",
        "batch_size": batch_size,
        "steps_per_epoch": n_batches,
        "sweep": {str(k): results[k] for k in ks},
        # the K>1 contract: identical loss trajectory, not just similar
        "loss_trajectory_bitwise_equal": all(
            trajectories[k] == trajectories[ks[0]] for k in ks),
    }

    if compile_probe:
        def probe_child(cache_dir, mode):
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            env.pop("XLA_FLAGS", None)  # one stable cache key across runs
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--dispatch-child", cache_dir, mode],
                capture_output=True, text=True, timeout=600, env=env)
            if r.returncode != 0:
                raise RuntimeError(
                    f"dispatch child failed:\n{(r.stderr or '')[-2000:]}")
            return json.loads(r.stdout.strip().splitlines()[-1])

        with tempfile.TemporaryDirectory() as cache_dir:
            cold = probe_child(cache_dir, "fit")       # empty cache
            warm = probe_child(cache_dir, "fit")       # populated cache
        with tempfile.TemporaryDirectory() as cache_dir:
            warmed = probe_child(cache_dir, "warmup-fit")
        doc["compile_plane"] = {
            "cold_first_fit_s": cold["first_fit_s"],
            "warm_first_fit_s": warm["first_fit_s"],
            "warm_over_cold": round(
                warm["first_fit_s"] / max(cold["first_fit_s"], 1e-9), 3),
            "post_warmup_fit_s": warmed["first_fit_s"],
            "warmup_compile_s": warmed.get("warmup_compile_s"),
            "note": ("cold/warm: two fresh processes sharing one "
                     "ZOO_COMPILE_CACHE dir; warmup-fit: same-process "
                     "estimator.warmup() before the first fit"),
        }

    doc["host_fingerprint"] = host_fingerprint()
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_DISPATCH_r07.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    doc["artifact"] = out_path
    return doc


def _dispatch_child_main(argv):
    """Subprocess body for the cold/warm probe: time-to-first-step of a
    one-batch fit with the persistent compile cache at argv's dir."""
    cache_dir = argv[argv.index("--dispatch-child") + 1]
    mode = argv[argv.index("--dispatch-child") + 2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.common.engine import ZooConfig

    zoo.init_zoo_context(ZooConfig(seed=11, compile_cache=cache_dir))
    x, y = _dispatch_data(16)
    m = _dispatch_model(width=256, depth=30)
    out = {}
    if mode == "warmup-fit":
        m._estimator = m._make_estimator()
        t0 = time.perf_counter()
        secs = m._estimator.warmup({"x": x, "y": y})
        out["warmup_compile_s"] = round(time.perf_counter() - t0, 4)
        out["warmup_detail"] = {k: round(v, 4) for k, v in secs.items()}
    t0 = time.perf_counter()
    m.fit(x, y, batch_size=16, nb_epoch=1)
    out["first_fit_s"] = round(time.perf_counter() - t0, 4)
    print(json.dumps(out))


def _dispatch_main(argv):
    # measures host dispatch overhead; the CPU backend is the point, and
    # it also sidesteps the flaky TPU init entirely
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    kwargs = {}
    if "--quick" in argv:
        kwargs["quick"] = True
    if "--out" in argv:
        kwargs["out_path"] = argv[argv.index("--out") + 1]
    print(json.dumps(dispatch_bench(**kwargs)))


# ---------------------------------------------------------------------------
# --oracle: predictive compile plane bench (analysis/costmodel.py +
# analysis/oracle.py).  Two legs: (a) the oracle-primed K autotune on
# the dispatch-bound synthetic must settle within 5% of the best
# fixed-K throughput in <= 8 dispatches (the blind hill-climb needed
# ~53, BENCH_AUTOTUNE_r08) at a trajectory bitwise-equal to fixed K=1;
# (b) estimator.fit(plan="auto") under a pinned HBM budget must choose
# the same plan the exhaustive BENCH_PARTITION_r10 sweep measured as
# best-under-budget.  Every prediction is scored against its measured
# outcome.  Emits BENCH_ORACLE_r11.json.
# ---------------------------------------------------------------------------

#: per-chip budget (bytes) for the plan="auto" leg — between fsdp's
#: measured ~115 kB and zero1's ~384 kB per-chip footprint for the
#: partition model on 8 devices (BENCH_PARTITION_r10), so exactly one
#: plan fits and the exhaustive-vs-predicted comparison is
#: deterministic on a CPU host whose throughput ranking is noise
ORACLE_PLAN_HBM_BUDGET = 200_000


def _oracle_k_leg(quick: bool) -> tuple[dict, object]:
    """Prior-primed K autotune vs fixed K legs on the dispatch-bound
    synthetic; returns (section, the ConfigOracle) so the caller can
    merge its prediction log into the artifact."""
    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.analysis.oracle import ConfigOracle
    from analytics_zoo_tpu.common.engine import ZooConfig
    from analytics_zoo_tpu.feature.autotune import AutotuneController

    n_batches = 192 if quick else 384
    batch_size = 16
    x, y = _dispatch_data(n_batches * batch_size)

    def fixed(k):
        zoo.init_zoo_context(ZooConfig(seed=11, steps_per_dispatch=k))
        m = _dispatch_model()
        m.fit(x, y, batch_size=batch_size, nb_epoch=1)  # warm/compile
        t0 = time.perf_counter()
        m.fit(x, y, batch_size=batch_size, nb_epoch=1)
        dt = time.perf_counter() - t0
        return (round(n_batches / dt, 1),
                [h["loss"] for h in m._estimator.history])

    # fixed K=1 pins the reference trajectory; fixed K=16 is the best
    # hand-tuned throughput (the blind climb's converged K, r08)
    k1_sps, k1_losses = fixed(1)
    k16_sps, _ = fixed(16)

    zoo.init_zoo_context(ZooConfig(seed=11))
    oracle = ConfigOracle.from_env()
    ctrl = AutotuneController(oracle=oracle)
    m = _dispatch_model()
    # epoch 1 hosts the prior jump + neighbor validation (the compile
    # of each visited K included); epoch 2 is the timed steady state
    m.fit(x, y, batch_size=batch_size, nb_epoch=1, autotune=ctrl)
    t0 = time.perf_counter()
    m.fit(x, y, batch_size=batch_size, nb_epoch=1, autotune=ctrl)
    dt = time.perf_counter() - t0
    ctrl.stop()
    auto_losses = [h["loss"] for h in m._estimator.history]
    auto_sps = round(n_batches / dt, 1)
    cur = ctrl.current()
    # close the prediction->outcome pairs the fixed legs measured; the
    # settled K's pair was already closed at settle time and the timed
    # steady state is the fresher measurement for it
    oracle.record_outcome("k=1", k1_sps, consumer="bench")
    oracle.record_outcome("k=16", k16_sps, consumer="bench")
    oracle.record_outcome(f"k={cur['k']}", auto_sps, consumer="bench")
    return {
        "steps_per_epoch": n_batches,
        "batch_size": batch_size,
        "untuned_default_steps_per_sec": k1_sps,
        "best_fixed_k16_steps_per_sec": k16_sps,
        "prior_tuned_steady_steps_per_sec": auto_sps,
        "vs_best_fixed": round(auto_sps / max(k16_sps, 1e-9), 3),
        "within_5pct_of_best": auto_sps >= 0.95 * k16_sps,
        "converged_k": cur["k"],
        "k_settled": cur["k_settled"],
        # tuning observations only — in-flight chunks queued before a
        # K switch keep their old size (pipeline latency, not search)
        "dispatches_to_converge": cur["k_settle_dispatch"],
        "total_dispatches_observed": cur["dispatches_observed"],
        "loss_trajectory_bitwise_equal_to_k1": auto_losses == k1_losses,
        "decisions": [
            {k: d[k] for k in ("knob", "old", "new", "reason")}
            for d in ctrl.decision_log()],
    }, oracle


def _oracle_blind_reference(quick: bool) -> dict:
    """Dispatches-to-converge without the prior.  The full tier
    re-measures the blind hill-climb; quick reuses the number the
    autotune bench already pinned (BENCH_AUTOTUNE_r08.json) instead of
    paying the ~53-dispatch climb again in CI."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_AUTOTUNE_r08.json")
    if quick:
        try:
            with open(path) as f:
                doc = json.load(f)
            return {
                "dispatches_to_converge":
                    doc["dispatch"]["dispatches_to_converge"],
                "source": os.path.basename(path),
            }
        except (OSError, ValueError, KeyError):
            return {"dispatches_to_converge": None,
                    "source": f"{os.path.basename(path)} (unreadable)"}
    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.common.engine import ZooConfig
    from analytics_zoo_tpu.feature.autotune import AutotuneController

    n_batches = 384
    x, y = _dispatch_data(n_batches * 16)
    zoo.init_zoo_context(ZooConfig(seed=11))
    ctrl = AutotuneController()  # no oracle: the blind hill-climb
    m = _dispatch_model()
    m.fit(x, y, batch_size=16, nb_epoch=2, autotune=ctrl)
    ctrl.stop()
    cur = ctrl.current()
    return {"dispatches_to_converge": cur["k_settle_dispatch"],
            "converged_k": cur["k"], "source": "measured"}


def _oracle_plan_leg(epochs: int) -> dict:
    """estimator.fit(plan="auto") with the HBM budget pinned via
    ZOO_ORACLE_PEAKS; returns the resolved plan + the oracle's
    candidate table from the estimator's plan record."""
    import analytics_zoo_tpu as zoo

    prior = os.environ.get("ZOO_ORACLE_PEAKS")
    os.environ["ZOO_ORACLE_PEAKS"] = json.dumps(
        {"hbm_bytes": ORACLE_PLAN_HBM_BUDGET})
    try:
        zoo.init_zoo_context(seed=11, mesh_shape={"data": 8},
                             platform="cpu")
        x, y = _partition_data()
        m = _partition_model()
        t0 = time.perf_counter()
        m.fit(x, y, batch_size=64, nb_epoch=epochs, plan="auto")
        dt = time.perf_counter() - t0
        est = m._estimator
        return {
            "resolved_plan": est._plan_record["name"],
            "steps": int(est.global_step),
            "steps_per_sec": round(
                est.global_step / max(dt, 1e-9), 2),
            "auto": est._plan_record.get("auto"),
        }
    finally:
        if prior is None:
            os.environ.pop("ZOO_ORACLE_PEAKS", None)
        else:
            os.environ["ZOO_ORACLE_PEAKS"] = prior


def oracle_bench(quick: bool = False,
                 out_path: str | None = None) -> dict:
    """Both oracle legs + prediction scoring; writes
    BENCH_ORACLE_r11.json."""
    k_leg, oracle = _oracle_k_leg(quick)
    blind = _oracle_blind_reference(quick)
    plan_leg = _oracle_plan_leg(epochs=1 if quick else 2)

    # exhaustive reference: the measured per-plan sweep from the
    # partition bench — best-under-budget by measured steps/sec must
    # match what the oracle predicted without running the sweep
    budget = ORACLE_PLAN_HBM_BUDGET
    r10_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_PARTITION_r10.json")
    exhaustive_best, chip_bytes_error = None, {}
    try:
        with open(r10_path) as f:
            r10 = json.load(f)
        legs = r10.get("legs") or {}
        feasible = {name: leg for name, leg in legs.items()
                    if leg["per_chip_param_opt_bytes"] <= budget}
        if feasible:
            exhaustive_best = max(
                feasible, key=lambda n: feasible[n]["steps_per_sec"])
        from analytics_zoo_tpu.analysis.costmodel import predict_chip_bytes

        rec = plan_leg.get("auto") or {}
        for cand in rec.get("candidates", []):
            # r10 measured param+opt state only, so score it against the
            # activations-excluded prediction (the full-memory-plan
            # candidates above additionally carry the activation/remat
            # terms the sweep never measured)
            leg = legs.get(cand["plan"])
            if leg is None or cand["remat"] is not None:
                continue
            measured = leg["per_chip_param_opt_bytes"]
            predicted = predict_chip_bytes(
                rec["param_bytes"], rec["opt_bytes"], cand["plan"],
                rec["n_shards"])
            chip_bytes_error[cand["plan"]] = {
                "predicted_chip_bytes": predicted,
                "measured_chip_bytes": measured,
                "rel_error": round(
                    abs(predicted - measured) / max(measured, 1), 4),
            }
            oracle.record_outcome(f"plan={cand['plan']}",
                                  leg["steps_per_sec"], consumer="bench")
    except (OSError, ValueError, KeyError):
        r10_path = None

    # score the plan predictions on the bench's own oracle so the
    # artifact's prediction table covers both consumers (the estimator
    # leg used its own per-process oracle instance)
    auto_rec = plan_leg.get("auto") or {}
    if auto_rec:
        oracle.choose_plan(auto_rec["param_bytes"], auto_rec["opt_bytes"],
                           auto_rec["n_shards"], hbm_budget=budget)

    doc = {
        "metric": "oracle_prior_dispatches_to_converge",
        "unit": "dispatches to K-settle (target <= 8; blind ~53)",
        "value": k_leg["dispatches_to_converge"],
        "platform": "cpu",
        "quick": bool(quick),
        "k_prior": {**k_leg, "blind": blind},
        "plan_auto": {
            "hbm_budget_bytes": budget,
            "chosen": plan_leg["resolved_plan"],
            # the r10 sweep measured sharding only, so exhaustive
            # agreement is on the base plan; the remat suffix (swept
            # against the activation estimate, which r10 excludes) is
            # recorded in "chosen" above
            "chosen_base_plan": plan_leg["resolved_plan"].split("+")[0],
            "exhaustive_best_under_budget": exhaustive_best,
            "agrees_with_exhaustive": (
                None if exhaustive_best is None
                else plan_leg["resolved_plan"].split("+")[0]
                == exhaustive_best),
            "exhaustive_source": (os.path.basename(r10_path)
                                  if r10_path else None),
            "predicted_vs_measured_chip_bytes": chip_bytes_error,
            "leg": plan_leg,
        },
        "predictions": oracle.prediction_log(),
        "oracle": oracle.to_doc() | {"predictions": None},
    }
    doc["host_fingerprint"] = host_fingerprint()
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_ORACLE_r11.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    doc["artifact"] = out_path
    return doc


def _oracle_main(argv):
    # CPU host, 8-device mesh: the K leg measures host dispatch
    # overhead and the plan leg needs the 8-way axis to shard over
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    kwargs = {}
    if "--quick" in argv:
        kwargs["quick"] = True
    if "--out" in argv:
        kwargs["out_path"] = argv[argv.index("--out") + 1]
    print(json.dumps(oracle_bench(**kwargs)))


# ---------------------------------------------------------------------------
# --overlap: the latency-hiding plane (ISSUE 15) — serial two-phase vs
# bucketed fused step on a comm-bound synthetic over the 8-device CPU
# mesh, checkpoint-stall sync vs async saves, and the overlap-aware
# roofline validated against the measured legs.  Emits
# BENCH_OVERLAP_r13.json.  The quick tier is the acceptance guard
# (tests/test_overlap.py): bucketed <= 0.85x serial at a BITWISE param
# trajectory, and async checkpoint stall p99 < 0.2x the synchronous
# save.
#
# What "serial" means on a 1-core emulated mesh: there is no device
# parallelism to overlap against, so the legs measure HOST-level
# latency hiding — the serial leg is the naive two-phase loop (backward
# dispatch, blocking host sync so the grads are materialized before the
# per-bucket reduction dispatches, sync again, THEN assemble the next
# feed: exactly the `host-sync` in-loop anti-pattern zoolint flags),
# while the bucketed leg issues ONE fused dispatch with the
# barrier-chained per-bucket psum_scatter and assembles the next feed
# while the device runs.  Both legs reduce over the SAME chunk
# boundaries with an elementwise update, so the parameter trajectory is
# bitwise identical and the time difference is pure dispatch/sync/feed
# stall.
# ---------------------------------------------------------------------------


def _overlap_comm_leg(plan_name, steps, dim=1 << 16, n_chunks=4,
                      lr=0.05):
    """Serial two-phase vs bucketed fused step for one plan family
    ("zero2": params replicated, grads bucket-reduce-scattered;
    "zero3": params stored sharded, gather-on-use with a
    prefetch-style barrier chain).  Returns measured p50s, the bitwise
    trajectory verdict and the fused program's HLO features."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from analytics_zoo_tpu.analysis.hlo import last_features
    from analytics_zoo_tpu.common.compile_cache import timed_compile

    n = 8
    mesh = jax.make_mesh((n,), ("data",))
    cm = dim // n_chunks
    m = cm // n                      # one device's slice of one bucket
    slices = [(i * cm, (i + 1) * cm) for i in range(n_chunks)]
    sharded = plan_name == "zero3"
    x_sharding = NamedSharding(mesh, P("data", None))
    meta = {"plan": plan_name, "mesh_shape": {"data": n},
            "steps_per_dispatch": 1,
            # these legs regather parameters by design (zero2 rebuilds
            # the replicated vector from its updated shard pieces,
            # zero3 gathers before backward), so all_gather is expected
            "expected_collectives": ("all_reduce", "all_gather",
                                     "collective_permute",
                                     "reduce_scatter")}

    base = np.arange(n * dim, dtype=np.float32).reshape(n, dim)

    def feed(step):
        # the per-step host data plane: deterministic batch assembly
        # on host, then the H2D put — the work the bucketed leg hides
        # behind the in-flight fused dispatch
        return jax.device_put(np.sin(base * 1e-3 + step * 0.13),
                              x_sharding)

    def local_grad(w, x):
        # analytic elementwise gradient of 0.5*mean((w-x)^2): no
        # cross-element reductions feed the update, so XLA cannot
        # reorder the math between the two differently-fused programs
        # — the bitwise pin is structural, not lucky
        return (w - x) * (2.0 / dim), jnp.sum((w - x) ** 2) / dim

    def gather_params(w_sh, chained):
        # zero3 forward: regather the per-bucket param pieces
        # (gather-on-use); the bucketed leg chains them with barriers —
        # the double-buffered prefetch schedule pinned at HLO level
        token, chunks = None, []
        for k in range(n_chunks):
            piece = w_sh[0, k * m:(k + 1) * m]
            if chained and token is not None:
                piece, token = jax.lax.optimization_barrier(
                    (piece, token))
            full = jax.lax.all_gather(piece, "data", tiled=True)
            token = full
            chunks.append(full)
        return jnp.concatenate(chunks)

    def reduce_chunk(chunk):
        return jax.lax.psum_scatter(
            chunk, "data", scatter_dimension=0, tiled=True) / n

    def updated_piece(w, w_sh, red, k, lo):
        # elementwise SGD on this device's slice of bucket k
        if sharded:
            return w_sh[0, k * m:(k + 1) * m] - lr * red
        idx = jax.lax.axis_index("data")
        return jax.lax.dynamic_slice(w, (lo + idx * m,), (m,)) \
            - lr * red

    # ---- serial (two-phase) programs -------------------------------
    def bwd_body(w, x):
        if sharded:
            w = gather_params(w, chained=False)
        g, loss = local_grad(w, x[0])
        return g[None], jax.lax.psum(loss, "data")[None] / n

    w_spec = P("data", None) if sharded else P()

    def make_red_chunk(k, lo, hi):
        def body(w, g):
            red = reduce_chunk(g[0][lo:hi])
            piece = updated_piece(w, w, red, k, lo)
            if sharded:
                return piece[None]
            return jax.lax.all_gather(piece, "data", tiled=True)
        out = P("data", None) if sharded else P()
        return shard_map(body, mesh=mesh, in_specs=(w_spec, P("data", None)),
                         out_specs=out, check_rep=False)

    def concat_fn(*chunks):
        return jnp.concatenate(chunks, axis=1 if sharded else 0)

    # ---- bucketed (fused) program ----------------------------------
    def fused_body(w_in, x):
        w = gather_params(w_in, chained=True) if sharded else w_in
        g, loss = local_grad(w, x[0])
        token, outs = None, []
        for k, (lo, hi) in enumerate(slices):
            c = g[lo:hi]
            if token is not None:
                # issue-order pin: bucket k's reduce-scatter is chained
                # behind bucket k-1's, matching the
                # backward-completion order plan.constrain_grads pins
                c, token = jax.lax.optimization_barrier((c, token))
            red = reduce_chunk(c)
            token = red
            piece = updated_piece(w, w_in, red, k, lo)
            outs.append(piece[None] if sharded else
                        jax.lax.all_gather(piece, "data", tiled=True))
        new_w = jnp.concatenate(outs, axis=1 if sharded else 0)
        return new_w, jax.lax.psum(loss, "data")[None] / n

    f_bwd = jax.jit(shard_map(
        bwd_body, mesh=mesh, in_specs=(w_spec, P("data", None)),
        out_specs=(P("data", None), P("data")), check_rep=False))
    f_red = [jax.jit(make_red_chunk(k, lo, hi))
             for k, (lo, hi) in enumerate(slices)]
    f_concat = jax.jit(concat_fn)
    f_fused = jax.jit(shard_map(
        fused_body, mesh=mesh, in_specs=(w_spec, P("data", None)),
        out_specs=((P("data", None) if sharded else P()), P("data")),
        check_rep=False))

    def w0():
        full = np.cos(np.arange(dim, dtype=np.float32) * 2e-3)
        if not sharded:
            return jax.device_put(jnp.asarray(full),
                                  NamedSharding(mesh, P()))
        # zero3 storage: device i's row = the concat of its m-slices
        # of each bucket (the strategies._shard_of chip layout)
        rows = np.stack([
            np.concatenate([full[lo + i * m: lo + (i + 1) * m]
                            for lo, _ in slices])
            for i in range(n)])
        return jax.device_put(jnp.asarray(rows), x_sharding)

    # every program through the one compile choke point, under its own
    # label — the gather-prefetch chain shows up in the fused report's
    # async/collective features
    x0, w_init = feed(0), w0()
    label = f"overlap_{plan_name}"
    exe_bwd = timed_compile(f_bwd.lower(w_init, x0),
                            f"{label}_serial_bwd", meta=meta)
    g0, _ = exe_bwd(w_init, x0)
    exe_red = [timed_compile(f.lower(w_init, g0),
                             f"{label}_serial_red{k}", meta=meta)
               for k, f in enumerate(f_red)]
    pieces0 = [e(w_init, g0) for e in exe_red]
    exe_concat = timed_compile(f_concat.lower(*pieces0),
                               f"{label}_serial_concat", meta=meta)
    exe_fused = timed_compile(
        f_fused.lower(w_init, x0), f"{label}_bucketed",
        meta=dict(meta, plan=f"{plan_name}+overlap"))

    warmup = 2

    def run_serial():
        w, x = w0(), feed(0)
        losses, times = [], []
        for s in range(steps + warmup):
            t0 = time.perf_counter()
            g, loss = exe_bwd(w, x)
            jax.block_until_ready(g)   # grads must land before the
            # per-bucket reduction dispatches can be issued
            pieces = [e(w, g) for e in exe_red]
            w = exe_concat(*pieces)
            jax.block_until_ready(w)   # naive loop: sync, THEN feed
            x = feed(s + 1)
            if s >= warmup:
                times.append(time.perf_counter() - t0)
            losses.append(float(np.asarray(loss)[0]))
        return np.asarray(w), losses, times

    def run_bucketed():
        w, x = w0(), feed(0)
        losses, times = [], []
        for s in range(steps + warmup):
            t0 = time.perf_counter()
            w, loss = exe_fused(w, x)  # one fused dispatch
            x = feed(s + 1)            # next feed hides behind it
            jax.block_until_ready(w)
            if s >= warmup:
                times.append(time.perf_counter() - t0)
            losses.append(float(np.asarray(loss)[0]))
        return np.asarray(w), losses, times

    # backward-only micro-leg: the calibrated roofline's compute term
    def measure_bwd():
        w, x = w0(), feed(0)
        ts = []
        for s in range(steps + warmup):
            t0 = time.perf_counter()
            g, _ = exe_bwd(w, x)
            jax.block_until_ready(g)
            if s >= warmup:
                ts.append(time.perf_counter() - t0)
        return ts

    def p50(vals):
        return sorted(vals)[len(vals) // 2]

    ws, ls, ts = run_serial()
    wb, lb, tb = run_bucketed()
    t_bwd = p50(measure_bwd())
    return {
        "plan": plan_name,
        "devices": n,
        "param_elements": dim,
        "bucket_count": n_chunks,
        "steps_timed": steps,
        "serial_step_p50_s": round(p50(ts), 6),
        "bucketed_step_p50_s": round(p50(tb), 6),
        "bucketed_vs_serial": round(p50(tb) / max(p50(ts), 1e-12), 4),
        "backward_only_p50_s": round(t_bwd, 6),
        "trajectory_bitwise_equal": bool(np.array_equal(ws, wb)),
        "loss_max_abs_diff": max(
            abs(a - b) for a, b in zip(ls, lb)),
        "losses_first_last": [ls[0], ls[-1]],
        "hlo_fused": last_features(f"{label}_bucketed") or {},
    }


def _overlap_roofline_row(leg):
    """Close the predicted-vs-measured loop for one comm leg: calibrate
    the peak table so the ADDITIVE model reproduces the serial
    measurement exactly, then compare both models against the measured
    BUCKETED step.  The overlap-aware prediction must not be further
    from the measurement than the additive one (and on serial legs the
    two coincide by construction — no regression on compute-bound
    legs)."""
    from analytics_zoo_tpu.analysis.costmodel import (
        PeakTable,
        predict_step_seconds,
    )

    feats = dict(leg["hlo_fused"])
    coll_bytes = feats.get("zoo_hlo_collective_bytes",
                           feats.get("collective_bytes", 0)) or 1.0
    bytes_acc = feats.get("zoo_hlo_bytes_accessed",
                          feats.get("bytes_accessed", 0)) or 1.0
    c = max(leg["backward_only_p50_s"], 1e-6)
    m_serial = leg["serial_step_p50_s"]
    m_bucketed = leg["bucketed_step_p50_s"]
    coll_s = max(m_serial - c, 1e-6)
    peaks = PeakTable(
        flops=1e30, hbm_bytes_per_s=bytes_acc / c,
        link_bytes_per_s=coll_bytes / coll_s,
        dispatch_overhead_s=0.0, hbm_bytes=int(4e9))
    norm = {"matmul_flops": feats.get("matmul_flops", 0),
            "bytes_accessed": bytes_acc,
            "collective_bytes": coll_bytes}
    t_additive = predict_step_seconds(norm, k=1, peaks=peaks,
                                      exposed_fraction=1.0)
    t_overlap = predict_step_seconds(norm, k=1, peaks=peaks,
                                     plan=f"{leg['plan']}+overlap")
    t_serial_model = predict_step_seconds(norm, k=1, peaks=peaks,
                                          plan=leg["plan"])
    rel = lambda pred, meas: abs(pred - meas) / max(meas, 1e-12)  # noqa: E731
    return {
        "plan": leg["plan"],
        "measured_serial_s": m_serial,
        "measured_bucketed_s": m_bucketed,
        "predicted_additive_s": round(t_additive, 6),
        "predicted_overlap_s": round(t_overlap, 6),
        # serial leg: the overlap-aware model with exposed=1.0 IS the
        # additive model — identical prediction, identical error
        "serial_rel_error_additive": round(rel(t_additive, m_serial), 4),
        "serial_rel_error_overlap": round(
            rel(t_serial_model, m_serial), 4),
        "bucketed_rel_error_additive": round(
            rel(t_additive, m_bucketed), 4),
        "bucketed_rel_error_overlap": round(
            rel(t_overlap, m_bucketed), 4),
    }


def _overlap_ckpt_leg(saves, payload_mb=48):
    """Checkpoint-stall comparison: the SAME save cadence (a work gap
    sized from the measured synchronous save) under
    ZOO_ASYNC_CHECKPOINT=0 (inline gather+serialize+rename) vs the
    async default (device snapshot on the caller thread, write on the
    daemon).  Returns per-mode stall percentiles."""
    import shutil
    import tempfile

    import jax.numpy as jnp
    import numpy as np

    from analytics_zoo_tpu.pipeline.estimator.estimator import (
        _Checkpointer,
    )

    elems = payload_mb * (1 << 20) // 4
    payload = {
        "params": jnp.asarray(
            np.arange(elems, dtype=np.float32) * 1e-3),
        "step": 7,
    }

    def pct(vals, q):
        s = sorted(vals)
        return s[min(int(q * len(s)), len(s) - 1)]

    def run(mode):
        prev = os.environ.get("ZOO_ASYNC_CHECKPOINT")
        os.environ["ZOO_ASYNC_CHECKPOINT"] = mode
        root = tempfile.mkdtemp(prefix=f"ovl-ckpt-{mode}-")
        try:
            ck = _Checkpointer(path=root, keep=2)
            # one untimed warmup save per mode: the first save pays
            # one-off costs (writer-thread spawn, cold fs paths) that a
            # training run amortizes over thousands of steps — they are
            # not the steady-state stall this leg measures
            ck.save("warm", dict(payload, step=-1))
            warm_pending = getattr(ck, "_pending", None)
            if warm_pending is not None:
                warm_pending.join()
            stalls = []
            for i in range(saves):
                t0 = time.perf_counter()
                ck.save(f"s{i}", dict(payload, step=i))
                stalls.append(time.perf_counter() - t0)
                time.sleep(run.gap)
            pending = getattr(ck, "_pending", None)
            if pending is not None:
                pending.join()
            assert ck.latest() is not None
            return stalls
        finally:
            if prev is None:
                os.environ.pop("ZOO_ASYNC_CHECKPOINT", None)
            else:
                os.environ["ZOO_ASYNC_CHECKPOINT"] = prev
            shutil.rmtree(root, ignore_errors=True)

    run.gap = 0.0
    sync_stalls = run("0")
    # the async leg's inter-save "compute" gap: big enough that the
    # previous write drains before the next save joins it (1.5x the
    # measured sync save), so the measured stall is the true
    # caller-visible cost, not a back-to-back writer queue
    run.gap = 1.5 * pct(sync_stalls, 0.5)
    async_stalls = run("1")
    sync_p99, async_p99 = pct(sync_stalls, 0.99), pct(async_stalls, 0.99)
    return {
        "saves_per_mode": saves,
        "payload_mb": payload_mb,
        "sync_stall_p50_s": round(pct(sync_stalls, 0.5), 6),
        "sync_stall_p99_s": round(sync_p99, 6),
        "async_stall_p50_s": round(pct(async_stalls, 0.5), 6),
        "async_stall_p99_s": round(async_p99, 6),
        "async_vs_sync_p99": round(async_p99 / max(sync_p99, 1e-12), 4),
    }


def overlap_bench(quick: bool = False,
                  out_path: str | None = None) -> dict:
    """The latency-hiding plane's number: serial two-phase vs bucketed
    fused step (zero2/zero3 families) at a bitwise-pinned trajectory,
    checkpoint stall sync vs async, and the overlap-aware roofline
    validated per leg; writes BENCH_OVERLAP_r13.json."""
    steps = 6 if quick else 16
    legs = {name: _overlap_comm_leg(name, steps)
            for name in ("zero2", "zero3")}
    roofline = [_overlap_roofline_row(leg) for leg in legs.values()]
    ckpt = _overlap_ckpt_leg(saves=6 if quick else 12)
    worst = max(leg["bucketed_vs_serial"] for leg in legs.values())
    doc = {
        "metric": "bucketed_overlap_step_time_vs_serial_two_phase",
        "unit": "ratio (lower is better; target <= 0.85)",
        "value": worst,
        "trajectory_bitwise_equal": all(
            leg["trajectory_bitwise_equal"] for leg in legs.values()),
        "checkpoint": ckpt,
        "checkpoint_target": "async_vs_sync_p99 < 0.2",
        "roofline": roofline,
        "roofline_target": ("bucketed_rel_error_overlap <= "
                            "bucketed_rel_error_additive on every leg; "
                            "serial errors coincide by construction"),
        "devices": 8,
        "platform": "cpu",
        "quick": bool(quick),
        "legs": legs,
        "note": ("host-level latency hiding on the emulated mesh: the "
                 "serial leg is the naive two-phase loop (backward "
                 "dispatch, host sync, per-bucket reduction "
                 "dispatches, sync, then next feed); the bucketed leg "
                 "is ONE fused dispatch with the barrier-chained "
                 "bucket schedule and the feed assembled while the "
                 "device runs.  Same bucket boundaries + elementwise "
                 "update => bitwise-equal trajectories; the delta is "
                 "pure dispatch/sync/feed stall"),
    }
    doc["host_fingerprint"] = host_fingerprint()
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_OVERLAP_r13.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    doc["artifact"] = out_path
    return doc


def _overlap_main(argv):
    # the 8-device CPU mesh is the point (dispatch structure, not FLOPs)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    kwargs = {}
    if "--quick" in argv:
        kwargs["quick"] = True
    if "--out" in argv:
        kwargs["out_path"] = argv[argv.index("--out") + 1]
    print(json.dumps(overlap_bench(**kwargs)))


def probe_backend(timeout: float, env: dict | None = None) \
        -> tuple[bool, str]:
    """Try `jax.devices()` in a subprocess with a hard timeout.

    A subprocess is the only reliable guard: the axon plugin can hang inside
    C++ without releasing the GIL, so an in-process watchdog thread could
    detect but never cancel it.  ``env`` overrides the child environment —
    the sweep-flag adoption path probes with candidate XLA_FLAGS applied,
    so a flag the (possibly fallen-back) backend would fatally reject
    aborts only the probe child, never this process.
    """
    try:
        r = subprocess.run(
            [sys.executable, "-c", PROBE_CODE],
            capture_output=True, text=True, timeout=timeout,
            env=dict(os.environ) if env is None else env,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout:.0f}s"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return False, (tail[-1] if tail else f"probe rc={r.returncode}")
    return True, r.stdout.strip()


def resolve_platform(attempts: int = 3, timeout: float = 150.0):
    """Probe TPU init with retry+backoff; fall back to CPU."""
    diags = []
    for i in range(attempts):
        ok, detail = probe_backend(timeout)
        if ok:
            diags.append(f"attempt {i + 1}: ok ({detail})")
            return detail.split()[0], diags
        diags.append(f"attempt {i + 1}: {detail}")
        time.sleep(min(10.0 * (2 ** i), 60.0))
    return "cpu", diags


def peak_flops_for(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for key, val in TPU_PEAK_FLOPS:
        if key in kind:
            return val
    return None


def host_fingerprint() -> dict:
    """Provenance block stamped into every ``--out`` artifact: cpu
    count, jax/jaxlib versions, platform/device kind and the resolved
    peak table.  The cost model's training join (analysis/costmodel.py)
    reads accumulated artifacts — numbers measured on a different
    host or toolchain must be distinguishable, not silently mixed.

    jax is consulted only when ALREADY imported: the data-pipeline
    bench is deliberately jax-free, and a cold ``jax.devices()`` here
    could hang on this image's flaky TPU plugin (see probe_backend).
    """
    import importlib.metadata

    def _ver(dist):
        try:
            return importlib.metadata.version(dist)
        except Exception:  # noqa: BLE001 - absent dist => null, not a crash
            return None

    from analytics_zoo_tpu.common.compile_cache import adopted_flags

    fp = {
        "cpu_count": os.cpu_count(),
        "jax_version": _ver("jax"),
        "jaxlib_version": _ver("jaxlib"),
        "platform": os.environ.get("JAX_PLATFORMS") or "unknown",
        "device_kind": "",
        "xla_flags_adopted": list(adopted_flags()),
    }
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            dev = jax.devices()[0]
            fp["platform"] = dev.platform
            fp["device_kind"] = getattr(dev, "device_kind", "") or ""
        except Exception:  # noqa: BLE001 - backend init failure
            pass
    try:
        from analytics_zoo_tpu.analysis.costmodel import resolve_peaks

        fp["peak_table"] = resolve_peaks(
            fp["platform"], fp["device_kind"]).to_doc()
    except Exception:  # noqa: BLE001 - bad ZOO_ORACLE_PEAKS etc.
        fp["peak_table"] = None
    return fp


def adopt_sweep_flags(probe=probe_backend, probe_timeout: float = 150.0,
                      path: str | None = None):
    """If the XLA flag sweep (tools/flag_sweep.py -> FLAGSWEEP_r05.json)
    found a combo beating baseline by >=1%, adopt its flags for the
    headline run.  Must run BEFORE any jax import: XLA_FLAGS is read at
    backend init.  Returns the adopted combo name or None.

    ADVICE r05 low (bench.py:136): the candidate flags are VALIDATED in a
    probe subprocess with XLA_FLAGS applied before this process commits
    to them.  `xla_tpu_*` flags are a fatal 'Unknown flag' abort on the
    CPU backend, so if the flagged probe fails or lands on a non-TPU
    platform, adoption is skipped and the a-number-always-lands contract
    survives.  Residual window: a plugin flaky enough to hand the probe
    child a TPU and the in-process init a CPU fallback still aborts
    (the C++ FATAL cannot be caught); the probe narrows the race to
    two inits moments apart but cannot close it."""
    if path is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "FLAGSWEEP_r05.json")
    try:
        with open(path) as f:
            sweep = json.load(f)
    except (OSError, ValueError):
        return None
    best, gain = sweep.get("best"), sweep.get("gain_pct")
    if not best or best == "baseline" or not gain or gain < 1.0:
        return None
    flags = sweep["results"][best]["flags"]
    candidate = (os.environ.get("XLA_FLAGS", "") + " " + flags).strip()
    ok, detail = probe(probe_timeout,
                       env=dict(os.environ, XLA_FLAGS=candidate))
    if not ok or not detail.startswith("tpu"):
        return None
    os.environ["XLA_FLAGS"] = candidate
    from analytics_zoo_tpu.common.compile_cache import (
        record_adopted_flags,
    )

    record_adopted_flags(flags.split())
    return f"{best} (+{gain}%)"


#: the XLA latency-hiding scheduler set (ISSUE 15): split collectives
#: into start/done pairs and let the scheduler hoist the starts behind
#: compute.  TPU-backend flags — a fatal 'Unknown flag' abort on CPU,
#: hence the same probe-validated, tpu-only adoption as the sweep
#: winners above.
LATENCY_HIDING_FLAGS = {
    "tpu": ("--xla_tpu_enable_latency_hiding_scheduler=true",
            "--xla_tpu_enable_async_collective_fusion=true"),
}


def adopt_latency_hiding_flags(probe=probe_backend,
                               probe_timeout: float = 150.0):
    """Adopt the async-collective / latency-hiding scheduler flag set
    for the headline run, per-platform and only when a probe subprocess
    WITH the flags applied still initializes a TPU (the
    adopt_sweep_flags contract: a flag the backend rejects aborts only
    the probe child, never this process).  Must run BEFORE any jax
    import.  Adopted flags are registered with
    ``compile_cache.record_adopted_flags`` so every subsequent compile
    stamps them into its zoo-hlo-report (``xla_flags``) and the bench
    ``host_fingerprint`` — a cost-model row says WHICH scheduler
    produced its graph.  Returns the adopted flag tuple or None."""
    flags = LATENCY_HIDING_FLAGS.get("tpu", ())
    if not flags:
        return None
    already = os.environ.get("XLA_FLAGS", "")
    new = tuple(f for f in flags if f not in already)
    if not new:
        return flags  # inherited from the environment; still record
    candidate = (already + " " + " ".join(new)).strip()
    ok, detail = probe(probe_timeout,
                       env=dict(os.environ, XLA_FLAGS=candidate))
    if not ok or not detail.startswith("tpu"):
        return None
    os.environ["XLA_FLAGS"] = candidate
    from analytics_zoo_tpu.common.compile_cache import (
        record_adopted_flags,
    )

    record_adopted_flags(flags)
    return flags


def main():
    if os.environ.get("ZOO_BENCH_FORCE_CPU"):
        platform, diags = "cpu", ["forced CPU rerun after mid-run TPU loss"]
    else:
        platform, diags = resolve_platform()
    fell_back = platform == "cpu"
    # adopt only once the platform resolved to TPU, and only after the
    # candidate flags survive a probe subprocess WITH the flags applied
    # (adopt_sweep_flags): the sweep's xla_tpu_* flags are a FATAL
    # 'Unknown flag' abort on the CPU backend, which would break every
    # fallback path's a-number-always-lands contract
    pre_adopt_flags = os.environ.get("XLA_FLAGS")
    adopted = None if fell_back else adopt_sweep_flags()
    lhs_adopted = None if fell_back else adopt_latency_hiding_flags()
    if fell_back:
        # Force-CPU the same way the test harness does; the axon plugin
        # ignores JAX_PLATFORMS, only the config knob is honored.
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if fell_back:
        jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from examples.resnet.train_imagenet import run

    # Re-check the ACTUAL in-process platform: the probe subprocess can
    # succeed while in-process init lands on CPU (flaky plugin).  Sizing
    # from the probe alone would run TPU-scale ResNet on CPU for hours.
    actual = jax.devices()[0].platform
    if actual != "tpu" and not fell_back:
        fell_back = True
        diags.append(f"in-process platform is {actual!r} despite probe ok")
    on_tpu = not fell_back
    # CPU fallback: shrink so a diagnostic number lands in minutes.
    try:
        r = run(
            image_size=224 if on_tpu else 64,
            per_chip_batch=256 if on_tpu else 16,
            steps=30 if on_tpu else 5,
        )
    except Exception as e:  # noqa: BLE001
        # The tunnel can die MID-RUN after a clean probe (observed: perf
        # stage lost at remote_compile, "connection reset by peer").  The
        # driver needs a JSON line regardless, and jax cannot re-init a
        # different backend in-process — re-exec ourselves forced to CPU
        # and forward that line with the TPU diagnostics attached.
        if not on_tpu:
            raise
        env = dict(os.environ, ZOO_BENCH_FORCE_CPU="1")
        # the child runs on CPU: it must not inherit adopted TPU-only
        # flags (fatal 'Unknown flag' on the CPU backend)
        if pre_adopt_flags is None:
            env.pop("XLA_FLAGS", None)
        else:
            env["XLA_FLAGS"] = pre_adopt_flags
        rr = subprocess.run([sys.executable, os.path.abspath(__file__)],
                            capture_output=True, text=True, env=env)
        line = (rr.stdout or "").strip().splitlines()
        if rr.returncode == 0 and line:
            try:
                doc = json.loads(line[-1])
            except json.JSONDecodeError:
                raise e  # surface the TPU failure, not the parse noise
            doc["note"] = "TPU lost mid-run; CPU fallback at reduced size"
            doc["tpu_init_diagnostics"] = diags + [
                f"mid-run failure: {str(e).splitlines()[0][:200]}"]
            print(json.dumps(doc))
            return
        raise
    ctx = r["ctx"]
    dp = max(ctx.data_parallel_size, 1)
    per_chip = r["e2e_ips"] / dp
    pure_per_chip = r["pure_ips"] / dp

    out = {
        "metric": "resnet50_imagenet_train_images_per_sec_per_chip",
        "xla_flags_adopted": adopted,
        "latency_hiding_flags_adopted": (list(lhs_adopted)
                                         if lhs_adopted else None),
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / A100_IMAGES_PER_SEC, 3),
        "pure_step_images_per_sec_per_chip": round(pure_per_chip, 1),
        "pure_step_ms": round(r["pure_step_ms"], 1),
        "pure_step_vs_baseline": round(pure_per_chip / A100_IMAGES_PER_SEC,
                                       3),
        "infeed_fraction": round(r["infeed_fraction"], 3),
        # What pins `value`: this harness's tunneled H2D link measures
        # ~30 MB/s (PROFILE_r03/ANALYSIS.md) so e2e is an ENVIRONMENT
        # ceiling, not framework speed — readers and gates keying on
        # `value` must check bound_by first.  pure_step_* is the portable
        # framework number; synthetic_infeed_* projects e2e on a healthy
        # (real TPU-VM) link where the uint8 infeed hides behind compute.
        "bound_by": ("infeed(env)" if r["infeed_fraction"] > 0.5
                     else "compute"),
        "synthetic_infeed_images_per_sec_per_chip": round(pure_per_chip, 1),
        "synthetic_infeed_note": (
            "e2e projection with device-resident data: on hardware whose "
            "H2D sustains > batch_bytes/step_time the uint8 infeed is "
            "fully hidden and e2e converges to pure_step"),
        "compiles_timed": r["compiles_timed"],
        "platform": ctx.platform,
        "devices": ctx.num_devices,
        "per_chip_batch": r["batch"] // dp,
        "image_size": r["image_size"],
        "steps_timed": r["steps_timed"],
    }
    if on_tpu and ctx.platform == "tpu":
        kind = jax.devices()[0].device_kind
        peak = peak_flops_for(kind)
        if peak:
            out["mfu_e2e"] = round(
                per_chip * RESNET50_TRAIN_FLOPS_PER_IMAGE / peak, 4)
            out["mfu_pure_step"] = round(
                pure_per_chip * RESNET50_TRAIN_FLOPS_PER_IMAGE / peak, 4)
            out["device_kind"] = kind
            out["peak_flops_assumed"] = peak
    if fell_back:
        out["note"] = "TPU backend unavailable; CPU fallback at reduced size"
        out["tpu_init_diagnostics"] = diags
    # Step-time breakdown from the metrics registry — the estimator's
    # built-in instrumentation (analytics_zoo_tpu.metrics), not a
    # bench-private timer: the same numbers a production scrape sees.
    from analytics_zoo_tpu.metrics import snapshot, write_jsonl

    breakdown = {}
    for s in snapshot()["samples"]:
        if s["name"] in ("zoo_train_data_wait_seconds",
                         "zoo_train_step_dispatch_seconds",
                         "zoo_train_step_seconds"):
            breakdown[s["name"]] = {
                k: round(float(s[k]), 6)
                for k in ("count", "p50", "p95", "p99")}
    if breakdown:
        out["step_breakdown"] = breakdown
    out["host_fingerprint"] = host_fingerprint()
    jsonl_path = os.environ.get("ZOO_METRICS_JSONL")
    if jsonl_path:
        write_jsonl(jsonl_path)
    print(json.dumps(out))


def _data_pipeline_main(argv):
    kwargs = {}
    if "--quick" in argv:
        # CPU-sized quick-tier configuration (also exercised by
        # tests/test_prefetch.py so pipeline regressions fail loudly)
        kwargs = dict(n_shards=4, shard_records=32, batch_size=8,
                      load_sleep_ms=15.0, transform_sleep_ms=1.0)
    if "--out" in argv:
        kwargs["out_path"] = argv[argv.index("--out") + 1]
    print(json.dumps(data_pipeline_bench(**kwargs)))


# ---------------------------------------------------------------------------
# --elastic: unattended chaos recovery bench (elastic/; ISSUE 16).  One
# 4-worker TrainSupervisor run over a dir: broker loses TWO workers mid-
# run — one to kill -9 (lease expiry), one to SIGTERM (graceful leave) —
# and regains both via respawn.  Reported: rejoin wall-time per
# generation change, steps replayed per fault, the full generation/
# decision timeline, and the trajectory's max |Δ| of final parameters
# against an uninterrupted in-process run of the SAME spec (the
# resume-from-LATEST + bit-exact-resharding contract; expect 0.0).
# Emits BENCH_ELASTIC_r14.json so recovery cost is pinned, not asserted.
# ---------------------------------------------------------------------------


def elastic_bench(quick: bool = False,
                  out_path: str | None = None) -> dict:
    import shutil
    import tempfile

    import numpy as np

    from analytics_zoo_tpu.elastic import ChaosSchedule, TrainSupervisor

    work = tempfile.mkdtemp(prefix="zoo-elastic-bench-")
    try:
        ck = os.path.join(work, "ckpt")
        spec = dict(ckpt_dir=ck, nb_epoch=4 if quick else 6,
                    plan="fsdp", k=1, throttle_s=0.08)
        total_steps = (256 // 32) * spec["nb_epoch"]
        chaos = ChaosSchedule.parse(
            f"kill@{total_steps // 3}:w1,term@{total_steps // 2}:w2")
        sup = TrainSupervisor(
            "dir:" + os.path.join(work, "spool"), spec, workers=4,
            lease_ms=800, min_workers=1, interval=0.1, chaos=chaos)
        t0 = time.time()
        res = sup.run(timeout_s=420)
        if res is None:
            raise RuntimeError(
                "elastic bench: cohort never posted its result; "
                "decisions=%r" % sup.decision_log())

        log = sup.decision_log()
        timeline = [dict(d, t=round(d["ts"] - t0, 3)) for d in log]
        for d in timeline:
            d.pop("ts")
        rejoin_s = [d["seconds"] for d in log
                    if d["action"] == "rejoined"]
        steps_lost = [
            {"generation": d["generation"], "steps_replayed":
             d["steps_lost"]}
            for d in log
            if d["action"] == "rejoin" and d["reason"] == "leave"]

        # uninterrupted oracle: same spec, straight through in-process
        import pickle

        import jax

        import analytics_zoo_tpu as zoo
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

        full = dict(TrainSupervisor.DEFAULT_SPEC, **spec)
        zoo.init_zoo_context(seed=full["seed"], mesh_shape={
            "data": min(4, len(jax.devices()))})
        m = Sequential()
        m.add(Dense(full["hidden"], activation="relu",
                    input_shape=(full["in_dim"],)))
        m.add(Dense(full["classes"], activation="softmax"))
        m.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy")
        rng = np.random.default_rng(full["seed"])
        x = rng.standard_normal(
            (full["n"], full["in_dim"])).astype(np.float32)
        y = rng.integers(0, full["classes"],
                         size=(full["n"],)).astype(np.int32)
        m.fit(x, y, batch_size=full["batch_size"],
              nb_epoch=full["nb_epoch"], plan=full["plan"])

        with open(os.path.join(ck, "LATEST")) as f:
            name = f.read().strip()
        with open(os.path.join(ck, name), "rb") as f:
            payload = pickle.load(f)
        diffs = [float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
                 for a, b in zip(
                     jax.tree_util.tree_leaves(payload["params"]),
                     jax.tree_util.tree_leaves(m.params))]
        traj_max_diff = max(diffs) if diffs else float("nan")

        doc = {
            "metric": "elastic_chaos_recovery",
            "unit": "max |Δ| of final params vs uninterrupted run",
            "platform": "cpu",
            "quick": bool(quick),
            "value": traj_max_diff,
            "workers": 4,
            "chaos": chaos.to_doc(),
            "final_step": res["final_step"],
            "steps_per_sec": round(res["steps_per_sec"], 3),
            "generations": res["generation"],
            "rejoin_seconds": [round(s, 3) for s in rejoin_s],
            "steps_replayed_per_fault": steps_lost,
            "repicks": sup.repick_log(),
            "timeline": timeline,
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)
    doc["host_fingerprint"] = host_fingerprint()
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_ELASTIC_r14.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    doc["artifact"] = out_path
    return doc


def _elastic_main(argv):
    # the workers and the in-process oracle leg both need the forced
    # 8-device CPU mesh (the supervisor folds world sizes onto it)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    kwargs = {}
    if "--quick" in argv:
        kwargs["quick"] = True
    if "--out" in argv:
        kwargs["out_path"] = argv[argv.index("--out") + 1]
    print(json.dumps(elastic_bench(**kwargs)))


# ---------------------------------------------------------------------------
# --federated: the zoowatch federation plane e2e (ISSUE 17).  Two legs:
#   1. federated_scaler_bench — a PROCESS-mode fleet whose replicas each
#      export /telemetryz on an ephemeral port; a VarzScraper discovers
#      them via the broker, feeds a TimeSeriesStore + SloEngine, and the
#      SloScaler runs ONLY on that federated view (the local registry is
#      never consulted) through a 10x offered-load step.  The story: the
#      burn-rate alert at /alertz fires BEFORE the estimated sojourn
#      hard-violates the serving SLO — the SLO spec's threshold is the
#      per-dispatch latency budget (batches filling up is the leading
#      indicator of saturation), so the multi-window burn crosses while
#      the client-visible p99 is still inside the SLO.
#   2. chaos_explainability_bench — a ChaosSchedule elastic run whose
#      per-process flight dumps are merged by tools/flight_merge.py onto
#      one wall-clock timeline; every generation change and respawn must
#      appear next to its cause event.
# Emits BENCH_FED_r15.json so both stories are pinned, not asserted.
# ---------------------------------------------------------------------------


def federated_scaler_bench(quick: bool = False) -> dict:
    import json as _json
    import shutil
    import tempfile
    import threading
    import urllib.request

    import numpy as np

    from analytics_zoo_tpu.metrics import (
        MetricsServer, SloEngine, SloSpec, TimeSeriesStore,
        VarzScraper, fleet_varz_targets)
    from analytics_zoo_tpu.serving import (
        ClusterServingHelper, InputQueue, OutputQueue)
    from analytics_zoo_tpu.serving.broker import connect_broker
    from analytics_zoo_tpu.serving.fleet import FleetController
    from analytics_zoo_tpu.serving.scaler import (
        FederatedSignalSource, SloScaler)

    service_ms = 20.0          # one replica saturates at ~50 rec/s
    slo_p99_ms = 400.0         # the HARD serving SLO (sojourn estimate)
    dispatch_budget_s = 0.08   # SLO-spec threshold: per-dispatch budget
    light_rps, heavy_rps = 8.0, 80.0  # the 10x step
    light_s = 3.0 if quick else 5.0
    heavy_s = 10.0 if quick else 18.0

    work = tempfile.mkdtemp(prefix="zoo-fed-bench-")
    spool = os.path.join(work, "spool")
    broker_spec = "dir:" + spool
    db = connect_broker(broker_spec)
    store = TimeSeriesStore(capacity=1024)
    spec = SloSpec(
        "predict_latency", "zoo_serving_predict_seconds",
        threshold=dispatch_budget_s, objective=0.95,
        short_window=1.5, long_window=6.0, burn_threshold=1.0,
        description="per-dispatch latency budget (early-warning tier "
                    "under the %.0fms sojourn SLO)" % slo_p99_ms)
    engine = SloEngine(store, [spec])
    scraper = VarzScraper(
        store=store, engine=engine, interval=0.2, timeout=5.0,
        discover=fleet_varz_targets(db))
    srv = MetricsServer(port=0).start()  # the /alertz the bench polls
    fed = FederatedSignalSource(store, db, "image_stream",
                                scraper=scraper)
    ctrl = FleetController(
        ClusterServingHelper(
            model_path=None, batch_size=8, batch_budget_ms=10.0,
            lease_ms=5_000, log_dir=os.path.join(work, "logs")),
        broker_spec,
        scaler=SloScaler(slo_p99_ms=slo_p99_ms, min_replicas=1,
                         max_replicas=3, up_windows=2,
                         down_windows=10_000),
        interval=0.4, mode="process", signal_source=fed,
        replica_metrics=True,
        replica_extra_args=("--synthetic-sleep-ms", str(service_ms)))

    t_wall0 = time.time()
    marks = {"alert": None, "hard_violation": None, "scale_up": None}
    timeline = []
    stop = threading.Event()

    def sampler():
        while not stop.is_set():
            now = time.time()
            cur = ctrl.current()
            win = cur["window"]
            # the sojourn estimate the scaler acts on, recomputed from
            # the federated window: predict p99 + backlog drain time
            est_ms = win["predict_p99_ms"]
            if win["queue_depth"]:
                est_ms = est_ms + (
                    win["queue_depth"] / win["service_rate"] * 1e3
                    if win["service_rate"] > 0 else float("inf"))
            if marks["hard_violation"] is None and est_ms > slo_p99_ms:
                marks["hard_violation"] = now
            if marks["scale_up"] is None:
                ups = [d for d in ctrl.decision_log()
                       if d["action"] == "up"]
                if ups:
                    marks["scale_up"] = ups[0]["ts"]
            if marks["alert"] is None:
                try:
                    with urllib.request.urlopen(
                            srv.url + "/alertz", timeout=2) as r:
                        if _json.load(r).get("firing"):
                            marks["alert"] = now
                except (OSError, ValueError):
                    pass
            timeline.append({
                "t_s": round(now - t_wall0, 2),
                "replicas": cur["replicas"], "hosts": cur["hosts"],
                "est_p99_ms": (None if est_ms == float("inf")
                               else round(est_ms, 1)),
            })
            time.sleep(0.1)

    served = {}
    outq = OutputQueue(broker=db)

    def collector():
        while not stop.is_set():
            served.update(outq.dequeue())
            time.sleep(0.01)

    scraper.start()
    ctrl.start()
    seq = 0
    try:
        # wait for discovery: the scraper must see the first replica's
        # /telemetryz before load starts (the federated view is the
        # ONLY view the scaler has)
        deadline = time.time() + 120
        while time.time() < deadline:
            hz = scraper.healthz()
            if hz["healthy"] and hz["targets"]:
                break
            time.sleep(0.1)
        else:
            raise RuntimeError(
                "scraper never discovered a replica: %r"
                % scraper.healthz())
        threading.Thread(target=sampler, daemon=True).start()
        threading.Thread(target=collector, daemon=True).start()
        inq = InputQueue(broker=db)
        rec = np.zeros((8,), np.float32)
        for rate, duration in ((light_rps, light_s),
                               (heavy_rps, heavy_s)):
            t_phase = time.perf_counter()
            while time.perf_counter() - t_phase < duration:
                inq.enqueue(f"q{seq}", rec)
                seq += 1
                time.sleep(1.0 / rate)
        deadline = time.time() + 240
        while len(served) < seq and time.time() < deadline:
            time.sleep(0.1)
    finally:
        stop.set()
        ctrl.stop()
        scraper.stop()
        srv.stop()
        shutil.rmtree(work, ignore_errors=True)

    cur = ctrl.current()
    hz = scraper.healthz()
    rel = lambda ts: None if ts is None else round(ts - t_wall0, 2)  # noqa: E731
    alert, hard = marks["alert"], marks["hard_violation"]
    return {
        "service_ms_per_record": service_ms,
        "slo_p99_ms": slo_p99_ms,
        "dispatch_budget_ms": dispatch_budget_s * 1e3,
        "load_step": {"light_rps": light_rps, "heavy_rps": heavy_rps,
                      "factor": heavy_rps / light_rps},
        "federated": cur["federated"],
        "enqueued": seq, "served": len(served),
        "alert_t_s": rel(alert),
        "hard_violation_t_s": rel(hard),
        "scale_up_t_s": rel(marks["scale_up"]),
        "alert_before_hard_violation": (
            alert is not None and (hard is None or alert <= hard)),
        "scaled_up": any(d["action"] == "up"
                         for d in ctrl.decision_log()),
        "max_replicas_seen": max(
            [t["replicas"] for t in timeline] + [1]),
        "hosts_seen": sorted({t["hosts"] for t in timeline
                              if t["hosts"] is not None}),
        "slo_spec": spec.to_doc(),
        "scrape_targets_final": len(hz["targets"]),
        "decisions": [
            {k: d.get(k) for k in ("action", "old", "new", "reason",
                                   "est_p99_ms", "queue_depth",
                                   "hosts", "hosts_target")}
            for d in ctrl.decision_log()],
        "alerts": engine.alerts(),
        "timeline": timeline[:: 2 if quick else 1],
    }


def chaos_explainability_bench(quick: bool = False,
                               keep_artifacts_in: str | None = None) \
        -> dict:
    import shutil
    import tempfile

    from analytics_zoo_tpu.elastic import ChaosSchedule, TrainSupervisor
    from analytics_zoo_tpu.metrics import get_flight_recorder

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    try:
        import flight_merge
    finally:
        sys.path.pop(0)

    work = tempfile.mkdtemp(prefix="zoo-fed-chaos-")
    flight_dir = os.path.join(work, "flight")
    try:
        spec = dict(ckpt_dir=os.path.join(work, "ckpt"),
                    nb_epoch=3 if quick else 4, plan="dp", k=1,
                    throttle_s=0.08)
        total_steps = (256 // 32) * spec["nb_epoch"]
        chaos = ChaosSchedule.parse(f"kill@{total_steps // 2}:w1")
        sup = TrainSupervisor(
            "dir:" + os.path.join(work, "spool"), spec, workers=3,
            lease_ms=800, min_workers=1, interval=0.1, chaos=chaos,
            worker_env={"ZOO_FLIGHT_DIR": flight_dir})
        run_start = time.time()
        res = sup.run(timeout_s=420)
        if res is None:
            raise RuntimeError(
                "chaos run never finished; decisions=%r"
                % sup.decision_log())
        # the supervisor's own ring is the third process-perspective
        # (workers dumped theirs on exit/SIGTERM; the SIGKILLed
        # incarnation could not — its death is explained by the
        # supervisor's chaos event instead).  Written directly so the
        # global recorder's dump-dir/once-per-reason state is untouched.
        os.makedirs(flight_dir, exist_ok=True)
        sup_doc = get_flight_recorder().to_doc("bench")
        # the process-global ring may hold elastic events from EARLIER
        # runs in this interpreter (other benches, earlier tests) whose
        # worker dumps are not in this run's flight_dir — they would
        # show up as uncaused effects.  Keep only this run's events.
        sup_doc["events"] = [e for e in sup_doc["events"]
                             if e.get("ts", 0.0) >= run_start]
        with open(os.path.join(
                flight_dir, f"flight-{os.getpid()}-bench.json"),
                "w") as f:
            json.dump(sup_doc, f)

        docs = flight_merge.load_inputs([flight_dir])
        merged = flight_merge.merge_flight_docs(docs)
        narrative = flight_merge.narrative_lines(merged)
        out_trace = os.path.join(
            keep_artifacts_in or os.path.dirname(
                os.path.abspath(__file__)),
            "BENCH_FED_r15_chaos_trace.json")
        flight_merge.write_outputs(merged, out=out_trace)

        elastic = [e for e in merged["timeline"]
                   if e.get("kind") == "elastic"]
        rejoins = [e for e in elastic if e.get("event") == "rejoin"]
        respawns = [e for e in elastic if e.get("event") == "respawn"]
        chaos_evs = [e for e in elastic if e.get("event") == "chaos"]

        def cause_of(effect):
            """Nearest earlier event that explains `effect` — the
            chaos kill, a worker leave/join, or a respawn."""
            causes = [e for e in elastic
                      if e["t"] <= effect["t"] and e is not effect
                      and e.get("event") in ("chaos", "leave", "join",
                                             "respawn")]
            return causes[-1] if causes else None

        explained = [
            {"event": e.get("event"), "t_s": round(
                e["t"] - merged["timeline"][0]["t"], 3),
             "generation": e.get("generation"),
             "cause": (cause_of(e) or {}).get("event"),
             "cause_src": (cause_of(e) or {}).get("src")}
            for e in rejoins + respawns]
        return {
            "workers": 3,
            "chaos": chaos.to_doc(),
            "final_step": res["final_step"],
            "flight_dumps_merged": merged["sources"],
            "timeline_events": len(merged["timeline"]),
            "skew": merged["skew"],
            "skew_beyond_tolerance": [
                s for s, v in merged["skew"].items()
                if v["beyond_tolerance"]],
            "generation_changes": len(rejoins),
            "respawns": len(respawns),
            "chaos_events_seen": len(chaos_evs),
            "all_effects_have_causes": all(
                r["cause"] is not None for r in explained),
            "explained": explained,
            "narrative_head": narrative[:40],
            "merged_trace_artifact": out_trace,
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


def federated_bench(quick: bool = False,
                    out_path: str | None = None) -> dict:
    doc = {
        "metric": "federated_slo_alert_lead_and_chaos_explainability",
        "unit": "alert fires before hard SLO violation (bool)",
        "platform": "cpu",
        "quick": bool(quick),
        "scaler": federated_scaler_bench(quick=quick),
        "explainability": chaos_explainability_bench(quick=quick),
    }
    doc["value"] = doc["scaler"]["alert_before_hard_violation"]
    doc["host_fingerprint"] = host_fingerprint()
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_FED_r15.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    doc["artifact"] = out_path
    return doc


def _federated_main(argv):
    # control-plane bench: subprocess replicas + elastic workers need
    # the forced 8-device CPU mesh, same as the elastic bench
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    kwargs = {}
    if "--quick" in argv:
        kwargs["quick"] = True
    if "--out" in argv:
        kwargs["out_path"] = argv[argv.index("--out") + 1]
    print(json.dumps(federated_bench(**kwargs)))


# ---------------------------------------------------------------------------
# --serving-predict: the predictive serving plane (ISSUE 20).  Three
# legs against the synthetic sleep model (the control-plane bench
# convention): (a) an oracle-primed fleet takes the BENCH_FED_r15 10x
# load step with zero hard SLO-violation windows where the reactive
# baseline accumulates seconds of violation, plus predicted-vs-measured
# predict-step latency per pad bucket; (b) a two-model router holds
# BOTH per-model p99 SLOs under skewed load; (c) under 20x overload the
# admission controller keeps accepted-work p99 under the SLO, sheds
# with typed retry-after, and the serve-log audit shows every accepted
# record served exactly once.  Emits BENCH_SERVE_r19.json.
# ---------------------------------------------------------------------------


def _serving_features(service_ms: float, buckets) -> dict:
    """Per-bucket cost-model features whose analytic predict time on
    the CPU peak table equals the synthetic model's service time
    (bucket * service_ms): flops = t * peak_flops, nothing else."""
    return {int(b): {"matmul_flops": int(b) * service_ms / 1e3 * 5e10,
                     "bytes_accessed": 0.0}
            for b in buckets}


def _p99(vals):
    if not vals:
        return None
    s = sorted(vals)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


def _load_step_run(quick: bool, prior_target=None) -> dict:
    """One 10x load-step run (light -> heavy, then drain) against a
    1-min fleet; ``prior_target`` seeds the scaler (the oracle-primed
    leg).  Returns the violation-window count the acceptance compares."""
    import threading

    import numpy as np

    from analytics_zoo_tpu.serving import InMemoryBroker, InputQueue, \
        OutputQueue
    from analytics_zoo_tpu.serving.scaler import SloScaler

    service_ms = 20.0          # one replica saturates at ~50 rec/s
    slo_p99_ms = 400.0
    light_rps, heavy_rps = 8.0, 80.0  # the BENCH_FED_r15 10x step
    light_s = 3.0 if quick else 5.0
    heavy_s = 6.0 if quick else 12.0
    interval = 0.25

    scaler = SloScaler(slo_p99_ms=slo_p99_ms, min_replicas=1,
                       max_replicas=3, up_windows=2,
                       down_windows=10_000, prior_target=prior_target)
    broker = InMemoryBroker()
    ctrl = _fleet_controller(broker, 1, service_ms, scaler=scaler,
                             interval=interval, slo_p99_ms=slo_p99_ms)
    inq = InputQueue(broker=broker)
    outq = OutputQueue(broker=broker)
    served = {}
    stop = threading.Event()
    violations = [0]
    timeline = []
    t0 = time.time()

    def sampler():
        while not stop.is_set():
            cur = ctrl.current()
            win = cur["window"]
            est_ms = win["predict_p99_ms"]
            if win["queue_depth"]:
                est_ms = est_ms + (
                    win["queue_depth"] / win["service_rate"] * 1e3
                    if win["service_rate"] > 0 else float("inf"))
            if est_ms > slo_p99_ms:
                violations[0] += 1
            timeline.append({
                "t_s": round(time.time() - t0, 2),
                "replicas": cur["replicas"],
                "est_p99_ms": (None if est_ms == float("inf")
                               else round(est_ms, 1))})
            time.sleep(0.1)

    def collector():
        while not stop.is_set():
            served.update(outq.dequeue())
            time.sleep(0.01)

    ctrl.start()
    seq = 0
    try:
        threading.Thread(target=sampler, daemon=True).start()
        threading.Thread(target=collector, daemon=True).start()
        rec = np.zeros((8,), np.float32)
        for rate, duration in ((light_rps, light_s),
                               (heavy_rps, heavy_s)):
            t_phase = time.perf_counter()
            while time.perf_counter() - t_phase < duration:
                inq.enqueue(f"q{seq}", rec)
                seq += 1
                time.sleep(1.0 / rate)
        deadline = time.time() + 120
        while len(served) < seq and time.time() < deadline:
            time.sleep(0.05)
    finally:
        stop.set()
        ctrl.stop()
    return {
        "prior_target": prior_target,
        "slo_p99_ms": slo_p99_ms,
        "load_step": {"light_rps": light_rps, "heavy_rps": heavy_rps,
                      "factor": heavy_rps / light_rps},
        "enqueued": seq, "served": len(served),
        "violation_windows": violations[0],
        "violation_seconds": round(violations[0] * 0.1, 2),
        "max_replicas_seen": max(
            [t["replicas"] for t in timeline] + [1]),
        "decisions": [
            {k: d.get(k) for k in ("action", "old", "new", "reason")}
            for d in ctrl.decision_log()],
        "timeline": timeline[:: 4 if quick else 2],
    }


def serving_predict_primed_bench(quick: bool = False) -> dict:
    """Leg (a): the same 10x load step twice — reactive baseline
    (scaler starts at min_replicas, scales on observed violation) vs
    oracle-primed (``choose_serving`` predicts the replica target from
    the per-bucket serving cost model and SEEDS the scaler).  Also
    closes the oracle's prediction log with measured per-bucket predict
    latencies so the rel_error lands per bucket."""
    import numpy as np

    from analytics_zoo_tpu.analysis.costmodel import resolve_peaks
    from analytics_zoo_tpu.analysis.oracle import ConfigOracle
    from analytics_zoo_tpu.serving.fleet import _SyntheticModel

    service_ms = 20.0
    heavy_rps = 80.0
    slo_p99_ms = 400.0
    buckets = (8, 16)
    reactive = _load_step_run(quick)

    oracle = ConfigOracle(peaks=resolve_peaks("cpu"))
    feats = _serving_features(service_ms, buckets)
    verdict = oracle.choose_serving(
        feats, slo_p99_ms=slo_p99_ms, offered_rate=heavy_rps,
        model="step")
    primed = _load_step_run(quick, prior_target=verdict["replicas"])

    # close the prediction -> outcome loop: measure the synthetic
    # model's real per-bucket service time and hand it back to the
    # oracle, so rel_error lands per bucket like every oracle pick
    model = _SyntheticModel(service_ms)
    rel_errors = {}
    for b in buckets:
        arr = np.zeros((b, 8), np.float32)
        t0 = time.perf_counter()
        model.predict(arr)
        measured_s = time.perf_counter() - t0
        oracle.record_outcome(f"serving:step:b{b}", 1.0 / measured_s,
                              consumer="serving")
    for row in oracle.prediction_log():
        if row["config"].startswith("serving:step:b") \
                and row.get("rel_error") is not None:
            rel_errors[row["config"]] = round(row["rel_error"], 4)
    return {
        "service_ms_per_record": service_ms,
        "verdict": verdict,
        "reactive": reactive,
        "primed": primed,
        "primed_zero_violations": primed["violation_windows"] == 0,
        "predict_rel_error_by_bucket": rel_errors,
    }


def serving_multi_model_bench(quick: bool = False) -> dict:
    """Leg (b): a two-model router under skewed load — a fast
    high-rate model and a slow low-rate one share ONE broker on
    per-model streams, and BOTH client-observed p99s stay under their
    own SLOs."""
    import threading

    import numpy as np

    from analytics_zoo_tpu.analysis.costmodel import resolve_peaks
    from analytics_zoo_tpu.analysis.oracle import ConfigOracle
    from analytics_zoo_tpu.serving import InMemoryBroker, InputQueue, \
        OutputQueue
    from analytics_zoo_tpu.serving.fleet import _SyntheticModel
    from analytics_zoo_tpu.serving.modelspec import ModelSpec
    from analytics_zoo_tpu.serving.router import ModelRouter

    service = {"fast": 5.0, "slow": 20.0}           # ms per record
    specs = [ModelSpec("fast", slo_p99_ms=300.0, offered_rate=60.0),
             ModelSpec("slow", slo_p99_ms=800.0, offered_rate=10.0)]
    duration = 4.0 if quick else 8.0

    broker = InMemoryBroker()
    oracle = ConfigOracle(peaks=resolve_peaks("cpu"))
    router = ModelRouter(
        broker, specs,
        model_factory=lambda spec: _SyntheticModel(service[spec.name]),
        oracle=oracle,
        features={name: _serving_features(ms, (8, 16))
                  for name, ms in service.items()},
        max_replicas=3, interval=0.25)
    t_enq = {}
    lock = threading.Lock()
    latencies = {"fast": [], "slow": []}
    stop = threading.Event()
    outq = OutputQueue(broker=broker)

    def collector():
        while not stop.is_set():
            done = outq.dequeue()
            now = time.perf_counter()
            with lock:
                for uri in done:
                    if uri in t_enq:
                        latencies[uri.split(":", 1)[0]].append(
                            now - t_enq.pop(uri))
            time.sleep(0.01)

    def load(name, rate):
        inq = InputQueue(broker=broker, model=name)
        rec = np.zeros((8,), np.float32)
        i = 0
        t_phase = time.perf_counter()
        while time.perf_counter() - t_phase < duration:
            uri = f"{name}:{i}"
            with lock:
                t_enq[uri] = time.perf_counter()
            inq.enqueue(uri, rec)
            i += 1
            time.sleep(1.0 / rate)

    router.start()
    try:
        threading.Thread(target=collector, daemon=True).start()
        loaders = [threading.Thread(
            target=load, args=(s.name, s.offered_rate)) for s in specs]
        for t in loaders:
            t.start()
        for t in loaders:
            t.join()
        deadline = time.time() + 60
        while time.time() < deadline:
            with lock:
                if not t_enq:
                    break
            time.sleep(0.05)
    finally:
        stop.set()
        router.stop()

    out = {"models": {}}
    all_met = True
    for s in specs:
        p99 = _p99(latencies[s.name])
        met = p99 is not None and p99 * 1e3 < s.slo_p99_ms
        all_met = all_met and met
        out["models"][s.name] = {
            "slo_p99_ms": s.slo_p99_ms,
            "offered_rate": s.offered_rate,
            "served": len(latencies[s.name]),
            "client_p99_ms": (None if p99 is None
                              else round(p99 * 1e3, 1)),
            "slo_met": met,
            "verdict": router.verdict(s.name),
        }
    out["router_decisions"] = router.decision_log()
    out["both_slos_met"] = all_met
    return out


def serving_admission_bench(quick: bool = False) -> dict:
    """Leg (c): 20x overload through the admission-guarded router —
    the front door sheds with typed retry-after, accepted-work p99
    stays under the SLO, and the serve-log audit shows every accepted
    record served exactly once (trim is OFF on the guarded stream)."""
    import collections
    import tempfile
    import threading

    import numpy as np

    from analytics_zoo_tpu.analysis.costmodel import resolve_peaks
    from analytics_zoo_tpu.analysis.oracle import ConfigOracle
    from analytics_zoo_tpu.serving import InMemoryBroker, InputQueue, \
        OutputQueue, ServingRejected
    from analytics_zoo_tpu.serving.fleet import _SyntheticModel
    from analytics_zoo_tpu.serving.modelspec import ModelSpec
    from analytics_zoo_tpu.serving.router import ModelRouter

    service_ms = 10.0
    slo_p99_ms = 500.0
    light_rps, overload_rps = 12.5, 250.0  # the 20x overload
    light_s = 2.0
    overload_s = 4.0 if quick else 8.0

    broker = InMemoryBroker()
    oracle = ConfigOracle(peaks=resolve_peaks("cpu"))
    serve_log = tempfile.NamedTemporaryFile(
        prefix="zoo-admission-audit-", suffix=".log", delete=False)
    serve_log.close()
    router = ModelRouter(
        broker,
        [ModelSpec("gate", slo_p99_ms=slo_p99_ms,
                   offered_rate=overload_rps)],
        model_factory=lambda spec: _SyntheticModel(service_ms),
        oracle=oracle,
        features={"gate": _serving_features(service_ms, (8, 16))},
        admission=True, max_replicas=2, interval=0.25,
        serve_log=serve_log.name,
        admission_kwargs={"backlog_limit": 20, "interval": 0.05})
    t_enq = {}
    lock = threading.Lock()
    latencies = []
    rejections = []
    stop = threading.Event()
    outq = OutputQueue(broker=broker)

    def collector():
        while not stop.is_set():
            done = outq.dequeue()
            now = time.perf_counter()
            with lock:
                for uri in done:
                    if uri in t_enq:
                        latencies.append(now - t_enq.pop(uri))
            time.sleep(0.01)

    accepted = []
    router.start()
    try:
        threading.Thread(target=collector, daemon=True).start()
        inq = InputQueue(broker=broker, model="gate")
        rec = np.zeros((8,), np.float32)
        seq = 0
        phase_base = 0
        for rate, duration in ((light_rps, light_s),
                               (overload_rps, overload_s)):
            t_phase = time.perf_counter()
            while True:
                elapsed = time.perf_counter() - t_phase
                if elapsed >= duration:
                    break
                # rate-paced without per-record sleeps: catch the
                # enqueue count up to the offered-rate schedule
                due = phase_base + int(elapsed * rate)
                while seq < due:
                    uri = f"a{seq}"
                    seq += 1
                    try:
                        with lock:
                            t_enq[uri] = time.perf_counter()
                        inq.enqueue(uri, rec)
                        accepted.append(uri)
                    except ServingRejected as e:
                        with lock:
                            t_enq.pop(uri, None)
                        rejections.append(e.retry_after_s)
                time.sleep(0.002)
            phase_base = seq
        deadline = time.time() + 90
        while time.time() < deadline:
            with lock:
                if not t_enq:
                    break
            time.sleep(0.05)
    finally:
        stop.set()
        router.stop()

    with open(serve_log.name) as f:
        served_uris = [line.split()[-1] for line in f
                       if line.strip()]
    os.unlink(serve_log.name)
    counts = collections.Counter(served_uris)
    audit_ok = (set(counts) == set(accepted)
                and all(c == 1 for c in counts.values()))
    p99 = _p99(latencies)
    return {
        "service_ms_per_record": service_ms,
        "slo_p99_ms": slo_p99_ms,
        "overload": {"light_rps": light_rps,
                     "overload_rps": overload_rps,
                     "factor": overload_rps / light_rps},
        "offered": len(accepted) + len(rejections),
        "accepted": len(accepted),
        "rejected": len(rejections),
        "shed_fraction": round(
            len(rejections) / max(len(accepted) + len(rejections), 1),
            3),
        "accepted_p99_ms": (None if p99 is None
                            else round(p99 * 1e3, 1)),
        "accepted_p99_under_slo": (p99 is not None
                                   and p99 * 1e3 < slo_p99_ms),
        "retry_after_s": {
            "min": round(min(rejections), 3) if rejections else None,
            "max": round(max(rejections), 3) if rejections else None,
        },
        "all_rejections_carry_retry_after": (
            bool(rejections) and all(r > 0 for r in rejections)),
        "served": len(latencies),
        "audit_exactly_once": audit_ok,
        "admission_decisions": (
            router.admission("gate").decision_log()
            if router.admission("gate") is not None else []),
    }


def serving_predict_bench(quick: bool = False,
                          out_path: str | None = None) -> dict:
    doc = {
        "metric": "predictive_serving_primed_violations_and_admission",
        "unit": "primed fleet violation windows (0 = SLO held through "
                "the 10x step)",
        "platform": "cpu",
        "quick": bool(quick),
        "primed_vs_reactive": serving_predict_primed_bench(quick=quick),
        "multi_model": serving_multi_model_bench(quick=quick),
        "admission": serving_admission_bench(quick=quick),
    }
    leg_a = doc["primed_vs_reactive"]
    doc["value"] = leg_a["primed"]["violation_windows"]
    doc["acceptance"] = {
        "primed_no_worse_than_reactive": (
            leg_a["primed"]["violation_windows"]
            <= leg_a["reactive"]["violation_windows"]),
        "both_model_slos_met": doc["multi_model"]["both_slos_met"],
        "accepted_p99_under_slo":
            doc["admission"]["accepted_p99_under_slo"],
        "audit_exactly_once": doc["admission"]["audit_exactly_once"],
    }
    doc["host_fingerprint"] = host_fingerprint()
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_SERVE_r19.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    doc["artifact"] = out_path
    return doc


def _serving_predict_main(argv):
    # control-plane bench: synthetic models, no mesh — plain CPU
    os.environ["JAX_PLATFORMS"] = "cpu"
    kwargs = {}
    if "--quick" in argv:
        kwargs["quick"] = True
    if "--out" in argv:
        kwargs["out_path"] = argv[argv.index("--out") + 1]
    print(json.dumps(serving_predict_bench(**kwargs)))


if __name__ == "__main__":
    if "--partition" in sys.argv:
        _partition_main(sys.argv[1:])
    elif "--memory" in sys.argv:
        _memory_main(sys.argv[1:])
    elif "--precision" in sys.argv:
        _precision_main(sys.argv[1:])
    elif "--kernels" in sys.argv:
        _kernels_main(sys.argv[1:])
    elif "--data-pipeline" in sys.argv:
        _data_pipeline_main(sys.argv[1:])
    elif "--fleet" in sys.argv:
        _fleet_main(sys.argv[1:])
    elif "--autotune" in sys.argv:
        _autotune_main(sys.argv[1:])
    elif "--oracle" in sys.argv:
        _oracle_main(sys.argv[1:])
    elif "--overlap" in sys.argv:
        _overlap_main(sys.argv[1:])
    elif "--elastic" in sys.argv:
        _elastic_main(sys.argv[1:])
    elif "--federated" in sys.argv:
        _federated_main(sys.argv[1:])
    elif "--serving-predict" in sys.argv:
        _serving_predict_main(sys.argv[1:])
    elif "--dispatch-child" in sys.argv:
        _dispatch_child_main(sys.argv[1:])
    elif "--dispatch" in sys.argv:
        _dispatch_main(sys.argv[1:])
    else:
        main()
