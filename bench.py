"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline (BASELINE.md): ResNet-50 ImageNet training throughput,
images/sec/chip.  The reference publishes no absolute numbers (its story is
scaling factors on Xeon clusters, docs/docs/wp-bigdl.md); the BASELINE.json
north star is ">= A100-class images/sec/chip".  vs_baseline is therefore
reported against a 2500 img/s A100 figure (public MLPerf-era ResNet-50
mixed-precision single-A100 training throughput ballpark).
"""

import json
import time

import numpy as np

A100_IMAGES_PER_SEC = 2500.0


def main():
    import jax

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.resnet import ResNet

    ctx = init_zoo_context(seed=0)
    model = ResNet.image_net(50, classes=1000, input_shape=(224, 224, 3))
    model.compile(
        optimizer=ResNet.imagenet_optimizer(batch_size=128,
                                            steps_per_epoch=100),
        loss="sparse_categorical_crossentropy",
    )

    batch = 128 * max(ctx.data_parallel_size, 1)
    steps = 20
    n = batch * steps
    x = np.random.default_rng(0).normal(size=(n, 224, 224, 3)).astype(
        np.float32)
    y = np.random.default_rng(1).integers(0, 1000, size=(n,)).astype(
        np.int32)

    # warmup epoch (includes compile)
    model.fit(x[:batch * 2], y[:batch * 2], batch_size=batch, nb_epoch=1)
    t0 = time.perf_counter()
    model.fit(x, y, batch_size=batch, nb_epoch=1)
    dt = time.perf_counter() - t0
    ips = n / dt
    per_chip = ips / max(ctx.data_parallel_size, 1)
    print(json.dumps({
        "metric": "resnet50_imagenet_train_images_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / A100_IMAGES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
