#!/usr/bin/env python
"""Merge per-process flight dumps (+ optional Chrome traces) onto ONE
wall-clock timeline.

Every zoo process keeps its own flight ring and trace clock — each is
self-consistent but says nothing about the others.  ISSUE 17 gave both
a ``(monotonic, epoch)`` anchor: flight events carry ``mono``
(CLOCK_MONOTONIC — shared by every process of one boot) next to ``ts``
(epoch), and traces carry a ``clock_anchor`` in their metadata mapping
trace-µs 0 to both clocks.  This tool consumes the anchors:

1. every input's per-process ``epoch - monotonic`` offset is estimated;
2. the MEDIAN offset becomes the reference clock — so one process with
   a skewed wall clock is corrected toward the cohort instead of
   dragging the merged timeline with it (same-host processes share
   CLOCK_MONOTONIC exactly, making the correction exact there);
3. all events are emitted on the reference timeline, as
   - a **narrative**: one chronological line per flight event, tagged
     with its source process — the artifact that explains a chaos run
     end-to-end (every generation change, takeover and respawn appears
     next to its cause), and
   - a **merged Chrome trace**: flight events as instant events plus
     every input trace's spans shifted onto the shared clock — load the
     single file in Perfetto and see the whole pod.

Usage::

    python tools/flight_merge.py FLIGHT_DIR_OR_FILES...
        [--trace trace.json ...] [--out merged_trace.json]
        [--narrative narrative.txt] [--skew-tolerance-s 0.25]

Library surface (used by tests and bench.py): :func:`load_inputs`,
:func:`merge_flight_docs`, :func:`write_outputs`.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def load_inputs(paths) -> list[dict]:
    """Flight docs from files, directories (``flight-*.json``), or
    globs; each doc is tagged with its source path."""
    files = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(
                os.path.join(p, "flight-*.json"))))
        elif any(ch in p for ch in "*?["):
            files.extend(sorted(glob.glob(p)))
        else:
            files.append(p)
    docs = []
    for f in files:
        try:
            with open(f) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"flight_merge: skipping {f}: {e}", file=sys.stderr)
            continue
        doc["_path"] = f
        docs.append(doc)
    return docs


def _doc_offset(doc: dict) -> float | None:
    """This process's ``epoch - monotonic`` offset, from the doc anchor
    or (better — closer to the events) the median per-event pair."""
    pairs = [(e["ts"], e["mono"]) for e in doc.get("events", ())
             if "mono" in e and "ts" in e]
    if pairs:
        offs = sorted(ts - mono for ts, mono in pairs)
        return offs[len(offs) // 2]
    anchor = doc.get("clock_anchor") or {}
    if "epoch" in anchor and "monotonic" in anchor:
        return float(anchor["epoch"]) - float(anchor["monotonic"])
    return None


def merge_flight_docs(docs: list[dict],
                      skew_tolerance_s: float = 0.25) -> dict:
    """One timeline from many flight docs.

    Returns ``{"timeline": [...], "skew": {...}, "sources": n}`` —
    timeline events carry ``t`` (reference epoch seconds), ``src``
    (``pid@reason`` of the dump), and the original fields.  ``skew``
    reports each source's wall-clock offset from the cohort median and
    whether it exceeded ``skew_tolerance_s`` (corrected either way when
    the event has a ``mono`` field; epoch-only events are trusted
    as-is)."""
    offsets = {}
    for i, doc in enumerate(docs):
        off = _doc_offset(doc)
        if off is not None:
            offsets[i] = off
    ref = None
    if offsets:
        vals = sorted(offsets.values())
        ref = vals[len(vals) // 2]
    timeline = []
    skew = {}
    for i, doc in enumerate(docs):
        src = "%s@%s" % (doc.get("pid", "?"), doc.get("reason", "?"))
        off = offsets.get(i)
        if off is not None and ref is not None:
            skew[src] = {
                "offset_s": round(off - ref, 6),
                "beyond_tolerance":
                    abs(off - ref) > skew_tolerance_s,
                "path": doc.get("_path"),
            }
        for ev in doc.get("events", ()):
            if "mono" in ev and ref is not None:
                # the shared monotonic clock + reference offset beats
                # trusting this process's wall clock
                t = float(ev["mono"]) + ref
            else:
                t = float(ev.get("ts", 0.0))
            timeline.append({"t": t, "src": src, **{
                k: v for k, v in ev.items() if k != "mono"}})
    timeline.sort(key=lambda e: e["t"])
    return {"timeline": timeline, "skew": skew, "sources": len(docs)}


def narrative_lines(merged: dict) -> list[str]:
    """Human-readable chronology: relative seconds, source, kind, and
    the event's own fields."""
    timeline = merged["timeline"]
    if not timeline:
        return []
    t0 = timeline[0]["t"]
    lines = []
    for ev in timeline:
        fields = " ".join(
            f"{k}={ev[k]}" for k in sorted(ev)
            if k not in ("t", "ts", "src", "kind"))
        lines.append("%10.3fs  %-16s %-14s %s" % (
            ev["t"] - t0, ev["src"], ev.get("kind", "?"), fields))
    return lines


def _load_traces(paths) -> list[dict]:
    out = []
    for p in paths:
        try:
            with open(p) as fh:
                out.append(json.load(fh))
        except (OSError, json.JSONDecodeError) as e:
            print(f"flight_merge: skipping trace {p}: {e}",
                  file=sys.stderr)
    return out


def merged_chrome_trace(merged: dict, traces=()) -> dict:
    """Flight events as instant events + input traces' spans, all on
    the reference clock (µs since the merged timeline's first event)."""
    timeline = merged["timeline"]
    t0 = timeline[0]["t"] if timeline else 0.0
    events = []
    for ev in timeline:
        args = {k: v for k, v in ev.items()
                if k not in ("t", "src", "kind")}
        pid = ev["src"].split("@", 1)[0]
        events.append({
            "name": ev.get("kind", "?"), "ph": "i", "s": "p",
            "ts": max(0.0, (ev["t"] - t0) * 1e6),
            "pid": int(pid) if str(pid).isdigit() else 0,
            "tid": 0, "cat": "flight", "args": args,
        })
    for doc in traces:
        anchor = (doc.get("metadata") or {}).get("clock_anchor") or {}
        epoch0 = anchor.get("epoch")
        if epoch0 is None:
            continue  # unanchored trace: cannot place on shared clock
        shift_us = (float(epoch0) - t0) * 1e6
        for ev in doc.get("traceEvents", ()):
            ev = dict(ev)
            ev["ts"] = float(ev.get("ts", 0.0)) + shift_us
            events.append(ev)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "producer": "tools/flight_merge.py",
            "sources": merged["sources"],
            "skew": merged["skew"],
            "t0_epoch": t0,
        },
    }


def write_outputs(merged: dict, traces=(), out: str | None = None,
                  narrative: str | None = None) -> dict:
    paths = {}
    if out:
        with open(out, "w") as f:
            json.dump(merged_chrome_trace(merged, traces), f)
        paths["trace"] = out
    if narrative:
        with open(narrative, "w") as f:
            f.write("\n".join(narrative_lines(merged)) + "\n")
        paths["narrative"] = narrative
    return paths


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="flight_merge",
        description="merge per-process flight dumps (and traces) onto "
                    "one wall-clock timeline")
    p.add_argument("inputs", nargs="+",
                   help="flight dump files, dirs, or globs")
    p.add_argument("--trace", action="append", default=[],
                   help="Chrome-trace JSON to fold in (repeatable)")
    p.add_argument("--out", default=None,
                   help="write merged Chrome trace JSON here")
    p.add_argument("--narrative", default=None,
                   help="write the event narrative here (default: "
                        "stdout)")
    p.add_argument("--skew-tolerance-s", type=float, default=0.25,
                   help="flag sources whose wall clock deviates more "
                        "than this from the cohort median")
    a = p.parse_args(argv)

    docs = load_inputs(a.inputs)
    if not docs:
        print("flight_merge: no flight dumps found", file=sys.stderr)
        return 2
    merged = merge_flight_docs(docs,
                               skew_tolerance_s=a.skew_tolerance_s)
    traces = _load_traces(a.trace)
    write_outputs(merged, traces, out=a.out, narrative=a.narrative)
    if not a.narrative:
        for line in narrative_lines(merged):
            print(line)
    bad = [s for s, v in merged["skew"].items()
           if v["beyond_tolerance"]]
    print(f"# {merged['sources']} sources, "
          f"{len(merged['timeline'])} events"
          + (f", skew beyond tolerance: {', '.join(bad)}" if bad
             else ""), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
