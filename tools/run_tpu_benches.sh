#!/bin/bash
# Round-4 TPU bench queue: waits for the axon tunnel to answer, then runs
# every TPU-dependent artifact producer sequentially (ONE process on the
# chip at a time — concurrent clients wedge the tunnel).
# Usage: bash tools/run_tpu_benches.sh [logdir]
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_benches}
mkdir -p "$LOG"

probe() {
  timeout 90 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" \
    >/dev/null 2>&1
}

echo "$(date) waiting for TPU..." | tee -a "$LOG/queue.log"
until probe; do
  sleep 120
done
echo "$(date) TPU is back — running queue" | tee -a "$LOG/queue.log"

run() {
  name=$1; shift
  echo "$(date) START $name" | tee -a "$LOG/queue.log"
  timeout 3000 "$@" >"$LOG/$name.log" 2>&1
  rc=$?  # capture BEFORE $(date) resets $?
  echo "$(date) DONE $name rc=$rc" | tee -a "$LOG/queue.log"
}

# 1. flash kernel micro-bench (clean vs train configs) -> FLASH_r04.json
run flash python tools/flash_bench.py

# 2. transformer at the honest config -> TRANSFORMER_r04.json
run transformer python tools/transformer_bench.py \
  --seq 2048 --batch 8 --blocks 8 --hidden 2560 --heads 20 --steps 8 \
  --remat --out TRANSFORMER_r04.json

# 3. serving latency on the real chip -> SERVING_r04.json
run serving python tools/serving_bench.py --rate 200 --n 2000

# 4. pure-step probe (the Task-4 number)
run perf python tools/perf_probe.py --batch 256 --steps 20

# 5. headline bench line
run bench python bench.py

echo "$(date) queue complete" | tee -a "$LOG/queue.log"
