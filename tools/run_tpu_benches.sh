#!/bin/bash
# Round-4 TPU bench queue: waits for the axon tunnel to answer, then runs
# every TPU-dependent artifact producer sequentially (ONE process on the
# chip at a time — concurrent clients wedge the tunnel; a client killed
# mid-compile wedges it for hours).
#
# Lessons encoded here:
# - serialize chip access; never run an ad-hoc python on the chip while
#   this queue runs (JAX_PLATFORMS env alone does NOT keep a script off
#   the axon plugin — only jax.config.update("jax_platforms", "cpu")).
# - bench.py's e2e path needs the HOST core for infeed generation: do not
#   run the pytest suite concurrently or e2e crawls ~10x (measured
#   2026-07-30: 50 min vs ~4 min idle).
# - serving on the tunneled chip sustains ~143 rps at batch 16; offer 100
#   for a stable-queue latency artifact (200 measures saturation only).
# Usage: bash tools/run_tpu_benches.sh [logdir]
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_benches}
mkdir -p "$LOG"

probe() {
  timeout 90 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" \
    >/dev/null 2>&1
}

wait_for_tpu() {
  echo "$(date) waiting for TPU..." | tee -a "$LOG/queue.log"
  until probe; do
    sleep 120
  done
  echo "$(date) TPU answered" | tee -a "$LOG/queue.log"
}

# The tunnel can drop MID-QUEUE (it did at 01:28 on 2026-07-31, killing
# the transformer stage at its first remote_compile): re-probe before
# every stage and retry each failed stage once after the tunnel returns.
run() {
  name=$1; tmo=$2; shift 2
  for attempt in 1 2; do
    wait_for_tpu
    echo "$(date) START $name (attempt $attempt)" | tee -a "$LOG/queue.log"
    timeout "$tmo" "$@" >"$LOG/$name.log" 2>&1
    rc=$?  # capture BEFORE $(date) resets $?
    echo "$(date) DONE $name rc=$rc" | tee -a "$LOG/queue.log"
    [ "$rc" -eq 0 ] && break
    # only a dead tunnel earns a retry; a real failure (tunnel still
    # answering) is a bug in the bench and repeats identically
    if probe; then
      echo "$(date) $name failed with TPU alive — not retrying" \
        | tee -a "$LOG/queue.log"
      break
    fi
  done
}

# 1. flash kernel micro-bench (clean vs train configs) -> FLASH_r04.json
run flash 3000 python tools/flash_bench.py

# 2. transformer at the honest config -> TRANSFORMER_r04.json
run transformer 3600 python tools/transformer_bench.py \
  --seq 2048 --batch 8 --blocks 8 --hidden 2560 --heads 20 --steps 8 \
  --remat --out TRANSFORMER_r04.json

# 3. serving latency on the real chip at a sustainable offered load
run serving 1800 python tools/serving_bench.py --rate 100 --n 1500

# 4. pure-step + dispatch/H2D/matmul probes (device-resident, fetch-forced)
run perf 3000 python tools/perf_probe.py --batch 256 --steps 20

# 5. jax.profiler trace of the pure step -> PROFILE_r04/ (the roofline
# evidence for the remaining pure-step gap)
run profile 3000 python tools/profile_step.py 256

# 6. headline bench line (host-infeed heavy: keep the core free)
run bench 4800 python bench.py

echo "$(date) queue complete" | tee -a "$LOG/queue.log"
