#!/bin/bash
# Round-5 TPU bench queue: waits for the axon tunnel to answer, then runs
# every TPU-dependent artifact producer sequentially.  Queue machinery
# (probe / wait_for_tpu / run with tunnel-death retry) lives in
# tpu_queue_lib.sh.
#
# Stage order puts the VERDICT top-next artifacts (flash + transformer,
# which need the fixed backward kernels) before the perf/profile retry
# and the bench headline, so a short tunnel window still lands the most
# valuable numbers first.  Serving is last: SERVING_r04.json already
# carries a real-chip stable-queue run.
# Usage: bash tools/run_tpu_benches.sh [logdir]
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_benches}
mkdir -p "$LOG"
. tools/tpu_queue_lib.sh || exit 1  # cwd is the repo root after the cd above

# 1. flash kernel micro-bench (clean vs train configs) -> FLASH_r05.json
run flash 3600 python tools/flash_bench.py

# 2. transformer at the honest config -> TRANSFORMER_r05.json
run transformer 4800 python tools/transformer_bench.py \
  --seq 2048 --batch 8 --blocks 8 --hidden 2560 --heads 20 --steps 8 \
  --remat --out TRANSFORMER_r05.json

# 2a. remat-policy sweep point: 'attn' saves only the per-block attention
#     context (less recompute than full) — whichever wins becomes the
#     headline MFU claim
run transformer_attn 4800 python tools/transformer_bench.py \
  --seq 2048 --batch 8 --blocks 8 --hidden 2560 --heads 20 --steps 8 \
  --remat attn --out TRANSFORMER_r05_attn.json

# 2b. transformer convergence artifact (curve + resume through the Pallas
#     backward, bf16 + remat + in-kernel dropout) -> ACCURACY_r05.json
run convergence 4800 python tools/transformer_convergence.py

# 3. pure-step + dispatch/H2D/matmul probes (device-resident, fetch-forced)
run perf 3000 python tools/perf_probe.py --batch 256 --steps 20

# 3b. r03->r04 drop bisect (interleaved repeats + control, 4 fresh
#     estimator builds) -> PERF_BISECT_r05.json.  Generous timeout: a
#     SIGTERM mid-compile wedges the tunnel (PERF_r04_STATUS lesson #1)
run bisect 5400 python tools/perf_probe.py --bisect --batch 256 --steps 20

# 3c. XLA flag sweep over the pure step -> FLAGSWEEP_r05.json (each
#     combo is a fresh subprocess with its own 2400s budget; bad-flag or
#     slow combos are contained; stage budget covers all 4 combos)
run flagsweep 10800 python tools/flag_sweep.py --batch 256 --steps 20

# 4. jax.profiler trace of the pure step -> PROFILE_r05/
run profile 3000 python tools/profile_step.py 256

# 5. per-fusion roofline table from the trace -> ROOFLINE_r05.json
run roofline 2400 python tools/roofline_table.py 256 PROFILE_r05 \
  --json ROOFLINE_r05.json

# 6. headline bench line (host-infeed heavy: keep the core free)
run bench 4800 python bench.py

# 7. serving latency at a sustainable offered load (merge-don't-clobber)
run serving 1800 python tools/serving_bench.py --rate 100 --n 1500

# 8. accuracy-parity artifacts on the chip (lenet >=0.99 w/ augmentation,
#    resume curve, resnet shapes) -> ACCURACY_r05.json
run accuracy 5400 python tools/accuracy_bench.py

echo "$(date) queue complete" | tee -a "$LOG/queue.log"
