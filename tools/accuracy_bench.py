"""Accuracy-parity evidence (VERDICT r03 missing #1): train flagship
recipes to convergence, record the full curve, and prove checkpoint-resume
reproduces it.  Writes ACCURACY_r05.json.

Dataset reality in this sandbox: there is NO network egress and no
MNIST/CIFAR archive on disk, so the reference configs are anchored as:

* ``lenet_digits`` — LeNet on scikit-learn's bundled **real** handwritten
  digits (1797 8x8 images, upscaled 2x), the closest available stand-in
  for the LeNet/MNIST config (BASELINE.json config 1).
* ``resnet_shapes`` — ResNet-20 (CIFAR topology, models/resnet.py:122)
  on a procedurally generated 10-class 32x32x3 shapes dataset with
  nuisance variation (position/scale/rotation/color/noise), trained with
  the TrainImageNet.scala:36-120 recipe equivalent (linear warmup + epoch
  decay, momentum, weight decay) scaled to the small run.

* ``resume`` — the lenet run is repeated with a mid-training stop +
  checkpoint-resume; the resumed loss curve must match the uninterrupted
  one (exact (epoch, cursor, seed) iterator resume, feature/dataset.py).

Usage: python tools/accuracy_bench.py [--configs lenet,resnet,resume]
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def digits_data():
    from sklearn.datasets import load_digits

    d = load_digits()
    x = (d.images / 16.0).astype(np.float32)
    x = np.kron(x, np.ones((1, 2, 2), np.float32))[..., None]  # 16x16
    y = d.target.astype(np.int32)
    rng = np.random.default_rng(0)
    idx = rng.permutation(len(x))
    x, y = x[idx], y[idx]
    n_train = 1536
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])


def _lenet16():
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Convolution2D,
        Dense,
        Flatten,
        MaxPooling2D,
    )

    m = Sequential(name="lenet16")
    m.add(Convolution2D(6, 5, 5, activation="tanh", border_mode="same",
                        input_shape=(16, 16, 1)))
    m.add(MaxPooling2D())
    m.add(Convolution2D(16, 5, 5, activation="tanh"))
    m.add(MaxPooling2D())
    m.add(Flatten())
    m.add(Dense(120, activation="tanh"))
    m.add(Dense(84, activation="tanh"))
    m.add(Dense(10, activation="softmax"))
    return m


def _augment(x, rng):
    """Per-sample random shift (±2 px), rotation (±12°) and zoom
    (0.9-1.12) — train-set only, the standard LeNet/MNIST augmentation
    family, sized for 16x16 digits."""
    from scipy.ndimage import affine_transform

    out = np.empty_like(x)
    n = x.shape[0]
    ang = rng.uniform(-12, 12, n) * np.pi / 180
    zoom = rng.uniform(0.9, 1.12, n)
    shift = rng.uniform(-2, 2, (n, 2))
    c = np.array([7.5, 7.5])
    for i in range(n):
        ca, sa = np.cos(ang[i]), np.sin(ang[i])
        mtx = np.array([[ca, -sa], [sa, ca]]) / zoom[i]
        off = c - mtx @ (c + shift[i])
        out[i, ..., 0] = affine_transform(x[i, ..., 0], mtx, offset=off,
                                          order=1, mode="constant")
    return out


def run_lenet(epochs=30, ckpt_dir=None, stop_at=None, augment=False):
    """Train LeNet on digits; returns (per-epoch history, final test acc,
    model).  ``augment=True`` regenerates a fresh random affine of the
    train set every epoch (the r4→r5 ≥99% push, VERDICT weak #6) and adds
    a step-decay LR schedule."""
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import (
        Adam,
        warmup_epoch_decay,
    )

    if augment and stop_at:
        raise ValueError(
            "augment=True is the headline ≥0.99 recipe (fixed augmented "
            "+ fine-tune leg structure); the resume experiment uses the "
            "plain path — combining them would train past the absolute "
            "epoch target")
    (xt, yt), (xv, yv) = digits_data()

    def build():
        m = _lenet16()
        steps = len(xt) // 64
        opt = Adam(lr=1.5e-3, schedule=warmup_epoch_decay(
            warmup_steps=0, steps_per_epoch=steps,
            boundaries_epochs=(int(epochs * 0.66), epochs),
            decay=0.2)) if augment else "adam"
        m.compile(optimizer=opt, loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
        return m

    m = build()
    if ckpt_dir:
        m.set_checkpoint(ckpt_dir)
    if augment:
        # fresh random affine every epoch, then a clean fine-tune leg at
        # the fully decayed LR (0.04x): the augmented phase buys the
        # invariances, the clean phase recovers the last few test digits
        arng = np.random.default_rng(7)
        for _ in range(epochs):
            m.fit(_augment(xt, arng), yt, batch_size=64, nb_epoch=1)
        for _ in range(epochs // 4):
            m.fit(xt, yt, batch_size=64, nb_epoch=1)
    else:
        m.fit(xt, yt, batch_size=64, nb_epoch=stop_at or epochs)
    if stop_at and stop_at < epochs:
        # fresh model resumes from the checkpoint dir (the crash-recovery
        # path) and continues to the absolute epoch target
        m = build()
        m.set_checkpoint(ckpt_dir)
        m.fit(xt, yt, batch_size=64, nb_epoch=epochs)
    hist = [h["loss"] for h in m._estimator.history]
    acc = float(m.evaluate(xv, yv, batch_size=87)["accuracy"])
    return hist, acc, m


def shapes_data(n=10000, seed=0):
    """10-class procedural shapes with nuisance variation: the conv net
    must generalize over position/scale/rotation/color/noise."""
    rng = np.random.default_rng(seed)
    n_cls = 10
    y = rng.integers(0, n_cls, size=n).astype(np.int32)
    x = rng.normal(0, 0.35, size=(n, 32, 32, 3)).astype(np.float32)
    yy, xx = np.mgrid[0:32, 0:32]
    for i in range(n):
        k = y[i]
        cx, cy = rng.uniform(10, 22, 2)
        s = rng.uniform(5, 9)
        # rotation IS a nuisance, but capped just below 45deg: under
        # full rotation a square is literally a diamond (classes 2/7
        # alias), which caps any model near 90% regardless of quality.
        # 42deg + the 0.35-sigma background keeps the task discriminative
        # (a weaker model scores visibly lower) without unlearnable labels
        th = rng.uniform(0, np.pi / 4.3)
        u = (xx - cx) * np.cos(th) + (yy - cy) * np.sin(th)
        v = -(xx - cx) * np.sin(th) + (yy - cy) * np.cos(th)
        if k == 0:      # disc
            mask = u ** 2 + v ** 2 < s ** 2
        elif k == 1:    # ring
            r2 = u ** 2 + v ** 2
            mask = (r2 < s ** 2) & (r2 > (0.55 * s) ** 2)
        elif k == 2:    # square
            mask = (np.abs(u) < s * 0.8) & (np.abs(v) < s * 0.8)
        elif k == 3:    # hollow square
            a, b = np.abs(u), np.abs(v)
            mask = (np.maximum(a, b) < s * 0.8) & \
                (np.maximum(a, b) > s * 0.45)
        elif k == 4:    # bar
            mask = (np.abs(u) < s) & (np.abs(v) < s * 0.3)
        elif k == 5:    # cross
            mask = ((np.abs(u) < s * 0.3) & (np.abs(v) < s)) | \
                ((np.abs(v) < s * 0.3) & (np.abs(u) < s))
        elif k == 6:    # triangle (half-plane cuts)
            mask = (v > -s * 0.5) & (v < 2 * (s - np.abs(u)) - s * 0.5)
        elif k == 7:    # diamond
            mask = np.abs(u) + np.abs(v) < s
        elif k == 8:    # two discs
            mask = ((u - s * 0.6) ** 2 + v ** 2 < (0.45 * s) ** 2) | \
                ((u + s * 0.6) ** 2 + v ** 2 < (0.45 * s) ** 2)
        else:           # checker texture patch
            mask = ((np.abs(u) < s) & (np.abs(v) < s)
                    & (((u // 2).astype(int) + (v // 2).astype(int)) % 2
                       == 0))
        color = rng.uniform(0.6, 1.4, size=3).astype(np.float32)
        x[i][mask] += color
    return x, y


def run_resnet(epochs=16, depth=20, n=10000, batch=128):
    from analytics_zoo_tpu.models.resnet import ResNet
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import (
        SGD,
        warmup_epoch_decay,
    )

    x, y = shapes_data(n)
    n_train = int(n * 0.8) // batch * batch
    xt, yt = x[:n_train], y[:n_train]
    xv, yv = x[n_train:], y[n_train:]
    steps = n_train // batch
    m = ResNet.cifar(depth=depth, classes=10)
    # TrainImageNet.scala recipe shape, scaled: 2-epoch linear warmup then
    # 0.1x decay at 50%/75% of the run, momentum 0.9, weight decay 1e-4
    sched = warmup_epoch_decay(
        warmup_steps=2 * steps, steps_per_epoch=steps,
        boundaries_epochs=(epochs // 2, (3 * epochs) // 4), decay=0.1)
    m.compile(optimizer=SGD(lr=0.1, momentum=0.9, weight_decay=1e-4,
                            schedule=sched),
              loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    m.fit(xt, yt, batch_size=batch, nb_epoch=epochs)
    hist = [h["loss"] for h in m._estimator.history]
    acc = float(m.evaluate(xv, yv, batch_size=100)["accuracy"])
    return hist, acc


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--configs", default="lenet,resume,resnet")
    p.add_argument("--resnet-epochs", type=int, default=16)
    p.add_argument("--out", default=None)
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend (env vars alone do not "
                        "keep the axon TPU plugin off; only the config "
                        "knob does)")
    a = p.parse_args()
    configs = a.configs.split(",")

    import jax

    if a.cpu:
        jax.config.update("jax_platforms", "cpu")

    from analytics_zoo_tpu import init_zoo_context

    init_zoo_context(seed=0)
    d = jax.devices()[0]
    out = {"platform": d.platform, "device_kind": d.device_kind,
           "notes": ("no network egress and no MNIST/CIFAR archives exist "
                     "in this sandbox; lenet uses scikit-learn's bundled "
                     "real digits, resnet uses procedural shapes with "
                     "nuisance variation — see tools/accuracy_bench.py")}

    if "lenet" in configs:
        t0 = time.time()
        hist, acc, _ = run_lenet(epochs=60, augment=True)
        out["lenet_digits"] = {
            "model": "LeNet-5 (16x16 input)",
            "dataset": "sklearn digits (1797 real 8x8 images, 2x upscale)",
            "train_size": 1536, "test_size": 261,
            "epochs": "60 augmented + 15 clean fine-tune @ decayed LR",
            "augmentation": "per-epoch random affine (shift ±2px, "
                            "rot ±12°, zoom 0.9-1.12) + step-decay LR",
            "loss_curve": [round(v, 4) for v in hist],
            "test_accuracy": round(acc, 4),
            "target": ">= 0.99 (MNIST-parity bar, not relabeled — "
                      "VERDICT r4 weak #6)",
            "passed": acc >= 0.99,
            "seconds": round(time.time() - t0, 1),
        }
        print("lenet_digits acc", acc)

    if "resume" in configs:
        t0 = time.time()
        full_hist, full_acc, _ = run_lenet(epochs=10)
        ck = tempfile.mkdtemp()
        res_hist, res_acc, _ = run_lenet(epochs=10, ckpt_dir=ck, stop_at=5)
        # the resumed run only has epochs 6..10 in its own history; compare
        # that tail against the uninterrupted curve
        tail = full_hist[-len(res_hist):]
        max_dev = float(np.max(np.abs(np.asarray(tail)
                                      - np.asarray(res_hist))))
        out["resume_reproduces_curve"] = {
            "uninterrupted_tail": [round(v, 5) for v in tail],
            "resumed_tail": [round(v, 5) for v in res_hist],
            "max_abs_deviation": round(max_dev, 6),
            "final_acc_uninterrupted": round(full_acc, 4),
            "final_acc_resumed": round(res_acc, 4),
            "passed": max_dev < 1e-3 and abs(full_acc - res_acc) < 0.02,
            "seconds": round(time.time() - t0, 1),
        }
        print("resume max_dev", max_dev, "accs", full_acc, res_acc)

    if "resnet" in configs:
        t0 = time.time()
        hist, acc = run_resnet(epochs=a.resnet_epochs)
        out["resnet_shapes"] = {
            "model": "ResNet-20 (CIFAR topology)",
            "dataset": "procedural 10-class shapes 32x32x3 "
                       "(position/scale/rotation/color/noise nuisance)",
            "train_size": 7936, "test_size": 2064,
            "epochs": a.resnet_epochs,
            "recipe": "TrainImageNet.scala:36-120 equivalent: 2-epoch "
                      "linear warmup, 0.1x decay at 50%/75%, momentum "
                      "0.9, wd 1e-4",
            "loss_curve": [round(v, 4) for v in hist],
            "test_accuracy": round(acc, 4),
            "target": ">= 0.93 (CIFAR-10/ResNet-56 parity stand-in)",
            "passed": acc >= 0.93,
            "seconds": round(time.time() - t0, 1),
        }
        print("resnet_shapes acc", acc)

    path = a.out or os.path.join(os.path.dirname(__file__), "..",
                                 "ACCURACY_r05.json")
    # merge-don't-clobber: transformer_convergence.py writes its own
    # section into the same artifact earlier in the bench queue
    blob = {}
    if os.path.exists(path):
        try:
            with open(path) as f:
                blob = json.load(f)
        except (OSError, ValueError):
            blob = {}
    blob.update(out)
    # atomic: never leave a half-written artifact
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(blob, f, indent=1)
    os.replace(tmp, path)
    print(json.dumps({k: (v if not isinstance(v, dict) else
                          {kk: vv for kk, vv in v.items()
                           if kk != "loss_curve"})
                      for k, v in out.items()}))


if __name__ == "__main__":
    main()
