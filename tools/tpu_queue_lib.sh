# Shared TPU bench-queue machinery (sourced by run_tpu_benches*.sh).
# Lessons encoded here (hard-won, see PERF_r04_STATUS.md):
# - serialize chip access; ONE client at a time — concurrent clients wedge
#   the tunnel, and a client killed mid-compile wedges it for hours.
# - JAX_PLATFORMS=cpu env alone does NOT keep a script off the axon
#   plugin; only jax.config.update("jax_platforms", "cpu") does.
# - the tunnel can drop MID-QUEUE: re-probe before every stage and retry
#   a failed stage once after the tunnel returns; a failure with the TPU
#   still answering is a bug in the bench and repeats identically, so it
#   earns no retry.
# Requires $LOG to be set (and mkdir'd) by the sourcing script.

probe() {
  timeout 90 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" \
    >/dev/null 2>&1
}

wait_for_tpu() {
  echo "$(date) waiting for TPU..." | tee -a "$LOG/queue.log"
  until probe; do
    sleep 120
  done
  echo "$(date) TPU answered" | tee -a "$LOG/queue.log"
}

run() {
  name=$1; tmo=$2; shift 2
  for attempt in 1 2; do
    wait_for_tpu
    echo "$(date) START $name (attempt $attempt)" | tee -a "$LOG/queue.log"
    timeout "$tmo" "$@" >"$LOG/$name.log" 2>&1
    rc=$?  # capture BEFORE $(date) resets $?
    echo "$(date) DONE $name rc=$rc" | tee -a "$LOG/queue.log"
    [ "$rc" -eq 0 ] && break
    if probe; then
      echo "$(date) $name failed with TPU alive — not retrying" \
        | tee -a "$LOG/queue.log"
      break
    fi
  done
}
