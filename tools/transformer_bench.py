"""Transformer (GPT-style) training benchmark — the MXU-bound counterpart
to the memory-bound ResNet-50 headline.

Drives the framework's own API end-to-end: keras Model(tokens ->
TransformerLayer -> Dense(vocab)) compiled through the estimator's jitted
SPMD train step, causal attention routed through the Pallas flash kernel
(ops/attention.py auto-routing).  Timing is fetch-forced (block_until_ready
is unreliable on the axon backend — PROFILE_r03/ANALYSIS.md).

FLOP accounting (conservative, executed-work):
  fwd = 2 * matmul_params * tokens + n_block * 4 * B * S^2 * D * 0.5
  (causal attention counted at half — the flash kernel skips fully-masked
  blocks); train = 3 * fwd.

Usage: python tools/transformer_bench.py [--seq 1024] [--batch 8]
       [--blocks 12] [--hidden 768] [--steps 10] [--out FILE.json]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def run(seq=1024, batch=8, blocks=12, hidden=768, heads=12, vocab=32768,
        steps=10, remat=False, attn_drop=0.1, hidden_drop=0.1):
    """``remat``: False, True/"full", "dots" or "attn" — the
    TransformerLayer checkpoint policy (sweep on hardware; the best
    memory/recompute point is device-dependent)."""
    import jax

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Input, Model
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Dense,
        TransformerLayer,
    )

    ctx = init_zoo_context("transformer bench", seed=0)
    tokens = Input(shape=(seq,), name="tokens")
    h = TransformerLayer(vocab=vocab, seq_len=seq, n_block=blocks,
                         n_head=heads, hidden_size=hidden,
                         embedding_drop=0.0, attn_drop=attn_drop,
                         hidden_drop=hidden_drop, remat=remat)(tokens)
    logits = Dense(vocab, name="lm_head")(h)
    net = Model(tokens, logits, name="gpt_bench")
    net.compile(optimizer="adam",
                loss="sparse_categorical_crossentropy_from_logits")
    est = net._make_estimator()
    params, state = est.model.build_params()
    opt_state = est.optimizer.init(params)
    params, opt_state, state = jax.device_put(
        (params, opt_state, state), ctx.replicated())
    step_fn = est._build_train_step()

    rng = np.random.default_rng(0)
    x = rng.integers(0, vocab, size=(batch, seq)).astype(np.int32)
    y = rng.integers(0, vocab, size=(batch, seq)).astype(np.int32)
    sharded = ctx.shard_batch({"x": x, "y": y})
    seed_arr = np.asarray(0, np.int32)

    t0 = time.perf_counter()
    params, opt_state, state, loss = step_fn(
        params, opt_state, state, seed_arr, np.asarray(0, np.int32),
        sharded)
    float(loss)  # fetch-forced
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for i in range(steps):
        params, opt_state, state, loss = step_fn(
            params, opt_state, state, seed_arr,
            np.asarray(i + 1, np.int32), sharded)
    float(loss)
    dt = (time.perf_counter() - t0) / steps

    # matmul params: everything except embeddings (lookups, ~0 flops)
    n_all = sum(int(np.prod(p.shape))
                for p in jax.tree_util.tree_leaves(params))
    n_embed = vocab * hidden + seq * hidden
    n_matmul = n_all - n_embed
    tokens_per_step = batch * seq
    fwd = 2 * n_matmul * tokens_per_step \
        + blocks * 4 * batch * seq * seq * hidden * 0.5
    # per-chip accounting: the global batch is sharded over the data axis
    dp = max(ctx.data_parallel_size, 1)
    train_flops = 3 * fwd / dp
    d = jax.devices()[0]
    out = {
        "metric": "gpt_transformer_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_step / dt / dp, 1),
        "unit": "tokens/sec/chip",
        "step_ms": round(dt * 1e3, 2),
        "compile_s": round(compile_s, 1),
        "params_m": round(n_all / 1e6, 1),
        "batch": batch, "seq": seq, "blocks": blocks, "hidden": hidden,
        "remat": remat, "attn_drop": attn_drop,
        "hidden_drop": hidden_drop, "loss": round(float(loss), 3),
        "platform": d.platform, "device_kind": d.device_kind,
        "train_flops_per_step": train_flops,
    }
    if d.platform == "tpu":
        from bench import peak_flops_for

        peak = peak_flops_for(d.device_kind)
        if peak:
            out["mfu"] = round(train_flops / dt / peak, 4)
            out["peak_flops_assumed"] = peak
    return out


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--blocks", type=int, default=12)
    p.add_argument("--hidden", type=int, default=768)
    p.add_argument("--heads", type=int, default=12)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--remat", nargs="?", const="full", default=False,
                   choices=["full", "dots", "attn"],
                   help="jax.checkpoint per transformer block; optional "
                        "policy argument (default 'full')")
    p.add_argument("--attn-drop", type=float, default=0.1)
    p.add_argument("--hidden-drop", type=float, default=0.1)
    p.add_argument("--out", default=None)
    a = p.parse_args()
    r = run(seq=a.seq, batch=a.batch, blocks=a.blocks, hidden=a.hidden,
            heads=a.heads, steps=a.steps, remat=a.remat,
            attn_drop=a.attn_drop, hidden_drop=a.hidden_drop)
    print(json.dumps(r))
    if a.out:
        with open(a.out, "w") as f:
            json.dump(r, f, indent=1)


if __name__ == "__main__":
    main()
