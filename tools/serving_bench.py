"""Cluster Serving latency benchmark: p50/p99 end-to-end latency at a fixed
offered load through InputQueue -> ClusterServing -> OutputQueue.

Mirrors the reference's serving data path (ClusterServing.scala:103-139:
stream read -> micro-batch -> predict -> write result hash -> xtrim
backpressure); the measured latency is enqueue-to-result-available per
record, i.e. queueing + decode + batch formation + jit inference + encode.

A client thread offers ``--rate`` records/sec (open-loop, so queueing delay
is visible, not hidden by back-to-back closed-loop pacing); the server runs
in its own thread on the in-memory broker; a collector polls result hashes
with a 1 ms tick and records completion times.

Writes SERVING_r05.json.  Usage:
  python tools/serving_bench.py [--rate 200] [--n 2000] [--batch 16]
                                [--shape 32,32,3]
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def build_model(tmp, shape, classes=10):
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Convolution2D,
        Dense,
        Flatten,
        GlobalAveragePooling2D,
    )
    from analytics_zoo_tpu.pipeline.api.keras.topology import Sequential

    m = Sequential()
    m.add(Convolution2D(16, 3, 3, activation="relu", input_shape=shape))
    m.add(Convolution2D(32, 3, 3, activation="relu"))
    m.add(GlobalAveragePooling2D())
    m.add(Dense(classes, activation="softmax"))
    m.build_params()
    path = os.path.join(tmp, "model.zoo")
    m.save(path)
    return path


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--rate", type=float, default=200.0,
                   help="offered load, records/sec")
    p.add_argument("--n", type=int, default=2000)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--shape", default="32,32,3")
    p.add_argument("--out", default=None)
    a = p.parse_args()
    shape = tuple(int(v) for v in a.shape.split(","))

    import jax

    from analytics_zoo_tpu.serving import (
        ClusterServing,
        ClusterServingHelper,
        InMemoryBroker,
        InputQueue,
        OutputQueue,
    )

    tmp = tempfile.mkdtemp()
    model_path = build_model(tmp, shape)
    broker = InMemoryBroker()
    serving = ClusterServing(
        ClusterServingHelper(model_path=model_path, batch_size=a.batch,
                             top_n=1, data_shape=shape,
                             log_dir=os.path.join(tmp, "logs")),
        broker=broker)
    inq = InputQueue(broker=broker)
    outq = OutputQueue(broker=broker)

    # warm the jit caches (full and ragged-tail buckets) before timing
    rng = np.random.default_rng(0)
    img = rng.normal(size=shape).astype(np.float32)
    for i in range(a.batch + 1):
        inq.enqueue_image(f"warm-{i}", img)
    serving.run(max_records=a.batch + 1)

    enq_t = {}
    done_t = {}

    def producer():
        period = 1.0 / a.rate
        t_next = time.perf_counter()
        for i in range(a.n):
            uri = f"r-{i}"
            enq_t[uri] = time.perf_counter()
            inq.enqueue_image(uri, img)
            t_next += period
            delay = t_next - time.perf_counter()
            if delay > 0:
                time.sleep(delay)

    def collector():
        pending = set(f"r-{i}" for i in range(a.n))
        deadline = time.time() + a.n / a.rate + 120
        while pending and time.time() < deadline:
            for uri in list(pending):
                if outq.query(uri) is not None:
                    done_t[uri] = time.perf_counter()
                    pending.discard(uri)
            time.sleep(0.001)

    server = serving.start(idle_timeout=a.n / a.rate + 120)
    col = threading.Thread(target=collector)
    col.start()
    t0 = time.perf_counter()
    producer()
    col.join()
    wall = time.perf_counter() - t0
    serving.stop()

    # Per-record latencies go through the metrics registry (the same
    # substrate the server's own telemetry uses — ISSUE 1: no more
    # bench-private timers as the only signal).  The headline p50/p99
    # stay exact-from-samples; the registry section carries the
    # histogram summary plus the SERVER-side telemetry recorded by
    # ClusterServing.step() during this very run.
    from analytics_zoo_tpu.metrics import (
        get_registry, sample_key, snapshot)

    client_lat = get_registry().histogram(
        "zoo_serving_client_latency_seconds",
        "enqueue -> result-available latency per record")
    for u in done_t:
        client_lat.observe(done_t[u] - enq_t[u])

    lats = np.array(sorted(
        (done_t[u] - enq_t[u]) * 1e3 for u in done_t))
    completed = len(lats)
    if completed == 0:
        print(json.dumps({
            "error": "no records completed — server-side failure "
                     "(check model path / broker); see serving logs",
            "offered": a.n,
        }))
        sys.exit(1)
    d = jax.devices()[0]
    out = {
        "metric": "cluster_serving_latency_ms",
        "p50": round(float(np.percentile(lats, 50)), 2),
        "p90": round(float(np.percentile(lats, 90)), 2),
        "p99": round(float(np.percentile(lats, 99)), 2),
        "mean": round(float(lats.mean()), 2),
        "offered_rate_rps": a.rate,
        "achieved_rps": round(completed / wall, 1),
        "completed": completed,
        "offered": a.n,
        "batch_size": a.batch,
        "data_shape": shape,
        "broker": "in-memory",
        "platform": d.platform,
        "device_kind": d.device_kind,
        "semantics": "enqueue->result-available, open-loop offered load "
                     "(ClusterServing.scala:103-139 path)",
    }
    if out["achieved_rps"] < 0.95 * a.rate:
        out["note"] = ("SATURATED: offered load exceeds capacity, latency "
                       "is queueing delay, not service time — see a "
                       "stable-queue run for the latency number")
    # registry section: server-side serving telemetry + the client
    # latency histogram summary (same names a Prometheus scrape exposes)
    reg_doc = {}
    for s in snapshot()["samples"]:
        if not s["name"].startswith("zoo_serving"):
            continue
        key = sample_key(s)
        if s["kind"] == "histogram":
            reg_doc[key] = {k: round(float(s[k]), 6)
                            for k in ("count", "p50", "p95", "p99")}
        else:
            reg_doc[key] = round(float(s["value"]), 6)
    out["registry"] = reg_doc
    print(json.dumps(out))
    path = a.out or os.path.join(os.path.dirname(__file__), "..",
                                 "SERVING_r05.json")
    # Merge, don't clobber: the artifact keeps one run per
    # (platform, offered_rate) and fronts the best STABLE-queue run, so a
    # saturation probe can never replace the latency headline.
    runs = []
    if os.path.exists(path):
        with open(path) as f:
            old = json.load(f)
        runs = old.get("runs") or ([{k: v for k, v in old.items()
                                     if k != "runs"}] if "p50" in old
                                   else [])
    runs = [r for r in runs
            if (r.get("platform"), r.get("offered_rate_rps"))
            != (out["platform"], out["offered_rate_rps"])]
    runs.append(out)

    def stable(r):
        return r.get("achieved_rps", 0) >= 0.95 * r.get(
            "offered_rate_rps", float("inf"))

    primary = max([r for r in runs if stable(r)] or runs,
                  key=lambda r: (r.get("platform") == "tpu",
                                 r.get("offered_rate_rps", 0)))
    doc = dict(primary)
    # A tunneled chip adds ~100ms of HTTP dispatch RTT per predict call
    # that a real TPU-VM does not have: flag a STABLE-queue TPU headline
    # whose latency dwarfs the in-process CPU run as environment-bound
    # (a saturated run already carries its own SATURATED note — its
    # latency is queueing delay, and excusing it as tunnel RTT would
    # mask a real regression), and record the best CPU stable-queue p50
    # as the same-code-path comparison point.
    cpu_runs = [r for r in runs if r.get("platform") == "cpu" and stable(r)]
    if (doc.get("platform") == "tpu" and stable(doc) and cpu_runs
            and doc.get("p50", 0) > 20 * min(r["p50"] for r in cpu_runs)):
        doc["bound_by"] = "tunnel-dispatch(env)"
        doc["cpu_inproc_stable_p50_ms"] = min(r["p50"] for r in cpu_runs)
    doc["runs"] = runs
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)


if __name__ == "__main__":
    main()
