"""Render a JSONL metrics file (or a live /varz endpoint) as a
latency/throughput summary table.

Reads the output of ``analytics_zoo_tpu.metrics.exporters.write_jsonl``
(one registry snapshot per line — e.g. what ``bench.py`` appends when
``ZOO_METRICS_JSONL`` is set), or scrapes one snapshot from a running
process's ``/varz`` endpoint (``MetricsServer``, ZOO_METRICS_PORT), and
prints, for the LATEST snapshot:

- histograms: count, mean, p50/p95/p99 (seconds-named metrics shown in
  ms);
- counters/gauges: the value, plus the delta and rate against the FIRST
  snapshot in the file when more than one line is present (file mode
  only — a single live scrape has no baseline).

Metric families worth a `--prefix` of their own: `zoo_train` (fit-loop
breakdown; under ``ZOO_STEPS_PER_DISPATCH=K`` one histogram observation
covers a K-step fused dispatch while the steps/records counters keep
counting real steps), `zoo_serving`, `zoo_inference`,
`zoo_data_prefetch` (host data plane), `zoo_compile` (the compile
plane: `zoo_compile_seconds{label=...}` per AOT compile plus the
`zoo_compile_cache_hits_total` / `zoo_compile_cache_misses_total` pair
that splits cold from ``ZOO_COMPILE_CACHE``-warm starts), and
`zoo_hlo` (the HLO graph lint's analytic cost features per compiled
program: `zoo_hlo_flops` / `zoo_hlo_bytes_accessed` /
`zoo_hlo_collectives` / `zoo_hlo_collective_bytes` /
`zoo_hlo_fused_dispatches` / `zoo_hlo_ops` / `zoo_hlo_findings`, all
`{label=<compile label>}`, plus `zoo_hlo_lint_findings_total{rule=}`
— see docs/static-analysis.md), `zoo_autotune` (the closed-loop
controller's current worker/depth/read-ahead/K gauges, RAM
budget/estimate pair, and `zoo_autotune_decisions_total{knob,reason}`),
and `zoo_fleet` (the serving fleet's live/target replica gauges,
`zoo_fleet_decisions_total{action,reason}`, the exactly-once
fault-tolerance pair `zoo_fleet_lease_takeovers_total` /
`zoo_fleet_replica_deaths_total`, the scaler's
`zoo_fleet_est_p99_seconds` / `zoo_fleet_unclaimed_backlog` window
signals, and `zoo_fleet_batch_flushes_total{reason}` from the
continuous batcher), `zoo_router` (the multi-tenant serving plane,
serving/router.py: `zoo_router_models`,
`zoo_router_decisions_total{model,action}` and the per-model
`zoo_fleet_model_replicas` / `zoo_fleet_model_backlog` /
`zoo_fleet_model_est_p99_seconds` gauges), `zoo_admission` (the
front-door shedding plane, serving/admission.py:
`zoo_admission_requests_total{model,verdict}`,
`zoo_admission_state{model}`,
`zoo_admission_retry_after_seconds{model}` and
`zoo_admission_evaluations_total`), and `zoo_oracle` (the predictive
compile plane,
analysis/oracle.py: `zoo_oracle_predictions_total{consumer}`,
`zoo_oracle_predicted_steps_per_sec{config}` /
`zoo_oracle_measured_steps_per_sec{config}` /
`zoo_oracle_rel_error{config}` per scored config, and
`zoo_oracle_fit_samples` — the residual model's training-set size, 0
while the oracle is analytic-only), `zoo_scrape` (the zoowatch
federation tier, metrics/scrape.py: `zoo_scrape_targets`,
per-target `zoo_scrape_fetches_total` / `zoo_scrape_errors_total` /
`zoo_scrape_staleness_seconds`, and the `zoo_scrape_fetch_seconds`
pull-latency histogram), and `zoo_slo` (the burn-rate engine,
metrics/slo.py: `zoo_slo_burn_rate{slo,window}` for the short/long
alert windows, `zoo_slo_alert_active{slo}`, `zoo_slo_alerts_total`
and `zoo_slo_evaluations_total`), and `zoo_kernel` (the Pallas kernel
plane, parallel/plan.py kernel_rules + ops/pallas:
`zoo_kernel_selections{label,scope,kernel}` — what the fifth rule
table resolved per compile label,
`zoo_kernel_invocations{kernel,backend}` — pallas vs fallback routing
counts, and the bytes loop
`zoo_kernel_measured_bytes{label}` /
`zoo_kernel_predicted_bytes{label}` /
`zoo_kernel_bytes_rel_error{label}` — measured custom-call HBM bytes
against costmodel.kernel_bytes; the HLO side is
`zoo_hlo_custom_kernels{label}` / `zoo_hlo_custom_kernel_bytes{label}`
under the `zoo_hlo` family).  When the scraped ``/varz`` carries
a structured decision log (``autotune`` / ``fleet`` / ``router`` /
``admission`` / ``oracle`` / ``elastic`` / ``scrape`` / ``slo``
sections), it is additionally
rendered as a table — time, knob/action, old → new, reason; predicted
vs measured per config; per-target scrape health; firing SLO alerts
with their short/long burn rates — above the metric rows.

Usage:
  python tools/metrics_dump.py METRICS.jsonl [--prefix zoo_serving]
  python tools/metrics_dump.py METRICS.jsonl --prefix zoo_compile
  python tools/metrics_dump.py --url host:9090 --prefix zoo_hlo
  python tools/metrics_dump.py METRICS.jsonl --prometheus   # re-render
  python tools/metrics_dump.py --url http://host:9090/varz
  python tools/metrics_dump.py --url host:9090   # /varz implied
  python tools/metrics_dump.py --url host:9090 --watch 2   # live panel
"""

import argparse
import json
import sys


def load(path):
    docs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                docs.append(json.loads(line))
            except json.JSONDecodeError:
                print(f"warning: skipping unparseable line", file=sys.stderr)
    if not docs:
        raise SystemExit(f"{path}: no snapshots found")
    return docs


def fetch(url):
    """One live /varz snapshot as a single-doc list (the same downstream
    shape as a one-line JSONL file).  Accepts ``host:port`` shorthand
    and a bare server root; ``/varz`` is implied."""
    import urllib.request

    if "://" not in url:
        url = "http://" + url
    if not url.rstrip("/").endswith("/varz"):
        url = url.rstrip("/") + "/varz"
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            doc = json.load(r)
    except (OSError, json.JSONDecodeError) as e:
        raise SystemExit(f"{url}: scrape failed: {e}")
    if "samples" not in doc:
        raise SystemExit(f"{url}: no samples in response — not a "
                         "MetricsServer /varz endpoint?")
    return [doc]


def _key(sample):
    try:
        from analytics_zoo_tpu.metrics import sample_key
    except ModuleNotFoundError:
        # standalone invocation (`python tools/metrics_dump.py ...`) puts
        # tools/ on sys.path, not the repo root: fall back to the same
        # canonical shape so the tool works without an installed package
        labels = sample.get("labels")
        if not labels:
            return sample["name"]
        inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
        return f"{sample['name']}{{{inner}}}"
    return sample_key(sample)


def _scale(name, value):
    """seconds-named metrics print in ms — latencies live there."""
    if name.endswith("_seconds") or "_seconds{" in name:
        return value * 1e3, "ms"
    return value, ""


def render_autotune(doc, prefix="", out=None):
    """Decision table for the ``autotune`` section a live ``/varz``
    carries when a closed-loop controller ran (feature/autotune.py):
    one row per knob change (time, knob, old→new, reason), plus each
    controller's current config.  Skipped when the snapshot has no
    autotune section or ``--prefix`` filters it out."""
    import datetime

    auto = doc.get("autotune")
    if not auto or (prefix and not "zoo_autotune".startswith(prefix)):
        return
    emit = print if out is None else (lambda s: out.append(s))
    for ctl in auto.get("controllers", []):
        cur = ctl.get("current", {})
        emit("\nautotune: workers={workers} depth={depth} "
             "read_ahead={read_ahead} K={k} (settled={k_settled})".format(
                 **{k: cur.get(k) for k in
                    ("workers", "depth", "read_ahead", "k", "k_settled")}))
    decisions = auto.get("decisions", [])
    if decisions:
        emit(f"\n{'time':<14}{'knob':<12}{'change':<14}reason")
        for d in decisions:
            t = datetime.datetime.fromtimestamp(d["ts"]).strftime(
                "%H:%M:%S.%f")[:-3]
            emit(f"{t:<14}{d['knob']:<12}"
                 f"{str(d['old']) + ' -> ' + str(d['new']):<14}"
                 f"{d['reason']}")


def render_fleet(doc, prefix="", out=None):
    """Fleet panel for the ``fleet`` section a live ``/varz`` carries
    when a FleetController ran (serving/fleet.py): each controller's
    replica/scaler state, then one row per scale decision (time, action,
    replicas old→new, estimated p99 vs the window's queue, reason).
    Skipped when the snapshot has no fleet section or ``--prefix``
    filters it out."""
    import datetime

    fleet = doc.get("fleet")
    if not fleet or (prefix and not "zoo_fleet".startswith(prefix)):
        return
    emit = print if out is None else (lambda s: out.append(s))
    for ctl in fleet.get("controllers", []):
        cur = ctl.get("current", {})
        win = cur.get("window", {})
        emit("\nfleet: replicas={replicas}/{target} (max={max_replicas}) "
             "slo_p99={slo_p99_ms}ms mode={mode}".format(
                 **{k: cur.get(k) for k in
                    ("replicas", "target", "max_replicas", "slo_p99_ms",
                     "mode")}))
        emit("  window: predict_p99={predict_p99_ms}ms "
             "rate={service_rate}/s queue={queue_depth} "
             "mem={memory_ratio}".format(
                 **{k: win.get(k) for k in
                    ("predict_p99_ms", "service_rate", "queue_depth",
                     "memory_ratio")}))
    decisions = fleet.get("decisions", [])
    if decisions:
        emit(f"\n{'time':<14}{'action':<9}{'replicas':<11}"
             f"{'est_p99':<11}{'queue':<7}reason")
        for d in decisions:
            t = datetime.datetime.fromtimestamp(d["ts"]).strftime(
                "%H:%M:%S.%f")[:-3]
            est = "-" if d.get("est_p99_ms") is None \
                else f"{d['est_p99_ms']:.0f} ms"
            emit(f"{t:<14}{d['action']:<9}"
                 f"{str(d['old']) + ' -> ' + str(d['new']):<11}"
                 f"{est:<11}{str(d.get('queue_depth', '-')):<7}"
                 f"{d['reason']}")


def render_router(doc, prefix="", out=None):
    """Router panel for the ``router`` section a live ``/varz`` carries
    when a ModelRouter ran (serving/router.py): each router's per-model
    state (stream, replicas, backlog, the oracle verdict's pad buckets
    and batch budget, the admission verdict), then one row per
    prime/scale/stop decision.  Skipped when the snapshot has no router
    section or ``--prefix`` filters it out."""
    import datetime

    router = doc.get("router")
    if not router or (prefix and not "zoo_router".startswith(prefix)):
        return
    emit = print if out is None else (lambda s: out.append(s))
    for r in router.get("routers", []):
        cur = r.get("current", {})
        emit("\nrouter: admission={admission} mode={mode}".format(
            **{k: cur.get(k) for k in ("admission", "mode")}))
        models = cur.get("models", {})
        if models:
            emit(f"  {'model':<12}{'replicas':>9}{'backlog':>9}"
                 f"{'slo_p99':>9}{'buckets':<16}{'budget':>9}  admission")
            for name in sorted(models):
                m = models[name]
                verdict = m.get("verdict") or {}
                adm = m.get("admission") or {}
                buckets = verdict.get("pad_buckets")
                budget = verdict.get("batch_budget_ms")
                emit(f"  {name:<12}{m.get('replicas', 0):>9}"
                     f"{m.get('backlog', 0):>9}"
                     f"{m.get('spec', {}).get('slo_p99_ms', 0):>8g}m"
                     f" {str(buckets or '-'):<15}"
                     f"{('-' if budget is None else f'{budget:.1f}ms'):>9}"
                     f"  {adm.get('state', '-')}")
    decisions = router.get("decisions", [])
    if decisions:
        emit(f"\n  {'time':<14}{'model':<12}{'action':<8}detail")
        for d in decisions:
            t = datetime.datetime.fromtimestamp(d["ts"]).strftime(
                "%H:%M:%S.%f")[:-3]
            if d.get("action") == "scale":
                detail = (f"{d.get('old')} -> {d.get('new')} "
                          f"backlog={d.get('backlog')}")
            else:
                detail = (f"replicas={d.get('replicas')} "
                          f"buckets={d.get('pad_buckets')} "
                          f"budget={d.get('batch_budget_ms')}")
            emit(f"  {t:<14}{d.get('model', '?'):<12}"
                 f"{d.get('action', '?'):<8}{detail}")


def render_admission(doc, prefix="", out=None):
    """Admission panel for the ``admission`` section a live ``/varz``
    carries when an AdmissionController ran (serving/admission.py):
    each controller's current verdict (state, reason, retry-after, the
    observed drain rate), then one row per accept/shed transition.
    Skipped when the snapshot has no admission section or ``--prefix``
    filters it out."""
    import datetime

    admission = doc.get("admission")
    if not admission or (prefix
                         and not "zoo_admission".startswith(prefix)):
        return
    emit = print if out is None else (lambda s: out.append(s))
    for ctl in admission.get("controllers", []):
        cur = ctl.get("current", {})
        emit("\nadmission: model={model} stream={stream} state={state} "
             "retry_after={retry_after_ms}ms backlog_limit="
             "{backlog_limit} drain={drain_rate}/s".format(
                 **{k: cur.get(k) for k in
                    ("model", "stream", "state", "retry_after_ms",
                     "backlog_limit", "drain_rate")}))
    decisions = admission.get("decisions", [])
    if decisions:
        emit(f"\n  {'time':<14}{'model':<12}{'state':<8}"
             f"{'retry_after':>12}{'backlog':>9}  reason")
        for d in decisions:
            t = datetime.datetime.fromtimestamp(d["ts"]).strftime(
                "%H:%M:%S.%f")[:-3]
            emit(f"  {t:<14}{d.get('model', '?'):<12}"
                 f"{d.get('state', '?'):<8}"
                 f"{d.get('retry_after_ms', 0):>10.0f}ms"
                 f"{d.get('backlog', 0):>9}  {d.get('reason', '')}")


def render_oracle(doc, prefix="", out=None):
    """Predicted-vs-measured panel for the ``oracle`` section a live
    ``/varz`` carries when a ConfigOracle ran (analysis/oracle.py):
    each oracle's peak-table source and residual-fit size, then one row
    per scored config — time, consumer, config, predicted and measured
    steps/sec, relative error ("-" while the outcome is still open).
    Skipped when the snapshot has no oracle section or ``--prefix``
    filters it out."""
    import datetime

    oracle = doc.get("oracle")
    if not oracle or (prefix and not "zoo_oracle".startswith(prefix)):
        return
    emit = print if out is None else (lambda s: out.append(s))
    for o in oracle.get("oracles", []):
        peaks = o.get("peaks", {})
        emit("\noracle: peaks={source} fit_samples={n} "
             "residual_ready={ready}".format(
                 source=peaks.get("source"), n=o.get("fit_samples"),
                 ready=o.get("residual_ready")))
    predictions = oracle.get("predictions", [])
    if predictions:
        emit(f"\n{'time':<14}{'consumer':<12}{'config':<14}"
             f"{'predicted/s':>12}{'measured/s':>12}{'rel_err':>9}")
        for p in predictions:
            t = datetime.datetime.fromtimestamp(p["ts"]).strftime(
                "%H:%M:%S.%f")[:-3]
            meas = p.get("measured_steps_per_sec")
            err = p.get("rel_error")
            chosen = "*" if p.get("chosen") else " "
            emit(f"{t:<14}{p['consumer']:<12}"
                 f"{chosen + p['config']:<14}"
                 f"{p['predicted_steps_per_sec']:>12.1f}"
                 f"{('-' if meas is None else f'{meas:.1f}'):>12}"
                 f"{('-' if err is None else f'{err:.3f}'):>9}")


def render_elastic(doc, prefix="", out=None):
    """Elastic panel for the ``elastic`` section a live ``/varz``
    carries when a TrainSupervisor ran (elastic/supervisor.py): each
    supervisor's generation/world/cohort state, the member table with
    per-worker micro-batch shares, then one row per rejoin decision
    (time, action, generation, world, reason).  Skipped when the
    snapshot has no elastic section or ``--prefix`` filters it out."""
    import datetime

    elastic = doc.get("elastic")
    if not elastic or (prefix and not "zoo_elastic".startswith(prefix)):
        return
    emit = print if out is None else (lambda s: out.append(s))
    for sup in elastic.get("supervisors", []):
        cur = sup.get("current", {})
        emit("\nelastic: generation={generation} "
             "world={world}/{target_workers} (min={min_workers}) "
             "mesh={mesh} plan={plan} k={k} chief={chief} "
             "repicks={repicks}".format(
                 **{k: cur.get(k) for k in
                    ("generation", "world", "target_workers",
                     "min_workers", "mesh", "plan", "k", "chief",
                     "repicks")}))
        members = cur.get("members", [])
        if members:
            shares = cur.get("shares", {})
            workers = cur.get("workers", {})
            emit(f"  {'member':<8}{'share':>6}  {'pid':>8}  alive")
            for w in members:
                info = workers.get(w, {})
                emit(f"  {w:<8}{str(shares.get(w, '-')):>6}  "
                     f"{str(info.get('pid', '-')):>8}  "
                     f"{info.get('alive', '-')}")
    decisions = elastic.get("decisions", [])
    if decisions:
        emit(f"\n{'time':<14}{'action':<10}{'gen':<5}{'world':<7}"
             f"{'worker':<8}reason")
        for d in decisions:
            t = datetime.datetime.fromtimestamp(d["ts"]).strftime(
                "%H:%M:%S.%f")[:-3]
            emit(f"{t:<14}{d['action']:<10}"
                 f"{str(d.get('generation', '-')):<5}"
                 f"{str(d.get('world', '-')):<7}"
                 f"{str(d.get('worker', '-')):<8}"
                 f"{d['reason']}")


def render_scrape(doc, prefix="", out=None):
    """Federation panel for the ``scrape`` section a live ``/varz``
    carries when a VarzScraper ran (metrics/scrape.py): one row per
    scraped target — health, staleness age, fetch/error counts, last
    error.  Skipped when the snapshot has no scrape section or
    ``--prefix`` filters it out."""
    scrapers = doc.get("scrape")
    if not scrapers or (prefix and not "zoo_scrape".startswith(prefix)):
        return
    emit = print if out is None else (lambda s: out.append(s))
    for s in scrapers:
        emit("\nscrape: healthy={healthy} interval={interval}s "
             "stale_after={stale_after}s".format(
                 **{k: s.get(k) for k in
                    ("healthy", "interval", "stale_after")}))
        targets = s.get("targets", {})
        if targets:
            emit(f"  {'target':<12}{'ok':<6}{'age':>8}{'fetches':>9}"
                 f"{'errors':>8}  last_error")
            for name in sorted(targets):
                t = targets[name]
                age = t.get("age_seconds")
                emit(f"  {name:<12}{str(t.get('healthy')):<6}"
                     f"{('-' if age is None else f'{age:.1f}s'):>8}"
                     f"{t.get('fetches', 0):>9}{t.get('errors', 0):>8}"
                     f"  {t.get('last_error') or '-'}")


def render_slo(doc, prefix="", out=None):
    """SLO/alert panel for the ``slo`` section a live ``/varz`` carries
    when an SloEngine ran (metrics/slo.py): each engine's specs with
    their objectives and windows, any alerts with short/long burn rates
    (firing alerts marked ``*``), then one row per decision-log entry.
    Skipped when the snapshot has no slo section or ``--prefix``
    filters it out."""
    import datetime

    engines = doc.get("slo")
    if not engines or (prefix and not "zoo_slo".startswith(prefix)):
        return
    emit = print if out is None else (lambda s: out.append(s))
    for eng in engines:
        specs = eng.get("specs", [])
        if specs:
            emit(f"\nslo: {'name':<24}{'family':<34}{'objective':>10}"
                 f"{'threshold':>11}{'windows':>12}")
            for sp in specs:
                win = (f"{sp.get('short_window'):g}/"
                       f"{sp.get('long_window'):g}s")
                emit(f"     {sp.get('name', '?'):<24}"
                     f"{sp.get('family', '?'):<34}"
                     f"{sp.get('objective'):>10g}"
                     f"{sp.get('threshold'):>11g}{win:>12}")
        alerts = eng.get("alerts", [])
        if alerts:
            emit(f"\n  {'alert':<25}{'burn short':>11}{'burn long':>11}"
                 f"{'thresh':>8}  since")
            for a in alerts:
                mark = "*" if a.get("firing") else " "
                since = a.get("since")
                t = "-" if not since else \
                    datetime.datetime.fromtimestamp(since).strftime(
                        "%H:%M:%S")
                emit(f"  {mark}{a.get('slo', '?'):<24}"
                     f"{a.get('short_burn', 0):>11.2f}"
                     f"{a.get('long_burn', 0):>11.2f}"
                     f"{a.get('burn_threshold', 0):>8g}  {t}")
        decisions = eng.get("decisions", [])
        if decisions:
            emit(f"\n  {'time':<14}{'slo':<25}{'state':<10}"
                 f"{'burn s/l':<16}")
            for d in decisions:
                t = datetime.datetime.fromtimestamp(d["ts"]).strftime(
                    "%H:%M:%S.%f")[:-3]
                burns = (f"{d.get('short_burn', 0):.2f}/"
                         f"{d.get('long_burn', 0):.2f}")
                emit(f"  {t:<14}{d.get('slo', '?'):<25}"
                     f"{d.get('state', '?'):<10}{burns:<16}")


def render_kernels(doc, prefix="", out=None):
    """Kernel-plane panel from the ``zoo_kernel_*`` gauge family
    (parallel/plan.py record_kernel_gauges + ops/pallas
    record_kernel_bytes): per-label scope→kernel selections from the
    plan's fifth rule table, measured-vs-predicted custom-call bytes
    with their relative error, and the per-kernel pallas/fallback
    routing counters.  Skipped when the snapshot carries no zoo_kernel
    samples or ``--prefix`` filters them out."""
    if prefix and not "zoo_kernel".startswith(prefix):
        return
    samples = [s for s in doc.get("samples", [])
               if s["name"].startswith("zoo_kernel_")]
    if not samples:
        return
    emit = print if out is None else (lambda s: out.append(s))
    selections = [s for s in samples
                  if s["name"] == "zoo_kernel_selections"]
    if selections:
        emit(f"\nkernels: {'label':<22}{'scope':<22}kernel")
        for s in sorted(selections,
                        key=lambda s: (s["labels"].get("label", ""),
                                       s["labels"].get("scope", ""))):
            lab = s["labels"]
            emit(f"         {lab.get('label', '?'):<22}"
                 f"{lab.get('scope', '?'):<22}{lab.get('kernel', '?')}")
    by_label = {}
    for s in samples:
        if s["name"] in ("zoo_kernel_measured_bytes",
                         "zoo_kernel_predicted_bytes",
                         "zoo_kernel_bytes_rel_error"):
            by_label.setdefault(
                s["labels"].get("label", "?"), {})[s["name"]] = s["value"]
    if by_label:
        emit(f"\n  {'label':<28}{'measured':>12}{'predicted':>12}"
             f"{'rel_err':>9}")
        for label in sorted(by_label):
            row = by_label[label]
            pred = row.get("zoo_kernel_predicted_bytes")
            err = row.get("zoo_kernel_bytes_rel_error")
            emit(f"  {label:<28}"
                 f"{row.get('zoo_kernel_measured_bytes', 0):>12.0f}"
                 f"{('-' if pred is None else f'{pred:.0f}'):>12}"
                 f"{('-' if err is None else f'{err:.4f}'):>9}")
    invocations = [s for s in samples
                   if s["name"] == "zoo_kernel_invocations"]
    if invocations:
        emit(f"\n  {'kernel':<24}{'backend':<12}count")
        for s in sorted(invocations,
                        key=lambda s: (s["labels"].get("kernel", ""),
                                       s["labels"].get("backend", ""))):
            lab = s["labels"]
            emit(f"  {lab.get('kernel', '?'):<24}"
                 f"{lab.get('backend', '?'):<12}{s['value']:.0f}")


def render(docs, a):
    """One full render pass over a snapshot list — the body shared by
    the one-shot path and the ``--watch`` loop."""
    first, last = docs[0], docs[-1]
    first_vals = {_key(s): s for s in first.get("samples", [])}
    dt = max(last.get("ts", 0) - first.get("ts", 0), 0.0)

    hist_rows, val_rows = [], []
    for s in last.get("samples", []):
        key = _key(s)
        if a.prefix and not s["name"].startswith(a.prefix):
            continue
        if s["kind"] == "histogram":
            unit_vals = [_scale(key, s[k])[0]
                         for k in ("mean", "p50", "p95", "p99")]
            unit = _scale(key, 0.0)[1]
            hist_rows.append((key, int(s["count"]), unit) +
                             tuple(unit_vals))
        else:
            v = s.get("value", 0.0)
            delta = ""
            rate = ""
            prev = first_vals.get(key)
            if prev is not None and len(docs) > 1 \
                    and s["kind"] == "counter":
                d = v - prev.get("value", 0.0)
                delta = f"{d:+.6g}"
                if dt > 0:
                    rate = f"{d / dt:.6g}/s"
            val_rows.append((key, s["kind"], f"{v:.6g}", delta, rate))

    if a.prometheus:
        for row in val_rows:
            print(f"{row[0]} {row[2]}")
        for row in hist_rows:
            print(f"{row[0]}_count {row[1]}")
        return

    src = a.url if a.url else a.path
    print(f"# {src}: {len(docs)} snapshot(s), window {dt:.1f}s")
    render_autotune(last, prefix=a.prefix)
    render_fleet(last, prefix=a.prefix)
    render_router(last, prefix=a.prefix)
    render_admission(last, prefix=a.prefix)
    render_oracle(last, prefix=a.prefix)
    render_elastic(last, prefix=a.prefix)
    render_scrape(last, prefix=a.prefix)
    render_slo(last, prefix=a.prefix)
    render_kernels(last, prefix=a.prefix)
    if hist_rows:
        print(f"\n{'histogram':<52}{'count':>9}{'mean':>11}"
              f"{'p50':>11}{'p95':>11}{'p99':>11}")
        for key, count, unit, mean, p50, p95, p99 in hist_rows:
            u = f" {unit}" if unit else ""
            print(f"{key:<52}{count:>9}"
                  f"{mean:>10.3f}{u}{p50:>10.3f}{u}"
                  f"{p95:>10.3f}{u}{p99:>10.3f}{u}")
    if val_rows:
        print(f"\n{'metric':<52}{'kind':>9}{'value':>14}"
              f"{'delta':>12}{'rate':>12}")
        for key, kind, v, delta, rate in val_rows:
            print(f"{key:<52}{kind:>9}{v:>14}{delta:>12}{rate:>12}")


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("path", nargs="?", help="JSONL metrics file")
    p.add_argument("--url", default=None,
                   help="scrape a live /varz endpoint instead of "
                        "reading a file (http://host:port[/varz] or "
                        "host:port)")
    p.add_argument("--prefix", default="",
                   help="only metrics whose name starts with this")
    p.add_argument("--prometheus", action="store_true",
                   help="ignored for histograms' full buckets (JSONL "
                        "carries summaries); prints name=value lines "
                        "instead of the table")
    p.add_argument("--watch", type=float, default=None, metavar="SECONDS",
                   help="re-fetch and re-render every SECONDS (live "
                        "panel; Ctrl-C to stop).  In --url mode each "
                        "refresh keeps the previous scrape as the "
                        "baseline, so counter deltas/rates become live")
    a = p.parse_args()

    if bool(a.path) == bool(a.url):
        p.error("exactly one of PATH or --url is required")
    if a.watch is not None and a.watch <= 0:
        p.error("--watch needs a positive interval")
    if a.watch is not None and a.prometheus:
        p.error("--watch and --prometheus do not combine")

    docs = fetch(a.url) if a.url else load(a.path)
    if a.watch is None:
        render(docs, a)
        return

    import time
    prev = docs[-1]
    try:
        while True:
            # clear + home, like watch(1), so the panel repaints in
            # place; harmless when stdout is not a terminal
            if sys.stdout.isatty():
                print("\x1b[2J\x1b[H", end="")
            render(docs, a)
            sys.stdout.flush()
            time.sleep(a.watch)
            try:
                fresh = fetch(a.url) if a.url else load(a.path)
            except SystemExit as e:
                # a restarting endpoint shouldn't kill the panel
                print(f"(refresh failed: {e})", file=sys.stderr)
                continue
            # live baseline: previous scrape first, newest last
            docs = [prev, fresh[-1]] if a.url else fresh
            prev = fresh[-1]
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
