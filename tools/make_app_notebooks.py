"""Generate the four round-4 apps/ notebooks (reference apps/ ports).

Each notebook mirrors a reference app's narrative
(/root/reference/apps/<name>) rebuilt on the TPU-native API, sized so the
cell-level CI gate (tests/test_examples.py) trains it in seconds on the
8-device CPU mesh.  Run: python tools/make_app_notebooks.py
"""

import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))
APPS = os.path.join(HERE, "..", "apps")


def nb(cells):
    return {
        "cells": cells,
        "metadata": {"kernelspec": {"display_name": "Python 3",
                                    "language": "python",
                                    "name": "python3"}},
        "nbformat": 4,
        "nbformat_minor": 5,
    }


def md(text):
    return {"cell_type": "markdown", "metadata": {},
            "source": text.splitlines(keepends=True)}


def code(text):
    return {"cell_type": "code", "execution_count": None, "metadata": {},
            "outputs": [], "source": text.splitlines(keepends=True)}


# ---------------------------------------------------------------------------
# 1. variational autoencoder (digits)
# ---------------------------------------------------------------------------

vae = nb([
    md("""# Using a variational autoencoder to generate digits

Mirror of the reference app
`apps/variational-autoencoder/using_variational_autoencoder_to_generate_digital_numbers.ipynb`,
rebuilt TPU-native: the encoder/decoder are keras-API `Dense` stacks, the
reparameterisation trick is the `GaussianSampler` layer
(reference GaussianSampler.scala), and the VAE objective
(reconstruction + KL) is an autograd `CustomLoss` — the same autograd
surface the reference notebook uses (`zoo.pipeline.api.autograd`).
We use the bundled scikit-learn digits (8x8) since this sandbox has no
network access for MNIST."""),
    code("""import numpy as np
from sklearn.datasets import load_digits

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.pipeline.api import autograd as A
from analytics_zoo_tpu.pipeline.api.autograd import CustomLoss
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Dense, GaussianSampler, Merge,
)

zoo.init_zoo_context(seed=0)
digits = load_digits()
x = (digits.images.reshape(-1, 64) / 16.0).astype(np.float32)
x = x[: (len(x) // 64) * 64]  # batch-divisible
print(x.shape)"""),
    md("""## Encoder -> (mean, log_var) -> sampler -> decoder

`LATENT=2` so the latent space can be visualised like the reference app."""),
    code("""LATENT = 2
inp = Input(shape=(64,), name="img")
h = Dense(32, activation="relu")(inp)
z_mean = Dense(LATENT, name="mean")(h)
z_log_var = Dense(LATENT, name="log_var")(h)
z = GaussianSampler()([z_mean, z_log_var])
d = Dense(32, activation="relu")(z)
recon = Dense(64, activation="sigmoid", name="recon")(d)
# pack [recon | mean | log_var] so the loss sees all three
packed = Merge(mode="concat", concat_axis=-1)([recon, z_mean, z_log_var])
vae = Model(inp, packed)"""),
    code("""def vae_loss(y_true, y_pred):
    # CustomLoss passes raw arrays; A.* ops dispatch on both
    recon = y_pred[:, :64]
    mean = y_pred[:, 64:64 + LATENT]
    log_var = y_pred[:, 64 + LATENT:]
    # binary cross-entropy reconstruction (summed over pixels)
    eps = 1e-7
    bce = -A.sum(y_true * A.log(recon + eps)
                 + (1.0 - y_true) * A.log(1.0 - recon + eps), axis=1)
    # KL(q(z|x) || N(0, I))
    kl = -0.5 * A.sum(1.0 + log_var - A.square(mean) - A.exp(log_var),
                      axis=1)
    return bce + kl


vae.compile(optimizer="adam", loss=CustomLoss(vae_loss, [64 + 2 * LATENT]))
vae.fit(x, x, batch_size=64, nb_epoch=25)
history = vae._estimator.history
loss0, loss1 = history[0]["loss"], history[-1]["loss"]
print("loss", loss0, "->", loss1)"""),
    md("## Generate new digits by decoding latent samples"),
    code("""import jax

params, state = vae._estimator.model.params, vae._estimator.model.state
# decoder-only forward: run the full model on images, then decode a grid
# of latent points by reusing the trained decoder weights
full, _ = vae.forward(params, x[:64])
recon_imgs = np.asarray(full)[:, :64]
recon_err = float(np.mean((recon_imgs - x[:64]) ** 2))
print("mean reconstruction mse:", recon_err)
assert loss1 < 0.7 * loss0
assert recon_err < 0.07"""),
])


# ---------------------------------------------------------------------------
# 2. sentiment analysis
# ---------------------------------------------------------------------------

sentiment = nb([
    md("""# Sentiment analysis with the TextSet pipeline

Mirror of the reference app `apps/sentiment-analysis/sentiment.ipynb`
(IMDB reviews -> embedding -> CNN/LSTM classifier), rebuilt on the
TPU-native `TextSet` pipeline (tokenize -> normalize -> word2idx ->
shape_sequence) and the `TextClassifier` zoo model.  A synthetic review
corpus stands in for IMDB (no dataset downloads in this sandbox)."""),
    code("""import numpy as np

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.feature.text import TextSet
from analytics_zoo_tpu.models.textclassification import TextClassifier

zoo.init_zoo_context(seed=0)
POS = ["great", "wonderful", "loved", "excellent", "amazing", "superb"]
NEG = ["terrible", "awful", "hated", "boring", "dreadful", "worst"]
FILLER = ["the", "movie", "was", "and", "plot", "acting", "film", "a"]
rng = np.random.default_rng(0)


def make_review(label):
    words = list(rng.choice(FILLER, size=6))
    vocab = POS if label else NEG
    for w in rng.choice(vocab, size=2):
        words.insert(int(rng.integers(0, len(words))), w)
    return " ".join(words)


labels = rng.integers(0, 2, size=256)
texts = [make_review(l) for l in labels]
print(texts[0], "->", labels[0])"""),
    md("## TextSet pipeline + persisted word index"),
    code("""import os
import tempfile

ts = TextSet.from_texts(texts, labels).tokenize().normalize().word2idx()
ts.shape_sequence(12)
wi_dir = tempfile.mkdtemp()
ts.save_word_index(os.path.join(wi_dir, "word_index.txt"))
xs = np.stack([f.indices for f in ts.features])
ys = np.asarray(labels, np.int32)
n_train = 192
print("vocab", len(ts.get_word_index()))"""),
    code("""clf = TextClassifier(class_num=2, token_length=32,
                     sequence_length=12, encoder="cnn",
                     vocab_size=len(ts.get_word_index()) + 1)
clf.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
            metrics=["accuracy"])
clf.fit(xs[:n_train], ys[:n_train], batch_size=32, nb_epoch=12)
metrics = clf.evaluate(xs[n_train:], ys[n_train:], batch_size=32)
test_acc = metrics["accuracy"]
print("held-out accuracy:", test_acc)
assert test_acc > 0.85"""),
    md("## Score a fresh review with the saved word index"),
    code("""fresh = TextSet.from_texts(
    ["the movie was excellent amazing plot and acting"]).tokenize()
fresh.normalize()
fresh.load_word_index(os.path.join(wi_dir, "word_index.txt"))
fresh.word2idx()
fresh.shape_sequence(12)
probs = clf.predict(np.stack([fresh.features[0].indices]))
print("P(positive) =", float(probs[0][1]))
assert np.argmax(probs[0]) == 1"""),
])


# ---------------------------------------------------------------------------
# 3. image similarity
# ---------------------------------------------------------------------------

imsim = nb([
    md("""# Image similarity with deep features

Mirror of the reference app `apps/image-similarity/image-similarity.ipynb`
(real-estate images -> pretrained-CNN features -> cosine ranking),
rebuilt TPU-native: train a small classifier, cut the graph at the
penultimate layer via a second `Model` over the same nodes (the reference
uses a truncated pretrained net), and rank a gallery by cosine
similarity in embedding space."""),
    code("""import numpy as np

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.pipeline.api.keras import Input, Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Convolution2D, Dense, GlobalAveragePooling2D,
)

zoo.init_zoo_context(seed=0)
rng = np.random.default_rng(1)


def make_image(klass):
    img = rng.normal(0, 0.35, size=(16, 16, 1)).astype(np.float32)
    if klass == 0:      # horizontal stripes
        img[::4, :, 0] += 1.5
    elif klass == 1:    # vertical stripes
        img[:, ::4, 0] += 1.5
    else:               # center blob
        img[5:11, 5:11, 0] += 1.5
    return img


ys = rng.integers(0, 3, size=384)
xs = np.stack([make_image(k) for k in ys])"""),
    md("## Train a classifier; expose its embedding as a second Model"),
    code("""inp = Input(shape=(16, 16, 1), name="img")
h = Convolution2D(8, 3, 3, activation="relu")(inp)
h = Convolution2D(16, 3, 3, activation="relu")(h)
feat = GlobalAveragePooling2D(name="feat")(h)
logits = Dense(3, activation="softmax")(feat)
clf = Model(inp, logits)
clf.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
            metrics=["accuracy"])
clf.fit(xs, ys.astype(np.int32), batch_size=32, nb_epoch=10)

embedder = Model(inp, feat)  # shares the trained nodes
embedder._estimator = None
emb_params = {k: v for k, v in clf._estimator.model.params.items()}"""),
    code("""import jax.numpy as jnp

gallery_y = rng.integers(0, 3, size=96)
gallery = np.stack([make_image(k) for k in gallery_y])
emb_g, _ = embedder.forward(emb_params, jnp.asarray(gallery))
emb_g = np.asarray(emb_g)
emb_g = emb_g / (np.linalg.norm(emb_g, axis=1, keepdims=True) + 1e-8)

query_y = 1
query = make_image(query_y)[None]
emb_q, _ = embedder.forward(emb_params, jnp.asarray(query))
emb_q = np.asarray(emb_q)[0]
emb_q = emb_q / (np.linalg.norm(emb_q) + 1e-8)

sims = emb_g @ emb_q
top10 = np.argsort(-sims)[:10]
precision_at_10 = float(np.mean(gallery_y[top10] == query_y))
print("precision@10 for the query class:", precision_at_10)
assert precision_at_10 >= 0.8"""),
])


# ---------------------------------------------------------------------------
# 4. recommendation wide & deep
# ---------------------------------------------------------------------------

wnd = nb([
    md("""# Wide & Deep recommendation

Mirror of the reference app
`apps/recommendation-wide-n-deep/wide_n_deep.ipynb` (MovieLens-1M ->
`ColumnFeatureInfo` -> `WideAndDeep` -> per-pair scoring), rebuilt
TPU-native with a synthetic interactions table (no dataset downloads
here): users have a latent genre preference; the label is whether the
user liked the item."""),
    code("""import numpy as np

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.models.recommendation import (
    ColumnFeatureInfo, WideAndDeep, to_wide_deep_features,
)

zoo.init_zoo_context(seed=0)
rng = np.random.default_rng(0)
N_USERS, N_ITEMS, N_GENRES = 40, 60, 4
user_pref = rng.integers(0, N_GENRES, size=N_USERS)
item_genre = rng.integers(0, N_GENRES, size=N_ITEMS)

n = 2048
users = rng.integers(0, N_USERS, size=n)
items = rng.integers(0, N_ITEMS, size=n)
age = rng.uniform(18, 70, size=n).astype(np.float32)
match = (user_pref[users] == item_genre[items]).astype(np.int32)
noise = rng.random(n) < 0.1
labels = np.where(noise, 1 - match, match).astype(np.int32)
rows = {
    "user": users, "item": items, "genre": item_genre[items],
    "age": (age - 44.0) / 26.0,
}"""),
    md("""## Declare the feature columns (reference `ColumnFeatureInfo`)
and build the model"""),
    code("""info = ColumnFeatureInfo(
    wide_base_cols=["user", "item"],
    wide_base_dims=[N_USERS, N_ITEMS],
    wide_cross_cols=["genre"], wide_cross_dims=[N_GENRES],
    indicator_cols=["genre"], indicator_dims=[N_GENRES],
    embed_cols=["user", "item"],
    embed_in_dims=[N_USERS, N_ITEMS],
    embed_out_dims=[8, 8],
    continuous_cols=["age"],
)
features = to_wide_deep_features(rows, info)
model = WideAndDeep(model_type="wide_n_deep", class_num=2,
                    column_info=info, hidden_layers=(32, 16))
model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
n_train = 1536
model.fit([f[:n_train] for f in features], labels[:n_train],
          batch_size=64, nb_epoch=12)
metrics = model.evaluate([f[n_train:] for f in features],
                         labels[n_train:], batch_size=64)
test_acc = metrics["accuracy"]
print("held-out accuracy:", test_acc)
assert test_acc > 0.8"""),
    md("## Score user-item pairs (reference `predictUserItemPair`)"),
    code("""pair_probs = model.predict_user_item_pair(
    [f[n_train:n_train + 64] for f in features])
assert pair_probs.shape == (64,)
# scores should separate matched vs unmatched pairs
matched = pair_probs[labels[n_train:n_train + 64] == 1]
unmatched = pair_probs[labels[n_train:n_train + 64] == 0]
print("mean P(like): matched", float(matched.mean()),
      "unmatched", float(unmatched.mean()))
assert matched.mean() > unmatched.mean() + 0.2"""),
])


for name, book in [("variational_autoencoder.ipynb", vae),
                   ("sentiment_analysis.ipynb", sentiment),
                   ("image_similarity.ipynb", imsim),
                   ("wide_n_deep.ipynb", wnd)]:
    path = os.path.join(APPS, name)
    with open(path, "w") as f:
        json.dump(book, f, indent=1)
    print("wrote", path)
