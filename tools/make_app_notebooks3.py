"""Generate the round-4 batch-3 apps/ notebooks (reference apps/ ports):
dogs-vs-cats (transfer learning), object-detection, anomaly-detection-hd,
pytorch face-generation, tfnet image-classification, ray parameter-server.

Each mirrors a reference app's narrative (/root/reference/apps/<name>)
rebuilt on the TPU-native API, sized so the cell-level CI gate
(tests/test_examples.py) runs it in seconds on the 8-device CPU mesh.
Run: python tools/make_app_notebooks3.py
"""

import json
import os

from make_app_notebooks import APPS, code, md, nb

# ---------------------------------------------------------------------------
# 1. dogs-vs-cats: transfer learning (reference
#    apps/dogs-vs-cats/transfer-learning.ipynb — pretrained Inception-V1,
#    new_graph at the feature layer, freeze_up_to, retrain a binary head)
# ---------------------------------------------------------------------------

dogs = nb([
    md("""# Transfer learning: dogs vs cats

Mirror of the reference app `apps/dogs-vs-cats/transfer-learning.ipynb`:
take a model pretrained on a broader task, truncate it at a feature
layer with `new_graph`, **freeze** the backbone, and train a fresh
binary classifier head — the reference's
`Net.load_bigdl(...).new_graph("pool5/drop_7x7_s1")` +
`freeze_up_to("pool4/3x3_s2")` recipe on the TPU-native API.

No Kaggle download exists in this sandbox, so the "pretrained model" is
a small convnet trained here on a 4-class shape task, and "dogs vs cats"
is the sub-task of telling 2 of those classes apart — the transfer
mechanics (truncate / freeze / retrain-head) are identical."""),
    code("""import numpy as np

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Convolution2D, Dense, Flatten, MaxPooling2D,
)

zoo.init_zoo_context(seed=0)
rng = np.random.default_rng(0)


def make_images(n, n_classes=4):
    \"\"\"Class = which quadrant carries a bright blob (learnable from
    pixels; random labels would never converge).\"\"\"
    x = rng.normal(0.0, 0.25, size=(n, 16, 16, 3)).astype(np.float32)
    y = rng.integers(n_classes, size=n).astype(np.int32)
    for i, c in enumerate(y):
        r, col = divmod(int(c), 2)
        x[i, r * 8:r * 8 + 8, col * 8:col * 8 + 8, :] += 1.0
    return x, y


xs, ys = make_images(768)
print(xs.shape, np.bincount(ys))"""),
    md("""## "Pretrained" backbone
(stands in for the reference's downloaded Inception-V1)"""),
    code("""base = Sequential()
base.add(Convolution2D(8, 3, 3, activation="relu",
                       input_shape=(16, 16, 3), name="c1"))
base.add(MaxPooling2D((2, 2), name="p1"))
base.add(Convolution2D(16, 3, 3, activation="relu", name="c2"))
base.add(Flatten(name="feat"))
base.add(Dense(4, activation="softmax", name="head4"))
base.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
             metrics=["accuracy"])
base.fit(xs, ys, batch_size=64, nb_epoch=12)
src_acc = base.evaluate(xs, ys, batch_size=64)["accuracy"]
print("pretraining accuracy:", src_acc)
assert src_acc > 0.9"""),
    md("""## Truncate at the feature layer and freeze the backbone
(reference `new_graph` + `freeze_up_to`)"""),
    code("""feat = base.new_graph("feat")     # backbone ending at Flatten
print([ly.name for ly in feat.layers])

# binary sub-task: class 0 ("cats") vs class 1 ("dogs")
keep = ys < 2
xt, yt = xs[keep], ys[keep]
n = (len(xt) // 64) * 64
xt, yt = xt[:n], yt[:n]

model = Sequential()
model.add(feat)
model.add(Dense(2, activation="softmax", name="dogcat_head"))
model.freeze(feat.name)
print("frozen:", model.frozen_layers)"""),
    code("""from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

model.compile(optimizer=Adam(lr=0.01),
              loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
model.build_params()
import jax
backbone_before = [np.asarray(a) for a in
                   jax.tree_util.tree_leaves(model.params[feat.name])]
model.fit(xt, yt, batch_size=64, nb_epoch=15)
acc = model.evaluate(xt, yt, batch_size=64)["accuracy"]
print("dogs-vs-cats accuracy:", acc)
assert acc > 0.9"""),
    md("## The frozen backbone did not move"),
    code("""for a, b in zip(backbone_before,
                jax.tree_util.tree_leaves(model.params[feat.name])):
    np.testing.assert_array_equal(a, np.asarray(b))
print("backbone weights unchanged through head training")
done = True"""),
])

# ---------------------------------------------------------------------------
# 2. object detection (reference apps/object-detection: load a pretrained
#    SSD, detect over an image set, visualize boxes)
# ---------------------------------------------------------------------------

objdet = nb([
    md("""# Object detection with SSD

Mirror of the reference app `apps/object-detection` (download a
pretrained SSD, run `ObjectDetector.predict_image_set`, draw the boxes
with the Visualizer).  No model downloads here, so the tiny SSD is
first fitted on the checked-in VOCmini fixture — the
predict → postprocess → visualize flow is the reference's."""),
    code("""import os
import sys
import tempfile

sys.path.insert(0, os.getcwd())
from examples.objectdetection.predict import predict_and_visualize

out_dir = tempfile.mkdtemp()
written, detections = predict_and_visualize(out_dir=out_dir, epochs=18,
                                            conf=0.25)
print("annotated files:", [os.path.basename(p) for p in written])"""),
    md("""## Inspect the detections
(reference `ObjectDetector.predict_image_set` output: per-image boxes,
classes and scores, drawn by the Visualizer)"""),
    code("""n_boxes = sum(len(d["boxes"]) for d in detections)
for i, d in enumerate(detections[:3]):
    print(f"image {i}: {len(d['boxes'])} boxes, "
          f"scores {[round(float(s), 2) for s in d['scores'][:3]]}")
assert written, "no annotated images written"
assert n_boxes > 0
done = True"""),
])

# ---------------------------------------------------------------------------
# 3. anomaly detection in high dimensions (reference
#    apps/anomaly-detection-hd/autoencoder-zoo.ipynb: autoencoder on a
#    32-dim table, reconstruction-error ranking finds the outliers)
# ---------------------------------------------------------------------------

ahd = nb([
    md("""# Anomaly detection in high dimensions with an autoencoder

Mirror of the reference app
`apps/anomaly-detection-hd/autoencoder-zoo.ipynb` (HiCS/ionosphere
32-dim table → min-max normalize → Dense autoencoder → rank by
reconstruction error → outliers).  The .arff dataset isn't shipped in
this sandbox; a synthetic 32-dim table with a low-dim inlier manifold
plus 10% scattered outliers reproduces its structure."""),
    code("""import numpy as np

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

zoo.init_zoo_context(seed=0)
rng = np.random.default_rng(0)
N, D, K = 640, 32, 4
basis = rng.normal(size=(K, D))
inlier = rng.normal(size=(N, K)) @ basis + rng.normal(0, 0.15, (N, D))
labels = (rng.random(N) < 0.10).astype(np.int32)   # ~10% outliers
outlier_noise = rng.uniform(-6, 6, size=(N, D))
data = np.where(labels[:, None] == 1, outlier_noise,
                inlier).astype(np.float32)
# min-max normalize to [0, 1] like the reference notebook
lo, hi = data.min(0), data.max(0)
x = (data - lo) / (hi - lo + 1e-9)
print(x.shape, "outliers:", labels.sum())"""),
    md("## Autoencoder: 32 -> 8 -> 32, MSE reconstruction"),
    code("""ae = Sequential()
ae.add(Dense(16, activation="relu", input_shape=(32,)))
ae.add(Dense(8, activation="relu"))
ae.add(Dense(16, activation="relu"))
ae.add(Dense(32, activation="sigmoid"))
ae.compile(optimizer="adam", loss="mse")
ae.fit(x, x, batch_size=64, nb_epoch=30)"""),
    md("""## Rank by reconstruction error
(outliers are off-manifold -> high error)"""),
    code("""recon = np.asarray(ae.predict(x, batch_size=64))
err = ((recon - x) ** 2).mean(axis=1)
k = int(labels.sum())
top = np.argsort(err)[::-1][:k]
precision_at_k = labels[top].mean()
print(f"precision@{k}:", round(float(precision_at_k), 3))

# threshold-free quality: AUC of error as an outlier score
order = np.argsort(err)
ranks = np.empty(len(err)); ranks[order] = np.arange(len(err))
pos, neg = ranks[labels == 1], ranks[labels == 0]
auc = (pos[:, None] > neg[None, :]).mean()
print("ROC-AUC of reconstruction error:", round(float(auc), 3))
assert precision_at_k > 0.7
assert auc > 0.9
done = True"""),
])

# ---------------------------------------------------------------------------
# 4. pytorch generative inference (reference
#    apps/pytorch/face_generation.ipynb: PGAN from torch hub wrapped in
#    TorchNet, distributed noise -> image generation)
# ---------------------------------------------------------------------------

ptgen = nb([
    md("""# Generative inference through a PyTorch model

Mirror of the reference app `apps/pytorch/face_generation.ipynb`: a
pretrained PyTorch generator (PGAN from torch hub there) is wrapped in
``TorchNet`` and driven by the framework's distributed ``predict`` —
noise batches are padded, sharded over the mesh, and the torch module
executes host-side inside the jitted graph via ``pure_callback``.

Torch hub needs a download, so a small deterministic deconvolution
generator stands in for PGAN; the wrap-and-distribute flow is the
reference's."""),
    code("""import numpy as np
import torch

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.net import TorchNet

zoo.init_zoo_context(seed=0)
torch.manual_seed(0)
LATENT = 16

generator = torch.nn.Sequential(
    torch.nn.Linear(LATENT, 64), torch.nn.ReLU(),
    torch.nn.Unflatten(1, (4, 4, 4)),
    torch.nn.ConvTranspose2d(4, 8, 4, stride=2, padding=1),
    torch.nn.ReLU(),
    torch.nn.ConvTranspose2d(8, 3, 4, stride=2, padding=1),
    torch.nn.Tanh(),
).eval()
with torch.no_grad():
    sample = generator(torch.zeros(1, LATENT))
print("generator output:", tuple(sample.shape))"""),
    md("## Wrap in TorchNet and generate a distributed batch"),
    code("""net = TorchNet.from_pytorch(generator, input_shape=(LATENT,))
m = Sequential()
m.add(net)

rng = np.random.default_rng(7)
noise = rng.normal(size=(40, LATENT)).astype(np.float32)
faces = np.asarray(m.predict(noise, batch_size=16))
print("generated:", faces.shape, "range:",
      round(float(faces.min()), 2), "..", round(float(faces.max()), 2))
assert faces.shape == (40, 3, 16, 16)
assert float(np.abs(faces).max()) <= 1.0 + 1e-5   # tanh range"""),
    md("""## The distributed path matches running torch directly
(same module, same inputs — the framework adds batching/sharding, not
numerics)"""),
    code("""with torch.no_grad():
    direct = generator(torch.from_numpy(noise)).numpy()
np.testing.assert_allclose(faces, direct, rtol=1e-4, atol=1e-5)
print("distributed generation == direct torch forward")
done = True"""),
])

# ---------------------------------------------------------------------------
# 5. tfnet image classification (reference
#    apps/tfnet/image_classification_inference.ipynb: TF-slim inception
#    checkpoint -> TFNet -> distributed top-5 prediction)
# ---------------------------------------------------------------------------

tfnet_nb = nb([
    md("""# Image classification through a TensorFlow model

Mirror of the reference app
`apps/tfnet/image_classification_inference.ipynb` (TF-slim Inception-V1
checkpoint loaded as ``TFNet``, distributed predict, top-5 labels).
The slim checkpoint needs a download, so a small tf.keras CNN exported
to a SavedModel stands in; the load → wrap → distributed-predict →
top-k flow is the reference's."""),
    code("""import tempfile

import numpy as np
import tensorflow as tf

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.net import Net

zoo.init_zoo_context(seed=0)
tf.keras.utils.set_random_seed(0)
SIZE, CLASSES = 32, 10

km = tf.keras.Sequential([
    tf.keras.layers.Conv2D(8, 3, strides=2, activation="relu"),
    tf.keras.layers.GlobalAveragePooling2D(),
    tf.keras.layers.Dense(CLASSES, activation="softmax"),
])
km.build((None, SIZE, SIZE, 3))
export_dir = tempfile.mkdtemp()


@tf.function(input_signature=[
    tf.TensorSpec([None, SIZE, SIZE, 3], tf.float32)])
def serve(x):
    return km(x)


tf.saved_model.save(km, export_dir, signatures=serve)
print("exported SavedModel to", export_dir)"""),
    md("## Load as TFNet and predict distributed"),
    code("""net = Net.load_tf(export_dir, input_shape=(SIZE, SIZE, 3))
model = Sequential()
model.add(net)

rng = np.random.default_rng(1)
images = rng.normal(size=(24, SIZE, SIZE, 3)).astype(np.float32)
probs = np.asarray(model.predict(images, batch_size=8))
print("probs:", probs.shape)
np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-4)"""),
    md("## Top-5 labels (reference LabelOutput)"),
    code("""from analytics_zoo_tpu.models.image.imageclassification import (
    LabelOutput,
)

label_map = {i: f"class_{i}" for i in range(CLASSES)}
top5 = LabelOutput(label_map, top_k=5)(probs)
print("image 0 top-5:", top5[0])
assert len(top5) == 24 and len(top5[0]) == 5
# parity with direct TF execution
direct = km(tf.constant(images)).numpy()
np.testing.assert_allclose(probs, direct, rtol=1e-4, atol=1e-5)
print("distributed TFNet == direct tf.keras forward")
done = True"""),
])

# ---------------------------------------------------------------------------
# 6. ray parameter server (reference apps/ray/parameter_server — the
#    @ray.remote sync PS; here the actor runtime plays Ray's role)
# ---------------------------------------------------------------------------

rayps = nb([
    md("""# Distributed parameter server on the actor runtime

Mirror of the reference app `apps/ray/parameter_server` (a
`@ray.remote` ParameterServer + workers on RayOnSpark,
reference raycontext.py:192-393).  The TPU-native framework's actor
runtime (`analytics_zoo_tpu.parallel.actors`) provides the same
pattern: process actors with ordered method calls, object refs and
`get`.  Workers hold data shards and compute gradients; the PS owns the
weights and applies the averaged update."""),
    code("""import os
import sys

import numpy as np

sys.path.insert(0, os.getcwd())
from analytics_zoo_tpu.parallel.actors import ActorContext, get
from examples.parameter_server.sync_parameter_server import (
    CLASSES, DIM, ParameterServer, Worker,
)

ctx = ActorContext.init()"""),
    md("""## Spin up the PS and 3 worker actors, run synchronous rounds
(each worker holds a shard of sklearn digits; the PS owns the flat
weight vector — the reference's `@ray.remote` pair)"""),
    code("""ps = ParameterServer.remote(0.5)
workers = [Worker.remote(i, 3) for i in range(3)]
weights = ps.get_weights.remote().get()
loss0 = float(np.mean(get(
    [w.loss_on_shard.remote(weights) for w in workers])))
for it in range(30):
    grads = get([w.compute_gradients.remote(weights) for w in workers])
    weights = ps.apply_gradients.remote(*grads).get()
loss1 = float(np.mean(get(
    [w.loss_on_shard.remote(weights) for w in workers])))
print("mean shard loss:", round(loss0, 3), "->", round(loss1, 3))
assert loss1 < loss0 * 0.5"""),
    md("## Evaluate the trained weights on the full dataset"),
    code("""from sklearn.datasets import load_digits

d = load_digits()
x = (d.images.reshape(-1, DIM) / 16.0).astype(np.float64)
y = d.target
W = weights[:DIM * CLASSES].reshape(DIM, CLASSES)
b = weights[DIM * CLASSES:]
acc = float(((x @ W + b).argmax(1) == y).mean())
print("accuracy:", round(acc, 3))
ctx.stop()
assert acc > 0.85
done = True"""),
])

for name, book in [("dogs_vs_cats.ipynb", dogs),
                   ("object_detection.ipynb", objdet),
                   ("anomaly_detection_hd.ipynb", ahd),
                   ("pytorch_face_generation.ipynb", ptgen),
                   ("tfnet_image_classification.ipynb", tfnet_nb),
                   ("ray_parameter_server.ipynb", rayps)]:
    path = os.path.join(APPS, name)
    with open(path, "w") as f:
        json.dump(book, f, indent=1)
    print("wrote", path)
