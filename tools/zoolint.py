"""zoolint — JAX/concurrency AST linter over the repo (Tiers 1+3 of
``analytics_zoo_tpu.analysis``; see docs/static-analysis.md).

Usage:
  python tools/zoolint.py [paths ...]             # default: analytics_zoo_tpu/
  python tools/zoolint.py --whole-program         # + cross-module lock-order
                                                  #   and guarded-by inference
  python tools/zoolint.py --changed               # only files modified vs
                                                  #   merge-base w/ origin/main
  python tools/zoolint.py --format json
  python tools/zoolint.py --list-rules
  python tools/zoolint.py --rules guarded-by,bare-except tests/

Exit status: 0 clean, 1 when any unsuppressed finding exists (CI /
pre-commit composable), 2 on usage errors.  The quick-tier gate
``tests/test_zoolint.py::test_package_is_clean`` runs the
``--whole-program`` check; ``tools/precommit.sh`` wires ``--changed``
plus the zoosan fixture tests into a fast pre-commit loop.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from analytics_zoo_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
