"""zoolint — JAX/concurrency AST linter over the repo (Tier 1 of
``analytics_zoo_tpu.analysis``; see docs/static-analysis.md).

Usage:
  python tools/zoolint.py [paths ...]             # default: analytics_zoo_tpu/
  python tools/zoolint.py --format json
  python tools/zoolint.py --list-rules
  python tools/zoolint.py --rules guarded-by,bare-except tests/

Exit status: 0 clean, 1 when any unsuppressed finding exists (CI /
pre-commit composable), 2 on usage errors.  The quick-tier gate
``tests/test_zoolint.py::test_package_is_clean`` runs the same check.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from analytics_zoo_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
