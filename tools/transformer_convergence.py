"""Transformer convergence artifact (VERDICT r4 next #3): train the
tools/transformer_bench.py stack at reduced width — Model(tokens ->
TransformerLayer -> Dense(vocab)) through the estimator's jitted SPMD
step — to a stated bits-per-char target, with remat + dropout + bf16 ON
so the backward runs through the Pallas flash kernels on TPU (reference
anchor: BERT.scala:66 — the reference could train BERT-style layers; this
artifact is the loss-curve proof for OUR newest kernels).

Corpus: the framework's own Python source tree (~1 MB of real,
compressible text — the sandbox has no network egress and no bundled text
datasets).  Byte-level vocab (256).  Targets are stated up front, not
relabeled after the fact (VERDICT r4 weak #6):

* held-out bits-per-char <= 2.0 after ~2 epochs (a byte-uniform model
  sits at 8.0 bpc; gzip -9 on this corpus is ~2.1 bits/byte, so beating
  ~2 bpc requires genuinely learned structure, not class priors);
* the resumed run reproduces the uninterrupted loss curve.

Merges its section into ACCURACY_r05.json (never clobbers other
sections).  Usage:
  python tools/transformer_convergence.py [--cpu] [--tiny] [--out FILE]
"""

import argparse
import glob
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def corpus_bytes() -> np.ndarray:
    """Every .py file of the package + tests + tools, concatenated."""
    parts = []
    for pat in ("analytics_zoo_tpu/**/*.py", "tests/*.py", "tools/*.py",
                "examples/**/*.py"):
        for f in sorted(glob.glob(os.path.join(REPO, pat),
                                  recursive=True)):
            with open(f, "rb") as fh:
                parts.append(fh.read())
    return np.frombuffer(b"\n".join(parts), dtype=np.uint8)


def windows(data: np.ndarray, seq: int):
    """(N, seq) inputs and next-byte targets, stride seq."""
    n = (len(data) - 1) // seq
    x = data[: n * seq].reshape(n, seq).astype(np.int32)
    y = data[1: n * seq + 1].reshape(n, seq).astype(np.int32)
    return x, y


def build(seq, blocks, hidden, heads, remat, ckpt_dir=None):
    from analytics_zoo_tpu.pipeline.api.keras import Input, Model
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Dense,
        TransformerLayer,
    )

    tokens = Input(shape=(seq,), name="tokens")
    h = TransformerLayer(vocab=256, seq_len=seq, n_block=blocks,
                         n_head=heads, hidden_size=hidden,
                         embedding_drop=0.0, attn_drop=0.1,
                         hidden_drop=0.1, remat=remat,
                         name="gpt_core")(tokens)
    logits = Dense(256, name="lm_head")(h)
    net = Model(tokens, logits, name="gpt_char_lm")
    net.compile(optimizer="adam",
                loss="sparse_categorical_crossentropy_from_logits")
    if ckpt_dir:
        net.set_checkpoint(ckpt_dir)
    return net


def bpc_of(net, xv, yv, batch):
    ev = net.evaluate(xv, yv, batch_size=batch)
    # plain python float: np.float64 would poison the JSON artifact
    # (np.bool_/np.float64 are not json-serializable, and a failed dump
    # mid-write corrupts the file)
    return float(ev["loss"] / float(np.log(2.0)))


def run(seq=256, blocks=4, hidden=256, heads=4, batch=16, epochs=2,
        remat="full", ckpt_dir=None, stop_at=None, data=None):
    """One training leg; returns (loss curve per epoch, held-out bpc)."""
    from analytics_zoo_tpu import init_zoo_context

    init_zoo_context(seed=0, compute_dtype="bfloat16")
    if data is None:
        data = corpus_bytes()
    x, y = windows(data, seq)
    n_train = (int(len(x) * 0.9) // batch) * batch
    xt, yt = x[:n_train], y[:n_train]
    xv, yv = x[n_train:], y[n_train:]

    net = build(seq, blocks, hidden, heads, remat, ckpt_dir)
    net.fit(xt, yt, batch_size=batch, nb_epoch=stop_at or epochs)
    if stop_at and stop_at < epochs:
        # crash-recovery leg: fresh process-equivalent model resumes from
        # the checkpoint dir to the absolute epoch target
        net = build(seq, blocks, hidden, heads, remat, ckpt_dir)
        net.fit(xt, yt, batch_size=batch, nb_epoch=epochs)
    hist = [h["loss"] for h in net._estimator.history]
    # pad the eval split to a batch multiple via evaluate's n_valid path
    nv = (len(xv) // batch) * batch
    return hist, bpc_of(net, xv[:nv], yv[:nv], batch), net


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--tiny", action="store_true",
                   help="CI-sized config (seconds, loss-decrease check "
                        "only)")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--out", default=None)
    a = p.parse_args()

    import jax

    if a.cpu:
        jax.config.update("jax_platforms", "cpu")

    t0 = time.time()
    if a.tiny:
        data = corpus_bytes()[:65536]
        hist, bpc, _ = run(seq=64, blocks=2, hidden=64, heads=2, batch=8,
                           epochs=1, data=data)
        print(json.dumps({"tiny": True, "loss_curve": hist, "bpc": bpc}))
        return

    d = jax.devices()[0]
    # full artifact: train, then the resume leg — one corpus read serves
    # both legs and the reported byte count
    data = corpus_bytes()
    hist, bpc, _ = run(epochs=a.epochs, data=data)
    ck = tempfile.mkdtemp()
    r_hist, r_bpc, _ = run(epochs=a.epochs, ckpt_dir=ck,
                           stop_at=max(1, a.epochs // 2), data=data)
    tail = hist[-len(r_hist):]
    max_dev = float(np.max(np.abs(np.asarray(tail) - np.asarray(r_hist))))

    section = {
        "model": "GPT char-LM (TransformerLayer x4, hidden 256, heads 4, "
                 "seq 256) — the transformer_bench stack at reduced width",
        "training": "estimator jitted SPMD step, bf16 params-in-compute, "
                    "remat=full, attn/hidden dropout 0.1 (through the "
                    "flash kernel's in-kernel dropout on TPU)",
        "dataset": "framework's own source tree, byte-level "
                   f"({len(data)} bytes, 90/10 split)",
        "epochs": a.epochs,
        "loss_curve_nats": [round(v, 4) for v in hist],
        "heldout_bits_per_char": round(float(bpc), 4),
        "target": "<= 2.0 bpc held-out (uniform = 8.0; gzip -9 ~ 2.1)",
        "passed": bool(bpc <= 2.0),
        "resume": {
            "resumed_tail": [round(v, 5) for v in r_hist],
            "uninterrupted_tail": [round(v, 5) for v in tail],
            "max_abs_deviation": round(float(max_dev), 6),
            "heldout_bpc_resumed": round(float(r_bpc), 4),
            "passed": bool(max_dev < 2e-3 and abs(r_bpc - bpc) < 0.05),
        },
        "platform": d.platform, "device_kind": d.device_kind,
        "seconds": round(time.time() - t0, 1),
    }

    path = a.out or os.path.join(REPO, "ACCURACY_r05.json")
    blob = {}
    if os.path.exists(path):
        try:
            with open(path) as f:
                blob = json.load(f)
        except ValueError:
            blob = {}  # recover from a previously corrupted artifact
    blob["transformer_char_lm"] = section
    # atomic: a serialization error must never leave a half-written file
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(blob, f, indent=1)
    os.replace(tmp, path)
    print(json.dumps({k: v for k, v in section.items()
                      if k != "loss_curve_nats"}))


if __name__ == "__main__":
    main()
