"""TPU perf probe — separates the candidate costs behind the e2e step time.

Prints one JSON line per measurement:
  dispatch_us        — round-trip latency of a trivial jitted op (sync each)
  dispatch_async_us  — amortized latency with 100 queued dispatches, 1 sync
  h2d_f32_gbps       — device_put bandwidth, 150 MB float32
  h2d_u8_gbps        — device_put bandwidth, 38 MB uint8
  matmul_tflops      — 8192^3 bf16 matmul sustained TFLOP/s (MXU ceiling probe)
  resnet_pure_step_ms / resnet_pure_ips — jitted train step on a
      device-resident batch, donated buffers, N steps, one block at the end.

CAVEAT (axon backend): ``block_until_ready`` can return before execution
finishes, so the h2d_* and matmul_* numbers here are OPTIMISTIC bounds on
this harness.  Honest numbers require fetch-forced sync (a dependent scalar
``float()``) — see PROFILE_r03/ANALYSIS.md for the corrected measurements
(real H2D ≈ 27-35 MB/s).  resnet_pure_step is fetch-forced and reliable.

Usage: python tools/perf_probe.py [--batch 256] [--steps 20]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np


def emit(**kw):
    print(json.dumps(kw), flush=True)


def probe_dispatch():
    x = jnp.zeros((8,), jnp.float32)
    f = jax.jit(lambda a: a + 1)
    f(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(100):
        f(x).block_until_ready()
    sync = (time.perf_counter() - t0) / 100
    t0 = time.perf_counter()
    y = x
    for _ in range(100):
        y = f(y)
    y.block_until_ready()
    async_ = (time.perf_counter() - t0) / 100
    emit(dispatch_us=round(sync * 1e6, 1),
         dispatch_async_us=round(async_ * 1e6, 1))


def probe_h2d():
    a32 = np.random.default_rng(0).normal(size=(256, 224, 224, 3)).astype(
        np.float32)  # ~154 MB
    a8 = (a32 * 32 + 128).clip(0, 255).astype(np.uint8)  # ~38 MB
    for name, arr in [("h2d_f32_gbps", a32), ("h2d_u8_gbps", a8)]:
        jax.device_put(arr).block_until_ready()  # warm
        t0 = time.perf_counter()
        n = 5
        for _ in range(n):
            jax.device_put(arr).block_until_ready()
        dt = (time.perf_counter() - t0) / n
        emit(**{name: round(arr.nbytes / dt / 1e9, 2),
                name.replace("gbps", "ms"): round(dt * 1e3, 1)})


def probe_matmul():
    # Random data + a scan of `reps` chained matmuls inside ONE dispatch, a
    # scalar checksum fetched at the end — nothing can be elided or skewed by
    # async-dispatch accounting.
    n = 8192
    reps = 20
    key = jax.random.PRNGKey(0)
    a = (jax.random.normal(key, (n, n)) * 1e-3).astype(jnp.bfloat16)
    b = (jax.random.normal(key, (n, n)) * 1e-3).astype(jnp.bfloat16)

    @jax.jit
    def chain(x, y):
        def body(c, _):
            return jnp.tanh(c @ y), ()
        c, _ = jax.lax.scan(body, x, None, length=reps)
        return jnp.sum(c.astype(jnp.float32))

    chain(a, b).block_until_ready()
    t0 = time.perf_counter()
    float(chain(a, b))
    dt = (time.perf_counter() - t0) / reps
    emit(matmul_tflops=round(2 * n**3 / dt / 1e12, 1),
         matmul_ms=round(dt * 1e3, 2))


def probe_resnet(batch, steps, image=224, stem="7x7"):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.resnet import ResNet

    ctx = init_zoo_context(seed=0)
    net = ResNet.image_net(50, classes=1000, input_shape=(image, image, 3),
                           stem=stem)
    net.compile(optimizer=ResNet.imagenet_optimizer(
        batch_size=batch, steps_per_epoch=100),
        loss="sparse_categorical_crossentropy")
    est = net._make_estimator()

    params, state = est.model.build_params()
    opt_state = est.optimizer.init(params)
    repl = ctx.replicated()
    params, opt_state, state = jax.device_put((params, opt_state, state), repl)
    step_fn = est._build_train_step()

    x = np.random.default_rng(0).normal(size=(batch, image, image, 3)).astype(
        np.float32)
    y = np.random.default_rng(1).integers(0, 1000, size=(batch,)).astype(
        np.int32)
    sharded = ctx.shard_batch({"x": x, "y": y})
    seed_arr = np.asarray(0, np.int32)

    t0 = time.perf_counter()
    params, opt_state, state, loss = step_fn(
        params, opt_state, state, seed_arr, np.asarray(0, np.int32), sharded)
    float(loss)  # fetch-forced sync (block_until_ready lies on axon)
    compile_s = time.perf_counter() - t0
    emit(resnet_compile_s=round(compile_s, 1), batch=batch, stem=stem)

    # batch arg (index 5) is not donated, safe to reuse across steps.
    t0 = time.perf_counter()
    for i in range(steps):
        params, opt_state, state, loss = step_fn(
            params, opt_state, state, seed_arr,
            np.asarray(i + 1, np.int32), sharded)
    float(loss)
    dt = (time.perf_counter() - t0) / steps
    ips = batch / dt
    flops = 3 * 4.09e9 * batch
    emit(resnet_pure_step_ms=round(dt * 1e3, 1),
         resnet_pure_ips=round(ips, 1),
         resnet_pure_mfu=round(flops / dt / 197e12, 4),
         batch=batch, stem=stem)
    return ips, dt


def probe_bisect(batch, steps, reps=2):
    """Pin the r03→r04 pure-step drop (2396.3 → 2348.5 img/s, VERDICT r4
    weak #2).  Code reading already eliminates the prime suspect: the
    ZeRO-1 GSPMD constraint is gated on ``data_parallel_size > 1``
    (estimator.py _shard_optimizer_on), so on the single bench chip it
    was INERT in r04 — the env-toggled pair below is kept as a control
    (it must measure ~equal) and the real variable is run-to-run and
    rebuild-to-rebuild variance, which ``reps`` runs per config bound.
    Writes PERF_BISECT_r05.json: conclusion 'noise' when the historical
    48 img/s gap sits inside the measured spread, else the control
    difference is flagged for deeper bisection."""
    # INTERLEAVED (plain, zero1, plain, zero1, ...): a monotonic drift
    # over the session (tunnel latency, thermal) would otherwise alias
    # straight into the control gap
    results = {"plain": [], "zero1_constraint": []}
    for _ in range(reps):
        for label, env in (("plain", "0"), ("zero1_constraint", "1")):
            os.environ["ZOO_SHARD_OPTIMIZER"] = env
            results[label].append(probe_resnet(batch, steps)[0])
    os.environ.pop("ZOO_SHARD_OPTIMIZER", None)
    for label, runs in results.items():
        emit(bisect_config=label, ips_runs=[round(v, 1) for v in runs])
    spread = max(max(v) - min(v) for v in results.values())
    gap = float(np.median(results["plain"])
                - np.median(results["zero1_constraint"]))
    historical_gap = 2396.3 - 2348.5
    if abs(gap) > spread:
        # the two programs are provably identical on one chip; a gap
        # outside the spread means the spread estimate itself is unstable
        conclusion = "control-difference-investigate"
    elif spread >= historical_gap:
        conclusion = "noise"
    else:
        # tight runs that still can't cover 47.8 img/s: the drop was NOT
        # within-session noise — cause sits outside the measured
        # candidates (e.g. cross-session tunnel/toolchain state)
        conclusion = "drop-exceeds-measured-noise"
    d = jax.devices()[0]
    out = {
        "question": "what explains the r03->r04 pure-step probe drop "
                    "(2396.3 -> 2348.5 img/s = 47.8)?",
        "method": f"{reps} runs per config (fresh estimator build each), "
                  f"same session, fetch-forced timing, batch {batch} x "
                  f"{steps} steps",
        "code_reading": "ZeRO-1 GSPMD constraint is gated on "
                        "data_parallel_size > 1 (estimator.py "
                        "_shard_optimizer_on) and was INERT on the "
                        "single-chip r04 probe; the env pair here is a "
                        "control and must measure ~equal",
        "ips": {k: [round(v, 1) for v in vs] for k, vs in results.items()},
        "control_median_gap_ips": round(gap, 1),
        "within_config_spread_ips": round(float(spread), 1),
        "historical_gap_ips": historical_gap,
        "conclusion": conclusion,
        "platform": d.platform, "device_kind": d.device_kind,
    }
    path = os.path.join(os.path.dirname(__file__), "..",
                        "PERF_BISECT_r05.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    emit(bisect_conclusion=conclusion,
         control_median_gap_ips=out["control_median_gap_ips"],
         spread_ips=out["within_config_spread_ips"])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--stem", default="7x7",
                   choices=["7x7", "space_to_depth"])
    p.add_argument("--skip-resnet", action="store_true")
    p.add_argument("--resnet-only", action="store_true")
    p.add_argument("--bisect", action="store_true",
                   help="r03/r04 drop bisect: repeat the pure step with "
                        "the ZeRO-1 constraint on/off, write "
                        "PERF_BISECT_r05.json")
    args = p.parse_args()
    if args.resnet_only and args.skip_resnet:
        p.error("--resnet-only and --skip-resnet are mutually exclusive")
    if args.bisect:
        d = jax.devices()[0]
        emit(platform=d.platform, device_kind=d.device_kind)
        probe_bisect(args.batch, args.steps)
        return

    d = jax.devices()[0]
    emit(platform=d.platform, device_kind=d.device_kind,
         n_devices=len(jax.devices()))
    if not args.resnet_only:
        probe_dispatch()
        probe_h2d()
        probe_matmul()
    if not args.skip_resnet:
        probe_resnet(args.batch, args.steps, stem=args.stem)


if __name__ == "__main__":
    main()
