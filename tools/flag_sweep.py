"""XLA TPU flag sweep over the pure step — the remaining sanctioned lever
toward pure_step >= 1.0x baseline (VERDICT r5 #2) after PROFILE_r03's
roofline analysis placed the step within ~1.5x of this machine's composite
ceiling: compiler scheduling/fusion knobs, not model changes.

Each combo runs in a FRESH subprocess (XLA flags are process-wide and
read at backend init), executing perf_probe --resnet-only and parsing its
fetch-forced resnet_pure_ips.  Writes FLAGSWEEP_r05.json with every
combo's number and the winner; if the winner beats baseline by >1%, adopt
its flags in bench.py's environment.

Caveats encoded in the artifact: a combo whose flag the backend doesn't
know fails its subprocess (recorded rc=1, sweep continues — verified on
the CPU build, which lacks the xla_tpu_* flags), and under axon
REMOTE compile (PALLAS_AXON_REMOTE_COMPILE=1) local XLA_FLAGS may not
reach the compiler at all — if every successful combo lands within
noise of baseline, suspect that bypass before concluding the knobs are
worthless.

Usage: python tools/flag_sweep.py [--batch 256] [--steps 20]
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

COMBOS = [
    ("baseline", ""),
    ("latency_hiding_scheduler",
     "--xla_tpu_enable_latency_hiding_scheduler=true"),
    ("scoped_vmem_32m", "--xla_tpu_scoped_vmem_limit_kib=32768"),
    ("lhs_plus_vmem32",
     "--xla_tpu_enable_latency_hiding_scheduler=true "
     "--xla_tpu_scoped_vmem_limit_kib=32768"),
]


def run_combo(flags: str, batch: int, steps: int, timeout: int):
    env = dict(os.environ)
    base = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = (base + " " + flags).strip()
    try:
        out = subprocess.run(
            [sys.executable, "tools/perf_probe.py", "--resnet-only",
             "--batch", str(batch), "--steps", str(steps)],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=timeout)
    except subprocess.TimeoutExpired as e:
        # one slow combo must not abort the sweep and discard the
        # finished measurements
        return None, "timeout", (e.stdout or "")[-500:] if e.stdout else ""
    ips = None
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if "resnet_pure_ips" in d:
            ips = d["resnet_pure_ips"]
    return ips, out.returncode, out.stdout[-500:] + out.stderr[-500:]


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--steps", type=int, default=20)
    # generous: a fresh remote compile over the tunnel can run long, and
    # killing a chip client mid-compile wedges the tunnel (PERF_r04
    # lesson #1) — the same budget logic as the bisect stage
    p.add_argument("--per-combo-timeout", type=int, default=2400)
    a = p.parse_args()

    results = {}
    for name, flags in COMBOS:
        ips, rc, tail = run_combo(flags, a.batch, a.steps,
                                  a.per_combo_timeout)
        results[name] = {"flags": flags, "ips": ips, "rc": rc}
        if ips is None:
            results[name]["tail"] = tail
        print(json.dumps({"combo": name, "ips": ips, "rc": rc}),
              flush=True)

    ok = {k: v for k, v in results.items() if v["ips"]}
    base_ips = (ok.get("baseline") or {}).get("ips")
    best = max(ok, key=lambda k: ok[k]["ips"]) if ok else None
    out = {
        "method": f"fresh subprocess per combo, perf_probe --resnet-only "
                  f"batch {a.batch} x {a.steps} steps, fetch-forced",
        "results": results,
        "baseline_ips": base_ips,
        "best": best,
        "best_ips": ok[best]["ips"] if best else None,
        "gain_pct": (round((ok[best]["ips"] / base_ips - 1) * 100, 2)
                     if best and base_ips else None),
    }
    with open(os.path.join(REPO, "FLAGSWEEP_r05.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "results"}))
    if base_ips is None:
        # no baseline number means the backend/tunnel was unusable: exit
        # nonzero so the queue's dead-tunnel retry logic re-runs the
        # sweep in a later chip window (flag-specific failures with a
        # healthy baseline stay rc=0 — deterministic, not retryable)
        sys.exit(1)


if __name__ == "__main__":
    main()
