"""Generate three more apps/ notebooks (reference apps/ ports, batch 2):
fraud-detection, image-augmentation, recommendation-ncf.
Run: python tools/make_app_notebooks2.py
"""

import json
import os

from make_app_notebooks import APPS, code, md, nb

fraud = nb([
    md("""# Fraud detection with imbalanced binary classification

Mirror of the reference app `apps/fraud-detection` (credit-card fraud on
a heavily imbalanced table -> MLP classifier -> threshold tuning on
precision/recall), rebuilt TPU-native.  A synthetic transactions table
(1.5% fraud rate, structured fraud signature + noise) stands in for the
Kaggle dataset (no downloads in this sandbox); the modelling steps —
class rebalancing by oversampling, AUC evaluation, threshold sweep — are
the reference's."""),
    code("""import numpy as np

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, Dropout

zoo.init_zoo_context(seed=0)
rng = np.random.default_rng(0)
N, D = 8192, 16
is_fraud = rng.random(N) < 0.015
x = rng.normal(size=(N, D)).astype(np.float32)
# fraud signature: a sparse directional shift + heavier tails
w_sig = rng.normal(size=(D,)) * (rng.random(D) < 0.4)
x[is_fraud] += 1.4 * w_sig + rng.normal(
    scale=1.5, size=(is_fraud.sum(), D)) * 0.3
y = is_fraud.astype(np.int32)
print("fraud rate:", y.mean())"""),
    md("""## Rebalance by oversampling the minority class
(the reference uses the same trick before training)"""),
    code("""n_train = 6144
xt, yt = x[:n_train], y[:n_train]
xv, yv = x[n_train:], y[n_train:]
fraud_idx = np.where(yt == 1)[0]
over = rng.choice(fraud_idx, size=len(yt) - 2 * len(fraud_idx))
xb = np.concatenate([xt, xt[over]])
yb = np.concatenate([yt, yt[over]])
perm = rng.permutation(len(xb))
xb, yb = xb[perm][:6144], yb[perm][:6144]
print("balanced fraud rate:", yb.mean())"""),
    code("""model = Sequential()
model.add(Dense(32, activation="relu", input_shape=(16,)))
model.add(Dropout(0.2))
model.add(Dense(16, activation="relu"))
model.add(Dense(2, activation="softmax"))
model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
model.fit(xb, yb, batch_size=64, nb_epoch=10)"""),
    md("## Evaluate with ROC-AUC and sweep the decision threshold"),
    code("""import jax.numpy as jnp

from analytics_zoo_tpu.pipeline.api.keras.metrics import AUC

probs = np.asarray(model.predict(xv))[:, 1]
auc = AUC(thresholds=200)  # streaming metric: device stats + host finalize
stats = auc.batch_stats(jnp.asarray(yv.astype(np.float32)),
                        jnp.asarray(probs))
auc_value = float(auc.finalize([np.asarray(s) for s in stats]))
print("ROC-AUC on held-out:", round(auc_value, 4))

best = None
for thr in np.linspace(0.05, 0.95, 19):
    pred = (probs > thr).astype(int)
    tp = int(((pred == 1) & (yv == 1)).sum())
    fp = int(((pred == 1) & (yv == 0)).sum())
    fn = int(((pred == 0) & (yv == 1)).sum())
    prec = tp / max(tp + fp, 1)
    rec = tp / max(tp + fn, 1)
    f1 = 2 * prec * rec / max(prec + rec, 1e-9)
    if best is None or f1 > best[1]:
        best = (thr, f1, prec, rec)
thr, f1, prec, rec = best
print(f"best threshold {thr:.2f}: F1 {f1:.3f} "
      f"(precision {prec:.3f}, recall {rec:.3f})")
assert auc_value > 0.9
assert f1 > 0.5"""),
])

augment = nb([
    md("""# Image augmentation gallery

Mirror of the reference apps `apps/image-augmentation` and
`apps/image-augmentation-3d`: every transform in the feature/image and
feature/image3d libraries applied to a sample image/volume, composed
with the `>>` operator (the reference's `->`), with deterministic
randomness via record seeds."""),
    code("""import numpy as np

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.feature.image import (
    ImageBrightness, ImageCenterCrop, ImageChannelNormalize, ImageExpand,
    ImageHFlip, ImageHue, ImageRandomCrop, ImageResize, ImageSaturation,
)

zoo.init_zoo_context(seed=0)
rng = np.random.default_rng(0)
img = np.clip(rng.normal(120, 40, (64, 64, 3)), 0, 255).astype(np.uint8)
img[16:48, 16:48] = [200, 80, 60]  # a "subject" patch
results = {}
for name, op in [
    ("resize", ImageResize(32, 32)),
    ("center_crop", ImageCenterCrop(40, 40)),
    ("random_crop", ImageRandomCrop(40, 40)),
    ("hflip", ImageHFlip(1.0)),
    ("brightness", ImageBrightness(-32, 32)),
    ("hue", ImageHue(18)),
    ("saturation", ImageSaturation(0.5, 1.5)),
    ("expand", ImageExpand(max_expand_ratio=2.0)),
]:
    out = op(img)
    results[name] = np.asarray(out).shape
results"""),
    md("## Compose a training pipeline with `>>` (reference `->`)"),
    code("""chain = (ImageResize(48, 48) >> ImageHFlip(0.5)
         >> ImageBrightness(-16, 16) >> ImageCenterCrop(40, 40)
         >> ImageChannelNormalize(127.0, 127.0, 127.0,
                                  58.0, 58.0, 58.0))
out = chain(img)
print(out.shape, float(np.asarray(out).mean()).__round__(3))
assert out.shape == (40, 40, 3)"""),
    md("""## 3D (medical) transforms — affine, rotation, warp
(reference image-augmentation-3d)"""),
    code("""from analytics_zoo_tpu.feature.image3d import (
    CenterCrop3D, RandomCrop3D, Rotate3D, Warp3D,
)

vol = rng.normal(size=(24, 24, 24)).astype(np.float32)
rot = Rotate3D(yaw=0.3)(vol)
crop = CenterCrop3D((16, 16, 16))(vol)
flow = np.zeros((3, 24, 24, 24))
flow[2] = 1.5  # shift sampling 1.5 voxels along x
warped = Warp3D(flow)(vol)
chain3d = Rotate3D(roll=0.2) >> RandomCrop3D((12, 12, 12))
out3d = chain3d(vol)
shapes = dict(rot=rot.shape, crop=crop.shape, warp=warped.shape,
              chain=out3d.shape)
print(shapes)
assert out3d.shape == (12, 12, 12)
done = True"""),
])

ncf = nb([
    md("""# Neural Collaborative Filtering recommendation

Mirror of the reference app `apps/recommendation-ncf` (MovieLens ->
NeuralCF -> recommend_for_user), rebuilt TPU-native on a synthetic
interaction matrix with latent taste structure (no dataset downloads
here).  The model/API surface is the reference's: `NeuralCF`,
`predict_user_item_pair`, `recommend_for_user`."""),
    code("""import numpy as np

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.models.recommendation import NeuralCF

zoo.init_zoo_context(seed=0)
rng = np.random.default_rng(0)
N_USERS, N_ITEMS, K = 60, 80, 3
u_taste = rng.normal(size=(N_USERS, K))
i_trait = rng.normal(size=(N_ITEMS, K))
score = u_taste @ i_trait.T + 0.3 * rng.normal(size=(N_USERS, N_ITEMS))
liked = (score > np.quantile(score, 0.75, axis=1, keepdims=True))

pairs, labels = [], []
for u in range(N_USERS):
    pos = np.where(liked[u])[0]
    neg = np.where(~liked[u])[0]
    neg = rng.choice(neg, size=len(pos), replace=False)
    for i in pos:
        pairs.append((u, i)); labels.append(1)
    for i in neg:
        pairs.append((u, i)); labels.append(0)
pairs = np.asarray(pairs, np.int32)
labels = np.asarray(labels, np.int32)
perm = rng.permutation(len(pairs))
pairs, labels = pairs[perm], labels[perm]
n_train = (int(len(pairs) * 0.85) // 64) * 64
print(len(pairs), "pairs,", labels.mean(), "positive")"""),
    code("""ncf = NeuralCF(user_count=N_USERS, item_count=N_ITEMS,
               class_num=2, user_embed=16, item_embed=16,
               hidden_layers=(32, 16), include_mf=True, mf_embed=8)
ncf.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
            metrics=["accuracy"])
# model inputs are [user_ids, item_ids] (the reference's two-column
# contract)
ncf.fit([pairs[:n_train, 0], pairs[:n_train, 1]], labels[:n_train],
        batch_size=64, nb_epoch=40)
test_acc = ncf.evaluate([pairs[n_train:, 0], pairs[n_train:, 1]],
                        labels[n_train:], batch_size=64)["accuracy"]
print("held-out accuracy:", test_acc)
assert test_acc > 0.75"""),
    md("## Recommend items for a user (reference `recommendForUser`)"),
    code("""user = 7
recs = ncf.recommend_for_user(user, candidate_items=np.arange(N_ITEMS),
                              max_items=5)
rec_items = [int(i) for i, _ in recs]
print("top-5 for user", user, ":", recs)
# the recommended items should mostly be ones the user actually likes
hit = np.mean([liked[user, i] for i in rec_items])
print("fraction of top-5 the user truly likes:", hit)
assert hit >= 0.6"""),
])

for name, book in [("fraud_detection.ipynb", fraud),
                   ("image_augmentation.ipynb", augment),
                   ("recommendation_ncf.ipynb", ncf)]:
    path = os.path.join(APPS, name)
    with open(path, "w") as f:
        json.dump(book, f, indent=1)
    print("wrote", path)
