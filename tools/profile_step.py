"""Capture a jax.profiler trace of the pure-device ResNet-50 train step.

Writes the trace under PROFILE_r05/ (override: second CLI arg) and prints
a JSON line with the top-k ops by self time parsed back out of the trace
(trace_viewer json.gz).
"""

import glob
import gzip
import json
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np


def build_step(batch, image=224):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.resnet import ResNet

    ctx = init_zoo_context(seed=0)
    net = ResNet.image_net(50, classes=1000, input_shape=(image, image, 3))
    net.compile(optimizer=ResNet.imagenet_optimizer(
        batch_size=batch, steps_per_epoch=100),
        loss="sparse_categorical_crossentropy")
    est = net._make_estimator()
    params, state = est.model.build_params()
    opt_state = est.optimizer.init(params)
    repl = ctx.replicated()
    params, opt_state, state = jax.device_put((params, opt_state, state), repl)
    step_fn = est._build_train_step()
    x = np.random.default_rng(0).normal(size=(batch, image, image, 3)).astype(
        np.float32)
    y = np.random.default_rng(1).integers(0, 1000, size=(batch,)).astype(
        np.int32)
    sharded = ctx.shard_batch({"x": x, "y": y})
    return step_fn, params, opt_state, state, sharded


def summarize(trace_dir, top=25):
    """Parse trace_viewer json.gz: aggregate event durations by name."""
    files = glob.glob(os.path.join(
        trace_dir, "**", "*.trace.json.gz"), recursive=True)
    if not files:
        return None
    with gzip.open(sorted(files)[-1], "rt") as f:
        data = json.load(f)
    # Restrict to TPU/device tracks (pid names containing TPU or /device)
    pid_names = {}
    for ev in data.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pid_names[ev["pid"]] = ev.get("args", {}).get("name", "")
    dur_by_name = defaultdict(float)
    dur_by_class = defaultdict(float)
    total = 0.0
    for ev in data.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        pname = pid_names.get(ev.get("pid"), "")
        if "TPU" not in pname and "tpu" not in pname and "XLA" not in pname:
            continue
        name = ev.get("name", "?")
        if name.startswith("jit_") or name.isdigit():
            continue  # umbrella / step markers, not leaf ops
        d = ev.get("dur", 0) / 1e3  # ms
        args = ev.get("args", {}) or {}
        long = " ".join(str(v) for v in args.values()) + " " + name
        if "convolution" in long or "conv" in name:
            cls = "convolution"
        elif any(k in long for k in ("select_and_scatter", "reduce_window")):
            cls = "pooling"
        elif "reduce" in long:
            cls = "reduce/stats"
        elif any(k in long for k in ("copy", "transpose", "bitcast")):
            cls = "copy/layout"
        else:
            cls = "elementwise/other"
        dur_by_name[name] += d
        dur_by_class[cls] += d
        total += d
    ranked = sorted(dur_by_name.items(), key=lambda kv: -kv[1])[:top]
    return {"total_ms": round(total, 1),
            "tracks": sorted(set(pid_names.values())),
            "by_class_ms": {k: round(v, 1)
                            for k, v in sorted(dur_by_class.items(),
                                               key=lambda kv: -kv[1])},
            "top_ops": [[n, round(d, 2)] for n, d in ranked]}


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    trace_dir = (sys.argv[2] if len(sys.argv) > 2 else
                 os.path.join(os.path.dirname(__file__), "..",
                              "PROFILE_r05"))
    step_fn, params, opt_state, state, sharded = build_step(batch)
    seed_arr = np.asarray(0, np.int32)

    # compile + warm
    params, opt_state, state, loss = step_fn(
        params, opt_state, state, seed_arr, np.asarray(0, np.int32), sharded)
    loss.block_until_ready()

    with jax.profiler.trace(trace_dir):
        for i in range(5):
            params, opt_state, state, loss = step_fn(
                params, opt_state, state, seed_arr,
                np.asarray(i + 1, np.int32), sharded)
        loss.block_until_ready()

    time.sleep(1)
    out = summarize(trace_dir) or {"error": "no trace files found"}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
