"""Per-fusion roofline table for the ResNet-50 pure step.

For every device op in a jax.profiler trace of the step, joins its
measured ms/step against a roofline bound computed from the compiled
HLO's operand/result shapes at THIS machine's measured ceilings
(PROFILE_r03/ANALYSIS.md): HBM streaming and sustained MXU rate.  An op
whose achieved bandwidth/compute sits at the ceiling is environment-
bound; anything far below ceiling is a framework target.

Usage: python tools/roofline_table.py [batch] [trace_dir] [--json out]
  trace_dir default PROFILE_r05 (or $ZOO_PROFILE_DIR).  Needs the same
  backend the trace came from (compiles the step to map op -> shapes).
"""

import glob
import gzip
import json
import os
import re
import sys
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

HBM_CEILING_GBPS = 514.0   # measured (differential timing, r+w), 63% of spec
MXU_CEILING_TFLOPS = 192.6  # measured (chained 4096^3 bf16 matmuls)

_DTYPE_BYTES = {"bf16": 2, "f16": 2, "f32": 4, "f64": 8, "s8": 1, "u8": 1,
                "s16": 2, "u16": 2, "s32": 4, "u32": 4, "s64": 8, "u64": 8,
                "pred": 1}


def shapes_in(line):
    """All dtype[shape] terms on an HLO line -> bytes each."""
    out = []
    for m in re.finditer(r"(\w+)\[([\d,]*)\]", line):
        dt = _DTYPE_BYTES.get(m.group(1))
        if dt is None:
            continue
        dims = [int(x) for x in m.group(2).split(",") if x]
        out.append(dt * int(np.prod(dims)) if dims else dt)
    return out


def conv_flops(line):
    """2 * prod(out_dims) * Cin * kh * kw for a conv HLO line, reading
    Cin and the spatial kernel dims from the rhs dim_labels (layout-
    proof: 'i' marks in-features, digits mark spatial)."""
    shp = re.findall(r"\w+\[([\d,]+)\]", line)
    dl = re.search(r"dim_labels=[\w?]+_([\w?]+)->", line)
    if not (len(shp) >= 3 and dl):
        return None
    out_dims = [int(x) for x in shp[0].split(",")]
    rhs = [int(x) for x in shp[2].split(",")]
    cin, k = None, 1
    for ch, d in zip(dl.group(1), rhs):
        if ch == "i":
            cin = d
        elif ch.isdigit():
            k *= d
    if cin is None:
        return None
    return 2 * int(np.prod(out_dims)) * cin * k


def main():
    if "--cpu" in sys.argv:
        # must precede ANY backend touch: jax.devices("cpu") still
        # initializes the axon plugin (and dies if the tunnel is down);
        # only the config knob keeps the process off it entirely
        import jax

        jax.config.update("jax_platforms", "cpu")
    argv = [a for a in sys.argv[1:] if a != "--cpu"]
    out_path = None
    if "--json" in argv:
        i = argv.index("--json")
        if i + 1 >= len(argv):
            sys.exit("--json needs a path")
        out_path = argv[i + 1]
        del argv[i:i + 2]
    flag_steps = 5  # profile_step.py's loop count (fallback when the
    # trace carries no recognisable jit module events)
    if "--steps" in argv:
        i = argv.index("--steps")
        if i + 1 >= len(argv):
            sys.exit("--steps needs a value")
        flag_steps = int(argv[i + 1])
        del argv[i:i + 2]
    # reject unknown flags: an unrecognized '--flag value' pair would leave
    # the value behind to be misparsed as the positional batch/trace_dir
    unknown = [a for a in argv if a.startswith("--")]
    if unknown:
        sys.exit(f"unknown flags: {' '.join(unknown)}")
    args = argv
    batch = int(args[0]) if args else 256
    trace_dir = args[1] if len(args) > 1 else os.environ.get(
        "ZOO_PROFILE_DIR", "PROFILE_r05")

    # Trace first: fail on a bad/missing trace BEFORE the multi-minute
    # step compile.
    files = glob.glob(f"{trace_dir}/**/*.trace.json.gz", recursive=True)
    if not files:
        sys.exit(f"no trace under {trace_dir}/ — run tools/profile_step.py")
    with gzip.open(sorted(files)[-1], "rt") as f:
        data = json.load(f)
    pid_names = {}
    for ev in data["traceEvents"]:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pid_names[ev["pid"]] = ev.get("args", {}).get("name", "")
    tpu_pids = sorted(p for p, n in pid_names.items() if "TPU" in n)
    if not tpu_pids:
        sys.exit("no TPU process in trace")
    # ONE core only: multi-chip traces repeat every fusion name per core,
    # and summing across cores would inflate ms by the core count while
    # the HLO-derived bounds would not
    pid0 = tpu_pids[0]
    dur_total = defaultdict(float)
    for ev in data["traceEvents"]:
        if ev.get("ph") != "X" or ev.get("pid") != pid0:
            continue
        dur_total[ev.get("name", "")] += ev.get("dur", 0) / 1e3
    # per-step divisor: how many times the jitted step module ran on this
    # core (profile_step.py loops it); prefer a module named like a step,
    # fall back to --steps (default 5 = profile_step.py's loop count)
    mod_counts = defaultdict(int)
    for ev in data["traceEvents"]:
        if (ev.get("ph") == "X" and ev.get("pid") == pid0
                and str(ev.get("name", "")).startswith("jit")):
            mod_counts[ev["name"]] += 1
    step_mods = {n: c for n, c in mod_counts.items() if "step" in n.lower()}
    pick = step_mods or mod_counts
    steps = max(pick.values()) if pick else None
    if steps is None or not (1 <= steps <= 1000):
        steps = int(flag_steps)
    dur = {n: d / steps for n, d in dur_total.items()}

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.resnet import ResNet

    # --cpu (handled above): structural smoke-testing off-chip — op names
    # then only partially join a TPU trace; the real run needs a chip
    init_zoo_context(seed=0)
    net = ResNet.image_net(50, classes=1000, input_shape=(224, 224, 3))
    net.compile(optimizer=ResNet.imagenet_optimizer(
        batch_size=batch, steps_per_epoch=100),
        loss="sparse_categorical_crossentropy")
    est = net._make_estimator()
    params, state = est.model.build_params()
    opt_state = est.optimizer.init(params)
    step = est._build_train_step()
    b = {"x": np.zeros((batch, 224, 224, 3), np.float32),
         "y": np.zeros((batch,), np.int32)}
    hlo = step.lower(params, opt_state, state, np.int32(0), np.int32(0),
                     b).compile().as_text()

    # Two passes: HLO op lines carry only the RESULT shape inline —
    # operands are %name references.  Pass 1 maps name -> result bytes;
    # pass 2 sums result + operand buffers per op (the HBM traffic bound).
    result_bytes = {}
    lines = []
    for line in hlo.splitlines():
        m = re.match(r"\s*(?:ROOT )?%?([\w.\-]+) = (.*)$", line)
        if not m:
            continue
        name, rhs = m.group(1), m.group(2)
        # rhs = "<type> <opcode>(<operands>), attrs..." where <type> may be
        # a tuple "(bf16[...], f32[...])" — split at the opcode call, not
        # at the first paren, or tuple-result ops (BN stats) undercount
        m2 = re.match(r"(.*?)\s([a-z][\w\-]*)\((.*)$", rhs)
        if not m2:
            continue
        type_part, _opcode, operand_part = m2.groups()
        rb = sum(shapes_in(type_part))
        result_bytes[name] = rb
        lines.append((name, line, rb, operand_part))
    info = {}
    for name, line, rb, operand_part in lines:
        operands = re.findall(r"%?([\w.\-]+)", operand_part.split(")", 1)[0])
        byts = rb + sum(result_bytes.get(o, 0) for o in operands)
        fl = conv_flops(line) if "convolution(" in line else None
        if byts:
            info[name] = (byts, fl)

    rows = []
    for name, ms in dur.items():
        if name not in info or ms <= 0.005:
            continue
        byts, fl = info[name]
        bound_ms_hbm = byts / (HBM_CEILING_GBPS * 1e6)
        row = {"op": name, "ms": round(ms, 3),
               "bytes_mb": round(byts / 1e6, 1),
               "achieved_gbps": round(byts / ms / 1e6, 1),
               "hbm_roofline_ms": round(bound_ms_hbm, 3),
               "x_hbm_roofline": round(ms / bound_ms_hbm, 2)
               if bound_ms_hbm else None}
        if fl:
            bound_ms_mxu = fl / (MXU_CEILING_TFLOPS * 1e9)
            row["gflop"] = round(fl / 1e9, 1)
            row["achieved_tflops"] = round(fl / ms / 1e9, 1)
            row["mxu_roofline_ms"] = round(bound_ms_mxu, 3)
            row["x_roofline"] = round(
                ms / max(bound_ms_mxu, bound_ms_hbm), 2)
        rows.append(row)
    rows.sort(key=lambda r: -r["ms"])

    total = sum(r["ms"] for r in rows)
    bound = sum(max(r.get("mxu_roofline_ms", 0), r["hbm_roofline_ms"])
                for r in rows)
    summary = {
        "trace": trace_dir, "batch": batch, "steps_divisor": steps,
        "tpu_processes_in_trace": len(tpu_pids),
        "attributed_ms_per_step": round(total, 1),
        "composite_roofline_ms": round(bound, 1),
        "x_composite_roofline": round(total / bound, 2) if bound else None,
        "ceilings": {"hbm_gbps_measured": HBM_CEILING_GBPS,
                     "mxu_tflops_measured": MXU_CEILING_TFLOPS},
    }
    print(json.dumps(summary))
    for r in rows[:40]:
        print(json.dumps(r))
    if out_path:
        with open(out_path, "w") as f:
            json.dump({"summary": summary, "rows": rows}, f, indent=1)


if __name__ == "__main__":
    main()
