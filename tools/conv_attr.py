"""Attribute profiled conv op times to conv shapes.

Compiles the ResNet-50 train step, dumps optimized HLO to map
convolution.N -> (operand shapes), then sums the profiled trace
durations per conv name and prints the per-shape cost ranking.
Usage: conv_attr.py [batch] [trace_dir]  (trace_dir default PROFILE_r03,
or $ZOO_PROFILE_DIR).
"""

import glob
import gzip
import json
import os
import re
import sys
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.resnet import ResNet

    ctx = init_zoo_context(seed=0)
    net = ResNet.image_net(50, classes=1000, input_shape=(224, 224, 3))
    net.compile(optimizer=ResNet.imagenet_optimizer(
        batch_size=batch, steps_per_epoch=100),
        loss="sparse_categorical_crossentropy")
    est = net._make_estimator()
    params, state = est.model.build_params()
    opt_state = est.optimizer.init(params)
    step = est._build_train_step()
    b = {"x": np.zeros((batch, 224, 224, 3), np.float32),
         "y": np.zeros((batch,), np.int32)}
    compiled = step.lower(params, opt_state, state, np.int32(0), np.int32(0),
                          b).compile()
    hlo = compiled.as_text()

    # map op name -> shapes involved
    shape_of = {}
    for m in re.finditer(
            r"%?(convolution[\w.\-]*|fusion[\w.\-]*) = (\S+?) (convolution|fusion)\(",
            hlo):
        shape_of[m.group(1)] = m.group(2)
    conv_lines = {}
    for line in hlo.splitlines():
        m = re.search(r"%?([\w.\-]+) = \S+ convolution\(", line)
        if m:
            shapes = re.findall(r"(?:bf16|f32)\[[\d,]+\]", line)
            conv_lines[m.group(1)] = " ".join(shapes[:3])

    trace_dir = sys.argv[2] if len(sys.argv) > 2 else os.environ.get(
        "ZOO_PROFILE_DIR", "PROFILE_r03")
    files = glob.glob(f"{trace_dir}/**/*.trace.json.gz", recursive=True)
    if not files:
        sys.exit(f"no trace under {trace_dir}/ — run tools/profile_step.py "
                 "first (usage: conv_attr.py [batch] [trace_dir])")
    with gzip.open(sorted(files)[-1], "rt") as f:
        data = json.load(f)
    pid_names = {}
    for ev in data["traceEvents"]:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pid_names[ev["pid"]] = ev.get("args", {}).get("name", "")
    dur = defaultdict(float)
    for ev in data["traceEvents"]:
        if ev.get("ph") != "X":
            continue
        if "TPU" not in pid_names.get(ev.get("pid"), ""):
            continue
        n = ev.get("name", "")
        if n.startswith("convolution") or (
                n in conv_lines):
            dur[n] += ev.get("dur", 0) / 1e3 / 5  # per step (5 steps traced)
    rows = []
    for n, d in dur.items():
        rows.append((d, n, conv_lines.get(n, shape_of.get(n, "?"))))
    rows.sort(reverse=True)
    total = sum(r[0] for r in rows)
    print(json.dumps({"conv_total_ms_per_step": round(total, 1)}))
    for d, n, s in rows[:30]:
        print(json.dumps({"op": n, "ms": round(d, 2), "shapes": s}))


if __name__ == "__main__":
    main()
