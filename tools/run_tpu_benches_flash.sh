#!/bin/bash
# Follow-up TPU queue for the fixed flash backward kernels: flash +
# transformer artifacts only (the stages the Mosaic i1-reshape bug killed
# in the main round-4 queue), then a perf/profile retry if requested
# (e.g. when the main queue's window was degraded).
# Usage: bash tools/run_tpu_benches_flash.sh [logdir] [--with-perf]
#        (arguments may appear in either order)
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/tpu_benches_flash
WITH_PERF=0
for arg in "$@"; do
  case "$arg" in
    --with-perf) WITH_PERF=1 ;;
    --*) echo "unknown flag: $arg" >&2; exit 2 ;;
    *) LOG=$arg ;;
  esac
done
mkdir -p "$LOG"
. tools/tpu_queue_lib.sh || exit 1  # cwd is the repo root after the cd above

run flash 3600 python tools/flash_bench.py

run transformer 4800 python tools/transformer_bench.py \
  --seq 2048 --batch 8 --blocks 8 --hidden 2560 --heads 20 --steps 8 \
  --remat --out TRANSFORMER_r05.json

if [ "$WITH_PERF" = 1 ]; then
  run perf 3000 python tools/perf_probe.py --batch 256 --steps 20
  run profile 3000 python tools/profile_step.py 256
fi

echo "$(date) queue complete" | tee -a "$LOG/queue.log"
