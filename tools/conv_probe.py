"""Per-shape conv microbench: times each representative ResNet-50 conv
shape (fwd only) with L reps inside ONE dispatch (scan), subtracting the
tunnel's fixed ~70ms fetch latency.  Prints JSON lines."""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

B = int(sys.argv[1]) if len(sys.argv) > 1 else 256
FETCH_S = 0.070

SHAPES = [
    # (name, H, Cin, Cout, k, stride)
    ("stem7x7", 224, 3, 64, 7, 2),
    ("c1_64_56", 56, 64, 64, 1, 1),
    ("c3_64_56", 56, 64, 64, 3, 1),
    ("c1_64_256_56", 56, 64, 256, 1, 1),
    ("c3_128_28", 28, 128, 128, 3, 1),
    ("c1_512_128_28", 28, 512, 128, 1, 1),
    ("c3_256_14", 14, 256, 256, 3, 1),
    ("c3_512_7", 7, 512, 512, 3, 1),
    ("c1_2048_512_7", 7, 2048, 512, 1, 1),
]


def time_shape(name, H, cin, cout, k, stride, L=30):
    key = jax.random.PRNGKey(0)
    x = (jax.random.normal(key, (B, H, H, cin)) * 0.1).astype(jnp.bfloat16)
    w = (jax.random.normal(key, (k, k, cin, cout)) * 0.1).astype(jnp.bfloat16)

    @jax.jit
    def f(x, w):
        def body(xc, _):
            y = lax.conv_general_dilated(
                xc, w, (stride, stride), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            # Serialize iterations: next input depends on this output, so
            # the conv cannot be hoisted out of the loop (loop-invariant
            # code motion elided an earlier version of this probe).
            s = jnp.tanh(jnp.sum(y.astype(jnp.float32))) * jnp.bfloat16(1e-6)
            return xc + s.astype(xc.dtype), ()
        xe, _ = jax.lax.scan(body, x, None, length=L)
        return jnp.sum(xe.astype(jnp.float32))

    float(f(x, w))  # warm/compile
    t0 = time.perf_counter()
    float(f(x, w))
    # FETCH_S is this harness's tunnel latency; clamp so a fast machine
    # (real TPU VM, ~1 ms fetch) can never print negative times.
    dt = max(time.perf_counter() - t0 - FETCH_S, 1e-9) / L
    Ho = H // stride
    flops = 2 * B * Ho * Ho * k * k * cin * cout
    print(json.dumps({
        "shape": name, "ms": round(dt * 1e3, 3),
        "tflops": round(flops / dt / 1e12, 1),
        "gflop": round(flops / 1e9, 1)}), flush=True)


for s in SHAPES:
    time_shape(*s)
