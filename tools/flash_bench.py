"""Flash-attention micro-bench on compiled TPU (not interpret mode).

Times the Pallas kernel vs the jnp O(L^2) reference at long context, both
inside one jit with a scan of dependent iterations (the only reliable
timing shape on this harness — see PROFILE_r03/ANALYSIS.md), and verifies
numerics vs the reference on the first block.

Round 4 adds the REAL training configurations (VERDICT r03 item 1): the
kernel is also timed with a BERT-style (B, 1, 1, L) padding mask plus
attention dropout, and with packed-segment masking — the acceptance bar is
masked+dropout within ~10% of the clean kernel's TFLOP/s.

Writes FLASH_r05.json.  Usage: python tools/flash_bench.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.ops.pallas.flash_attention import (
    _attention_reference,
    _flash_fwd_pallas,
    _resolve_blocks,
    flash_attention,
)

FETCH_S = 0.070  # tunnel fixed fetch latency (PROFILE_r03/ANALYSIS.md)


def timed(fn, q, k, v, reps=10):
    @jax.jit
    def loop(q, k, v):
        def body(c, _):
            o = fn(c, k, v)
            s = jnp.tanh(jnp.sum(o.astype(jnp.float32))) * 1e-6
            return c + s.astype(c.dtype), ()
        c, _ = jax.lax.scan(body, q, None, length=reps)
        return jnp.sum(c.astype(jnp.float32))

    float(loop(q, k, v))  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(loop(q, k, v))
        best = min(best, time.perf_counter() - t0)
    return max(best - FETCH_S, 1e-9) / reps


def main():
    d = jax.devices()[0]
    out = {"device": d.device_kind, "platform": d.platform,
           "mode": "compiled (not interpret)"}
    results = []
    # batch 8 (not 4): at B=4 the 16.8 MB bf16 q/k/v operands fit XLA's
    # scoped-VMEM stack-placement heuristic inside the scan harness and OOM
    # the 16 MB budget — a harness artifact, not a kernel limit (the kernel
    # compiles standalone at any of these shapes).  33 MB operands are
    # never stack-placed.
    for L in (4096, 8192):
        B, H, D = 8, 8, 64
        key = jax.random.PRNGKey(0)
        q = (jax.random.normal(key, (B, H, L, D)) * 0.3).astype(jnp.bfloat16)
        k = (jax.random.normal(jax.random.PRNGKey(1), (B, H, L, D))
             * 0.3).astype(jnp.bfloat16)
        v = (jax.random.normal(jax.random.PRNGKey(2), (B, H, L, D))
             * 0.3).astype(jnp.bfloat16)
        scale = 1.0 / np.sqrt(D)
        # BERT-style padding mask: last 12.5% of keys padded out
        keep = np.ones((B, 1, 1, L), np.float32)
        keep[:, :, :, int(L * 0.875):] = 0.0
        bias = jnp.asarray((1.0 - keep) * -1e9)
        segs = jnp.asarray(np.repeat(
            [[0] * (L // 2) + [1] * (L - L // 2)], B, 0).astype(np.int32))
        seed = jnp.asarray([3, 11], jnp.int32)

        variants = {
            "clean": dict(),
            "causal": dict(causal=True),
            "train_mask_dropout": dict(bias=bias, dropout_p=0.1, seed=seed),
            "train_causal_seg_dropout": dict(
                causal=True, q_seg=segs, kv_seg=segs, dropout_p=0.1,
                seed=seed),
        }

        def make_flash(kw):
            causal = kw.get("causal", False)
            # blocks resolved per-variant: dropout narrows block_k to fit
            # the PRNG-bits tile in scoped VMEM
            bq, bk = _resolve_blocks(None, None,
                                     dropout=kw.get("dropout_p", 0) > 0)
            return lambda q, k, v: _flash_fwd_pallas(
                q, k, v, causal, scale, bq, bk,
                bias=kw.get("bias"), q_seg=kw.get("q_seg"),
                kv_seg=kw.get("kv_seg"), dropout_p=kw.get("dropout_p", 0.0),
                seed=kw.get("seed"))

        row = {"seq_len": L, "batch": B, "heads": H, "head_dim": D,
               "blocks_clean": _resolve_blocks(None, None),
               "blocks_dropout": _resolve_blocks(None, None, dropout=True)}
        flops = 4 * B * H * L * L * D  # 2 matmuls, 2*L*L*D each
        for name, kw in variants.items():
            t = timed(make_flash(kw), q, k, v)
            eff_flops = flops * (0.5 if kw.get("causal") else 1.0)
            row[name] = {"ms": round(t * 1e3, 2),
                         "tflops": round(eff_flops / t / 1e12, 1)}
            # full train step (fwd + Pallas bwd kernels) through the
            # public custom_vjp: grad wrt q
            def fl_pub(q, k, v, kw=kw):
                return flash_attention(
                    q, k, v, kw.get("causal", False), scale,
                    bias=kw.get("bias"), q_segment_ids=kw.get("q_seg"),
                    kv_segment_ids=kw.get("kv_seg"),
                    dropout_p=kw.get("dropout_p", 0.0),
                    dropout_seed=seed if kw.get("dropout_p") else None)

            grad_fn = jax.grad(
                lambda q, k, v: jnp.sum(
                    fl_pub(q, k, v).astype(jnp.float32) ** 2))
            t_tr = timed(grad_fn, q, k, v)
            row[name]["train_ms"] = round(t_tr * 1e3, 2)
            row[name]["train_tflops"] = round(
                eff_flops * 3.5 / t_tr / 1e12, 1)  # fwd 2 + bwd 5 matmuls
        row["train_vs_clean"] = round(
            row["train_mask_dropout"]["tflops"] / row["clean"]["tflops"], 3)

        # numerics: compiled Pallas vs reference on one batch row (the
        # dense path's f32 L x L matrix at full batch OOMs 16G HBM at 8k)
        kw = variants["train_mask_dropout"]
        got = np.asarray(jax.jit(make_flash(kw))(q[:1], k[:1], v[:1]),
                         np.float32)
        want = np.asarray(jax.jit(lambda q, k, v: _attention_reference(
            q, k, v, False, scale, bias=bias[:1], dropout_p=0.1,
            seed=seed))(q[:1], k[:1], v[:1]), np.float32)
        row["train_max_abs_err_vs_reference"] = float(
            np.max(np.abs(got - want)))
        if L == 4096:
            try:
                t_ref = timed(lambda q, k, v: _attention_reference(
                    q, k, v, False, scale, bias=bias, dropout_p=0.1,
                    seed=seed), q, k, v)
                row["jnp_train_ms"] = round(t_ref * 1e3, 2)
                row["train_speedup"] = round(
                    t_ref * 1e3 / row["train_mask_dropout"]["ms"], 2)
            except Exception as e:  # noqa: BLE001 — record OOM, don't die
                row["jnp_train_error"] = str(e).splitlines()[0][:200]
        results.append(row)
        print(json.dumps(row))
    out["results"] = results
    path = os.path.join(os.path.dirname(__file__), "..", "FLASH_r05.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
