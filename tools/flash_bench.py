"""Flash-attention micro-bench on compiled TPU (not interpret mode).

Times the Pallas kernel vs the jnp O(L^2) reference at long context, both
inside one jit with a scan of dependent iterations (the only reliable
timing shape on this harness — see PROFILE_r03/ANALYSIS.md), and verifies
numerics vs the reference on the first block.  Writes FLASH_r03.json.

Usage: python tools/flash_bench.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.ops.pallas.flash_attention import (
    _attention_reference,
    _flash_fwd_pallas,
    _resolve_blocks,
)

FETCH_S = 0.070  # tunnel fixed fetch latency (PROFILE_r03/ANALYSIS.md)


def timed(fn, q, k, v, reps=10):
    @jax.jit
    def loop(q, k, v):
        def body(c, _):
            o = fn(c, k, v)
            s = jnp.tanh(jnp.sum(o.astype(jnp.float32))) * 1e-6
            return c + s.astype(c.dtype), ()
        c, _ = jax.lax.scan(body, q, None, length=reps)
        return jnp.sum(c.astype(jnp.float32))

    float(loop(q, k, v))  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(loop(q, k, v))
        best = min(best, time.perf_counter() - t0)
    return max(best - FETCH_S, 1e-9) / reps


def main():
    d = jax.devices()[0]
    out = {"device": d.device_kind, "platform": d.platform,
           "mode": "compiled (not interpret)"}
    results = []
    for L in (4096, 8192):
        B, H, D = 4, 8, 64
        key = jax.random.PRNGKey(0)
        q = (jax.random.normal(key, (B, H, L, D)) * 0.3).astype(jnp.bfloat16)
        k = (jax.random.normal(jax.random.PRNGKey(1), (B, H, L, D))
             * 0.3).astype(jnp.bfloat16)
        v = (jax.random.normal(jax.random.PRNGKey(2), (B, H, L, D))
             * 0.3).astype(jnp.bfloat16)
        scale = 1.0 / np.sqrt(D)

        bq, bk = _resolve_blocks(L, None, None)
        flash = lambda q, k, v: _flash_fwd_pallas(
            q, k, v, False, scale, bq, bk)
        ref = lambda q, k, v: _attention_reference(q, k, v, False, scale)

        # numerics: compiled Pallas vs reference on one batch row (the
        # dense path's f32 L x L matrix at full batch OOMs 16G HBM at 8k)
        got = np.asarray(jax.jit(flash)(q[:1], k[:1], v[:1]), np.float32)
        want = np.asarray(jax.jit(ref)(q[:1], k[:1], v[:1]), np.float32)
        err = float(np.max(np.abs(got - want)))
        t_flash = timed(flash, q, k, v)
        flops = 4 * B * H * L * L * D  # 2 matmuls, 2*L*L*D each
        row = {
            "seq_len": L, "batch": B, "heads": H, "head_dim": D,
            "flash_ms": round(t_flash * 1e3, 2),
            "flash_tflops": round(flops / t_flash / 1e12, 1),
            "max_abs_err_vs_reference": round(err, 4),
        }
        try:
            t_ref = timed(ref, q, k, v)
            row["jnp_ms"] = round(t_ref * 1e3, 2)
            row["speedup"] = round(t_ref / t_flash, 2)
        except Exception as e:  # noqa: BLE001 — record the OOM, don't die
            msg = str(e)
            row["jnp_ms"] = None
            row["jnp_error"] = ("OOM: dense O(L^2) attention exceeds HBM"
                                if "memory" in msg.lower() else
                                msg.splitlines()[0][:200])
            row["speedup"] = None
        results.append(row)
    out["results"] = results
    path = os.path.join(os.path.dirname(__file__), "..", "FLASH_r03.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
