"""Generate the per-module API reference under docs/api/ from docstrings
(VERDICT r4 next #9: the largest remaining docs gap vs the reference's
mkdocs site, closed without hand-writing 5k lines).

One markdown page per public module of ``analytics_zoo_tpu``: the module
docstring, then every public class (init signature, docstring, public
methods with their first docstring paragraph) and public function
(signature + docstring).  ``docs/api/index.md`` is the table of contents.

Usage: python tools/make_api_docs.py   (rerun after API changes; CI
checks the tree is in sync via tests/test_api_docs.py)
"""

import importlib
import inspect
import os
import pkgutil
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")  # no backend init at import

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
OUT = os.path.join(REPO, "docs", "api")
PKG = "analytics_zoo_tpu"


def _sig(obj) -> str:
    import re

    try:
        s = str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"
    # default-value reprs carry memory addresses (nondeterministic
    # across runs; the sync test would always fail): strip them
    s = re.sub(r"<function ([\w.]+) at 0x[0-9a-f]+>", r"\1", s)
    s = re.sub(r"<([\w.]+) object at 0x[0-9a-f]+>", r"<\1>", s)
    return s


def _first_para(doc: str) -> str:
    if not doc:
        return ""
    return inspect.cleandoc(doc).split("\n\n")[0]


def _doc(doc: str) -> str:
    return inspect.cleandoc(doc) if doc else ""


def _public_members(mod):
    """Classes/functions DEFINED in this module (not re-exports), public
    name, in source order."""
    members = []
    for name, obj in vars(mod).items():
        if name.startswith("_"):
            continue
        if not (inspect.isclass(obj) or inspect.isfunction(obj)):
            continue
        if getattr(obj, "__module__", None) != mod.__name__:
            continue
        try:
            line = inspect.getsourcelines(obj)[1]
        except (OSError, TypeError):
            line = 0
        members.append((line, name, obj))
    return [(n, o) for _, n, o in sorted(members)]


def render_module(mod) -> str | None:
    members = _public_members(mod)
    moddoc = _doc(mod.__doc__)
    if not members and not moddoc:
        return None
    lines = [f"# `{mod.__name__}`", ""]
    if moddoc:
        lines += [moddoc, ""]
    for name, obj in members:
        if inspect.isclass(obj):
            lines += [f"## class `{name}{_sig(obj)}`", ""]
            d = _doc(obj.__doc__)
            if d:
                lines += [d, ""]
            for mname, m in sorted(vars(obj).items()):
                if mname.startswith("_"):
                    continue
                # unwrap descriptors: vars() yields raw classmethod/
                # staticmethod/property objects, not callables
                kind = ""
                if isinstance(m, (classmethod, staticmethod)):
                    kind = ("classmethod " if isinstance(m, classmethod)
                            else "staticmethod ")
                    m = m.__func__
                elif isinstance(m, property):
                    md = _first_para(getattr(m, "__doc__", None))
                    lines.append(f"- **property `{mname}`**"
                                 + (f" — {md}" if md else ""))
                    continue
                if not callable(m):
                    continue
                md = _first_para(getattr(m, "__doc__", None))
                lines.append(f"- **{kind}`{mname}{_sig(m)}`**"
                             + (f" — {md}" if md else ""))
            lines.append("")
        else:
            lines += [f"## `{name}{_sig(obj)}`", ""]
            d = _doc(obj.__doc__)
            if d:
                lines += [d, ""]
    return "\n".join(lines).rstrip() + "\n"


def generate() -> tuple[dict[str, str], list[str]]:
    """(module name -> rendered markdown, skipped module names).  Import
    failures are skipped with a stderr note — optional-dependency
    modules; their committed pages are PRESERVED by main(), not deleted,
    so regenerating in a leaner environment cannot drop docs."""
    pages = {}
    skipped: list[str] = []
    pkg = importlib.import_module(PKG)

    def onerror(name):  # subpackage __init__ import failure: note + go on
        print(f"skip subtree {name}: import failed", file=sys.stderr)
        skipped.append(name)

    for info in pkgutil.walk_packages(pkg.__path__, prefix=PKG + ".",
                                      onerror=onerror):
        name = info.name
        if any(part.startswith("_") for part in name.split(".")):
            continue
        try:
            mod = importlib.import_module(name)
        except Exception as e:  # optional deps (torch/tf interop, ...)
            print(f"skip {name}: {type(e).__name__}: {e}",
                  file=sys.stderr)
            skipped.append(name)
            continue
        page = render_module(mod)
        if page:
            pages[name] = page
    return pages, skipped


def main():
    pages, skipped = generate()
    os.makedirs(OUT, exist_ok=True)
    keep = {s.replace(".", "_") + ".md" for s in skipped}
    keep |= {s.replace(".", "_") + "_" for s in skipped}  # subtree prefix
    # clear stale pages so renames don't leave orphans — but never the
    # pages of modules this environment couldn't import
    for f in os.listdir(OUT):
        if f.endswith(".md") and f not in keep \
                and not any(f.startswith(p) for p in keep):
            os.remove(os.path.join(OUT, f))
    # preserved pages (modules this env couldn't import) stay in the TOC
    listed = dict.fromkeys(sorted(pages))
    for s in skipped:
        if os.path.exists(os.path.join(OUT, s.replace(".", "_") + ".md")):
            listed[s] = None
    index = ["# API reference", "",
             f"Generated from docstrings by `tools/make_api_docs.py` "
             f"({len(listed)} modules).  Regenerate after API changes.",
             ""]
    by_pkg: dict[str, list[str]] = {}
    for name in sorted(listed):
        sub = name.split(".")[1] if "." in name else ""
        by_pkg.setdefault(sub, []).append(name)
    for sub in sorted(by_pkg):
        index.append(f"## {sub or PKG}")
        index.append("")
        for name in by_pkg[sub]:
            fname = name.replace(".", "_") + ".md"
            if name in pages:
                with open(os.path.join(OUT, fname), "w") as f:
                    f.write(pages[name])
            index.append(f"- [`{name}`]({fname})")
        index.append("")
    with open(os.path.join(OUT, "index.md"), "w") as f:
        f.write("\n".join(index).rstrip() + "\n")
    print(f"wrote {len(pages)} pages to docs/api/")


if __name__ == "__main__":
    main()
