#!/usr/bin/env bash
# Fast pre-commit loop: lint the files you touched, then run the
# sanitizer fixture tests so a planted-deadlock-shaped change is caught
# before CI.  Wire up with:
#   ln -s ../../tools/precommit.sh .git/hooks/pre-commit
#
# Full-tree equivalents (the CI gates):
#   python tools/zoolint.py --whole-program analytics_zoo_tpu/
#   ZOO_SAN=1 python -m pytest tests/ -q -m quick
set -euo pipefail
cd "$(git rev-parse --show-toplevel)"

echo "== zoolint --changed =="
python tools/zoolint.py --changed

echo "== zoosan quick fixtures (ZOO_SAN=1) =="
ZOO_SAN=1 python -m pytest tests/test_zoosan.py -q -p no:cacheprovider

echo "precommit: OK"
