"""Parallelism tests on the 8-device CPU mesh — ring attention vs dense,
explicit shard_map training step vs the jit+sharding path, TP dense blocks
(the reference has no TP/SP to compare against; dense math is the oracle)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


@pytest.fixture()
def seq_ctx():
    from analytics_zoo_tpu import init_zoo_context

    return init_zoo_context(
        mesh_shape={"data": 2, "seq": 4},
        mesh_axes=("data", "model", "seq"), seed=0,
    )


class TestRingAttention:
    def test_matches_dense(self, seq_ctx):
        from analytics_zoo_tpu.ops.attention import dot_product_attention
        from analytics_zoo_tpu.parallel import ring_attention

        rng = np.random.default_rng(0)
        q, k, v = (
            jnp.asarray(rng.normal(size=(2, 3, 32, 8)).astype(np.float32))
            for _ in range(3)
        )
        for causal in (False, True):
            out = ring_attention(q, k, v, causal=causal)
            ref = dot_product_attention(q, k, v, causal=causal,
                                        use_flash=False)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=1e-5)

    def test_gradients_flow_through_ring(self, seq_ctx):
        from analytics_zoo_tpu.ops.attention import dot_product_attention
        from analytics_zoo_tpu.parallel import ring_attention

        rng = np.random.default_rng(1)
        q, k, v = (
            jnp.asarray(rng.normal(size=(1, 2, 16, 4)).astype(np.float32))
            for _ in range(3)
        )
        g = jax.grad(lambda q: jnp.sum(
            ring_attention(q, k, v, causal=True) ** 2))(q)
        gr = jax.grad(lambda q: jnp.sum(dot_product_attention(
            q, k, v, causal=True, use_flash=False) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=1e-4)

    def test_sharded_inputs_under_jit(self, seq_ctx):
        """Ring attention with L actually sharded over the seq axis."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from analytics_zoo_tpu.ops.attention import dot_product_attention
        from analytics_zoo_tpu.parallel import ring_attention

        mesh = seq_ctx.mesh
        rng = np.random.default_rng(2)
        q, k, v = (
            jax.device_put(
                rng.normal(size=(2, 2, 64, 8)).astype(np.float32),
                NamedSharding(mesh, P(None, None, "seq", None)),
            )
            for _ in range(3)
        )
        out = jax.jit(lambda q, k, v: ring_attention(q, k, v, causal=True))(
            q, k, v)
        ref = dot_product_attention(q, k, v, causal=True, use_flash=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)


class TestShardMapStep:
    def test_explicit_psum_step_trains(self, zoo_ctx):
        from analytics_zoo_tpu.feature.dataset import FeatureSet
        from analytics_zoo_tpu.parallel import make_shard_map_train_step
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
        from analytics_zoo_tpu.pipeline.api.keras.objectives import get_loss
        from analytics_zoo_tpu.pipeline.api.keras.optimizers import (
            get_optimizer,
        )

        rng = np.random.default_rng(3)
        x = rng.normal(size=(256, 8)).astype(np.float32)
        w = rng.normal(size=(8, 1)).astype(np.float32)
        y = (x @ w).astype(np.float32)

        from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

        model = Sequential()
        model.add(Dense(1, input_shape=(8,)))
        params, state = model.build_params(jax.random.PRNGKey(0))
        opt = Adam(lr=0.05)
        loss = get_loss("mse")
        step = make_shard_map_train_step(model, loss, opt)
        opt_state = opt.init(params)
        ctx = zoo_ctx
        losses = []
        fs = FeatureSet.of(x, y)
        for epoch in range(40):
            for batch in fs.batches(64, seed=0, epoch=epoch):
                sharded = ctx.shard_batch(batch)
                params, opt_state, state, l = step(
                    params, opt_state, state, jax.random.PRNGKey(0), sharded
                )
            losses.append(float(l))
        assert losses[-1] < 0.05 * losses[0], losses[::10]

    def test_matches_jit_sharding_path(self, zoo_ctx):
        """Explicit psum and implicit jit-sharding must produce identical
        updates (same math, different formulation)."""
        from analytics_zoo_tpu.feature.dataset import FeatureSet
        from analytics_zoo_tpu.parallel import make_shard_map_train_step
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
        from analytics_zoo_tpu.pipeline.api.keras.objectives import get_loss
        from analytics_zoo_tpu.pipeline.api.keras.optimizers import (
            get_optimizer,
        )

        rng = np.random.default_rng(4)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        y = rng.normal(size=(64, 1)).astype(np.float32)

        model = Sequential()
        model.add(Dense(1, input_shape=(4,)))
        params, state = model.build_params(jax.random.PRNGKey(5))
        params0 = jax.tree_util.tree_map(jnp.copy, params)

        # path A: explicit shard_map psum
        opt = get_optimizer("sgd")
        step = make_shard_map_train_step(model, get_loss("mse"), opt)
        opt_state = opt.init(params)
        batch = next(FeatureSet.of(x, y).batches(64, shuffle=False))
        pa, _, _, la = step(params, opt_state, state,
                            jax.random.PRNGKey(0),
                            zoo_ctx.shard_batch(batch))

        # path B: estimator's jit + NamedSharding step
        model.params = params0
        model.state = dict(state)
        model.compile(optimizer="sgd", loss="mse")
        model.fit(x, y, batch_size=64, nb_epoch=1)
        pb = model.params
        for ka in pa:
            np.testing.assert_allclose(
                np.asarray(pa[ka]["kernel"]),
                np.asarray(pb[ka]["kernel"]), rtol=1e-5)
        np.testing.assert_allclose(float(la),
                                   model._estimator.history[0]["loss"],
                                   rtol=1e-4)


class TestTensorParallel:
    def test_tp_mlp_matches_dense(self, zoo_ctx):
        from jax.sharding import PartitionSpec as P

        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.parallel import (
            column_parallel_dense,
            row_parallel_dense,
        )
        from analytics_zoo_tpu.parallel.strategies import tp_mlp

        ctx = init_zoo_context(mesh_shape={"data": 2, "model": 4}, seed=0)
        mesh = ctx.mesh
        rng = np.random.default_rng(5)
        x = rng.normal(size=(8, 16)).astype(np.float32)
        w1 = rng.normal(size=(16, 32)).astype(np.float32)
        b1 = rng.normal(size=(32,)).astype(np.float32)
        w2 = rng.normal(size=(32, 16)).astype(np.float32)
        b2 = rng.normal(size=(16,)).astype(np.float32)

        ref = (jax.nn.gelu(x @ w1 + b1)) @ w2 + b2

        fn = jax.shard_map(
            lambda x, w1, b1, w2, b2: tp_mlp(x, w1, b1, w2, b2),
            mesh=mesh,
            in_specs=(P(), P(None, "model"), P("model"),
                      P("model", None), P()),
            out_specs=P(),
            check_vma=False,
        )
        out = fn(x, w1, b1, w2, b2)
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4)


class TestExpertParallelMoE:
    def test_matches_dense_oracle(self):
        import jax
        import numpy as np
        from jax.sharding import PartitionSpec as P

        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.parallel.strategies import ep_moe_mlp

        ctx = init_zoo_context(
            mesh_shape={"data": 1, "expert": 4},
            mesh_axes=("data", "expert"), seed=0)
        mesh = ctx.mesh
        rng = np.random.default_rng(9)
        T, D, F, E = 6, 8, 16, 4
        x = rng.normal(size=(T, D)).astype(np.float32)
        gate = rng.normal(size=(D, E)).astype(np.float32)
        ew1 = rng.normal(size=(E, D, F)).astype(np.float32)
        eb1 = rng.normal(size=(E, F)).astype(np.float32)
        ew2 = rng.normal(size=(E, F, D)).astype(np.float32)
        eb2 = rng.normal(size=(D,)).astype(np.float32)

        # dense single-device oracle
        logits = x @ gate
        g = np.exp(logits - logits.max(-1, keepdims=True))
        g = g / g.sum(-1, keepdims=True)
        h = np.stack([
            np.asarray(jax.nn.gelu(x @ ew1[e] + eb1[e])) @ ew2[e]
            for e in range(E)
        ], axis=1)  # (T, E, D)
        ref = (h * g[..., None]).sum(1) + eb2

        fn = jax.shard_map(
            lambda x, gw, w1, b1, w2, b2: ep_moe_mlp(x, gw, w1, b1, w2, b2),
            mesh=mesh,
            in_specs=(P(), P(None, "expert"), P("expert"), P("expert"),
                      P("expert"), P()),
            out_specs=P(),
            check_vma=False,
        )
        out = fn(x, gate, ew1, eb1, ew2, eb2)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-4)

    def test_differentiable(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import PartitionSpec as P

        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.parallel.strategies import ep_moe_mlp

        ctx = init_zoo_context(
            mesh_shape={"data": 1, "expert": 2},
            mesh_axes=("data", "expert"), seed=0)
        rng = np.random.default_rng(2)
        T, D, F, E = 4, 6, 8, 2
        x = rng.normal(size=(T, D)).astype(np.float32)
        args = dict(
            gw=rng.normal(size=(D, E)).astype(np.float32),
            w1=rng.normal(size=(E, D, F)).astype(np.float32),
            b1=np.zeros((E, F), np.float32),
            w2=rng.normal(size=(E, F, D)).astype(np.float32),
            b2=np.zeros((D,), np.float32),
        )

        def loss(p, x):
            y = ep_moe_mlp(x, p["gw"], p["w1"], p["b1"], p["w2"], p["b2"])
            return jax.lax.pmean(jnp.mean(y ** 2), "expert")

        pspec = dict(gw=P(None, "expert"), w1=P("expert"), b1=P("expert"),
                     w2=P("expert"), b2=P())
        fn = jax.jit(jax.shard_map(
            jax.grad(loss), mesh=ctx.mesh,
            in_specs=(pspec, P()), out_specs=pspec, check_vma=False))
        grads = fn(args, x)
        for k, g in grads.items():
            assert np.isfinite(np.asarray(g)).all(), k
        assert float(np.abs(np.asarray(grads["w1"])).sum()) > 0


class TestRoutedMoETopK:
    """moe_mlp_topk: GShard/Switch top-k routing + capacity + all_to_all
    (VERDICT r03 item 6).  ep_moe_mlp (dense dispatch) is the oracle."""

    def _params(self, T=8, D=8, F=16, E=4, seed=9):
        import numpy as np

        rng = np.random.default_rng(seed)
        return dict(
            x=rng.normal(size=(T, D)).astype(np.float32),
            gate=rng.normal(size=(D, E)).astype(np.float32),
            ew1=rng.normal(size=(E, D, F)).astype(np.float32),
            eb1=rng.normal(size=(E, F)).astype(np.float32),
            ew2=rng.normal(size=(E, F, D)).astype(np.float32),
            eb2=rng.normal(size=(D,)).astype(np.float32),
        )

    def _dense_oracle(self, p):
        import jax
        import numpy as np

        x, gate = p["x"], p["gate"]
        E = gate.shape[1]
        logits = x @ gate
        g = np.exp(logits - logits.max(-1, keepdims=True))
        g = g / g.sum(-1, keepdims=True)
        h = np.stack([
            np.asarray(jax.nn.gelu(x @ p["ew1"][e] + p["eb1"][e]))
            @ p["ew2"][e] for e in range(E)
        ], axis=1)  # (T, E, D)
        return (h * g[..., None]).sum(1) + p["eb2"], g, h

    def _run(self, p, top_k, capacity_factor, n_shards=4, tokens_sharded=True):
        import jax
        from jax.sharding import PartitionSpec as P

        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.parallel.strategies import moe_mlp_topk

        ctx = init_zoo_context(
            mesh_shape={"data": 1, "expert": n_shards},
            mesh_axes=("data", "expert"), seed=0)
        fn = jax.shard_map(
            lambda x, gw, w1, b1, w2, b2: moe_mlp_topk(
                x, gw, w1, b1, w2, b2, top_k=top_k,
                capacity_factor=capacity_factor),
            mesh=ctx.mesh,
            in_specs=(P("expert") if tokens_sharded else P(), P(),
                      P("expert"), P("expert"), P("expert"), P()),
            out_specs=P("expert") if tokens_sharded else P(),
            check_vma=False,
        )
        return fn(p["x"], p["gate"], p["ew1"], p["eb1"], p["ew2"], p["eb2"])

    def test_topk_equals_dense_dispatch_oracle(self):
        """top_k=E + enough capacity == the dense-dispatch oracle exactly
        (every token reaches every expert with full softmax gates)."""
        import numpy as np

        p = self._params(T=8, E=4)
        ref, _, _ = self._dense_oracle(p)
        out = self._run(p, top_k=4, capacity_factor=1.0)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-4)

    def test_top1_routes_to_argmax_expert(self):
        import numpy as np

        p = self._params(T=8, E=4, seed=3)
        _, g, h = self._dense_oracle(p)
        top1 = g.argmax(-1)
        ref = h[np.arange(8), top1] * g[np.arange(8), top1][:, None] \
            + p["eb2"]
        out = self._run(p, top_k=1, capacity_factor=8.0)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-4)

    def test_capacity_drops_lowest_priority(self):
        """With per-shard capacity C=1 and top_k=1, only the first token
        (in priority order) routed to each expert on each shard survives;
        dropped tokens output exactly b2."""
        import numpy as np

        p = self._params(T=8, E=4, seed=5)
        _, g, h = self._dense_oracle(p)
        top1 = g.argmax(-1)
        out = np.asarray(self._run(p, top_k=1, capacity_factor=1e-9))
        # per shard of 2 tokens (T=8 over 4 shards): cap = 1 slot/expert
        kept = np.zeros(8, bool)
        for sh in range(4):
            seen = set()
            for t in range(sh * 2, sh * 2 + 2):
                if top1[t] not in seen:
                    seen.add(top1[t])
                    kept[t] = True
        assert kept.any() and (~kept).any(), "test needs both cases"
        for t in range(8):
            if kept[t]:
                ref = h[t, top1[t]] * g[t, top1[t]] + p["eb2"]
            else:
                ref = p["eb2"]
            np.testing.assert_allclose(out[t], ref, rtol=1e-4, atol=1e-4,
                                       err_msg=f"token {t} kept={kept[t]}")

    def test_differentiable_and_aux_loss(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import PartitionSpec as P

        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.parallel.strategies import moe_mlp_topk

        ctx = init_zoo_context(
            mesh_shape={"data": 1, "expert": 2},
            mesh_axes=("data", "expert"), seed=0)
        p = self._params(T=8, E=2, D=6, F=8, seed=7)
        params = {k: p[k] for k in ("gate", "ew1", "eb1", "ew2", "eb2")}

        def loss(params, x):
            y, aux = moe_mlp_topk(
                x, params["gate"], params["ew1"], params["eb1"],
                params["ew2"], params["eb2"], top_k=1, return_aux=True)
            return (jax.lax.pmean(jnp.mean(y ** 2), "expert")
                    + 0.01 * aux)

        pspec = dict(gate=P(), ew1=P("expert"), eb1=P("expert"),
                     ew2=P("expert"), eb2=P())
        fn = jax.jit(jax.shard_map(
            jax.value_and_grad(loss), mesh=ctx.mesh,
            in_specs=(pspec, P("expert")),
            out_specs=(P(), pspec), check_vma=False))
        val, grads = fn(params, p["x"])
        assert np.isfinite(float(val))
        for k, g in grads.items():
            assert np.isfinite(np.asarray(g)).all(), k
        # routing grads reach the gate (via gate values + aux loss)
        assert float(np.abs(np.asarray(grads["gate"])).sum()) > 0
        assert float(np.abs(np.asarray(grads["ew1"])).sum()) > 0

    def test_aux_loss_balanced_is_one(self):
        """Uniform router -> aux == 1.0 (perfect balance)."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import PartitionSpec as P

        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.parallel.strategies import moe_mlp_topk

        ctx = init_zoo_context(
            mesh_shape={"data": 1, "expert": 2},
            mesh_axes=("data", "expert"), seed=0)
        p = self._params(T=8, E=2, D=6, F=8, seed=1)
        p["gate"] = np.zeros((6, 2), np.float32)  # uniform router

        def run(x):
            _, aux = moe_mlp_topk(
                x, jnp.asarray(p["gate"]), p["ew1"], p["eb1"], p["ew2"],
                p["eb2"], top_k=1, return_aux=True)
            return aux

        fn = jax.shard_map(run, mesh=ctx.mesh, in_specs=(P("expert"),),
                           out_specs=P(), check_vma=False)
        # ties all route to expert 0 -> ce=(1,0), me=(.5,.5): aux = 1.0
        assert abs(float(fn(p["x"])) - 1.0) < 1e-5


class TestRingAttentionPallasInner:
    """Ring attention with the Pallas flash inner kernel (interpret mode
    forced on CPU): VERDICT r03 weak #8 — the seq-parallel path streams
    K/V through VMEM and skips fully-masked causal hops."""

    def _data(self, L=512, d=64, seed=0):
        import numpy as np

        rng = np.random.default_rng(seed)
        return tuple(
            jnp.asarray(rng.normal(size=(1, 2, L, d)).astype(np.float32)
                        * 0.5)
            for _ in range(3))

    def test_pallas_inner_matches_dense(self, seq_ctx, monkeypatch):
        import analytics_zoo_tpu.ops.pallas.flash_attention as fa
        from analytics_zoo_tpu.ops.attention import dot_product_attention
        from analytics_zoo_tpu.parallel import ring_attention

        monkeypatch.setenv("ZOO_FLASH_INTERPRET", "1")
        q, k, v = self._data()
        for causal in (False, True):
            before = fa.invocation_counts["pallas"]
            out = ring_attention(q, k, v, causal=causal)
            assert fa.invocation_counts["pallas"] > before, (
                "ring inner did not use the Pallas kernel")
            ref = dot_product_attention(q, k, v, causal=causal,
                                        use_flash=False)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-4, err_msg=str(causal))

    def test_grads_with_pallas_forward(self, seq_ctx, monkeypatch):
        """custom-VJP backward (reverse ring, jnp remat) against dense
        autodiff while the forward runs the Pallas inner kernel."""
        from analytics_zoo_tpu.ops.attention import dot_product_attention
        from analytics_zoo_tpu.parallel import ring_attention

        monkeypatch.setenv("ZOO_FLASH_INTERPRET", "1")
        q, k, v = self._data(seed=1)

        g = jax.grad(lambda q, k, v: jnp.sum(
            ring_attention(q, k, v, causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(dot_product_attention(
            q, k, v, causal=True, use_flash=False) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-3, err_msg=name)

    def test_kv_grads_ride_the_ring_home(self, seq_ctx):
        """dK/dV from remote hops must land on the owning shard: compare
        vs dense autodiff with the jnp inner (no interpret env)."""
        from analytics_zoo_tpu.ops.attention import dot_product_attention
        from analytics_zoo_tpu.parallel import ring_attention

        q, k, v = self._data(L=32, d=8, seed=2)
        g = jax.grad(lambda k: jnp.sum(
            ring_attention(q, k, v, causal=False) ** 2))(k)
        gr = jax.grad(lambda k: jnp.sum(dot_product_attention(
            q, k, v, causal=False, use_flash=False) ** 2))(k)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                                   atol=1e-4)


def test_ring_backward_chunk_padding(seq_ctx):
    """lc not a multiple of the 256 backward chunk (here lc=320): the
    padded last chunk must not corrupt dK/dV (zero-padding masked)."""
    from analytics_zoo_tpu.ops.attention import dot_product_attention
    from analytics_zoo_tpu.parallel import ring_attention

    rng = np.random.default_rng(5)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 1, 1280, 8))
                           .astype(np.float32) * 0.5) for _ in range(3))
    g = jax.grad(lambda q, k, v: jnp.sum(
        ring_attention(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(
            q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(dot_product_attention(
        q, k, v, causal=True, use_flash=False) ** 2), argnums=(0, 1, 2))(
            q, k, v)
    for a, b, name in zip(g, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, err_msg=name)


class TestZigzagRingAttention:
    """Causal-load-balanced variant (VERDICT r03 weak #8): same contract
    as ring_attention (contiguous sharding in/out), balanced work."""

    def test_matches_dense(self, seq_ctx):
        from analytics_zoo_tpu.ops.attention import dot_product_attention
        from analytics_zoo_tpu.parallel.ring_attention import (
            zigzag_ring_attention,
        )

        rng = np.random.default_rng(0)
        q, k, v = (
            jnp.asarray(rng.normal(size=(2, 3, 32, 8)).astype(np.float32))
            for _ in range(3)
        )
        for causal in (False, True):
            out = zigzag_ring_attention(q, k, v, causal=causal)
            ref = dot_product_attention(q, k, v, causal=causal,
                                        use_flash=False)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=1e-5, err_msg=str(causal))

    def test_gradients_match_dense(self, seq_ctx):
        from analytics_zoo_tpu.ops.attention import dot_product_attention
        from analytics_zoo_tpu.parallel.ring_attention import (
            zigzag_ring_attention,
        )

        rng = np.random.default_rng(3)
        q, k, v = (
            jnp.asarray(rng.normal(size=(1, 2, 32, 8))
                        .astype(np.float32) * 0.5)
            for _ in range(3)
        )
        g = jax.grad(lambda q, k, v: jnp.sum(
            zigzag_ring_attention(q, k, v, causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(dot_product_attention(
            q, k, v, causal=True, use_flash=False) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-3, err_msg=name)

    def test_layout_roundtrip(self, seq_ctx):
        """to-zigzag -> from-zigzag is the identity on any sharded block."""
        import jax.sharding as shd

        from analytics_zoo_tpu.common.engine import get_zoo_context
        from analytics_zoo_tpu.parallel.ring_attention import (
            _zz_from,
            _zz_to,
        )

        ctx = get_zoo_context()
        mesh = ctx.mesh
        n = mesh.shape["seq"]
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(1, 1, 8 * n, 4))
                        .astype(np.float32))
        spec = shd.PartitionSpec(None, None, "seq", None)

        def body(xl):
            return _zz_from(_zz_to(xl, "seq", n), "seq", n)

        out = jax.shard_map(body, mesh=mesh, in_specs=(spec,),
                            out_specs=spec, check_vma=False)(x)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))

    def test_odd_local_length_rejected(self, seq_ctx):
        from analytics_zoo_tpu.parallel.ring_attention import (
            zigzag_ring_attention,
        )

        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(1, 1, 36, 8)).astype(np.float32))
        with pytest.raises(ValueError, match="even local sequence"):
            zigzag_ring_attention(q, q, q, causal=True)


def test_ring_pallas_backward_fires_and_matches(seq_ctx, monkeypatch):
    """The reverse-ring backward must route through the Pallas bwd
    kernels (not the jnp chunk scan) when the inner kernel is available,
    and still match dense autodiff — contiguous causal ring."""
    import analytics_zoo_tpu.ops.pallas.flash_attention as fa
    from analytics_zoo_tpu.ops.attention import dot_product_attention
    from analytics_zoo_tpu.parallel import ring_attention

    monkeypatch.setenv("ZOO_FLASH_INTERPRET", "1")
    rng = np.random.default_rng(11)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 2, 512, 64))
                           .astype(np.float32) * 0.5) for _ in range(3))
    before = fa.invocation_counts["pallas"]
    g = jax.grad(lambda q, k, v: jnp.sum(
        ring_attention(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(
            q, k, v)
    # fwd hops + bwd hop kernels all counted at trace time; the bwd
    # contributes at least one pallas invocation beyond the forward's 2
    assert fa.invocation_counts["pallas"] >= before + 3, (
        "ring backward did not route through the Pallas kernels")
    gr = jax.grad(lambda q, k, v: jnp.sum(dot_product_attention(
        q, k, v, causal=True, use_flash=False) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, err_msg=name)


def test_zigzag_pallas_backward_matches(seq_ctx, monkeypatch):
    """Zigzag reverse ring through the Pallas quadrant backward (piece
    length >= 128 so the gate opens) vs dense autodiff."""
    from analytics_zoo_tpu.ops.attention import dot_product_attention
    from analytics_zoo_tpu.parallel import zigzag_ring_attention

    monkeypatch.setenv("ZOO_FLASH_INTERPRET", "1")
    rng = np.random.default_rng(12)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 1, 1024, 64))
                           .astype(np.float32) * 0.5) for _ in range(3))
    g = jax.grad(lambda q, k, v: jnp.sum(
        zigzag_ring_attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(dot_product_attention(
        q, k, v, causal=True, use_flash=False) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, err_msg=name)


def test_zero1_step_matches_plain_dp(zoo_ctx):
    """ZeRO-1 sharded-optimizer step (reduce-scatter grads, 1/n-shard
    Adam state, all-gather params) must produce the SAME parameters as
    the plain replicated-optimizer step — identical math, sharded
    layout.  Also asserts the memory win: each optimizer-state leaf is
    1/n of the flat parameter size."""
    from analytics_zoo_tpu.parallel import (
        make_shard_map_train_step,
        make_zero1_train_step,
    )
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.api.keras.objectives import get_loss
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    rng_np = np.random.default_rng(9)
    x = rng_np.normal(size=(64, 10)).astype(np.float32)
    w = rng_np.normal(size=(10, 3)).astype(np.float32)
    y = (x @ w).astype(np.float32)

    model = Sequential()
    model.add(Dense(7, activation="tanh", input_shape=(10,)))
    model.add(Dense(3))
    params, state = model.build_params(jax.random.PRNGKey(1))
    loss = get_loss("mse")

    plain = make_shard_map_train_step(model, loss, Adam(lr=0.03))
    z_step, z_init = make_zero1_train_step(model, loss, Adam(lr=0.03))

    opt_plain = Adam(lr=0.03).init(params)
    opt_z = z_init(params)

    n = zoo_ctx.data_parallel_size
    flat_size = sum(int(np.prod(v.shape)) for v in
                    jax.tree_util.tree_leaves(params))
    padded = flat_size + ((-flat_size) % n)
    for leaf in jax.tree_util.tree_leaves(opt_z):
        if hasattr(leaf, "shape") and leaf.ndim == 1 and leaf.size > 1:
            assert leaf.shape[0] == padded, (leaf.shape, padded)

    p1, p2 = params, jax.tree_util.tree_map(jnp.copy, params)
    s1 = s2 = state
    key = jax.random.PRNGKey(0)
    batch = zoo_ctx.shard_batch({"x": x, "y": y})
    for _ in range(4):
        p1, opt_plain, s1, l1 = plain(p1, opt_plain, s1, key, batch)
        p2, opt_z, s2, l2 = z_step(p2, opt_z, s2, key, batch)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("clip", [("l2norm", 0.05), ("const", -0.001, 0.001)])
def test_zero1_grad_clip_contract_matches_plain(zoo_ctx, clip):
    """Both train-step factories accept the SAME grad_clip spec
    (('l2norm', max) | ('const', lo, hi) — the Estimator's _clip_grads
    format) and produce identical parameters; a tight clip makes the
    assertion sensitive to the clip actually being applied."""
    from analytics_zoo_tpu.parallel import (
        make_shard_map_train_step,
        make_zero1_train_step,
    )
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.api.keras.objectives import get_loss
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    rng_np = np.random.default_rng(3)
    x = rng_np.normal(size=(32, 6)).astype(np.float32)
    y = (x[:, :2] * 5.0).astype(np.float32)

    model = Sequential()
    model.add(Dense(2, input_shape=(6,)))
    params, state = model.build_params(jax.random.PRNGKey(1))
    loss = get_loss("mse")

    plain = make_shard_map_train_step(model, loss, Adam(lr=0.05),
                                      grad_clip=clip)
    z_step, z_init = make_zero1_train_step(model, loss, Adam(lr=0.05),
                                           grad_clip=clip)
    opt_plain = Adam(lr=0.05).init(params)
    opt_z = z_init(params)
    p1, p2 = params, jax.tree_util.tree_map(jnp.copy, params)
    s1 = s2 = state
    key = jax.random.PRNGKey(0)
    batch = zoo_ctx.shard_batch({"x": x, "y": y})
    for _ in range(3):
        p1, opt_plain, s1, l1 = plain(p1, opt_plain, s1, key, batch)
        p2, opt_z, s2, l2 = z_step(p2, opt_z, s2, key, batch)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    with pytest.raises(ValueError, match="grad clip"):
        make_zero1_train_step(model, loss, Adam(lr=0.05),
                              grad_clip=("bogus", 1.0))


def test_estimator_zero1_shards_opt_state_and_matches():
    """ZOO_SHARD_OPTIMIZER through the real Estimator path (GSPMD
    sharding constraints): optimizer moments end up sharded over the
    data axis, and training matches the replicated-optimizer run
    bit-for-equal math."""
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    rng_np = np.random.default_rng(21)
    x = rng_np.normal(size=(128, 16)).astype(np.float32)
    w = rng_np.normal(size=(16, 1)).astype(np.float32)
    y = (x @ w).astype(np.float32)

    def run(shard):
        init_zoo_context({"shard_optimizer": shard}, seed=3)
        m = Sequential()
        m.add(Dense(8, activation="tanh", input_shape=(16,)))
        m.add(Dense(1))
        m.compile(optimizer="adam", loss="mse")
        m.fit(x, y, batch_size=32, nb_epoch=3)
        est = m._estimator
        return m.params, est._opt_state

    p_ref, _ = run(False)
    p_sh, opt_sh = run(True)

    # moments sharded over data where dim0 divides; scalars replicated
    from analytics_zoo_tpu.common.engine import get_zoo_context

    dp = get_zoo_context().data_parallel_size
    assert dp > 1
    sharded_leaves = [
        leaf for leaf in jax.tree_util.tree_leaves(opt_sh)
        if hasattr(leaf, "sharding") and leaf.ndim >= 1
        and leaf.shape[0] % dp == 0 and leaf.shape[0] > 0
    ]
    assert sharded_leaves, "no shardable optimizer leaves found"
    assert any(
        any(s is not None for s in (leaf.sharding.spec or ()))
        for leaf in sharded_leaves
    ), "optimizer state is fully replicated despite ZOO_SHARD_OPTIMIZER"

    for a, b in zip(jax.tree_util.tree_leaves(p_ref),
                    jax.tree_util.tree_leaves(p_sh)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
