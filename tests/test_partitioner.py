"""Unified partitioner (parallel/plan.py): ShardingPlan rule tables,
canned plans, the hybrid mesh builder, and compile_step — the ONE
compile choke point every strategy lowers through.

Acceptance (ISSUE 10): every strategy (plain DP, shard_map, zero1,
fsdp, TP) compiles through compile_step → timed_compile — a
second-process warm start over a shared ZOO_COMPILE_CACHE shows cache
hits and zoo_hlo_* features for ALL plans — and the fsdp plan's
per-chip param+opt bytes are <= 0.6x replicated DP at a bit-identical
loss trajectory on the 8-device CPU mesh.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _data(n=256, feat=8, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, feat)).astype(np.float32)
    y = np.argmax(x @ rng.normal(size=(feat, classes)),
                  axis=1).astype(np.int32)
    return x, y


def _model():
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    m = Sequential()
    m.add(Dense(64, activation="relu", input_shape=(8,)))
    m.add(Dense(4, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    return m


# ---------------------------------------------------------------------------
# ShardingPlan unit behavior
# ---------------------------------------------------------------------------


class TestShardingPlan:
    def test_canned_plans_and_rule_resolution(self):
        from analytics_zoo_tpu.parallel import plan as zp

        dp, fs, z1 = zp.data_parallel(), zp.fsdp(), zp.zero1()
        assert not dp.shards_params and not dp.shards_opt
        assert fs.shards_params and fs.shards_opt
        assert not z1.shards_params and z1.shards_opt
        tp = zp.tensor_parallel([(r"kernel", P(None, "model"))])
        assert tp.shards_params
        # catch-all appended so unmatched leaves replicate, not raise
        assert tp.param_rules[-1][0] == r".*"

    def test_specs_clamped_to_mesh_divisibility(self):
        from analytics_zoo_tpu.parallel import plan as zp

        mesh = zp.build_mesh({"data": 8})
        params = {"k": np.zeros((16, 4)), "ragged": np.zeros((3, 4)),
                  "scalar": np.zeros(())}
        specs = zp.fsdp().param_specs(params, mesh)
        assert specs["k"] == P("data")
        assert specs["ragged"] == P()   # 3 % 8 != 0 -> replicate
        assert specs["scalar"] == P()
        # axis absent from the mesh drops to None instead of erroring
        tp = zp.tensor_parallel([(r"k", P(None, "model"))])
        specs = tp.param_specs(params, mesh)  # mesh has no model axis
        assert specs["k"] == P()

    def test_resolve_plan_precedence(self, monkeypatch):
        from analytics_zoo_tpu.common.engine import ZooConfig
        from analytics_zoo_tpu.parallel import plan as zp

        monkeypatch.delenv("ZOO_SHARDING_PLAN", raising=False)
        monkeypatch.delenv("ZOO_SHARD_OPTIMIZER", raising=False)
        assert zp.resolve_plan(None, ZooConfig()).name == "dp"
        # env tier
        monkeypatch.setenv("ZOO_SHARDING_PLAN", "fsdp")
        assert zp.resolve_plan(None, ZooConfig()).name == "fsdp"
        # explicit beats env
        assert zp.resolve_plan("zero1", ZooConfig()).name == "zero1"
        # legacy ZOO_SHARD_OPTIMIZER maps to zero1
        monkeypatch.delenv("ZOO_SHARDING_PLAN")
        monkeypatch.setenv("ZOO_SHARD_OPTIMIZER", "1")
        assert zp.resolve_plan(None, ZooConfig()).name == "zero1"
        # a plan object passes through untouched
        tp = zp.tensor_parallel([("kernel", P(None, "model"))])
        assert zp.resolve_plan(tp, ZooConfig()) is tp

    def test_bad_plan_name_fails_eagerly(self, monkeypatch):
        from analytics_zoo_tpu.common.engine import ZooConfig
        from analytics_zoo_tpu.parallel import plan as zp

        with pytest.raises(ValueError, match="fsdp"):
            zp.resolve_plan("fsdqqp")
        # the env knob fails at config init naming itself
        monkeypatch.setenv("ZOO_SHARDING_PLAN", "nope")
        with pytest.raises(ValueError, match="ZOO_SHARDING_PLAN"):
            ZooConfig()

    def test_bare_string_spec_rejected(self):
        """P(*"model") would splat into per-character axes that all
        clamp to replicate — a silent no-op plan; rejected loudly."""
        from analytics_zoo_tpu.parallel import plan as zp

        with pytest.raises(TypeError, match="bare string"):
            zp.tensor_parallel([(r"kernel", "model")])

    def test_batch_specs(self):
        from analytics_zoo_tpu.parallel import plan as zp

        p = zp.fsdp()
        assert p.batch_spec(2) == P("data", None)
        assert p.batch_spec(0) == P()
        assert p.batch_spec(3, stacked=True) == P(None, "data", None)
        assert p.batch_spec(1, stacked=True) == P()
        hy = zp.ShardingPlan(name="hybrid", batch_axes=("dcn", "data"))
        assert hy.batch_spec(2) == P(("dcn", "data"), None)

    def test_spec_serialization_roundtrip(self):
        from analytics_zoo_tpu.parallel import plan as zp

        specs = {"a": P("data"), "b": {"c": P(None, ("dcn", "data")),
                                       "d": P()}}
        ser = zp.serialize_specs(specs)
        assert all(isinstance(e, list) for e in ser)  # safe_load clean
        flat = zp.deserialize_specs(json.loads(json.dumps(ser)))
        assert flat == [P("data"), P(None, ("dcn", "data")), P()]


class TestBuildMesh:
    def test_single_slice_falls_back_to_plain_mesh(self):
        from analytics_zoo_tpu.parallel import plan as zp

        mesh = zp.build_mesh({"data": 4, "model": 2})
        assert dict(mesh.shape) == {"data": 4, "model": 2}

    def test_hybrid_dcn_outer_axis(self, monkeypatch):
        from analytics_zoo_tpu.parallel import plan as zp

        devs = jax.devices()
        mesh = zp.build_mesh({"data": 2, "model": 2}, dcn_shape=2,
                             dcn_axis="dcn",
                             slice_groups=[devs[:4], devs[4:]])
        assert mesh.axis_names[0] == "dcn"  # crossing axis outermost
        assert dict(mesh.shape) == {"dcn": 2, "data": 2, "model": 2}
        # ZOO_DCN_AXIS names the crossing axis when not passed
        monkeypatch.setenv("ZOO_DCN_AXIS", "data")
        mesh = zp.build_mesh({"data": 4}, dcn_shape=2,
                             slice_groups=[devs[:4], devs[4:]])
        assert dict(mesh.shape) == {"data": 8}


# ---------------------------------------------------------------------------
# compile_step: the choke point's dispatch semantics
# ---------------------------------------------------------------------------


class TestCompileStep:
    def test_compiles_once_per_signature_through_timed_compile(self):
        from analytics_zoo_tpu.metrics import (
            MetricsRegistry,
            set_registry,
            snapshot,
        )
        from analytics_zoo_tpu.parallel.plan import compile_step

        reg = MetricsRegistry(enabled=True)
        prev = set_registry(reg)
        try:
            calls = []
            step = compile_step(lambda a: a * 2.0, label="probe_cs")
            for _ in range(3):
                calls.append(np.asarray(step(jnp.ones((4,)))))
            # new shape => new lowering, same wrapper
            step(jnp.ones((8,)))
            hist = [s for s in snapshot(reg)["samples"]
                    if s["name"] == "zoo_compile_seconds"
                    and s["labels"] == {"label": "probe_cs"}]
            assert hist and hist[0]["count"] == 2  # 2 signatures, 3 calls
            np.testing.assert_array_equal(calls[0], 2.0 * np.ones(4))
        finally:
            set_registry(prev)

    def test_python_scalar_retype_recompiles(self):
        """An int and a float at the same position are different
        programs (int32 vs f32 weak avals): the signature must key on
        the scalar's TYPE, or the cached executable rejects the
        mismatched aval instead of recompiling."""
        from analytics_zoo_tpu.parallel.plan import compile_step

        step = compile_step(lambda a, s: a * s, label="probe_scalar")
        out_i = step(jnp.ones((4,)), 2)
        out_f = step(jnp.ones((4,)), 2.5)
        assert float(out_i[0]) == 2.0
        assert float(out_f[0]) == 2.5

    def test_shard_map_mode_requires_specs(self):
        from analytics_zoo_tpu.parallel.plan import (
            ShardingPlan,
            compile_step,
        )

        with pytest.raises(ValueError, match="in_specs"):
            compile_step(lambda x: x,
                         ShardingPlan(name="sm", mode="shard_map"))


# ---------------------------------------------------------------------------
# Estimator integration: plans end to end
# ---------------------------------------------------------------------------


def _fit_under(plan, nb_epoch=3, **fit_kw):
    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.parallel.plan import per_chip_bytes

    zoo.init_zoo_context(seed=3, mesh_shape={"data": 8})
    x, y = _data()
    m = _model()
    m.fit(x, y, batch_size=32, nb_epoch=nb_epoch, plan=plan, **fit_kw)
    est = m._estimator
    return {
        "losses": [h["loss"] for h in est.history],
        "bytes": per_chip_bytes((m.params, est._opt_state)),
        "spec0": jax.tree_util.tree_leaves(m.params)[0].sharding.spec,
        "model": m,
    }


class TestEstimatorPlans:
    def test_fsdp_bitwise_trajectory_and_memory(self):
        """The headline contract: fsdp trains bit-identically to
        replicated DP while holding <= 0.6x (measured ~0.13x) the
        per-chip param+opt bytes."""
        dp = _fit_under(None)
        fs = _fit_under("fsdp")
        assert fs["losses"] == dp["losses"]  # BITWISE
        assert fs["spec0"] == P("data")
        assert dp["spec0"] == P()
        assert fs["bytes"] <= 0.6 * dp["bytes"], (fs["bytes"], dp["bytes"])

    def test_zero1_plan_shards_opt_only(self):
        dp = _fit_under(None)
        z1 = _fit_under("zero1")
        assert z1["spec0"] == P()  # params pinned replicated
        assert z1["bytes"] < dp["bytes"]
        np.testing.assert_allclose(z1["losses"], dp["losses"],
                                   rtol=1e-5, atol=1e-6)

    def test_env_knob_selects_plan(self, monkeypatch):
        monkeypatch.setenv("ZOO_SHARDING_PLAN", "fsdp")
        got = _fit_under(None, nb_epoch=1)
        assert got["spec0"] == P("data")

    def test_tensor_parallel_plan_through_estimator(self):
        import analytics_zoo_tpu as zoo
        from analytics_zoo_tpu.parallel.plan import tensor_parallel

        dp = _fit_under(None)
        zoo.init_zoo_context(seed=3, mesh_shape={"data": 2, "model": 4})
        x, y = _data()
        m = _model()
        tp = tensor_parallel([(r"kernel", P(None, "model"))])
        m.fit(x, y, batch_size=32, nb_epoch=3, plan=tp)
        k0 = m.params["dense_1"]["kernel"]
        assert k0.sharding.spec == P(None, "model")
        # same global math on a different mesh topology: the schedule
        # depends only on (seed, epoch), so the trajectory matches the
        # 8-way DP run to float tolerance
        np.testing.assert_allclose(
            [h["loss"] for h in m._estimator.history], dp["losses"],
            rtol=1e-5, atol=1e-6)

    def test_checkpoint_saves_plan_spec_tree(self, tmp_path):
        from analytics_zoo_tpu.common.safe_pickle import safe_load

        import analytics_zoo_tpu as zoo

        zoo.init_zoo_context(seed=3, mesh_shape={"data": 8})
        x, y = _data()
        m = _model()
        m.set_checkpoint(str(tmp_path))
        m.fit(x, y, batch_size=32, nb_epoch=1, plan="fsdp")
        files = [f for f in os.listdir(tmp_path) if f.endswith(".pkl")]
        assert files
        with open(os.path.join(tmp_path, sorted(files)[-1]), "rb") as f:
            payload = safe_load(f)
        rec = payload["plan"]
        assert rec["name"] == "fsdp"
        assert rec["mesh"] == {"data": 8, "model": 1}
        assert ["data"] in rec["param_specs"]  # sharded leaves recorded
        assert len(rec["opt_specs"]) == len(payload["opt_flat"])


# ---------------------------------------------------------------------------
# Acceptance: ALL plans through the choke point, cross-process warm start
# ---------------------------------------------------------------------------

_CHILD = r"""
import json, os
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.metrics import get_registry, snapshot
from analytics_zoo_tpu.parallel import (
    make_shard_map_train_step, make_zero1_train_step, tensor_parallel,
)
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
from analytics_zoo_tpu.pipeline.api.keras.objectives import get_loss


def model():
    m = Sequential()
    m.add(Dense(16, activation="relu", input_shape=(8,)))
    m.add(Dense(4, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    return m


rng = np.random.default_rng(0)
x = rng.normal(size=(64, 8)).astype(np.float32)
y = rng.integers(0, 4, size=(64,)).astype(np.int32)
batch = {"x": x[:32], "y": y[:32]}

# jit-mode plans through the estimator's warmup: ONE choke-point
# compile + dispatch per plan
for plan in ("dp", "fsdp", "zero1"):
    zoo.init_zoo_context(seed=0, mesh_shape={"data": 8})
    model()._make_estimator().warmup(batch, plan=plan)

# tensor parallelism on a {data: 2, model: 4} mesh
zoo.init_zoo_context(seed=0, mesh_shape={"data": 2, "model": 4})
tp = tensor_parallel([(r"kernel", P(None, "model"))])
model()._make_estimator().warmup(batch, plan=tp)

# explicit shard_map strategies (mode="shard_map" plans)
zoo.init_zoo_context(seed=0, mesh_shape={"data": 8})
m = model()
loss = get_loss("sparse_categorical_crossentropy")
opt = optax.adam(1e-2)
db = {"x": jnp.asarray(x[:32]), "y": jnp.asarray(y[:32])}
params, state = m.build_params()
step = make_shard_map_train_step(m, loss, opt)
step(params, opt.init(params), state, jax.random.PRNGKey(0), db)
m2 = model()  # fresh buffers: the step above donated m's
zstep, zinit = make_zero1_train_step(m2, loss, opt)
params2, state2 = m2.build_params()
zstep(params2, zinit(params2), state2, jax.random.PRNGKey(0), db)

out = {"hits": 0, "misses": 0, "hlo_flops": {}, "compiled": []}
for s in snapshot(get_registry())["samples"]:
    if s["name"] == "zoo_compile_cache_hits_total":
        out["hits"] += s["value"]
    elif s["name"] == "zoo_compile_cache_misses_total":
        out["misses"] += s["value"]
    elif s["name"] == "zoo_hlo_flops":
        out["hlo_flops"][s["labels"]["label"]] = s["value"]
    elif s["name"] == "zoo_compile_seconds":
        out["compiled"].append(s["labels"]["label"])
print("RESULT " + json.dumps(out))
"""

ALL_PLAN_LABELS = {
    "train_step", "train_step_fsdp", "train_step_zero1", "train_step_tp",
    "shard_map_step", "zero1_step", "zero1_init_opt_state",
}


def _run_child(cache_dir):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        ZOO_COMPILE_CACHE=str(cache_dir),
    )
    env.pop("ZOO_SHARDING_PLAN", None)
    env.pop("ZOO_SHARD_OPTIMIZER", None)
    r = subprocess.run([sys.executable, "-c", _CHILD], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    line = [ln for ln in r.stdout.splitlines()
            if ln.startswith("RESULT ")][-1]
    return json.loads(line[len("RESULT "):])


def test_every_plan_compiles_through_choke_point_and_warm_starts(tmp_path):
    """The acceptance pin: plain DP, fsdp, zero1, TP, explicit
    shard_map and explicit zero1 ALL lower through compile_step →
    timed_compile.  Evidence: (a) every plan label lands in
    zoo_compile_seconds AND carries zoo_hlo_* features (the HLO lint
    rides the choke point), (b) a SECOND process over the same
    ZOO_COMPILE_CACHE compiles every one of those programs as a
    persistent-cache HIT (zero misses)."""
    cache = tmp_path / "cc"
    cold = _run_child(cache)
    assert ALL_PLAN_LABELS <= set(cold["compiled"]), cold["compiled"]
    assert ALL_PLAN_LABELS <= set(cold["hlo_flops"]), cold["hlo_flops"]
    # every compiled program extracted nonzero analytic FLOPs except the
    # collective-free init (its program is gather/pad, not matmul)
    for label in ALL_PLAN_LABELS - {"zero1_init_opt_state"}:
        assert cold["hlo_flops"][label] > 0, label
    assert cold["hits"] == 0
    assert cold["misses"] == len(ALL_PLAN_LABELS)

    warm = _run_child(cache)
    assert warm["misses"] == 0, warm
    assert warm["hits"] == len(ALL_PLAN_LABELS)
    assert ALL_PLAN_LABELS <= set(warm["hlo_flops"])


# ---------------------------------------------------------------------------
# Quick-tier bench guard (bench.py --partition)
# ---------------------------------------------------------------------------


def test_partition_bench_quick_tier(tmp_path):
    """CI guard on the bench itself: fsdp per-chip param+opt bytes <=
    0.6x replicated at a bitwise-equal loss trajectory."""
    sys.path.insert(0, REPO)
    try:
        from bench import partition_bench
    finally:
        sys.path.remove(REPO)
    doc = partition_bench(quick=True,
                          out_path=str(tmp_path / "bench.json"))
    assert doc["trajectory_bitwise_equal"] is True
    assert doc["value"] <= 0.6, doc["value"]
    assert doc["zero1_ratio"] <= 0.6, doc["zero1_ratio"]
    assert doc["zero1_trajectory_max_abs_diff"] < 1e-5
