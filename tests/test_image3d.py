"""3D transform tests (reference image3d specs)."""

import numpy as np
import pytest

from analytics_zoo_tpu.feature.image3d import (
    AffineTransform3D,
    CenterCrop3D,
    Crop3D,
    RandomCrop3D,
    Rotate3D,
    rotation_matrix_3d,
)


def _vol(shape=(8, 10, 12), seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


class TestCrop:
    def test_crop_shape_and_content(self):
        v = _vol()
        out = Crop3D((1, 2, 3), (4, 5, 6))(v)
        assert out.shape == (4, 5, 6)
        np.testing.assert_array_equal(out, v[1:5, 2:7, 3:9])

    def test_crop_out_of_bounds_raises(self):
        with pytest.raises(ValueError):
            Crop3D((6, 0, 0), (4, 4, 4))(_vol())

    def test_center_crop(self):
        out = CenterCrop3D((4, 4, 4))(_vol())
        np.testing.assert_array_equal(out, _vol()[2:6, 3:7, 4:8])

    def test_random_crop_in_bounds_and_reproducible(self):
        op1 = RandomCrop3D((4, 4, 4))
        out = op1(_vol())
        assert out.shape == (4, 4, 4)

    def test_channel_volume(self):
        v = _vol((8, 8, 8)).reshape(8, 8, 8)[..., None].repeat(2, -1)
        assert Crop3D((0, 0, 0), (4, 4, 4))(v).shape == (4, 4, 4, 2)


class TestRotate:
    def test_identity_rotation(self):
        v = _vol()
        out = Rotate3D(0, 0, 0)(v)
        np.testing.assert_allclose(out, v, atol=1e-5)

    def test_full_turn_approximates_identity(self):
        v = _vol((9, 9, 9))
        out = Rotate3D(roll=np.pi / 2)(v)
        back = Rotate3D(roll=-np.pi / 2)(out)
        # interior voxels survive two resamples
        np.testing.assert_allclose(back[2:-2, 2:-2, 2:-2],
                                   v[2:-2, 2:-2, 2:-2], atol=1e-4)

    def test_rotation_matrix_orthonormal(self):
        m = rotation_matrix_3d(0.3, -0.2, 0.9)
        np.testing.assert_allclose(m @ m.T, np.eye(3), atol=1e-10)

    def test_quarter_roll_permutes_axes(self):
        """roll=90° about the depth axis maps (h, w) -> (w, -h)."""
        v = np.zeros((5, 5, 5), np.float32)
        v[2, 1, 2] = 1.0  # one voxel off-center along h
        out = Rotate3D(roll=np.pi / 2)(v)
        assert out[2].argmax() != v[2].argmax() or not np.allclose(out, v)
        assert out.sum() == pytest.approx(1.0, abs=1e-4)


class TestAffine:
    def test_translation_shifts_content(self):
        v = np.zeros((6, 6, 6), np.float32)
        v[2, 2, 2] = 1.0
        out = AffineTransform3D(np.eye(3), translation=(1, 0, 0))(v)
        assert out[1, 2, 2] == pytest.approx(1.0, abs=1e-6)

    def test_scale_matrix(self):
        v = _vol((8, 8, 8))
        out = AffineTransform3D(np.eye(3) * 2.0)(v)  # zoom in 2x
        assert out.shape == v.shape
        # center voxel unchanged by center-anchored scaling
        assert out[3, 3, 3] != 0
