"""3D transform tests (reference image3d specs)."""

import numpy as np
import pytest

from analytics_zoo_tpu.feature.image3d import (
    AffineTransform3D,
    CenterCrop3D,
    Crop3D,
    RandomCrop3D,
    Rotate3D,
    rotation_matrix_3d,
)


def _vol(shape=(8, 10, 12), seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


class TestCrop:
    def test_crop_shape_and_content(self):
        v = _vol()
        out = Crop3D((1, 2, 3), (4, 5, 6))(v)
        assert out.shape == (4, 5, 6)
        np.testing.assert_array_equal(out, v[1:5, 2:7, 3:9])

    def test_crop_out_of_bounds_raises(self):
        with pytest.raises(ValueError):
            Crop3D((6, 0, 0), (4, 4, 4))(_vol())

    def test_center_crop(self):
        out = CenterCrop3D((4, 4, 4))(_vol())
        np.testing.assert_array_equal(out, _vol()[2:6, 3:7, 4:8])

    def test_random_crop_in_bounds_and_reproducible(self):
        op1 = RandomCrop3D((4, 4, 4))
        out = op1(_vol())
        assert out.shape == (4, 4, 4)

    def test_channel_volume(self):
        v = _vol((8, 8, 8)).reshape(8, 8, 8)[..., None].repeat(2, -1)
        assert Crop3D((0, 0, 0), (4, 4, 4))(v).shape == (4, 4, 4, 2)


class TestRotate:
    def test_identity_rotation(self):
        v = _vol()
        out = Rotate3D(0, 0, 0)(v)
        np.testing.assert_allclose(out, v, atol=1e-5)

    def test_full_turn_approximates_identity(self):
        v = _vol((9, 9, 9))
        out = Rotate3D(roll=np.pi / 2)(v)
        back = Rotate3D(roll=-np.pi / 2)(out)
        # interior voxels survive two resamples
        np.testing.assert_allclose(back[2:-2, 2:-2, 2:-2],
                                   v[2:-2, 2:-2, 2:-2], atol=1e-4)

    def test_rotation_matrix_orthonormal(self):
        m = rotation_matrix_3d(0.3, -0.2, 0.9)
        np.testing.assert_allclose(m @ m.T, np.eye(3), atol=1e-10)

    def test_quarter_roll_permutes_axes(self):
        """roll=90° about the depth axis maps (h, w) -> (w, -h)."""
        v = np.zeros((5, 5, 5), np.float32)
        v[2, 1, 2] = 1.0  # one voxel off-center along h
        out = Rotate3D(roll=np.pi / 2)(v)
        assert out[2].argmax() != v[2].argmax() or not np.allclose(out, v)
        assert out.sum() == pytest.approx(1.0, abs=1e-4)


class TestAffine:
    def test_translation_shifts_content(self):
        v = np.zeros((6, 6, 6), np.float32)
        v[2, 2, 2] = 1.0
        out = AffineTransform3D(np.eye(3), translation=(1, 0, 0))(v)
        assert out[1, 2, 2] == pytest.approx(1.0, abs=1e-6)

    def test_scale_matrix(self):
        v = _vol((8, 8, 8))
        out = AffineTransform3D(np.eye(3) * 2.0)(v)  # zoom in 2x
        assert out.shape == v.shape
        # center voxel unchanged by center-anchored scaling
        assert out[3, 3, 3] != 0


class TestWarp3D:
    """Flow-field warp vs reference Warp.scala semantics (1-based coords,
    offset/absolute modes, clamp vs padding borders)."""

    def test_zero_offset_flow_is_identity(self):
        from analytics_zoo_tpu.feature.image3d import Warp3D

        vol = np.random.default_rng(0).normal(
            size=(4, 5, 6)).astype(np.float32)
        flow = np.zeros((3, 4, 5, 6))
        out = Warp3D(flow, offset=True)(vol)
        np.testing.assert_allclose(out, vol, atol=1e-6)

    def test_integer_shift(self):
        from analytics_zoo_tpu.feature.image3d import Warp3D

        vol = np.arange(4 * 4 * 4, dtype=np.float32).reshape(4, 4, 4)
        flow = np.zeros((3, 4, 4, 4))
        flow[2] = 1.0  # sample one voxel to the right
        out = Warp3D(flow)(vol)
        np.testing.assert_allclose(out[:, :, :3], vol[:, :, 1:], atol=1e-6)
        # off the right edge clamps to the border column
        np.testing.assert_allclose(out[:, :, 3], vol[:, :, 3], atol=1e-6)

    def test_padding_mode(self):
        from analytics_zoo_tpu.feature.image3d import Warp3D

        vol = np.ones((3, 3, 3), np.float32)
        flow = np.zeros((3, 3, 3, 3))
        flow[0] = 5.0  # everything off-image in z
        out = Warp3D(flow, clamp_mode="padding", pad_val=-7.0)(vol)
        np.testing.assert_allclose(out, -7.0)

    def test_absolute_mode_fractional_interpolation(self):
        from analytics_zoo_tpu.feature.image3d import Warp3D

        vol = np.zeros((2, 2, 2), np.float32)
        vol[0, 0, 0] = 1.0
        vol[1, 0, 0] = 3.0
        # absolute coords (offset=False): sample midpoint between the two
        # voxels along z at (1.5, 1, 1) in 1-based coords
        flow = np.zeros((3, 1, 1, 1))
        flow[0, 0, 0, 0] = 1.5
        flow[1, 0, 0, 0] = 1.0
        flow[2, 0, 0, 0] = 1.0
        out = Warp3D(flow, offset=False)(vol)
        np.testing.assert_allclose(out[0, 0, 0], 2.0, atol=1e-6)

    def test_output_takes_flow_shape_and_channels(self):
        from analytics_zoo_tpu.feature.image3d import Warp3D

        vol = np.random.default_rng(1).normal(
            size=(4, 4, 4, 2)).astype(np.float32)
        flow = np.zeros((3, 2, 3, 4))
        out = Warp3D(flow)(vol)
        assert out.shape == (2, 3, 4, 2)
        np.testing.assert_allclose(out, vol[:2, :3, :4], atol=1e-6)

    def test_matches_reference_scalar_loop(self):
        """Vectorized warp vs a direct transcription of the reference's
        per-voxel algorithm (Warp.scala:53-97) on random flow."""
        from analytics_zoo_tpu.feature.image3d import Warp3D

        rng = np.random.default_rng(2)
        vol = rng.normal(size=(3, 4, 5)).astype(np.float32)
        flow = rng.normal(scale=1.5, size=(3, 3, 4, 5))

        def oracle(src, flow, offset=True, clamp="clamp", pad=0.0):
            sd, sh, sw = src.shape
            _, dd, dh, dw = flow.shape
            dst = np.zeros((dd, dh, dw), np.float64)
            for z in range(1, dd + 1):
                for y in range(1, dh + 1):
                    for x in range(1, dw + 1):
                        om = 1 if offset else 0
                        iz = om * z + flow[0, z - 1, y - 1, x - 1]
                        iy = om * y + flow[1, z - 1, y - 1, x - 1]
                        ix = om * x + flow[2, z - 1, y - 1, x - 1]
                        off = (iz < 1 or iz > sd or iy < 1 or iy > sh
                               or ix < 1 or ix > sw)
                        if off and clamp == "padding":
                            dst[z - 1, y - 1, x - 1] = pad
                            continue
                        iz = min(max(iz, 1), sd)
                        iy = min(max(iy, 1), sh)
                        ix = min(max(ix, 1), sw)
                        iz0, iy0, ix0 = int(np.floor(iz)), \
                            int(np.floor(iy)), int(np.floor(ix))
                        iz1 = min(iz0 + 1, sd)
                        iy1 = min(iy0 + 1, sh)
                        ix1 = min(ix0 + 1, sw)
                        wz, wy, wx = iz - iz0, iy - iy0, ix - ix0
                        s = lambda a, b, c: float(src[a - 1, b - 1, c - 1])
                        dst[z - 1, y - 1, x - 1] = (
                            (1-wy)*(1-wx)*(1-wz)*s(iz0, iy0, ix0)
                            + (1-wy)*(1-wx)*wz*s(iz1, iy0, ix0)
                            + (1-wy)*wx*(1-wz)*s(iz0, iy0, ix1)
                            + (1-wy)*wx*wz*s(iz1, iy0, ix1)
                            + wy*(1-wx)*(1-wz)*s(iz0, iy1, ix0)
                            + wy*(1-wx)*wz*s(iz1, iy1, ix0)
                            + wy*wx*(1-wz)*s(iz0, iy1, ix1)
                            + wy*wx*wz*s(iz1, iy1, ix1))
            return dst.astype(np.float32)

        for mode in ("clamp", "padding"):
            got = Warp3D(flow, clamp_mode=mode, pad_val=0.5)(vol)
            want = oracle(vol, flow, clamp=mode, pad=0.5)
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5,
                                       err_msg=mode)
