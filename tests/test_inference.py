"""Inference stack tests — mirrors the reference's inference suite
(zoo/src/test/.../inference, pyzoo/test/zoo/pipeline/inference)."""

import threading

import numpy as np
import pytest

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
from analytics_zoo_tpu.pipeline.api.keras.topology import Sequential
from analytics_zoo_tpu.pipeline.inference import (
    InferenceModel,
    quantize_params,
    dequantize_params,
)
from analytics_zoo_tpu.pipeline.inference.quantize import quantization_error


def _small_net():
    net = Sequential()
    net.add(Dense(64, input_shape=(16,), activation="relu"))
    net.add(Dense(8))
    net.build_params()
    return net


class TestInferenceModel:
    def setup_method(self, _):
        init_zoo_context(seed=0)

    def test_predict_matches_forward(self):
        net = _small_net()
        m = InferenceModel().from_keras_net(net)
        x = np.random.default_rng(0).normal(size=(10, 16)).astype(np.float32)
        got = m.predict(x)
        want, _ = net.forward(net.params, x, state=net.state)
        np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5,
                                   atol=1e-5)

    def test_bucketed_batching_single_compile_per_bucket(self):
        net = _small_net()
        m = InferenceModel().from_keras_net(net)
        x = np.zeros((5, 16), np.float32)
        m.predict(x)       # bucket 8
        m.predict(x[:7])   # same bucket 8 -> no new executable
        assert len(m._compiled) == 1
        m.predict(np.zeros((9, 16), np.float32))  # bucket 16
        assert len(m._compiled) == 2

    def test_warm_bucket_does_not_increment_compile_counter(self):
        """Pad-to-bucket reuse guard (regression): a second predict at an
        already-compiled bucket shape must be served by the cached
        executable — the per-bucket zoo_inference_compiles_total counter
        stays flat, whatever sub-bucket batch size arrives."""
        from analytics_zoo_tpu.metrics import (
            MetricsRegistry,
            set_registry,
            snapshot,
        )

        reg = MetricsRegistry(enabled=True)
        prev = set_registry(reg)
        try:
            net = _small_net()
            m = InferenceModel().from_keras_net(net)

            def compiles(bucket):
                return sum(
                    s["value"] for s in snapshot(reg)["samples"]
                    if s["name"] == "zoo_inference_compiles_total"
                    and (s.get("labels") or {}).get("bucket") == bucket)

            m.predict(np.zeros((3, 16), np.float32))   # pads 3 -> bucket 4
            assert compiles("4") == 1
            m.predict(np.zeros((4, 16), np.float32))   # exact bucket hit
            m.predict(np.zeros((2, 16), np.float32))   # pads 2 -> bucket 4
            assert compiles("4") == 1
            m.predict(np.zeros((5, 16), np.float32))   # new bucket 8
            assert compiles("8") == 1
            assert compiles("4") == 1
        finally:
            set_registry(prev)

    def test_save_load_roundtrip(self, tmp_path):
        net = _small_net()
        p = str(tmp_path / "model.zoo")
        net.save(p)
        m = InferenceModel().load(p)
        x = np.random.default_rng(1).normal(size=(4, 16)).astype(np.float32)
        want, _ = net.forward(net.params, x, state=net.state)
        np.testing.assert_allclose(m.predict(x), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_concurrent_predict(self):
        net = _small_net()
        m = InferenceModel(concurrent_num=2).from_keras_net(net)
        x = np.random.default_rng(2).normal(size=(8, 16)).astype(np.float32)
        want = m.predict(x)
        results, errs = [None] * 8, []

        def worker(i):
            try:
                results[i] = m.predict(x)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert not errs
        for r in results:
            np.testing.assert_allclose(r, want, rtol=1e-5, atol=1e-5)

    def test_warmup_precompiles(self):
        net = _small_net()
        m = InferenceModel().from_keras_net(net)
        m.warmup((16,), batch_sizes=(1, 8))
        assert len(m._compiled) == 2
        m.predict(np.zeros((8, 16), np.float32))
        assert len(m._compiled) == 2  # served from cache


class TestQuantization:
    def setup_method(self, _):
        init_zoo_context(seed=0)

    def test_roundtrip_error_small(self):
        net = _small_net()
        q = quantize_params(net.params, min_size=8)
        err = quantization_error(net.params, q)
        assert 0 < err < 0.02  # per-channel int8: <2% relative L2

    def test_dequantize_shapes(self):
        net = _small_net()
        q = quantize_params(net.params, min_size=8)
        d = dequantize_params(q)
        import jax
        for a, b in zip(jax.tree_util.tree_leaves(net.params),
                        jax.tree_util.tree_leaves(d)):
            assert a.shape == b.shape

    def test_int8_predictions_close(self):
        net = _small_net()
        m = InferenceModel().from_keras_net(net).optimize("int8")
        x = np.random.default_rng(3).normal(size=(16, 16)).astype(np.float32)
        want, _ = net.forward(net.params, x, state=net.state)
        got = m.predict(x)
        # accuracy-preserving claim (wp-bigdl.md:192: <=0.1% drop); here:
        # small relative output error
        rel = (np.linalg.norm(got - np.asarray(want))
               / np.linalg.norm(np.asarray(want)))
        assert rel < 0.05

    def test_bf16_mode(self):
        net = _small_net()
        m = InferenceModel().from_keras_net(net).optimize("bf16")
        x = np.random.default_rng(4).normal(size=(4, 16)).astype(np.float32)
        want, _ = net.forward(net.params, x, state=net.state)
        got = m.predict(x)
        rel = (np.linalg.norm(got - np.asarray(want))
               / np.linalg.norm(np.asarray(want)))
        assert rel < 0.05


class TestTorchEscapeHatch:
    def test_load_torch(self):
        torch = pytest.importorskip("torch")
        lin = torch.nn.Linear(4, 2)
        m = InferenceModel().load_torch(lin, input_shape=(4,))
        x = np.random.default_rng(5).normal(size=(3, 4)).astype(np.float32)
        got = m.predict(x)
        with torch.no_grad():
            want = lin(torch.as_tensor(x)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
