"""zoofleet: exactly-once work claiming, continuous batching, and the
SLO-aware autoscaling fleet (serving/broker.py claim protocol,
serving/server.py fleet mode, serving/fleet.py, serving/scaler.py)."""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from analytics_zoo_tpu.serving import (
    ClusterServing, ClusterServingHelper, FileBroker, InMemoryBroker,
    InputQueue, OutputQueue, ServingTimeout,
)
from analytics_zoo_tpu.serving.fleet import (
    FleetController, _SyntheticModel, varz_doc,
)
from analytics_zoo_tpu.serving.scaler import FleetSignals, SloScaler

STREAM = "image_stream"


@pytest.fixture(params=["memory", "file", "redis"])
def broker(request, tmp_path):
    if request.param == "memory":
        return InMemoryBroker()
    if request.param == "file":
        return FileBroker(str(tmp_path / "spool"))
    # Redis leg (ISSUE 20 satellite): the claim/lease protocol against a
    # REAL redis — opt-in via ZOO_TEST_REDIS=host[:port] so CI without a
    # server skips instead of hanging on a connect timeout.
    spec = os.environ.get("ZOO_TEST_REDIS")
    if not spec:
        pytest.skip("set ZOO_TEST_REDIS=host[:port] to run the "
                    "RedisBroker protocol leg")
    host, _, port = spec.partition(":")
    from analytics_zoo_tpu.serving import RedisBroker

    try:
        b = RedisBroker(host=host or "localhost",
                        port=int(port) if port else 6379)
        b.xlen(STREAM)  # fail fast on an unreachable server
    except Exception as e:
        pytest.skip(f"redis at {spec!r} unusable: {e}")
    # isolate this test's keys: the shared server may hold state from
    # previous runs
    for key in list(b.keys("")):
        b.delete(key)
    b.xtrim(STREAM, 0)
    return b


# ---------------------------------------------------------------------------
# Broker claim/extend/release protocol
# ---------------------------------------------------------------------------


def test_claim_is_exclusive_and_preserves_order(broker):
    for i in range(6):
        broker.xadd(STREAM, {"i": str(i)})
    a = broker.claim(STREAM, "A", 4, lease_ms=5000)
    b = broker.claim(STREAM, "B", 10, lease_ms=5000)
    assert [f["i"] for _, f in a] == ["0", "1", "2", "3"]
    assert [f["i"] for _, f in b] == ["4", "5"]  # disjoint, no overlap
    assert broker.claim(STREAM, "C", 10, lease_ms=5000) == []
    assert broker.xlen(STREAM) == 6  # claimed records stay in the stream
    assert broker.unclaimed(STREAM) == 0


def test_lease_expiry_enables_takeover(broker):
    for i in range(3):
        broker.xadd(STREAM, {"i": str(i)})
    broker.claim(STREAM, "dead", 3, lease_ms=200)
    assert broker.claim(STREAM, "B", 3, lease_ms=200) == []
    time.sleep(0.25)
    got = broker.claim(STREAM, "B", 3, lease_ms=5000)
    assert [f["i"] for _, f in got] == ["0", "1", "2"]
    assert broker.pop_takeovers("B") == 3  # counted once...
    assert broker.pop_takeovers("B") == 0  # ...and reset on read


def test_extend_prolongs_lease(broker):
    broker.xadd(STREAM, {"i": "0"})
    [(rid, _)] = broker.claim(STREAM, "A", 1, lease_ms=300)
    time.sleep(0.15)
    broker.extend(STREAM, "A", [rid], lease_ms=5000)
    time.sleep(0.3)  # past the ORIGINAL expiry
    assert broker.claim(STREAM, "B", 1, lease_ms=300) == []
    assert broker.unclaimed(STREAM) == 0


def test_release_done_acks_and_release_requeues(broker):
    for i in range(4):
        broker.xadd(STREAM, {"i": str(i)})
    recs = broker.claim(STREAM, "A", 4, lease_ms=5000)
    ids = [r[0] for r in recs]
    broker.release(STREAM, "A", ids[:2], done=True)
    assert broker.xlen(STREAM) == 2  # served records left the stream
    broker.release(STREAM, "A", ids[2:], done=False)
    assert broker.unclaimed(STREAM) == 2  # requeued, immediately claimable
    again = broker.claim(STREAM, "B", 4, lease_ms=5000)
    assert [f["i"] for _, f in again] == ["2", "3"]
    assert broker.pop_takeovers("B") == 0  # requeue is not a takeover


def test_release_skips_foreign_claims(broker):
    broker.xadd(STREAM, {"i": "0"})
    [(rid, _)] = broker.claim(STREAM, "A", 1, lease_ms=5000)
    broker.release(STREAM, "B", [rid], done=True)  # not B's to ack
    assert broker.xlen(STREAM) == 1
    broker.extend(STREAM, "B", [rid], lease_ms=50)  # nor B's to extend
    time.sleep(0.1)
    assert broker.claim(STREAM, "C", 1, lease_ms=300) == []


def test_inmemory_blocking_xread_wakes_on_add():
    """Satellite pin: a blocking xread is Condition-woken by xadd within
    milliseconds — no poll/busy-wait loop (an idle replica burns no
    CPU waiting out block_ms)."""
    b = InMemoryBroker()
    out = {}

    def waiter():
        t0 = time.monotonic()
        out["recs"] = b.xread(STREAM, 4, block_ms=5000)
        out["dt"] = time.monotonic() - t0

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.15)
    b.xadd(STREAM, {"i": "0"})
    t.join(timeout=5)
    assert out["recs"], "woke empty"
    assert 0.1 < out["dt"] < 0.6, out["dt"]  # woke on notify, not timeout


def test_inmemory_blocking_claim_wakes_on_add_and_expiry():
    b = InMemoryBroker()
    out = {}

    def waiter(key):
        t0 = time.monotonic()
        out[key] = b.claim(STREAM, "W", 1, lease_ms=1000, block_ms=5000)
        out[key + "_dt"] = time.monotonic() - t0

    t = threading.Thread(target=waiter, args=("add",))
    t.start()
    time.sleep(0.15)
    b.xadd(STREAM, {"i": "0"})
    t.join(timeout=5)
    assert out["add"] and 0.1 < out["add_dt"] < 0.6, out
    b.release(STREAM, "W", [out["add"][0][0]], done=True)
    # expiry wake: a dead owner's lease ends mid-wait — the blocked
    # claimer self-wakes at the expiry instant, no notify involved
    b.xadd(STREAM, {"i": "1"})
    b.claim(STREAM, "dead", 1, lease_ms=300)
    t2 = threading.Thread(target=waiter, args=("exp",))
    t2.start()
    t2.join(timeout=5)
    assert out["exp"] and 0.2 < out["exp_dt"] < 0.8, out
    assert b.pop_takeovers("W") == 1


# ---------------------------------------------------------------------------
# Client polling (satellite: timeout + bounded backoff)
# ---------------------------------------------------------------------------


def test_client_poll_returns_late_result():
    broker = InMemoryBroker()
    outq = OutputQueue(broker=broker)

    def later():
        time.sleep(0.2)
        broker.hset("result:u1", {"value": "[[1, 0.9]]"})

    threading.Thread(target=later).start()
    res = outq.poll("u1", timeout=5.0)
    assert res == [[1, 0.9]]


def test_client_poll_timeout_is_typed_and_backoff_bounded():
    broker = InMemoryBroker()
    calls = {"n": 0}
    orig = broker.hgetall

    def counting(key):
        calls["n"] += 1
        return orig(key)

    broker.hgetall = counting
    outq = OutputQueue(broker=broker)
    t0 = time.monotonic()
    with pytest.raises(ServingTimeout) as ei:
        outq.poll("lost", timeout=0.6, initial_delay=0.005, max_delay=0.05)
    dt = time.monotonic() - t0
    assert 0.5 < dt < 2.0, dt
    assert ei.value.uri == "lost" and ei.value.timeout == 0.6
    assert isinstance(ei.value, TimeoutError)  # typed, catchable broadly
    # exponential backoff bounds the broker round-trips: a 5ms spin
    # loop would make ~120 calls in 0.6s; backoff to 50ms makes ~< 20
    assert calls["n"] < 30, calls["n"]


# ---------------------------------------------------------------------------
# Fleet-mode serving: continuous batching + exactly-once across replicas
# ---------------------------------------------------------------------------


class _CountingModel:
    """Sleep model that records each predict's batch size."""

    def __init__(self, sleep_per_record_s=0.0):
        self.sleep_s = sleep_per_record_s
        self.batches = []
        self._lock = threading.Lock()

    def predict(self, arr):
        with self._lock:
            self.batches.append(int(arr.shape[0]))
        if self.sleep_s:
            time.sleep(self.sleep_s * arr.shape[0])
        out = np.zeros((arr.shape[0], 5), np.float32)
        out[:, 0] = 1.0
        return out


def _fleet_server(broker, owner, model, tmp_path, batch_size=8,
                  budget_ms=25.0, lease_ms=3000, serve_log=None):
    return ClusterServing(
        ClusterServingHelper(model_path=None, batch_size=batch_size,
                             batch_budget_ms=budget_ms, lease_ms=lease_ms,
                             log_dir=str(tmp_path / ("logs-" + owner))),
        model=model, broker=broker, owner=owner, serve_log=serve_log)


def test_two_replicas_serve_exactly_once(tmp_path):
    broker = InMemoryBroker()
    log = str(tmp_path / "served.log")
    inq = InputQueue(broker=broker)
    for i in range(24):
        inq.enqueue(f"u{i}", np.zeros((3,), np.float32))
    m1, m2 = _CountingModel(0.002), _CountingModel(0.002)
    s1 = _fleet_server(broker, "r1", m1, tmp_path, serve_log=log)
    s2 = _fleet_server(broker, "r2", m2, tmp_path, serve_log=log)
    s1.start()
    s2.start()
    outq = OutputQueue(broker=broker)
    got = {}
    deadline = time.time() + 30
    while len(got) < 24 and time.time() < deadline:
        got.update(outq.dequeue())
        time.sleep(0.01)
    s1.stop()
    s2.stop()
    assert len(got) == 24
    assert broker.xlen(STREAM) == 0  # all acked via release(done=True)
    # the serve audit log is the exactly-once ledger: every uri exactly
    # once across BOTH replicas, and both replicas did real work
    lines = [ln.split() for ln in open(log).read().splitlines()]
    uris = sorted(u for _, u in lines)
    assert uris == sorted(f"u{i}" for i in range(24))
    owners = {o for o, _ in lines}
    assert owners == {"r1", "r2"}  # the claim protocol shared the load


def test_lone_request_served_within_budget(tmp_path):
    """Continuous batching's latency bound: one request against a
    batch_size-8 bucket is flushed at the budget, not held for
    co-batchable traffic that never arrives."""
    broker = InMemoryBroker()
    model = _CountingModel()
    srv = _fleet_server(broker, "solo", model, tmp_path, batch_size=8,
                        budget_ms=150.0)
    srv.start()
    try:
        inq = InputQueue(broker=broker)
        t0 = time.perf_counter()
        inq.enqueue("lone", np.zeros((3,), np.float32))
        res = OutputQueue(broker=broker).poll("lone", timeout=10.0)
        dt = time.perf_counter() - t0
    finally:
        srv.stop()
    assert res is not None
    # budget 150ms + claim/predict/write overhead; far under any
    # "wait for a full bucket" regime (which would be the 10s timeout)
    assert dt < 1.5, dt
    assert model.batches == [1]


def test_trickle_coalesces_into_padded_bucket(tmp_path):
    """A trickle of same-shape requests inside one budget window lands
    in ONE padded predict, not 6 singleton dispatches."""
    broker = InMemoryBroker()
    model = _CountingModel()
    srv = _fleet_server(broker, "solo", model, tmp_path, batch_size=8,
                        budget_ms=400.0)
    srv.start()
    try:
        inq = InputQueue(broker=broker)
        for i in range(6):
            inq.enqueue(f"t{i}", np.zeros((3,), np.float32))
            time.sleep(0.02)
        outq = OutputQueue(broker=broker)
        got = {}
        deadline = time.time() + 15
        while len(got) < 6 and time.time() < deadline:
            got.update(outq.dequeue())
            time.sleep(0.01)
    finally:
        srv.stop()
    assert len(got) == 6
    assert sum(model.batches) == 6
    assert len(model.batches) <= 2, model.batches  # coalesced
    assert max(model.batches) >= 3


def test_keepalive_extends_lease_through_slow_predict(tmp_path):
    """A predict longer than the lease (the first-compile shape) must
    NOT forfeit its records: the keepalive extends in-flight leases, so
    an idle second replica never takes them over."""
    broker = InMemoryBroker()
    log = str(tmp_path / "served.log")
    slow = _CountingModel(1.2)  # one record -> 1.2s predict >> 400ms lease
    fast = _CountingModel()
    s1 = _fleet_server(broker, "slow", slow, tmp_path, budget_ms=5.0,
                       lease_ms=400, serve_log=log)
    s2 = _fleet_server(broker, "idle", fast, tmp_path, budget_ms=5.0,
                       lease_ms=400, serve_log=log)
    s1.start()
    try:
        InputQueue(broker=broker).enqueue(
            "x", np.zeros((3,), np.float32))
        deadline = time.time() + 10
        while broker.unclaimed(STREAM) and time.time() < deadline:
            time.sleep(0.01)  # s1 holds the claim before s2 exists
        s2.start()
        res = OutputQueue(broker=broker).poll("x", timeout=15.0)
        time.sleep(1.0)  # a takeover double-serve would land here
    finally:
        s1.stop()
        s2.stop()
    assert res is not None
    lines = open(log).read().splitlines()
    assert lines == ["slow x"], lines  # exactly once, by the slow owner
    assert fast.batches == []  # never taken over


def test_kill9_replica_mid_batch_survivors_serve_exactly_once(tmp_path):
    """THE fleet fault-tolerance acceptance: kill -9 a replica that has
    claimed records mid-batch; after lease expiry the survivor serves
    every enqueued record exactly once (serve-log ledger)."""
    spool = str(tmp_path / "spool")
    log = str(tmp_path / "served.log")
    broker = FileBroker(spool)
    inq = InputQueue(broker=broker)
    for i in range(20):
        inq.enqueue(f"u{i}", np.zeros((3,), np.float32))

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               ZOO_SERVING_LOG_DIR=str(tmp_path))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def spawn(owner, sleep_ms):
        return subprocess.Popen(
            [sys.executable, "-m", "analytics_zoo_tpu.serving.fleet",
             "--replica", "--broker", "dir:" + spool, "--owner", owner,
             "--batch-size", "4", "--budget-ms", "10",
             "--lease-ms", "1500", "--synthetic-sleep-ms", str(sleep_ms),
             "--serve-log", log],
            env=env, cwd=repo)

    # A's 2s/record predict means its first batch takes ~8s: it will be
    # SIGKILLed long before completing anything, holding live claims
    a = spawn("A", 2000)
    sdir = os.path.join(spool, "stream-" + STREAM)
    deadline = time.time() + 60
    while time.time() < deadline:
        if os.path.isdir(sdir) and any(
                n.startswith(".c-") for n in os.listdir(sdir)):
            break
        time.sleep(0.05)
    else:
        a.kill()
        pytest.fail("replica A never claimed")
    os.kill(a.pid, signal.SIGKILL)
    a.wait()
    assert not os.path.exists(log) or not open(log).read(), \
        "A must die mid-batch, before serving anything"

    b = spawn("B", 0)
    try:
        outq = OutputQueue(broker=broker)
        got = {}
        deadline = time.time() + 90
        while len(got) < 20 and time.time() < deadline:
            got.update(outq.dequeue())
            time.sleep(0.05)
    finally:
        b.terminate()
        b.wait(timeout=20)
    assert len(got) == 20, f"survivor served {len(got)}/20"
    lines = [ln.split() for ln in open(log).read().splitlines()]
    uris = sorted(u for _, u in lines)
    assert uris == sorted(f"u{i}" for i in range(20))  # exactly once
    assert {o for o, _ in lines} == {"B"}  # all by the survivor
    assert broker.xlen(STREAM) == 0  # nothing leaked


# ---------------------------------------------------------------------------
# SLO scaler policy (pure unit tests on fabricated windows)
# ---------------------------------------------------------------------------


def _sig(p99_ms=0.0, count=10, rate=100.0, queue=0, mem=0.0):
    return FleetSignals(predict_p99_s=p99_ms / 1e3, window_count=count,
                        service_rate=rate, queue_depth=queue,
                        memory_ratio=mem)


def test_scaler_scales_up_only_on_sustained_violation():
    s = SloScaler(slo_p99_ms=100.0, min_replicas=1, max_replicas=4,
                  up_windows=2, down_windows=3)
    bad = _sig(p99_ms=300.0)
    assert s.decide(1, bad) == (1, "violation_streak")  # not yet
    target, reason = s.decide(1, bad)
    assert target == 3 and reason == "slo_violation"  # ceil(1 * 300/100)
    # a single good window resets the streak
    s2 = SloScaler(slo_p99_ms=100.0, up_windows=2)
    s2.decide(1, bad)
    s2.decide(1, _sig(p99_ms=80.0))
    assert s2.decide(1, bad) == (1, "violation_streak")


def test_scaler_queue_delay_counts_toward_violation():
    s = SloScaler(slo_p99_ms=100.0, up_windows=1, max_replicas=4)
    # predict itself is fast, but 50 queued / 100 rec/s = 500ms wait
    target, reason = s.decide(1, _sig(p99_ms=10.0, queue=50, rate=100.0))
    assert target > 1 and reason == "slo_violation"


def test_scaler_stalled_backlog_and_memory_pressure():
    s = SloScaler(slo_p99_ms=100.0, up_windows=1, max_replicas=4)
    assert s.decide(2, _sig(count=0, rate=0.0, queue=10)) == \
        (3, "stalled_backlog")  # unbounded wait estimate: step up
    s2 = SloScaler(slo_p99_ms=100.0, up_windows=1, max_replicas=4,
                   memory_high=0.5)
    assert s2.decide(1, _sig(p99_ms=10.0, mem=0.6)) == \
        (4, "broker_pressure")  # records about to be trimmed: jump


def test_scaler_scales_down_on_sustained_slack_respecting_min():
    s = SloScaler(slo_p99_ms=100.0, min_replicas=1, max_replicas=4,
                  up_windows=1, down_windows=3)
    idle = _sig(p99_ms=5.0, count=0, rate=0.0, queue=0)
    assert s.decide(3, idle) == (3, "slack_streak")
    assert s.decide(3, idle) == (3, "slack_streak")
    assert s.decide(3, idle) == (2, "sustained_slack")
    # never below min
    s.decide(1, idle)
    s.decide(1, idle)
    assert s.decide(1, idle) == (1, "slack_streak")
    # the comfort band (neither violated nor slack) resets the streak
    s3 = SloScaler(slo_p99_ms=100.0, down_windows=2, slack_ratio=0.5)
    s3.decide(2, idle)
    assert s3.decide(2, _sig(p99_ms=80.0)) == (2, "")
    assert s3.decide(2, idle) == (2, "slack_streak")


def test_scaler_validates_bounds():
    with pytest.raises(ValueError):
        SloScaler(slo_p99_ms=0)
    with pytest.raises(ValueError):
        SloScaler(min_replicas=3, max_replicas=2)


# ---------------------------------------------------------------------------
# FleetController integration: autoscale up + down, telemetry trail
# ---------------------------------------------------------------------------


def test_fleet_autoscales_up_and_down_with_full_telemetry(tmp_path):
    from analytics_zoo_tpu.metrics import get_flight_recorder, snapshot

    broker = InMemoryBroker()
    helper = ClusterServingHelper(
        model_path=None, batch_size=8, batch_budget_ms=10, lease_ms=3000,
        log_dir=str(tmp_path))
    ctrl = FleetController(
        helper, broker, model_factory=lambda: _SyntheticModel(5.0),
        scaler=SloScaler(slo_p99_ms=300.0, min_replicas=1, max_replicas=3,
                         up_windows=2, down_windows=4),
        interval=0.3)
    ctrl.start()
    try:
        inq = InputQueue(broker=broker)
        outq = OutputQueue(broker=broker)
        for i in range(600):  # ~3 replica-seconds of service in one burst
            inq.enqueue(f"u{i}", np.zeros((3,), np.float32))
        got, max_reps = {}, 1
        deadline = time.time() + 60
        while len(got) < 600 and time.time() < deadline:
            got.update(outq.dequeue())
            max_reps = max(max_reps, ctrl.replica_count())
            time.sleep(0.02)
        assert len(got) == 600
        assert max_reps >= 2, "never scaled up under overload"
        deadline = time.time() + 20
        while ctrl.replica_count() > 1 and time.time() < deadline:
            time.sleep(0.1)
        assert ctrl.replica_count() == 1, "never scaled back down"
        decisions = ctrl.decision_log()
    finally:
        ctrl.stop()
    acts = [d["action"] for d in decisions]
    assert "up" in acts and "down" in acts
    up = next(d for d in decisions if d["action"] == "up")
    assert up["reason"] in ("slo_violation", "stalled_backlog",
                            "broker_pressure")
    assert up["est_p99_ms"] is None or up["est_p99_ms"] > 300.0
    # decision trail parity: /varz panel, flight events, metric family
    doc = varz_doc()
    assert any(c["current"]["slo_p99_ms"] == 300.0
               for c in doc["controllers"])
    assert [d["action"] for d in doc["decisions"][-len(acts):]] == acts
    kinds = {e.get("kind") for e in get_flight_recorder().events()}
    assert "fleet_scale" in kinds
    names = {s["name"] for s in snapshot()["samples"]}
    for n in ("zoo_fleet_replicas", "zoo_fleet_replicas_target",
              "zoo_fleet_decisions_total", "zoo_fleet_est_p99_seconds",
              "zoo_fleet_unclaimed_backlog",
              "zoo_fleet_batch_flushes_total"):
        assert n in names, n


def test_fleet_supervision_replaces_dead_replica(tmp_path):
    broker = InMemoryBroker()
    helper = ClusterServingHelper(
        model_path=None, batch_size=4, batch_budget_ms=5, lease_ms=1000,
        log_dir=str(tmp_path))
    ctrl = FleetController(
        helper, broker, model_factory=lambda: _SyntheticModel(0.0),
        scaler=SloScaler(slo_p99_ms=1000.0, min_replicas=2,
                         max_replicas=2),
        interval=0.2)
    ctrl.start()
    try:
        assert ctrl.replica_count() == 2
        # simulate a replica death: stop its server thread directly
        with ctrl._lock:
            victim = ctrl._replicas[0]
        victim.server.stop()
        deadline = time.time() + 15
        while time.time() < deadline:
            with ctrl._lock:
                alive = [r for r in ctrl._replicas if r.alive()]
            if len(alive) == 2 and victim not in alive:
                break
            time.sleep(0.05)
        else:
            pytest.fail("controller never replaced the dead replica")
        assert any(d["action"] == "replace" for d in ctrl.decision_log())
    finally:
        ctrl.stop()


def test_metrics_dump_renders_fleet_panel():
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    try:
        import metrics_dump
    finally:
        sys.path.pop(0)
    doc = {"fleet": {
        "controllers": [{"current": {
            "replicas": 2, "target": 3, "max_replicas": 4,
            "slo_p99_ms": 500.0, "mode": "thread",
            "window": {"predict_p99_ms": 12.0, "service_rate": 180.0,
                       "queue_depth": 40, "memory_ratio": 0.01}},
            "decisions": []}],
        "decisions": [{"ts": 1.0, "action": "up", "old": 1, "new": 3,
                       "reason": "slo_violation", "est_p99_ms": 750.0,
                       "queue_depth": 82}],
    }}
    out = []
    metrics_dump.render_fleet(doc, out=out)
    text = "\n".join(out)
    assert "replicas=2/3" in text and "slo_p99=500.0ms" in text
    assert "slo_violation" in text and "1 -> 3" in text
    # --prefix filtering skips the panel
    out2 = []
    metrics_dump.render_fleet(doc, prefix="zoo_serving", out=out2)
    assert out2 == []


# ---------------------------------------------------------------------------
# Review-hardening regressions
# ---------------------------------------------------------------------------


def test_reader_failure_requeues_claims_instead_of_wedging(tmp_path):
    """A broker hiccup AFTER claiming (here: pop_takeovers raising
    mid-admission) must not wedge the claimed records: they are dropped
    from the keepalive's in-flight set and requeued for immediate
    re-claim — not lease-extended forever while invisible to every
    replica.  The 60s lease makes the requeue path the ONLY way these
    records can be re-served inside the test deadline."""

    class HiccupBroker(InMemoryBroker):
        def __init__(self):
            super().__init__()
            self.hiccups = 2

        def pop_takeovers(self, owner):
            if self.hiccups > 0:
                self.hiccups -= 1
                raise ConnectionError("transient broker hiccup")
            return super().pop_takeovers(owner)

    broker = HiccupBroker()
    inq = InputQueue(broker=broker)
    for i in range(8):
        inq.enqueue(f"u{i}", np.zeros((3,), np.float32))
    model = _CountingModel()
    srv = _fleet_server(broker, "r1", model, tmp_path, lease_ms=60_000)
    srv.start()
    try:
        outq = OutputQueue(broker=broker)
        got = {}
        deadline = time.time() + 15
        while len(got) < 8 and time.time() < deadline:
            got.update(outq.dequeue())
            time.sleep(0.01)
    finally:
        srv.stop()
    assert sorted(got) == sorted(f"u{i}" for i in range(8))
    assert broker.hiccups == 0  # the failure path actually ran


def test_scaler_window_falls_back_to_backlog_drain_rate(tmp_path):
    """mode='process' replicas record into their OWN registries, so the
    controller sees no predict samples.  A draining backlog must then
    read as a finite drain-rate sojourn estimate — not service_rate=0
    => est=inf 'stalled_backlog' scaling a healthy fleet to max."""
    broker = InMemoryBroker()
    for i in range(100):
        broker.xadd(STREAM, {"i": str(i)})
    ctrl = FleetController(
        ClusterServingHelper(model_path=None, batch_size=4,
                             log_dir=str(tmp_path)),
        broker, model_factory=_CountingModel, interval=60.0)
    try:
        ctrl._gather_window()  # baseline window
        time.sleep(0.05)
        # other processes' replicas drain 60 records
        drained = broker.claim(STREAM, "elsewhere", 60, lease_ms=5000)
        broker.release(STREAM, "elsewhere", [r[0] for r in drained],
                       done=True)
        sig = ctrl._gather_window()
    finally:
        ctrl.stop()
    assert sig.queue_depth == 40
    assert sig.service_rate > 0, "drain-rate fallback did not engage"
    assert ctrl.scaler.estimate_p99_s(sig) != float("inf")


# ---------------------------------------------------------------------------
# Config knobs + bench guard
# ---------------------------------------------------------------------------


def test_zooconfig_fleet_knobs_validated_eagerly(monkeypatch):
    from analytics_zoo_tpu.common.engine import ZooConfig

    cfg = ZooConfig()
    assert cfg.serving_batch_budget_ms == 25.0
    assert cfg.slo_p99_ms == 500.0
    assert (cfg.fleet_min_replicas, cfg.fleet_max_replicas) == (1, 4)
    assert cfg.fleet_interval == 1.0 and cfg.fleet_lease_ms == 10_000
    monkeypatch.setenv("ZOO_SERVING_BATCH_BUDGET_MS", "7.5")
    monkeypatch.setenv("ZOO_FLEET_MAX_REPLICAS", "8")
    cfg2 = ZooConfig()
    assert cfg2.serving_batch_budget_ms == 7.5
    assert cfg2.fleet_max_replicas == 8
    for var, bad in [("ZOO_SERVING_BATCH_BUDGET_MS", "-1"),
                     ("ZOO_SLO_P99_MS", "nope"),
                     ("ZOO_FLEET_MIN_REPLICAS", "0"),
                     ("ZOO_FLEET_LEASE_MS", "50"),
                     ("ZOO_FLEET_INTERVAL", "0")]:
        monkeypatch.setenv(var, bad)
        with pytest.raises(ValueError, match=var):
            ZooConfig()
        monkeypatch.delenv(var)
    # explicit argument beats env, and min > max is rejected
    with pytest.raises(ValueError, match="MAX_REPLICAS"):
        ZooConfig(fleet_min_replicas=5, fleet_max_replicas=2)


def test_helper_fleet_knobs_env_and_override(monkeypatch, tmp_path):
    monkeypatch.setenv("ZOO_SERVING_BATCH_BUDGET_MS", "12.5")
    monkeypatch.setenv("ZOO_FLEET_LEASE_MS", "2500")
    h = ClusterServingHelper(model_path=None, log_dir=str(tmp_path))
    assert h.batch_budget_ms == 12.5 and h.lease_ms == 2500
    h2 = ClusterServingHelper(model_path=None, batch_budget_ms=3.0,
                              lease_ms=700, log_dir=str(tmp_path))
    assert h2.batch_budget_ms == 3.0 and h2.lease_ms == 700
    monkeypatch.setenv("ZOO_FLEET_LEASE_MS", "bogus")
    with pytest.raises(ValueError, match="ZOO_FLEET_LEASE_MS"):
        ClusterServingHelper(model_path=None, log_dir=str(tmp_path))
    # documented precedence: an explicit override wins WITHOUT parsing
    # the (bad) env var at all
    h3 = ClusterServingHelper(model_path=None, lease_ms=700,
                              log_dir=str(tmp_path))
    assert h3.lease_ms == 700


def test_fleet_scaling_bench_quick_tier():
    """CI guard (the --fleet bench's scaling half): a fleet of 2 over
    ONE broker sustains >= 1.8x the single-replica throughput on the
    synthetic — the claim protocol + continuous batching tax is
    bounded at 10%."""
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    try:
        from bench import fleet_scaling_bench
    finally:
        sys.path.pop(0)
    out = fleet_scaling_bench(quick=True)
    assert out["scaling_2x_vs_1x"] >= 1.8, out
