"""Distributed telemetry plane (ISSUE 2): mergeable snapshots and the
driver-side aggregator, the `__zoo_telemetry__` actor/worker control
frame, the HTTP scrape endpoints, the health rollup behind /healthz, and
the crash flight recorder."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from analytics_zoo_tpu.metrics import (
    FlightRecorder,
    HealthRegistry,
    MetricsRegistry,
    MetricsServer,
    StragglerDetector,
    TelemetryAggregator,
    Tracer,
    get_health,
    merge_samples,
    set_flight_recorder,
    set_registry,
    telemetry_snapshot,
)

metrics_mark = pytest.mark.metrics


@pytest.fixture()
def fresh_registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    try:
        yield reg
    finally:
        set_registry(prev)


@pytest.fixture()
def fresh_flight():
    fr = FlightRecorder(capacity=256)
    prev = set_flight_recorder(fr)
    try:
        yield fr
    finally:
        set_flight_recorder(prev)


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


# ---------------------------------------------------------------------------
# snapshot + merge semantics
# ---------------------------------------------------------------------------


def _metered_registry(c_val, h_obs):
    reg = MetricsRegistry()
    reg.counter("work_total", "items", ("kind",)).labels(
        kind="a").inc(c_val)
    reg.gauge("depth", "backlog").set(c_val)
    h = reg.histogram("lat_seconds", "", buckets=(0.1, 1.0))
    for v in h_obs:
        h.observe(v)
    return reg


@metrics_mark
class TestMergeSemantics:
    def test_snapshot_is_json_roundtrippable(self):
        snap = telemetry_snapshot(_metered_registry(3, [0.05, 5.0]),
                                  health=HealthRegistry())
        snap2 = json.loads(json.dumps(snap))  # +Inf encoded as null
        hist = [s for s in snap2["samples"]
                if s["kind"] == "histogram"][0]
        assert hist["buckets"][-1][0] is None
        assert hist["buckets"][-1][1] == hist["count"] == 2

    def test_counters_sum_histograms_bucket_merge(self):
        h = HealthRegistry()
        a = telemetry_snapshot(_metered_registry(3, [0.05]), health=h)
        b = telemetry_snapshot(_metered_registry(5, [0.5, 5.0]), health=h)
        totals = {s["name"]: s for s in merge_samples(
            [a["samples"], b["samples"]])}
        assert totals["work_total"]["value"] == 8
        assert totals["work_total"]["labels"] == {"kind": "a"}
        merged = totals["lat_seconds"]
        assert merged["count"] == 3
        assert [cum for _, cum in merged["buckets"]] == [1, 2, 3]
        # gauges have no meaningful cross-source total
        assert "depth" not in totals

    def test_conflicting_histogram_buckets_not_merged(self):
        h = HealthRegistry()
        a = telemetry_snapshot(_metered_registry(1, [0.05]), health=h)
        other = MetricsRegistry()
        other.histogram("lat_seconds", "", buckets=(7.0,)).observe(1.0)
        b = telemetry_snapshot(other, health=h)
        totals = {s["name"] for s in merge_samples(
            [a["samples"], b["samples"]])}
        assert "lat_seconds" not in totals  # silently adding would lie

    def test_aggregator_labels_per_source_and_replaces(self):
        h = HealthRegistry()
        agg = TelemetryAggregator(registry=MetricsRegistry())
        agg.ingest(telemetry_snapshot(_metered_registry(3, [0.05]),
                                      health=h), actor="a0")
        agg.ingest(telemetry_snapshot(_metered_registry(5, []),
                                      health=h), actor="a1")
        doc = agg.merged()
        per_source = {(s["labels"].get("actor"), s["name"]): s
                      for s in doc["samples"]}
        assert per_source[("a0", "work_total")]["value"] == 3
        assert per_source[("a1", "work_total")]["value"] == 5
        # gauges stay per-source labeled series
        assert per_source[("a0", "depth")]["value"] == 3
        # re-ingesting the same source REPLACES, never double-counts
        agg.ingest(telemetry_snapshot(_metered_registry(7, []),
                                      health=h), actor="a1")
        totals = {s["name"]: s for s in agg.merged()["totals"]}
        assert totals["work_total"]["value"] == 10
        assert set(agg.merged()["sources"]) == {"actor=a0", "actor=a1"}

    def test_unlabeled_ingest_rejected(self):
        agg = TelemetryAggregator()
        with pytest.raises(ValueError, match="source label"):
            agg.ingest({"samples": []})

    def test_aggregator_prometheus_text_is_valid(self):
        import re

        h = HealthRegistry()
        agg = TelemetryAggregator()
        agg.ingest(telemetry_snapshot(_metered_registry(2, [0.5]),
                                      health=h), host="h1", actor="x")
        text = agg.prometheus_text()
        assert 'work_total{actor="x",host="h1",kind="a"} 2.0' in text
        name_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{|\s)")
        for line in text.splitlines():
            if not line.startswith("#"):
                assert name_re.match(line), line
        inf = [l for l in text.splitlines() if 'le="+Inf"' in l][0]
        cnt = [l for l in text.splitlines()
               if l.startswith("lat_seconds_count")][0]
        assert inf.split()[-1] == cnt.split()[-1]


# ---------------------------------------------------------------------------
# health model
# ---------------------------------------------------------------------------


@metrics_mark
class TestHealthRegistry:
    def test_stale_rollup_and_recovery(self, fresh_flight):
        now = [0.0]
        h = HealthRegistry(clock=lambda: now[0])
        h.register("serving_loop", stale_after=5.0)
        h.register("infeed", stale_after=50.0)
        assert h.status()["healthy"]
        now[0] = 10.0  # serving_loop silent past its budget
        st = h.status()
        assert not st["healthy"]
        assert not st["components"]["serving_loop"]["healthy"]
        assert st["components"]["infeed"]["healthy"]
        h.heartbeat("serving_loop")
        assert h.status()["healthy"]
        # both transitions landed in the flight ring
        trans = [(e["component"], e["state"])
                 for e in fresh_flight.events("health")]
        assert trans == [("serving_loop", "stale"),
                         ("serving_loop", "healthy")]

    def test_explicit_status_overrides_age(self):
        now = [0.0]
        h = HealthRegistry(clock=lambda: now[0])
        h.set_status("actor:PS-0", True)
        now[0] = 1e6  # idle forever is fine for a connection
        assert h.status()["healthy"]
        h.set_status("actor:PS-0", False)
        assert not h.status()["healthy"]
        h.heartbeat("actor:PS-0")  # a beat clears the forced verdict
        assert h.status()["healthy"]

    def test_unregister_removes_component(self):
        h = HealthRegistry()
        h.set_status("x", False)
        assert not h.status()["healthy"]
        h.unregister("x")
        assert h.status()["healthy"] and h.status()["components"] == {}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


@metrics_mark
class TestFlightRecorder:
    def test_ring_keeps_newest_counts_drops(self):
        fr = FlightRecorder(capacity=3)
        for i in range(7):
            fr.record("step", i=i)
        assert [e["i"] for e in fr.events()] == [4, 5, 6]
        assert fr.dropped == 4

    def test_disabled_records_nothing(self):
        fr = FlightRecorder(enabled=False)
        assert fr.record("step") is None
        assert fr.events() == []

    def test_dump_once_per_reason_atomic(self, tmp_path):
        fr = FlightRecorder(capacity=8, dump_dir=str(tmp_path))
        fr.record("step", i=1)
        p = fr.dump("crash")
        assert p and json.load(open(p))["events"][0]["i"] == 1
        assert fr.dump("crash") is None  # once per reason
        assert fr.dump("exit") is not None  # distinct reason still dumps

    def test_record_exception_carries_type_and_traceback(self):
        fr = FlightRecorder()
        try:
            raise RuntimeError("device burned down")
        except RuntimeError as e:
            fr.record_exception(e, where="serving.step")
        (ev,) = fr.events("exception")
        assert ev["exc_type"] == "RuntimeError"
        assert "device burned down" in ev["message"]
        assert "RuntimeError" in ev["traceback"]
        assert ev["where"] == "serving.step"

    def test_excepthook_chain_dumps_and_calls_previous(self, tmp_path):
        import sys

        fr = FlightRecorder(capacity=8, dump_dir=str(tmp_path))
        seen = []
        prev_hook = sys.excepthook
        sys.excepthook = lambda *a: seen.append(a)
        try:
            fr.install()
            try:
                raise ValueError("unhandled boom")
            except ValueError as e:
                sys.excepthook(type(e), e, e.__traceback__)
            assert len(seen) == 1  # prior hook still ran
            (dump,) = [f for f in tmp_path.iterdir()
                       if "crash" in f.name]
            doc = json.load(open(dump))
            assert doc["reason"] == "crash"
            assert any(e["kind"] == "exception" and
                       "unhandled boom" in e["message"]
                       for e in doc["events"])
        finally:
            sys.excepthook = prev_hook

    def test_straggler_detector_flags_against_rolling_p50(self):
        sd = StragglerDetector(k=3.0, window=32, min_steps=8)
        for _ in range(8):
            assert not sd.observe(0.1)  # warmup: no verdicts
        assert sd.observe(0.5)          # 5x the p50
        assert not sd.observe(0.12)     # normal step
        assert sd.rolling_p50() == pytest.approx(0.1, abs=0.05)
        with pytest.raises(ValueError):
            StragglerDetector(k=1.0)


# ---------------------------------------------------------------------------
# MetricsServer endpoints (acceptance: port 0, prometheus parse, healthz
# flip, flightz carries a crashed step's events)
# ---------------------------------------------------------------------------


@metrics_mark
class TestMetricsServer:
    def test_endpoints_end_to_end(self):
        import re

        now = [0.0]
        reg = _metered_registry(4, [0.05, 0.5])
        health = HealthRegistry(clock=lambda: now[0])
        health.register("serving_loop", stale_after=5.0)
        flight = FlightRecorder(capacity=16)
        tracer = Tracer(jax_bridge=False)
        srv = MetricsServer(port=0, host="127.0.0.1", registry=reg,
                            health=health, flight=flight,
                            tracer=tracer).start()
        try:
            assert srv.port != 0  # ephemeral bind resolved
            # /metrics parses as Prometheus text exposition
            status, text = _get(srv.url + "/metrics")
            assert status == 200
            line_re = re.compile(
                r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
                r'(\{[a-zA-Z_][a-zA-Z0-9_]*=".*"(,[a-zA-Z_]'
                r'[a-zA-Z0-9_]*=".*")*\})? '
                r"[-+0-9.eInf]+$")
            body = [l for l in text.splitlines() if not l.startswith("#")]
            assert body
            for line in body:
                assert line_re.match(line), line
            assert 'work_total{kind="a"} 4.0' in body
            # /varz is the JSONL snapshot shape + health/trace/flight
            status, varz = _get(srv.url + "/varz")
            doc = json.loads(varz)
            assert {s["name"] for s in doc["samples"]} >= {
                "work_total", "lat_seconds"}
            assert doc["health"]["healthy"] is True
            assert doc["trace"]["dropped_spans"] == 0
            # /trace is chrome-trace JSON
            with tracer_span(tracer):
                pass
            _, tr = _get(srv.url + "/trace")
            assert json.loads(tr)["traceEvents"][0]["name"] == "probe"
            # /healthz flips 200 -> 503 when a heartbeat goes stale
            assert _get(srv.url + "/healthz")[0] == 200
            now[0] = 60.0
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(srv.url + "/healthz")
            assert err.value.code == 503
            stale = json.loads(err.value.read())
            assert not stale["components"]["serving_loop"]["healthy"]
            # /flightz returns what a simulated crashed step recorded
            flight.record("step", loop="serving", records=8)
            try:
                raise RuntimeError("XLA halted")
            except RuntimeError as e:
                flight.record_exception(e, where="serving.step")
            _, fl = _get(srv.url + "/flightz")
            events = json.loads(fl)["events"]
            assert events[0]["kind"] == "step"
            assert events[-1]["kind"] == "exception"
            assert "XLA halted" in events[-1]["message"]
            # unknown path: 404 with the endpoint directory
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(srv.url + "/nope")
            assert err.value.code == 404
        finally:
            srv.stop()

    def test_metrics_includes_aggregated_sources(self):
        h = HealthRegistry()
        agg = TelemetryAggregator()
        agg.ingest(telemetry_snapshot(_metered_registry(9, []), health=h),
                   actor="w0")
        # the DRIVER registry shares family names with the sources: the
        # exposition must still emit ONE group with ONE TYPE line per
        # family, or a Prometheus parser rejects the whole body
        driver_reg = _metered_registry(2, [0.5])
        srv = MetricsServer(port=0, host="127.0.0.1",
                            registry=driver_reg,
                            aggregator=agg).start()
        try:
            _, text = _get(srv.url + "/metrics")
            assert 'work_total{actor="w0",kind="a"} 9.0' in text
            assert 'work_total{kind="a"} 2.0' in text  # driver's own
            type_lines = [l for l in text.splitlines()
                          if l.startswith("# TYPE work_total")]
            assert len(type_lines) == 1
            # family groups are contiguous (exposition-format contract)
            names = [l.split("{")[0].split(" ")[0].split("_bucket")[0]
                     for l in text.splitlines() if not l.startswith("#")]
            seen, prev = set(), None
            for n in names:
                assert not (n != prev and n in seen), f"{n} split"
                seen.add(n)
                prev = n
            _, varz = _get(srv.url + "/varz")
            doc = json.loads(varz)
            assert doc["aggregate"]["totals"][0]["name"] in (
                "depth", "lat_seconds", "work_total")
            assert "actor=w0" in doc["aggregate"]["sources"]
        finally:
            srv.stop()

    def test_env_opt_in(self, monkeypatch):
        import analytics_zoo_tpu.metrics.http as http_mod

        monkeypatch.setattr(http_mod, "_env_server", None)
        monkeypatch.delenv("ZOO_METRICS_PORT", raising=False)
        assert http_mod.maybe_start_from_env() is None
        monkeypatch.setenv("ZOO_METRICS_PORT", "0")
        monkeypatch.setenv("ZOO_METRICS_HOST", "127.0.0.1")
        srv = http_mod.maybe_start_from_env()
        try:
            assert srv is not None
            assert http_mod.maybe_start_from_env() is srv  # idempotent
            assert _get(srv.url + "/metrics")[0] == 200
        finally:
            srv.stop()
            monkeypatch.setattr(http_mod, "_env_server", None)


def tracer_span(tracer):
    from analytics_zoo_tpu.metrics import span

    return span("probe", tracer=tracer)


# ---------------------------------------------------------------------------
# serving loop wiring: crashed step lands in the flight ring
# ---------------------------------------------------------------------------


@metrics_mark
class TestServingFlightWiring:
    def test_crashed_step_records_exception(self, tmp_path,
                                            fresh_registry, fresh_flight):
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Dense,
            Flatten,
        )
        from analytics_zoo_tpu.pipeline.api.keras.topology import (
            Sequential,
        )
        from analytics_zoo_tpu.serving import (
            ClusterServing,
            ClusterServingHelper,
            InMemoryBroker,
            InputQueue,
        )

        m = Sequential()
        m.add(Flatten(input_shape=(4, 4, 1)))
        m.add(Dense(5, activation="softmax"))
        m.build_params()
        path = str(tmp_path / "model.zoo")
        m.save(path)
        broker = InMemoryBroker()
        serving = ClusterServing(
            ClusterServingHelper(model_path=path, batch_size=4,
                                 data_shape=(4, 4, 1),
                                 log_dir=str(tmp_path / "logs")),
            broker=broker)
        inq = InputQueue(broker=broker)
        inq.enqueue_image("ok", np.zeros((4, 4, 1), np.float32))
        assert serving.step(block_ms=0) == 1
        # a healthy non-empty cycle recorded one step event
        (step_ev,) = fresh_flight.events("step")
        assert step_ev["loop"] == "serving" and step_ev["served"] == 1
        # now crash the model mid-step: the exception must land in the
        # ring before propagating
        serving.model = _Boom()
        inq.enqueue_image("bad", np.zeros((4, 4, 1), np.float32))
        with pytest.raises(RuntimeError, match="model exploded"):
            serving.step(block_ms=0)
        (exc_ev,) = fresh_flight.events("exception")
        assert exc_ev["where"] == "serving.step"
        assert "model exploded" in exc_ev["message"]
        serving.summary.close()


class _Boom:
    def predict(self, x):
        raise RuntimeError("model exploded")


# ---------------------------------------------------------------------------
# acceptance e2e: >=2 actor processes doing metered work, snapshots
# pulled over the __zoo_telemetry__ frame, merged driver-side
# ---------------------------------------------------------------------------


@metrics_mark
class TestActorTelemetryE2E:
    def test_two_actor_pull_merge(self, fresh_registry):
        from analytics_zoo_tpu.parallel.actors import (
            ActorContext,
            get,
            remote,
        )

        @remote
        class Metered:
            def __init__(self):
                from analytics_zoo_tpu.metrics import get_registry

                self.reg = get_registry()

            def work(self, n):
                c = self.reg.counter("zoo_e2e_work_total", "work",
                                     ("kind",))
                h = self.reg.histogram("zoo_e2e_work_seconds", "",
                                       buckets=(0.01, 0.1))
                for _ in range(n):
                    c.labels(kind="unit").inc()
                    h.observe(0.05)
                return n

        ctx = ActorContext.init()
        try:
            a = Metered.remote()
            b = Metered.remote()
            assert get([a.work.remote(3), b.work.remote(5)],
                       timeout=60) == [3, 5]
            # driver-side metric so the merged doc carries the driver
            # registry alongside
            fresh_registry.counter("zoo_e2e_driver_total", "").inc()
            doc = ctx.metrics(timeout=60)
            assert not doc.get("errors")
            # summed counters across the two actor processes
            totals = {s["name"]: s for s in doc["totals"]}
            assert totals["zoo_e2e_work_total"]["value"] == 8
            assert totals["zoo_e2e_work_total"]["labels"] == {
                "kind": "unit"}
            # bucket-merged histogram: all 8 obs in the (0.01, 0.1] bucket
            merged_h = totals["zoo_e2e_work_seconds"]
            assert merged_h["count"] == 8
            assert [cum for _, cum in merged_h["buckets"]] == [0, 8, 8]
            # per-source series labeled actor=Metered-<i>
            per_source = {
                (s["labels"]["actor"], s["name"]): s["value"]
                for s in doc["samples"]
                if s["name"] == "zoo_e2e_work_total"}
            assert per_source[("Metered-0", "zoo_e2e_work_total")] == 3
            assert per_source[("Metered-1", "zoo_e2e_work_total")] == 5
            # both actor processes report healthy in their snapshots
            assert all(src["healthy"]
                       for src in doc["sources"].values())
            # the driver registry rides alongside
            assert any(s["name"] == "zoo_e2e_driver_total"
                       for s in doc["driver"]["samples"])
            # actor connections appear in the DRIVER health rollup
            comps = get_health().status()["components"]
            assert "actor:Metered-0" in comps
            assert comps["actor:Metered-0"]["healthy"]
        finally:
            ctx.stop()

    def test_terminated_actor_skipped_by_metrics_pull(self,
                                                      fresh_registry):
        from analytics_zoo_tpu.parallel.actors import (
            ActorContext,
            remote,
        )

        @remote
        class Idle:
            def ping(self):
                return "pong"

        ctx = ActorContext.init()
        try:
            a = Idle.remote()
            b = Idle.remote()
            assert a.ping.remote().get(timeout=60) == "pong"
            a.terminate()  # deliberate shutdown: not an error source
            doc = ctx.metrics(timeout=60)
            assert not doc.get("errors")
            assert set(doc["sources"]) == {"actor=Idle-1"}
            # ...and the driver health rollup dropped its component
            assert "actor:Idle-0" not in get_health().status()[
                "components"]
        finally:
            ctx.stop()

    def test_worker_server_telemetry_frame(self):
        from analytics_zoo_tpu.metrics import get_registry
        from analytics_zoo_tpu.parallel.actor_worker import (
            fetch_worker_telemetry,
            start_worker_server,
        )

        srv = start_worker_server(0, bind="127.0.0.1", block=False)
        try:
            addr = f"127.0.0.1:{srv.getsockname()[1]}"
            get_registry().counter("zoo_worker_probe_total", "").inc(2)
            snap = fetch_worker_telemetry(addr, timeout=30)
            assert snap["health"]["healthy"] in (True, False)
            names = {s["name"] for s in snap["samples"]}
            # the worker "server" here runs in-process, so its snapshot
            # sees this process's registry — the frame works end to end
            assert "zoo_worker_probe_total" in names
        finally:
            srv.close()

    def test_worker_telemetry_requires_auth(self):
        from analytics_zoo_tpu.parallel.actor_worker import (
            fetch_worker_telemetry,
            start_worker_server,
        )

        srv = start_worker_server(0, bind="127.0.0.1", block=False,
                                  secret="sesame")
        try:
            addr = f"127.0.0.1:{srv.getsockname()[1]}"
            with pytest.raises(RuntimeError, match="secret"):
                fetch_worker_telemetry(addr, timeout=10)
            snap = fetch_worker_telemetry(addr, secret="sesame",
                                          timeout=30)
            assert "samples" in snap
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# tools/metrics_dump.py --url scrapes a live /varz
# ---------------------------------------------------------------------------


@metrics_mark
class TestMetricsDumpUrl:
    def _load_tool(self):
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "metrics_dump", os.path.join(os.path.dirname(__file__), "..",
                                         "tools", "metrics_dump.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_scrapes_live_varz(self, capsys):
        import sys

        srv = MetricsServer(port=0, host="127.0.0.1",
                            registry=_metered_registry(6, [0.05, 0.5]),
                            health=HealthRegistry(),
                            flight=FlightRecorder(),
                            tracer=Tracer(jax_bridge=False)).start()
        mod = self._load_tool()
        old_argv = sys.argv
        try:
            # host:port shorthand: /varz implied
            sys.argv = ["metrics_dump.py", "--url",
                        f"127.0.0.1:{srv.port}"]
            mod.main()
        finally:
            sys.argv = old_argv
            srv.stop()
        out = capsys.readouterr().out
        assert "work_total" in out and "lat_seconds" in out
        assert "1 snapshot(s)" in out

    def test_path_and_url_mutually_exclusive(self):
        import sys

        mod = self._load_tool()
        old_argv = sys.argv
        sys.argv = ["metrics_dump.py"]
        try:
            with pytest.raises(SystemExit):
                mod.main()
        finally:
            sys.argv = old_argv
