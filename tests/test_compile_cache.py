"""Compile plane unit tests (common/compile_cache.py): enablement
resolution, idempotence, and the timed_compile hit/miss telemetry."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.common import compile_cache
from analytics_zoo_tpu.metrics import (
    MetricsRegistry,
    set_registry,
    snapshot,
)


@pytest.fixture
def fresh_registry():
    reg = MetricsRegistry(enabled=True)
    prev = set_registry(reg)
    try:
        yield reg
    finally:
        set_registry(prev)


@pytest.fixture(autouse=True)
def cache_teardown():
    try:
        yield
    finally:
        compile_cache.disable_persistent_cache()


def _samples(reg, name):
    return [s for s in snapshot(reg)["samples"] if s["name"] == name]


def test_disabled_without_env_or_path(monkeypatch):
    monkeypatch.delenv("ZOO_COMPILE_CACHE", raising=False)
    assert compile_cache.maybe_enable_persistent_cache(None) is None
    assert compile_cache.cache_dir() is None


def test_enable_from_env_and_idempotence(tmp_path, monkeypatch):
    d = str(tmp_path / "cc")
    monkeypatch.setenv("ZOO_COMPILE_CACHE", d)
    got = compile_cache.maybe_enable_persistent_cache()
    assert got == os.path.abspath(d)
    assert os.path.isdir(d)
    # idempotent: re-enable with no path keeps the enabled dir
    monkeypatch.delenv("ZOO_COMPILE_CACHE")
    assert compile_cache.maybe_enable_persistent_cache() == got
    assert compile_cache.cache_dir() == got


def test_explicit_path_beats_env(tmp_path, monkeypatch):
    monkeypatch.setenv("ZOO_COMPILE_CACHE", str(tmp_path / "env"))
    explicit = str(tmp_path / "explicit")
    assert compile_cache.maybe_enable_persistent_cache(explicit) \
        == os.path.abspath(explicit)


def test_timed_compile_records_miss_then_hit(tmp_path, fresh_registry):
    """First compile of a program = miss (writes the cache entry); an
    identical re-lower+compile = hit (served from disk, no new entry).
    Both land in zoo_compile_seconds."""
    compile_cache.maybe_enable_persistent_cache(str(tmp_path / "cc"))

    def f(a, b):
        return jnp.tanh(a @ b).sum()

    args = (jnp.ones((8, 8)), jnp.ones((8, 8)))
    compile_cache.timed_compile(jax.jit(f).lower(*args), "probe")
    compile_cache.timed_compile(jax.jit(f).lower(*args), "probe")

    (hist,) = _samples(fresh_registry, "zoo_compile_seconds")
    assert hist["labels"] == {"label": "probe"}
    assert hist["count"] == 2
    hits = _samples(fresh_registry, "zoo_compile_cache_hits_total")
    misses = _samples(fresh_registry, "zoo_compile_cache_misses_total")
    assert sum(s["value"] for s in misses) == 1
    assert sum(s["value"] for s in hits) == 1


def test_timed_compile_without_cache_counts_misses(fresh_registry):
    """No persistent cache enabled: every AOT compile is a miss (and the
    executable still comes back usable)."""
    def g(a):
        return (a * 2.0).sum()

    exe = compile_cache.timed_compile(
        jax.jit(g).lower(jnp.ones((4,))), "nocache")
    assert float(exe(jnp.ones((4,)))) == 8.0
    hits = _samples(fresh_registry, "zoo_compile_cache_hits_total")
    misses = _samples(fresh_registry, "zoo_compile_cache_misses_total")
    assert sum(s["value"] for s in misses) == 1
    assert sum(s["value"] for s in hits) == 0


def test_zoo_config_resolves_dispatch_and_cache_knobs(monkeypatch):
    from analytics_zoo_tpu.common.engine import ZooConfig

    monkeypatch.setenv("ZOO_STEPS_PER_DISPATCH", "8")
    monkeypatch.setenv("ZOO_COMPILE_CACHE", "/tmp/zoo-cc-env")
    cfg = ZooConfig()
    assert cfg.steps_per_dispatch == 8
    assert cfg.compile_cache == "/tmp/zoo-cc-env"
    # explicit beats env (the documented precedence)
    cfg2 = ZooConfig(steps_per_dispatch=2, compile_cache="/tmp/other")
    assert cfg2.steps_per_dispatch == 2
    assert cfg2.compile_cache == "/tmp/other"
    monkeypatch.setenv("ZOO_STEPS_PER_DISPATCH", "0")
    with pytest.raises(ValueError):
        ZooConfig()
