"""Minimum end-to-end slice (SURVEY.md §7 step 4): LeNet-style models via
Sequential + compile/fit on a CPU mesh — the analogue of the reference's
test_simple_integration.py (pyzoo/test/zoo/pipeline/api/test_simple_integration.py)."""

import numpy as np
import pytest

from analytics_zoo_tpu.pipeline.api.keras import Input, Model, Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Convolution2D,
    Dense,
    Dropout,
    Flatten,
    MaxPooling2D,
)


def make_blobs(n=512, dim=12, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(classes, dim)) * 3.0
    y = rng.integers(0, classes, size=n)
    x = centers[y] + rng.normal(size=(n, dim))
    return x.astype(np.float32), y.astype(np.int32)


def test_mlp_fit_learns(zoo_ctx):
    x, y = make_blobs()
    model = Sequential()
    model.add(Dense(32, activation="relu", input_shape=(12,)))
    model.add(Dropout(0.1))
    model.add(Dense(4, activation="softmax"))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, batch_size=64, nb_epoch=8)
    results = model.evaluate(x, y, batch_size=64)
    assert results["accuracy"] > 0.9, results
    # fit must actually reduce loss
    hist = model._estimator.history
    assert hist[-1]["loss"] < hist[0]["loss"]


def test_lenet_conv_fit(zoo_ctx):
    rng = np.random.default_rng(1)
    n = 256
    x = rng.normal(size=(n, 12, 12, 1)).astype(np.float32)
    # learnable rule: class = quadrant with the largest mean intensity
    q = np.stack([
        x[:, :6, :6, 0].mean(axis=(1, 2)),
        x[:, :6, 6:, 0].mean(axis=(1, 2)),
        x[:, 6:, :6, 0].mean(axis=(1, 2)),
        x[:, 6:, 6:, 0].mean(axis=(1, 2)),
    ], axis=1)
    y = np.argmax(q, axis=1).astype(np.int32)

    model = Sequential()
    model.add(Convolution2D(8, 3, 3, activation="relu",
                            input_shape=(12, 12, 1)))
    model.add(MaxPooling2D())
    model.add(Flatten())
    model.add(Dense(32, activation="relu"))
    model.add(Dense(4, activation="softmax"))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, batch_size=32, nb_epoch=15)
    results = model.evaluate(x, y, batch_size=32)
    assert results["accuracy"] > 0.8, results


def test_functional_model_multi_input(zoo_ctx):
    from analytics_zoo_tpu.pipeline.api.keras import merge

    n = 256
    rng = np.random.default_rng(2)
    a = rng.normal(size=(n, 8)).astype(np.float32)
    b = rng.normal(size=(n, 8)).astype(np.float32)
    y = (np.sum(a * b, axis=1) > 0).astype(np.float32)[:, None]

    ia, ib = Input(shape=(8,)), Input(shape=(8,))
    h = merge([ia, ib], mode="mul")
    h = Dense(16, activation="relu")(h)
    out = Dense(1, activation="sigmoid")(h)
    model = Model([ia, ib], out)
    model.compile(optimizer="adam", loss="binary_crossentropy",
                  metrics=["binary_accuracy"])
    model.fit([a, b], y, batch_size=32, nb_epoch=30)
    results = model.evaluate([a, b], y, batch_size=32)
    assert results["binary_accuracy"] > 0.85, results


def test_predict_shapes_and_padding(zoo_ctx):
    x, y = make_blobs(n=130)  # not a multiple of 8 devices
    model = Sequential()
    model.add(Dense(4, activation="softmax", input_shape=(12,)))
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
    preds = model.predict(x, batch_size=64)
    assert preds.shape == (130, 4)
    np.testing.assert_allclose(preds.sum(axis=1), 1.0, rtol=1e-4)


def test_save_load_roundtrip(zoo_ctx, tmp_path):
    x, y = make_blobs(n=128)
    model = Sequential()
    model.add(Dense(16, activation="relu", input_shape=(12,)))
    model.add(Dense(4, activation="softmax"))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.fit(x, y, batch_size=32, nb_epoch=2)
    p1 = model.predict(x, batch_size=32)

    path = str(tmp_path / "model.zoo")
    model.save(path)
    from analytics_zoo_tpu.pipeline.api.keras import KerasNet

    loaded = KerasNet.load(path)
    p2 = loaded.predict(x, batch_size=32)
    np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-6)


def test_evaluate_padding_unbiased(zoo_ctx):
    """Padded rows in the last eval batch must not bias loss/metrics."""
    x, y = make_blobs(n=130)  # 130 % 64 = 2 → last batch padded to 8
    model = Sequential()
    model.add(Dense(4, activation="softmax", input_shape=(12,)))
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    res = model.evaluate(x, y, batch_size=64)

    # manual reference with numpy
    probs = model.predict(x, batch_size=64)
    eps = 1e-7
    ll = -np.log(np.clip(probs[np.arange(130), y], eps, 1.0))
    acc = float(np.mean(np.argmax(probs, -1) == y))
    np.testing.assert_allclose(res["loss"], ll.mean(), rtol=1e-4)
    np.testing.assert_allclose(res["accuracy"], acc, rtol=1e-6)


def test_fit_with_validation(zoo_ctx):
    x, y = make_blobs(n=256)
    model = Sequential()
    model.add(Dense(16, activation="relu", input_shape=(12,)))
    model.add(Dense(4, activation="softmax"))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, batch_size=64, nb_epoch=5,
              validation_data=(x[:100], y[:100]))
    # model usable after training with validation enabled (no deleted
    # donated buffers)
    preds = model.predict(x[:10], batch_size=64)
    assert preds.shape == (10, 4)


def test_duplicate_layer_names_rejected(zoo_ctx):
    a = Sequential()
    a.add(Dense(4, input_shape=(3,)))
    b = Sequential()
    b.add(Dense(4, input_shape=(3,)))
    c = Sequential()
    c.add(a.layers[0])
    with pytest.raises(ValueError, match="duplicate layer names"):
        c.add(b.layers[0])


def test_summary_runs(zoo_ctx):
    model = Sequential()
    model.add(Dense(16, input_shape=(12,)))
    model.add(Dense(4))
    text = model.summary()
    assert "Total params" in text


def test_consecutive_fits_both_train(zoo_ctx):
    """Each fit() call must train nb_epoch MORE epochs (Keras semantics).
    Regression: MaxEpoch was absolute, so a second fit(nb_epoch=1) trained
    zero steps — which would have silently voided warm-up + timed benchmark
    patterns (bench.py)."""
    import numpy as np

    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)
    model = Sequential()
    model.add(Dense(2, activation="softmax", input_shape=(8,)))
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
    model.fit(x, y, batch_size=16, nb_epoch=1)
    est = model._estimator
    steps_after_first = est.global_step
    assert steps_after_first == 4
    model.fit(x, y, batch_size=16, nb_epoch=1)
    assert est.global_step == 2 * steps_after_first, (
        "second fit() trained zero steps")
