"""Model-zoo tests — each model trains on a tiny learnable task, the
analogue of the reference's model specs (e.g. NeuralCFSpec, KNRMSpec,
AnomalyDetectorSpec under zoo/src/test)."""

import numpy as np
import pytest


def test_lenet_builds_and_fits(zoo_ctx):
    from analytics_zoo_tpu.models import build_lenet

    model = build_lenet()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 28, 28, 1)).astype(np.float32)
    y = (x[:, :14].mean(axis=(1, 2, 3)) >
         x[:, 14:].mean(axis=(1, 2, 3))).astype(np.int32)
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, batch_size=32, nb_epoch=25)
    assert model.evaluate(x, y, batch_size=32)["accuracy"] > 0.85


def test_resnet_cifar_trains(zoo_ctx):
    from analytics_zoo_tpu.models import ResNet

    model = ResNet.cifar(depth=8, classes=4)
    rng = np.random.default_rng(1)
    x = rng.normal(size=(64, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 4, size=(64,)).astype(np.int32)
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.fit(x, y, batch_size=16, nb_epoch=2)
    hist = model._estimator.history
    assert hist[-1]["loss"] < hist[0]["loss"]  # memorizing random labels


def test_neural_cf_learns_and_recommends(zoo_ctx):
    from analytics_zoo_tpu.models import NeuralCF

    n_users, n_items = 30, 40
    rng = np.random.default_rng(2)
    users = rng.integers(0, n_users, size=(2048,))
    items = rng.integers(0, n_items, size=(2048,))
    # learnable rule: like if (user + item) even
    labels = ((users + items) % 2 == 0).astype(np.int32)

    ncf = NeuralCF(n_users, n_items, class_num=2, user_embed=8, item_embed=8,
                   hidden_layers=(16, 8), mf_embed=8)
    ncf.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    ncf.fit([users, items], labels, batch_size=128, nb_epoch=20)
    res = ncf.evaluate([users, items], labels, batch_size=128)
    assert res["accuracy"] > 0.9, res

    recs = ncf.recommend_for_user(3, np.arange(n_items), max_items=5)
    assert len(recs) == 5
    # top recommendations should be items with (3+item) even
    assert all((3 + item) % 2 == 0 for item, _ in recs[:3])


def test_wide_and_deep(zoo_ctx):
    from analytics_zoo_tpu.models import (
        ColumnFeatureInfo,
        WideAndDeep,
        to_wide_deep_features,
    )

    info = ColumnFeatureInfo(
        wide_base_cols=["gender"], wide_base_dims=[2],
        embed_cols=["occupation"], embed_in_dims=[10], embed_out_dims=[4],
        continuous_cols=["age"],
    )
    rng = np.random.default_rng(3)
    n = 1024
    rows = {
        "gender": rng.integers(0, 2, n),
        "occupation": rng.integers(0, 10, n),
        "age": rng.normal(size=n).astype(np.float32),
    }
    # rule: positive iff (occupation<5) xor age>0 — both features reach the
    # deep arm, so the MLP can express the interaction
    labels = ((rows["occupation"] < 5) ^ (rows["age"] > 0)).astype(np.int32)
    feats = to_wide_deep_features(rows, info)
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    wnd = WideAndDeep(class_num=2, column_info=info, hidden_layers=(16, 8))
    wnd.compile(optimizer=Adam(lr=0.01),
                loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    wnd.fit(feats, labels, batch_size=128, nb_epoch=25)
    res = wnd.evaluate(feats, labels, batch_size=128)
    assert res["accuracy"] > 0.9, res


def test_session_recommender(zoo_ctx):
    from analytics_zoo_tpu.models import SessionRecommender

    n_items = 20
    rng = np.random.default_rng(4)
    sess = rng.integers(1, n_items, size=(512, 4))
    labels = sess[:, -1]  # predict the last item seen

    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    sr = SessionRecommender(n_items, item_embed=16, rnn_hidden_layers=(16,),
                            session_length=4)
    sr.compile(optimizer=Adam(lr=0.01),
               loss="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    sr.fit(sess, labels, batch_size=64, nb_epoch=30)
    res = sr.evaluate(sess, labels, batch_size=64)
    assert res["accuracy"] > 0.9, res
    recs = sr.recommend_for_session(sess[:3], max_items=3)
    assert len(recs) == 3 and len(recs[0]) == 3


def test_text_classifier_cnn(zoo_ctx):
    from analytics_zoo_tpu.models import TextClassifier

    rng = np.random.default_rng(5)
    x = rng.integers(0, 50, size=(512, 20))
    y = (np.sum(x == 7, axis=1) > 0).astype(np.int32)  # contains token 7

    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    tc = TextClassifier(class_num=2, token_length=16, sequence_length=20,
                        encoder="cnn", encoder_output_dim=32, vocab_size=50)
    tc.compile(optimizer=Adam(lr=0.01),
               loss="sparse_categorical_crossentropy",
               metrics=["accuracy"])
    tc.fit(x, y, batch_size=64, nb_epoch=25)
    assert tc.evaluate(x, y, batch_size=64)["accuracy"] > 0.9


def test_anomaly_detector(zoo_ctx):
    from analytics_zoo_tpu.models import AnomalyDetector

    t = np.arange(600, dtype=np.float32)
    series = np.sin(t / 10.0)
    series[450] = 5.0  # planted anomaly
    x, y = AnomalyDetector.unroll(series, 10)
    ad = AnomalyDetector(feature_shape=(10, 1), hidden_layers=(8, 8),
                         dropouts=(0.0, 0.0))
    ad.compile(optimizer="adam", loss="mse")
    ad.fit(x, y, batch_size=64, nb_epoch=10)
    preds = np.asarray(ad.predict(x, batch_size=64)).reshape(-1)
    flagged = ad.detect_anomalies(y, preds, anomaly_size=3)
    anomaly_idx = [i for i, (_, _, a) in enumerate(flagged) if a]
    assert any(abs(i - 440) < 12 for i in anomaly_idx), anomaly_idx[:5]


def test_knrm_ranking(zoo_ctx):
    from analytics_zoo_tpu.models import KNRM
    from analytics_zoo_tpu.pipeline.api.keras.objectives import RankHinge

    rng = np.random.default_rng(6)
    vocab, lq, ld = 30, 4, 6
    n_pairs = 256
    # positive doc contains the query tokens, negative doc is random
    q = rng.integers(1, vocab, size=(n_pairs, lq))
    pos = np.concatenate([q, rng.integers(1, vocab, (n_pairs, ld - lq))], 1)
    neg = rng.integers(1, vocab, size=(n_pairs, ld))
    # interleave (pos, neg) pairs for RankHinge
    qs = np.repeat(q, 2, axis=0)
    ds = np.empty((2 * n_pairs, ld), dtype=np.int64)
    ds[0::2], ds[1::2] = pos, neg
    labels = np.zeros((2 * n_pairs, 1), np.float32)

    knrm = KNRM(lq, ld, vocab_size=vocab, embed_size=16)
    knrm.compile(optimizer="adam", loss=RankHinge())
    knrm.fit([qs, ds], labels, batch_size=64, nb_epoch=10)
    s_pos = np.asarray(knrm.predict([q, pos], batch_size=64)).reshape(-1)
    s_neg = np.asarray(knrm.predict([q, neg], batch_size=64)).reshape(-1)
    assert (s_pos > s_neg).mean() > 0.9

    ndcg = knrm.ndcg([[1, 0]], [[2.0, 1.0]], k=2)
    assert ndcg == 1.0


def test_seq2seq_copy_task(zoo_ctx):
    from analytics_zoo_tpu.models import Seq2seq

    rng = np.random.default_rng(7)
    vocab, le, ld = 12, 5, 5
    n = 512
    enc = rng.integers(2, vocab, size=(n, le))
    # target: copy the input sequence; decoder input is shifted (teacher)
    dec_in = np.concatenate([np.ones((n, 1), np.int64), enc[:, :-1]], 1)
    target = enc

    model = Seq2seq(vocab_size=vocab, embed_dim=16, hidden_sizes=(32,))
    from analytics_zoo_tpu.pipeline.api.keras import Input, Model

    e_in = Input(shape=(le,), name="enc_in")
    d_in = Input(shape=(ld,), name="dec_in")
    out = model([e_in, d_in])
    net = Model([e_in, d_in], out)
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    net.compile(optimizer=Adam(lr=0.01),
                loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    net.fit([enc, dec_in], target, batch_size=64, nb_epoch=30)
    res = net.evaluate([enc, dec_in], target, batch_size=64)
    assert res["accuracy"] > 0.8, res

    # greedy inference emits the copy
    toks = model.infer(net.params[model.name], enc[:4], start_sign=1,
                       max_len=le)
    assert (toks == enc[:4]).mean() > 0.5


class TestInceptionV1:
    def test_shapes_and_param_count(self):
        from analytics_zoo_tpu.models.inception import Inception

        net = Inception.v1(classes=1000)
        net.build_params()
        import jax

        n_params = sum(int(np.prod(p.shape))
                       for p in jax.tree_util.tree_leaves(net.params))
        # GoogLeNet no-aux has ~7.0M params (6.99M conv/fc + biases)
        assert 6.5e6 < n_params < 7.5e6, n_params
        x = np.zeros((2, 224, 224, 3), np.float32)
        out, _ = net.forward(net.params, x, state=net.state)
        assert out.shape == (2, 1000)

    def test_trains_on_tiny_task(self):
        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.models.inception import Inception

        init_zoo_context(seed=0)
        net = Inception.v1(classes=2, input_shape=(64, 64, 3),
                           has_dropout=False)
        rng = np.random.default_rng(0)
        n = 32
        x = np.zeros((n, 64, 64, 3), np.float32)
        y = rng.integers(0, 2, size=(n,)).astype(np.int32)
        x[np.arange(n), :, :, 0] += y[:, None, None] * 1.0
        net.compile(optimizer="adam",
                    loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"])
        net.fit(x, y, batch_size=8, nb_epoch=8)
        res = net.evaluate(x, y, batch_size=8)
        assert res["accuracy"] > 0.8, res


def test_resnet_space_to_depth_stem(zoo_ctx):
    """The TPU stem variant: same downstream network, same output shape,
    trains; stem kernel is 4x4x12 instead of 7x7x3."""
    import jax

    from analytics_zoo_tpu.models.resnet import ResNet

    net = ResNet.image_net(18, classes=4, input_shape=(32, 32, 3),
                           stem="space_to_depth")
    params, state = net.build_params(jax.random.PRNGKey(0))
    assert params["stem_conv"]["kernel"].shape == (4, 4, 12, 64)
    rng = np.random.default_rng(0)
    n = 16
    x = rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) > 0).astype(np.int32) * 3
    out, _ = net.forward(params, x, state=state, training=False)
    assert out.shape == (n, 4)
    net.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    net.fit(x, y, batch_size=8, nb_epoch=6)
    hist = net._estimator.history
    assert hist[-1]["loss"] < 0.8 * hist[0]["loss"], hist
