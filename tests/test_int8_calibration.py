"""Calibrated int8 inference (pipeline/inference/quantize.py): activation
calibration + int8 x int8 execution must preserve accuracy (reference
claim: OpenVINO int8 calibration at <= 0.1% drop, wp-bigdl.md:192)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _train_cnn(seed=0, size=12, n=512, epochs=12):
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Convolution2D,
        Dense,
        Flatten,
        MaxPooling2D,
    )

    init_zoo_context(seed=seed)
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, size=n).astype(np.int32)
    x = (rng.random((n, size, size, 3)) * 0.5 +
         y[:, None, None, None] * 0.4).astype(np.float32)
    m = Sequential()
    m.add(Convolution2D(8, 3, 3, activation="relu", border_mode="same",
                        input_shape=(size, size, 3)))
    m.add(MaxPooling2D())
    m.add(Flatten())
    m.add(Dense(16, activation="relu"))
    m.add(Dense(2, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    m.fit(x, y, batch_size=64, nb_epoch=epochs)
    return m, x, y


class TestCalibration:
    def test_scales_cover_target_layers(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.inference.quantize import (
            calibrate_activations,
        )

        m, x, y = _train_cnn()
        scales = calibrate_activations(m, [x[:32], x[32:64]])
        names = set(scales)
        # conv + 2 dense layers calibrate; scales positive
        assert len(names) == 3, names
        assert all(s > 0 for s in scales.values())

    def test_hooks_are_restored(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.inference.quantize import (
            calibrate_activations,
        )

        m, x, _ = _train_cnn()
        ref = np.asarray(
            m.forward(m.params, x[:8], state=m.state, training=False)[0])
        scales = calibrate_activations(m, [x[:16]])
        n_scales = len(scales)
        # post-calibration forwards are bit-identical to pre-calibration
        # (a leaked hook would either change outputs or keep recording)
        out, _ = m.forward(m.params, x[:8], state=m.state, training=False)
        np.testing.assert_array_equal(np.asarray(out), ref)
        assert len(scales) == n_scales  # no new entries appeared


class TestInt8Model:
    def test_accuracy_preserved_vs_float(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.inference.quantize import (
            quantize_model,
        )

        m, x, y = _train_cnn()
        float_preds = np.asarray(m.predict(x, batch_size=64))
        float_acc = (float_preds.argmax(1) == y).mean()
        assert float_acc > 0.9, float_acc

        q = quantize_model(m, x[:128])
        int8_preds = q.predict(x, batch_size=64)
        int8_acc = (int8_preds.argmax(1) == y).mean()
        agreement = (int8_preds.argmax(1) == float_preds.argmax(1)).mean()
        # reference claim: <= 0.1% drop; allow 1% at toy scale
        assert int8_acc >= float_acc - 0.01, (float_acc, int8_acc)
        assert agreement >= 0.98, agreement
        # probabilities stay close, not just argmax
        assert np.abs(int8_preds - float_preds).mean() < 0.05

    def test_float_path_untouched_after_predict(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.inference.quantize import (
            quantize_model,
        )

        m, x, y = _train_cnn()
        before = np.asarray(m.predict(x[:16], batch_size=16))
        q = quantize_model(m, x[:64])
        q.predict(x[:16], batch_size=16)
        after = np.asarray(m.predict(x[:16], batch_size=16))
        np.testing.assert_array_equal(before, after)

    def test_int8_matmul_actually_int8(self, zoo_ctx):
        """The executed dense path quantizes inputs to int8 (outputs lie on
        the scale grid), proving it's not silently running float."""
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
        from analytics_zoo_tpu.pipeline.inference.quantize import (
            Int8Model,
            calibrate_activations,
            quantize_params,
        )

        rng = np.random.default_rng(0)
        m = Sequential()
        m.add(Dense(64, bias=False, input_shape=(64,)))
        m.build_params(jax.random.PRNGKey(0))
        x = rng.normal(size=(4, 64)).astype(np.float32)
        scales = calibrate_activations(m, [x])
        qp = quantize_params(m.params, min_size=1)
        q = Int8Model(m, qp, scales)
        out = q.predict(x)
        name = m.layers[0].name
        qt = qp[name]["kernel"]
        s = scales[name]
        xs = np.clip(np.round(x / s), -127, 127).astype(np.int32)
        ref = (xs @ np.asarray(qt.values, np.int32)).astype(np.float32)
        ref = ref * (s * np.asarray(qt.scale).reshape(-1))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


class TestInferenceModelCalibrated:
    def test_optimize_with_calibration_data(self, zoo_ctx):
        """InferenceModel.optimize('int8', calibration_data=...) serves the
        calibrated int8 path through the pooled AOT predict surface."""
        from analytics_zoo_tpu.pipeline.inference import InferenceModel

        m, x, y = _train_cnn(seed=1)
        float_im = InferenceModel().from_keras_net(m)
        float_preds = float_im.predict(x[:128], batch_size=32)

        im = InferenceModel().from_keras_net(m)
        im.optimize("int8", calibration_data=x[:128])
        preds = im.predict(x[:128], batch_size=32)
        agree = (preds.argmax(1) == float_preds.argmax(1)).mean()
        assert agree >= 0.98, agree
        # second predict reuses the cached executable (no hooks leaked)
        preds2 = im.predict(x[:128], batch_size=32)
        np.testing.assert_array_equal(preds, preds2)
        # and the float model instance is untouched
        np.testing.assert_array_equal(
            float_im.predict(x[:128], batch_size=32), float_preds)


class TestReviewRegressions:
    def test_switching_precision_resets_calibration(self, zoo_ctx):
        """optimize('bf16') after a calibrated pass must NOT keep serving
        the int8 path."""
        from analytics_zoo_tpu.pipeline.inference import InferenceModel

        m, x, y = _train_cnn(seed=2)
        ref = InferenceModel().from_keras_net(m).predict(x[:32],
                                                         batch_size=32)
        im = InferenceModel().from_keras_net(m)
        im.optimize("int8", calibration_data=x[:64])
        int8_preds = im.predict(x[:32], batch_size=32)
        im.optimize("bf16")
        bf16_preds = im.predict(x[:32], batch_size=32)
        # bf16 output tracks f32 to bf16 precision, NOT the int8 output
        assert np.abs(bf16_preds - ref).max() < 0.02
        # weight-only int8 after calibrated also works (no stale hooks)
        im.optimize("int8")
        w8 = im.predict(x[:32], batch_size=32)
        assert w8.shape == ref.shape

    def test_only_hooked_kernels_quantized(self, zoo_ctx):
        """quantize_model must never leave a QuantizedTensor where no int8
        hook will consume it (e.g. embedding tables)."""
        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Dense,
            Embedding,
            Flatten,
        )
        from analytics_zoo_tpu.pipeline.inference.quantize import (
            QuantizedTensor,
            quantize_model,
        )

        init_zoo_context(seed=0)
        m = Sequential()
        m.add(Embedding(512, 32, input_shape=(8,)))  # 16k-element table
        m.add(Flatten())
        m.add(Dense(64, activation="relu"))
        m.add(Dense(2, activation="softmax"))
        m.build_params(jax.random.PRNGKey(0))
        x = np.random.default_rng(0).integers(
            0, 512, size=(64, 8)).astype(np.int32)
        # calibration + prediction must not crash on the embedding
        q = quantize_model(m, x.astype(np.float32), min_size=1)
        emb_name = m.layers[0].name
        for leaf in jax.tree_util.tree_leaves(
                q.qparams[emb_name],
                is_leaf=lambda l: isinstance(l, QuantizedTensor)):
            assert not isinstance(leaf, QuantizedTensor)
        out = q.predict(x.astype(np.float32), batch_size=32)
        assert out.shape == (64, 2)

    def test_multi_input_calibration_rejected(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.inference.quantize import (
            quantize_model,
        )

        m, x, _ = _train_cnn(seed=3, epochs=1)
        with pytest.raises(ValueError, match="multi-input"):
            quantize_model(m, [x[:8], x[:8]])

    def test_repeat_predict_no_recompile(self, zoo_ctx):
        """The jitted forward is cached on the wrapper: repeated predicts
        must not retrace (checked via jit cache stats)."""
        from analytics_zoo_tpu.pipeline.inference.quantize import (
            quantize_model,
        )

        m, x, _ = _train_cnn(seed=4, epochs=1)
        q = quantize_model(m, x[:64])
        q.predict(x[:64], batch_size=32)
        misses0 = q._fwd._cache_size()
        q.predict(x[:64], batch_size=32)
        assert q._fwd._cache_size() == misses0

    def test_int8_conv_accuracy(self, zoo_ctx):
        """_int8_conv itself (not just dense) must preserve accuracy: with
        min_size=1, the conv kernel quantizes and runs int8 x int8."""
        from analytics_zoo_tpu.pipeline.inference.quantize import (
            QuantizedTensor,
            quantize_model,
        )

        m, x, y = _train_cnn(seed=5)
        float_preds = np.asarray(m.predict(x, batch_size=64))
        q = quantize_model(m, x[:128], min_size=1)
        conv_name = m.layers[0].name
        assert isinstance(q.qparams[conv_name]["kernel"], QuantizedTensor)
        preds = q.predict(x, batch_size=64)
        agree = (preds.argmax(1) == float_preds.argmax(1)).mean()
        assert agree >= 0.97, agree

    def test_tail_batch_padded_single_executable(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.inference.quantize import (
            quantize_model,
        )

        m, x, _ = _train_cnn(seed=6, epochs=1)
        q = quantize_model(m, x[:64])
        out = q.predict(x[:100], batch_size=32)  # 3 full + tail of 4
        assert out.shape[0] == 100
        assert q._fwd._cache_size() == 1  # tail padded, no extra compile

    def test_from_keras_net_resets_bf16(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.inference import InferenceModel

        m, x, _ = _train_cnn(seed=7, epochs=2)
        im = InferenceModel().from_keras_net(m)
        ref = im.predict(x[:32], batch_size=32)
        im.optimize("bf16")
        im.from_keras_net(m)  # reload: must serve full f32 again
        np.testing.assert_allclose(im.predict(x[:32], batch_size=32), ref,
                                   atol=1e-6)
        with pytest.raises(ValueError, match="unknown precision"):
            im.optimize("fp16")
        # failed optimize left the model fully serviceable in f32
        np.testing.assert_allclose(im.predict(x[:32], batch_size=32), ref,
                                   atol=1e-6)
