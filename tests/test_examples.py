"""Smoke tests: every example's run() executes end-to-end at tiny scale
(the reference ships ~30 runnable example scripts; these are the CI gate
that ours stay runnable)."""

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def test_textclassification_example_learns():
    from examples.textclassification.train import run

    res = run(epochs=6, sequence_length=40, batch_size=32)
    assert res["accuracy"] > 0.5, res  # 4 classes, chance = 0.25


def test_neuralcf_example_learns():
    from examples.recommendation.neuralcf import run

    res, recs = run(epochs=4, batch_size=256)
    assert res["accuracy"] > 0.6, res
    assert len(recs) == 5


def test_ssd_example_runs():
    from examples.objectdetection.train_ssd import run

    m, det = run(epochs=2, batch_size=8)
    assert 0.0 <= m <= 1.0
    assert det.model is not None


def test_serving_demo_roundtrip():
    from examples.serving.demo import run

    results, expected = run(n=6)
    assert len(results) == 6
    hits = 0
    for i in range(6):
        res = results[f"img-{i}"]
        assert res is not None, f"no result for img-{i}"
        # result is the top-n list [[class, prob], ...] (reference
        # cluster-serving result schema)
        if isinstance(res, dict):
            top = int(max(res.items(), key=lambda kv: float(kv[1]))[0])
        else:
            top = int(res[0][0])
        hits += int(top == expected[i])
    assert hits >= 4, (results, expected)


def test_lenet_example_runs():
    from examples.lenet.train import run

    out = run(epochs=1, limit=256)
    assert out is not None


def test_resnet_cifar_example_runs():
    from examples.resnet.train_cifar10 import run

    out = run(steps=2, per_chip_batch=8, depth=8)
    assert out is not None


def test_anomaly_example_flags_injected():
    from examples.anomalydetection.train import run

    anomalies, offset, injected = run(epochs=3)
    idx = [i + offset for i, (_, _, f) in enumerate(anomalies) if f]
    assert len(idx) >= 1
    hits = sum(any(abs(i - a) <= 2 for a in injected) for i in idx)
    assert hits >= 1, (idx, injected)


def test_qaranker_example_ranks():
    from examples.qaranker.train import run

    res = run(epochs=5)
    assert res["recall@1"] > 0.4, res  # chance = 0.25 (1 of 4 answers)


def test_inception_example_runs():
    from examples.inception.train import run

    net = run(image_size=64, batch_size=8, steps=2, classes=10)
    assert net._estimator is not None


def test_chatbot_example_learns():
    from examples.chatbot.train import run

    res, replies, expect = run(epochs=15)
    assert res["accuracy"] > 0.7, res
    # generated answers match the deterministic mapping most of the time
    assert (replies == expect).mean() > 0.5


def test_nnframes_example_both_criteria():
    from examples.nnframes.finetune import run

    acc, acc2 = run(epochs=12)
    assert acc > 0.85, acc
    assert acc2 > 0.85, acc2


def test_tfpark_example_both_paths():
    from examples.tfpark.estimator_example import run

    est_m, km_m = run(steps=200)
    assert est_m["accuracy"] > 0.8, est_m
    assert km_m["accuracy"] > 0.8, km_m


def test_vnni_perf_example():
    from examples.vnni.perf import run

    r = run(batch=8, iters=2, image_size=32)
    assert r["size_reduction"] > 3.0, r   # ~4x from f32 -> int8 weights
    assert r["max_quant_error"] < 0.05, r
    assert r["images_per_sec_f32"] > 0


def test_transformer_example_learns():
    from examples.attention.transformer import run

    res = run(epochs=4, n=512, batch_size=64)
    assert res["accuracy"] > 0.7, res  # 2 classes, chance = 0.5


def test_autograd_customloss_example_fits():
    from examples.autograd.customloss import run

    r = run(epochs=40)
    assert r["mae"] < 0.05, r
    np.testing.assert_allclose(r["kernel"], [1.0, 1.0], atol=0.1)


def test_imageclassification_predict_example():
    from examples.imageclassification.predict import run

    labeled, truths = run(n=6, epochs=6)
    assert len(labeled) == 6 and len(labeled[0]) == 2  # top-2 pairs
    agree = sum(1 for l, t in zip(labeled, truths) if l[0][0] == t)
    assert agree >= 5, (labeled, truths)


def test_pytorch_finetune_example_learns():
    from examples.pytorch.finetune import run

    res = run(epochs=12, n=256)
    assert res["accuracy"] > 0.8, res


def test_streaming_textclassification_example():
    from examples.streaming.streaming_text_classification import run

    results, truth, _ = run(n_stream=4, epochs=6)
    assert len(results) == 4
    correct = sum(
        1 for i in range(4)
        if results[f"line-{i}"] and
        int(results[f"line-{i}"][0][0]) == int(truth[i]))
    assert correct >= 3, (results, truth)


def _run_notebook(path):
    """Execute every code cell of a notebook in one namespace (the apps/
    smoke gate — the reference's 16 notebooks have no CI at all)."""
    import json

    with open(path) as f:
        nb = json.load(f)
    ns = {}
    for cell in nb["cells"]:
        if cell["cell_type"] == "code":
            exec("".join(cell["source"]), ns)  # noqa: S102
    return ns


def test_getting_started_notebook_runs():
    ns = _run_notebook(os.path.join(REPO, "apps/getting_started.ipynb"))
    assert ns["results"]["accuracy"] > 0.85


def test_anomaly_detection_notebook_runs():
    ns = _run_notebook(os.path.join(REPO, "apps/anomaly_detection.ipynb"))
    assert ns["hits"] >= 3, ns["hits"]


def test_streaming_objectdetection_example():
    from examples.streaming.streaming_object_detection import run

    results, out_dir = run(epochs=2, n_stream=3)
    assert len(results) == 3
    outs = sorted(os.listdir(out_dir))
    assert outs == ["img-0.npy", "img-1.npy", "img-2.npy"]
    # annotated copies keep image shape
    a = np.load(os.path.join(out_dir, outs[0]))
    assert a.shape == (64, 64, 3)


def test_variational_autoencoder_notebook_runs():
    ns = _run_notebook(os.path.join(REPO, "apps/variational_autoencoder.ipynb"))
    assert ns["recon_err"] < 0.07


def test_sentiment_analysis_notebook_runs():
    ns = _run_notebook(os.path.join(REPO, "apps/sentiment_analysis.ipynb"))
    assert ns["test_acc"] > 0.85


def test_image_similarity_notebook_runs():
    ns = _run_notebook(os.path.join(REPO, "apps/image_similarity.ipynb"))
    assert ns["precision_at_10"] >= 0.8


def test_wide_n_deep_notebook_runs():
    ns = _run_notebook(os.path.join(REPO, "apps/wide_n_deep.ipynb"))
    assert ns["test_acc"] > 0.8


def test_autograd_custom_layer_example():
    from examples.autograd.custom import run

    assert run(epochs=25) < 0.2


def test_async_parameter_server_example():
    from examples.parameter_server.async_parameter_server import run

    loss0, loss1 = run(num_workers=3, updates_per_worker=30)
    assert loss1 < 0.5 * loss0


def test_tfpark_keras_ndarray_example():
    from examples.tfpark.keras_ndarray import run

    assert run(epochs=20) > 0.9


def test_tfpark_gan_train_example():
    from examples.tfpark.gan_train import run

    assert run(steps=500) > 1.2


def test_wide_and_deep_example():
    from examples.recommendation.wide_and_deep import run

    assert run(epochs=14) > 0.78


def test_nnframes_image_inference_example():
    from examples.nnframes.image_inference import run

    assert run() >= 0.9


def test_objectdetection_predict_example(tmp_path):
    from examples.objectdetection.predict import predict_and_visualize

    written, dets = predict_and_visualize(out_dir=str(tmp_path),
                                          epochs=12)
    assert written and all(os.path.exists(p) for p in written)


def test_fraud_detection_notebook_runs():
    ns = _run_notebook(os.path.join(REPO, "apps/fraud_detection.ipynb"))
    assert ns["auc_value"] > 0.9 and ns["f1"] > 0.5


def test_image_augmentation_notebook_runs():
    ns = _run_notebook(os.path.join(REPO, "apps/image_augmentation.ipynb"))
    assert ns["done"] and ns["out3d"].shape == (12, 12, 12)


def test_recommendation_ncf_notebook_runs():
    ns = _run_notebook(os.path.join(REPO, "apps/recommendation_ncf.ipynb"))
    assert ns["test_acc"] > 0.75 and ns["hit"] >= 0.6


def test_dogs_vs_cats_notebook_runs():
    ns = _run_notebook(os.path.join(REPO, "apps/dogs_vs_cats.ipynb"))
    assert ns["done"] and ns["acc"] > 0.9 and ns["src_acc"] > 0.9


def test_object_detection_notebook_runs():
    ns = _run_notebook(os.path.join(REPO, "apps/object_detection.ipynb"))
    assert ns["done"] and ns["n_boxes"] > 0


def test_anomaly_detection_hd_notebook_runs():
    ns = _run_notebook(
        os.path.join(REPO, "apps/anomaly_detection_hd.ipynb"))
    assert ns["done"] and ns["auc"] > 0.9


def test_pytorch_face_generation_notebook_runs():
    ns = _run_notebook(
        os.path.join(REPO, "apps/pytorch_face_generation.ipynb"))
    assert ns["done"] and ns["faces"].shape == (40, 3, 16, 16)


def test_tfnet_image_classification_notebook_runs():
    ns = _run_notebook(
        os.path.join(REPO, "apps/tfnet_image_classification.ipynb"))
    assert ns["done"] and len(ns["top5"]) == 24


def test_ray_parameter_server_notebook_runs():
    ns = _run_notebook(
        os.path.join(REPO, "apps/ray_parameter_server.ipynb"))
    assert ns["done"] and ns["acc"] > 0.85


def test_pytorch_predict_example():
    # Fresh interpreter on purpose: the torch-in-pure_callback SPMD
    # program is sensitive to prior in-process thread/scheduler state on
    # small CPU hosts — observed as a WEDGED 8-participant all-reduce
    # rendezvous (one partition's host callback never returns) when run
    # after the actor-runtime notebooks in the same process, a latent
    # jax-0.4-CPU callback+collective deadlock this repo cannot fix.
    # Isolation also keeps ITS callback state away from later tests.
    import subprocess

    code = (
        "import os, sys; sys.path.insert(0, os.getcwd());"
        "from examples.pytorch.predict import run;"
        "err, agree = run(n=32);"
        "assert err < 1e-4 and agree == 1.0, (err, agree);"
        "print('PYTORCH_PREDICT_OK')"
    )
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr[-2000:]
    assert "PYTORCH_PREDICT_OK" in r.stdout


def test_tfnet_predict_example():
    # Fresh interpreter for the same reason as
    # test_pytorch_predict_example: the tf-in-pure_callback SPMD program
    # wedges the 8-participant all-reduce rendezvous (latent jax-0.4 CPU
    # callback+collective deadlock; it has hung full-suite runs).  The
    # wedge is probabilistic in ANY process once the callback program is
    # 8-way sharded (~1 in 5 even in a fresh interpreter), so the
    # subprocess runs on a single device — no collective, no rendezvous
    # to wedge — which keeps the zoo-vs-tf parity assertion this example
    # is actually about.
    import pytest
    import subprocess

    pytest.importorskip("tensorflow")
    code = (
        "import os, sys; sys.path.insert(0, os.getcwd());"
        "from examples.tfnet.predict import run;"
        "err, agree = run(n=16);"
        "assert err < 1e-4 and agree == 1.0, (err, agree);"
        "print('TFNET_PREDICT_OK')"
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)  # single device: the sharded path wedges
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "TFNET_PREDICT_OK" in r.stdout


def test_gan_eval_example_restores_checkpoint():
    # Fresh interpreter for the same reason as test_pytorch_predict_example:
    # run in-process after ~40 earlier example tests this wedges inside an
    # 8-device collective rendezvous on small CPU hosts (latent jax-0.4
    # CPU deadlock); in a clean process it runs (and asserts) normally.
    import subprocess

    code = (
        "import os, sys; sys.path.insert(0, os.getcwd());"
        "from examples.tfpark.gan_eval import run;"
        "mean, spread = run(train_steps=400);"
        "assert mean > 1.2, mean;"
        "print('GAN_EVAL_OK')"
    )
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, (r.stdout[-500:], r.stderr[-1500:])
    assert "GAN_EVAL_OK" in r.stdout


def test_tfpark_keras_dataset_example():
    from examples.tfpark.keras_dataset import run

    m = run(epochs=18)
    assert m["accuracy"] > 0.9, m


def test_tfpark_estimator_inception_example():
    from examples.tfpark.estimator_inception import run

    m = run(steps=120)
    assert m["accuracy"] > 0.8, m


def test_tf_optimizer_lenet_train_then_evaluate():
    from examples.tfpark.tf_optimizer_lenet import run

    m = run(epochs=16)
    assert m["accuracy"] > 0.9, m


def test_pytorch_train_lenet_example():
    from examples.pytorch.train_lenet import run

    m = run(epochs=25)
    assert m["accuracy"] > 0.9, m


def test_pytorch_simple_training_example():
    from examples.pytorch.simple_training import run

    assert run(epochs=25) < 0.05


def test_nnframes_simple_training_example():
    from examples.nnframes.simple_training import run

    assert run(epochs=40) > 0.85


def test_nnframes_transfer_learning_example():
    from examples.nnframes.transfer_learning import run

    assert run(epochs=15) > 0.85


def test_openvino_predict_example():
    from examples.openvino.predict import run

    assert run(n=32) > 0.9


def test_ray_rl_pong_example_learns():
    from examples.ray_rl.rl_pong import run

    first, last = run(rounds=40, workers=3)
    assert last > first + 0.5, (first, last)


def test_image_augmentation_3d_notebook_runs():
    ns = _run_notebook(
        os.path.join(REPO, "apps/image_augmentation_3d.ipynb"))
    assert ns["pipeline_data"].shape == (5, 40, 40, 1)
    assert ns["batch"]["x"].shape == (2, 5, 40, 40, 1)
    assert ns["center"].shape == (3, 32, 32, 1)


def test_model_inference_text_classification_app():
    import tempfile

    from examples.model_inference import text_classification as app

    d = tempfile.mkdtemp(prefix="zoo_tc_app_")
    acc = app.train_and_save(d, epochs=8)
    assert acc > 0.8, acc
    probs = app.run_simple(d)
    assert probs.shape[1] == 4
    server = app.serve(d, port=0)
    try:
        out = app.post_predict(server.server_address[1],
                               ["w0_1 w0_2 w0_3 c1", "w2_9 w2_8 c4"])
        assert len(out["predictions"]) == 2
        assert len(out["probabilities"][0]) == 4
    finally:
        server.shutdown()


def test_model_inference_recommendation_app():
    from examples.model_inference.recommendation_inference import run

    train_acc, probs = run(train_first=True)
    assert train_acc > 0.7, train_acc
    assert probs.shape == (9, 2)


def test_model_inference_streaming_image_classification():
    from examples.model_inference.streaming_image_classification import run

    results, truth = run(epochs=25, n_stream=5)
    assert len(results) == 5
    got = [label for _, (label, _) in
           sorted(results.items(),
                  key=lambda kv: int(kv[0].split("-")[1].split(".")[0]))]
    correct = sum(1 for g, t in zip(got, truth) if g == t)
    assert correct >= 4, (got, truth)


def test_moe_example_learns_with_healthy_router():
    from examples.moe.train_moe import run

    res = run(epochs=4, n=512, batch_size=64)
    assert res["accuracy"] > 0.7, res       # 2 classes, chance 0.5
    # aux ~1.0 = balanced router; >2 would be collapsing
    assert 0.5 < res["moe_aux_loss"] < 2.0, res
    assert res["moe_drop_fraction"] < 0.4, res
