"""Planted guarded-by runtime violation: `_state` declares its lock,
`bad_write` ignores it.  The runtime sanitizer (after
`instrument_module`) must flag `bad_write` and stay quiet for
`good_write` and for the statically-suppressed `lockfree_write` (one
justification covers both halves)."""

import threading


class GuardedBox:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = 0  # guarded-by: _lock

    def good_write(self, v):
        with self._lock:
            self._state = v

    def bad_write(self, v):  # POSITIVE at runtime (and for Tier 1)
        self._state = v

    def lockfree_write(self, v):
        # zoolint: disable=guarded-by -- planted suppressed case: atomic replace, last-writer-wins
        self._state = v
