"""Planted blocking-calls-under-lock: an unbounded queue put and a
sleep while holding a lock are findings; the bounded get is not; the
suppressed sleep carries its justification."""

import queue
import threading
import time

LOCK = threading.Lock()


def sleep_under_lock():
    with LOCK:
        time.sleep(0.001)  # POSITIVE


def unbounded_put_under_lock(q: queue.Queue):
    with LOCK:
        q.put("item")  # POSITIVE: block=True, timeout=None


def bounded_get_under_lock(q: queue.Queue):
    with LOCK:
        try:
            return q.get(timeout=0.001)  # negative: bounded wait
        except queue.Empty:
            return None


def suppressed_sleep_under_lock():
    with LOCK:
        # zoolint: disable=san-blocking-under-lock -- planted suppressed case: bounded test-only pause
        time.sleep(0.001)
