"""Shared locks for the cross-file ABBA fixtures — the static pass must
unify `LOCK_A`/`LOCK_B` across the two importing modules, and the
runtime sanitizer must wrap them when this directory is watched."""

import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()
