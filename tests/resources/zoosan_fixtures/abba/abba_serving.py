"""Half of the planted cross-file ABBA: A then B (the "serving" side).
No single-file witness exists — `abba_metrics.py` holds the reverse
order, so only the whole-program pass (or the runtime lockdep) sees the
cycle."""

from abba_locks import LOCK_A, LOCK_B


def a_then_b():
    with LOCK_A:
        with LOCK_B:  # POSITIVE (with abba_metrics.b_then_a)
            return "ab"
