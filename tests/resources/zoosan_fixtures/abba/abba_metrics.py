"""Other half of the planted cross-file ABBA: B then A (the "metrics
export" side)."""

from abba_locks import LOCK_A, LOCK_B


def b_then_a():
    with LOCK_B:
        with LOCK_A:  # POSITIVE (with abba_serving.a_then_b)
            return "ba"
