"""Negative fixture: two locks always taken in the SAME order, guarded
writes under their lock, bounded waits only — zero findings from both
the static whole-program pass and the runtime sanitizer."""

import threading

OUTER = threading.Lock()
INNER = threading.Lock()


class OrderedPair:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0  # guarded-by: _lock

    def bump(self):
        with self._lock:
            self._value += 1

    def nested_consistent(self):
        with OUTER:
            with INNER:
                with self._lock:
                    self._value += 1


def also_consistent():
    with OUTER:
        with INNER:
            return "ok"
