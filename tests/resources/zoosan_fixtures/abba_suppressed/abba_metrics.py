"""Suppressed variant of the cross-file ABBA (B-then-A side)."""

from abba_locks import LOCK_A, LOCK_B


def b_then_a():
    with LOCK_B:
        # zoolint: disable=lock-order-global -- planted fixture: order is owned by the test harness
        with LOCK_A:
            return "ba"
