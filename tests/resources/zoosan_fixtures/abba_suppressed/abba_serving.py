"""Suppressed variant of the cross-file ABBA (A-then-B side): the
justified suppression at the witness site silences the whole-program
finding — the fixture pins that interprocedural findings honor the same
comment syntax as Tier 1."""

from abba_locks import LOCK_A, LOCK_B


def a_then_b():
    with LOCK_A:
        # zoolint: disable=lock-order-global -- planted fixture: order is owned by the test harness
        with LOCK_B:
            return "ab"
