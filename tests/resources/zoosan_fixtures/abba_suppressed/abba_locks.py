"""Locks for the SUPPRESSED cross-file ABBA variant."""

import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()
