"""Guarded-by inference fixtures.

`MixedWrites._items` is written once under `_lock` and once without it
(no annotation) — the inference pass must emit a
``guarded-by-candidate`` naming the unlocked site.  `HelperLocked`
writes only inside a private helper whose every call site holds the
lock — the interprocedural fact makes those writes count as locked, so
the candidate finding must report NO unlocked writes.  `Annotated` is
the negative: the declaration already exists."""

import threading


class MixedWrites:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def add(self, x):
        with self._lock:
            self._items.append(x)

    def reset(self):  # POSITIVE: unlocked write to a sometimes-locked attr
        self._items = []


class HelperLocked:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def bump(self):
        with self._lock:
            self._bump_locked()

    def bump_twice(self):
        with self._lock:
            self._bump_locked()
            self._bump_locked()

    def _bump_locked(self):
        self._count += 1  # locked via every caller (interproc fact)


class Annotated:
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0  # guarded-by: _lock

    def add(self, x):
        with self._lock:
            self._total += x
