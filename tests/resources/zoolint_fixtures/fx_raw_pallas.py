"""zoolint fixture: raw-pallas-call — decorator/partial/call-site
positives plus a suppressed negative.  Never imported; linted
statically."""

from functools import partial

import jax.experimental.pallas as pl


def kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2


@pl.pallas_call  # POSITIVE (decorator)
def bare_decorated(x):
    return x


@partial(pl.pallas_call, grid=(1,))  # POSITIVE (partial decorator)
def partial_decorated(x):
    return x


bad_call = pl.pallas_call(kernel, out_shape=None)  # POSITIVE (call site)

justified = pl.pallas_call(kernel)  # zoolint: disable=raw-pallas-call -- fixture: deliberate bypass with a recorded reason
