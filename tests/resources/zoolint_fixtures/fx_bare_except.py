"""zoolint fixture: bare-except — swallowing positive, re-raising
negative, suppressed negative.  Never imported; linted statically."""


def work():
    pass


def swallows():
    try:
        work()
    except:  # POSITIVE: eats SystemExit/KeyboardInterrupt silently
        pass


def reraises():
    try:
        work()
    except:  # no finding: the handler re-raises
        raise


def justified():
    try:
        work()
    except:  # zoolint: disable=bare-except -- last-resort guard while the interpreter shuts down
        pass
