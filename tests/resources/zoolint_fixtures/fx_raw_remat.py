"""zoolint fixture: raw-remat — decorator/partial/call-site positives,
apply_remat choke-point + suppressed negatives.  Never imported; linted
statically."""

from functools import partial

import jax

from analytics_zoo_tpu.parallel.plan import apply_remat


@jax.checkpoint  # POSITIVE (decorator)
def bare_decorated(x):
    return x * 2


@partial(jax.remat, static_argnums=(1,))  # POSITIVE (partial decorator)
def partial_decorated(x, flag):
    return x * 2


def plain(x):
    return x + 1


bad_call = jax.checkpoint(plain)  # POSITIVE (call site)

# NEGATIVE: routed through the plan's one blessed checkpoint site — the
# policy stays overridable by a plan's remat_rules
blessed = apply_remat(plain, "full")

justified = jax.remat(plain)  # zoolint: disable=raw-remat -- fixture: deliberate bypass with a recorded reason
