"""zoolint fixture: jit-side-effect — positives + a suppressed negative.

Never imported; linted statically by tests/test_zoolint.py.
"""

import time

import jax
import numpy as np


@jax.jit
def traced_print(x):
    print("tracing", x)  # POSITIVE: runs once at trace time
    return x + 1


def scan_body(carry, x):
    t = time.time()  # POSITIVE: scan-traced via run() below
    return carry + t, x


def run(xs):
    return jax.lax.scan(scan_body, 0.0, xs)


@jax.jit
def traced_np_random(x):
    noise = np.random.rand(3)  # POSITIVE: one sample baked into the graph
    return x + noise


def helper_called_from_traced(x):
    print("transitively traced")  # POSITIVE: called from traced_caller
    return x


@jax.jit
def traced_caller(x):
    return helper_called_from_traced(x)


@jax.jit
def justified(x):
    print("marker")  # zoolint: disable=jit-side-effect -- deliberate trace-time marker
    return x


def untraced(x):
    print("plain host function — no finding")
    return x
