"""zoolint fixture: prng-reuse — positive + derived-key negative +
suppressed negative.  Never imported; linted statically."""

import jax


def reused(key):
    a = jax.random.normal(key, (2,))
    b = jax.random.uniform(key, (2,))  # POSITIVE: same key, same bits
    return a + b


def derived(key):
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (2,))
    b = jax.random.uniform(k2, (2,))
    c = jax.random.normal(jax.random.fold_in(k1, 7), (2,))
    return a + b + c


def reassigned(key):
    a = jax.random.normal(key, (2,))
    key = jax.random.fold_in(key, 1)
    b = jax.random.normal(key, (2,))
    return a + b


def justified(key):
    a = jax.random.normal(key, (2,))
    b = jax.random.normal(key, (2,))  # zoolint: disable=prng-reuse -- identical draws wanted (antithetic pair)
    return a + b
