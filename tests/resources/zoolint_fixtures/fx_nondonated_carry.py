"""zoolint fixture: nondonated-carry — decorator + call-site positives,
donated negative, suppressed negative.  Never imported; linted
statically."""

from functools import partial

import jax


@jax.jit
def step_nodonate(params, opt_state, batch):  # POSITIVE (decorator)
    return params, opt_state


@partial(jax.jit, donate_argnums=(0, 1))
def step_donated(params, opt_state, batch):
    return params, opt_state


def step_fn(params, opt_state):
    return params, opt_state


bad = jax.jit(step_fn)  # POSITIVE (call site)
good = jax.jit(step_fn, donate_argnums=(0, 1))


@jax.jit
def step_justified(params, opt_state, batch):  # zoolint: disable=nondonated-carry -- carries reused across probes on purpose
    return params, opt_state
