"""zoolint fixture: raw-jit — decorator/partial/call-site positives,
choke-point + suppressed negatives.  Never imported; linted statically."""

from functools import partial

import jax

from analytics_zoo_tpu.common.compile_cache import timed_compile
from analytics_zoo_tpu.parallel.plan import compile_step


@jax.jit  # POSITIVE (decorator)
def bare_decorated(x):
    return x * 2


@partial(jax.jit, donate_argnums=(0,))  # POSITIVE (partial decorator)
def partial_decorated(x):
    return x * 2


def plain(x):
    return x + 1


bad_call = jax.jit(plain)  # POSITIVE (call site)

# NEGATIVE: the jit's lowering flows into timed_compile — that IS the
# choke point (the inference_model idiom)
exe = timed_compile(jax.jit(plain).lower(1.0), "fixture")

# NEGATIVE: routed through the partitioner's entry
stepped = compile_step(plain, label="fixture_step")

justified = jax.jit(plain)  # zoolint: disable=raw-jit -- fixture: deliberate bypass with a recorded reason
