"""zoolint fixture: guarded-by — locked negatives, unguarded-write
positives (plain/item/augmented/mutating-call), suppressed negative.
Never imported; linted statically."""

import threading


class SharedMap:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}  # guarded-by: _lock
        self.count = 0  # guarded-by: _lock
        self.unguarded = 0  # no annotation: writes never flagged

    def put_locked(self, k, v):
        with self._lock:
            self._items[k] = v
            self.count += 1

    def put_racy(self, k, v):
        self._items[k] = v  # POSITIVE: item assignment, no lock
        self.count += 1  # POSITIVE: augmented assignment, no lock

    def evict_racy(self, k):
        self._items.pop(k, None)  # POSITIVE: mutating call, no lock

    def rebind_racy(self):
        self._items = {}  # POSITIVE: rebinding loses concurrent writes

    def tuple_racy(self, v):
        self.count, other = v, 0  # POSITIVE: tuple-unpacking write, no lock
        return other

    def free_writes(self):
        self.unguarded += 1  # no finding: not declared guarded

    def reset_justified(self):
        self.count = 0  # zoolint: disable=guarded-by -- only called before the worker threads start
