"""zoolint fixture: lock-order — an ABBA pair across two methods plus a
consistent-order pair that must NOT fire.  Never imported; linted
statically."""

import threading


class AbbaPair:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def forward(self):
        with self._a_lock:
            with self._b_lock:  # POSITIVE half: A then B ...
                pass

    def backward(self):
        with self._b_lock:
            with self._a_lock:  # ... while here B then A
                pass


class ConsistentPair:
    def __init__(self):
        self._x_lock = threading.Lock()
        self._y_lock = threading.Lock()

    def one(self):
        with self._x_lock:
            with self._y_lock:
                pass

    def two(self):
        with self._x_lock, self._y_lock:  # same order: no finding
            pass
