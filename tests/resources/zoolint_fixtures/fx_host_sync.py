"""zoolint fixture: host-sync — hot-path positives (in-loop and
straight-line), a suppressed negative, and an unannotated (cold)
function that never fires.  Never imported; linted statically."""

import jax
import numpy as np


# zoolint: hot-path
def hot_loop(batches, step_fn, params):
    loss = None
    for batch in batches:
        params, loss = step_fn(params, batch)
        val = float(loss)  # POSITIVE (in loop)
        arr = np.asarray(loss)  # POSITIVE (in loop)
        loss.block_until_ready()  # POSITIVE (in loop)
        jax.device_get(loss)  # POSITIVE (in loop)
        n = int(arr.sum())  # POSITIVE (in loop)
        scalar = loss.item()  # POSITIVE (in loop, .item())
    return params, val, n, scalar


# zoolint: hot-path
def hot_straightline(loss):
    return float(loss)  # POSITIVE (hot path, not in a loop)


# zoolint: hot-path
def hot_justified(loss):
    return float(loss)  # zoolint: disable=host-sync -- epoch-boundary sync, documented contract


def cold_path(loss):
    return float(loss)  # no finding: not annotated hot-path
