"""zoolint fixture: host-sync — hot-path positives, a suppressed
negative, and an unannotated (cold) function that never fires.
Never imported; linted statically."""

import jax
import numpy as np


# zoolint: hot-path
def hot_loop(batches, step_fn, params):
    loss = None
    for batch in batches:
        params, loss = step_fn(params, batch)
        val = float(loss)  # POSITIVE
        arr = np.asarray(loss)  # POSITIVE
        loss.block_until_ready()  # POSITIVE
        jax.device_get(loss)  # POSITIVE
        n = int(arr.sum())  # POSITIVE
    return params, val, n


# zoolint: hot-path
def hot_justified(loss):
    return float(loss)  # zoolint: disable=host-sync -- epoch-boundary sync, documented contract


def cold_path(loss):
    return float(loss)  # no finding: not annotated hot-path
