"""ONNX loader tests (reference pyzoo/test/zoo/pipeline/onnx mapper suite).

The ``onnx`` package is unavailable, so models are fabricated with the
in-repo wire encoder (which doubles as a codec round-trip test) and mapper
outputs are oracle-checked against torch functional ops.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.pipeline.api.onnx import OnnxNet, load_onnx
from analytics_zoo_tpu.pipeline.api.onnx.proto import (
    FLOAT, INT64, Graph, Model, Node, ValueInfo, decode_model, encode_model,
)

rng0 = np.random.default_rng(0)


def make_model(nodes, inputs, outputs, initializers):
    g = Graph(name="g", nodes=nodes, initializers=initializers,
              inputs=[ValueInfo(n, s, FLOAT) for n, s in inputs],
              outputs=[ValueInfo(n, s, FLOAT) for n, s in outputs])
    return encode_model(Model(graph=g))


def test_proto_roundtrip():
    w = rng0.normal(size=(4, 3)).astype(np.float32)
    shape = np.asarray([1, -1], dtype=np.int64)
    data = make_model(
        nodes=[
            Node(op_type="MatMul", inputs=["x", "w"], outputs=["y"]),
            Node(op_type="Relu", inputs=["y"], outputs=["z"],
                 attrs={}),
        ],
        inputs=[("x", (None, 4))],
        outputs=[("z", (None, 3))],
        initializers={"w": w, "shape": shape},
    )
    m = decode_model(data)
    assert [n.op_type for n in m.graph.nodes] == ["MatMul", "Relu"]
    np.testing.assert_allclose(m.graph.initializers["w"], w)
    np.testing.assert_array_equal(m.graph.initializers["shape"], shape)
    assert m.graph.inputs[0].name == "x"
    assert m.graph.inputs[0].shape == (None, 4)
    assert m.graph.outputs[0].name == "z"


def _run(net_bytes, *xs, trainable=True):
    net = load_onnx(net_bytes, trainable=trainable)
    net.ensure_built(tuple(np.shape(xs[0]))[1:])
    params = net.init_params(jax.random.PRNGKey(0))
    state = net.init_state()
    arrs = [jnp.asarray(x) for x in xs]
    out, _ = net.apply(params, arrs if len(arrs) > 1 else arrs[0],
                       state=state or None)
    return out, net, params


def test_mlp_gemm_relu_softmax():
    import torch

    w1 = rng0.normal(size=(6, 8)).astype(np.float32)
    b1 = rng0.normal(size=(8,)).astype(np.float32)
    w2 = rng0.normal(size=(8, 3)).astype(np.float32)
    b2 = rng0.normal(size=(3,)).astype(np.float32)
    data = make_model(
        nodes=[
            Node(op_type="Gemm", inputs=["x", "w1", "b1"], outputs=["h"]),
            Node(op_type="Relu", inputs=["h"], outputs=["hr"]),
            Node(op_type="Gemm", inputs=["hr", "w2", "b2"], outputs=["l"]),
            Node(op_type="Softmax", inputs=["l"], outputs=["p"],
                 attrs={"axis": -1}),
        ],
        inputs=[("x", (None, 6))],
        outputs=[("p", (None, 3))],
        initializers={"w1": w1, "b1": b1, "w2": w2, "b2": b2},
    )
    x = rng0.normal(size=(5, 6)).astype(np.float32)
    out, net, params = _run(data, x)

    t = torch.from_numpy
    ref = torch.softmax(
        torch.relu(t(x) @ t(w1) + t(b1)) @ t(w2) + t(b2), dim=-1
    ).numpy()
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)
    # float initializers are trainable params
    assert set(params) == {"w1", "b1", "w2", "b2"}


def test_convnet_nchw_vs_torch():
    import torch
    import torch.nn.functional as F

    w = rng0.normal(size=(4, 3, 3, 3)).astype(np.float32) * 0.2
    b = rng0.normal(size=(4,)).astype(np.float32)
    scale = rng0.uniform(0.5, 1.5, size=(4,)).astype(np.float32)
    bias = rng0.normal(size=(4,)).astype(np.float32)
    mean = rng0.normal(size=(4,)).astype(np.float32) * 0.1
    var = rng0.uniform(0.5, 1.5, size=(4,)).astype(np.float32)
    reshape = np.asarray([0, -1], dtype=np.int64)

    data = make_model(
        nodes=[
            Node(op_type="Conv", inputs=["x", "w", "b"], outputs=["c"],
                 attrs={"kernel_shape": [3, 3], "strides": [1, 1],
                        "pads": [1, 1, 1, 1]}),
            Node(op_type="BatchNormalization",
                 inputs=["c", "scale", "bias", "mean", "var"],
                 outputs=["bn"], attrs={"epsilon": 1e-5}),
            Node(op_type="Relu", inputs=["bn"], outputs=["r"]),
            Node(op_type="MaxPool", inputs=["r"], outputs=["mp"],
                 attrs={"kernel_shape": [2, 2], "strides": [2, 2]}),
            Node(op_type="GlobalAveragePool", inputs=["mp"],
                 outputs=["gap"]),
            Node(op_type="Reshape", inputs=["gap", "rs"], outputs=["f"]),
        ],
        inputs=[("x", (None, 3, 8, 8))],
        outputs=[("f", (None, 4))],
        initializers={"w": w, "b": b, "scale": scale, "bias": bias,
                      "mean": mean, "var": var, "rs": reshape},
    )
    x = rng0.normal(size=(2, 3, 8, 8)).astype(np.float32)
    out, net, params = _run(data, x)

    t = torch.from_numpy
    y = F.conv2d(t(x), t(w), t(b), padding=1)
    y = F.batch_norm(y, t(mean), t(var), t(scale), t(bias), eps=1e-5)
    y = F.max_pool2d(torch.relu(y), 2, 2)
    ref = y.mean((2, 3)).numpy()
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)
    # int64 reshape initializer stays static, not a param
    assert "rs" not in params


def test_elementwise_and_reduce_ops():
    import torch

    x = rng0.normal(size=(3, 4)).astype(np.float32)
    y = rng0.normal(size=(3, 4)).astype(np.float32)
    data = make_model(
        nodes=[
            Node(op_type="Add", inputs=["x", "y"], outputs=["s"]),
            Node(op_type="Sigmoid", inputs=["s"], outputs=["sg"]),
            Node(op_type="Mul", inputs=["sg", "x"], outputs=["m"]),
            Node(op_type="ReduceMean", inputs=["m"], outputs=["r"],
                 attrs={"axes": [1], "keepdims": 0}),
        ],
        inputs=[("x", (3, 4)), ("y", (3, 4))],
        outputs=[("r", (3,))],
        initializers={},
    )
    out, _, _ = _run(data, x, y)
    t = torch.from_numpy
    ref = (torch.sigmoid(t(x) + t(y)) * t(x)).mean(1).numpy()
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)


def test_concat_slice_transpose_pad():
    x = rng0.normal(size=(2, 3, 4)).astype(np.float32)
    data = make_model(
        nodes=[
            Node(op_type="Transpose", inputs=["x"], outputs=["t"],
                 attrs={"perm": [0, 2, 1]}),
            Node(op_type="Concat", inputs=["t", "t"], outputs=["c"],
                 attrs={"axis": 2}),
            Node(op_type="Slice", inputs=["c"], outputs=["s"],
                 attrs={"starts": [1], "ends": [5], "axes": [2]}),
            Node(op_type="Pad", inputs=["s"], outputs=["p"],
                 attrs={"pads": [0, 0, 0, 0, 0, 1], "mode": "constant",
                        "value": 9.0}),
        ],
        inputs=[("x", (2, 3, 4))],
        outputs=[("p", (2, 4, 5))],
        initializers={},
    )
    out, _, _ = _run(data, x)
    ref = np.transpose(x, (0, 2, 1))
    ref = np.concatenate([ref, ref], axis=2)[:, :, 1:5]
    ref = np.pad(ref, ((0, 0), (0, 0), (0, 1)), constant_values=9.0)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-6)


def test_constant_and_gather_and_split():
    x = rng0.normal(size=(2, 6)).astype(np.float32)
    idx = np.asarray([2, 0], dtype=np.int64)
    data = make_model(
        nodes=[
            Node(op_type="Constant", inputs=[], outputs=["k"],
                 attrs={"value": np.asarray(2.0, dtype=np.float32)}),
            Node(op_type="Mul", inputs=["x", "k"], outputs=["m"]),
            Node(op_type="Split", inputs=["m"], outputs=["a", "b"],
                 attrs={"axis": 1, "split": [3, 3]}),
            Node(op_type="Gather", inputs=["a", "gidx"], outputs=["g"],
                 attrs={"axis": 1}),
        ],
        inputs=[("x", (2, 6))],
        outputs=[("g", (2, 2)), ("b", (2, 3))],
        initializers={"gidx": idx},
    )
    net = load_onnx(data)
    net.ensure_built((6,))
    params = net.init_params(jax.random.PRNGKey(0))
    out, _ = net.apply(params, jnp.asarray(x))
    g, b = out
    np.testing.assert_allclose(np.asarray(g), (2 * x)[:, :3][:, [2, 0]],
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(b), (2 * x)[:, 3:], atol=1e-6)


def test_onnx_net_finetunes_in_sequential():
    rng = np.random.default_rng(42)  # own stream: order-independent data
    w = (rng.normal(size=(4, 2)) * 0.5).astype(np.float32)
    b = np.zeros(2, dtype=np.float32)
    data = make_model(
        nodes=[
            Node(op_type="Gemm", inputs=["x", "w", "b"], outputs=["l"]),
            Node(op_type="Softmax", inputs=["l"], outputs=["p"],
                 attrs={"axis": -1}),
        ],
        inputs=[("x", (None, 4))],
        outputs=[("p", (None, 2))],
        initializers={"w": w, "b": b},
    )
    from analytics_zoo_tpu.pipeline.api.keras import Sequential

    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = (x.sum(-1) > 0).astype(np.int64)
    m = Sequential()
    m.add(load_onnx(data))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    m.fit(x, y, batch_size=32, nb_epoch=250)
    res = m.evaluate(x, y, batch_size=32)
    assert res["accuracy"] > 0.85, res


def test_frozen_onnx_net_state():
    w = rng0.normal(size=(3, 2)).astype(np.float32)
    b = np.zeros(2, dtype=np.float32)
    data = make_model(
        nodes=[Node(op_type="Gemm", inputs=["x", "w", "b"],
                    outputs=["y"])],
        inputs=[("x", (None, 3))],
        outputs=[("y", (None, 2))],
        initializers={"w": w, "b": b},
    )
    net = load_onnx(data, trainable=False)
    net.ensure_built((3,))
    params = net.init_params(jax.random.PRNGKey(0))
    assert params == {}
    state = net.init_state()
    x = rng0.normal(size=(2, 3)).astype(np.float32)
    out, _ = net.apply(params, jnp.asarray(x), state=state)
    np.testing.assert_allclose(np.asarray(out), x @ w + b, rtol=1e-5,
                               atol=1e-6)


def test_unsupported_op_reports_cleanly():
    data = make_model(
        nodes=[Node(op_type="FancyCustomOp", inputs=["x"],
                    outputs=["y"])],
        inputs=[("x", (1, 2))],
        outputs=[("y", (1, 2))],
        initializers={},
    )
    with pytest.raises(NotImplementedError, match="FancyCustomOp"):
        load_onnx(data)


def test_net_facade_load_onnx(tmp_path):
    from analytics_zoo_tpu.pipeline.api.net import Net

    w = rng0.normal(size=(3, 2)).astype(np.float32)
    data = make_model(
        nodes=[Node(op_type="MatMul", inputs=["x", "w"], outputs=["y"])],
        inputs=[("x", (None, 3))],
        outputs=[("y", (None, 2))],
        initializers={"w": w},
    )
    p = tmp_path / "m.onnx"
    p.write_bytes(data)
    net = Net.load_onnx(str(p))
    assert isinstance(net, OnnxNet)
    net.ensure_built((3,))
    params = net.init_params(jax.random.PRNGKey(0))
    x = rng0.normal(size=(2, 3)).astype(np.float32)
    out, _ = net.apply(params, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), x @ w, rtol=1e-5,
                               atol=1e-6)


def test_maxpool_ceil_mode_vs_torch():
    import torch
    import torch.nn.functional as F

    x = rng0.normal(size=(1, 2, 7, 7)).astype(np.float32)
    data = make_model(
        nodes=[Node(op_type="MaxPool", inputs=["x"], outputs=["y"],
                    attrs={"kernel_shape": [3, 3], "strides": [2, 2],
                           "ceil_mode": 1})],
        inputs=[("x", (1, 2, 7, 7))],
        outputs=[("y", (1, 2, 4, 4))],
        initializers={},
    )
    out, _, _ = _run(data, x)
    ref = F.max_pool2d(torch.from_numpy(x), 3, 2, ceil_mode=True).numpy()
    assert np.asarray(out).shape == ref.shape
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-6)


def test_conv_same_lower_shifts_padding():
    x = np.zeros((1, 1, 4, 4), dtype=np.float32)
    x[0, 0, 0, 0] = 1.0
    w = np.ones((1, 1, 2, 2), dtype=np.float32)

    def run(auto_pad):
        data = make_model(
            nodes=[Node(op_type="Conv", inputs=["x", "w"], outputs=["y"],
                        attrs={"kernel_shape": [2, 2],
                               "auto_pad": auto_pad})],
            inputs=[("x", (1, 1, 4, 4))],
            outputs=[("y", (1, 1, 4, 4))],
            initializers={"w": w},
        )
        out, _, _ = _run(data, x)
        return np.asarray(out)[0, 0]

    upper = run("SAME_UPPER")   # pad at end: windows start at x[i, j]
    lower = run("SAME_LOWER")   # pad at start: windows end at x[i, j]
    assert upper.shape == lower.shape == (4, 4)
    assert not np.allclose(upper, lower)
    # with the impulse at x[0,0]: SAME_UPPER's out[1,1] window is
    # x[1:3,1:3] (misses it); SAME_LOWER's out[1,1] window is x[0:2,0:2]
    assert upper[1, 1] == 0.0 and lower[1, 1] == 1.0


def test_conv_transpose_output_padding_vs_torch():
    import torch
    import torch.nn.functional as F

    x = rng0.normal(size=(1, 3, 5, 5)).astype(np.float32)
    w = (rng0.normal(size=(3, 2, 3, 3)) * 0.3).astype(np.float32)
    data = make_model(
        nodes=[Node(op_type="ConvTranspose", inputs=["x", "w"],
                    outputs=["y"],
                    attrs={"kernel_shape": [3, 3], "strides": [2, 2],
                           "pads": [1, 1, 1, 1],
                           "output_padding": [1, 1]})],
        inputs=[("x", (1, 3, 5, 5))],
        outputs=[("y", (1, 2, 10, 10))],
        initializers={"w": w},
    )
    out, _, _ = _run(data, x)
    ref = F.conv_transpose2d(torch.from_numpy(x), torch.from_numpy(w),
                             stride=2, padding=1, output_padding=1).numpy()
    assert np.asarray(out).shape == ref.shape
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_expand_right_aligned_broadcast():
    x = rng0.normal(size=(2, 3, 4)).astype(np.float32)
    shape = np.asarray([4], dtype=np.int64)
    data = make_model(
        nodes=[Node(op_type="Expand", inputs=["x", "s"], outputs=["y"])],
        inputs=[("x", (2, 3, 4))],
        outputs=[("y", (2, 3, 4))],
        initializers={"s": shape},
    )
    out, _, _ = _run(data, x)
    np.testing.assert_allclose(np.asarray(out), x, atol=1e-6)


def test_pre13_softmax_coerce_2d():
    from analytics_zoo_tpu.pipeline.api.onnx.proto import (
        Graph as G, Model as M, ValueInfo as VI, encode_model as enc,
    )

    x = rng0.normal(size=(2, 3, 4)).astype(np.float32)
    g = G(name="g",
          nodes=[Node(op_type="Softmax", inputs=["x"], outputs=["y"])],
          inputs=[VI("x", (2, 3, 4), FLOAT)],
          outputs=[VI("y", (2, 3, 4), FLOAT)])
    data = enc(M(graph=g, opset=9))
    out, _, _ = _run(data, x)
    flat = x.reshape(2, -1)
    e = np.exp(flat - flat.max(-1, keepdims=True))
    ref = (e / e.sum(-1, keepdims=True)).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)


def test_proto3_omitted_scalar_attr_defaults():
    from analytics_zoo_tpu.pipeline.api.onnx.proto import (
        ATTR_INT, _decode_attribute, _put_bytes, _put_varint,
    )

    # fabricate an AttributeProto with name + type=INT but NO value field,
    # as proto3 writers do for zero values
    buf = bytearray()
    _put_bytes(buf, 1, b"axis")
    _put_varint(buf, 20, ATTR_INT)
    a = _decode_attribute(bytes(buf))
    assert a.name == "axis" and a.value == 0


class TestExternalFixture:
    """Round-1 advisor finding (e): the suite previously only round-tripped
    its own encoder.  This fixture's bytes were serialized by the OFFICIAL
    protobuf runtime (protoc-compiled subset of the public onnx.proto3
    schema — see tests/resources/protoc_fixture.onnx), so the wire-format
    decoder is validated against an independent producer."""

    def test_loads_external_bytes_and_matches_numpy_oracle(self):
        import os

        import numpy as np

        from analytics_zoo_tpu.pipeline.api.onnx import load_onnx

        res = os.path.join(os.path.dirname(__file__), "resources")
        import jax

        net = load_onnx(os.path.join(res, "protoc_fixture.onnx"))
        io = np.load(os.path.join(res, "protoc_fixture_io.npz"))
        net.ensure_built(io["x"].shape[1:])
        params = net.init_params(jax.random.PRNGKey(0))
        out, _ = net.apply(params, io["x"], state=net.init_state())
        np.testing.assert_allclose(np.asarray(out), io["y"],
                                   rtol=1e-4, atol=1e-5)
