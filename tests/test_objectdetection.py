"""Object detection suite — mirrors the reference's objectdetection specs
(MultiBoxLoss, NMS, MeanAveragePrecision, SSDGraph shape tests)."""

import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.models.image.objectdetection import (
    MultiBoxLoss,
    ObjectDetector,
    PriorSpec,
    SSD300_SPECS,
    average_precision,
    decode_boxes,
    encode_boxes,
    generate_priors,
    match_priors,
    mean_average_precision,
    nms_numpy,
    pad_ground_truth,
    ssd_tiny,
)
from analytics_zoo_tpu.models.image.objectdetection.priors import (
    center_to_corner,
)


class TestPriors:
    def test_ssd300_count_is_8732(self):
        priors = generate_priors(SSD300_SPECS)
        assert priors.shape == (8732, 4)

    def test_priors_normalized(self):
        priors = generate_priors(SSD300_SPECS)
        assert priors.min() >= 0.0 and priors.max() <= 1.0

    def test_boxes_per_loc(self):
        assert [s.boxes_per_loc for s in SSD300_SPECS] == [4, 6, 6, 6, 4, 4]


class TestEncodeDecode:
    def test_roundtrip(self):
        priors = jnp.asarray(generate_priors([PriorSpec(4, 0.2, 0.4,
                                                        (2.0,))]))
        gt = jnp.asarray([[0.1, 0.1, 0.4, 0.5]] * priors.shape[0],
                         jnp.float32)
        enc = encode_boxes(gt, priors)
        dec = decode_boxes(enc, priors)
        np.testing.assert_allclose(dec, gt, rtol=1e-4, atol=1e-5)


class TestMatching:
    def test_every_gt_gets_a_prior(self):
        priors_c = jnp.asarray(generate_priors([PriorSpec(4, 0.2, 0.4,
                                                          (2.0,))]))
        priors_corner = jnp.asarray(center_to_corner(np.asarray(priors_c)))
        gt = jnp.asarray([[0.0, 0.0, 0.3, 0.3], [0.6, 0.6, 0.9, 0.9],
                          [0, 0, 0, 0]], jnp.float32)
        labels = jnp.asarray([0, 2, -1], jnp.int32)
        conf_t, matched = match_priors(gt, labels, priors_corner)
        # both real gts own at least one prior (force-match), padding none
        assert int(jnp.sum(conf_t == 1)) >= 1
        assert int(jnp.sum(conf_t == 3)) >= 1

    def test_contended_best_prior_split_between_gts(self):
        # two gts whose best prior is the SAME prior: bipartite matching
        # must give each a distinct prior (plain argmax would drop one)
        priors_c = np.asarray([[0.25, 0.25, 0.5, 0.5],
                               [0.8, 0.8, 0.2, 0.2]], np.float32)
        priors_corner = jnp.asarray(center_to_corner(priors_c))
        gt = jnp.asarray([[0.0, 0.0, 0.5, 0.5], [0.0, 0.0, 0.45, 0.45],
                          [0, 0, 0, 0]], jnp.float32)
        labels = jnp.asarray([0, 1, -1], jnp.int32)
        conf_t, _ = match_priors(gt, labels, priors_corner)
        # both gts force-matched, necessarily to the two different priors
        assert int(jnp.sum(conf_t == 1)) >= 1
        assert int(jnp.sum(conf_t == 2)) >= 1

    def test_padding_ignored(self):
        priors_c = jnp.asarray(generate_priors([PriorSpec(2, 0.3, 0.5,
                                                          (2.0,))]))
        priors_corner = jnp.asarray(center_to_corner(np.asarray(priors_c)))
        gt = jnp.zeros((4, 4), jnp.float32)
        labels = jnp.full((4,), -1, jnp.int32)
        conf_t, _ = match_priors(gt, labels, priors_corner)
        assert int(jnp.sum(conf_t)) == 0  # all background


class TestNMS:
    def test_suppresses_overlaps(self):
        boxes = np.array([[0, 0, 1, 1], [0.02, 0, 1, 1], [2, 2, 3, 3]],
                         np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        keep = nms_numpy(boxes, scores, iou_threshold=0.5)
        assert list(keep) == [0, 2]

    def test_keeps_all_disjoint(self):
        boxes = np.array([[0, 0, 1, 1], [2, 2, 3, 3], [5, 5, 6, 6]],
                         np.float32)
        scores = np.array([0.5, 0.9, 0.7], np.float32)
        keep = nms_numpy(boxes, scores, iou_threshold=0.5)
        assert sorted(keep) == [0, 1, 2]


class TestMAP:
    def test_perfect_detection_ap_1(self):
        gt = [dict(boxes=np.array([[0, 0, 0.5, 0.5]]), classes=np.array([0]))]
        det = [dict(boxes=np.array([[0, 0, 0.5, 0.5]]),
                    scores=np.array([0.9]), classes=np.array([0]))]
        assert average_precision(det, gt, 0) == pytest.approx(1.0)

    def test_miss_halves_recall(self):
        gt = [dict(boxes=np.array([[0, 0, 0.5, 0.5], [0.6, 0.6, 0.9, 0.9]]),
                   classes=np.array([0, 0]))]
        det = [dict(boxes=np.array([[0, 0, 0.5, 0.5]]),
                    scores=np.array([0.9]), classes=np.array([0]))]
        ap = average_precision(det, gt, 0)
        assert ap == pytest.approx(0.5)

    def test_false_positive_lowers_precision(self):
        gt = [dict(boxes=np.array([[0, 0, 0.5, 0.5]]), classes=np.array([0]))]
        det = [dict(
            boxes=np.array([[0.7, 0.7, 0.9, 0.9], [0, 0, 0.5, 0.5]]),
            scores=np.array([0.95, 0.9]), classes=np.array([0, 0]))]
        ap = average_precision(det, gt, 0)
        assert 0.4 < ap < 0.6  # fp ranked first: precision 1/2 at recall 1

    def test_map_averages_classes(self):
        gt = [dict(boxes=np.array([[0, 0, 0.5, 0.5], [0.5, 0.5, 1, 1]]),
                   classes=np.array([0, 1]))]
        det = [dict(boxes=np.array([[0, 0, 0.5, 0.5]]),
                    scores=np.array([0.9]), classes=np.array([0]))]
        m = mean_average_precision(det, gt, 2)
        assert m == pytest.approx(0.5)

    def test_map_skips_classes_with_no_gt(self):
        # VOC convention: absent classes are excluded, not scored 0
        gt = [dict(boxes=np.array([[0, 0, 0.5, 0.5]]),
                   classes=np.array([0]))]
        det = [dict(boxes=np.array([[0, 0, 0.5, 0.5]]),
                    scores=np.array([0.9]), classes=np.array([0]))]
        assert mean_average_precision(det, gt, 20) == pytest.approx(1.0)


class TestSSDTrainingE2E:
    def setup_method(self, _):
        init_zoo_context(seed=0)

    def _toy_dataset(self, n=64, size=64, seed=0):
        """One bright square per image; class = quadrant-ish color id."""
        rng = np.random.default_rng(seed)
        images = np.zeros((n, size, size, 3), np.float32)
        boxes, labels = [], []
        for i in range(n):
            cls = int(rng.integers(0, 2))
            s = int(rng.integers(14, 22))
            x0 = int(rng.integers(0, size - s))
            y0 = int(rng.integers(0, size - s))
            images[i, y0:y0 + s, x0:x0 + s, cls] = 1.0
            boxes.append([[x0 / size, y0 / size, (x0 + s) / size,
                           (y0 + s) / size]])
            labels.append([cls])
        return images, boxes, labels

    def test_tiny_ssd_shapes(self):
        net, priors = ssd_tiny(n_classes=2)
        n_priors = priors.shape[0]
        assert n_priors == 8 * 8 * 4 + 4 * 4 * 4
        net.build_params()
        x = np.zeros((2, 64, 64, 3), np.float32)
        out, _ = net.forward(net.params, x, state=net.state)
        assert out.shape == (2, n_priors, 4 + 3)

    def test_multibox_loss_decreases_and_detects(self):
        det = ObjectDetector("ssd-tiny", class_names=("red", "green"))
        images, boxes, labels = self._toy_dataset()
        y = pad_ground_truth(boxes, labels, max_boxes=4)
        loss_fn = det.loss()
        det.model.build_params()
        out0, _ = det.model.forward(det.model.params, images[:8],
                                    state=det.model.state)
        l0 = float(jnp.mean(loss_fn(jnp.asarray(y[:8]), out0)))
        from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

        det.compile(Adam(lr=1e-3))
        det.fit_detection(images, boxes, labels, batch_size=16, nb_epoch=30,
                          max_boxes=4)
        out1, _ = det.model.forward(det.model.params, images[:8],
                                    state=det.model.state)
        l1 = float(jnp.mean(loss_fn(jnp.asarray(y[:8]), out1)))
        assert l1 < l0 * 0.5, (l0, l1)

        dets = det.predict_image_set(images[:8], conf_threshold=0.3)
        gts = [dict(boxes=np.asarray(boxes[i], np.float32),
                    classes=np.asarray(labels[i]))
               for i in range(8)]
        m = mean_average_precision(dets, gts, 2, iou_threshold=0.3)
        assert m > 0.25, m
