"""Precision plane (ISSUE 16): ``dtype_rules`` as the FOURTH rule table
on :class:`ShardingPlan` — bf16 compute + f32 masters/accumulation
(``mixed_precision()``), the int8 weight-only serving role, the dtype-
aware cost-model ceilings behind ``plan="auto"``, the generalized
``hlo-dtype-policy`` lint, the checkpoint's dtype-policy contract, and
the ``bench.py --precision`` artifact's invariants.

The core claims pinned here:

- masters stay f32 and the bf16 trajectory tracks f32 within tolerance
  (the cast is in-graph, so grads/collectives/optimizer stay f32);
- elastic resume of the f32 masters across world sizes under
  ``mixed_precision()`` is BIT-exact (same contract as the sharding
  plans' resume tests);
- resuming under a DIFFERENT dtype policy fails loudly
  (``ZOO_DTYPE_RESUME=cast`` is the deliberate escape hatch);
- ``dtype_rules`` participate in the plan cache key, so a bf16 program
  never collides with its f32 twin in the compiled-step cache.
"""

import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Rule table / plan vocabulary units
# ---------------------------------------------------------------------------


class TestDtypeRules:
    def test_first_match_wins_and_scalars_exempt(self):
        from analytics_zoo_tpu.parallel.plan import ShardingPlan

        plan = ShardingPlan(
            name="t",
            dtype_rules=((r"dense_0/kernel", "f32"), (r".*", "bf16")))
        tree = {"dense_0": {"kernel": np.zeros((4, 4), np.float32),
                            "bias": np.zeros((4,), np.float32)},
                "step": np.zeros((), np.float32)}
        roles = plan.dtype_roles(tree)
        assert roles["dense_0/kernel"] == "f32"
        assert roles["dense_0/bias"] == "bf16"
        # scalar leaves never appear in the role map (never down-cast)
        assert "step" not in roles

    def test_invalid_role_raises_at_construction(self):
        from analytics_zoo_tpu.parallel.plan import ShardingPlan

        with pytest.raises(ValueError, match="role"):
            ShardingPlan(name="t", dtype_rules=((".*", "f8"),))

    def test_cast_params_for_compute_keeps_masters(self):
        from analytics_zoo_tpu.parallel.plan import mixed_precision

        plan = mixed_precision()
        params = {"dense_0": {"kernel": jnp.ones((4, 4), jnp.float32),
                              "bias": jnp.ones((4,), jnp.float32)},
                  "scale": jnp.ones((), jnp.float32)}
        compute = plan.cast_params_for_compute(params)
        assert compute["dense_0"]["kernel"].dtype == jnp.bfloat16
        assert compute["dense_0"]["bias"].dtype == jnp.bfloat16
        # scalar exemption: a loss scale keeps its width
        assert compute["scale"].dtype == jnp.float32
        # masters untouched
        assert params["dense_0"]["kernel"].dtype == jnp.float32

    def test_cache_key_participation(self):
        from analytics_zoo_tpu.parallel.plan import (
            data_parallel,
            mixed_precision,
            with_dtype,
        )

        dp = data_parallel()
        mp = mixed_precision()
        assert dp.cache_key() != mp.cache_key()
        assert with_dtype(dp, "f16").cache_key() != mp.cache_key()

    def test_policy_round_trip_and_names(self):
        from analytics_zoo_tpu.parallel.plan import (
            fsdp,
            int8_serving,
            mixed_precision,
            resolve_dtype_rules,
            resolve_plan,
            with_dtype_policy,
        )

        mp = mixed_precision()
        assert mp.name == "dp+bf16"
        assert mp.dtype_policy_str() == ".*=bf16"
        assert resolve_dtype_rules(mp.dtype_policy_str()) == mp.dtype_rules
        assert resolve_dtype_rules("bf16_mixed") == mp.dtype_rules
        assert int8_serving().dtype_rules == ((".*", "int8"),)
        assert with_dtype_policy(fsdp(), "int8_serving").name == "fsdp+int8"
        # name suffix resolution composes with +overlap
        p = resolve_plan("zero1+overlap+bf16")
        assert p.name == "zero1+overlap+bf16"
        assert p.dtype_rules == ((".*", "bf16"),)
        # "auto" is the oracle's job, not a rule string
        with pytest.raises(ValueError, match="auto"):
            resolve_dtype_rules("auto")

    def test_zoo_dtype_policy_env_validated_eagerly(self, monkeypatch):
        from analytics_zoo_tpu.common.engine import ZooConfig

        monkeypatch.setenv("ZOO_DTYPE_POLICY", "bf17")
        with pytest.raises(ValueError, match="ZOO_DTYPE_POLICY"):
            ZooConfig()
        monkeypatch.setenv("ZOO_DTYPE_POLICY", "bf16_mixed")
        assert ZooConfig().dtype_policy == "bf16_mixed"
        monkeypatch.setenv("ZOO_DTYPE_POLICY", "auto")
        assert ZooConfig().dtype_policy == "auto"

    def test_sharding_plan_env_accepts_dtype_suffix(self, monkeypatch):
        from analytics_zoo_tpu.common.engine import ZooConfig

        monkeypatch.setenv("ZOO_SHARDING_PLAN", "zero1+overlap+bf16")
        assert ZooConfig().sharding_plan == "zero1+overlap+bf16"
        monkeypatch.setenv("ZOO_SHARDING_PLAN", "zero1+bf17")
        with pytest.raises(ValueError, match="ZOO_SHARDING_PLAN"):
            ZooConfig()


# ---------------------------------------------------------------------------
# Training: trajectory tolerance, masters, resume contracts
# ---------------------------------------------------------------------------


def _data():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    w = np.random.default_rng(1).normal(size=(8, 4))
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return x, y


def _fit(mesh_size, ckpt_dir, epochs, plan=None):
    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    zoo.init_zoo_context(seed=3, mesh_shape={"data": mesh_size})
    x, y = _data()
    m = Sequential()
    m.add(Dense(16, activation="relu", input_shape=(8,)))
    m.add(Dense(4, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    if ckpt_dir:
        m.set_checkpoint(ckpt_dir)
    m.fit(x, y, batch_size=32, nb_epoch=epochs, plan=plan)
    return m


class TestMixedPrecisionTraining:
    def test_bf16_trajectory_tracks_f32_with_f32_masters(self):
        from analytics_zoo_tpu.parallel.plan import mixed_precision

        f32 = _fit(2, None, 2)
        mp = _fit(2, None, 2, plan=mixed_precision())
        l32 = [h["loss"] for h in f32._estimator.history]
        lmp = [h["loss"] for h in mp._estimator.history]
        for a, b in zip(l32, lmp):
            assert abs(a - b) / max(abs(a), 1e-9) < 0.05, (l32, lmp)
        # masters (and optimizer moments) stay f32 — the bitwise-stable
        # optimizer state contract
        for leaf in jax.tree_util.tree_leaves(mp._estimator.model.params):
            assert leaf.dtype == jnp.float32
        for leaf in jax.tree_util.tree_leaves(mp._estimator._opt_state):
            if hasattr(leaf, "dtype") and jnp.issubdtype(
                    leaf.dtype, jnp.floating):
                assert leaf.dtype == jnp.float32
        rec = mp._estimator._plan_record
        assert rec["name"] == "dp+bf16"
        assert rec["dtype_policy"] == ".*=bf16"

    def test_elastic_resume_bit_exact_across_world_sizes(self, tmp_path):
        """f32 masters reshard bit-exact 8 → 4 under mixed_precision():
        the precision plane composes with the elastic-resume contract
        (same shape as the fsdp/zeroN resume tests)."""
        from analytics_zoo_tpu.parallel.plan import mixed_precision

        ckdir = str(tmp_path / "ck_mp")
        full = _fit(8, None, 4, plan=mixed_precision())
        losses_full = [h["loss"] for h in full._estimator.history]

        first = _fit(8, ckdir, 2, plan=mixed_precision())
        assert [h["loss"] for h in first._estimator.history] \
            == losses_full[:2]  # bitwise

        resumed = _fit(4, ckdir, 4, plan=mixed_precision())
        losses_resumed = [h["loss"] for h in resumed._estimator.history]
        assert len(losses_resumed) == 2, losses_resumed
        assert losses_resumed == losses_full[2:]  # bitwise

    def test_resume_under_different_policy_fails_loudly(
            self, tmp_path, monkeypatch):
        from analytics_zoo_tpu.parallel.plan import mixed_precision

        ckdir = str(tmp_path / "ck_policy")
        _fit(2, ckdir, 1, plan=mixed_precision())
        with pytest.raises(ValueError, match="dtype policy"):
            _fit(2, ckdir, 2, plan=None)
        # the deliberate escape hatch
        monkeypatch.setenv("ZOO_DTYPE_RESUME", "cast")
        m = _fit(2, ckdir, 2, plan=None)
        assert len(m._estimator.history) == 1  # epoch 2 only: resumed

    def test_auto_plan_sweeps_dtype_under_auto_policy(self, monkeypatch):
        monkeypatch.setenv("ZOO_DTYPE_POLICY", "auto")
        m = _fit(2, None, 1, plan="auto")
        rec = m._estimator._plan_record
        assert rec["auto"]["chosen_dtype"] == "bf16"
        assert any(c["dtype"] == "bf16" for c in rec["auto"]["candidates"])
        assert rec["name"].endswith("+bf16")
        assert rec["dtype_policy"] == ".*=bf16"


# ---------------------------------------------------------------------------
# Cost model: dtype ceilings + collective accounting
# ---------------------------------------------------------------------------


class TestDtypeCostModel:
    def test_dtype_peaks_scale_flops_only(self):
        from analytics_zoo_tpu.analysis.costmodel import (
            PeakTable,
            dtype_peaks,
        )

        peaks = PeakTable(flops=1e12, hbm_bytes_per_s=1e11,
                          link_bytes_per_s=1e10,
                          dispatch_overhead_s=1e-4,
                          hbm_bytes=16e9, source="test")
        b = dtype_peaks(peaks, "bf16")
        assert b.flops == 2e12
        assert b.hbm_bytes_per_s == peaks.hbm_bytes_per_s
        assert dtype_peaks(peaks, None) is peaks
        with pytest.raises(ValueError):
            dtype_peaks(peaks, "f8")

    def test_gather_bytes_shrink_grad_bytes_do_not(self):
        """fsdp at bf16: only the param-gather 2P scales by 0.5 — the
        reduce-scatter P stays f32 per the accumulation contract, so
        the predicted ratio is exactly (1 + 2·0.5)/3 = 2/3."""
        from analytics_zoo_tpu.analysis.costmodel import (
            plan_collective_bytes,
        )

        pb = 1 << 20
        f32 = plan_collective_bytes(pb, "fsdp", 8)
        bf16 = plan_collective_bytes(pb, "fsdp", 8, dtype="bf16")
        assert abs(bf16 / f32 - 2 / 3) < 1e-6
        # dp has no param gather: nothing shrinks
        assert plan_collective_bytes(pb, "dp", 8, dtype="bf16") \
            == plan_collective_bytes(pb, "dp", 8)

    def test_choose_plan_dtype_sweep_prefers_bf16_under_tight_slo(self):
        from analytics_zoo_tpu.analysis.costmodel import PeakTable
        from analytics_zoo_tpu.analysis.oracle import ConfigOracle

        peaks = PeakTable(flops=1e12, hbm_bytes_per_s=1e11,
                          link_bytes_per_s=1e10,
                          dispatch_overhead_s=1e-5,
                          hbm_bytes=64 << 30, source="test")
        oracle = ConfigOracle(peaks=peaks)
        # a compute-bound program: 10 TFLOP per step over the 1 TFLOP/s
        # ceiling dominates the collective seconds, so the doubled bf16
        # matmul rate is the decisive term
        feats = {"matmul_flops": 1e13, "bytes_accessed": 1e9}
        # default: no dtype options — behavior (and the pinned oracle
        # tests' expectations) unchanged
        name, doc = oracle.choose_plan(1 << 30, 2 << 30, 8,
                                       features=feats,
                                       activation_bytes=1 << 30)
        assert doc.get("chosen_dtype") is None
        # with the sweep: the candidates carry the dtype dimension and
        # bf16 wins on the halved compute term
        name2, doc2 = oracle.choose_plan(
            1 << 30, 2 << 30, 8, features=feats,
            activation_bytes=1 << 30,
            dtype_options=(None, "bf16"))
        assert doc2["chosen_dtype"] == "bf16"
        assert any(c["config"].endswith("+bf16")
                   for c in doc2["candidates"])
        assert {c["dtype"] for c in doc2["candidates"]} == {None, "bf16"}

    def test_histogram_compute_dtype(self):
        from analytics_zoo_tpu.analysis.costmodel import (
            histogram_compute_dtype,
        )

        assert histogram_compute_dtype({"f32": 10, "bf16": 40}) == "bf16"
        assert histogram_compute_dtype({"f32": 10, "i32": 99}) == "f32"
        assert histogram_compute_dtype({}) is None
        assert histogram_compute_dtype(None) is None


# ---------------------------------------------------------------------------
# hlo-dtype-policy lint fixtures
# ---------------------------------------------------------------------------


class TestDtypePolicyLint:
    def test_f32_matmul_under_bf16_policy_flagged(self):
        from analytics_zoo_tpu.analysis import analyze_hlo_text

        text = jax.jit(lambda a, b: a @ b).lower(
            np.zeros((8, 16), np.float32),
            np.zeros((16, 4), np.float32)).as_text()
        rpt = analyze_hlo_text(text, "mm", dtype_policy=".*=bf16")
        assert "hlo-dtype-policy" in {f.rule for f in rpt.findings}
        assert rpt.dtype_policy == ".*=bf16"

    def test_bf16_matmul_under_bf16_policy_clean(self):
        from analytics_zoo_tpu.analysis import analyze_hlo_text

        text = jax.jit(lambda a, b: a @ b).lower(
            np.zeros((8, 16), np.dtype("bfloat16")),
            np.zeros((16, 4), np.dtype("bfloat16"))).as_text()
        rpt = analyze_hlo_text(text, "mm16", dtype_policy=".*=bf16")
        assert "hlo-dtype-policy" not in {f.rule for f in rpt.findings}

    def test_low_precision_all_reduce_breaks_accum_contract(self):
        from analytics_zoo_tpu.analysis import analyze_hlo_text

        devices = jax.devices()[:2]
        f = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i",
                     devices=devices)
        text = f.lower(
            np.zeros((2, 8), np.dtype("bfloat16"))).as_text()
        rpt = analyze_hlo_text(text, "psum16", dtype_policy=".*=bf16")
        msgs = [f.message for f in rpt.findings
                if f.rule == "hlo-dtype-policy"]
        assert any("f32-accumulation" in m for m in msgs), msgs

    def test_suppressed_without_policy(self):
        """The same f32 matmul is CLEAN with no policy declared (None)
        or under a pure-f32 policy — the lint only checks a declared
        low-precision contract."""
        from analytics_zoo_tpu.analysis import analyze_hlo_text

        text = jax.jit(lambda a, b: a @ b).lower(
            np.zeros((8, 16), np.float32),
            np.zeros((16, 4), np.float32)).as_text()
        for policy in (None, "", ".*=f32"):
            rpt = analyze_hlo_text(text, "mm", dtype_policy=policy)
            assert "hlo-dtype-policy" not in {
                f.rule for f in rpt.findings}, policy


# ---------------------------------------------------------------------------
# int8 serving + explicit zero1 policy carry
# ---------------------------------------------------------------------------


class TestInt8Serving:
    def test_plan_aware_quantization_respects_roles_and_heuristic(self):
        from analytics_zoo_tpu.parallel.plan import ShardingPlan, int8_serving
        from analytics_zoo_tpu.pipeline.inference.quantize import (
            QuantizedTensor,
            quantize_params_for_plan,
        )

        params = {
            "dense_0": {"kernel": jnp.ones((64, 64), jnp.float32),
                        "bias": jnp.ones((64,), jnp.float32)},
            "norm": {"scale": jnp.ones((64,), jnp.float32)},
        }
        q = quantize_params_for_plan(params, int8_serving())
        assert isinstance(q["dense_0"]["kernel"], QuantizedTensor)
        # 1-D leaves fail the structural heuristic even under .*=int8
        assert not isinstance(q["dense_0"]["bias"], QuantizedTensor)
        assert not isinstance(q["norm"]["scale"], QuantizedTensor)
        # a rule that marks nothing int8 is a no-op tree
        noop = ShardingPlan(name="t", dtype_rules=((".*", "bf16"),))
        q2 = quantize_params_for_plan(params, noop)
        assert q2 is params

    def test_predict_parity_and_bytes_ratio(self):
        from analytics_zoo_tpu.parallel.plan import int8_serving
        from analytics_zoo_tpu.pipeline.inference.quantize import (
            dequantize_params,
            quantize_params_for_plan,
            quantized_bytes_ratio,
        )

        rng = np.random.default_rng(5)
        params = {"k": jnp.asarray(
            rng.normal(size=(64, 64)).astype(np.float32))}
        q = quantize_params_for_plan(params, int8_serving())
        ratio = quantized_bytes_ratio(params, q)
        # int8 values + per-channel f32 scales ≈ 0.266x of f32
        assert ratio < 0.3, ratio
        x = rng.normal(size=(8, 64)).astype(np.float32)
        base = np.asarray(x @ params["k"])
        served = np.asarray(x @ dequantize_params(q)["k"])
        denom = np.linalg.norm(base)
        assert np.linalg.norm(base - served) / denom < 0.01

    def test_reshard_zero1_carries_dtype_policy(self):
        """The explicit zero1 reshard path records the dtype policy on
        its placement plan, so the resharded state keeps the precision
        contract it was trained under."""
        from analytics_zoo_tpu.parallel.plan import ShardingPlan
        from analytics_zoo_tpu.parallel.strategies import (
            reshard_zero1_opt_state,
        )

        import analytics_zoo_tpu as zoo

        zoo.init_zoo_context(seed=0, mesh_shape={"data": 4})
        params = {"w": np.zeros((8, 4), np.float32)}
        n_old = 8
        size = 32
        pad = (-size) % n_old
        flat = np.arange(size + pad, dtype=np.float32)
        opt_state = {"mu": flat.copy(), "nu": flat.copy(),
                     "count": np.zeros((), np.float32)}
        out = reshard_zero1_opt_state(opt_state, params, n_old=n_old,
                                      dtype_policy=".*=bf16")
        # values re-padded for the new axis and still intact
        np.testing.assert_array_equal(
            np.asarray(out["mu"])[:size], flat[:size])
        # and the policy string round-trips through a plan
        probe = ShardingPlan(name="t", dtype_rules=((".*", "bf16"),))
        assert probe.dtype_policy_str() == ".*=bf16"


# ---------------------------------------------------------------------------
# Bench quick tier (the acceptance guard on bench.py --precision)
# ---------------------------------------------------------------------------


def test_precision_bench_quick_tier(tmp_path):
    """CI guard on the bench itself: bf16 trajectory within tolerance
    of f32, a measured bf16 histogram shift, the predicted 2/3 fsdp
    collective-bytes ratio, and the int8 serving bytes/parity numbers.
    CPU tier: throughput wins recorded, not required."""
    sys.path.insert(0, REPO)
    try:
        from bench import precision_bench
    finally:
        sys.path.remove(REPO)
    doc = precision_bench(quick=True, out_path=str(tmp_path / "b.json"))
    assert doc["value"] <= 0.05, doc["value"]
    shift = doc["bf16_hlo_shift"]
    assert shift["f32_leg_bf16_ops"] == 0
    assert shift["bf16_leg_bf16_ops"] > 0
    assert doc["predicted_fsdp_collective_bytes"]["ratio"] < 1.0
    assert doc["int8_serving_bytes_ratio"] < 0.5
    assert doc["legs"]["int8_serving"]["predict_max_abs_diff"] < 0.05
    legs = doc["legs"]
    assert legs["bf16"]["plan"] == "dp+bf16"
    assert legs["bf16"]["dtype_policy"] == ".*=bf16"
    # the compile plane saw both programs (per-plan labels, distinct
    # cache keys): each leg carries its own feature block, and the
    # bf16 leg moves fewer bytes through the lowered program
    assert legs["bf16"]["hlo"]["zoo_hlo_bytes_accessed"] \
        < legs["f32"]["hlo"]["zoo_hlo_bytes_accessed"]
    # a bench row is load_bench_rows-harvestable (steps_per_sec + hlo)
    assert legs["f32"]["steps_per_sec"] > 0
