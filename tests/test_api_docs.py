"""docs/api/ stays in sync with the code: the generator's output for a
couple of load-bearing modules must match the committed pages, and every
committed page must correspond to an importable module (no orphans)."""

import os

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
API = os.path.join(REPO, "docs", "api")


def test_api_pages_exist_and_cover_core_modules():
    assert os.path.isdir(API), "run tools/make_api_docs.py"
    pages = {f for f in os.listdir(API) if f.endswith(".md")}
    for must in (
        "index.md",
        "analytics_zoo_tpu_common_engine.md",
        "analytics_zoo_tpu_parallel_pipeline.md",
        "analytics_zoo_tpu_parallel_strategies.md",
        "analytics_zoo_tpu_pipeline_estimator_estimator.md",
        "analytics_zoo_tpu_ops_moe.md",
        "analytics_zoo_tpu_ops_pallas_flash_attention.md",
    ):
        assert must in pages, must
    assert len(pages) > 80  # the full per-module sweep, not a stub


def test_no_orphan_pages():
    """Every committed page corresponds to an importable module — a
    rename without regeneration leaves a stale page behind."""
    import importlib

    for f in os.listdir(API):
        if not f.endswith(".md") or f == "index.md":
            continue
        modname = f[:-3].replace("analytics_zoo_tpu_", "", 1)
        # module paths may contain underscores themselves: try the
        # greedy candidates ("a_b_c" -> a.b.c, a.b_c, a_b.c, ...)
        parts = modname.split("_")
        ok = False
        for mask in range(1 << max(0, len(parts) - 1)):
            cand, seg = [], parts[0]
            for i, p in enumerate(parts[1:]):
                if mask >> i & 1:
                    seg += "_" + p
                else:
                    cand.append(seg)
                    seg = p
            cand.append(seg)
            try:
                importlib.import_module(
                    "analytics_zoo_tpu." + ".".join(cand))
                ok = True
                break
            except ImportError:
                continue
        assert ok, f"orphan page {f}: no importable module matches"


def test_index_links_every_page():
    """The TOC and the page set move together: every committed page is
    linked from index.md and every link resolves."""
    import re

    with open(os.path.join(API, "index.md")) as f:
        idx = f.read()
    links = set(re.findall(r"\]\((\S+\.md)\)", idx))
    pages = {f for f in os.listdir(API)
             if f.endswith(".md") and f != "index.md"}
    assert links == pages, (links ^ pages)


def test_committed_pages_match_generator():
    """Regenerate EVERY page in memory and compare against the committed
    tree — drift anywhere means someone changed an API without rerunning
    tools/make_api_docs.py."""
    from tools.make_api_docs import generate

    pages, _ = generate()
    assert len(pages) > 80
    stale = []
    for modname, want in pages.items():
        path = os.path.join(API, modname.replace(".", "_") + ".md")
        if not os.path.exists(path):
            stale.append(modname + " (missing)")
            continue
        with open(path) as f:
            if f.read() != want:
                stale.append(modname)
    assert not stale, (
        f"stale pages {stale[:5]} — rerun tools/make_api_docs.py")
