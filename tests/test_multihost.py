"""Multi-host wiring tests: 2 jax.distributed processes (gloo CPU
collectives, 4 virtual devices each) must produce the SAME loss curve as a
single 8-device process — proving per-process batch slicing
(FeatureSet.batches(process_shard=...) + make_array_from_process_local_data
in ZooContext.shard_batch) reconstructs the identical global batches — and
the single-writer + barrier checkpoint path must resume exactly across a
2-process stop/restart.

Reference semantics being matched: per-partition data locality of
FeatureSet.scala:240-289 — no host ever loads another host's rows.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# one worker template for all 2-process tests; ckdir "-" = no checkpointing
WORKER = """
import json, os, sys
sys.path.insert(0, %(repo)r)
port, pid, nproc, ckdir, epochs, out = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
    int(sys.argv[5]), sys.argv[6])
mode = sys.argv[7] if len(sys.argv) > 7 else "plain"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
from analytics_zoo_tpu.parallel.multihost import init_distributed
init_distributed(coordinator_address=f"127.0.0.1:{port}",
                 num_processes=nproc, process_id=pid)
assert jax.process_count() == nproc
from tests.test_multihost import build_and_fit
hist = build_and_fit(None if ckdir == "-" else ckdir, epochs,
                     hybrid=(mode == "hybrid"))
if pid == 0:
    with open(out, "w") as f:
        json.dump(hist, f)
"""


def build_and_fit(ckpt_dir=None, epochs=3, hybrid=False):
    """Deterministic tiny training run; returns per-epoch losses + eval.

    Runs identically single-process (8 devices) and 2-process (4+4): the
    global batch schedule depends only on (seed, epoch).  With ``ckpt_dir``
    set, checkpoints land there and ``epochs`` is an ABSOLUTE target, so a
    second invocation resumes (the _Checkpointer single-writer + barrier
    path).
    """
    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    if hybrid:
        # each PROCESS is a slice (what real multi-slice looks like:
        # distinct host groups per slice); DP crosses the emulated DCN
        import jax

        groups: dict = {}
        for d in jax.devices():
            groups.setdefault(d.process_index, []).append(d)
        sg = [groups[k] for k in sorted(groups)]
        zoo.init_zoo_context(
            seed=3, mesh_shape={"data": len(sg[0])},
            dcn_shape={"data": len(sg)}, slice_groups=sg)
    else:
        zoo.init_zoo_context(seed=3)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    w = np.random.default_rng(1).normal(size=(8, 4))
    y = np.argmax(x @ w, axis=1).astype(np.int32)

    m = Sequential()
    m.add(Dense(16, activation="relu", input_shape=(8,)))
    m.add(Dense(4, activation="softmax"))
    m.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    if ckpt_dir:
        m.set_checkpoint(ckpt_dir)
    m.fit(x, y, batch_size=32, nb_epoch=epochs)
    res = m.evaluate(x, y, batch_size=32)
    hist = [h["loss"] for h in m._estimator.history]
    return {"losses": hist, "eval": res}


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_two_process(tmp_path, tag, ckdir="-", epochs=3, mode="plain"):
    """Launch the 2-process run; ALWAYS reaps both workers (a worker that
    died before a collective leaves its sibling blocked in the barrier —
    without the finally-kill it would orphan and wedge later tests)."""
    port = _free_port()
    out = str(tmp_path / f"{tag}.json")
    script = str(tmp_path / f"worker_{tag}.py")
    with open(script, "w") as f:
        f.write(WORKER % {"repo": REPO})
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, script, str(port), str(i), "2", ckdir,
             str(epochs), out, mode],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    try:
        logs = [p.communicate(timeout=600)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
    for i, p in enumerate(procs):
        assert p.returncode == 0, f"worker {i} failed:\n{logs[i][-3000:]}"
    with open(out) as f:
        return json.load(f)


def test_two_process_matches_single_process(tmp_path):
    # single-process baseline on the conftest 8-device mesh
    base = build_and_fit()
    mh = _run_two_process(tmp_path, "plain")
    np.testing.assert_allclose(mh["losses"], base["losses"],
                               rtol=1e-4, atol=1e-5)
    assert abs(mh["eval"]["loss"] - base["eval"]["loss"]) < 1e-4
    assert abs(mh["eval"]["accuracy"] - base["eval"]["accuracy"]) < 1e-6


def test_two_process_checkpoint_resume(tmp_path):
    """Multi-host single-writer checkpointing: process 0 is the only
    writer to the shared dir, the barrier in latest() keeps both
    processes on the same snapshot, and a second 2-process run RESUMES
    to the absolute epoch target with the exact continuation curve."""
    ckdir = str(tmp_path / "shared_ck")
    full = build_and_fit(str(tmp_path / "solo_ck"), 4)

    first = _run_two_process(tmp_path, "phase1", ckdir, 2)
    np.testing.assert_allclose(first["losses"], full["losses"][:2],
                               rtol=1e-4, atol=1e-5)
    files = [f for f in os.listdir(ckdir) if f.startswith("ckpt-")]
    assert files, "process 0 wrote no checkpoints"

    resumed = _run_two_process(tmp_path, "phase2", ckdir, 4)
    # restoration must actually have happened: only epochs 3..4 trained.
    # (Without this length pin, a silently-broken resume retrains 1..4
    # from scratch and the deterministic curve still matches.)
    assert len(resumed["losses"]) == 2, resumed["losses"]
    np.testing.assert_allclose(resumed["losses"], full["losses"][2:],
                               rtol=1e-4, atol=1e-5)
    assert abs(resumed["eval"]["loss"] - full["eval"]["loss"]) < 1e-4


class TestHybridMesh:
    """Multi-slice mesh layout (SURVEY §2.4 DCN axis): DCN-crossing axis
    outermost, ICI axes inner, slice groups stay contiguous."""

    def _mesh(self, ici, dcn):
        import jax

        from analytics_zoo_tpu.parallel import hybrid_mesh

        devs = jax.devices()
        return hybrid_mesh(ici, dcn,
                           slice_groups=[devs[:4], devs[4:]])

    def test_shape_and_slice_placement(self):
        import jax

        m = self._mesh({"data": 2, "model": 2}, {"data": 2})
        assert dict(m.shape) == {"data": 4, "model": 2}
        devs = jax.devices()
        # outermost (DCN) blocks = one slice each: rows 0-1 from slice 0
        assert set(m.devices[:2].ravel()) == set(devs[:4])
        assert set(m.devices[2:].ravel()) == set(devs[4:])

    def test_collective_spans_slices(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        m = self._mesh({"data": 2, "model": 2}, {"data": 2})
        x = np.arange(8, dtype=np.float32)
        f = jax.jit(jax.shard_map(
            lambda v: jax.lax.psum(v, "data"), mesh=m,
            in_specs=P("data"), out_specs=P(), check_vma=False))
        # 4 data shards [0,1],[2,3],[4,5],[6,7] -> elementwise sums
        np.testing.assert_allclose(np.asarray(f(x)), [12.0, 16.0])

    def test_dcn_axis_must_be_outermost(self):
        import pytest

        from analytics_zoo_tpu.parallel import hybrid_mesh

        with pytest.raises(ValueError, match="outermost"):
            hybrid_mesh({"data": 2, "model": 2}, {"model": 2},
                        axes=("data", "model"))
        with pytest.raises(ValueError, match="one axis"):
            hybrid_mesh({"data": 2}, {"data": 2, "model": 2})

    def test_unknown_axis_key_raises(self):
        import pytest

        from analytics_zoo_tpu.parallel import hybrid_mesh

        # a typo'd axis name must not silently yield a size-1 mesh
        with pytest.raises(ValueError, match="not in mesh axes"):
            hybrid_mesh({"dtaa": 2}, {"data": 2}, axes=("data",))

    def test_surplus_devices_require_allow_idle(self):
        import jax
        import pytest

        from analytics_zoo_tpu.parallel import hybrid_mesh

        devs = jax.devices()
        with pytest.raises(ValueError, match="allow_idle"):
            hybrid_mesh({"data": 2}, {"data": 2},
                        slice_groups=[devs[:4], devs[4:]])
        m = hybrid_mesh({"data": 2}, {"data": 2},
                        slice_groups=[devs[:4], devs[4:]],
                        allow_idle=True)
        assert dict(m.shape) == {"data": 4}

    def test_group_count_mismatch_raises(self):
        import jax
        import pytest

        from analytics_zoo_tpu.parallel import hybrid_mesh

        devs = jax.devices()
        with pytest.raises(ValueError, match="device"):
            hybrid_mesh({"data": 2}, {"data": 4},
                        slice_groups=[devs[:4], devs[4:]])

    def test_fit_through_hybrid_context_matches_plain(self):
        """init_zoo_context(dcn_shape=...) makes fit() itself train
        multi-slice: identical loss curve to the plain 8-way DP mesh."""
        import jax

        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

        def build_and_fit():
            rng = np.random.default_rng(0)
            x = rng.normal(size=(64, 6)).astype(np.float32)
            y = (x[:, :2] * 3.0).astype(np.float32)
            m = Sequential()
            m.add(Dense(2, input_shape=(6,)))
            m.compile(optimizer="sgd", loss="mse")
            m.fit(x, y, batch_size=16, nb_epoch=3)
            return [h["loss"] for h in m._estimator.history]

        devs = jax.devices()
        ctx = init_zoo_context(
            seed=0, mesh_shape={"data": 2, "model": 2},
            dcn_shape={"data": 2},
            slice_groups=[devs[:4], devs[4:]])
        assert dict(ctx.mesh.shape) == {"data": 4, "model": 2}
        hybrid_losses = build_and_fit()

        init_zoo_context(seed=0, mesh_shape={"data": 4, "model": 2})
        plain_losses = build_and_fit()
        np.testing.assert_allclose(hybrid_losses, plain_losses, rtol=1e-5)

    def test_hybrid_context_keeps_unlisted_axes_at_size_one(self):
        """Pure-DP multi-slice with default axes must keep the model axis
        at size 1 (like the plain path) so PartitionSpecs naming it still
        resolve; slice_groups without dcn_shape is an error."""
        import jax
        import pytest

        from analytics_zoo_tpu import init_zoo_context

        devs = jax.devices()
        ctx = init_zoo_context(
            seed=0, mesh_shape={"data": 4}, dcn_shape={"data": 2},
            slice_groups=[devs[:4], devs[4:]])
        assert dict(ctx.mesh.shape) == {"data": 8, "model": 1}
        ctx.sharding(None, "model")  # must not raise
        with pytest.raises(ValueError, match="requires dcn_shape"):
            init_zoo_context(seed=0, mesh_shape={"data": 8},
                             slice_groups=[devs[:4], devs[4:]])


def test_two_process_hybrid_slices_match_single_process(tmp_path):
    """2 jax.distributed processes, each one an emulated SLICE (hybrid
    mesh, DP crossing the process boundary as the DCN axis): identical
    loss curve to the plain single-process 8-device run — multi-host AND
    multi-slice semantics compose."""
    two = _run_two_process(tmp_path, "hybrid2p", mode="hybrid")
    one = build_and_fit()
    np.testing.assert_allclose(two["losses"], one["losses"], rtol=1e-4,
                               atol=1e-5)
    assert abs(two["eval"]["loss"] - one["eval"]["loss"]) < 1e-4
