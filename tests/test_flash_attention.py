"""Flash-attention correctness: custom_vjp blockwise backward vs the dense
reference, including ragged lengths (lk % block != 0) and end-aligned causal
masking with lq != lk.  Runs on CPU (the Pallas forward is TPU-only; the
blockwise backward runs everywhere)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.ops.pallas.flash_attention import (
    _attention_reference,
    flash_attention,
)


def _rand(shape, seed):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("lq,lk", [(64, 64), (300, 300), (64, 300),
                                   (37, 128)])
def test_forward_matches_reference(causal, lq, lk):
    q = _rand((2, 2, lq, 8), 0)
    k = _rand((2, 2, lk, 8), 1)
    v = _rand((2, 2, lk, 8), 2)
    got = flash_attention(q, k, v, causal, None, 128, 128)
    want = _attention_reference(q, k, v, causal, 1.0 / np.sqrt(8))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("lq,lk", [(64, 64), (300, 300), (64, 300)])
def test_blockwise_backward_matches_reference(causal, lq, lk):
    q = _rand((1, 2, lq, 8), 3)
    k = _rand((1, 2, lk, 8), 4)
    v = _rand((1, 2, lk, 8), 5)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, None, 128, 128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(
            _attention_reference(q, k, v, causal, 1.0 / np.sqrt(8)) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_backward_memory_is_blockwise():
    """The full (lq, lk) score matrix must never appear in the backward
    jaxpr — only (lq, block_k) tiles.  (The CPU *forward* fallback is dense
    by design; on TPU the Pallas kernel serves the forward.)"""
    from analytics_zoo_tpu.ops.pallas.flash_attention import _bwd

    lq = lk = 512
    q = _rand((1, 1, lq, 8), 6)
    k = _rand((1, 1, lk, 8), 7)
    v = _rand((1, 1, lk, 8), 8)
    out = flash_attention(q, k, v, True, None, 128, 128)
    g = jnp.ones_like(out)
    jaxpr = jax.make_jaxpr(
        lambda res, g: _bwd(True, None, 128, 128, res, g))((q, k, v, out), g)
    text = str(jaxpr).replace(" ", "")
    assert f"1,1,{lq},{lk}]" not in text, (
        "full (lq, lk) score matrix materialized in backward")
    assert "1,1,512,128]" in text  # block tiles are present


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("lq,lk", [(128, 128), (128, 384), (100, 260)])
def test_pallas_kernel_interpret_matches_reference(causal, lq, lk):
    """Run the ACTUAL Pallas kernel (grid-streamed K/V, scratch
    accumulators) in interpret mode on CPU and compare against the dense
    oracle — so the kernel logic itself is CI-tested without a TPU."""
    from analytics_zoo_tpu.ops.pallas.flash_attention import _flash_fwd_pallas

    q = _rand((2, 2, lq, 8), 10)
    k = _rand((2, 2, lk, 8), 11)
    v = _rand((2, 2, lk, 8), 12)
    got = _flash_fwd_pallas(q, k, v, causal, 1.0 / np.sqrt(8), 64, 64,
                            interpret=True)
    want = _attention_reference(q, k, v, causal, 1.0 / np.sqrt(8))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
