"""Flash-attention correctness: custom_vjp blockwise backward vs the dense
reference, including ragged lengths (lk % block != 0) and end-aligned causal
masking with lq != lk.  Runs on CPU (the Pallas forward is TPU-only; the
blockwise backward runs everywhere)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.ops.pallas.flash_attention import (
    _attention_reference,
    flash_attention,
)


def _rand(shape, seed):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("lq,lk", [(64, 64), (300, 300), (64, 300),
                                   (37, 128)])
def test_forward_matches_reference(causal, lq, lk):
    q = _rand((2, 2, lq, 8), 0)
    k = _rand((2, 2, lk, 8), 1)
    v = _rand((2, 2, lk, 8), 2)
    got = flash_attention(q, k, v, causal, None, 128, 128)
    want = _attention_reference(q, k, v, causal, 1.0 / np.sqrt(8))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("lq,lk", [(64, 64), (300, 300), (64, 300)])
def test_blockwise_backward_matches_reference(causal, lq, lk):
    q = _rand((1, 2, lq, 8), 3)
    k = _rand((1, 2, lk, 8), 4)
    v = _rand((1, 2, lk, 8), 5)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, None, 128, 128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(
            _attention_reference(q, k, v, causal, 1.0 / np.sqrt(8)) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_backward_memory_is_blockwise():
    """The full (lq, lk) score matrix must never appear in the backward
    jaxpr — only (lq, block_k) tiles.  (The CPU *forward* fallback is dense
    by design; on TPU the Pallas kernel serves the forward.)"""
    from analytics_zoo_tpu.ops.pallas.flash_attention import _bwd

    lq = lk = 512
    q = _rand((1, 1, lq, 8), 6)
    k = _rand((1, 1, lk, 8), 7)
    v = _rand((1, 1, lk, 8), 8)
    out = flash_attention(q, k, v, True, None, 128, 128)
    g = jnp.ones_like(out)
    jaxpr = jax.make_jaxpr(
        lambda res, g: _bwd(True, None, 0.0, 128, 128, res, g))(
            (q, k, v, None, None, None, None, out, None, None), g)
    text = str(jaxpr).replace(" ", "")
    assert f"1,1,{lq},{lk}]" not in text, (
        "full (lq, lk) score matrix materialized in backward")
    assert "1,1,512,128]" in text  # block tiles are present


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("lq,lk", [(128, 128), (128, 384), (100, 260)])
def test_pallas_kernel_interpret_matches_reference(causal, lq, lk):
    """Run the ACTUAL Pallas kernel (grid-streamed K/V, scratch
    accumulators) in interpret mode on CPU and compare against the dense
    oracle — so the kernel logic itself is CI-tested without a TPU."""
    from analytics_zoo_tpu.ops.pallas.flash_attention import _flash_fwd_pallas

    q = _rand((2, 2, lq, 8), 10)
    k = _rand((2, 2, lk, 8), 11)
    v = _rand((2, 2, lk, 8), 12)
    got = _flash_fwd_pallas(q, k, v, causal, 1.0 / np.sqrt(8), 64, 64,
                            interpret=True)
    want = _attention_reference(q, k, v, causal, 1.0 / np.sqrt(8))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# Round-4 training-path features: additive bias/mask, segment ids, dropout
# (VERDICT r03 item 1 — flash must serve the REAL training config)
# ---------------------------------------------------------------------------


def _grad_check(loss_flash, loss_ref, args, rtol=1e-4, atol=1e-4):
    n = len(args)
    g1 = jax.grad(loss_flash, argnums=tuple(range(n)))(*args)
    g2 = jax.grad(loss_ref, argnums=tuple(range(n)))(*args)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, rtol=rtol, atol=atol)


@pytest.mark.parametrize("bias_shape", [(2, 1, 1, 300), (1, 1, 200, 300),
                                        (2, 2, 200, 300)])
def test_bias_forward_and_grad(bias_shape):
    """Additive bias in every broadcast form — incl. the BERT (B,1,1,L)
    padding-mask convention (reference BERT.scala:66) — matches the dense
    oracle in both forward and all grads (incl. dbias)."""
    q = _rand((2, 2, 200, 8), 0)
    k = _rand((2, 2, 300, 8), 1)
    v = _rand((2, 2, 300, 8), 2)
    bias = _rand(bias_shape, 3) * 2.0

    def f_flash(q, k, v, bias):
        return jnp.sum(flash_attention(q, k, v, False, None, 128, 128,
                                       bias=bias) ** 2)

    def f_ref(q, k, v, bias):
        return jnp.sum(_attention_reference(
            q, k, v, False, 1.0 / np.sqrt(8), bias=bias) ** 2)

    np.testing.assert_allclose(
        flash_attention(q, k, v, False, None, 128, 128, bias=bias),
        _attention_reference(q, k, v, False, 1.0 / np.sqrt(8), bias=bias),
        rtol=2e-5, atol=2e-5)
    _grad_check(f_flash, f_ref, (q, k, v, bias))


def test_padding_mask_fully_masked_rows_zero():
    """BERT-style key-padding mask with some rows fully masked: output 0
    for those queries (kernel l->0 semantics), no NaNs in grads."""
    q = _rand((2, 2, 256, 8), 4)
    k = _rand((2, 2, 256, 8), 5)
    v = _rand((2, 2, 256, 8), 6)
    keep = np.ones((2, 1, 1, 256), np.float32)
    keep[1] = 0.0  # batch 1: ALL keys masked
    # finfo.min mask (the BERT-layer convention) sits below the kernel's
    # -1e30 running-max floor, so fully-masked rows emit exact zeros
    bias = jnp.asarray((1.0 - keep) * np.finfo(np.float32).min)
    out = flash_attention(q, k, v, False, None, 128, 128, bias=bias)
    np.testing.assert_allclose(out[1], 0.0, atol=1e-6)
    g = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, False, None, 128, 128, bias=bias)))(q)
    assert np.isfinite(np.asarray(g)).all()


def test_segment_ids_forward_and_grad():
    """Packed-sequence segment masking (new TPU capability; the reference
    has no packing, SequenceShaper truncation only)."""
    q = _rand((2, 2, 200, 8), 7)
    k = _rand((2, 2, 200, 8), 8)
    v = _rand((2, 2, 200, 8), 9)
    rng = np.random.default_rng(0)
    segs = jnp.asarray(np.sort(rng.integers(0, 3, size=(2, 200)), axis=1)
                       .astype(np.int32))

    got = flash_attention(q, k, v, False, None, 64, 64,
                          q_segment_ids=segs, kv_segment_ids=segs)
    want = _attention_reference(q, k, v, False, 1.0 / np.sqrt(8),
                                q_seg=segs, kv_seg=segs)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, False, None, 64, 64,
            q_segment_ids=segs, kv_segment_ids=segs) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_attention_reference(
            q, k, v, False, 1.0 / np.sqrt(8), q_seg=segs,
            kv_seg=segs) ** 2)

    _grad_check(f_flash, f_ref, (q, k, v))


@pytest.mark.parametrize("causal", [False, True])
def test_dropout_forward_and_grad(causal):
    """Hash-derived dropout: the custom blockwise backward must reproduce
    the forward's exact mask (no stored mask) — grads match autodiff
    through the dense reference using the same hash."""
    q = _rand((1, 2, 200, 8), 10)
    k = _rand((1, 2, 200, 8), 11)
    v = _rand((1, 2, 200, 8), 12)
    seed = jnp.asarray([123, 7], jnp.int32)

    got = flash_attention(q, k, v, causal, None, 64, 64,
                          dropout_p=0.3, dropout_seed=seed)
    want = _attention_reference(q, k, v, causal, 1.0 / np.sqrt(8),
                                dropout_p=0.3, seed=seed)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, causal, None, 64, 64, dropout_p=0.3,
            dropout_seed=seed) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_attention_reference(
            q, k, v, causal, 1.0 / np.sqrt(8), dropout_p=0.3,
            seed=seed) ** 2)

    _grad_check(f_flash, f_ref, (q, k, v))


def test_dropout_statistics():
    """Dropout keeps ~(1-p) of probs and preserves the mean (inverted
    scaling); different seeds give different masks."""
    q = _rand((1, 1, 256, 8), 13)
    k = _rand((1, 1, 256, 8), 14)
    v = jnp.ones((1, 1, 256, 8), jnp.float32)
    clean = flash_attention(q, k, v, False, None, 128, 128)
    d1 = flash_attention(q, k, v, False, None, 128, 128,
                         dropout_p=0.5, dropout_seed=1)
    d2 = flash_attention(q, k, v, False, None, 128, 128,
                         dropout_p=0.5, dropout_seed=2)
    assert not np.allclose(d1, d2)
    # with v=1 every output row = sum of kept scaled probs; mean ~ 1
    np.testing.assert_allclose(np.mean(np.asarray(d1)), 
                               np.mean(np.asarray(clean)), rtol=0.05)


def test_pallas_kernel_interpret_training_config():
    """The ACTUAL Pallas kernel (interpret mode on CPU) with the full
    training config — padding mask + segment ids + dropout + causal —
    vs the dense oracle."""
    from analytics_zoo_tpu.ops.pallas.flash_attention import _flash_fwd_pallas

    q = _rand((2, 2, 130, 64), 15)
    k = _rand((2, 2, 130, 64), 16)
    v = _rand((2, 2, 130, 64), 17)
    keep = np.ones((2, 1, 1, 130), np.float32)
    keep[:, :, :, 100:] = 0.0
    bias = jnp.asarray((1.0 - keep) * -1e30)
    segs = jnp.asarray(
        np.repeat([[0] * 70 + [1] * 60], 2, 0).astype(np.int32))
    seed = jnp.asarray([5, 9], jnp.int32)
    got = _flash_fwd_pallas(q, k, v, True, 0.125, 64, 64, interpret=True,
                            bias=bias, q_seg=segs, kv_seg=segs,
                            dropout_p=0.2, seed=seed)
    want = _attention_reference(q, k, v, True, 0.125, bias=bias,
                                q_seg=segs, kv_seg=segs, dropout_p=0.2,
                                seed=seed)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_routing_training_config_reaches_pallas(monkeypatch):
    """VERDICT r03 weak #1 regression test: dot_product_attention with a
    BERT-style padded mask AND attention dropout (the realistic training
    config) must route to the Pallas kernel — exercised end-to-end in
    interpret mode on CPU."""
    import analytics_zoo_tpu.ops.pallas.flash_attention as fa
    from analytics_zoo_tpu.ops.attention import dot_product_attention

    monkeypatch.setenv("ZOO_FLASH_INTERPRET", "1")
    q = _rand((2, 2, 256, 64), 18)
    k = _rand((2, 2, 256, 64), 19)
    v = _rand((2, 2, 256, 64), 20)
    keep = np.ones((2, 1, 1, 256), np.float32)
    keep[:, :, :, 200:] = 0.0
    mask = jnp.asarray((1.0 - keep) * -1e9)
    rng = jax.random.PRNGKey(0)
    before = fa.invocation_counts["pallas"]
    out = dot_product_attention(q, k, v, mask=mask, dropout_p=0.1, rng=rng)
    assert fa.invocation_counts["pallas"] == before + 1, (
        "training-config attention (mask + dropout) fell back to the "
        "dense path")
    assert np.isfinite(np.asarray(out)).all()
    # grads flow through the custom blockwise backward
    g = jax.grad(lambda q: jnp.sum(dot_product_attention(
        q, k, v, mask=mask, dropout_p=0.1, rng=rng) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()


def test_flash_eligible_predicate():
    from analytics_zoo_tpu.ops.attention import flash_eligible

    q4 = (2, 12, 512, 64)
    # clean
    assert flash_eligible(q4, None, None, 0.0, False, 512)
    # BERT padding mask
    assert flash_eligible(q4, (2, 1, 1, 512), 4, 0.0, False, 512)
    # full bias
    assert flash_eligible(q4, (2, 12, 512, 512), 4, 0.0, False, 512)
    # dropout with rng ok, without rng not
    assert flash_eligible(q4, None, None, 0.1, True, 512)
    assert not flash_eligible(q4, None, None, 0.1, False, 512)
    # short seq / odd head dim stay on the jnp path
    assert not flash_eligible((2, 12, 128, 64), None, None, 0.0, False, 128)
    assert not flash_eligible((2, 12, 512, 40), None, None, 0.0, False, 512)
    # non-broadcastable mask shapes
    assert not flash_eligible(q4, (3, 1, 1, 512), 4, 0.0, False, 512)
    assert not flash_eligible(q4, (512, 512), 2, 0.0, False, 512)
    # explicit opt-out
    assert not flash_eligible(q4, None, None, 0.0, False, 512,
                              use_flash=False)


def test_bert_training_forward_routes_to_pallas(monkeypatch):
    """End-to-end: BERT layer *training* forward (attention dropout on,
    padded attention mask — reference BERT.scala:66 semantics) lowers to
    the Pallas flash kernel, not the dense O(L²) path.  VERDICT r03
    item 1 acceptance."""
    import analytics_zoo_tpu.ops.pallas.flash_attention as fa
    from analytics_zoo_tpu.pipeline.api.keras.layers import BERT

    monkeypatch.setenv("ZOO_FLASH_INTERPRET", "1")
    layer = BERT(vocab=100, hidden_size=768, n_block=1, n_head=12,
                 seq_len=256, intermediate_size=256)
    params = layer.init_params(jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 256), jnp.int32)
    types = jnp.zeros((2, 256), jnp.int32)
    attn_mask = jnp.asarray(
        np.repeat([[1] * 200 + [0] * 56], 2, 0).astype(np.float32))
    before = fa.invocation_counts["pallas"]
    seq, pooled = layer.call(params, [tokens, types, None, attn_mask],
                             training=True, rng=jax.random.PRNGKey(1))
    assert fa.invocation_counts["pallas"] > before, (
        "BERT training attention (dropout + padding mask) did not route "
        "to the Pallas kernel")
    assert np.isfinite(np.asarray(seq)).all()


def test_transformer_training_forward_routes_to_pallas(monkeypatch):
    """GPT-style TransformerLayer training (causal + attention dropout)
    lowers to the Pallas flash kernel."""
    import analytics_zoo_tpu.ops.pallas.flash_attention as fa
    from analytics_zoo_tpu.pipeline.api.keras.layers import TransformerLayer

    monkeypatch.setenv("ZOO_FLASH_INTERPRET", "1")
    layer = TransformerLayer(vocab=100, seq_len=256, n_block=1, n_head=4,
                             hidden_size=256, intermediate_size=256)
    params = layer.init_params(jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 256), jnp.int32)
    before = fa.invocation_counts["pallas"]
    out = layer.call(params, tokens, training=True,
                     rng=jax.random.PRNGKey(1))
    assert fa.invocation_counts["pallas"] > before, (
        "TransformerLayer training attention (causal + dropout) did not "
        "route to the Pallas kernel")
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.parametrize("causal", [False, True])
def test_attention_stats_matches_reference(causal):
    """(out, m, l) partial form: kernel (interpret) vs jnp reference, and
    the combine identity — two disjoint key halves merged with the flash
    update must equal full attention."""
    from analytics_zoo_tpu.ops.pallas.flash_attention import (
        _attention_stats_reference,
        _flash_fwd_pallas,
    )

    q = _rand((2, 2, 128, 64), 60)
    k = _rand((2, 2, 128, 64), 61)
    v = _rand((2, 2, 128, 64), 62)
    got = _flash_fwd_pallas(q, k, v, causal, 0.125, 64, 64,
                            interpret=True, return_stats=True)
    want = _attention_stats_reference(q, k, v, causal, 0.125)
    for a, b, name in zip(got, want, ("out", "m", "l")):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4,
                                   err_msg=name)

    if not causal:
        # combine two halves of the keys -> full attention
        o1, m1, l1 = _attention_stats_reference(q, k[:, :, :64],
                                                v[:, :, :64], False, 0.125)
        o2, m2, l2 = _attention_stats_reference(q, k[:, :, 64:],
                                                v[:, :, 64:], False, 0.125)
        m12 = np.maximum(m1, m2)
        a1, a2 = np.exp(m1 - m12), np.exp(m2 - m12)
        l12 = l1 * a1 + l2 * a2
        acc = (np.asarray(o1) * np.asarray(l1)[..., None] * a1[..., None]
               + np.asarray(o2) * np.asarray(l2)[..., None]
               * a2[..., None])
        full = _attention_reference(q, k, v, False, 0.125)
        np.testing.assert_allclose(acc / l12[..., None], full, rtol=1e-4,
                                   atol=1e-4)


import contextlib
import re


@contextlib.contextmanager
def _mosaic_module_spy():
    """Capture the raw (pre-serialization) Mosaic module of every pallas
    kernel lowered inside the block, and on exit reject the op class the
    chip compiler rejects but client-side lowering does not: a
    ``vector.shape_cast`` on a sub-32-bit element type that changes the
    minor dimension ("Insertion of minor dim that is not a no-op only
    supported for 32-bit types" — apply-vector-layout runs inside libtpu,
    so without this scan the failure only surfaces on the real chip; it
    did, twice, in round 4)."""
    import jax._src.tpu_custom_call as tcc

    captured = []
    orig = tcc._lower_mosaic_module_to_asm

    def spy(module, *a, **k):
        captured.append(str(module.operation))
        return orig(module, *a, **k)

    tcc._lower_mosaic_module_to_asm = spy
    try:
        yield
    finally:
        tcc._lower_mosaic_module_to_asm = orig
    # a vacuously-green guard is worse than none: if a jax upgrade stops
    # routing pallas lowering through the patched hook, fail loudly
    assert captured, (
        "Mosaic spy captured no modules — pallas lowering no longer goes "
        "through jax._src.tpu_custom_call._lower_mosaic_module_to_asm; "
        "re-point the spy")
    pat = re.compile(
        r"vector\.shape_cast.*?:\s*vector<([0-9x]+)x(i1|i8|i16|bf16|f16)>"
        r"\s*to\s*vector<([0-9x]+)x(?:i1|i8|i16|bf16|f16)>")
    bad = []
    for mod in captured:
        for m in pat.finditer(mod):
            src_minor = m.group(1).split("x")[-1]
            dst_minor = m.group(3).split("x")[-1]
            if src_minor != dst_minor:
                bad.append(m.group(0))
    assert not bad, (
        "sub-32-bit shape_cast changing the minor dim — lowers client-side "
        "but Mosaic's apply-vector-layout rejects it on the chip; build the "
        f"mask in the target orientation with broadcasted_iota instead: {bad}")


def test_mosaic_tpu_lowering_all_variants():
    """Cross-lower every production flash configuration for the TPU backend
    (no chip needed: Mosaic's block-shape validation — second-to-last dim
    divisible by 8 or full, last divisible by 128 or full — runs at lowering
    time).  Interpret-mode numerics tests cannot catch these; the round-4
    chip run failed exactly here on the (1, block) segment-id specs."""
    from analytics_zoo_tpu.ops.pallas.flash_attention import (
        _flash_fwd_pallas,
        _resolve_blocks,
    )

    B, H, L, D = 2, 2, 4096, 64
    q = jnp.zeros((B, H, L, D), jnp.bfloat16)
    segs = jnp.zeros((B, L), jnp.int32)
    bias = jnp.zeros((B, 1, 1, L), jnp.float32)
    seed = jnp.asarray([3, 11], jnp.int32)
    full_bias = jnp.zeros((B, 1, L, L), jnp.float32)
    variants = {
        "clean": dict(),
        "causal": dict(causal=True),
        "bias_dropout": dict(bias=bias, dropout_p=0.1, seed=seed),
        "full_bias": dict(bias=full_bias),
        "causal_seg_dropout": dict(causal=True, q_seg=segs, kv_seg=segs,
                                   dropout_p=0.1, seed=seed),
        "stats": dict(return_stats=True),
    }
    with _mosaic_module_spy():
        for name, kw in variants.items():
            b = kw.get("bias")
            bq, bk = _resolve_blocks(
                None, None,
                full_bias=b is not None and b.shape[-2] > 1,
                dropout=kw.get("dropout_p", 0) > 0)
            causal = kw.pop("causal", False)

            def fn(q, kw=kw, causal=causal, bq=bq, bk=bk):
                return _flash_fwd_pallas(q, q, q, causal, 0.125, bq, bk, **kw)

            jax.jit(fn).trace(q).lower(lowering_platforms=("tpu",))


@pytest.mark.parametrize("variant", [
    "clean", "causal", "bias", "bias_dropout", "seg_causal_dropout",
])
def test_pallas_backward_interpret_matches_reference(variant, monkeypatch):
    """The Pallas backward kernels (dq + dk/dv/dbias), run in interpret
    mode via the REAL custom_vjp route (ZOO_FLASH_INTERPRET -> pallas fwd
    saves stats -> pallas bwd), must match the dense oracle's grads for
    every training variant, on ragged multi-block shapes."""
    monkeypatch.setenv("ZOO_FLASH_INTERPRET", "1")
    b, h, lq, lk, d = 2, 2, 600, 700, 8
    q = _rand((b, h, lq, d), 30)
    k = _rand((b, h, lk, d), 31)
    v = _rand((b, h, lk, d), 32)
    rng = np.random.default_rng(3)
    segs_q = jnp.asarray(np.sort(rng.integers(0, 3, (b, lq)), 1), jnp.int32)
    segs_k = jnp.asarray(np.sort(rng.integers(0, 3, (b, lk)), 1), jnp.int32)
    bias = _rand((b, 1, 1, lk), 33) * 2.0
    seed = jnp.asarray([5, 9], jnp.int32)
    cfg = {
        "clean": (False, {}, {}),
        "causal": (True, {}, {}),
        "bias": (False, {"bias": bias}, {"bias": bias}),
        "bias_dropout": (False,
                         {"bias": bias, "dropout_p": 0.1,
                          "dropout_seed": seed},
                         {"bias": bias, "dropout_p": 0.1, "seed": seed}),
        "seg_causal_dropout": (True,
                               {"q_segment_ids": segs_q,
                                "kv_segment_ids": segs_k,
                                "dropout_p": 0.1, "dropout_seed": seed},
                               {"q_seg": segs_q, "kv_seg": segs_k,
                                "dropout_p": 0.1, "seed": seed}),
    }
    causal, kw_flash, kw_ref = cfg[variant]
    import analytics_zoo_tpu.ops.pallas.flash_attention as fa
    before = fa.invocation_counts["pallas"]

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, None,
                                       **kw_flash) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_attention_reference(
            q, k, v, causal, 1.0 / np.sqrt(d), **kw_ref) ** 2)

    _grad_check(f_flash, f_ref, (q, k, v), rtol=5e-4, atol=5e-4)
    # fwd + bwd kernels both fired (no silent jnp fallback)
    assert fa.invocation_counts["pallas"] >= before + 2, (
        "Pallas forward/backward did not both fire")
    if "bias" in variant:
        db1 = jax.grad(lambda bias: jnp.sum(flash_attention(
            q, k, v, causal, None,
            **{**kw_flash, "bias": bias}) ** 2))(bias)
        db2 = jax.grad(lambda bias: jnp.sum(_attention_reference(
            q, k, v, causal, 1.0 / np.sqrt(d),
            **{**kw_ref, "bias": bias}) ** 2))(bias)
        np.testing.assert_allclose(db1, db2, rtol=5e-4, atol=5e-4)


@pytest.mark.parametrize("D", [64, 128])
def test_mosaic_tpu_lowering_backward(D):
    """Cross-lower the Pallas BACKWARD kernels for the TPU backend at the
    production shapes — the same no-chip Mosaic block-rule guard as the
    forward test (a bwd-spec regression otherwise only fails on the
    chip).  head_dim 128 is the transformer-bench config (hidden 2560 /
    20 heads); 64 is BERT-base."""
    B, H, L = 2, 2, 4096
    q = jnp.zeros((B, H, L, D), jnp.bfloat16)
    segs = jnp.zeros((B, L), jnp.int32)
    bias = jnp.zeros((B, 1, 1, L), jnp.float32)
    seed = jnp.asarray([3, 11], jnp.int32)
    variants = {
        "clean": dict(),
        "bias_dropout": dict(bias=bias, dropout_p=0.1, dropout_seed=seed),
        "seg_causal": dict(causal=True, q_segment_ids=segs,
                           kv_segment_ids=segs),
    }
    import os

    # FORCE_PALLAS (not INTERPRET): interpret-mode pallas lowers to plain
    # jax ops and never reaches Mosaic, which made this guard vacuous in
    # round 4 — the i1 minor-dim shape_cast sailed through to the chip.
    # The forced route traces the REAL kernels; lowering needs no TPU.
    os.environ["ZOO_FLASH_FORCE_PALLAS"] = "1"
    try:
        with _mosaic_module_spy():
            for name, kw in variants.items():
                causal = kw.pop("causal", False)

                def fn(q, kw=kw, causal=causal):
                    return jnp.sum(flash_attention(q, q, q, causal, 0.125,
                                                   **kw) ** 2)

                jax.jit(jax.grad(fn)).trace(q).lower(
                    lowering_platforms=("tpu",))
    finally:
        os.environ.pop("ZOO_FLASH_FORCE_PALLAS", None)
