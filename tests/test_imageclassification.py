"""ImageClassifier zoo tests (reference imageclassification specs)."""

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.feature.image.imageset import ImageSet
from analytics_zoo_tpu.models.image.imageclassification import (
    ImageClassificationConfig,
    ImageClassifier,
    ImagenetConfig,
    LabelOutput,
)


class TestConfig:
    def test_imagenet_config_chain(self):
        cfg = ImagenetConfig(224)
        pre = cfg.preprocessing()
        img = np.random.default_rng(0).integers(
            0, 255, size=(300, 400, 3)).astype(np.uint8)
        out = pre(img)
        assert out.shape == (224, 224, 3)
        assert out.dtype == np.float32

    def test_grayscale_config(self):
        cfg = ImageClassificationConfig(resize=28, crop=28, mean=(0,),
                                        std=(255.0,))
        out = cfg.preprocessing()(np.full((32, 32, 1), 255, np.uint8))
        assert out.shape == (28, 28, 1)
        np.testing.assert_allclose(out, 1.0)


class TestLabelOutput:
    def test_topk_with_names(self):
        probs = np.array([[0.1, 0.7, 0.2]])
        out = LabelOutput({0: "cat", 1: "dog", 2: "fish"}, top_k=2)(probs)
        assert out[0][0] == ("dog", 0.7)
        assert out[0][1] == ("fish", 0.2)


class TestImageClassifier:
    def setup_method(self, _):
        init_zoo_context(seed=0)

    def test_wrap_custom_model_predict_image_set(self):
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Dense,
            Flatten,
        )
        from analytics_zoo_tpu.pipeline.api.keras.topology import Sequential

        net = Sequential()
        net.add(Flatten(input_shape=(8, 8, 3)))
        net.add(Dense(4, activation="softmax"))
        clf = ImageClassifier(
            model=net,
            config=ImageClassificationConfig(resize=8, crop=8,
                                             label_map={i: f"c{i}"
                                                        for i in range(4)}))
        imgs = ImageSet.from_arrays(
            np.random.default_rng(1).integers(
                0, 255, size=(6, 16, 16, 3)).astype(np.uint8))
        out = clf.predict_image_set(imgs, top_k=2)
        assert len(out) == 6
        assert len(out[0]) == 2
        name, p = out[0][0]
        assert name.startswith("c") and 0 <= p <= 1

    def test_resnet18_builds(self):
        clf = ImageClassifier("resnet-18", classes=10)
        clf.model.build_params()
        x = np.zeros((2, 224, 224, 3), np.float32)
        out, _ = clf.model.forward(clf.model.params, x,
                                   state=clf.model.state)
        assert out.shape == (2, 10)
