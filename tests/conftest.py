"""Test harness: force an 8-device CPU mesh so all psum/pjit/sharding code
paths run without TPUs — the analogue of the reference's local[4] Spark
testing strategy (SURVEY.md §4: pyzoo/test/zoo/pipeline/utils/test_utils.py
sets sparkConf local[4])."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    )

import jax  # noqa: E402

# The axon TPU plugin in this image ignores JAX_PLATFORMS; the config knob
# is honored.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def zoo_ctx():
    from analytics_zoo_tpu import init_zoo_context

    return init_zoo_context(seed=42)


@pytest.fixture()
def rng():
    import jax

    return jax.random.PRNGKey(0)
