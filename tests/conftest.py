"""Test harness: force an 8-device CPU mesh so all psum/pjit/sharding code
paths run without TPUs — the analogue of the reference's local[4] Spark
testing strategy (SURVEY.md §4: pyzoo/test/zoo/pipeline/utils/test_utils.py
sets sparkConf local[4])."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    )

import jax  # noqa: E402

# The axon TPU plugin in this image ignores JAX_PLATFORMS; the config knob
# is honored.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# ---------------------------------------------------------------------------
# zoosan pytest plugin: under ZOO_SAN=1 the runtime sanitizer installs
# BEFORE any test imports the package, so every lock the package creates
# is wrapped and the whole quick tier doubles as a sanitizer workload.
# Findings are passive (tests assert on the ones they plant); whatever
# is left at session end is reported in the terminal summary, and
# ZOO_SAN_STRICT=1 turns leftovers into a failing exit status.
# ---------------------------------------------------------------------------

if os.environ.get("ZOO_SAN") == "1":
    from analytics_zoo_tpu.analysis import sanitizer as _zoosan

    _zoosan.install()

# ---------------------------------------------------------------------------
# Quick tier (VERDICT r03 weak #10): `pytest -m quick` runs a <2-minute
# subset covering the end-to-end slice (compile/fit/evaluate/predict on the
# CPU mesh) plus every fast subsystem — the per-commit gate.  The full
# ~15-minute suite (examples retraining, transformer stacks, pipelines)
# stays the nightly/pre-merge gate.  Files are tier-marked here centrally
# so new tests in these files inherit the marker.
# ---------------------------------------------------------------------------

QUICK_FILES = {
    "test_config.py", "test_tfrecord.py", "test_safe_pickle.py",
    "test_tensorboard.py", "test_dataset.py", "test_minimum_slice.py",
    "test_onnx.py", "test_image_ops.py", "test_inference.py",
    "test_serving.py", "test_keras2.py", "test_caffe.py",
    "test_layer_oracle_enforcement.py", "test_api_docs.py",
    "test_textset.py", "test_image3d.py", "test_transfer_learning.py",
    "test_layer_serialization.py", "test_metrics.py",
    "test_prefetch.py",  # host data plane + --data-pipeline bench guard
    "test_dispatch.py",  # fused scan-K dispatch + --dispatch bench guard
    "test_autotune.py",  # closed-loop autotune + --autotune bench guard
    "test_compile_cache.py",  # persistent compile plane
    "test_partitioner.py",  # unified partitioner + --partition guard
    "test_partition_rules.py",  # rule matching + path rendering
    "test_zoolint.py",  # static analysis + package-clean CI gate
    "test_zoosan.py",  # whole-program pass + runtime sanitizer
    "test_telemetry.py",  # ~9s incl. two actor spawns
    "test_fleet.py",  # serving fleet: claim protocol, autoscaler, kill -9
    "test_overlap.py",  # latency-hiding plane + --overlap bench guard
    "test_elastic.py",  # elastic runtime: membership, chaos, supervisor
    "test_zoowatch.py",  # federation plane: scrape/SLO + two e2e guards
    # test_actors.py left OUT since the spawn switch: interpreter
    # startup per actor puts the file at ~5 min — nightly tier
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "quick: fast per-commit tier (<2 min; see conftest)")
    config.addinivalue_line(
        "markers", "metrics: observability-subsystem telemetry tests "
        "(analytics_zoo_tpu.metrics; tier-1 — not marked slow)")


def pytest_collection_modifyitems(config, items):
    for item in items:
        if os.path.basename(str(item.fspath)) in QUICK_FILES:
            item.add_marker(pytest.mark.quick)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    try:
        from analytics_zoo_tpu.analysis import sanitizer
    except Exception:
        return
    if not sanitizer.installed():
        return
    leftovers = sanitizer.findings()
    terminalreporter.section("zoosan (ZOO_SAN=1)")
    terminalreporter.line(
        f"runtime sanitizer active; {len(leftovers)} finding(s) left "
        "un-drained at session end"
        + (" — set ZOO_SAN_STRICT=1 to fail on these" if leftovers
           else ""))
    for f in leftovers[:25]:
        terminalreporter.line(
            f"  {f.path}:{f.line} [{f.rule}] {f.message[:100]}")


def pytest_sessionfinish(session, exitstatus):
    if os.environ.get("ZOO_SAN_STRICT") != "1":
        return
    try:
        from analytics_zoo_tpu.analysis import sanitizer
    except Exception:
        return
    if sanitizer.installed() and sanitizer.findings() \
            and session.exitstatus == 0:
        session.exitstatus = 1


@pytest.fixture()
def zoo_ctx():
    from analytics_zoo_tpu import init_zoo_context

    return init_zoo_context(seed=42)


@pytest.fixture()
def rng():
    import jax

    return jax.random.PRNGKey(0)
