"""Regex partition rules → PartitionSpec pytrees (parallel/partition.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class TestMatchPartitionRules:
    def test_first_match_wins_and_paths_join(self):
        from analytics_zoo_tpu.parallel.partition import (
            match_partition_rules,
        )

        params = {
            "dense_1": {"kernel": np.zeros((4, 8)), "bias": np.zeros(8)},
            "dense_2": {"kernel": np.zeros((8, 2)), "bias": np.zeros(2)},
            "embedding": {"table": np.zeros((16, 4))},
        }
        rules = [
            (r"dense_\d+/kernel", P(None, "model")),
            (r"embedding", P("model", None)),
            (r".*", P()),
        ]
        specs = match_partition_rules(rules, params)
        assert specs["dense_1"]["kernel"] == P(None, "model")
        assert specs["dense_2"]["kernel"] == P(None, "model")
        assert specs["dense_1"]["bias"] == P()
        assert specs["embedding"]["table"] == P("model", None)

    def test_scalars_never_partitioned(self):
        from analytics_zoo_tpu.parallel.partition import (
            match_partition_rules,
        )

        params = {"step": np.asarray(3), "scale": np.ones((1,))}
        specs = match_partition_rules([(r".*", P("data"))], params)
        assert specs["step"] == P()
        assert specs["scale"] == P()

    def test_unmatched_raises_with_name(self):
        from analytics_zoo_tpu.parallel.partition import (
            match_partition_rules,
        )

        with pytest.raises(ValueError, match="lstm/kernel"):
            match_partition_rules(
                [(r"dense", P())], {"lstm": {"kernel": np.zeros((2, 2))}})

    def test_list_and_tuple_paths(self):
        from analytics_zoo_tpu.parallel.partition import (
            match_partition_rules,
        )

        params = [{"w": np.zeros((2, 2))}, {"w": np.zeros((2, 2))}]
        specs = match_partition_rules(
            [(r"^1/w", P("model")), (r".*", P())], params)
        assert specs[0]["w"] == P()
        assert specs[1]["w"] == P("model")


class TestLeafPathName:
    """The rendering is the rule-matching CONTRACT — pinned here so
    regexes stay stable across jax versions (ISSUE 10 satellite)."""

    def _names(self, tree):
        from analytics_zoo_tpu.parallel.partition import leaf_path_name

        flat = jax.tree_util.tree_flatten_with_path(tree)[0]
        return [leaf_path_name(path) for path, _ in flat]

    def test_dict_list_tuple_rendering(self):
        tree = {"block": [{"w": np.zeros(2)}, {"w": np.zeros(2)}],
                "pair": (np.zeros(2), np.zeros(2))}
        assert self._names(tree) == \
            ["block/0/w", "block/1/w", "pair/0", "pair/1"]

    def test_dataclass_rendering(self):
        import dataclasses

        @jax.tree_util.register_pytree_node_class
        class Box:
            def __init__(self, a, b):
                self.a, self.b = a, b

            def tree_flatten(self):
                return (self.a, self.b), None

            @classmethod
            def tree_unflatten(cls, aux, children):
                return cls(*children)

        @dataclasses.dataclass
        class DC:
            kernel: object
            bias: object

        jax.tree_util.register_dataclass(
            DC, data_fields=["kernel", "bias"], meta_fields=[])
        names = self._names({"layer": DC(np.zeros(2), np.zeros(2))})
        assert names == ["layer/kernel", "layer/bias"]
        # opaque custom node: leaves get FlattenedIndexKey positions
        names = self._names({"box": Box(np.zeros(2), np.zeros(2))})
        assert names == ["box/0", "box/1"]

    def test_optax_state_paths_are_matchable(self):
        """The opt_rules=param_rules contract: adam moments render with
        the param path as a SUFFIX, so param regexes re.search-match."""
        import optax
        import re

        params = {"dense_1": {"kernel": np.zeros((4, 8))}}
        state = optax.adam(1e-2).init(params)
        names = self._names(state)
        assert any(n.endswith("dense_1/kernel") for n in names), names
        assert all(re.search(r"dense_1/kernel", n)
                   for n in names if "kernel" in n)


class TestReportUnused:
    def test_typo_regex_surfaces(self, caplog):
        """A typo'd rule silently replicating a whole model is the
        failure mode report_unused exists for (ISSUE 10 satellite)."""
        import logging

        from analytics_zoo_tpu.parallel.partition import (
            match_partition_rules,
        )

        params = {"dense": {"kernel": np.zeros((4, 8))}}
        rules = [(r"dense/kernl", P(None, "model")), (r".*", P())]
        with caplog.at_level(logging.WARNING, "analytics_zoo_tpu"):
            specs, unused = match_partition_rules(rules, params,
                                                  report_unused=True)
        assert unused == [r"dense/kernl"]
        assert specs["dense"]["kernel"] == P()  # fell through to catch-all
        assert any("zero leaves" in r.message for r in caplog.records)

    def test_all_rules_used_reports_empty(self):
        from analytics_zoo_tpu.parallel.partition import (
            match_partition_rules,
        )

        params = {"dense": {"kernel": np.zeros((4, 8)),
                            "bias": np.zeros(8)}}
        specs, unused = match_partition_rules(
            [(r"kernel", P(None, "model")), (r".*", P())], params,
            report_unused=True)
        assert unused == []

    def test_default_return_shape_unchanged(self):
        """report_unused=False (the default) keeps the bare-specs
        return every existing caller relies on."""
        from analytics_zoo_tpu.parallel.partition import (
            match_partition_rules,
        )

        specs = match_partition_rules(
            [(r".*", P())], {"w": np.zeros((2, 2))})
        assert specs == {"w": P()}


class TestShardParams:
    def test_device_put_lays_out_on_mesh(self):
        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.parallel.partition import shard_params

        ctx = init_zoo_context(mesh_shape={"data": 2, "model": 4}, seed=0)
        params = {
            "mlp": {"kernel": np.ones((8, 16), np.float32),
                    "bias": np.zeros(16, np.float32)},
        }
        sharded = shard_params(
            ctx.mesh,
            [(r"kernel", P(None, "model")), (r".*", P())],
            params,
        )
        k = sharded["mlp"]["kernel"]
        assert k.sharding.spec == P(None, "model")
        # 16 cols over model=4 → 4-col shards
        assert k.addressable_shards[0].data.shape == (8, 4)
        assert sharded["mlp"]["bias"].sharding.spec == P()
        np.testing.assert_array_equal(np.asarray(k), params["mlp"]["kernel"])

    def test_composes_with_tp_matmul(self):
        """Shard a kernel by rules, jit a matmul over it — result matches
        the unsharded oracle."""
        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.parallel.partition import shard_params

        ctx = init_zoo_context(mesh_shape={"data": 1, "model": 8}, seed=0)
        rng = np.random.default_rng(0)
        params = {"kernel": rng.normal(size=(8, 32)).astype(np.float32)}
        x = rng.normal(size=(4, 8)).astype(np.float32)
        sharded = shard_params(
            ctx.mesh, [(r"kernel", P(None, "model"))], params)
        out = jax.jit(lambda p, x: x @ p["kernel"])(sharded, x)
        np.testing.assert_allclose(
            np.asarray(out), x @ params["kernel"], atol=1e-5)
