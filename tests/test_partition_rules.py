"""Regex partition rules → PartitionSpec pytrees (parallel/partition.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class TestMatchPartitionRules:
    def test_first_match_wins_and_paths_join(self):
        from analytics_zoo_tpu.parallel.partition import (
            match_partition_rules,
        )

        params = {
            "dense_1": {"kernel": np.zeros((4, 8)), "bias": np.zeros(8)},
            "dense_2": {"kernel": np.zeros((8, 2)), "bias": np.zeros(2)},
            "embedding": {"table": np.zeros((16, 4))},
        }
        rules = [
            (r"dense_\d+/kernel", P(None, "model")),
            (r"embedding", P("model", None)),
            (r".*", P()),
        ]
        specs = match_partition_rules(rules, params)
        assert specs["dense_1"]["kernel"] == P(None, "model")
        assert specs["dense_2"]["kernel"] == P(None, "model")
        assert specs["dense_1"]["bias"] == P()
        assert specs["embedding"]["table"] == P("model", None)

    def test_scalars_never_partitioned(self):
        from analytics_zoo_tpu.parallel.partition import (
            match_partition_rules,
        )

        params = {"step": np.asarray(3), "scale": np.ones((1,))}
        specs = match_partition_rules([(r".*", P("data"))], params)
        assert specs["step"] == P()
        assert specs["scale"] == P()

    def test_unmatched_raises_with_name(self):
        from analytics_zoo_tpu.parallel.partition import (
            match_partition_rules,
        )

        with pytest.raises(ValueError, match="lstm/kernel"):
            match_partition_rules(
                [(r"dense", P())], {"lstm": {"kernel": np.zeros((2, 2))}})

    def test_list_and_tuple_paths(self):
        from analytics_zoo_tpu.parallel.partition import (
            match_partition_rules,
        )

        params = [{"w": np.zeros((2, 2))}, {"w": np.zeros((2, 2))}]
        specs = match_partition_rules(
            [(r"^1/w", P("model")), (r".*", P())], params)
        assert specs[0]["w"] == P()
        assert specs[1]["w"] == P("model")


class TestShardParams:
    def test_device_put_lays_out_on_mesh(self):
        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.parallel.partition import shard_params

        ctx = init_zoo_context(mesh_shape={"data": 2, "model": 4}, seed=0)
        params = {
            "mlp": {"kernel": np.ones((8, 16), np.float32),
                    "bias": np.zeros(16, np.float32)},
        }
        sharded = shard_params(
            ctx.mesh,
            [(r"kernel", P(None, "model")), (r".*", P())],
            params,
        )
        k = sharded["mlp"]["kernel"]
        assert k.sharding.spec == P(None, "model")
        # 16 cols over model=4 → 4-col shards
        assert k.addressable_shards[0].data.shape == (8, 4)
        assert sharded["mlp"]["bias"].sharding.spec == P()
        np.testing.assert_array_equal(np.asarray(k), params["mlp"]["kernel"])

    def test_composes_with_tp_matmul(self):
        """Shard a kernel by rules, jit a matmul over it — result matches
        the unsharded oracle."""
        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.parallel.partition import shard_params

        ctx = init_zoo_context(mesh_shape={"data": 1, "model": 8}, seed=0)
        rng = np.random.default_rng(0)
        params = {"kernel": rng.normal(size=(8, 32)).astype(np.float32)}
        x = rng.normal(size=(4, 8)).astype(np.float32)
        sharded = shard_params(
            ctx.mesh, [(r"kernel", P(None, "model"))], params)
        out = jax.jit(lambda p, x: x @ p["kernel"])(sharded, x)
        np.testing.assert_allclose(
            np.asarray(out), x @ params["kernel"], atol=1e-5)
