"""Actor runtime (RayOnSpark-equivalent generic distributed Python;
reference raycontext.py:192-393 + the @ray.remote examples under
pyzoo/zoo/examples/ray/)."""

import time

import numpy as np
import pytest

from analytics_zoo_tpu.parallel.actors import (
    ActorContext,
    ActorError,
    get,
    remote,
)


@remote
class Counter:
    def __init__(self, start=0):
        self.v = start

    def incr(self, by=1):
        self.v += by
        return self.v

    def value(self):
        return self.v

    def boom(self):
        raise ValueError("inside the actor")

    def slow_echo(self, x, delay=0.2):
        time.sleep(delay)
        return x


@remote
class ArrayStore:
    def __init__(self):
        self.arrays = {}

    def put(self, key, arr):
        self.arrays[key] = np.asarray(arr)
        return key

    def dot(self, a, b):
        return self.arrays[a] @ self.arrays[b]


@remote
def square(x):
    return x * x


@pytest.fixture()
def ctx():
    c = ActorContext.init()
    yield c
    c.stop()


def test_actor_method_calls_are_ordered(ctx):
    c = Counter.remote(10)
    refs = [c.incr.remote() for _ in range(5)]
    assert get(refs) == [11, 12, 13, 14, 15]
    assert c.value.remote().get() == 15


def test_actor_state_isolated_per_actor(ctx):
    a, b = Counter.remote(0), Counter.remote(100)
    a.incr.remote(5)
    b.incr.remote(7)
    assert get([a.value.remote(), b.value.remote()]) == [5, 107]


def test_numpy_payloads_roundtrip(ctx):
    s = ArrayStore.remote()
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    y = np.ones((4, 2), np.float32)
    get([s.put.remote("x", x), s.put.remote("y", y)])
    np.testing.assert_allclose(s.dot.remote("x", "y").get(), x @ y)


def test_actor_exception_surfaces_at_get(ctx):
    c = Counter.remote()
    ref = c.boom.remote()
    with pytest.raises(ActorError, match="inside the actor"):
        ref.get()
    # the actor survives its own exception
    assert c.incr.remote().get() == 1


def test_calls_to_different_actors_run_concurrently(ctx):
    actors = [Counter.remote() for _ in range(4)]
    t0 = time.perf_counter()
    refs = [a.slow_echo.remote(i, 0.4) for i, a in enumerate(actors)]
    assert get(refs) == [0, 1, 2, 3]
    dt = time.perf_counter() - t0
    assert dt < 1.2, f"4 x 0.4s calls took {dt:.2f}s — not concurrent"


def test_remote_function_pool(ctx):
    refs = [square.remote(i) for i in range(5)]
    assert get(refs) == [0, 1, 4, 9, 16]


def test_parameter_server_example_learns():
    """The reference's sync_parameter_server pattern end-to-end: loss on
    the digit shards drops under distributed SGD."""
    from examples.parameter_server.sync_parameter_server import run

    loss0, loss1 = run(num_workers=3, iterations=30)
    assert loss1 < 0.4 * loss0, (loss0, loss1)


def test_get_timeout_is_total_deadline(ctx):
    c = Counter.remote()
    ref = c.slow_echo.remote("x", 1.0)
    import time as _t

    t0 = _t.perf_counter()
    with pytest.raises(TimeoutError):
        ref.get(timeout=0.2)
    assert _t.perf_counter() - t0 < 0.8
    assert ref.get(timeout=5) == "x"  # still retrievable afterwards


def test_concurrent_getters_on_one_actor(ctx):
    import threading

    c = Counter.remote()
    refs = [c.slow_echo.remote(i, 0.15) for i in range(4)]
    results = {}

    def getter(i):
        results[i] = refs[i].get(timeout=10)

    threads = [threading.Thread(target=getter, args=(i,))
               for i in reversed(range(4))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == {0: 0, 1: 1, 2: 2, 3: 3}


def test_remote_rejects_non_module_level():
    def make():
        @remote
        def f(x):
            return x

    with pytest.raises(ValueError, match="module-level"):
        make()
    with pytest.raises(ValueError, match="module-level"):
        remote(lambda x: x)


def test_same_ref_concurrent_and_repeated_gets(ctx):
    import threading

    c = Counter.remote()
    ref = c.slow_echo.remote("v", 0.3)
    results = []
    threads = [threading.Thread(
        target=lambda: results.append(ref.get(timeout=10)))
        for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == ["v", "v", "v"]
    assert ref.get() == "v"  # repeated get returns the cached outcome


def test_fire_and_forget_replies_do_not_accumulate(ctx):
    import gc

    c = Counter.remote()
    for _ in range(50):
        c.incr.remote()          # refs dropped immediately
    gc.collect()
    assert c.value.remote().get(timeout=10) == 50
    # replies for the dropped refs were discarded by the reader
    assert len(c._results) == 0


def test_nested_actor_class_allowed(ctx):
    def make():
        @remote
        class Inner:
            def __init__(self):
                self.v = 7

            def get_v(self):
                return self.v

        return Inner

    handle = make().remote()
    assert handle.get_v.remote().get(timeout=10) == 7


class TestCrossHostActors:
    """Cross-host placement (VERDICT r4 missing #5): two worker servers
    stand in for two pod hosts; the same Ray-shaped surface places
    actors on them over the TCP transport (actor_worker.py)."""

    @pytest.fixture()
    def two_workers(self):
        from analytics_zoo_tpu.parallel.actor_worker import (
            start_worker_server,
        )

        srvs = [start_worker_server(0, bind="127.0.0.1", block=False)
                for _ in range(2)]
        addrs = [f"127.0.0.1:{s.getsockname()[1]}" for s in srvs]
        ActorContext.init(workers=addrs)
        yield addrs
        ActorContext.current().stop()
        for s in srvs:
            s.close()

    def test_explicit_placement_and_ordering(self, two_workers):
        a = Counter.options(worker=two_workers[0]).remote(0)
        b = Counter.options(worker=1).remote(100)
        refs = [a.incr.remote() for _ in range(5)]
        assert get(refs) == [1, 2, 3, 4, 5]      # TCP order = actor order
        assert b.value.remote().get() == 100     # isolated per actor

    def test_round_robin_default_placement(self, two_workers):
        handles = [Counter.remote(i) for i in range(4)]
        assert [h.value.remote().get() for h in handles] == [0, 1, 2, 3]
        # local spawn still available by explicit opt-out
        local = Counter.options(worker="local").remote(7)
        assert local.value.remote().get() == 7
        assert local._proc is not None           # really local
        assert all(h._proc is None for h in handles)  # really remote

    def test_numpy_payloads_and_errors_over_tcp(self, two_workers):
        s = ArrayStore.options(worker=0).remote()
        x = np.arange(12.0).reshape(3, 4)
        s.put.remote("k", x).get()
        s.put.remote("i", np.eye(4)).get()
        np.testing.assert_array_equal(s.dot.remote("k", "i").get(), x)
        c = Counter.options(worker=0).remote()
        with pytest.raises(ActorError, match="boom"):
            c.boom.remote().get()

    def test_parameter_server_across_hosts(self, two_workers):
        """The reference's flagship RayOnSpark pattern, spanning hosts:
        a PS on worker 0, a rollout actor on worker 1."""
        @remote
        class PS:
            def __init__(self, d):
                self.w = np.zeros(d, np.float32)

            def push(self, g):
                self.w -= 0.5 * g

            def pull(self):
                return self.w

        @remote
        class Rollout:
            def grad(self, w):
                return 2.0 * (np.asarray(w) - 1.0)

        ps = PS.options(worker=0).remote(4)
        ro = Rollout.options(worker=1).remote()
        for _ in range(6):
            w = ps.pull.remote().get()
            ps.push.remote(ro.grad.remote(w).get()).get()
        # x' = x - 0.5*2(x-1): converges to 1 in one step, stays
        np.testing.assert_allclose(ps.pull.remote().get(), 1.0)
