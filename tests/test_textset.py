"""TextSet depth: parquet ingestion, word-index persistence, relation
readers (VERDICT r03 missing #5; reference TextSet.scala:207-243/372/687,
feature/common/Relations.scala:43-85)."""

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu.feature.text import (
    Relation,
    TextSet,
    read_relations_csv,
    read_relations_parquet,
)


def test_read_parquet(tmp_path):
    path = str(tmp_path / "texts.parquet")
    pd.DataFrame({
        "id": ["a", "b", "c"],
        "text": ["hello world", "the quick fox", "hello again"],
    }).to_parquet(path)
    ts = TextSet.read_parquet(path)
    assert len(ts) == 3
    assert [f.uri for f in ts.features] == ["a", "b", "c"]
    assert ts.features[1].text == "the quick fox"


def test_word_index_save_load_roundtrip(tmp_path):
    ts = TextSet.from_texts(["the cat sat", "the dog sat down"])
    ts.tokenize().normalize().word2idx()
    path = str(tmp_path / "word_index.txt")
    ts.save_word_index(path)

    # inference-time set: fresh TextSet reuses the saved index exactly
    # (TextSet.scala:243 loadWordIndex -> word2idx needs no arguments)
    ts2 = TextSet.from_texts(["the cat ran"]).tokenize().normalize()
    ts2.load_word_index(path)
    ts2.word2idx()
    wi = ts.get_word_index()
    got = ts2.features[0].indices
    assert got[0] == wi["the"]
    assert got[1] == wi["cat"]
    assert got[2] == 0  # "ran" unseen -> padding index


def test_save_word_index_requires_word2idx(tmp_path):
    ts = TextSet.from_texts(["abc"])
    with pytest.raises(ValueError, match="wordIndex"):
        ts.save_word_index(str(tmp_path / "wi.txt"))


def test_set_word_index_drives_word2idx():
    ts = TextSet.from_texts(["b a"]).tokenize().normalize()
    ts.set_word_index({"a": 1, "b": 2})
    ts.word2idx()
    np.testing.assert_array_equal(ts.features[0].indices, [2, 1])


def test_relations_parquet_and_csv(tmp_path):
    pq = str(tmp_path / "rel.parquet")
    pd.DataFrame({
        "id1": ["q1", "q1", "q2"],
        "id2": ["d1", "d2", "d3"],
        "label": [1, 0, 1],
    }).to_parquet(pq)
    rels = read_relations_parquet(pq)
    assert rels == [Relation("q1", "d1", 1), Relation("q1", "d2", 0),
                    Relation("q2", "d3", 1)]

    csv = tmp_path / "rel.csv"
    csv.write_text("id1,id2,label\nq1,d1,1\nq1,d2,0\n")
    assert read_relations_csv(str(csv)) == rels[:2]


def test_parquet_to_ranking_pipeline(tmp_path):
    """End-to-end: parquet corpus + parquet relations -> word2idx ->
    shaped -> pairwise arrays (the qaranker ingestion path)."""
    cpq = str(tmp_path / "corpus.parquet")
    pd.DataFrame({
        "id": ["q1", "d1", "d2"],
        "text": ["what is tall", "a very tall tower", "a short wall"],
    }).to_parquet(cpq)
    rpq = str(tmp_path / "rels.parquet")
    pd.DataFrame({"id1": ["q1", "q1"], "id2": ["d1", "d2"],
                  "label": [1, 0]}).to_parquet(rpq)

    corpus = TextSet.read_parquet(cpq).tokenize().normalize().word2idx()
    corpus.shape_sequence(6)
    rels = read_relations_parquet(rpq)
    q = TextSet([f for f in corpus.features if f.uri.startswith("q")],
                corpus.word_index)
    d = TextSet([f for f in corpus.features if f.uri.startswith("d")],
                corpus.word_index)
    qa, da, y = TextSet.from_relation_pairs(rels, q, d)
    assert qa.shape == (2, 6) and da.shape == (2, 6)
    np.testing.assert_array_equal(y[:, 0], [1, 0])
