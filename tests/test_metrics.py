"""Observability subsystem (ISSUE 1): registry semantics, exporters,
span tracing, serving/estimator telemetry wiring — plus regression tests
for the satellite fixes that rode the same PR (actor-worker auth, bench
flag-probe validation, ZeRO-1 reshard exact matching)."""

import json
import math
import os
import socket
import threading
import time

import numpy as np
import pytest

from analytics_zoo_tpu.metrics import (
    NULL,
    JsonlExporter,
    MetricsRegistry,
    TensorBoardExporter,
    Tracer,
    get_registry,
    prometheus_text,
    set_registry,
    set_tracer,
    snapshot,
    span,
)

# The `metrics` marker selects the observability-subsystem tests; the
# satellite-regression classes at the bottom of this file ride the same
# PR but are deliberately NOT tagged (they test actor auth / bench /
# reshard, not telemetry).
metrics_mark = pytest.mark.metrics


@pytest.fixture()
def fresh_registry():
    """Swap in a private process-global registry; restore after."""
    reg = MetricsRegistry()
    prev = set_registry(reg)
    try:
        yield reg
    finally:
        set_registry(prev)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


@metrics_mark
class TestRegistry:
    def test_counter_and_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("req_total", "requests", ("route",))
        c.labels(route="/a").inc()
        c.labels(route="/a").inc(2)
        c.labels(route="/b").inc(5)
        assert c.labels(route="/a").get() == 3
        assert c.labels(route="/b").get() == 5
        with pytest.raises(ValueError):
            c.labels(route="/a").inc(-1)  # counters only go up
        with pytest.raises(ValueError):
            c.labels(wrong="x")  # undeclared label name

    def test_gauge(self):
        reg = MetricsRegistry()
        g = reg.gauge("depth", "")
        g.set(7)
        g.inc(3)
        g.dec(1)
        assert g.get() == 9

    def test_reregistration_conflicts(self):
        reg = MetricsRegistry()
        reg.counter("m", "")
        assert reg.counter("m", "") is reg.counter("m", "")  # idempotent
        with pytest.raises(ValueError):
            reg.gauge("m", "")  # kind conflict
        with pytest.raises(ValueError):
            reg.counter("m", "", ("l",))  # label conflict
        h = reg.histogram("h", "", buckets=(1, 2))
        assert reg.histogram("h", "") is h  # no buckets -> no check
        assert reg.histogram("h", "", buckets=(2, 1)) is h  # same bounds
        with pytest.raises(ValueError):
            reg.histogram("h", "", buckets=(1, 2, 4))  # bucket conflict

    def test_histogram_percentiles(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "", buckets=(1, 2, 4, 8, 16))
        for v in range(1, 9):  # uniform on (0, 8]
            h.observe(v)
        s = h.summary()
        assert s["count"] == 8 and s["sum"] == 36
        # p50 of uniform(0,8] sits in the (2,4] bucket; interpolation
        # keeps it within one bucket width of the true 4.0
        assert 2.0 <= s["p50"] <= 4.0
        # true p99 is 8; the estimate stays inside its (4, 8] bucket
        assert 4.0 <= s["p99"] <= 8.0
        # le= semantics are inclusive: value == bound lands in that bucket
        h2 = reg.histogram("lat2", "", buckets=(1, 2))
        h2.observe(1.0)
        assert dict(h2._default().buckets())[1.0] == 1
        # +Inf-bucket quantiles report the TAIL mean, not the overall
        # mean clamped to the last bound: 95 fast steps + 5 huge stalls
        # must surface the stall magnitude at p99
        h3 = reg.histogram("lat3", "", buckets=(1, 10))
        for _ in range(95):
            h3.observe(0.01)
        for _ in range(5):
            h3.observe(120.0)
        assert h3.percentile(0.99) == pytest.approx(120.0)

    def test_histogram_timer(self):
        reg = MetricsRegistry()
        h = reg.histogram("t", "")
        with h.time():
            pass
        assert h.summary()["count"] == 1

    def test_disabled_registry_is_allocation_free_noop(self):
        reg = MetricsRegistry(enabled=False)
        # every factory returns the ONE shared singleton: the hot path
        # never allocates children, label tuples, or timer objects
        assert reg.counter("a", "") is NULL
        assert reg.gauge("b", "") is NULL
        assert reg.histogram("c", "") is NULL
        assert NULL.labels(x="1") is NULL
        assert NULL.time() is NULL.time()  # shared no-op timer too
        NULL.inc()
        NULL.set(3)
        NULL.observe(0.1)  # all silently no-op
        assert reg.collect() == []
        # side-channel gate: work done ONLY to feed a metric (e.g. the
        # serving queue-depth xlen round-trip) keys off this flag
        from analytics_zoo_tpu.metrics import ServingMetrics

        assert ServingMetrics(reg).enabled is False

    def test_thread_safety(self):
        reg = MetricsRegistry()
        c = reg.counter("n", "")
        h = reg.histogram("h", "", buckets=(0.5,))

        def work():
            for _ in range(1000):
                c.inc()
                h.observe(0.1)

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.get() == 8000
        assert h.summary()["count"] == 8000


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------


def _populated_registry():
    reg = MetricsRegistry()
    reg.counter("zoo_req_total", "requests", ("route",)).labels(
        route="/predict").inc(4)
    reg.gauge("zoo_depth", "queue depth").set(2)
    h = reg.histogram("zoo_lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    return reg


@metrics_mark
class TestExporters:
    def test_prometheus_text(self):
        text = prometheus_text(_populated_registry())
        lines = text.splitlines()
        assert "# TYPE zoo_req_total counter" in lines
        assert 'zoo_req_total{route="/predict"} 4.0' in lines
        assert "# TYPE zoo_lat_seconds histogram" in lines
        # cumulative buckets end with the +Inf total == _count
        assert 'zoo_lat_seconds_bucket{le="0.1"} 1' in lines
        assert 'zoo_lat_seconds_bucket{le="1.0"} 2' in lines
        assert 'zoo_lat_seconds_bucket{le="+Inf"} 3' in lines
        assert "zoo_lat_seconds_count 3" in lines
        sum_line = [l for l in lines
                    if l.startswith("zoo_lat_seconds_sum")][0]
        assert math.isclose(float(sum_line.split()[-1]), 5.55)

    def test_jsonl_roundtrip(self, tmp_path):
        reg = _populated_registry()
        path = str(tmp_path / "m.jsonl")
        exp = JsonlExporter(path, reg)
        exp.write(step=1)
        reg.gauge("zoo_depth", "").set(9)
        exp.write(step=2)
        docs = [json.loads(l) for l in open(path)]
        assert len(docs) == 2 and docs[1]["step"] == 2
        by_name = {s["name"]: s for s in docs[1]["samples"]
                   if "labels" not in s}
        assert by_name["zoo_depth"]["value"] == 9
        assert by_name["zoo_lat_seconds"]["count"] == 3

    def test_metrics_dump_tool(self, tmp_path, capsys):
        import importlib.util
        import sys

        reg = _populated_registry()
        path = str(tmp_path / "m.jsonl")
        JsonlExporter(path, reg).write()
        spec = importlib.util.spec_from_file_location(
            "metrics_dump", os.path.join(os.path.dirname(__file__), "..",
                                         "tools", "metrics_dump.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        old_argv = sys.argv
        sys.argv = ["metrics_dump.py", path]
        try:
            mod.main()
        finally:
            sys.argv = old_argv
        out = capsys.readouterr().out
        assert "zoo_lat_seconds" in out and "zoo_depth" in out

    def test_tensorboard_bridge(self, tmp_path):
        from analytics_zoo_tpu.tensorboard import TrainSummary

        reg = _populated_registry()
        w = TrainSummary(str(tmp_path), "metrics-test")
        n = TensorBoardExporter(w, reg).export(step=3)
        w.close()
        assert n > 0
        scal = w.read_scalar("zoo_depth")
        assert scal and scal[0][0] == 3 and scal[0][1] == 2.0
        p50 = w.read_scalar("zoo_lat_seconds/p50")
        assert p50 and p50[0][1] > 0


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------


@metrics_mark
class TestTracing:
    def test_nested_spans_chrome_trace(self, tmp_path):
        t = Tracer(jax_bridge=False)
        with span("outer", tracer=t):
            with span("inner", args={"k": 1}, tracer=t):
                time.sleep(0.001)
        doc = t.to_chrome_trace()
        json.dumps(doc)  # serializable
        evs = {e["name"]: e for e in doc["traceEvents"]}
        assert set(evs) == {"outer", "inner"}
        for e in evs.values():
            assert e["ph"] == "X" and e["dur"] >= 0 and "ts" in e \
                and "pid" in e and "tid" in e
        assert evs["inner"]["args"]["parent"] == "outer"
        assert evs["inner"]["args"]["k"] == 1
        # inner is contained in outer's interval
        assert evs["outer"]["ts"] <= evs["inner"]["ts"]
        assert (evs["inner"]["ts"] + evs["inner"]["dur"]
                <= evs["outer"]["ts"] + evs["outer"]["dur"] + 1e-3)
        p = t.save(str(tmp_path / "trace.json"))
        assert json.load(open(p))["traceEvents"]

    def test_span_sync_blocks_on_device_values(self):
        import jax.numpy as jnp

        t = Tracer(jax_bridge=False)
        x = jnp.ones((8, 8))
        with span("compute", sync=x @ x, tracer=t):
            pass
        assert t.events()[0]["name"] == "compute"

    def test_event_cap_keeps_newest_counts_drops(self):
        t = Tracer(jax_bridge=False, max_events=2)
        for i in range(5):
            with span(f"s{i}", tracer=t):
                pass
        # ring buffer: the NEWEST window survives (a day-2 anomaly must
        # be capturable), evictions are counted
        assert [e["name"] for e in t.events()] == ["s3", "s4"]
        assert t.to_chrome_trace()["metadata"]["dropped_events"] == 3

    def test_disabled_tracer_records_nothing(self):
        t = Tracer(enabled=False)
        with span("x", tracer=t):
            pass
        assert t.events() == []


# ---------------------------------------------------------------------------
# wiring: serving + estimator telemetry land in the default registry
# ---------------------------------------------------------------------------


def _tiny_classifier(tmp_path):
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, Flatten
    from analytics_zoo_tpu.pipeline.api.keras.topology import Sequential

    m = Sequential()
    m.add(Flatten(input_shape=(4, 4, 1)))
    m.add(Dense(5, activation="softmax"))
    m.build_params()
    path = str(tmp_path / "model.zoo")
    m.save(path)
    return path


@metrics_mark
class TestServingTelemetry:
    def test_step_populates_queue_latency_and_broker_gauge(
            self, tmp_path, fresh_registry):
        from analytics_zoo_tpu.serving import (
            ClusterServing,
            ClusterServingHelper,
            InMemoryBroker,
            InputQueue,
        )

        broker = InMemoryBroker()
        serving = ClusterServing(
            ClusterServingHelper(model_path=_tiny_classifier(tmp_path),
                                 batch_size=4, data_shape=(4, 4, 1),
                                 log_dir=str(tmp_path / "logs")),
            broker=broker)
        inq = InputQueue(broker=broker)
        for i in range(6):
            inq.enqueue_image(f"u{i}", np.zeros((4, 4, 1), np.float32))
        served = serving.step(block_ms=0)
        assert served == 4
        reg = fresh_registry
        # latency histogram populated by the non-empty step
        lat = reg.histogram("zoo_serving_step_latency_seconds", "")
        assert lat.summary()["count"] == 1 and lat.summary()["sum"] > 0
        assert reg.histogram("zoo_serving_batch_size", "").summary() != {}
        assert reg.counter("zoo_serving_records_total", "").get() == 4
        # queue depth observed AFTER the poll: 2 records remain
        assert reg.gauge("zoo_serving_queue_depth", "").get() == 2
        # broker memory_ratio published as a gauge (broker.py wiring)
        g = reg.gauge("zoo_serving_broker_memory_ratio", "").get()
        assert 0.0 <= g <= 1.0
        # inference layer: per-bucket compile count + predict latency
        text = prometheus_text(reg)
        assert "zoo_inference_compiles_total" in text
        assert "zoo_inference_predict_seconds_count" in text
        serving.summary.close()

    def test_prometheus_export_after_serving_is_valid(
            self, tmp_path, fresh_registry):
        from analytics_zoo_tpu.serving import (
            ClusterServing,
            ClusterServingHelper,
            InMemoryBroker,
            InputQueue,
        )

        broker = InMemoryBroker()
        serving = ClusterServing(
            ClusterServingHelper(model_path=_tiny_classifier(tmp_path),
                                 batch_size=2, data_shape=(4, 4, 1),
                                 log_dir=str(tmp_path / "logs")),
            broker=broker)
        InputQueue(broker=broker).enqueue_image(
            "one", np.zeros((4, 4, 1), np.float32))
        serving.step(block_ms=0)
        text = prometheus_text(fresh_registry)
        # every family has a TYPE line and histograms end at +Inf == count
        assert "# TYPE zoo_serving_step_latency_seconds histogram" in text
        inf_line = [l for l in text.splitlines()
                    if l.startswith("zoo_serving_step_latency_seconds_"
                                    "bucket") and 'le="+Inf"' in l][0]
        count_line = [l for l in text.splitlines()
                      if l.startswith(
                          "zoo_serving_step_latency_seconds_count")][0]
        assert inf_line.split()[-1] == count_line.split()[-1]
        # idle polls record NO spans: an idle loop must not flood the
        # bounded tracer with zero-information events
        t = Tracer(jax_bridge=False)
        prev = set_tracer(t)
        try:
            assert serving.step(block_ms=0) == 0
            assert t.events() == []
        finally:
            set_tracer(prev)
        serving.summary.close()


@metrics_mark
class TestEstimatorTelemetry:
    def test_fit_records_step_breakdown(self, fresh_registry, zoo_ctx):
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
        from analytics_zoo_tpu.pipeline.api.keras.topology import (
            Sequential,
        )

        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 8)).astype(np.float32)
        y = rng.integers(0, 4, size=(64,)).astype(np.int32)
        m = Sequential()
        m.add(Dense(4, activation="softmax", input_shape=(8,)))
        m.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy")
        m.fit(x, y, batch_size=32, nb_epoch=1)
        reg = fresh_registry
        assert reg.counter("zoo_train_steps_total", "").get() == 2
        assert reg.counter("zoo_train_records_total", "").get() == 64
        for name in ("zoo_train_data_wait_seconds",
                     "zoo_train_step_dispatch_seconds",
                     "zoo_train_step_seconds"):
            assert reg.histogram(name, "").summary()["count"] == 2, name
        assert reg.gauge("zoo_train_throughput_records_per_sec",
                         "").get() > 0
        # span() instrumentation is on by default: the fit loop produced
        # zoo.train.step events in the default tracer
        from analytics_zoo_tpu.metrics import get_tracer

        assert any(e["name"] == "zoo.train.step_dispatch"
                   for e in get_tracer().events())


@metrics_mark
class TestPipelineTelemetry:
    def test_gpipe_records_bubble_metrics(self, fresh_registry):
        import jax.numpy as jnp

        import analytics_zoo_tpu as zoo
        from analytics_zoo_tpu.parallel.pipeline import gpipe

        zoo.init_zoo_context(seed=0, mesh_shape={"data": 2, "pipe": 4},
                             mesh_axes=("data", "pipe"))
        stages = jnp.ones((4, 6, 6)) * 0.5

        def stage_fn(w, h):
            return jnp.tanh(h @ w)

        x = jnp.ones((8, 6))
        try:
            out = gpipe(stage_fn, stages, x, n_microbatch=4)
            assert out.shape == (8, 6)
        except AttributeError:
            # this image's jax lacks jax.shard_map (pre-existing for all
            # pipeline schedules here); the schedule metrics under test
            # are recorded before the shard_map construction
            pass
        reg = fresh_registry
        g = reg.gauge("zoo_pipeline_bubble_fraction", "", ("schedule",))
        # GPipe bubble: (S-1)/(M+S-1) = 3/7
        assert g.labels(schedule="gpipe").get() == pytest.approx(3 / 7)
        per_mb = reg.gauge("zoo_pipeline_bubble_ticks_per_microbatch",
                           "", ("schedule",))
        assert per_mb.labels(schedule="gpipe").get() == \
            pytest.approx(3 / 4)

    def test_1f1b_records_bubble_metrics(self, fresh_registry):
        import jax
        import jax.numpy as jnp

        import analytics_zoo_tpu as zoo
        from analytics_zoo_tpu.parallel.pipeline import gpipe_1f1b_grads

        zoo.init_zoo_context(seed=0, mesh_shape={"data": 2, "pipe": 4},
                             mesh_axes=("data", "pipe"))
        S, M = 4, 8
        stages = jnp.ones((S, 6, 6)) * 0.1

        def stage_fn(w, h):
            return jnp.tanh(h @ w)

        def loss_fn(o, t):
            return jnp.mean((o - t) ** 2)

        x = jnp.ones((16, 6))
        try:
            gpipe_1f1b_grads(stage_fn, loss_fn, stages, x, x,
                             n_microbatch=M)
        except AttributeError:
            pass  # pre-shim jax: metrics still recorded at trace time
        g = fresh_registry.gauge("zoo_pipeline_bubble_fraction", "",
                                 ("schedule",))
        # dual fwd/bwd schedule: T = M + 2S - 1 ticks, each stream
        # idles 2S - 1 of them -> 7/15 (NOT 6/15: the fwd->bwd offset
        # at the last stage costs one extra tick)
        assert g.labels(schedule="1f1b").get() == pytest.approx(7 / 15)


# ---------------------------------------------------------------------------
# satellite regressions
# ---------------------------------------------------------------------------


class TestActorWorkerAuth:
    """ADVICE r05 medium: loopback default + shared-secret handshake
    before any unpickling."""

    def test_default_bind_is_loopback(self):
        from analytics_zoo_tpu.parallel.actor_worker import (
            start_worker_server,
        )

        srv = start_worker_server(0, block=False)
        try:
            assert srv.getsockname()[0] == "127.0.0.1"
        finally:
            srv.close()

    def test_nonloopback_bind_requires_secret_or_optin(self, monkeypatch):
        from analytics_zoo_tpu.parallel.actor_worker import (
            start_worker_server,
        )

        monkeypatch.delenv("ZOO_ACTOR_SECRET", raising=False)
        with pytest.raises(ValueError, match="secret"):
            start_worker_server(0, bind="0.0.0.0", block=False)
        srv = start_worker_server(0, bind="0.0.0.0", block=False,
                                  secret="tok")
        srv.close()
        srv = start_worker_server(0, bind="0.0.0.0", block=False,
                                  allow_unauthenticated=True)
        srv.close()

    def test_handshake_gates_unpickling(self):
        from analytics_zoo_tpu.parallel.actor_worker import (
            _HELLO_AUTH,
            SockConn,
            _client_proof,
            _server_proof,
            start_worker_server,
        )

        srv = start_worker_server(0, block=False, secret="s3cret")
        port = srv.getsockname()[1]
        try:
            # correct secret: passes auth, reaches the frame dispatcher
            # (a bad spawn kind comes back as init_error — proof the
            # server processed our pickle AFTER auth).  Mutual: the
            # server's counter-proof must verify too.
            c = SockConn(socket.create_connection(("127.0.0.1", port),
                                                  timeout=10))
            hello = c.recv_bytes(timeout=10, max_len=64)
            assert hello.startswith(_HELLO_AUTH)
            challenge = hello[len(_HELLO_AUTH):]
            nonce = os.urandom(32)
            c.send_bytes(nonce + _client_proof(b"s3cret", challenge,
                                               nonce))
            counter = c.recv_bytes(timeout=10, max_len=64)
            assert counter == _server_proof(b"s3cret", challenge, nonce)
            c.send(("not-spawn", None))
            kind, _ = c.recv()
            assert kind == "init_error"
            c.close()

            # wrong secret: connection closed before any unpickling
            c = SockConn(socket.create_connection(("127.0.0.1", port),
                                                  timeout=10))
            c.recv_bytes(timeout=10, max_len=64)
            c.send_bytes(b"\x00" * 32)
            c.send(("spawn", b"evil"))
            with pytest.raises((EOFError, OSError, TimeoutError)):
                for _ in range(10):  # server closes; recv must fail
                    c.poll(0.2)
                    c.recv()
            c.close()
        finally:
            srv.close()

    def test_secret_presence_mismatch_fails_fast(self, monkeypatch):
        """Hello frame announces the auth mode: a driver/worker secret
        mismatch raises immediately (either direction), no 30s hang."""
        from analytics_zoo_tpu.parallel.actor_worker import (
            connect_and_spawn,
            start_worker_server,
        )

        monkeypatch.delenv("ZOO_ACTOR_SECRET", raising=False)
        # worker authenticated, driver without a secret
        srv = start_worker_server(0, block=False, secret="s3cret")
        addr = "127.0.0.1:%d" % srv.getsockname()[1]
        try:
            with pytest.raises(RuntimeError, match="requires a shared"):
                connect_and_spawn(addr, b"payload")
        finally:
            srv.close()
        # worker open, driver configured with a secret: refuse downgrade
        srv = start_worker_server(0, block=False)
        addr = "127.0.0.1:%d" % srv.getsockname()[1]
        try:
            with pytest.raises(RuntimeError, match="unauthenticated"):
                connect_and_spawn(addr, b"payload", secret="s3cret")
        finally:
            srv.close()
        # WRONG secret value (both ends authenticated): the server's
        # silent close surfaces as an auth error, not a bare EOFError
        srv = start_worker_server(0, block=False, secret="right")
        addr = "127.0.0.1:%d" % srv.getsockname()[1]
        try:
            with pytest.raises(RuntimeError,
                               match="WRONG shared secret"):
                connect_and_spawn(addr, b"payload", secret="wrong")
        finally:
            srv.close()

    def test_options_secret_reaches_connect(self, monkeypatch):
        """The public actor API (`.options(secret=...)`) plumbs the
        shared secret down to connect_and_spawn for drivers that cannot
        set ZOO_ACTOR_SECRET."""
        import analytics_zoo_tpu.parallel.actor_worker as aw
        from analytics_zoo_tpu.parallel.actors import _RemoteClass

        seen = {}

        def fake_connect(addr, payload, secret=None):
            seen["addr"], seen["secret"] = addr, secret
            raise RuntimeError("stop-here")

        monkeypatch.setattr(aw, "connect_and_spawn", fake_connect)

        class Dummy:
            pass

        import analytics_zoo_tpu.parallel.actors as actors_mod

        ctx = actors_mod.ActorContext.current()
        monkeypatch.setattr(
            ctx, "_resolve_worker", lambda w: w, raising=False)
        rc = _RemoteClass(Dummy).options(worker="127.0.0.1:9040",
                                         secret="vault-token")
        with pytest.raises(RuntimeError, match="stop-here"):
            rc.remote()
        assert seen == {"addr": "127.0.0.1:9040",
                        "secret": "vault-token"}

    def test_spoofed_server_rejected_before_driver_unpickles(self):
        """Mutual auth: an endpoint that speaks the hello protocol but
        cannot produce the server counter-proof is refused BEFORE the
        driver deserializes anything it sends."""
        from analytics_zoo_tpu.parallel.actor_worker import (
            _HELLO_AUTH,
            _LEN,
            connect_and_spawn,
        )

        srv = socket.create_server(("127.0.0.1", 0))
        addr = "127.0.0.1:%d" % srv.getsockname()[1]

        def fake_worker():
            sock, _ = srv.accept()
            frame = _HELLO_AUTH + b"\x00" * 32
            sock.sendall(_LEN.pack(len(frame)) + frame)
            sock.recv(4096)  # client's nonce+proof (useless to us)
            bogus = b"\x11" * 32  # cannot forge _server_proof
            sock.sendall(_LEN.pack(len(bogus)) + bogus)
            sock.close()

        t = threading.Thread(target=fake_worker, daemon=True)
        t.start()
        try:
            with pytest.raises(RuntimeError, match="prove knowledge"):
                connect_and_spawn(addr, b"payload", secret="s3cret")
        finally:
            srv.close()

    def test_oversized_preauth_frame_rejected(self):
        from analytics_zoo_tpu.parallel.actor_worker import (
            SockConn,
            start_worker_server,
        )

        srv = start_worker_server(0, block=False, secret="s3cret")
        port = srv.getsockname()[1]
        try:
            c = SockConn(socket.create_connection(("127.0.0.1", port),
                                                  timeout=10))
            c.recv_bytes(timeout=10, max_len=64)
            c.send_bytes(b"\x00" * 4096)  # > pre-auth 64-byte limit
            with pytest.raises((EOFError, OSError, TimeoutError)):
                for _ in range(10):
                    c.poll(0.2)
                    c.recv()
            c.close()
        finally:
            srv.close()


class TestBenchFlagAdoption:
    """ADVICE r05 low (bench.py:136): sweep flags must be validated in a
    probe subprocess WITH the flags applied before being adopted."""

    @pytest.fixture()
    def bench(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "zoo_bench", os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    @pytest.fixture()
    def sweep_file(self, tmp_path):
        path = str(tmp_path / "FLAGSWEEP.json")
        with open(path, "w") as f:
            json.dump({"best": "combo", "gain_pct": 2.0,
                       "results": {"combo": {
                           "flags": "--xla_tpu_fake_flag=1"}}}, f)
        return path

    def test_flags_probed_before_adoption(self, bench, sweep_file,
                                          monkeypatch):
        monkeypatch.delenv("XLA_FLAGS", raising=False)
        seen = {}

        def fake_probe(timeout, env=None):
            seen["env"] = env
            return True, "tpu 4"

        adopted = bench.adopt_sweep_flags(probe=fake_probe,
                                          path=sweep_file)
        assert adopted == "combo (+2.0%)"
        # probe child saw the candidate flags...
        assert "--xla_tpu_fake_flag=1" in seen["env"]["XLA_FLAGS"]
        # ...and only then were they committed to this process
        assert os.environ["XLA_FLAGS"] == "--xla_tpu_fake_flag=1"

    def test_failed_probe_skips_adoption(self, bench, sweep_file,
                                         monkeypatch):
        monkeypatch.delenv("XLA_FLAGS", raising=False)
        adopted = bench.adopt_sweep_flags(
            probe=lambda t, env=None: (False, "Unknown flag"),
            path=sweep_file)
        assert adopted is None
        assert "XLA_FLAGS" not in os.environ

    def test_cpu_fallback_probe_skips_adoption(self, bench, sweep_file,
                                               monkeypatch):
        monkeypatch.delenv("XLA_FLAGS", raising=False)
        adopted = bench.adopt_sweep_flags(
            probe=lambda t, env=None: (True, "cpu 1"), path=sweep_file)
        assert adopted is None
        assert "XLA_FLAGS" not in os.environ


class TestReshardZero1:
    """ADVICE r05 low (strategies.py:219): flat vectors matched by exact
    padded length; everything else replicated, never truncated."""

    def test_exact_match_and_replication(self):
        import jax

        import analytics_zoo_tpu as zoo
        from analytics_zoo_tpu.parallel import reshard_zero1_opt_state

        # model axis soaks up the spare devices: leftover devices would
        # otherwise fold INTO the data axis (engine._infer_mesh_shape)
        zoo.init_zoo_context(seed=0, mesh_shape={"data": 4, "model": 2})
        params = {"w": np.arange(10.0, dtype=np.float32)}  # size 10
        padded_old = 16  # saved under n_old=8: 10 + 6 pad
        opt_state = {
            "mu": np.arange(padded_old, dtype=np.float32),
            "nu": np.ones(padded_old, np.float32),
            "count": np.zeros((), np.float32),
            # coincidental 1-D leaf LONGER than the flat layout: the old
            # `size >= param_size` match would truncate + force-shard it
            "odd": np.arange(17, dtype=np.float32),
            # coincidental 1-D leaf BETWEEN size and the padded length:
            # the shared-length preference must not let this unique
            # length shadow the mu/nu mirrors' agreed padded length
            "odd2": np.arange(12, dtype=np.float32),
            # ndim>=1 leaf whose dim 0 the new mesh cannot divide: the
            # old force-shard P(DATA_AXIS) made device_put fail
            "mat": np.ones((3, 3), np.float32),
        }
        for n_old in (8, None):  # explicit and inferred old layouts
            out = reshard_zero1_opt_state(opt_state, params, n_old=n_old)
            # matched vectors: pad stripped, re-padded for n_new=4 -> 12
            assert out["mu"].shape == (12,)
            np.testing.assert_array_equal(
                np.asarray(out["mu"])[:10], opt_state["mu"][:10])
            assert np.asarray(out["mu"])[10:].sum() == 0
            # non-matching leaves: untouched values, replicated layout
            np.testing.assert_array_equal(np.asarray(out["odd"]),
                                          opt_state["odd"])
            np.testing.assert_array_equal(np.asarray(out["odd2"]),
                                          opt_state["odd2"])
            np.testing.assert_array_equal(np.asarray(out["mat"]),
                                          opt_state["mat"])
            assert out["odd"].sharding.is_fully_replicated
            assert out["odd2"].sharding.is_fully_replicated
            assert out["mat"].sharding.is_fully_replicated
            assert not out["mu"].sharding.is_fully_replicated
            assert out["count"].shape == ()


# ---------------------------------------------------------------------------
# ISSUE 2 satellites: exposition name hygiene, disabled-mode exporters,
# tracer eviction counter
# ---------------------------------------------------------------------------


@metrics_mark
class TestPrometheusNameHygiene:
    """Satellite regression: registry names are unconstrained (dotted
    span-style names are natural), but the exposition must stay inside
    the Prometheus charset instead of emitting invalid series."""

    def test_dots_and_invalid_chars_sanitized(self):
        from analytics_zoo_tpu.metrics import sanitize_metric_name

        reg = MetricsRegistry()
        reg.counter("zoo.serving.step_total", "dotted").inc(2)
        reg.gauge("weird name-metric", "").set(1)
        h = reg.histogram("zoo.lat.seconds", "", buckets=(1.0,))
        h.observe(0.5)
        text = prometheus_text(reg)
        import re

        name_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
        for line in text.splitlines():
            if line.startswith("#"):
                continue
            name = line.split("{")[0].split(" ")[0]
            assert name_re.match(name), f"invalid exposition name {name!r}"
        assert "zoo_serving_step_total 2.0" in text
        assert "weird_name_metric 1.0" in text
        assert 'zoo_lat_seconds_bucket{le="1.0"} 1' in text
        # leading digit gets a prefix, valid names pass through untouched
        assert sanitize_metric_name("9lives") == "_9lives"
        assert sanitize_metric_name("zoo_ok_total") == "zoo_ok_total"

    def test_label_names_sanitized(self):
        reg = MetricsRegistry()
        reg.counter("c_total", "", ("my.label",)).labels(
            **{"my.label": "v"}).inc()
        text = prometheus_text(reg)
        assert 'c_total{my_label="v"} 1.0' in text

    def test_label_name_collisions_get_deterministic_suffix(self):
        # "a.b" and "a_b" both sanitize to a_b: a duplicate label name
        # inside one sample is invalid exposition, so one key gets a
        # stable crc32 suffix
        reg = MetricsRegistry()
        reg.counter("c_total", "", ("a.b", "a_b")).labels(
            **{"a.b": "1", "a_b": "2"}).inc()
        text = prometheus_text(reg)
        line = [l for l in text.splitlines()
                if l.startswith("c_total{")][0]
        import re

        names = re.findall(r'([a-zA-Z0-9_]+)="', line)
        assert len(names) == len(set(names)) == 2
        assert "a_b" in names
        assert prometheus_text(reg) == text  # deterministic

    def test_sanitize_collisions_get_deterministic_suffix(self):
        # two DISTINCT registry names mapping onto one exposition name
        # must not emit duplicate TYPE blocks (a parser rejects the
        # whole body) — the later one gets a stable crc32 suffix
        reg = MetricsRegistry()
        reg.counter("zoo.lat_total", "").inc(1)
        reg.counter("zoo_lat_total", "").inc(2)
        text = prometheus_text(reg)
        type_lines = [l for l in text.splitlines()
                      if l.startswith("# TYPE")]
        names = [l.split()[2] for l in type_lines]
        assert len(names) == len(set(names)) == 2
        assert "zoo_lat_total" in names
        suffixed = next(n for n in names if n != "zoo_lat_total")
        assert suffixed.startswith("zoo_lat_total_x")
        # deterministic: a second render produces the same names
        assert prometheus_text(reg) == text


@metrics_mark
class TestDisabledExporters:
    """Satellite: every exporter against the ZOO_METRICS=0 no-op
    registry must produce empty-but-valid output and allocate no
    families/children per call."""

    def test_disabled_registry_hands_out_null_only(self):
        reg = MetricsRegistry(enabled=False)
        assert reg.counter("a_total", "") is NULL
        assert reg.gauge("g", "") is NULL
        assert reg.gauge("g", "").labels() is NULL
        assert reg.histogram("h_seconds", "") is NULL
        assert reg.counter("a_total", "", ("l",)).labels(l="x") is NULL
        assert reg.collect() == []  # nothing was ever allocated

    def test_prometheus_text_empty_but_valid(self):
        reg = MetricsRegistry(enabled=False)
        reg.counter("a_total", "").inc(5)
        assert prometheus_text(reg) == ""

    def test_jsonl_empty_but_valid(self, tmp_path):
        reg = MetricsRegistry(enabled=False)
        reg.histogram("h", "").observe(1.0)
        path = str(tmp_path / "m.jsonl")
        doc = JsonlExporter(path, reg).write(step=7)
        assert doc["samples"] == [] and doc["step"] == 7
        line = json.loads(open(path).read())
        assert line["samples"] == []

    def test_tensorboard_export_writes_nothing(self):
        class Writer:
            def __init__(self):
                self.calls = []

            def add_scalar(self, *a):
                self.calls.append(a)

        reg = MetricsRegistry(enabled=False)
        reg.gauge("g", "").set(3)
        w = Writer()
        assert TensorBoardExporter(w, reg).export(step=1) == 0
        assert w.calls == []

    def test_no_allocation_per_call(self):
        reg = MetricsRegistry(enabled=False)
        for _ in range(100):
            reg.counter("x_total", "").inc()
            reg.histogram("y_seconds", "").observe(0.1)
        assert reg.collect() == []  # still zero families
        # the snapshot side allocates nothing either
        from analytics_zoo_tpu.metrics import telemetry_snapshot

        assert telemetry_snapshot(reg)["samples"] == []


@metrics_mark
class TestTracerDropCounter:
    def test_ring_evictions_increment_registry_counter(self,
                                                       fresh_registry):
        t = Tracer(jax_bridge=False, max_events=2)
        for i in range(5):
            with span(f"s{i}", tracer=t):
                pass
        assert t.dropped == 3
        c = fresh_registry.counter(
            "zoo_trace_spans_dropped_total", "")
        assert c.get() == 3
        # and /varz carries the same number without needing /trace
        from analytics_zoo_tpu.metrics import MetricsServer

        srv = MetricsServer(port=0, host="127.0.0.1",
                            registry=fresh_registry, tracer=t).start()
        try:
            import urllib.request

            doc = json.loads(urllib.request.urlopen(
                srv.url + "/varz", timeout=10).read())
            assert doc["trace"]["dropped_spans"] == 3
            assert any(s["name"] == "zoo_trace_spans_dropped_total"
                       and s["value"] == 3 for s in doc["samples"])
        finally:
            srv.stop()


@metrics_mark
class TestHistogramDeltaSince:
    """Histogram.snapshot_state/delta_since — the rolling-window reader
    controllers use (feature/autotune.py) instead of lifetime blurs."""

    def _hist(self):
        from analytics_zoo_tpu.metrics import MetricsRegistry

        return MetricsRegistry().histogram(
            "h", "", buckets=(0.001, 0.01, 0.1, 1.0))

    def test_window_sees_only_recent_observations(self):
        h = self._hist()
        for _ in range(50):
            h.observe(0.0005)  # old regime: sub-ms
        base = h.snapshot_state()
        for _ in range(10):
            h.observe(0.5)  # new regime: half a second
        d = h.delta_since(base)
        assert d["count"] == 10
        assert d["p50"] > 0.1  # the window reflects the NEW regime...
        assert h.summary()["p50"] < 0.01  # ...while lifetime still blurs
        assert abs(d["sum"] - 5.0) < 1e-9
        assert abs(d["mean"] - 0.5) < 1e-9

    def test_empty_window(self):
        h = self._hist()
        h.observe(0.05)
        base = h.snapshot_state()
        d = h.delta_since(base)
        assert d == {"count": 0, "sum": 0.0, "mean": 0.0,
                     "p50": 0.0, "p95": 0.0, "p99": 0.0}

    def test_none_baseline_is_lifetime(self):
        h = self._hist()
        h.observe(0.05)
        assert h.delta_since(None) == h.summary()

    def test_partial_window_spanning_merged_buckets(self):
        h = self._hist()
        h.observe(0.0005)
        base = h.snapshot_state()
        # the window spans three different buckets + the +Inf tail
        for v in (0.005, 0.005, 0.05, 0.5, 2.0):
            h.observe(v)
        d = h.delta_since(base)
        assert d["count"] == 5
        assert 0.001 < d["p50"] <= 0.1
        assert d["p99"] >= 1.0  # the +Inf-tail observation is visible

    def test_mismatched_bucket_layout_raises(self):
        from analytics_zoo_tpu.metrics import MetricsRegistry

        h = self._hist()
        other = MetricsRegistry().histogram("h2", "", buckets=(0.1,))
        other.observe(0.05)
        with pytest.raises(ValueError, match="buckets"):
            h.delta_since(other.snapshot_state())

    def test_reset_baseline_degrades_to_full_summary(self):
        h = self._hist()
        h.observe(0.05)
        h.observe(0.05)
        ahead = (list(h.snapshot_state()[0]), 99.0, 99, 0.0)
        ahead[0][0] += 100  # a baseline AHEAD of the child (reset case)
        d = h.delta_since(tuple(ahead))
        assert d == h.summary()

    def test_null_metric_parity(self):
        from analytics_zoo_tpu.metrics import NULL

        assert NULL.snapshot_state() is None
        assert NULL.delta_since(None) == {}
