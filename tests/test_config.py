"""Typed ZooConfig (reference three-tier conf, NNContext.scala:188-237)
+ the estimator profiler/timing knobs."""

import glob
import os

import numpy as np
import pytest

from analytics_zoo_tpu import ZooConfig, init_zoo_context
from analytics_zoo_tpu.common.utils import get_timings, reset_timings


def _fit_tiny(nb_epoch=1):
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int32)
    m = Sequential()
    m.add(Dense(2, activation="softmax", input_shape=(4,)))
    m.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
    m.fit(x, y, batch_size=8, nb_epoch=nb_epoch)
    return m


def test_zooconfig_from_dict_and_env(monkeypatch):
    monkeypatch.setenv("ZOO_FAILURE_RETRY_TIMES", "2")
    monkeypatch.setenv("ZOO_INFEED_DEPTH", "3")
    ctx = init_zoo_context({"app_name": "t", "seed": 11})
    assert ctx.config.seed == 11
    assert ctx.config.failure_retry_times == 2   # env tier
    assert ctx.config.infeed_depth == 3
    # explicit arg beats env
    ctx = init_zoo_context(ZooConfig(failure_retry_times=9))
    assert ctx.config.failure_retry_times == 9


def test_unknown_conf_key_rejected():
    with pytest.raises(ValueError, match="unknown conf"):
        init_zoo_context({"not_a_knob": 1})


def test_profiler_knob_writes_trace(tmp_path):
    prof = str(tmp_path / "prof")
    init_zoo_context(ZooConfig(profile_dir=prof, profile_steps=2))
    _fit_tiny(nb_epoch=2)
    traces = glob.glob(os.path.join(prof, "**", "*.trace.json.gz"),
                       recursive=True)
    assert traces, f"no trace under {prof}"
    init_zoo_context(seed=0)  # reset global ctx for other tests


def test_time_it_records_infeed_and_step():
    init_zoo_context(seed=0)
    reset_timings()
    _fit_tiny()
    t = get_timings()
    assert "zoo.infeed" in t and "zoo.step_dispatch" in t
    assert t["zoo.step_dispatch"]["count"] == 8  # 64/8 batches


def test_explicit_value_beats_env(monkeypatch):
    monkeypatch.setenv("ZOO_FAILURE_RETRY_TIMES", "0")
    # explicit value equal to the default must still win over env
    ctx = init_zoo_context({"failure_retry_times": 5})
    assert ctx.config.failure_retry_times == 5


def test_caller_config_not_mutated():
    cfg = ZooConfig(seed=3)
    ctx = init_zoo_context(cfg, seed=42)
    assert ctx.config.seed == 42  # explicit kwarg wins over config
    assert cfg.seed == 3  # caller's object untouched


def test_profiler_fires_with_tiny_epochs(tmp_path):
    # 3-step epochs: the capture must still happen (armed per fit, not
    # per epoch)
    prof = str(tmp_path / "prof2")
    init_zoo_context(ZooConfig(profile_dir=prof, profile_steps=2))
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    rng = np.random.default_rng(0)
    x = rng.normal(size=(24, 4)).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int32)
    m = Sequential()
    m.add(Dense(2, activation="softmax", input_shape=(4,)))
    m.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
    m.fit(x, y, batch_size=8, nb_epoch=4)  # 3 steps/epoch
    traces = glob.glob(os.path.join(prof, "**", "*.trace.json.gz"),
                       recursive=True)
    assert traces, "no trace captured with 3-step epochs"
    init_zoo_context(seed=0)


def test_async_checkpoint_roundtrip(tmp_path):
    """Async saves must survive donation and resume exactly (the save's
    device copies are taken before the next step donates the buffers)."""
    import glob as g

    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    init_zoo_context(seed=0)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int32)

    def fresh():
        m = Sequential()
        m.add(Dense(2, activation="softmax", input_shape=(4,)))
        m.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
        m.set_checkpoint(str(tmp_path / "ck"))
        return m

    m = fresh()
    m.fit(x, y, batch_size=8, nb_epoch=3)
    ref = m.evaluate(x, y, batch_size=8)
    assert g.glob(str(tmp_path / "ck" / "ckpt-*.pkl"))

    # resume into a fresh process-equivalent: same eval after 0 extra work
    m2 = fresh()
    m2.fit(x, y, batch_size=8, nb_epoch=3)  # absolute target reached: noop
    res = m2.evaluate(x, y, batch_size=8)
    assert abs(res["loss"] - ref["loss"]) < 1e-6


def test_checkpoint_schema_version(tmp_path):
    """Checkpoints carry a format_version (VERDICT r03 weak #9: bare
    pickle with no schema); newer-format snapshots are refused, legacy
    (unversioned) ones still load."""
    import pickle

    import jax.numpy as jnp

    from analytics_zoo_tpu.pipeline.estimator.estimator import _Checkpointer

    ck = _Checkpointer(str(tmp_path / "ck"))
    ck.save("0000", {"params": {"w": jnp.ones((2,))}, "step": 3})
    ck._wait()
    raw = pickle.load(open(ck.list()[-1], "rb"))
    assert raw["__ckpt_meta__"]["format_version"] == 1
    got = ck.latest()
    assert "__ckpt_meta__" not in got and got["step"] == 3

    # legacy snapshot (no meta) loads as version 0
    legacy = str(tmp_path / "ck" / "ckpt-0001.pkl")
    with open(legacy, "wb") as f:
        pickle.dump({"step": 9}, f)
    assert ck.latest()["step"] == 9

    # future snapshot is refused with a clear error
    future = str(tmp_path / "ck" / "ckpt-0002.pkl")
    with open(future, "wb") as f:
        pickle.dump({"__ckpt_meta__": {"format_version": 99},
                     "step": 1}, f)
    with pytest.raises(ValueError, match="format_version"):
        ck.latest()
