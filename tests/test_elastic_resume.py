"""Elastic resume across mesh shapes (SURVEY §5 slice-down restart;
VERDICT r4 missing #6): a checkpoint written under one data-axis size must
resume under another — both the estimator path (save on {data:8}, resume
on {data:4} and 4→8, ZOO_SHARD_OPTIMIZER ZeRO-1 leaves included) and the
explicit shard_map ZeRO-1 layout (reshard_zero1_opt_state re-pads the
flat-vector shards).

The oracle is the straight-through run: SPMD math is mesh-size-invariant
(the global batch schedule depends only on (seed, epoch)), so the resumed
curve must equal the uninterrupted one to float tolerance.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _data():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    w = np.random.default_rng(1).normal(size=(8, 4))
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return x, y


def _fit(mesh_size, ckpt_dir, epochs, plan=None):
    """One training leg on a {data: mesh_size} mesh; absolute epoch
    target so a second call RESUMES from ckpt_dir.  ``plan`` selects a
    sharding plan (parallel/plan.py) for the leg."""
    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    zoo.init_zoo_context(seed=3, mesh_shape={"data": mesh_size})
    x, y = _data()
    m = Sequential()
    m.add(Dense(16, activation="relu", input_shape=(8,)))
    m.add(Dense(4, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    if ckpt_dir:
        m.set_checkpoint(ckpt_dir)
    m.fit(x, y, batch_size=32, nb_epoch=epochs, plan=plan)
    res = m.evaluate(x, y, batch_size=32)
    return {"losses": [h["loss"] for h in m._estimator.history],
            "eval": res}


@pytest.mark.parametrize("n_save,n_resume", [(8, 4), (4, 8)])
def test_estimator_resume_across_mesh_sizes(tmp_path, n_save, n_resume):
    ckdir = str(tmp_path / f"ck_{n_save}to{n_resume}")
    full = _fit(n_save, None, 4)

    first = _fit(n_save, ckdir, 2)
    np.testing.assert_allclose(first["losses"], full["losses"][:2],
                               rtol=1e-4, atol=1e-5)

    resumed = _fit(n_resume, ckdir, 4)
    # resume really happened: only epochs 3..4 trained on the NEW mesh
    assert len(resumed["losses"]) == 2, resumed["losses"]
    np.testing.assert_allclose(resumed["losses"], full["losses"][2:],
                               rtol=1e-4, atol=1e-5)
    assert abs(resumed["eval"]["loss"] - full["eval"]["loss"]) < 1e-4


def test_estimator_resume_with_sharded_optimizer(tmp_path, monkeypatch):
    """ZeRO-1 (GSPMD) leaves ride the same checkpoint as global logical
    arrays: 8 -> 4 with ZOO_SHARD_OPTIMIZER=1 on both legs."""
    monkeypatch.setenv("ZOO_SHARD_OPTIMIZER", "1")
    ckdir = str(tmp_path / "ck_zero1")
    full = _fit(8, None, 4)
    _fit(8, ckdir, 2)
    resumed = _fit(4, ckdir, 4)
    assert len(resumed["losses"]) == 2
    np.testing.assert_allclose(resumed["losses"], full["losses"][2:],
                               rtol=1e-4, atol=1e-5)


def test_estimator_resume_fsdp_plan_across_mesh_sizes(tmp_path):
    """Elastic resume through the UNIFIED PARTITIONER (ISSUE 10): save
    under the {data: 8} fsdp plan, resume under {data: 4} — the
    checkpoint stores global logical arrays and the resume leg reshards
    them through the plan's placement, so the continuation is BIT-EXACT
    against the uninterrupted 8-mesh run (generalizes the zero1 special
    case: no flat-vector heuristic involved)."""
    ckdir = str(tmp_path / "ck_fsdp")
    full = _fit(8, None, 4, plan="fsdp")

    first = _fit(8, ckdir, 2, plan="fsdp")
    assert first["losses"] == full["losses"][:2]  # bitwise

    resumed = _fit(4, ckdir, 4, plan="fsdp")
    assert len(resumed["losses"]) == 2, resumed["losses"]
    assert resumed["losses"] == full["losses"][2:]  # bitwise
    assert abs(resumed["eval"]["loss"] - full["eval"]["loss"]) < 1e-6


@pytest.mark.parametrize("plan", ["zero1", "zero2", "zero3"])
def test_estimator_resume_zero_plans_across_mesh_sizes(tmp_path, plan):
    """The full ZeRO ladder through the unified partitioner (ISSUE 14):
    save under the {data: 8} plan, resume under {data: 4} — same
    global-logical-array checkpoint, same plan placement at load, so
    every tier's continuation is BIT-EXACT against its own
    uninterrupted 8-mesh run."""
    ckdir = str(tmp_path / f"ck_{plan}")
    full = _fit(8, None, 4, plan=plan)

    first = _fit(8, ckdir, 2, plan=plan)
    assert first["losses"] == full["losses"][:2]  # bitwise

    resumed = _fit(4, ckdir, 4, plan=plan)
    assert len(resumed["losses"]) == 2, resumed["losses"]
    assert resumed["losses"] == full["losses"][2:]  # bitwise
    assert abs(resumed["eval"]["loss"] - full["eval"]["loss"]) < 1e-6


def test_estimator_resume_across_plans(tmp_path):
    """A checkpoint saved under fsdp resumes under plain DP (and the
    reverse direction of the memory ladder): the partitioner reshards
    at load, and placement never changes the math — the fsdp-saved →
    dp-resumed trajectory is bit-exact too."""
    ckdir = str(tmp_path / "ck_cross")
    full = _fit(8, None, 4, plan="fsdp")
    _fit(8, ckdir, 2, plan="fsdp")
    resumed = _fit(8, ckdir, 4, plan=None)  # dp leg over an fsdp save
    assert resumed["losses"] == full["losses"][2:]  # bitwise


class TestExplicitZero1Reshard:
    """The shard_map ZeRO-1 layout pads the flat param vector to the
    data-axis size, so ITS state needs real resharding."""

    def _setup(self, mesh_size):
        import optax

        import analytics_zoo_tpu as zoo
        from analytics_zoo_tpu.parallel.strategies import (
            make_zero1_train_step,
        )
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
        from analytics_zoo_tpu.pipeline.api.keras.objectives import (
            get_loss,
        )

        zoo.init_zoo_context(seed=3, mesh_shape={"data": mesh_size})
        m = Sequential()
        m.add(Dense(16, activation="relu", input_shape=(8,)))
        m.add(Dense(4, activation="softmax"))
        params, state = m.build_params()
        opt = optax.adam(1e-2)
        loss = get_loss("sparse_categorical_crossentropy")
        step, init_opt = make_zero1_train_step(m, loss, opt)
        return m, params, state, step, init_opt

    def test_8_to_4_matches_uninterrupted(self):
        from analytics_zoo_tpu.parallel import reshard_zero1_opt_state

        x, y = _data()
        batch = {"x": jnp.asarray(x[:64]), "y": jnp.asarray(y[:64])}
        rng = jax.random.PRNGKey(0)

        # leg A: 4 steps straight through on 8
        m, p, st, step8, init8 = self._setup(8)
        o = init8(p)
        for _ in range(4):
            p, o, st, l_full = step8(p, o, st, rng, batch)
        p_full = jax.tree_util.tree_map(np.asarray, p)

        # leg B: 2 steps on 8, "checkpoint" to host, resume 2 more on 4
        m, p, st, step8, init8 = self._setup(8)
        o = init8(p)
        for _ in range(2):
            p, o, st, _ = step8(p, o, st, rng, batch)
        saved = jax.tree_util.tree_map(np.asarray, (p, o, st))

        m4, _, _, step4, _ = self._setup(4)
        p4, o4, st4 = saved
        from analytics_zoo_tpu.common.engine import get_zoo_context

        ctx4 = get_zoo_context()
        p4 = jax.device_put(p4, ctx4.replicated())
        st4 = jax.device_put(st4, ctx4.replicated())
        o4 = reshard_zero1_opt_state(o4, p4)
        for _ in range(2):
            p4, o4, st4, l4 = step4(p4, o4, st4, rng, batch)
        p_resumed = jax.tree_util.tree_map(np.asarray, p4)

        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5,
                                                    atol=1e-6),
            p_full, p_resumed)
        np.testing.assert_allclose(float(l4), float(l_full), rtol=1e-5)

    def test_4_to_8_roundtrip_values(self):
        """Slice-UP: the resharded state's logical content is identical
        (pad-strip + re-pad is value-preserving)."""
        from analytics_zoo_tpu.parallel import reshard_zero1_opt_state
        from jax.flatten_util import ravel_pytree

        m, p, st, step4, init4 = self._setup(4)
        o = init4(p)
        x, y = _data()
        batch = {"x": jnp.asarray(x[:64]), "y": jnp.asarray(y[:64])}
        p, o, st, _ = step4(p, o, st, jax.random.PRNGKey(0), batch)
        saved = jax.tree_util.tree_map(np.asarray, o)

        import analytics_zoo_tpu as zoo

        zoo.init_zoo_context(seed=3, mesh_shape={"data": 8})
        o8 = reshard_zero1_opt_state(saved, p)
        size = ravel_pytree(p)[0].size
        for a, b in zip(jax.tree_util.tree_leaves(saved),
                        jax.tree_util.tree_leaves(o8)):
            if np.ndim(a) == 1:
                np.testing.assert_allclose(np.asarray(b)[:size],
                                           np.asarray(a)[:size])
            else:
                np.testing.assert_allclose(np.asarray(b), np.asarray(a))
