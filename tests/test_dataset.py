"""FeatureSet data-layer tests: sharded iteration, O(1)-IO resume,
process-shard slicing (multi-host locality), padding contracts.

Reference semantics: FeatureSet.scala:240-289 (iterator), :332-409
(DiskFeatureSet slice residency); tf_dataset.py:136-143 (batch contract).
"""

import glob
import os

import numpy as np
import pytest

from analytics_zoo_tpu.feature.dataset import (
    ArrayFeatureSet,
    FeatureSet,
    ShardedFeatureSet,
)


@pytest.fixture
def shard_dir(tmp_path):
    """6 shards with uneven sizes (including tiny ones < batch_size)."""
    rng = np.random.default_rng(0)
    sizes = [17, 5, 23, 11, 3, 19]
    start = 0
    for i, n in enumerate(sizes):
        x = np.arange(start, start + n, dtype=np.float32)[:, None] * [1.0, 2.0]
        y = np.arange(start, start + n, dtype=np.int32)
        np.savez(tmp_path / f"shard{i}.npz", x=x, y=y)
        start += n
    return str(tmp_path)


def _collect(fs, batch_size, **kw):
    return list(fs.batches(batch_size, shuffle=True, seed=5, epoch=2, **kw))


def test_npz_header_sizer(shard_dir):
    paths = sorted(glob.glob(os.path.join(shard_dir, "*.npz")))
    fs = ShardedFeatureSet(paths, n_slices=3)
    assert fs.num_samples == 17 + 5 + 23 + 11 + 3 + 19
    # sizing must not have populated the data cache
    assert not fs._cache


def test_sharded_resume_matches_full_iteration(shard_dir):
    paths = sorted(glob.glob(os.path.join(shard_dir, "*.npz")))
    full = _collect(ShardedFeatureSet(paths, n_slices=2), 8)
    for start in (1, 3, 5, len(full) - 1):
        tail = _collect(ShardedFeatureSet(paths, n_slices=2), 8,
                        start_batch=start)
        assert len(tail) == len(full) - start
        for a, b in zip(full[start:], tail):
            np.testing.assert_array_equal(a["x"], b["x"])
            np.testing.assert_array_equal(a["y"], b["y"])


def test_sharded_resume_skips_shard_io(shard_dir):
    paths = sorted(glob.glob(os.path.join(shard_dir, "*.npz")))
    loads = []

    def counting_loader(path):
        loads.append(path)
        data = np.load(path, allow_pickle=False)
        return {k: data[k] for k in data.files}

    fs = ShardedFeatureSet(paths, n_slices=2, loader=counting_loader)
    full = _collect(ShardedFeatureSet(paths, n_slices=2), 8)
    # size discovery for a custom loader loads each shard once
    fs._shard_sizes()
    n_size_loads = len(loads)
    fs._cache.clear()
    loads.clear()

    tail = _collect(fs, 8, start_batch=len(full) - 1)
    assert len(tail) == 1
    # only the shards contributing rows to the last batch are re-loaded
    assert 0 < len(loads) < len(paths), loads
    assert n_size_loads == len(paths)


def test_sharded_process_shard_reassembles(shard_dir):
    paths = sorted(glob.glob(os.path.join(shard_dir, "*.npz")))
    full = _collect(ShardedFeatureSet(paths, n_slices=2), 8)
    parts = [
        _collect(ShardedFeatureSet(paths, n_slices=2), 8,
                 process_shard=(pid, 2))
        for pid in range(2)
    ]
    for bi, batch in enumerate(full):
        rebuilt = np.concatenate([parts[0][bi]["x"], parts[1][bi]["x"]])
        np.testing.assert_array_equal(batch["x"], rebuilt)


def test_array_process_shard_and_padding():
    x = np.arange(22, dtype=np.float32)[:, None]
    y = np.arange(22, dtype=np.int32)
    fs = ArrayFeatureSet(x, y)
    full = list(fs.batches(8, shuffle=False, drop_last=False,
                           pad_to_batch=4))
    # last batch: 6 valid rows padded to 8
    assert len(full[-1]["x"]) == 8 and int(full[-1]["n_valid"]) == 6
    parts = [
        list(fs.batches(8, shuffle=False, drop_last=False, pad_to_batch=4,
                        process_shard=(pid, 2)))
        for pid in range(2)
    ]
    for bi, batch in enumerate(full):
        rebuilt = np.concatenate([parts[0][bi]["x"], parts[1][bi]["x"]])
        np.testing.assert_array_equal(batch["x"], rebuilt)
        # n_valid stays the GLOBAL count on every process
        for pid in range(2):
            assert parts[pid][bi].get("n_valid") == batch.get("n_valid")


def test_resume_past_end_yields_nothing(shard_dir):
    paths = sorted(glob.glob(os.path.join(shard_dir, "*.npz")))
    fs = ShardedFeatureSet(paths, n_slices=2)
    n = len(_collect(ShardedFeatureSet(paths, n_slices=2), 8))
    assert _collect(fs, 8, start_batch=n + 3) == []


class TestPmemTier:
    """PMEM memory tier (reference FeatureSet.scala Optane tier): arrays
    spill to memory-mapped spool files; iteration, exact resume and fit()
    behave identically to DRAM while resident memory stays O(pages)."""

    def test_spill_produces_memmaps_with_identical_batches(self):
        from analytics_zoo_tpu.feature.dataset import FeatureSet

        rng = np.random.default_rng(0)
        x = rng.normal(size=(256, 12)).astype(np.float32)
        y = rng.integers(0, 3, size=(256,)).astype(np.int32)
        dram = FeatureSet.array(x, y)
        pmem = FeatureSet.array(x, y, memory_type="PMEM")
        assert isinstance(pmem.xs[0], np.memmap)
        assert not isinstance(dram.xs[0], np.memmap)
        for bd, bp in zip(dram.batches(32, seed=5, epoch=2),
                          pmem.batches(32, seed=5, epoch=2)):
            np.testing.assert_array_equal(bd["x"], bp["x"])
            np.testing.assert_array_equal(bd["y"], bp["y"])

    def test_resume_contract_survives_spill(self):
        from analytics_zoo_tpu.feature.dataset import FeatureSet

        rng = np.random.default_rng(1)
        x = rng.normal(size=(128, 4)).astype(np.float32)
        fs = FeatureSet.array(x, memory_type="PMEM")
        full = list(fs.batches(16, seed=3, epoch=1))
        resumed = list(fs.batches(16, seed=3, epoch=1, start_batch=4))
        for a, b in zip(full[4:], resumed):
            np.testing.assert_array_equal(a["x"], b["x"])

    def test_fit_through_pmem_tier(self):
        import analytics_zoo_tpu as zoo
        from analytics_zoo_tpu.feature.dataset import FeatureSet
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

        zoo.init_zoo_context(seed=0)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(256, 8)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.int32)
        accs = {}
        for tier in ("DRAM", "PMEM"):
            fs = FeatureSet.array(x, y, memory_type=tier)
            m = Sequential()
            m.add(Dense(16, activation="relu", input_shape=(8,)))
            m.add(Dense(2, activation="softmax"))
            m.compile(optimizer="adam",
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"])
            m.fit(fs, batch_size=32, nb_epoch=30)
            accs[tier] = m.evaluate(x, y)["accuracy"]
        # the tier changes WHERE bytes live, not a single training bit
        assert accs["PMEM"] == accs["DRAM"], accs
        assert accs["PMEM"] > 0.9, accs


def test_npz_sizer_handles_v3_headers_and_falls_back(tmp_path):
    """npy header version (3,0) (numpy emits it for long utf-8 field
    names) must size from the header, and an unparseable member must fall
    back to a full load instead of raising."""
    import zipfile

    arr = np.arange(42, dtype=np.float32)[:, None] * [1.0, 2.0]
    p3 = str(tmp_path / "v3.npz")
    with zipfile.ZipFile(p3, "w") as z:
        with z.open("x.npy", "w") as f:
            np.lib.format.write_array(f, arr, version=(3, 0))
    assert ShardedFeatureSet._npz_first_dim(p3) == 42

    # header parse fails -> full-load fallback (np.load's own reader is
    # untouched: only the public per-version wrapper our sizer calls is
    # broken here)
    pbad = str(tmp_path / "bad.npz")
    np.savez(pbad, x=arr)
    import unittest.mock as mock
    with mock.patch("numpy.lib.format.read_array_header_1_0",
                    side_effect=ValueError("bad header")):
        assert ShardedFeatureSet._npz_first_dim(pbad) == 42

    # and num_samples uses it end-to-end
    fs = ShardedFeatureSet([p3], n_slices=1)
    assert fs.num_samples == 42
