"""Latency-hiding plane acceptance (ISSUE 15): bucketed gradient
overlap pins bitwise trajectories on every canned plan's GSPMD path
(same reduction grouping — the bucket boundaries only reorder the
schedule), the explicit chunked/ring spellings are ulp-recorded,
elastic resume rides through a bucketed plan bit-exact, a kill -9
during an async checkpoint write leaves the previous COMPLETE snapshot
loadable, the fsdp gather-prefetch program compiles under its own
label and warm-starts from the persistent cache in a second process,
the overlap-aware roofline reproduces the old additive model at
exposed=1.0, and the quick-sized --overlap bench is the acceptance
guard (bucketed faster than the serial two-phase loop, async
checkpoint stall < 0.2x the synchronous save)."""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _data():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    w = np.random.default_rng(1).normal(size=(8, 4))
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return x, y


def _fit(plan, epochs=2, ckpt_dir=None, mesh_size=8):
    """One training leg under ``plan`` on a {data: mesh_size} mesh;
    absolute epoch target so a second call with the same ckpt_dir
    RESUMES (the test_elastic_resume idiom)."""
    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    zoo.init_zoo_context(seed=3, mesh_shape={"data": mesh_size})
    x, y = _data()
    m = Sequential()
    m.add(Dense(16, activation="relu", input_shape=(8,)))
    m.add(Dense(4, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    if ckpt_dir:
        m.set_checkpoint(ckpt_dir)
    m.fit(x, y, batch_size=32, nb_epoch=epochs, plan=plan)
    res = m.evaluate(x, y, batch_size=32)
    return {"losses": [h["loss"] for h in m._estimator.history],
            "eval": res}


# ---------------------------------------------------------------------------
# Tentpole pin: overlap vs serial trajectories, per plan
# ---------------------------------------------------------------------------


class TestOverlapTrajectory:
    @pytest.mark.parametrize("plan", ["zero1", "zero2", "zero3", "fsdp"])
    def test_gspmd_overlap_is_bitwise(self, plan):
        """`<plan>+overlap` through the estimator is the SAME reduction
        grouping as the serial plan — bucketing only reorders the
        schedule — so the loss trajectory must be bit-identical, not
        merely close."""
        serial = _fit(plan)
        overlap = _fit(plan + "+overlap")
        assert serial["losses"] == overlap["losses"], (plan, serial,
                                                       overlap)
        assert serial["eval"]["loss"] == overlap["eval"]["loss"]

    def test_explicit_bucketed_and_ring_are_ulp_recorded(self, zoo_ctx):
        """The explicit shard_map spellings (chunked psum_scatter /
        ppermute ring) recompose the flat vector per chunk — a
        different compiled program, recorded at the zero1-vs-dp ulp
        tolerance rather than pinned bitwise."""
        import optax

        from analytics_zoo_tpu.parallel import make_zero1_train_step
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
        from analytics_zoo_tpu.pipeline.api.keras.objectives import (
            get_loss,
        )

        x, y = _data()
        batch = {"x": jnp.asarray(x[:64]), "y": jnp.asarray(y[:64])}
        loss = get_loss("sparse_categorical_crossentropy")
        opt = optax.adam(1e-2)

        def leg(**kw):
            m = Sequential()
            m.add(Dense(16, activation="relu", input_shape=(8,)))
            m.add(Dense(4, activation="softmax"))
            m.compile(optimizer="adam",
                      loss="sparse_categorical_crossentropy")
            params, state = m.build_params(jax.random.PRNGKey(0))
            step, init = make_zero1_train_step(m, loss, opt, **kw)
            opt_state = init(params)
            ls = []
            for _ in range(4):
                params, opt_state, state, l = step(
                    params, opt_state, state, jax.random.PRNGKey(0),
                    batch)
                ls.append(float(l))
            return ls

        base = leg()
        bucketed = leg(bucket_bytes=256)
        ring = leg(bucket_bytes=256, ring=True)
        np.testing.assert_allclose(bucketed, base, rtol=2e-5)
        np.testing.assert_allclose(ring, base, rtol=2e-5)


def test_elastic_resume_through_bucketed_plan(tmp_path):
    """A checkpoint written mid-run under zero2+overlap resumes
    bit-exact: same mesh + same plan => same programs, and the bucketed
    schedule does not leak into the snapshot layout."""
    plan = "zero2+overlap"
    full = _fit(plan, epochs=4)
    ckdir = str(tmp_path / "ck_overlap")
    first = _fit(plan, epochs=2, ckpt_dir=ckdir)
    assert first["losses"] == full["losses"][:2]
    resumed = _fit(plan, epochs=4, ckpt_dir=ckdir)
    assert len(resumed["losses"]) == 2, resumed["losses"]
    assert resumed["losses"] == full["losses"][2:]
    assert resumed["eval"]["loss"] == full["eval"]["loss"]


# ---------------------------------------------------------------------------
# Async checkpointing: kill -9 mid-write leaves the previous snapshot
# ---------------------------------------------------------------------------

_CRASH_CHILD = r"""
import os, signal, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

from analytics_zoo_tpu.pipeline.estimator.estimator import _Checkpointer

root = sys.argv[1]
ck = _Checkpointer(path=root, keep=3)
ck.save("good", {"params": jnp.asarray(np.arange(64, dtype=np.float32)),
                 "step": 1})
ck._pending.join()  # 'good' is durably complete (data + rename fsynced)
print("GOOD_DONE", flush=True)
# a payload big enough that pickling + fsync takes hundreds of ms on
# this host: save() returns after the device-side snapshot, the daemon
# starts writing, and SIGKILL lands mid-write
big = jnp.asarray(np.arange((32 << 20) // 4, dtype=np.float32))
ck.save("bad", {"params": big, "step": 2})
os.kill(os.getpid(), signal.SIGKILL)
"""


def test_kill9_mid_async_write_leaves_previous_checkpoint(tmp_path):
    """THE crash-safety pin: kill -9 while the writer daemon is
    serializing leaves (a) no advanced LATEST pointer and (b) the
    previous complete snapshot loadable."""
    root = str(tmp_path / "ck")
    os.makedirs(root)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("ZOO_ASYNC_CHECKPOINT", None)
    r = subprocess.run([sys.executable, "-c", _CRASH_CHILD, root],
                       env=env, cwd=REPO, capture_output=True,
                       text=True, timeout=420)
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr)
    assert "GOOD_DONE" in r.stdout

    from analytics_zoo_tpu.pipeline.estimator.estimator import (
        _Checkpointer,
    )

    with open(os.path.join(root, _Checkpointer.LATEST)) as f:
        assert f.read().strip() == "ckpt-good.pkl"
    ck = _Checkpointer(path=root, keep=3)
    snap = ck.latest()
    assert snap is not None
    assert snap["step"] == 1
    np.testing.assert_array_equal(
        snap["params"], np.arange(64, dtype=np.float32))


def test_sync_fallback_env_knob(tmp_path, monkeypatch):
    """ZOO_ASYNC_CHECKPOINT=0 runs the write inline: no writer thread
    is left pending and the snapshot is complete when save returns."""
    from analytics_zoo_tpu.pipeline.estimator.estimator import (
        _Checkpointer,
    )

    monkeypatch.setenv("ZOO_ASYNC_CHECKPOINT", "0")
    root = str(tmp_path / "ck_sync")
    ck = _Checkpointer(path=root, keep=3)
    fname = ck.save("s", {"params": jnp.ones((8,)), "step": 5})
    assert ck._pending is None
    assert os.path.exists(fname)
    assert ck.latest()["step"] == 5


# ---------------------------------------------------------------------------
# fsdp gather prefetch: own compile label + persistent-cache warm start
# ---------------------------------------------------------------------------

_PREFETCH_CHILD = r"""
import json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.metrics import get_registry, snapshot
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

zoo.init_zoo_context(seed=0, mesh_shape={"data": 8})
m = Sequential()
m.add(Dense(16, activation="relu", input_shape=(8,)))
m.add(Dense(4, activation="softmax"))
m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
rng = np.random.default_rng(0)
batch = {"x": rng.normal(size=(32, 8)).astype(np.float32),
         "y": rng.integers(0, 4, size=(32,)).astype(np.int32)}
m._make_estimator().warmup(batch, plan="fsdp+overlap")
out = {"hits": 0, "misses": 0, "compiled": []}
for s in snapshot(get_registry())["samples"]:
    if s["name"] == "zoo_compile_cache_hits_total":
        out["hits"] += s["value"]
    elif s["name"] == "zoo_compile_cache_misses_total":
        out["misses"] += s["value"]
    elif s["name"] == "zoo_compile_seconds":
        out["compiled"].append(s["labels"]["label"])
print("RESULT " + json.dumps(out))
"""


def test_prefetch_compiles_own_label_and_warm_starts(tmp_path):
    """fsdp+overlap (gather prefetch + bucketed grads) lowers through
    the choke point under its OWN label — a different program from
    serial fsdp — and a second process over the same ZOO_COMPILE_CACHE
    compiles it as a pure persistent-cache hit."""

    def run(cache):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=8",
                   ZOO_COMPILE_CACHE=str(cache))
        env.pop("ZOO_SHARDING_PLAN", None)
        r = subprocess.run([sys.executable, "-c", _PREFETCH_CHILD],
                           env=env, cwd=REPO, capture_output=True,
                           text=True, timeout=420)
        assert r.returncode == 0, r.stdout + "\n" + r.stderr
        line = [ln for ln in r.stdout.splitlines()
                if ln.startswith("RESULT ")][-1]
        return json.loads(line[len("RESULT "):])

    cache = tmp_path / "cc"
    cold = run(cache)
    labels = set(cold["compiled"])
    assert any("fsdp+overlap" in lb for lb in labels), labels
    assert cold["misses"] > 0 and cold["hits"] == 0, cold
    warm = run(cache)
    assert warm["misses"] == 0, warm
    assert warm["hits"] == cold["misses"], (cold, warm)


# ---------------------------------------------------------------------------
# Overlap-aware roofline: unit matrix
# ---------------------------------------------------------------------------


class TestOverlapRoofline:
    FEATURES = {"matmul_flops": 4e9, "bytes_accessed": 1e9,
                "collective_bytes": 5e9}

    def _peaks(self):
        from analytics_zoo_tpu.analysis.costmodel import PeakTable

        return PeakTable(flops=1e12, hbm_bytes_per_s=1e12,
                         link_bytes_per_s=1e10,
                         dispatch_overhead_s=0.001, hbm_bytes=int(1e10))

    def test_serial_reproduces_additive_model(self):
        """exposed=1.0 (every serial plan) must be EXACTLY the old
        ``max(compute, mem) + collectives + overhead/k`` model."""
        from analytics_zoo_tpu.analysis.costmodel import (
            predict_step_seconds,
        )

        peaks = self._peaks()
        f = self.FEATURES
        old = max(f["matmul_flops"] / peaks.flops,
                  f["bytes_accessed"] / peaks.hbm_bytes_per_s) \
            + f["collective_bytes"] / peaks.link_bytes_per_s \
            + peaks.dispatch_overhead_s
        for plan in (None, "dp", "zero2", "fsdp"):
            assert predict_step_seconds(f, peaks=peaks, plan=plan) == old

    def test_overlap_hides_collectives_behind_compute(self):
        from analytics_zoo_tpu.analysis.costmodel import (
            predict_step_seconds,
        )

        peaks = self._peaks()
        serial = predict_step_seconds(self.FEATURES, peaks=peaks,
                                      plan="zero2")
        overlap = predict_step_seconds(self.FEATURES, peaks=peaks,
                                       plan="zero2+overlap")
        assert overlap < serial
        # exposed=0.25 of the 0.5s collective serializes; the hidden
        # 0.375s exceeds compute (0.004s) so it sets the max() term
        assert overlap == pytest.approx(0.5 * 0.75 + 0.5 * 0.25 + 0.001)

    def test_feature_driven_exposure_beats_plan_table(self):
        """When the HLO actually contains async start/done pairs, the
        measured overlapped bytes win over the plan-name table."""
        from analytics_zoo_tpu.analysis.costmodel import (
            predict_step_seconds,
        )

        peaks = self._peaks()
        f = dict(self.FEATURES, overlapped_collective_bytes=5e9)
        fully_hidden = predict_step_seconds(f, peaks=peaks, plan="dp")
        # exposed=0: the whole 0.5s is overlappable -> max() term
        assert fully_hidden == pytest.approx(0.5 + 0.001)

    def test_exposed_fraction_clamped(self):
        from analytics_zoo_tpu.analysis.costmodel import (
            predict_step_seconds,
        )

        peaks = self._peaks()
        lo = predict_step_seconds(self.FEATURES, peaks=peaks,
                                  exposed_fraction=-3.0)
        hi = predict_step_seconds(self.FEATURES, peaks=peaks,
                                  exposed_fraction=7.0)
        assert lo == predict_step_seconds(self.FEATURES, peaks=peaks,
                                          exposed_fraction=0.0)
        assert hi == predict_step_seconds(self.FEATURES, peaks=peaks,
                                          exposed_fraction=1.0)

    def test_plan_exposed_fraction_table(self):
        from analytics_zoo_tpu.analysis.costmodel import (
            EXPOSED_FRACTIONS,
            plan_exposed_fraction,
        )

        assert plan_exposed_fraction(None) == 1.0
        assert plan_exposed_fraction("zero2") == 1.0
        assert plan_exposed_fraction("zero2+overlap") \
            == EXPOSED_FRACTIONS["overlap"]
        assert plan_exposed_fraction("fsdp+overlap+remat_full") \
            == EXPOSED_FRACTIONS["overlap"]


# ---------------------------------------------------------------------------
# Quick-tier bench guard (bench.py --overlap)
# ---------------------------------------------------------------------------


def test_overlap_bench_quick_tier(tmp_path):
    """THE acceptance guard: on the quick-sized --overlap bench the
    bucketed fused schedule beats the serial two-phase loop on every
    comm-bound leg at a bitwise trajectory, the async checkpoint hides
    at least half the synchronous save stall (the < 0.2x acceptance
    number is pinned by the full-run artifact), and the roofline
    is no worse than the additive model on every leg."""
    sys.path.insert(0, REPO)
    try:
        from bench import overlap_bench
    finally:
        sys.path.remove(REPO)
    doc = overlap_bench(quick=True,
                        out_path=str(tmp_path / "bench.json"))
    assert doc["trajectory_bitwise_equal"] is True
    for name, leg in doc["legs"].items():
        assert leg["bucketed_vs_serial"] < 1.0, (name, leg)
        assert leg["loss_max_abs_diff"] == 0.0, (name, leg)
    # the acceptance gate (< 0.2) is pinned by the full-run artifact
    # (BENCH_OVERLAP_r13.json: 0.1577); the quick run's few saves make
    # p99 one bad fs write, so the per-commit guard only requires that
    # async hides at least half the stall
    assert doc["checkpoint"]["async_vs_sync_p99"] < 0.5, doc["checkpoint"]
    for row in doc["roofline"]:
        assert row["bucketed_rel_error_overlap"] \
            <= row["bucketed_rel_error_additive"] + 1e-9, row
        assert row["serial_rel_error_additive"] == pytest.approx(0.0)
