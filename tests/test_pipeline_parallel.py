"""Pipeline parallelism (GPipe over the ``pipe`` mesh axis) vs a sequential
oracle — the reference has no PP (SURVEY.md §2.4), so dense math is the
oracle, as for TP/SP/EP."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _oracle(stage_params, x):
    for i in range(stage_params["w"].shape[0]):
        x = np.tanh(x @ stage_params["w"][i] + stage_params["b"][i])
    return x


def _make(rng, n_stages, d):
    return {
        "w": rng.normal(0, 0.5, (n_stages, d, d)).astype(np.float32),
        "b": rng.normal(0, 0.1, (n_stages, d)).astype(np.float32),
    }


@pytest.fixture()
def pipe_ctx():
    from analytics_zoo_tpu import init_zoo_context

    return init_zoo_context(
        mesh_shape={"data": 2, "pipe": 4},
        mesh_axes=("data", "pipe"), seed=0,
    )


class TestGPipe:
    def test_forward_matches_sequential(self, pipe_ctx):
        from analytics_zoo_tpu.parallel.pipeline import gpipe

        rng = np.random.default_rng(0)
        params = _make(rng, 4, 8)
        x = rng.normal(size=(16, 8)).astype(np.float32)
        out = gpipe(_stage_fn, params, jnp.asarray(x), n_microbatch=8)
        np.testing.assert_allclose(
            np.asarray(out), _oracle(params, x), atol=1e-5)

    def test_forward_under_jit_with_sharded_stages(self, pipe_ctx):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from analytics_zoo_tpu.parallel.pipeline import gpipe

        mesh = pipe_ctx.mesh
        rng = np.random.default_rng(1)
        params = _make(rng, 4, 8)
        sharded = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, NamedSharding(mesh, P("pipe"))),
            params,
        )
        x = rng.normal(size=(32, 8)).astype(np.float32)
        out = jax.jit(
            lambda p, x: gpipe(_stage_fn, p, x, n_microbatch=8)
        )(sharded, jnp.asarray(x))
        np.testing.assert_allclose(
            np.asarray(out), _oracle(params, x), atol=1e-5)

    def test_grad_is_reverse_pipeline(self, pipe_ctx):
        """jax.grad through the scanned ppermute schedule must equal the
        sequential model's gradients, for stage params AND input."""
        from analytics_zoo_tpu.parallel.pipeline import gpipe

        rng = np.random.default_rng(2)
        params = _make(rng, 4, 6)
        x = jnp.asarray(rng.normal(size=(8, 6)).astype(np.float32))
        tgt = jnp.asarray(rng.normal(size=(8, 6)).astype(np.float32))

        def piped_loss(p, x):
            return jnp.mean((gpipe(_stage_fn, p, x, n_microbatch=4)
                             - tgt) ** 2)

        def seq_loss(p, x):
            for i in range(4):
                x = jnp.tanh(x @ p["w"][i] + p["b"][i])
            return jnp.mean((x - tgt) ** 2)

        gp, gx = jax.grad(piped_loss, argnums=(0, 1))(params, x)
        rp, rx = jax.grad(seq_loss, argnums=(0, 1))(params, x)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), atol=1e-5)
        for k in gp:
            np.testing.assert_allclose(
                np.asarray(gp[k]), np.asarray(rp[k]), atol=1e-5, err_msg=k)

    def test_training_step_converges(self, pipe_ctx):
        """Full pipelined train step: gpipe forward, grad, sgd — loss falls
        on a learnable mapping."""
        from analytics_zoo_tpu.parallel.pipeline import gpipe

        rng = np.random.default_rng(3)
        params = jax.tree_util.tree_map(jnp.asarray, _make(rng, 4, 4))
        x = jnp.asarray(rng.normal(size=(32, 4)).astype(np.float32))
        w_true = rng.normal(size=(4, 4)).astype(np.float32)
        y = jnp.tanh(jnp.asarray(x @ w_true))

        @jax.jit
        def step(p, x, y):
            def loss(p):
                return jnp.mean((gpipe(_stage_fn, p, x, n_microbatch=8)
                                 - y) ** 2)

            l, g = jax.value_and_grad(loss)(p)
            return jax.tree_util.tree_map(
                lambda a, b: a - 0.3 * b, p, g), l

        losses = []
        for _ in range(60):
            params, l = step(params, x, y)
            losses.append(float(l))
        assert losses[-1] < 0.2 * losses[0], losses[::15]

    def test_single_stage_fallback(self):
        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.parallel.pipeline import gpipe

        init_zoo_context(mesh_shape={"data": 8}, seed=0)
        rng = np.random.default_rng(4)
        params = _make(rng, 1, 5)
        x = rng.normal(size=(6, 5)).astype(np.float32)
        out = gpipe(_stage_fn, params, jnp.asarray(x), n_microbatch=2)
        np.testing.assert_allclose(
            np.asarray(out), _oracle(params, x), atol=1e-6)

    def test_stack_stage_params(self, pipe_ctx):
        from analytics_zoo_tpu.parallel.pipeline import (
            gpipe,
            stack_stage_params,
        )

        rng = np.random.default_rng(5)
        per_stage = [
            {"w": rng.normal(0, 0.5, (4, 4)).astype(np.float32),
             "b": np.zeros(4, np.float32)}
            for _ in range(4)
        ]
        stacked = stack_stage_params(per_stage)
        assert stacked["w"].shape == (4, 4, 4)
        x = rng.normal(size=(8, 4)).astype(np.float32)
        out = gpipe(_stage_fn, stacked, jnp.asarray(x), n_microbatch=4)
        ref = x
        for p in per_stage:
            ref = np.tanh(ref @ p["w"] + p["b"])
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)

    def test_shape_errors(self, pipe_ctx):
        from analytics_zoo_tpu.parallel.pipeline import gpipe

        rng = np.random.default_rng(6)
        params = _make(rng, 3, 4)  # wrong: pipe axis is 4
        x = jnp.zeros((8, 4), jnp.float32)
        with pytest.raises(ValueError, match="pipe axis size"):
            gpipe(_stage_fn, params, x, n_microbatch=4)
        good = _make(rng, 4, 4)
        with pytest.raises(ValueError, match="not divisible"):
            gpipe(_stage_fn, good, x, n_microbatch=3)


class TestGPipeDataParallel:
    def test_batch_axis_shards_rows_and_matches_oracle(self, pipe_ctx):
        """PP x DP: microbatch rows sharded over `data`; forward and the
        DP-summed parameter grads must equal the sequential oracle."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from analytics_zoo_tpu.parallel.pipeline import gpipe

        mesh = pipe_ctx.mesh
        rng = np.random.default_rng(7)
        params = jax.device_put(
            _make(rng, 4, 6), NamedSharding(mesh, P("pipe")))
        host = jax.tree_util.tree_map(np.asarray, params)
        x = rng.normal(size=(16, 6)).astype(np.float32)
        tgt = rng.normal(size=(16, 6)).astype(np.float32)
        xd = jax.device_put(x, NamedSharding(mesh, P("data")))
        td = jax.device_put(tgt, NamedSharding(mesh, P("data")))

        @jax.jit
        def loss_and_grad(p, x, t):
            def loss(p):
                out = gpipe(_stage_fn, p, x, n_microbatch=4,
                            batch_axis="data")
                return jnp.mean((out - t) ** 2), out

            (l, out), g = jax.value_and_grad(loss, has_aux=True)(p)
            return l, out, g

        l, out, g = loss_and_grad(params, xd, td)
        # forward oracle
        np.testing.assert_allclose(
            np.asarray(out), _oracle(host, x), atol=1e-5)
        # the output stays row-sharded over data (no all-gather of compute)
        assert out.sharding.spec[0] in (P("data")[0], "data")

        def seq_loss(p):
            a = jnp.asarray(x)
            for i in range(4):
                a = jnp.tanh(a @ p["w"][i] + p["b"][i])
            return jnp.mean((a - tgt) ** 2)

        ref = jax.grad(seq_loss)(host)
        for k in g:
            np.testing.assert_allclose(
                np.asarray(g[k]), np.asarray(ref[k]), atol=1e-5, err_msg=k)


class TestTransformerGPipe:
    def test_block_stack_matches_sequential(self, pipe_ctx):
        """A real TransformerLayer's blocks pipelined over pipe=4 must
        reproduce the sequential stack (fwd + grads)."""
        from analytics_zoo_tpu.parallel.pipeline import transformer_gpipe
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            TransformerLayer,
        )

        layer = TransformerLayer(vocab=64, seq_len=8, n_block=4, n_head=2,
                                 hidden_size=16, embedding_drop=0.0,
                                 hidden_drop=0.0, attn_drop=0.0)
        params = layer.init_params(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        h = jnp.asarray(rng.normal(size=(8, 8, 16)).astype(np.float32))

        ref = layer._run_blocks(params["blocks"], h, None, False, None)
        out = transformer_gpipe(layer, params, h, n_microbatch=4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

        def piped(params, h):
            return jnp.mean(
                transformer_gpipe(layer, params, h, n_microbatch=4) ** 2)

        def seq(params, h):
            return jnp.mean(
                layer._run_blocks(params["blocks"], h, None, False,
                                  None) ** 2)

        gp = jax.grad(piped)(params, h)
        gs = jax.grad(seq)(params, h)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5), gp, gs)

    def test_structural_mask_and_remat(self, pipe_ctx):
        """Batch-independent mask is honored; remat=True stays exact;
        per-sample masks are rejected."""
        from analytics_zoo_tpu.parallel.pipeline import transformer_gpipe
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            TransformerLayer,
        )

        layer = TransformerLayer(vocab=64, seq_len=8, n_block=4, n_head=2,
                                 hidden_size=16, embedding_drop=0.0,
                                 hidden_drop=0.0, attn_drop=0.0,
                                 bidirectional=True, remat=True)
        params = layer.init_params(jax.random.PRNGKey(1))
        rng = np.random.default_rng(1)
        h = jnp.asarray(rng.normal(size=(8, 8, 16)).astype(np.float32))
        # structural mask (1, 1, Lq, Lk): block attention to the last two
        # key positions for every query
        mask = jnp.broadcast_to(
            jnp.where(jnp.arange(8) < 6, 0.0, -1e9), (8, 8))[None, None]

        ref = layer._run_blocks(params["blocks"], h, mask, False, None)
        out = transformer_gpipe(layer, params, h, n_microbatch=4,
                                mask=mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)
        g = jax.grad(lambda p: jnp.mean(transformer_gpipe(
            layer, p, h, n_microbatch=4, mask=mask) ** 2))(params)
        gr = jax.grad(lambda p: jnp.mean(layer._run_blocks(
            p["blocks"], h, mask, False, None) ** 2))(params)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5), g, gr)

        with pytest.raises(ValueError, match="per-sample masks"):
            transformer_gpipe(layer, params, h, n_microbatch=4,
                              mask=jnp.zeros((8, 1, 8, 8)))
