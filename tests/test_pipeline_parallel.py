"""Pipeline parallelism (GPipe over the ``pipe`` mesh axis) vs a sequential
oracle — the reference has no PP (SURVEY.md §2.4), so dense math is the
oracle, as for TP/SP/EP."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _oracle(stage_params, x):
    for i in range(stage_params["w"].shape[0]):
        x = np.tanh(x @ stage_params["w"][i] + stage_params["b"][i])
    return x


def _make(rng, n_stages, d):
    return {
        "w": rng.normal(0, 0.5, (n_stages, d, d)).astype(np.float32),
        "b": rng.normal(0, 0.1, (n_stages, d)).astype(np.float32),
    }


@pytest.fixture()
def pipe_ctx():
    from analytics_zoo_tpu import init_zoo_context

    return init_zoo_context(
        mesh_shape={"data": 2, "pipe": 4},
        mesh_axes=("data", "pipe"), seed=0,
    )


class TestGPipe:
    def test_forward_matches_sequential(self, pipe_ctx):
        from analytics_zoo_tpu.parallel.pipeline import gpipe

        rng = np.random.default_rng(0)
        params = _make(rng, 4, 8)
        x = rng.normal(size=(16, 8)).astype(np.float32)
        out = gpipe(_stage_fn, params, jnp.asarray(x), n_microbatch=8)
        np.testing.assert_allclose(
            np.asarray(out), _oracle(params, x), atol=1e-5)

    def test_forward_under_jit_with_sharded_stages(self, pipe_ctx):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from analytics_zoo_tpu.parallel.pipeline import gpipe

        mesh = pipe_ctx.mesh
        rng = np.random.default_rng(1)
        params = _make(rng, 4, 8)
        sharded = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, NamedSharding(mesh, P("pipe"))),
            params,
        )
        x = rng.normal(size=(32, 8)).astype(np.float32)
        out = jax.jit(
            lambda p, x: gpipe(_stage_fn, p, x, n_microbatch=8)
        )(sharded, jnp.asarray(x))
        np.testing.assert_allclose(
            np.asarray(out), _oracle(params, x), atol=1e-5)

    def test_grad_is_reverse_pipeline(self, pipe_ctx):
        """jax.grad through the scanned ppermute schedule must equal the
        sequential model's gradients, for stage params AND input."""
        from analytics_zoo_tpu.parallel.pipeline import gpipe

        rng = np.random.default_rng(2)
        params = _make(rng, 4, 6)
        x = jnp.asarray(rng.normal(size=(8, 6)).astype(np.float32))
        tgt = jnp.asarray(rng.normal(size=(8, 6)).astype(np.float32))

        def piped_loss(p, x):
            return jnp.mean((gpipe(_stage_fn, p, x, n_microbatch=4)
                             - tgt) ** 2)

        def seq_loss(p, x):
            for i in range(4):
                x = jnp.tanh(x @ p["w"][i] + p["b"][i])
            return jnp.mean((x - tgt) ** 2)

        gp, gx = jax.grad(piped_loss, argnums=(0, 1))(params, x)
        rp, rx = jax.grad(seq_loss, argnums=(0, 1))(params, x)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), atol=1e-5)
        for k in gp:
            np.testing.assert_allclose(
                np.asarray(gp[k]), np.asarray(rp[k]), atol=1e-5, err_msg=k)

    def test_training_step_converges(self, pipe_ctx):
        """Full pipelined train step: gpipe forward, grad, sgd — loss falls
        on a learnable mapping."""
        from analytics_zoo_tpu.parallel.pipeline import gpipe

        rng = np.random.default_rng(3)
        params = jax.tree_util.tree_map(jnp.asarray, _make(rng, 4, 4))
        x = jnp.asarray(rng.normal(size=(32, 4)).astype(np.float32))
        w_true = rng.normal(size=(4, 4)).astype(np.float32)
        y = jnp.tanh(jnp.asarray(x @ w_true))

        @jax.jit
        def step(p, x, y):
            def loss(p):
                return jnp.mean((gpipe(_stage_fn, p, x, n_microbatch=8)
                                 - y) ** 2)

            l, g = jax.value_and_grad(loss)(p)
            return jax.tree_util.tree_map(
                lambda a, b: a - 0.3 * b, p, g), l

        losses = []
        for _ in range(60):
            params, l = step(params, x, y)
            losses.append(float(l))
        assert losses[-1] < 0.2 * losses[0], losses[::15]

    def test_single_stage_fallback(self):
        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.parallel.pipeline import gpipe

        init_zoo_context(mesh_shape={"data": 8}, seed=0)
        rng = np.random.default_rng(4)
        params = _make(rng, 1, 5)
        x = rng.normal(size=(6, 5)).astype(np.float32)
        out = gpipe(_stage_fn, params, jnp.asarray(x), n_microbatch=2)
        np.testing.assert_allclose(
            np.asarray(out), _oracle(params, x), atol=1e-6)

    def test_stack_stage_params(self, pipe_ctx):
        from analytics_zoo_tpu.parallel.pipeline import (
            gpipe,
            stack_stage_params,
        )

        rng = np.random.default_rng(5)
        per_stage = [
            {"w": rng.normal(0, 0.5, (4, 4)).astype(np.float32),
             "b": np.zeros(4, np.float32)}
            for _ in range(4)
        ]
        stacked = stack_stage_params(per_stage)
        assert stacked["w"].shape == (4, 4, 4)
        x = rng.normal(size=(8, 4)).astype(np.float32)
        out = gpipe(_stage_fn, stacked, jnp.asarray(x), n_microbatch=4)
        ref = x
        for p in per_stage:
            ref = np.tanh(ref @ p["w"] + p["b"])
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)

    def test_shape_errors(self, pipe_ctx):
        from analytics_zoo_tpu.parallel.pipeline import gpipe

        rng = np.random.default_rng(6)
        params = _make(rng, 3, 4)  # wrong: pipe axis is 4
        x = jnp.zeros((8, 4), jnp.float32)
        with pytest.raises(ValueError, match="pipe axis size"):
            gpipe(_stage_fn, params, x, n_microbatch=4)
        good = _make(rng, 4, 4)
        with pytest.raises(ValueError, match="not divisible"):
            gpipe(_stage_fn, good, x, n_microbatch=3)


class TestGPipeDataParallel:
    def test_batch_axis_shards_rows_and_matches_oracle(self, pipe_ctx):
        """PP x DP: microbatch rows sharded over `data`; forward and the
        DP-summed parameter grads must equal the sequential oracle."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from analytics_zoo_tpu.parallel.pipeline import gpipe

        mesh = pipe_ctx.mesh
        rng = np.random.default_rng(7)
        params = jax.device_put(
            _make(rng, 4, 6), NamedSharding(mesh, P("pipe")))
        host = jax.tree_util.tree_map(np.asarray, params)
        x = rng.normal(size=(16, 6)).astype(np.float32)
        tgt = rng.normal(size=(16, 6)).astype(np.float32)
        xd = jax.device_put(x, NamedSharding(mesh, P("data")))
        td = jax.device_put(tgt, NamedSharding(mesh, P("data")))

        @jax.jit
        def loss_and_grad(p, x, t):
            def loss(p):
                out = gpipe(_stage_fn, p, x, n_microbatch=4,
                            batch_axis="data")
                return jnp.mean((out - t) ** 2), out

            (l, out), g = jax.value_and_grad(loss, has_aux=True)(p)
            return l, out, g

        l, out, g = loss_and_grad(params, xd, td)
        # forward oracle
        np.testing.assert_allclose(
            np.asarray(out), _oracle(host, x), atol=1e-5)
        # the output stays row-sharded over data (no all-gather of compute)
        assert out.sharding.spec[0] in (P("data")[0], "data")

        def seq_loss(p):
            a = jnp.asarray(x)
            for i in range(4):
                a = jnp.tanh(a @ p["w"][i] + p["b"][i])
            return jnp.mean((a - tgt) ** 2)

        ref = jax.grad(seq_loss)(host)
        for k in g:
            np.testing.assert_allclose(
                np.asarray(g[k]), np.asarray(ref[k]), atol=1e-5, err_msg=k)


class TestTransformerGPipe:
    def test_block_stack_matches_sequential(self, pipe_ctx):
        """A real TransformerLayer's blocks pipelined over pipe=4 must
        reproduce the sequential stack (fwd + grads)."""
        from analytics_zoo_tpu.parallel.pipeline import transformer_gpipe
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            TransformerLayer,
        )

        layer = TransformerLayer(vocab=64, seq_len=8, n_block=4, n_head=2,
                                 hidden_size=16, embedding_drop=0.0,
                                 hidden_drop=0.0, attn_drop=0.0)
        params = layer.init_params(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        h = jnp.asarray(rng.normal(size=(8, 8, 16)).astype(np.float32))

        ref = layer._run_blocks(params["blocks"], h, None, False, None)
        out = transformer_gpipe(layer, params, h, n_microbatch=4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

        def piped(params, h):
            return jnp.mean(
                transformer_gpipe(layer, params, h, n_microbatch=4) ** 2)

        def seq(params, h):
            return jnp.mean(
                layer._run_blocks(params["blocks"], h, None, False,
                                  None) ** 2)

        gp = jax.grad(piped)(params, h)
        gs = jax.grad(seq)(params, h)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5), gp, gs)

    def test_structural_mask_and_remat(self, pipe_ctx):
        """Batch-independent mask is honored; remat=True stays exact;
        per-sample masks are rejected."""
        from analytics_zoo_tpu.parallel.pipeline import transformer_gpipe
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            TransformerLayer,
        )

        layer = TransformerLayer(vocab=64, seq_len=8, n_block=4, n_head=2,
                                 hidden_size=16, embedding_drop=0.0,
                                 hidden_drop=0.0, attn_drop=0.0,
                                 bidirectional=True, remat=True)
        params = layer.init_params(jax.random.PRNGKey(1))
        rng = np.random.default_rng(1)
        h = jnp.asarray(rng.normal(size=(8, 8, 16)).astype(np.float32))
        # structural mask (1, 1, Lq, Lk): block attention to the last two
        # key positions for every query
        mask = jnp.broadcast_to(
            jnp.where(jnp.arange(8) < 6, 0.0, -1e9), (8, 8))[None, None]

        ref = layer._run_blocks(params["blocks"], h, mask, False, None)
        out = transformer_gpipe(layer, params, h, n_microbatch=4,
                                mask=mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)
        g = jax.grad(lambda p: jnp.mean(transformer_gpipe(
            layer, p, h, n_microbatch=4, mask=mask) ** 2))(params)
        gr = jax.grad(lambda p: jnp.mean(layer._run_blocks(
            p["blocks"], h, mask, False, None) ** 2))(params)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5), g, gr)

        with pytest.raises(ValueError, match="per-sample masks"):
            transformer_gpipe(layer, params, h, n_microbatch=4,
                              mask=jnp.zeros((8, 1, 8, 8)))


class TestGPipeHetero:
    """Non-shape-preserving pipelines (VERDICT r03 weak #6): stage
    boundaries change shape/dtype; union-buffer carry + lax.switch."""

    def test_changing_shapes_match_sequential(self, pipe_ctx):
        from analytics_zoo_tpu.parallel.pipeline import gpipe_hetero

        rng = np.random.default_rng(0)
        w0 = rng.normal(0, .5, (4, 10)).astype(np.float32)
        w1 = rng.normal(0, .5, (10, 6)).astype(np.float32)
        w2 = rng.normal(0, .5, (6, 6)).astype(np.float32)
        w3 = rng.normal(0, .5, (6, 3)).astype(np.float32)
        edge = [{"w": w0}, {"w": w1}, {"w": w2}, {"w": w3}]
        fns = [lambda e, s, a: jnp.tanh(a @ e["w"])] * 4
        x = rng.normal(size=(16, 4)).astype(np.float32)

        def seq(x):
            a = jnp.asarray(x)
            for wi in (w0, w1, w2, w3):
                a = jnp.tanh(a @ wi)
            return a

        out = gpipe_hetero(fns, edge, {}, jnp.asarray(x), n_microbatch=8)
        assert out.shape == (16, 3)
        np.testing.assert_allclose(np.asarray(out), np.asarray(seq(x)),
                                   atol=1e-5)

    def test_int_tokens_and_pytree_boundary(self, pipe_ctx):
        """Stage 0 consumes int32 tokens (bitcast through the f32 union
        buffer must be exact) and emits a pytree boundary."""
        from analytics_zoo_tpu.parallel.pipeline import gpipe_hetero

        rng = np.random.default_rng(1)
        table = rng.normal(0, .5, (50, 8)).astype(np.float32)
        w = rng.normal(0, .5, (8, 8)).astype(np.float32)
        wh = rng.normal(0, .5, (8, 5)).astype(np.float32)
        toks = rng.integers(0, 50, size=(8, 6)).astype(np.int32)

        def f0(e, s, t):
            h = jnp.take(e["tbl"], t, axis=0)
            return {"h": h, "t": t}

        def f1(e, s, d):
            return {"h": jnp.tanh(d["h"] @ e["w"]), "t": d["t"]}

        def f2(e, s, d):
            return d["h"] + jnp.take(e["tbl"], d["t"], axis=0)

        def f3(e, s, h):
            return h @ e["wh"]

        edge = [{"tbl": table}, {"w": w}, {"tbl": table}, {"wh": wh}]
        out = gpipe_hetero([f0, f1, f2, f3], edge, {}, jnp.asarray(toks),
                           n_microbatch=4)
        emb = table[toks]
        ref = (np.tanh(emb @ w) + emb) @ wh
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)

    def test_grads_match_sequential(self, pipe_ctx):
        from analytics_zoo_tpu.parallel.pipeline import gpipe_hetero

        rng = np.random.default_rng(2)
        edge = [{"w": rng.normal(0, .5, (4, 7)).astype(np.float32)},
                {"w": rng.normal(0, .5, (7, 5)).astype(np.float32)},
                {"w": rng.normal(0, .5, (5, 5)).astype(np.float32)},
                {"w": rng.normal(0, .5, (5, 2)).astype(np.float32)}]
        fns = [lambda e, s, a: jnp.tanh(a @ e["w"])] * 4
        x = jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32))

        def piped(edge, x):
            return jnp.mean(gpipe_hetero(fns, list(edge), {}, x,
                                         n_microbatch=4) ** 2)

        def seq(edge, x):
            a = x
            for e in edge:
                a = jnp.tanh(a @ e["w"])
            return jnp.mean(a ** 2)

        gp, gx = jax.grad(piped, argnums=(0, 1))(tuple(edge), x)
        rp, rx = jax.grad(seq, argnums=(0, 1))(tuple(edge), x)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                                   atol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5), gp, rp)

    def test_full_lm_embed_blocks_head(self, pipe_ctx):
        """The GPT stack (tools/transformer_bench.py shape) pipelined
        end-to-end: tokens -> embed -> 4 blocks -> LM head, vs the
        sequential model.  Forward and grads."""
        from analytics_zoo_tpu.parallel.pipeline import transformer_gpipe_lm
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            TransformerLayer,
        )

        layer = TransformerLayer(vocab=32, seq_len=8, n_block=4, n_head=2,
                                 hidden_size=16, embedding_drop=0.0,
                                 hidden_drop=0.0, attn_drop=0.0)
        params = layer.init_params(jax.random.PRNGKey(0))
        rng = np.random.default_rng(3)
        head_w = jnp.asarray(rng.normal(0, .2, (16, 32)).astype(np.float32))
        head_b = jnp.zeros((32,), jnp.float32)
        toks = jnp.asarray(rng.integers(0, 32, size=(8, 8)).astype(np.int32))

        def seq(params, head_w):
            h = layer.call(params, toks, training=False)
            return h @ head_w + head_b

        def piped(params, head_w):
            return transformer_gpipe_lm(layer, params, head_w, head_b,
                                        toks, n_microbatch=4)

        ref = seq(params, head_w)
        out = piped(params, head_w)
        assert out.shape == (8, 8, 32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
        gp, gh = jax.grad(
            lambda p, w: jnp.mean(piped(p, w) ** 2), argnums=(0, 1))(
                params, head_w)
        rp, rh = jax.grad(
            lambda p, w: jnp.mean(seq(p, w) ** 2), argnums=(0, 1))(
                params, head_w)
        np.testing.assert_allclose(np.asarray(gh), np.asarray(rh),
                                   atol=2e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5), gp, rp)

    def test_full_lm_with_data_parallel(self, pipe_ctx):
        """PP x DP composition for the hetero pipeline."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from analytics_zoo_tpu.parallel.pipeline import transformer_gpipe_lm
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            TransformerLayer,
        )

        layer = TransformerLayer(vocab=16, seq_len=4, n_block=4, n_head=2,
                                 hidden_size=8, embedding_drop=0.0,
                                 hidden_drop=0.0, attn_drop=0.0)
        params = layer.init_params(jax.random.PRNGKey(1))
        rng = np.random.default_rng(4)
        head_w = jnp.asarray(rng.normal(0, .2, (8, 16)).astype(np.float32))
        head_b = jnp.zeros((16,), jnp.float32)
        toks = rng.integers(0, 16, size=(8, 4)).astype(np.int32)
        mesh = pipe_ctx.mesh
        toks_d = jax.device_put(jnp.asarray(toks),
                                NamedSharding(mesh, P("data")))

        if getattr(jax.shard_map, "_zoo_compat_04x", False):
            # hetero+DP computes wrong numbers under the 0.4.x shard_map
            # shim (outputs scaled by the data-axis size); the library
            # must refuse loudly rather than return corrupted logits
            with pytest.raises(NotImplementedError, match="batch_axis"):
                jax.jit(lambda p, w, t: transformer_gpipe_lm(
                    layer, p, w, head_b, t, n_microbatch=4,
                    batch_axis="data"))(params, head_w, toks_d)
            return
        out = jax.jit(lambda p, w, t: transformer_gpipe_lm(
            layer, p, w, head_b, t, n_microbatch=4,
            batch_axis="data"))(params, head_w, toks_d)
        ref = layer.call(params, jnp.asarray(toks),
                         training=False) @ head_w + head_b
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)


class TestGPipeCircular:
    """Interleaved/circular schedule (virtual stages): shard i hosts
    stages i, i+S, ... and the ring is traversed v times."""

    def test_matches_sequential(self, pipe_ctx):
        from analytics_zoo_tpu.parallel.pipeline import gpipe

        rng = np.random.default_rng(5)
        params = _make(rng, 8, 6)  # 8 virtual stages on pipe=4, v=2
        x = rng.normal(size=(16, 6)).astype(np.float32)
        out = gpipe(_stage_fn, params, jnp.asarray(x), n_microbatch=8,
                    circular_repeats=2)
        np.testing.assert_allclose(
            np.asarray(out), _oracle(params, x), atol=1e-5)

    def test_grads_match_sequential(self, pipe_ctx):
        from analytics_zoo_tpu.parallel.pipeline import gpipe

        rng = np.random.default_rng(6)
        params = _make(rng, 8, 5)
        x = jnp.asarray(rng.normal(size=(8, 5)).astype(np.float32))

        def piped(p, x):
            return jnp.mean(gpipe(_stage_fn, p, x, n_microbatch=4,
                                  circular_repeats=2) ** 2)

        def seq(p, x):
            for i in range(8):
                x = jnp.tanh(x @ p["w"][i] + p["b"][i])
            return jnp.mean(x ** 2)

        gp, gx = jax.grad(piped, argnums=(0, 1))(params, x)
        rp, rx = jax.grad(seq, argnums=(0, 1))(params, x)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                                   atol=1e-5)
        for k in gp:
            np.testing.assert_allclose(
                np.asarray(gp[k]), np.asarray(rp[k]), atol=1e-5, err_msg=k)

    def test_exact_microbatch_equals_pipe_size(self, pipe_ctx):
        """M == S: the delay line degenerates to a direct hand-off."""
        from analytics_zoo_tpu.parallel.pipeline import gpipe

        rng = np.random.default_rng(7)
        params = _make(rng, 12, 4)  # v=3
        x = rng.normal(size=(8, 4)).astype(np.float32)
        out = gpipe(_stage_fn, params, jnp.asarray(x), n_microbatch=4,
                    circular_repeats=3)
        np.testing.assert_allclose(
            np.asarray(out), _oracle(params, x), atol=1e-5)

    def test_requires_enough_microbatches(self, pipe_ctx):
        from analytics_zoo_tpu.parallel.pipeline import gpipe

        rng = np.random.default_rng(8)
        params = _make(rng, 8, 4)
        with pytest.raises(ValueError, match="circular"):
            gpipe(_stage_fn, params, jnp.zeros((4, 4)), n_microbatch=2,
                  circular_repeats=2)


class Test1F1B:
    """Explicit-backward 1F1B schedule (gpipe_1f1b_grads): grads must equal
    the sequential reference, and — the point of the schedule — the
    compiled temp footprint must be flat in the microbatch count while
    jax.grad(gpipe)'s grows linearly (VERDICT r4 weak #9)."""

    def _loss(self, o, t):
        return jnp.mean((o - t) ** 2)

    def test_matches_sequential_loss_and_grads(self, pipe_ctx):
        from analytics_zoo_tpu.parallel import gpipe_1f1b_grads

        S, M, B, D = 4, 8, 32, 16
        rng = np.random.default_rng(0)
        sp = _make(rng, S, D)
        x = rng.normal(0, 1, (B, D)).astype(np.float32)
        y = rng.normal(0, 1, (B, D)).astype(np.float32)

        loss, grads = jax.jit(lambda sp, x, y: gpipe_1f1b_grads(
            _stage_fn, self._loss, sp, x, y, n_microbatch=M,
            batch_axis="data"))(sp, x, y)

        def ref(sp):
            out = jnp.asarray(x)
            for j in range(S):
                out = _stage_fn(
                    jax.tree_util.tree_map(lambda a, _j=j: a[_j], sp), out)
            om = out.reshape(M, B // M, D)
            ym = y.reshape(M, B // M, D)
            return jnp.mean(jax.vmap(self._loss)(om, ym))

        rl, rg = jax.value_and_grad(ref)(
            jax.tree_util.tree_map(jnp.asarray, sp))
        np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
        for k in grads:
            np.testing.assert_allclose(np.asarray(grads[k]),
                                       np.asarray(rg[k]),
                                       rtol=1e-4, atol=1e-5)

    def test_sgd_with_1f1b_converges(self, pipe_ctx):
        from analytics_zoo_tpu.parallel import gpipe_1f1b_grads

        S, M, B, D = 4, 8, 32, 8
        rng = np.random.default_rng(1)
        sp = jax.tree_util.tree_map(jnp.asarray, _make(rng, S, D))
        x = jnp.asarray(rng.normal(0, 1, (B, D)), jnp.float32)
        y = jnp.asarray(rng.normal(0, 1, (B, D)), jnp.float32)

        @jax.jit
        def step(sp):
            l, g = gpipe_1f1b_grads(_stage_fn, self._loss, sp, x, y,
                                    n_microbatch=M, batch_axis="data")
            return jax.tree_util.tree_map(
                lambda p, d: p - 0.5 * d, sp, g), l

        losses = []
        for _ in range(30):
            sp, l = step(sp)
            losses.append(float(l))
        assert losses[-1] < 0.6 * losses[0], losses
        assert losses[-1] == min(losses)

    def test_temp_memory_flat_in_microbatches(self):
        """The memory claim itself, from XLA's own accounting: growing M
        4x grows jax.grad(gpipe) temps ~linearly but leaves the 1F1B
        schedule's temps flat (ring buffer is O(S), not O(M))."""
        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.parallel import gpipe, gpipe_1f1b_grads

        init_zoo_context(mesh_shape={"pipe": 4}, mesh_axes=("pipe",),
                         seed=0)
        S, D = 4, 128
        rng = np.random.default_rng(0)
        sp = jax.tree_util.tree_map(jnp.asarray, _make(rng, S, D))

        def temps(M, mode):
            B = 8 * M
            x = jax.ShapeDtypeStruct((B, D), jnp.float32)
            y = jax.ShapeDtypeStruct((B, D), jnp.float32)
            if mode == "1f1b":
                def f(sp, x, y):
                    return gpipe_1f1b_grads(_stage_fn, self._loss, sp, x,
                                            y, n_microbatch=M)
            else:
                def f(sp, x, y):
                    def loss(sp):
                        out = gpipe(_stage_fn, sp, x, n_microbatch=M)
                        return self._loss(out, y)
                    return jax.value_and_grad(loss)(sp)
            c = jax.jit(f).lower(sp, x, y).compile()
            ma = c.memory_analysis()
            if ma is None:  # backend without memory accounting
                pytest.skip("memory_analysis unavailable")
            return ma.temp_size_in_bytes

        g8, g32 = temps(8, "gpipe"), temps(32, "gpipe")
        f8, f32 = temps(8, "1f1b"), temps(32, "1f1b")
        assert g32 > 2.0 * g8          # GPipe backward temps scale with M
        assert f32 < 1.2 * f8          # 1F1B stays flat
        assert f32 < 0.5 * g32         # and wins outright at M=32

    def test_stage_dim_validation(self, pipe_ctx):
        from analytics_zoo_tpu.parallel import gpipe_1f1b_grads

        rng = np.random.default_rng(0)
        sp = _make(rng, 3, 8)  # wrong: pipe axis is 4
        with pytest.raises(ValueError, match="leading dim"):
            gpipe_1f1b_grads(_stage_fn, self._loss, sp,
                             jnp.zeros((8, 8)), jnp.zeros((8, 8)),
                             n_microbatch=2)


class TestHetero1F1B:
    """1F1B over heterogeneous stages (embed -> blocks -> head): the
    union-buffer carry of gpipe_hetero under the explicit-backward
    schedule — grads must equal the sequential reference, temps must
    stay flat in M (the LM shape is exactly where PP memory matters)."""

    def _setup(self, S=4, B=16, L=6, D=8, V=12, seed=0):
        rng = np.random.default_rng(seed)
        edge = [
            {"tok": jnp.asarray(rng.normal(0, .5, (V, D)), jnp.float32)},
            None, None,
            {"w": jnp.asarray(rng.normal(0, .5, (D, V)), jnp.float32)},
        ]
        stacked = {
            "w": jnp.asarray(rng.normal(0, .4, (S, D, D)), jnp.float32),
            "b": jnp.zeros((S, D), jnp.float32)}

        def f0(e, sl, t):
            h = jnp.take(e["tok"], t, axis=0)
            return jnp.tanh(h @ sl["w"] + sl["b"])

        def fmid(e, sl, h):
            return jnp.tanh(h @ sl["w"] + sl["b"])

        def flast(e, sl, h):
            h = jnp.tanh(h @ sl["w"] + sl["b"])
            return h @ e["w"]

        fns = [f0] + [fmid] * (S - 2) + [flast]
        toks = jnp.asarray(rng.integers(0, V, (B, L)), jnp.int32)
        y = jnp.asarray(rng.integers(0, V, (B, L)), jnp.int32)
        return fns, edge, stacked, toks, y

    @staticmethod
    def _loss(logits, labels):
        lp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(lp, labels[..., None], -1))

    def test_matches_sequential_lm_grads(self, pipe_ctx):
        from analytics_zoo_tpu.parallel import gpipe_hetero_1f1b_grads

        S, M = 4, 8
        fns, edge, stacked, toks, y = self._setup(S=S)
        loss, ge, gs = jax.jit(
            lambda e, s, x, yy: gpipe_hetero_1f1b_grads(
                fns, e, s, x, yy, self._loss, n_microbatch=M))(
            tuple(edge), stacked, toks, y)

        def ref(params):
            e, sl = params
            h = jnp.take(e[0]["tok"], toks, axis=0)
            for j in range(S):
                slj = jax.tree_util.tree_map(lambda a, _j=j: a[_j], sl)
                h = jnp.tanh(h @ slj["w"] + slj["b"])
            logits = h @ e[S - 1]["w"]
            B, L, V = logits.shape
            lm = logits.reshape(M, B // M, L, V)
            ym = y.reshape(M, B // M, L)
            return jnp.mean(jax.vmap(self._loss)(lm, ym))

        rl, (rge, rgs) = jax.value_and_grad(ref)((tuple(edge), stacked))
        np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
        for got, want in ((ge[0]["tok"], rge[0]["tok"]),
                          (ge[S - 1]["w"], rge[S - 1]["w"]),
                          (gs["w"], rgs["w"]), (gs["b"], rgs["b"])):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-4, atol=1e-5)

    def test_temp_memory_beats_grad_at_fixed_batch(self, pipe_ctx):
        """Fixed global batch, growing M: the 1F1B live set (in-flight
        frames, O(S) of them) must stay well under grad-of-gpipe_hetero's
        per-tick saves at every microbatch count, and shrink as frames
        get finer — the O(in-flight) behavior.  (Unlike the homogeneous
        test, input frames are staged in-graph here, so 'flat in M with
        growing B' is not the right invariant.)"""
        from analytics_zoo_tpu.parallel import gpipe_hetero_1f1b_grads
        from analytics_zoo_tpu.parallel.pipeline import gpipe_hetero

        S, L, D, V, B = 4, 6, 64, 32, 128

        def temps(M, mode):
            fns, edge, stacked, _, _ = self._setup(S=S, B=B, L=L, D=D,
                                                   V=V)
            toks = jax.ShapeDtypeStruct((B, L), jnp.int32)
            y = jax.ShapeDtypeStruct((B, L), jnp.int32)
            if mode == "1f1b":
                def f(e, s, x, yy):
                    return gpipe_hetero_1f1b_grads(
                        fns, e, s, x, yy, self._loss, n_microbatch=M)
            else:
                def f(e, s, x, yy):
                    def loss(params):
                        ee, ss = params
                        out = gpipe_hetero(fns, list(ee), ss, x,
                                           n_microbatch=M)
                        om = out.reshape((M, B // M) + out.shape[1:])
                        ym = yy.reshape(M, B // M, L)
                        return jnp.mean(jax.vmap(self._loss)(om, ym))
                    return jax.value_and_grad(loss)((e, s))
            c = jax.jit(f).lower(tuple(edge), stacked, toks, y).compile()
            ma = c.memory_analysis()
            if ma is None:
                pytest.skip("memory_analysis unavailable")
            return ma.temp_size_in_bytes

        for M in (8, 32):
            assert temps(M, "1f1b") < 0.5 * temps(M, "grad"), M
        assert temps(32, "1f1b") < temps(8, "1f1b")

    def test_stacked_dim_validation_and_single_stage(self):
        from analytics_zoo_tpu import init_zoo_context
        from analytics_zoo_tpu.parallel import gpipe_hetero_1f1b_grads

        init_zoo_context(mesh_shape={"data": 8}, seed=0)  # no pipe axis
        rng = np.random.default_rng(0)
        D, V, B, L = 8, 12, 8, 6
        edge1 = [{"tok": jnp.asarray(rng.normal(0, .5, (V, D)),
                                     jnp.float32),
                  "w": jnp.asarray(rng.normal(0, .5, (D, V)),
                                   jnp.float32)}]
        st1 = {"w": jnp.asarray(rng.normal(0, .4, (1, D, D)),
                                jnp.float32)}

        def whole_lm(e, sl, t):
            h = jnp.take(e["tok"], t, axis=0)
            return jnp.tanh(h @ sl["w"]) @ e["w"]

        toks = jnp.asarray(rng.integers(0, V, (B, L)), jnp.int32)
        y = jnp.asarray(rng.integers(0, V, (B, L)), jnp.int32)
        # single-stage fallback works without a pipe axis
        loss, ge, gs = gpipe_hetero_1f1b_grads(
            [whole_lm], edge1, st1, toks, y, self._loss, n_microbatch=2)
        assert np.isfinite(float(loss))
        assert gs["w"].shape == (1, D, D)

        init_zoo_context(mesh_shape={"data": 2, "pipe": 4},
                         mesh_axes=("data", "pipe"), seed=0)
        fns4, edge4, stacked4, toks4, y4 = self._setup(S=4)
        bad = jax.tree_util.tree_map(  # 8 blocks on a 4-stage pipe
            lambda a: jnp.concatenate([a, a]), stacked4)
        with pytest.raises(ValueError, match="leading dim"):
            gpipe_hetero_1f1b_grads(fns4, edge4, bad, toks4, y4,
                                    self._loss, n_microbatch=4)
