"""Predictive compile plane (analysis/costmodel.py + analysis/oracle.py):
roofline shape (monotone in work, concave K-amortization), peak-table
resolution + the ZOO_ORACLE_PEAKS override contract, residual
fit/predict round-trip with the analytic fallback below the sample
floor, the zoo-hlo-report/2 + tune-log readers and their training-row
join, choose_plan budget cases, the autotuner's oracle-prior
convergence in <= 8 tuning dispatches, the ZOO_TUNE_LOG_DIR JSONL
persistence + rotation satellite, and the bench quick-tier guard."""

import json
import os
import sys

import pytest

from analytics_zoo_tpu.analysis.costmodel import (
    PLATFORM_PEAKS,
    ResidualModel,
    load_report_rows,
    load_tune_log_rows,
    normalize_features,
    plan_collective_bytes,
    predict_chip_bytes,
    predict_step_seconds,
    predict_steps_per_sec,
    resolve_peaks,
    training_rows,
)
from analytics_zoo_tpu.analysis.hlo import HloReport, remember_report
from analytics_zoo_tpu.analysis.oracle import ConfigOracle, oracle_enabled
from analytics_zoo_tpu.feature.autotune import (
    AutotuneController,
    _append_tune_log,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(autouse=True)
def _clean_oracle_env(monkeypatch):
    """Peaks/dirs resolve from the env — keep each test hermetic."""
    for var in ("ZOO_ORACLE_PEAKS", "ZOO_HLO_REPORT_DIR",
                "ZOO_TUNE_LOG_DIR", "ZOO_TUNE_LOG_MAX_BYTES",
                "ZOO_ORACLE"):
        monkeypatch.delenv(var, raising=False)


def _feats(flops=1e9, bytes_accessed=4e8, collective_bytes=0,
           op_count=100):
    return {"matmul_flops": flops, "bytes_accessed": bytes_accessed,
            "collective_bytes": collective_bytes, "op_count": op_count}


# ---------------------------------------------------------------------------
# roofline shape
# ---------------------------------------------------------------------------

def test_roofline_monotone_in_work():
    """More flops / more bytes / more collective traffic must never
    predict a FASTER step — the roofline is monotone in every work
    term."""
    peaks = PLATFORM_PEAKS["cpu"]
    base = predict_step_seconds(_feats(), peaks=peaks)
    for grown in (_feats(flops=4e9),
                  _feats(bytes_accessed=4e9),
                  _feats(collective_bytes=1e9)):
        assert predict_step_seconds(grown, peaks=peaks) >= base


def test_roofline_k_amortization_concave():
    """step_seconds(K) falls monotonically with diminishing returns
    (only the dispatch-overhead term divides by K) and plateaus at the
    compute/memory bound — the exact shape the measured K curve in
    BENCH_AUTOTUNE_r08 has."""
    peaks = PLATFORM_PEAKS["cpu"]
    ks = (1, 2, 4, 8, 16)
    s = [predict_step_seconds(_feats(), k=k, peaks=peaks) for k in ks]
    gains = [a - b for a, b in zip(s, s[1:])]
    assert all(g > 0 for g in gains)            # monotone improvement
    assert all(a > b for a, b in zip(gains, gains[1:]))  # concave
    floor = predict_step_seconds(_feats(), k=10**9, peaks=peaks)
    bound = max(1e9 / peaks.flops, 4e8 / peaks.hbm_bytes_per_s)
    assert floor == pytest.approx(bound, rel=1e-6)  # plateau = roofline


def test_roofline_inverse():
    sps = predict_steps_per_sec(_feats(), k=4)
    assert sps == pytest.approx(
        1.0 / predict_step_seconds(_feats(), k=4), rel=1e-9)


def test_normalize_features_aliases():
    """All three emitted shapes (HloReport.features, zoo_hlo_* scrape,
    bench hlo block) normalize to one canonical vector; missing keys
    become 0 so a v1 report with nulls still yields a usable vector."""
    canon = normalize_features({"zoo_hlo_flops": 7, "zoo_hlo_ops": 3})
    assert canon["matmul_flops"] == 7.0
    assert canon["op_count"] == 3.0
    assert canon["bytes_accessed"] == 0.0


# ---------------------------------------------------------------------------
# peak resolution + env override
# ---------------------------------------------------------------------------

def test_resolve_peaks_device_kind():
    assert resolve_peaks("tpu", "TPU v4").source == "tpu-v4"
    assert resolve_peaks(None, "TPU v5 lite").source.startswith("tpu")
    assert resolve_peaks("cpu", None).source == "cpu-default"
    # unknown TPU generations fall to the v4 row, not the CPU row
    assert resolve_peaks("tpu", "tpu-v99").source == "tpu-v4"


def test_peaks_env_override(monkeypatch):
    monkeypatch.setenv("ZOO_ORACLE_PEAKS", json.dumps(
        {"hbm_bytes": 123456.0}))
    peaks = resolve_peaks("cpu")
    assert peaks.hbm_bytes == 123456.0
    assert peaks.source == "env"
    # untouched fields keep the platform row
    assert peaks.flops == PLATFORM_PEAKS["cpu"].flops


def test_peaks_env_override_rejects_unknown_field(monkeypatch):
    monkeypatch.setenv("ZOO_ORACLE_PEAKS", json.dumps({"hbm_byte": 1}))
    with pytest.raises(ValueError, match="hbm_byte"):
        resolve_peaks("cpu")


def test_peaks_env_override_rejects_non_object(monkeypatch):
    monkeypatch.setenv("ZOO_ORACLE_PEAKS", "[1, 2]")
    with pytest.raises(ValueError):
        resolve_peaks("cpu")
    monkeypatch.setenv("ZOO_ORACLE_PEAKS", "{not json")
    with pytest.raises(ValueError):
        resolve_peaks("cpu")


def test_oracle_enabled_default_on(monkeypatch):
    assert oracle_enabled()
    monkeypatch.setenv("ZOO_ORACLE", "0")
    assert not oracle_enabled()


# ---------------------------------------------------------------------------
# residual model: fit/predict round-trip + analytic fallback
# ---------------------------------------------------------------------------

def _synthetic_rows(peaks, factor=1.7):
    rows = []
    for k in (1, 2, 4, 8, 16):
        for scale in (1.0, 2.0):
            f = _feats(flops=1e9 * scale, bytes_accessed=4e8 * scale)
            rows.append({
                "features": f, "k": k,
                "measured_steps_per_sec":
                    factor * predict_steps_per_sec(f, k=k, peaks=peaks)})
    return rows


def test_residual_fit_round_trip():
    """Measurements a constant 1.7x off the analytic roofline: the
    fitted residual must reproduce them — on every training row the
    corrected prediction lands within 5% of the measurement."""
    peaks = PLATFORM_PEAKS["cpu"]
    rows = _synthetic_rows(peaks)
    model = ResidualModel(peaks=peaks).fit(rows)
    assert model.ready
    assert model.n_samples == len(rows)
    for row in rows:
        pred = model.predict_steps_per_sec(row["features"], k=row["k"])
        assert pred == pytest.approx(
            row["measured_steps_per_sec"], rel=0.05)


def test_residual_zero_sample_analytic_fallback():
    """Below MIN_FIT_SAMPLES the model stays analytic: ready is False
    and predictions equal the pure roofline bit-for-bit, so callers
    never branch on readiness."""
    peaks = PLATFORM_PEAKS["cpu"]
    rows = _synthetic_rows(peaks)[:3]
    model = ResidualModel(peaks=peaks).fit(rows)
    assert not model.ready
    assert model.n_samples == 3
    f = _feats()
    assert model.predict_steps_per_sec(f, k=4) == \
        predict_steps_per_sec(f, k=4, peaks=peaks)
    # unfit model (no fit() call at all) behaves identically
    assert ResidualModel(peaks=peaks).predict_steps_per_sec(f, k=4) == \
        predict_steps_per_sec(f, k=4, peaks=peaks)


def test_residual_drops_unmeasured_rows():
    peaks = PLATFORM_PEAKS["cpu"]
    rows = _synthetic_rows(peaks)
    rows += [{"features": _feats(), "k": 1,
              "measured_steps_per_sec": 0}] * 5
    model = ResidualModel(peaks=peaks).fit(rows)
    assert model.n_samples == len(rows) - 5


# ---------------------------------------------------------------------------
# report/tune-log readers + the training join
# ---------------------------------------------------------------------------

def _write_report_doc(report_dir, doc, name="hlo-t-1-1.json"):
    os.makedirs(report_dir, exist_ok=True)
    with open(os.path.join(report_dir, name), "w") as f:
        json.dump(doc, f)


def test_report_reader_v2_and_v1(tmp_path):
    """The v2 writer round-trips through the reader; a v1 report (no
    compile/config context) still loads with the new fields None."""
    rpt = HloReport(label="step", matmul_flops=123, bytes_accessed=456,
                    op_count=7, compile_seconds=0.41, plan="fsdp",
                    mesh_shape={"data": 8}, steps_per_dispatch=16,
                    dtype_histogram={"f32": 5})
    _write_report_doc(str(tmp_path), rpt.to_doc(), "hlo-step-1-1.json")
    _write_report_doc(str(tmp_path), {
        "schema": "zoo-hlo-report/1", "label": "old",
        "features": {"matmul_flops": 9},
    }, "hlo-old-1-2.json")
    _write_report_doc(str(tmp_path), {"schema": "other"}, "hlo-x-1-3.json")
    (tmp_path / "hlo-broken-1-4.json").write_text("{not json")

    rows = {r["label"]: r for r in load_report_rows(str(tmp_path))}
    assert set(rows) == {"step", "old"}
    v2 = rows["step"]
    assert v2["features"]["matmul_flops"] == 123.0
    assert v2["k"] == 16
    assert v2["plan"] == "fsdp"
    assert v2["mesh_shape"] == {"data": 8}
    assert v2["compile_seconds"] == 0.41
    assert v2["dtype_histogram"] == {"f32": 5}
    v1 = rows["old"]
    assert v1["features"]["matmul_flops"] == 9.0
    assert v1["k"] is None and v1["plan"] is None
    assert v1["compile_seconds"] is None


def test_tune_log_persistence_and_rotation(tmp_path, monkeypatch):
    """ZOO_TUNE_LOG_DIR persists decisions as JSONL; past the byte cap
    the file rotates to .1 (one predecessor kept) instead of growing
    unboundedly; the reader turns settle records' cost curves into
    per-K measurement rows."""
    monkeypatch.setenv("ZOO_TUNE_LOG_DIR", str(tmp_path))
    settle = {"type": "settle", "label": "step", "k": 16,
              "k_cost_per_step_s": {"1": 0.01, "16": 0.002}}
    _append_tune_log(settle)
    path = tmp_path / f"tune-{os.getpid()}.jsonl"
    assert path.exists()

    rows = load_tune_log_rows(str(tmp_path))
    assert {(r["k"], r["measured_steps_per_sec"]) for r in rows} == \
        {(1, 100.0), (16, 500.0)}
    assert all(r["label"] == "step" for r in rows)

    monkeypatch.setenv("ZOO_TUNE_LOG_MAX_BYTES", "150")
    for _ in range(10):
        _append_tune_log(settle)
    assert (tmp_path / (path.name + ".1")).exists()
    assert path.stat().st_size <= 150 + len(json.dumps(settle)) + 1


def test_training_rows_join(tmp_path, monkeypatch):
    """Tune-log rows (measurement, no features) join with the latest
    report row of the same compile label; unjoinable labels drop
    silently and the empty-history result is []."""
    report_dir, tune_dir = tmp_path / "rpt", tmp_path / "tune"
    rpt = HloReport(label="step", matmul_flops=123, bytes_accessed=456)
    _write_report_doc(str(report_dir), rpt.to_doc())
    monkeypatch.setenv("ZOO_TUNE_LOG_DIR", str(tune_dir))
    _append_tune_log({"type": "settle", "label": "step", "k": 4,
                      "k_cost_per_step_s": {"4": 0.004}})
    _append_tune_log({"type": "settle", "label": "orphan", "k": 2,
                      "k_cost_per_step_s": {"2": 0.02}})

    rows = training_rows(report_dir=str(report_dir),
                         tune_log_dir=str(tune_dir))
    assert len(rows) == 1
    assert rows[0]["k"] == 4
    assert rows[0]["features"]["matmul_flops"] == 123.0
    assert rows[0]["measured_steps_per_sec"] == pytest.approx(250.0)
    assert training_rows(report_dir=str(tmp_path / "none"),
                         tune_log_dir=str(tmp_path / "none")) == []


# ---------------------------------------------------------------------------
# ConfigOracle: predict_k, choose_plan, the prediction->outcome log
# ---------------------------------------------------------------------------

def test_predict_k_overhead_bound_prefers_large_k():
    """Tiny program: dispatch overhead dominates, so the largest K wins
    by a margin — and EVERY candidate's prediction is logged so the
    settled K always has a pair to score."""
    oracle = ConfigOracle(peaks=PLATFORM_PEAKS["cpu"])
    tiny = _feats(flops=1e3, bytes_accessed=1e3)
    k_hat = oracle.predict_k(tiny, (1, 2, 4, 8, 16))
    assert k_hat == 16
    log = {p["config"]: p for p in oracle.prediction_log()}
    assert set(log) == {f"k={k}" for k in (1, 2, 4, 8, 16)}
    assert log["k=16"]["chosen"]
    assert not log["k=1"]["chosen"]


def test_predict_k_compute_bound_prefers_small_k():
    """Compute-bound program: K cannot help, all candidates tie within
    the margin, and the tie goes to the smallest K (finer checkpoint
    cadence for free)."""
    oracle = ConfigOracle(peaks=PLATFORM_PEAKS["cpu"])
    big = _feats(flops=1e12, bytes_accessed=1e10)
    assert oracle.predict_k(big, (1, 2, 4, 8, 16)) == 1


def test_record_outcome_closes_pair():
    oracle = ConfigOracle(peaks=PLATFORM_PEAKS["cpu"])
    oracle.predict_k(_feats(flops=1e3, bytes_accessed=1e3),
                     (1, 2, 4, 8, 16))
    predicted = {p["config"]: p["predicted_steps_per_sec"]
                 for p in oracle.prediction_log()}["k=16"]
    pair = oracle.record_outcome("k=16", predicted * 1.25,
                                 consumer="autotune_k")
    assert pair is not None
    assert pair["rel_error"] == pytest.approx(0.2, abs=1e-3)
    # an outcome with no recorded prediction logs but returns None
    assert oracle.record_outcome("k=99", 1.0) is None
    doc = oracle.to_doc()
    assert doc["fit_samples"] == 0 and not doc["residual_ready"]


def test_choose_plan_budget_cases():
    """Tight budget -> the only feasible plan (fsdp); generous budget
    -> the least-collective plan (dp); infeasible-everywhere -> the
    most memory-frugal candidate with feasible=False recorded."""
    oracle = ConfigOracle(peaks=PLATFORM_PEAKS["cpu"])
    p, o, n = 800_000, 1_600_000, 8
    assert predict_chip_bytes(p, o, "dp", n) == p + o
    assert predict_chip_bytes(p, o, "zero1", n) == p + o // n
    assert predict_chip_bytes(p, o, "fsdp", n) == (p + o) // n

    name, doc = oracle.choose_plan(p, o, n, hbm_budget=400_000)
    assert name == "fsdp" and doc["feasible"]
    name, doc = oracle.choose_plan(p, o, n, hbm_budget=10 * (p + o))
    assert name == "dp" and doc["feasible"]
    name, doc = oracle.choose_plan(p, o, n, hbm_budget=1_000)
    assert name == "fsdp" and not doc["feasible"]
    by_plan = {c["plan"]: c for c in doc["candidates"]}
    assert not by_plan["dp"]["fits_budget"]
    # sharding only adds collectives: dp moves the least per step
    assert plan_collective_bytes(p, "dp", n) < \
        plan_collective_bytes(p, "fsdp", n)


# ---------------------------------------------------------------------------
# the autotuner consuming the prior: <= 8 tuning dispatches to settle
# ---------------------------------------------------------------------------

def test_controller_prior_converges_in_few_dispatches():
    """Overhead-dominated synthetic cost curve: with the oracle prior
    the controller jumps to the predicted K=16 and settles after
    validating only the +-1 ladder neighbors — the acceptance budget is
    <= 8 TUNING dispatches (stale in-flight chunks from before a K
    switch are pipeline latency and excluded by design)."""
    label = "oracle-prior-unit"
    remember_report(HloReport(label=label, matmul_flops=1e3,
                              bytes_accessed=1e3, op_count=10))
    oracle = ConfigOracle(peaks=PLATFORM_PEAKS["cpu"])
    ctrl = AutotuneController(oracle=oracle,
                              k_candidates=(1, 2, 4, 8, 16))
    ctrl.set_feature_label(label)
    # per-dispatch cost model: 1e-4 s/step + 5e-4 s dispatch overhead
    for _ in range(64):
        if ctrl.k_settled:
            break
        k = ctrl.current()["k"]
        ctrl.observe_dispatch(k, k * 1e-4 + 5e-4)
    assert ctrl.k_settled
    snap = ctrl.current()
    assert snap["k"] == 16
    assert snap["k_settle_dispatch"] <= 8
    # the first dispatch (queued at K=1 before the prior flipped the
    # knob) is stale: observed, but not a tuning dispatch
    assert snap["dispatches_observed"] == snap["tuning_dispatches"] + 1
    reasons = [d["reason"] for d in ctrl.decision_log()]
    assert "oracle_prior" in reasons
    assert "probe_up" not in reasons  # validation pass, not a climb
    # settle closed a prediction->outcome pair on the chosen config
    pairs = {p["config"]: p for p in oracle.prediction_log()}
    assert pairs["k=16"]["measured_steps_per_sec"] is not None
    assert pairs["k=16"]["rel_error"] is not None


def test_controller_blind_without_oracle():
    """No oracle attached: the blind hill-climb still probes up from
    K=1 — the prior is an accelerator, not a dependency."""
    ctrl = AutotuneController(k_candidates=(1, 2, 4), k_samples=2,
                              k_warm_skip=1)
    for _ in range(64):
        if ctrl.k_settled:
            break
        k = ctrl.current()["k"]
        ctrl.observe_dispatch(k, k * 1e-4 + 5e-4)
    assert ctrl.k_settled
    assert ctrl.current()["k"] == 4
    assert "probe_up" in [d["reason"] for d in ctrl.decision_log()]


# ---------------------------------------------------------------------------
# bench quick-tier guard (the acceptance pins)
# ---------------------------------------------------------------------------

def test_oracle_bench_quick_tier(tmp_path):
    """CI guard: the prior-guided controller must settle within the
    8-tuning-dispatch budget with the loss trajectory bitwise-equal to
    the K=1 baseline, and plan="auto" must agree with the exhaustive
    partition sweep's best-under-budget — the full-tier acceptance
    (BENCH_ORACLE_r11.json) additionally pins within-5%-of-best
    steady-state throughput against the measured blind climb."""
    import bench

    doc = bench.oracle_bench(quick=True,
                             out_path=str(tmp_path / "bench.json"))
    assert doc["value"] <= 8, doc["k_prior"]
    assert doc["k_prior"]["k_settled"], doc["k_prior"]
    assert doc["k_prior"]["loss_trajectory_bitwise_equal_to_k1"], \
        doc["k_prior"]
    assert doc["plan_auto"]["agrees_with_exhaustive"], doc["plan_auto"]
    rel = doc["plan_auto"]["predicted_vs_measured_chip_bytes"]
    assert all(v["rel_error"] < 0.05 for v in rel.values()), rel
    fp = doc["host_fingerprint"]
    assert fp["cpu_count"] and fp["peak_table"], fp
    assert (tmp_path / "bench.json").exists()
