"""ONNX exporter round-trip tests: export_onnx(net) reloaded through
load_onnx (itself validated against official-protobuf fixtures +
numpy oracles in test_onnx.py) must reproduce the original net's forward.
Exported graphs are NCHW per ONNX convention; inputs transpose accordingly."""

import numpy as np
import pytest

import jax

rng0 = np.random.default_rng(0)


def _roundtrip(net, x_nhwc, atol=1e-4):
    from analytics_zoo_tpu.pipeline.api.onnx import load_onnx
    from analytics_zoo_tpu.pipeline.api.onnx.export import export_onnx

    ref, _ = net.forward(net.params, x_nhwc, state=net.state,
                         training=False)
    data = export_onnx(net)
    loaded = load_onnx(data)
    x = x_nhwc.transpose(0, 3, 1, 2) if x_nhwc.ndim == 4 else x_nhwc
    loaded.ensure_built(tuple(x.shape)[1:])
    lp = loaded.init_params(jax.random.PRNGKey(0))
    out, _ = loaded.apply(lp, x, state=loaded.init_state() or None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=atol, rtol=1e-4)
    return data


class TestSequentialExport:
    def test_mlp(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Dense,
            Dropout,
        )

        m = Sequential()
        m.add(Dense(16, activation="relu", input_shape=(8,)))
        m.add(Dropout(0.5))
        m.add(Dense(4, activation="softmax"))
        m.build_params(jax.random.PRNGKey(0))
        x = rng0.normal(size=(5, 8)).astype(np.float32)
        _roundtrip(m, x)

    def test_cnn_with_flatten_permutation(self, zoo_ctx):
        """The NHWC->NCHW flatten-order fix-up: Dense-after-Flatten only
        matches if its kernel rows were permuted to CHW order."""
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Convolution2D,
            Dense,
            Flatten,
            MaxPooling2D,
        )

        m = Sequential()
        m.add(Convolution2D(6, 3, 3, activation="relu", border_mode="same",
                            input_shape=(12, 10, 3)))
        m.add(MaxPooling2D(pool_size=(2, 2)))
        m.add(Flatten())
        m.add(Dense(5, activation="softmax"))
        m.build_params(jax.random.PRNGKey(1))
        x = rng0.normal(size=(3, 12, 10, 3)).astype(np.float32)
        _roundtrip(m, x)

    def test_bn_and_pools(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Activation,
            AveragePooling2D,
            BatchNormalization,
            Convolution2D,
            GlobalAveragePooling2D,
        )

        m = Sequential()
        m.add(Convolution2D(4, 3, 3, subsample=(2, 2), border_mode="same",
                            input_shape=(16, 16, 3)))
        m.add(BatchNormalization())
        m.add(Activation("relu"))
        m.add(AveragePooling2D(pool_size=(2, 2)))
        m.add(GlobalAveragePooling2D())
        m.build_params(jax.random.PRNGKey(2))
        # non-trivial BN stats: run a training forward to update them
        xw = rng0.normal(size=(8, 16, 16, 3)).astype(np.float32)
        _, st = m.forward(m.params, xw, state=m.state, training=True,
                          rng=jax.random.PRNGKey(0))
        m.state = st
        x = rng0.normal(size=(4, 16, 16, 3)).astype(np.float32)
        _roundtrip(m, x)


class TestGraphModelExport:
    def test_residual_graph_with_merge(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.api.keras import Input, Model
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Activation,
            Convolution2D,
            GlobalAveragePooling2D,
            Dense,
            Merge,
        )

        inp = Input(shape=(8, 8, 3), name="img")
        a = Convolution2D(4, 3, 3, border_mode="same")(inp)
        b = Convolution2D(4, 1, 1, border_mode="same")(inp)
        s = Merge(mode="sum")([a, b])
        s = Activation("relu")(s)
        pooled = GlobalAveragePooling2D()(s)
        out = Dense(3, activation="softmax")(pooled)
        net = Model(inp, out)
        net.build_params(jax.random.PRNGKey(3))
        x = rng0.normal(size=(2, 8, 8, 3)).astype(np.float32)
        _roundtrip(net, x)

    def test_concat_merge(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.api.keras import Input, Model
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Dense,
            Merge,
        )

        inp = Input(shape=(6,), name="x")
        a = Dense(4, activation="tanh")(inp)
        b = Dense(3, activation="relu")(inp)
        cat = Merge(mode="concat", concat_axis=-1)([a, b])
        out = Dense(2)(cat)
        net = Model(inp, out)
        net.build_params(jax.random.PRNGKey(4))
        x = rng0.normal(size=(5, 6)).astype(np.float32)
        _roundtrip(net, x)

    def test_lenet_model_exports(self, zoo_ctx):
        """A real zoo model end-to-end through the exporter."""
        from analytics_zoo_tpu.models.lenet import build_lenet

        net = build_lenet(classes=10)
        net.build_params(jax.random.PRNGKey(5))
        x = rng0.normal(size=(2, 28, 28, 1)).astype(np.float32)
        _roundtrip(net, x)


class TestExportErrors:
    def test_unsupported_layer_named(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import LSTM
        from analytics_zoo_tpu.pipeline.api.onnx.export import export_onnx

        m = Sequential()
        m.add(LSTM(4, input_shape=(5, 3)))
        m.build_params(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="no ONNX exporter"):
            export_onnx(m)

    def test_custom_activation_rejected(self, zoo_ctx):
        import jax.numpy as jnp

        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
        from analytics_zoo_tpu.pipeline.api.onnx.export import export_onnx

        m = Sequential()
        m.add(Dense(4, activation=lambda v: jnp.sin(v), input_shape=(3,)))
        m.build_params(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="no ONNX export"):
            export_onnx(m)

    def test_writes_file(self, zoo_ctx, tmp_path):
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
        from analytics_zoo_tpu.pipeline.api.onnx import load_onnx
        from analytics_zoo_tpu.pipeline.api.onnx.export import export_onnx

        m = Sequential()
        m.add(Dense(2, input_shape=(3,)))
        m.build_params(jax.random.PRNGKey(0))
        p = tmp_path / "model.onnx"
        data = export_onnx(m, path=str(p))
        assert p.read_bytes() == data
        assert load_onnx(str(p)) is not None


ONNX_MINI_PROTO = """
syntax = "proto3";
package onnxmini;
message AttributeProto {
  string name = 1;
  float f = 2;
  int64 i = 3;
  bytes s = 4;
  TensorProto t = 5;
  repeated float floats = 7;
  repeated int64 ints = 8;
  int32 type = 20;
}
message ValueInfoProto {
  string name = 1;
  TypeProto type = 2;
}
message NodeProto {
  repeated string input = 1;
  repeated string output = 2;
  string name = 3;
  string op_type = 4;
  repeated AttributeProto attribute = 5;
}
message ModelProto {
  int64 ir_version = 1;
  GraphProto graph = 7;
  repeated OperatorSetIdProto opset_import = 8;
}
message GraphProto {
  repeated NodeProto node = 1;
  string name = 2;
  repeated TensorProto initializer = 5;
  repeated ValueInfoProto input = 11;
  repeated ValueInfoProto output = 12;
}
message TensorProto {
  repeated int64 dims = 1;
  int32 data_type = 2;
  repeated float float_data = 4;
  string name = 8;
  bytes raw_data = 9;
}
message TensorShapeProto {
  message Dimension { int64 dim_value = 1; }
  repeated Dimension dim = 1;
}
message TypeProto {
  message Tensor {
    int32 elem_type = 1;
    TensorShapeProto shape = 2;
  }
  Tensor tensor_type = 1;
}
message OperatorSetIdProto {
  string domain = 1;
  int64 version = 2;
}
"""


class TestOfficialRuntimeParsesExport:
    """Mirror of TestExternalFixture in test_onnx.py: round 2 proved the
    DECODER against official-runtime-produced bytes; this proves the
    ENCODER's bytes parse with the official protobuf runtime (protoc-
    compiled subset of the public onnx.proto3 schema) and carry the
    intended graph."""

    def test_exported_bytes_parse_with_official_protobuf(self, zoo_ctx,
                                                         tmp_path):
        import subprocess
        import sys

        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Convolution2D,
            Dense,
            Flatten,
        )
        from analytics_zoo_tpu.pipeline.api.onnx.export import export_onnx

        (tmp_path / "onnxmini.proto").write_text(ONNX_MINI_PROTO)
        subprocess.run(
            ["protoc", f"--python_out={tmp_path}", "onnxmini.proto"],
            cwd=tmp_path, check=True)
        sys.path.insert(0, str(tmp_path))
        try:
            import onnxmini_pb2
        finally:
            sys.path.remove(str(tmp_path))

        m = Sequential()
        m.add(Convolution2D(4, 3, 3, activation="relu", border_mode="same",
                            input_shape=(8, 8, 3)))
        m.add(Flatten())
        m.add(Dense(5, activation="softmax"))
        m.build_params(jax.random.PRNGKey(0))
        data = export_onnx(m)

        pm = onnxmini_pb2.ModelProto()
        pm.ParseFromString(data)  # official parser accepts our bytes
        assert pm.ir_version == 8
        assert pm.opset_import[0].version == 13
        ops = [n.op_type for n in pm.graph.node]
        assert ops == ["Conv", "Relu", "Flatten", "Gemm", "Softmax"], ops
        assert pm.graph.input[0].name == "input"
        dims = [d.dim_value for d in
                pm.graph.input[0].type.tensor_type.shape.dim]
        assert dims == [0, 3, 8, 8]  # NCHW, batch dim unknown (0)
        # conv kernel initializer: OIHW transpose of our HWIO weights
        conv_w_name = pm.graph.node[0].input[1]
        init = {t.name: t for t in pm.graph.initializer}
        t = init[conv_w_name]
        assert list(t.dims) == [4, 3, 3, 3]
        ours = np.transpose(
            np.asarray(m.params[m.layers[0].name]["kernel"]), (3, 2, 0, 1))
        got = np.frombuffer(t.raw_data, np.float32).reshape(4, 3, 3, 3)
        np.testing.assert_array_equal(got, ours)
        # conv pads attribute (SAME 3x3 stride 1 -> [1,1,1,1])
        attrs = {a.name: a for a in pm.graph.node[0].attribute}
        assert list(attrs["pads"].ints) == [1, 1, 1, 1]


class TestFlatPermPropagation:
    """Review findings: every emitter that can receive a flattened
    (CHW-permuted) tensor must honor and propagate the order."""

    def test_bn_after_flatten(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            BatchNormalization,
            Convolution2D,
            Dense,
            Flatten,
        )

        m = Sequential()
        m.add(Convolution2D(3, 3, 3, border_mode="same",
                            input_shape=(6, 5, 2)))
        m.add(Flatten())
        m.add(BatchNormalization())
        m.add(Dense(4))
        m.build_params(jax.random.PRNGKey(0))
        xw = rng0.normal(size=(16, 6, 5, 2)).astype(np.float32)
        _, st = m.forward(m.params, xw, state=m.state, training=True,
                          rng=jax.random.PRNGKey(1))
        m.state = st
        x = rng0.normal(size=(3, 6, 5, 2)).astype(np.float32)
        _roundtrip(m, x)

    def test_sum_merge_of_flattened_branches(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.api.keras import Input, Model
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Convolution2D,
            Dense,
            Flatten,
            Merge,
        )

        inp = Input(shape=(4, 4, 2), name="x")
        a = Flatten()(Convolution2D(3, 3, 3, border_mode="same")(inp))
        b = Flatten()(Convolution2D(3, 1, 1, border_mode="same")(inp))
        out = Dense(4)(Merge(mode="sum")([a, b]))
        net = Model(inp, out)
        net.build_params(jax.random.PRNGKey(2))
        x = rng0.normal(size=(3, 4, 4, 2)).astype(np.float32)
        _roundtrip(net, x)

    def test_concat_merge_of_flattened_branches(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.api.keras import Input, Model
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Convolution2D,
            Dense,
            Flatten,
            Merge,
        )

        inp = Input(shape=(4, 4, 2), name="x")
        a = Flatten()(Convolution2D(3, 3, 3, border_mode="same")(inp))
        b = Dense(5, activation="tanh")(Flatten()(inp))
        cat = Merge(mode="concat", concat_axis=-1)([a, b])
        out = Dense(4)(cat)
        net = Model(inp, out)
        net.build_params(jax.random.PRNGKey(3))
        x = rng0.normal(size=(3, 4, 4, 2)).astype(np.float32)
        _roundtrip(net, x)

    def test_spatial_softmax_activation(self, zoo_ctx):
        """Softmax over NHWC channels must become axis=1 on NCHW."""
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Convolution2D,
        )

        m = Sequential()
        m.add(Convolution2D(4, 3, 3, activation="softmax",
                            border_mode="same", input_shape=(5, 6, 2)))
        m.build_params(jax.random.PRNGKey(4))
        x = rng0.normal(size=(2, 5, 6, 2)).astype(np.float32)
        ref, _ = m.forward(m.params, x, state=m.state, training=False)
        from analytics_zoo_tpu.pipeline.api.onnx import load_onnx
        from analytics_zoo_tpu.pipeline.api.onnx.export import export_onnx

        loaded = load_onnx(export_onnx(m))
        xt = x.transpose(0, 3, 1, 2)
        loaded.ensure_built(xt.shape[1:])
        lp = loaded.init_params(jax.random.PRNGKey(0))
        out, _ = loaded.apply(lp, xt, state=loaded.init_state() or None)
        np.testing.assert_allclose(
            np.asarray(out).transpose(0, 2, 3, 1), np.asarray(ref),
            atol=1e-4, rtol=1e-4)

    def test_nd_dense_uses_matmul(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

        m = Sequential()
        m.add(Dense(4, activation="relu", input_shape=(5, 3)))
        m.add(Dense(2))
        m.build_params(jax.random.PRNGKey(5))
        x = rng0.normal(size=(3, 5, 3)).astype(np.float32)
        ref, _ = m.forward(m.params, x, state=m.state, training=False)
        assert np.asarray(ref).shape == (3, 5, 2)
        from analytics_zoo_tpu.pipeline.api.onnx import load_onnx
        from analytics_zoo_tpu.pipeline.api.onnx.export import export_onnx

        data = export_onnx(m)
        loaded = load_onnx(data)
        loaded.ensure_built(x.shape[1:])
        lp = loaded.init_params(jax.random.PRNGKey(0))
        out, _ = loaded.apply(lp, x, state=loaded.init_state() or None)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4, rtol=1e-4)

    def test_flatten_as_output_restores_order(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Convolution2D,
            Flatten,
        )

        m = Sequential()
        m.add(Convolution2D(3, 3, 3, border_mode="same",
                            input_shape=(4, 5, 2)))
        m.add(Flatten())
        m.build_params(jax.random.PRNGKey(6))
        x = rng0.normal(size=(2, 4, 5, 2)).astype(np.float32)
        _roundtrip(m, x)  # exporter appends a Gather restoring HWC order

    def test_dense_on_spatial_tensor_rejected(self, zoo_ctx):
        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
        from analytics_zoo_tpu.pipeline.api.onnx.export import export_onnx

        m = Sequential()
        m.add(Dense(4, input_shape=(5, 6, 2)))
        m.build_params(jax.random.PRNGKey(7))
        with pytest.raises(ValueError, match="Flatten or a global pool"):
            export_onnx(m)


def test_double_flatten_keeps_order(zoo_ctx):
    """A Flatten on an already-flat tensor must propagate the CHW perm."""
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Convolution2D,
        Dense,
        Flatten,
    )

    m = Sequential()
    m.add(Convolution2D(3, 3, 3, border_mode="same",
                        input_shape=(4, 4, 2)))
    m.add(Flatten())
    m.add(Flatten())
    m.add(Dense(4))
    m.build_params(jax.random.PRNGKey(8))
    x = rng0.normal(size=(2, 4, 4, 2)).astype(np.float32)
    _roundtrip(m, x)


def test_global_max_pool_export(zoo_ctx):
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Convolution2D,
        Dense,
        GlobalMaxPooling2D,
    )

    m = Sequential()
    m.add(Convolution2D(4, 3, 3, border_mode="same",
                        input_shape=(6, 6, 2)))
    m.add(GlobalMaxPooling2D())
    m.add(Dense(3))
    m.build_params(jax.random.PRNGKey(9))
    x = rng0.normal(size=(2, 6, 6, 2)).astype(np.float32)
    _roundtrip(m, x)
