"""Registry-enforced save/load round-trip for EVERY public keras layer —
the serialization half of the reference's SerializerSpec
(zoo/src/test/.../serializer/SerializerSpec.scala:32: every module class
must round-trip through serialization or CI fails; the oracle half lives
in tests/test_layer_oracle_enforcement.py).

Each spec builds a small net containing the layer, materializes weights,
saves with ``KerasNet.save`` (the whitelisting-unpickler path) and
reloads; forward outputs must be IDENTICAL (predict = inference mode, so
stochastic layers are deterministic).  The enforcement test fails for
any public layer class with no spec — a new layer cannot ship without
round-trip coverage.
"""

import os
import tempfile

import numpy as np
import pytest

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.pipeline.api.keras import Input, Model, Sequential
from analytics_zoo_tpu.pipeline.api.keras.topology import KerasNet


@pytest.fixture(autouse=True)
def _ctx():
    init_zoo_context("layer-serialization-test", seed=0)


def _x(shape, seed=0, scale=1.0):
    return (np.random.default_rng(seed).normal(size=shape)
            * scale).astype(np.float32)


def _ints(shape, hi, seed=0):
    return np.random.default_rng(seed).integers(
        0, hi, size=shape).astype(np.int32)


def _seq(layer_fn, in_shape, ints=None):
    """Single-input spec: Sequential([layer]) + input maker."""
    def build():
        net = Sequential()
        net.add(layer_fn())
        x = (_ints((2,) + in_shape[:1], ints) if ints
             else _x((2,) + in_shape))
        return net, x
    return build


def _glove_file():
    d = tempfile.mkdtemp()
    path = os.path.join(d, "glove.txt")
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
        for w in ("alpha", "beta", "gamma"):
            vec = " ".join(f"{v:.4f}" for v in rng.normal(size=4))
            f.write(f"{w} {vec}\n")
    return path


def _specs():
    from analytics_zoo_tpu.pipeline.api.keras import layers as L

    S = {}

    def seq(name, fn, shape, ints=None):
        S[name] = _seq(fn, shape, ints)

    # ---- core / activations / elementwise ------------------------------
    seq("Dense", lambda: L.Dense(5, input_shape=(4,)), (4,))
    seq("Activation",
        lambda: L.Activation("tanh", input_shape=(4,)), (4,))
    seq("Dropout", lambda: L.Dropout(0.4, input_shape=(4,)), (4,))
    seq("Flatten", lambda: L.Flatten(input_shape=(2, 3)), (2, 3))
    seq("Reshape", lambda: L.Reshape((3, 2), input_shape=(2, 3)), (2, 3))
    seq("Permute",
        lambda: L.Permute((2, 1), input_shape=(2, 3)), (2, 3))
    seq("RepeatVector",
        lambda: L.RepeatVector(3, input_shape=(4,)), (4,))
    seq("Masking", lambda: L.Masking(0.0, input_shape=(3, 4)), (3, 4))
    seq("Highway", lambda: L.Highway(input_shape=(4,)), (4,))
    seq("MaxoutDense",
        lambda: L.MaxoutDense(5, input_shape=(4,)), (4,))
    seq("SparseDense",
        lambda: L.SparseDense(5, input_shape=(4,)), (4,))
    seq("Identity", lambda: L.Identity(input_shape=(4,)), (4,))
    seq("GaussianNoise",
        lambda: L.GaussianNoise(0.2, input_shape=(4,)), (4,))
    seq("GaussianDropout",
        lambda: L.GaussianDropout(0.2, input_shape=(4,)), (4,))
    seq("SpatialDropout1D",
        lambda: L.SpatialDropout1D(0.3, input_shape=(4, 3)), (4, 3))
    seq("SpatialDropout2D",
        lambda: L.SpatialDropout2D(0.3, input_shape=(4, 4, 3)), (4, 4, 3))
    seq("SpatialDropout3D",
        lambda: L.SpatialDropout3D(0.3, input_shape=(2, 4, 4, 3)),
        (2, 4, 4, 3))
    seq("ELU", lambda: L.ELU(input_shape=(4,)), (4,))
    seq("LeakyReLU", lambda: L.LeakyReLU(input_shape=(4,)), (4,))
    seq("PReLU", lambda: L.PReLU(input_shape=(4,)), (4,))
    seq("RReLU", lambda: L.RReLU(input_shape=(4,)), (4,))
    seq("SReLU", lambda: L.SReLU(input_shape=(4,)), (4,))
    seq("ParametricSoftPlus",
        lambda: L.ParametricSoftPlus(input_shape=(4,)), (4,))
    seq("ThresholdedReLU",
        lambda: L.ThresholdedReLU(0.5, input_shape=(4,)), (4,))
    seq("Threshold",
        lambda: L.Threshold(0.3, input_shape=(4,)), (4,))
    seq("BinaryThreshold",
        lambda: L.BinaryThreshold(0.1, input_shape=(4,)), (4,))
    seq("HardShrink", lambda: L.HardShrink(input_shape=(4,)), (4,))
    seq("SoftShrink", lambda: L.SoftShrink(input_shape=(4,)), (4,))
    seq("HardTanh", lambda: L.HardTanh(input_shape=(4,)), (4,))
    seq("Softmax", lambda: L.Softmax(input_shape=(4,)), (4,))
    seq("AddConstant",
        lambda: L.AddConstant(1.5, input_shape=(4,)), (4,))
    seq("MulConstant",
        lambda: L.MulConstant(2.0, input_shape=(4,)), (4,))
    seq("Negative", lambda: L.Negative(input_shape=(4,)), (4,))
    seq("Exp", lambda: L.Exp(input_shape=(4,)), (4,))
    seq("Log", lambda: L.Log(input_shape=(4,)), (4,))
    seq("Sqrt", lambda: L.Sqrt(input_shape=(4,)), (4,))
    seq("Square", lambda: L.Square(input_shape=(4,)), (4,))
    seq("Power", lambda: L.Power(2.0, input_shape=(4,)), (4,))
    seq("CAdd", lambda: L.CAdd((4,), input_shape=(4,)), (4,))
    seq("CMul", lambda: L.CMul((4,), input_shape=(4,)), (4,))
    seq("Scale", lambda: L.Scale((4,), input_shape=(4,)), (4,))
    seq("Mul", lambda: L.Mul(input_shape=(4,)), (4,))
    seq("Select", lambda: L.Select(1, 2, input_shape=(4, 3)), (4, 3))
    seq("Squeeze", lambda: L.Squeeze(1, input_shape=(1, 4)), (1, 4))
    seq("ExpandDim", lambda: L.ExpandDim(1, input_shape=(4,)), (4,))
    seq("Expand",
        lambda: L.Expand((3, 4), input_shape=(1, 4)), (1, 4))
    seq("Narrow",
        lambda: L.Narrow(1, 1, 2, input_shape=(4, 3)), (4, 3))
    seq("Max", lambda: L.Max(1, input_shape=(4, 3)), (4, 3))
    seq("GetShape", lambda: L.GetShape(input_shape=(4, 3)), (4, 3))
    seq("SpaceToDepth",
        lambda: L.SpaceToDepth(2, input_shape=(4, 4, 3)), (4, 4, 3))
    seq("ResizeBilinear",
        lambda: L.ResizeBilinear(6, 6, input_shape=(4, 4, 3)), (4, 4, 3))

    # ---- conv / pooling / padding / upsampling -------------------------
    seq("Convolution1D",
        lambda: L.Convolution1D(4, 3, input_shape=(8, 3)), (8, 3))
    seq("Convolution2D",
        lambda: L.Convolution2D(4, 3, 3, input_shape=(8, 8, 3)),
        (8, 8, 3))
    seq("Convolution3D",
        lambda: L.Convolution3D(4, 3, 3, 3, input_shape=(6, 6, 6, 2)),
        (6, 6, 6, 2))
    seq("AtrousConvolution1D",
        lambda: L.AtrousConvolution1D(4, 3, atrous_rate=2,
                                      input_shape=(10, 3)), (10, 3))
    seq("AtrousConvolution2D",
        lambda: L.AtrousConvolution2D(4, 3, 3, atrous_rate=(2, 2),
                                      input_shape=(10, 10, 3)),
        (10, 10, 3))
    seq("SeparableConvolution2D",
        lambda: L.SeparableConvolution2D(4, 3, input_shape=(8, 8, 3)),
        (8, 8, 3))
    seq("DepthwiseConvolution2D",
        lambda: L.DepthwiseConvolution2D(3, input_shape=(8, 8, 3)),
        (8, 8, 3))
    seq("Deconvolution2D",
        lambda: L.Deconvolution2D(4, 3, 3, input_shape=(6, 6, 3)),
        (6, 6, 3))
    seq("ShareConvolution2D",
        lambda: L.ShareConvolution2D(4, 3, 3, input_shape=(8, 8, 3)),
        (8, 8, 3))
    seq("LocallyConnected1D",
        lambda: L.LocallyConnected1D(4, 3, input_shape=(8, 3)), (8, 3))
    seq("LocallyConnected2D",
        lambda: L.LocallyConnected2D(4, 3, 3, input_shape=(6, 6, 2)),
        (6, 6, 2))
    for rank, shape in ((1, (8, 3)), (2, (8, 8, 3)), (3, (4, 4, 4, 2))):
        seq(f"MaxPooling{rank}D",
            lambda rank=rank, shape=shape: getattr(
                L, f"MaxPooling{rank}D")(input_shape=shape), shape)
        seq(f"AveragePooling{rank}D",
            lambda rank=rank, shape=shape: getattr(
                L, f"AveragePooling{rank}D")(input_shape=shape), shape)
        seq(f"GlobalMaxPooling{rank}D",
            lambda rank=rank, shape=shape: getattr(
                L, f"GlobalMaxPooling{rank}D")(input_shape=shape), shape)
        seq(f"GlobalAveragePooling{rank}D",
            lambda rank=rank, shape=shape: getattr(
                L, f"GlobalAveragePooling{rank}D")(input_shape=shape),
            shape)
        # Cropping1D takes (left, right); 2D/3D take per-dim pairs
        crop_arg = (1, 1) if rank == 1 else [1] * rank
        seq(f"Cropping{rank}D",
            lambda rank=rank, shape=shape, crop_arg=crop_arg: getattr(
                L, f"Cropping{rank}D")(crop_arg, input_shape=shape),
            shape)
        seq(f"ZeroPadding{rank}D",
            lambda rank=rank, shape=shape: getattr(
                L, f"ZeroPadding{rank}D")(1, input_shape=shape), shape)
        seq(f"UpSampling{rank}D",
            lambda rank=rank, shape=shape: getattr(
                L, f"UpSampling{rank}D")(input_shape=shape), shape)
    seq("LRN2D", lambda: L.LRN2D(input_shape=(6, 6, 4)), (6, 6, 4))
    seq("WithinChannelLRN2D",
        lambda: L.WithinChannelLRN2D(input_shape=(6, 6, 4)), (6, 6, 4))

    # ---- normalization -------------------------------------------------
    seq("BatchNormalization",
        lambda: L.BatchNormalization(input_shape=(6, 6, 4)), (6, 6, 4))
    seq("LayerNormalization",
        lambda: L.LayerNormalization(input_shape=(6,)), (6,))

    # ---- recurrent -----------------------------------------------------
    seq("SimpleRNN",
        lambda: L.SimpleRNN(5, input_shape=(4, 3)), (4, 3))
    seq("LSTM", lambda: L.LSTM(5, input_shape=(4, 3)), (4, 3))
    seq("GRU", lambda: L.GRU(5, input_shape=(4, 3)), (4, 3))
    seq("ConvLSTM2D",
        lambda: L.ConvLSTM2D(4, 3, input_shape=(3, 6, 6, 2)),
        (3, 6, 6, 2))
    seq("ConvLSTM3D",
        lambda: L.ConvLSTM3D(2, 3, input_shape=(2, 4, 4, 4, 2)),
        (2, 4, 4, 4, 2))
    seq("Bidirectional",
        lambda: L.Bidirectional(L.LSTM(4, return_sequences=True),
                                input_shape=(4, 3)), (4, 3))
    seq("TimeDistributed",
        lambda: L.TimeDistributed(L.Dense(5), input_shape=(4, 3)),
        (4, 3))

    # ---- embeddings / attention ----------------------------------------
    seq("Embedding",
        lambda: L.Embedding(11, 6, input_shape=(5,)), (5,), ints=11)
    seq("SparseEmbedding",
        lambda: L.SparseEmbedding(11, 6, input_shape=(5,)), (5,),
        ints=11)
    seq("WordEmbedding",
        lambda: L.WordEmbedding(_glove_file(), input_length=5), (5,),
        ints=3)
    seq("TransformerLayer",
        lambda: L.TransformerLayer(vocab=17, seq_len=6, n_block=1,
                                   n_head=2, hidden_size=8,
                                   input_shape=(6,)), (6,), ints=17)

    # ---- multi-input / multi-output graphs -----------------------------
    def merge_spec():
        a, b = Input(shape=(4,)), Input(shape=(4,))
        out = L.Merge(mode="sum")([a, b])
        net = Model([a, b], out)
        return net, [_x((2, 4), 1), _x((2, 4), 2)]
    S["Merge"] = merge_spec

    def select_table_spec():
        a, b = Input(shape=(4,)), Input(shape=(3,))
        out = L.SelectTable(1)([a, b])
        net = Model([a, b], out)
        return net, [_x((2, 4), 1), _x((2, 3), 2)]
    S["SelectTable"] = select_table_spec

    def split_tensor_spec():
        a = Input(shape=(4, 6))
        parts = L.SplitTensor(2, 2)(a)
        net = Model(a, parts)
        return net, _x((2, 4, 6))
    S["SplitTensor"] = split_tensor_spec

    def sampler_spec():
        mean, logv = Input(shape=(4,)), Input(shape=(4,))
        out = L.GaussianSampler()([mean, logv])
        net = Model([mean, logv], out)
        return net, [_x((2, 4), 1), _x((2, 4), 2)]
    S["GaussianSampler"] = sampler_spec

    def bert_spec():
        bert = L.BERT(vocab=17, hidden_size=8, n_block=1, n_head=2,
                      seq_len=6, intermediate_size=16)
        ids = Input(shape=(6,))
        types = Input(shape=(6,))
        pos = Input(shape=(6,))
        mask = Input(shape=(6,))   # (B, L) 1/0 — the reference contract
        seq_out, pooled = bert([ids, types, pos, mask])
        net = Model([ids, types, pos, mask], [seq_out, pooled])
        rng = np.random.default_rng(0)
        return net, [
            rng.integers(0, 17, (2, 6)).astype(np.int32),
            np.zeros((2, 6), np.int32),
            np.tile(np.arange(6, dtype=np.int32), (2, 1)),
            np.ones((2, 6), np.float32),
        ]
    S["BERT"] = bert_spec

    return S


# Symbolic/abstract surface with no concrete serialization story of its
# own (Input returns a Variable; InputLayer/Layer are plumbing).
SKIP = {"Input", "InputLayer", "Layer"}


def _public_classes():
    import inspect

    import analytics_zoo_tpu.pipeline.api.keras.layers as L

    out = {}
    for n in dir(L):
        if n.startswith("_"):
            continue
        obj = getattr(L, n)
        if inspect.ismodule(obj):
            continue
        out[n] = obj
    return out


def test_every_public_layer_has_a_serialization_spec():
    """The SerializerSpec enforcement: a public layer class with neither a
    spec nor an alias sharing one fails CI."""
    public = _public_classes()
    specs = _specs()
    covered_objs = {id(public[n]) for n in specs if n in public}
    missing = [
        n for n, obj in public.items()
        if n not in SKIP and n not in specs and id(obj) not in covered_objs
    ]
    assert not missing, (
        f"{len(missing)} public layers lack a save/load round-trip spec "
        f"in test_layer_serialization.py: {sorted(missing)}")
    stale = [n for n in specs if n not in public]
    assert not stale, f"specs for nonexistent layers: {stale}"


@pytest.mark.parametrize("name", sorted(_specs()))
def test_layer_roundtrip(name, tmp_path):
    net, x = _specs()[name]()
    before = net.predict(x, batch_size=2)
    path = str(tmp_path / f"{name}.zoo")
    net.save(path)
    loaded = KerasNet.load(path)
    after = loaded.predict(x, batch_size=2)
    if isinstance(before, list):
        assert isinstance(after, list) and len(after) == len(before)
        for b, a in zip(before, after):
            np.testing.assert_array_equal(np.asarray(b), np.asarray(a))
    else:
        np.testing.assert_array_equal(np.asarray(before),
                                      np.asarray(after))
