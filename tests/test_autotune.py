"""Closed-loop autotuning (feature/autotune.py): resizable pipeline
byte-identity, controller convergence on both synthetics, K hill-climb
trajectory bit-identity, RAM budget, disabled-mode zero overhead, the
ZooConfig knob validation satellite, and the /varz + metrics_dump
decision-log surfaces."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from analytics_zoo_tpu.feature.autotune import AutotuneController
from analytics_zoo_tpu.feature.common import FnPreprocessing
from analytics_zoo_tpu.feature.dataset import FeatureSet, ShardedFeatureSet
from analytics_zoo_tpu.feature.prefetch import (
    PrefetchFeatureSet,
    PrefetchPipeline,
    worth_prefetching,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _sleepy_sharded(n_shards=4, records=32, load_sleep=0.01,
                    transform_sleep=0.001):
    def loader(path):
        i = int(path.rsplit("-", 1)[-1])
        time.sleep(load_sleep)
        rng = np.random.default_rng(1234 + i)
        return {"x": rng.standard_normal((records, 16)).astype("float32"),
                "y": rng.integers(0, 10, size=(records,)).astype("int32")}

    base = ShardedFeatureSet(
        [f"synth://shard-{i}" for i in range(n_shards)],
        n_slices=n_shards, loader=loader, sizer=lambda p: records)

    def slow(r):
        time.sleep(transform_sleep)
        return r

    return base.transform(FnPreprocessing(slow))


def _streams_equal(a_batches, b_batches):
    if len(a_batches) != len(b_batches):
        return False
    for a, b in zip(a_batches, b_batches):
        if set(a) != set(b):
            return False
        for k in a:
            if not np.array_equal(a[k], b[k]):
                return False
    return True


# ---------------------------------------------------------------------------
# resizable pipeline primitives
# ---------------------------------------------------------------------------

def test_pipeline_resize_preserves_byte_identical_stream():
    """The acceptance pin: aggressive concurrent grow/shrink of BOTH
    knobs while the stream is consumed must not reorder, drop, or
    duplicate a single batch."""
    x = np.arange(4000, dtype=np.float32).reshape(1000, 4)
    fs = FeatureSet.of(x).transform(FnPreprocessing(lambda r: r * 2.0))
    serial = list(fs.batches(8, shuffle=True, seed=5, epoch=2))

    # controller-style attach exposes the live pipeline so a second
    # thread can churn its knobs mid-iteration
    live = {}

    class Grabber:
        data_metrics = None

        def pipeline_config(self, w, d):
            return w, d

        def attach_pipeline(self, pipe, sharded=None):
            live["pipe"] = pipe

        def detach_pipeline(self, pipe):
            pass

    pre = PrefetchFeatureSet(fs, depth=1, workers=1,
                             controller=Grabber())
    gen = pre.batches(8, shuffle=True, seed=5, epoch=2)
    got = [next(gen)]
    stop = threading.Event()

    def churn():
        sizes = [(1, 1), (4, 8), (2, 3), (8, 16), (1, 2), (3, 8)]
        i = 0
        while not stop.is_set():
            w, d = sizes[i % len(sizes)]
            live["pipe"].resize(workers=w, depth=d)
            i += 1
            time.sleep(0.001)

    churner = threading.Thread(target=churn, daemon=True)
    churner.start()
    try:
        got.extend(gen)
    finally:
        stop.set()
        churner.join(timeout=5)
    assert _streams_equal(serial, got)


def test_worker_pool_grows_and_shrinks():
    from analytics_zoo_tpu.feature.prefetch import _WorkerPool

    pool = _WorkerPool(1, thread_name_prefix="zoo-test-pool")
    try:
        def live():
            return sum(t.name.startswith("zoo-test-pool") and t.is_alive()
                       for t in threading.enumerate())

        assert live() == 1
        pool.resize(3)
        deadline = time.monotonic() + 5
        while live() < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert live() == 3
        # shrink is lazy: workers exit between tasks
        pool.resize(1)
        deadline = time.monotonic() + 5
        while live() > 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert live() == 1
        # futures still work after resizing
        assert pool.submit(lambda a: a + 1, 41).result(timeout=5) == 42
    finally:
        pool.shutdown()
    with pytest.raises(RuntimeError):
        pool.submit(lambda: None)


def test_resizable_queue_blocks_and_unblocks_on_resize():
    import queue as _q

    from analytics_zoo_tpu.feature.prefetch import _ResizableQueue

    q = _ResizableQueue(1)
    q.put("a")
    with pytest.raises(_q.Full):
        q.put("b", timeout=0.05)
    q.resize(2)
    q.put("b", timeout=0.5)  # grow admitted it without a drain
    assert q.get() == "a" and q.get() == "b"  # FIFO preserved
    q.resize(1)
    with pytest.raises(_q.Empty):
        q.get_nowait()


def test_read_ahead_count_knob(shard_paths=None, tmp_path=None):
    fs = _sleepy_sharded(n_shards=5, load_sleep=0.0, transform_sleep=0.0)
    inner = fs.base
    inner.set_read_ahead_count(3)
    assert inner._ra_ahead == 3
    with pytest.raises(ValueError):
        inner.set_read_ahead_count(0)
    # read-ahead=3 still loads each shard exactly once
    pre = PrefetchFeatureSet(fs, depth=2, workers=2)
    serial = list(fs.batches(8, shuffle=True, seed=3, epoch=0))
    got = list(pre.batches(8, shuffle=True, seed=3, epoch=0))
    assert _streams_equal(serial, got)
    assert inner.last_shard_nbytes > 0


# ---------------------------------------------------------------------------
# controller: data plane
# ---------------------------------------------------------------------------

def test_controller_grows_pipeline_and_stays_byte_identical():
    fs = _sleepy_sharded()
    serial = [list(fs.batches(8, shuffle=True, seed=7, epoch=e))
              for e in range(4)]
    ctrl = AutotuneController(interval=0.03, min_window=4)
    pre = PrefetchFeatureSet(fs, depth=1, workers=1, controller=ctrl)
    try:
        for e in range(4):
            got = list(pre.batches(8, shuffle=True, seed=7, epoch=e))
            assert _streams_equal(serial[e], got)
    finally:
        ctrl.stop()
    log = ctrl.decision_log()
    assert any(d["knob"] == "workers" and d["new"] > d["old"]
               for d in log), log
    cur = ctrl.current()
    assert cur["workers"] > 1
    # every decision also landed in the flight ring
    from analytics_zoo_tpu.metrics import get_flight_recorder
    flight_autotune = get_flight_recorder().events(kind="autotune")
    assert len(flight_autotune) >= len(log) > 0


def test_ram_budget_caps_depth_growth():
    """A budget of ~4 batches: the controller must keep
    batch_bytes x (depth + workers) under it instead of growing depth
    toward 2x workers."""
    fs = _sleepy_sharded(records=64)
    batch = next(iter(fs.batches(8, shuffle=True, seed=1, epoch=0)))
    batch_bytes = sum(v.nbytes for v in batch.values())
    budget = batch_bytes * 6
    ctrl = AutotuneController(interval=0.02, min_window=3,
                              ram_budget=budget, max_read_ahead=1)
    pre = PrefetchFeatureSet(fs, depth=1, workers=1, controller=ctrl)
    try:
        for e in range(4):
            list(pre.batches(8, shuffle=True, seed=1, epoch=e))
    finally:
        ctrl.stop()
    cur = ctrl.current()
    est = batch_bytes * (cur["depth"] + cur["workers"])
    assert est <= budget * 2, (cur, batch_bytes, budget)
    assert cur["depth"] <= 8, cur


# ---------------------------------------------------------------------------
# controller: K hill-climb (trajectory bit-identity is the contract)
# ---------------------------------------------------------------------------

def _fit_tiny(autotune=None, epochs=2, n=1024, **cfg_kwargs):
    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.common.engine import ZooConfig
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    zoo.init_zoo_context(ZooConfig(seed=3, mesh_shape={"data": 8},
                                   **cfg_kwargs))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = rng.integers(0, 4, size=(n,)).astype(np.int32)
    m = Sequential()
    m.add(Dense(16, activation="relu", input_shape=(8,)))
    m.add(Dense(4, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    m.fit(x, y, batch_size=32, nb_epoch=epochs, autotune=autotune)
    return [h["loss"] for h in m._estimator.history]


def test_k_hill_climb_policy_on_synthetic_costs():
    """Deterministic policy pin (no timing noise): per-dispatch wall
    modeled as nk x step + fixed overhead must climb the whole ladder;
    a cost curve whose optimum is K=2 must settle exactly there."""
    ctrl = AutotuneController(k_samples=2, k_warm_skip=0)
    for _ in range(100):
        if ctrl.k_settled:
            break
        k = ctrl.current_k()
        ctrl.observe_dispatch(k, k * 0.0005 + 0.005)  # overhead-bound
    assert ctrl.k_settled and ctrl.current_k() == 16
    assert ctrl.current()["k_settle_dispatch"] is not None

    ctrl2 = AutotuneController(k_samples=2, k_warm_skip=0)
    costs = {1: 0.0011, 2: 0.00100, 4: 0.0015, 8: 0.002, 16: 0.003}
    for _ in range(100):
        if ctrl2.k_settled:
            break
        k = ctrl2.current_k()
        ctrl2.observe_dispatch(k, k * costs[k])
    assert ctrl2.k_settled and ctrl2.current_k() == 2
    # stale chunks from before a switch never pollute a window
    ctrl2.observe_dispatch(4, 99.0)
    assert ctrl2.current_k() == 2


def test_k_hill_climb_explores_and_trajectory_is_bitwise_identical():
    """The online contract: exploring K during a REAL fit leaves the
    loss trajectory bit-for-bit unchanged (which K it settles on is
    timing-dependent — the convergence quality itself is pinned by
    bench --autotune / BENCH_AUTOTUNE_r08.json)."""
    l1 = _fit_tiny(autotune=False, epochs=2, n=2048)
    ctrl = AutotuneController(k_samples=3, k_warm_skip=2)
    try:
        la = _fit_tiny(autotune=ctrl, epochs=2, n=2048)
    finally:
        ctrl.stop()
    # the climb probed beyond K=1, and the trajectory did not move
    assert any(d["knob"] == "k" for d in ctrl.decision_log())
    assert la == l1  # bitwise float equality, no tolerance


def test_autotune_env_knob_via_config(monkeypatch):
    monkeypatch.setenv("ZOO_AUTOTUNE", "1")
    monkeypatch.setenv("ZOO_AUTOTUNE_INTERVAL", "0.05")
    l1 = _fit_tiny(autotune=False, epochs=1)
    la = _fit_tiny(epochs=1)  # autotune=None defers to the env tier
    assert la == l1
    # the estimator's own controller was stopped when fit returned
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and any(
            t.name == "zoo-autotune" and t.is_alive()
            for t in threading.enumerate()):
        time.sleep(0.05)
    assert not any(t.name == "zoo-autotune" and t.is_alive()
                   for t in threading.enumerate())


# ---------------------------------------------------------------------------
# disabled mode: zero threads, zero import (the ZOO_SAN pattern)
# ---------------------------------------------------------------------------

def test_autotune_unset_means_no_thread_and_no_import():
    """ZOO_AUTOTUNE unset ⇒ a plain fit never imports feature.autotune
    and never starts a controller thread (subprocess so other tests'
    imports can't contaminate sys.modules)."""
    code = """
import os, sys, threading
os.environ.pop("ZOO_AUTOTUNE", None)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
zoo.init_zoo_context(seed=0)
rng = np.random.default_rng(0)
x = rng.normal(size=(64, 4)).astype(np.float32)
y = (x.sum(1) > 0).astype(np.int32)
m = Sequential()
m.add(Dense(2, activation="softmax", input_shape=(4,)))
m.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
m.fit(x, y, batch_size=8, nb_epoch=1)
assert "analytics_zoo_tpu.feature.autotune" not in sys.modules, \\
    "autotune imported on the disabled path"
assert not [t.name for t in threading.enumerate()
            if t.name == "zoo-autotune"]
print("CLEAN")
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=300,
                       cwd=os.path.join(os.path.dirname(__file__), ".."))
    assert r.returncode == 0, r.stderr[-2000:]
    assert "CLEAN" in r.stdout


def test_worth_prefetching_heuristic():
    x = np.zeros((32, 4), np.float32)
    plain = FeatureSet.of(x)
    assert not worth_prefetching(plain)  # resident, nothing to hide
    assert worth_prefetching(plain.transform(
        FnPreprocessing(lambda r: r)))  # pooled map stage
    assert worth_prefetching(_sleepy_sharded())  # shard loads
    assert worth_prefetching(
        FeatureSet.array(x, memory_type="PMEM"))  # page-cache reads


# ---------------------------------------------------------------------------
# ZooConfig satellite: eager validation naming the env var
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("var,val,msg", [
    ("ZOO_PREFETCH_WORKERS", "two", "ZOO_PREFETCH_WORKERS"),
    ("ZOO_PREFETCH_WORKERS", "-1", "ZOO_PREFETCH_WORKERS"),
    ("ZOO_PREFETCH_DEPTH", "0", "ZOO_PREFETCH_DEPTH"),
    ("ZOO_PREFETCH_DEPTH", "4.5", "ZOO_PREFETCH_DEPTH"),
    ("ZOO_STEPS_PER_DISPATCH", "0", "ZOO_STEPS_PER_DISPATCH"),
    ("ZOO_STEPS_PER_DISPATCH", "x", "ZOO_STEPS_PER_DISPATCH"),
    ("ZOO_AUTOTUNE_RAM_BUDGET", "lots", "ZOO_AUTOTUNE_RAM_BUDGET"),
    ("ZOO_AUTOTUNE_MAX_WORKERS", "0", "ZOO_AUTOTUNE_MAX_WORKERS"),
])
def test_env_knobs_validated_eagerly_with_clear_errors(
        monkeypatch, var, val, msg):
    from analytics_zoo_tpu.common.engine import ZooConfig

    monkeypatch.setenv(var, val)
    with pytest.raises(ValueError, match=msg):
        ZooConfig()


def test_explicit_knobs_validated_naming_the_field():
    from analytics_zoo_tpu.common.engine import ZooConfig

    with pytest.raises(ValueError, match="prefetch_workers"):
        ZooConfig(prefetch_workers=-2)
    with pytest.raises(ValueError, match="steps_per_dispatch"):
        ZooConfig(steps_per_dispatch=0)


def test_ram_budget_suffix_parsing(monkeypatch):
    from analytics_zoo_tpu.common.engine import ZooConfig

    monkeypatch.setenv("ZOO_AUTOTUNE_RAM_BUDGET", "512M")
    assert ZooConfig().autotune_ram_budget == 512 << 20
    monkeypatch.setenv("ZOO_AUTOTUNE_RAM_BUDGET", "2G")
    assert ZooConfig().autotune_ram_budget == 2 << 30
    monkeypatch.setenv("ZOO_AUTOTUNE_RAM_BUDGET", "65536")
    assert ZooConfig().autotune_ram_budget == 65536


# ---------------------------------------------------------------------------
# map-fusion satellite: one _preprocess_batch pass per batch
# ---------------------------------------------------------------------------

def test_transform_chain_fuses_to_one_pass_per_batch(monkeypatch):
    import analytics_zoo_tpu.feature.prefetch as prefetch_mod

    calls = []
    real = prefetch_mod._preprocess_batch

    def counting(pre, batch):
        calls.append(type(pre).__name__)
        return real(pre, batch)

    monkeypatch.setattr(prefetch_mod, "_preprocess_batch", counting)
    x = np.arange(120, dtype=np.float32).reshape(40, 3)
    fs = FeatureSet.of(x).transform(
        FnPreprocessing(lambda r: r + 1.0)).transform(
        FnPreprocessing(lambda r: r * 3.0)).transform(
        FnPreprocessing(lambda r: r - 0.5))
    serial = list(fs.batches(8, shuffle=True, seed=2, epoch=1))
    got = list(fs.prefetch(depth=2, workers=2).batches(
        8, shuffle=True, seed=2, epoch=1))
    assert _streams_equal(serial, got)
    # 5 batches, 3 transforms: ONE fused pass per batch, not 15
    assert len(calls) == 5, calls
    assert all(c == "FusedPreprocessing" for c in calls)


def test_fused_stages_see_materialized_rows_like_serial():
    """Review pin: stage N receives an ndarray row (the serial np.stack
    boundary shape), not stage N-1's raw Python return — a stage-1
    transform returning a LIST must not break (or change the bytes of)
    a stage-2 transform that uses ndarray methods."""
    x = np.arange(60, dtype=np.float32).reshape(20, 3)
    fs = FeatureSet.of(x).transform(
        FnPreprocessing(lambda r: list(r * 2.0))).transform(  # raw list!
        FnPreprocessing(lambda r: r.mean() * np.ones(3, r.dtype)))
    serial = list(fs.batches(4, shuffle=False))
    got = list(fs.prefetch(depth=2, workers=2).batches(4, shuffle=False))
    assert _streams_equal(serial, got)


def test_autotune_false_does_not_resurrect_fit_controller():
    """Review pin: train(autotune=True) on a caller-owned
    PrefetchFeatureSet must not leave its fit-local controller attached —
    a later train(autotune=False) on the SAME set spawns no thread."""
    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.common.engine import ZooConfig
    from analytics_zoo_tpu.feature.dataset import FeatureSet
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    zoo.init_zoo_context(ZooConfig(seed=3, mesh_shape={"data": 8}))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    y = rng.integers(0, 4, size=(256,)).astype(np.int32)
    pre_fs = FeatureSet.of(x, y).prefetch(depth=2, workers=1)
    m = Sequential()
    m.add(Dense(4, activation="softmax", input_shape=(8,)))
    m.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
    est = m._make_estimator()
    m._estimator = est
    est.train(pre_fs, batch_size=32, nb_epoch=1, autotune=True)
    assert pre_fs._controller is None  # fit-scoped attachment undone
    est.train(pre_fs, batch_size=32, nb_epoch=1, autotune=False)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and any(
            t.name == "zoo-autotune" and t.is_alive()
            for t in threading.enumerate()):
        time.sleep(0.05)
    assert not any(t.name == "zoo-autotune" and t.is_alive()
                   for t in threading.enumerate())


def test_zoo_autotune_env_rejects_non_boolean(monkeypatch):
    from analytics_zoo_tpu.common.engine import ZooConfig

    monkeypatch.setenv("ZOO_AUTOTUNE", "false")
    assert ZooConfig().autotune is False  # 'false' DISABLES, never enables
    monkeypatch.setenv("ZOO_AUTOTUNE", "maybe")
    with pytest.raises(ValueError, match="ZOO_AUTOTUNE"):
        ZooConfig()


# ---------------------------------------------------------------------------
# observability surfaces: /varz + metrics_dump decision table
# ---------------------------------------------------------------------------

def test_varz_and_metrics_dump_render_decisions():
    import urllib.request

    from analytics_zoo_tpu.metrics import MetricsServer

    fs = _sleepy_sharded()
    ctrl = AutotuneController(interval=0.02, min_window=3)
    pre = PrefetchFeatureSet(fs, depth=1, workers=1, controller=ctrl)
    try:
        for e in range(3):
            list(pre.batches(8, shuffle=True, seed=7, epoch=e))
    finally:
        ctrl.stop()
    assert ctrl.decision_log(), "controller made no decisions"
    srv = MetricsServer(port=0).start()
    try:
        with urllib.request.urlopen(srv.url + "/varz", timeout=10) as r:
            doc = json.load(r)
    finally:
        srv.stop()
    auto = doc.get("autotune")
    assert auto and auto["decisions"], auto
    d0 = auto["decisions"][0]
    assert {"ts", "knob", "old", "new", "reason"} <= set(d0)

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import metrics_dump

    lines = []
    metrics_dump.render_autotune(doc, out=lines)
    text = "\n".join(lines)
    assert "autotune:" in text
    assert d0["knob"] in text and d0["reason"] in text


def test_zoo_autotune_metrics_family_exported():
    from analytics_zoo_tpu.metrics import MetricsRegistry, snapshot

    reg = MetricsRegistry(enabled=True)
    ctrl = AutotuneController(registry=reg, interval=0.02, min_window=3)
    fs = _sleepy_sharded()
    pre = PrefetchFeatureSet(fs, depth=1, workers=1, controller=ctrl)
    try:
        for e in range(3):
            list(pre.batches(8, shuffle=True, seed=7, epoch=e))
    finally:
        ctrl.stop()
    names = {s["name"] for s in snapshot(reg)["samples"]}
    assert {"zoo_autotune_workers", "zoo_autotune_depth",
            "zoo_autotune_read_ahead", "zoo_autotune_k",
            "zoo_autotune_ram_budget_bytes",
            "zoo_autotune_decisions_total"} <= names, sorted(names)


# ---------------------------------------------------------------------------
# bench quick-tier guard (the acceptance pins)
# ---------------------------------------------------------------------------

def test_autotune_bench_quick_tier(tmp_path):
    """CI guard: from worst-case (workers=1, depth=1) the controller
    must reach at least the untuned-default throughput on the
    sleep-bound synthetic with the stream byte-identical under
    resizing.  (The full --autotune bench additionally pins >= 0.9x the
    best hand-tuned config on BOTH synthetics —
    BENCH_AUTOTUNE_r08.json.)"""
    import bench

    doc = bench.autotune_data_plane_bench(quick=True)
    assert doc["deterministic_under_resizing"], doc
    assert doc["autotuned_final_batches_per_sec"] >= \
        doc["untuned_default_batches_per_sec"], doc
    assert doc["decisions"], doc
