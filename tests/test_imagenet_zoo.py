"""Image-classification zoo families (reference
ImageClassificationConfig.scala:31-50 model set): every builder
constructs, runs forward at toy scale with the right output shape, and
one representative (mobilenet-v2, the hardest block structure) learns.
"""

import numpy as np
import pytest

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.models import imagenet_zoo as zoo_nets


@pytest.fixture(autouse=True)
def _ctx():
    init_zoo_context("imagenet-zoo-test", seed=0)


def _x(n=4, size=32):
    return np.random.default_rng(0).normal(
        size=(n, size, size, 3)).astype(np.float32)


def _check(net, size=32, classes=5, n=4):
    probs = net.predict(_x(n, size), batch_size=n)
    assert probs.shape == (n, classes)
    np.testing.assert_allclose(np.asarray(probs).sum(1), 1.0, atol=1e-4)


def test_alexnet_forward():
    # 67 is the minimum input for the valid-padding plan (pool5 hits
    # spatial 1); smaller inputs now fail fast at build time
    _check(zoo_nets.alexnet(classes=5, input_shape=(67, 67, 3),
                            width=0.05), size=67)


def test_alexnet_too_small_input_fails_at_build():
    with pytest.raises(ValueError, match="spatial dim collapses"):
        zoo_nets.alexnet(classes=5, input_shape=(32, 32, 3), width=0.05)


def test_vgg16_forward():
    _check(zoo_nets.vgg(16, classes=5, input_shape=(32, 32, 3),
                        width=0.05))


def test_vgg19_forward():
    _check(zoo_nets.vgg(19, classes=5, input_shape=(32, 32, 3),
                        width=0.05))


def test_vgg_bad_depth():
    with pytest.raises(ValueError, match="depth"):
        zoo_nets.vgg(13)


def test_squeezenet_forward():
    _check(zoo_nets.squeezenet(classes=5, input_shape=(64, 64, 3),
                               width=0.25), size=64)


def test_densenet_forward_tiny_plan():
    net = zoo_nets.densenet(classes=5, input_shape=(32, 32, 3),
                            block_plan=(2, 2), growth_rate=8,
                            init_features=16)
    _check(net)


def test_densenet_161_plan():
    # full 161 plan constructs with the paper's layer counts (48 growth)
    net = zoo_nets.densenet(161, classes=7, input_shape=(64, 64, 3))
    names = [ly.name for ly in net.layers]
    assert "block3/layer36/conv3x3" in names   # 36-layer third block
    assert sum(1 for n in names if n.endswith("/conv3x3")) == 6 + 12 + 36 + 24


def test_inception_v3_forward():
    from analytics_zoo_tpu.models.inception import inception_v3

    # 79px: the smallest input whose valid-padding stem + two reductions
    # stay positive; width 0.05 keeps the 11-module graph tiny
    net = inception_v3(classes=5, input_shape=(79, 79, 3), width=0.05)
    _check(net, size=79)
    names = [ly.name for ly in net.layers]
    # the factorized-asymmetric-conv signature blocks are all present
    assert "mixed_6b/7x7_1x7/conv" in names
    assert "mixed_7c/dbl_3x1/conv" in names


def test_mobilenet_forward():
    _check(zoo_nets.mobilenet(classes=5, input_shape=(32, 32, 3),
                              alpha=0.25))


def test_mobilenet_v2_forward_and_residuals():
    net = zoo_nets.mobilenet_v2(classes=5, input_shape=(32, 32, 3),
                                alpha=0.25)
    _check(net)
    # inverted residuals with stride 1 and equal channels carry an add
    names = [ly.name for ly in net.layers]
    assert any(n.endswith("/add") for n in names)


def test_mobilenet_v2_learns():
    rng = np.random.default_rng(1)
    y = rng.integers(0, 2, size=192).astype(np.int32)
    x = rng.normal(0, 0.2, size=(192, 32, 32, 3)).astype(np.float32)
    x[y == 1, 8:24, 8:24, :] += 1.0     # bright center patch = class 1
    # bn_momentum 0.9: the default 0.99 window cannot converge the 30+
    # stacked BNs' running stats inside this short CI run
    net = zoo_nets.mobilenet_v2(classes=2, input_shape=(32, 32, 3),
                                alpha=0.125, bn_momentum=0.9)
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

    net.compile(optimizer=Adam(lr=0.005),
                loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    net.fit(x, y, batch_size=32, nb_epoch=15)
    acc = net.evaluate(x, y, batch_size=64)["accuracy"]
    assert acc > 0.8, acc


def test_predict_image_set_with_zoo_family():
    """Full reference flow on a new family: preprocess chain (resize/crop/
    normalize per the model's config) -> batched forward -> LabelOutput."""
    from analytics_zoo_tpu.feature.image.imageset import ImageSet
    from analytics_zoo_tpu.models.image.imageclassification import (
        ImageClassificationConfig,
        ImageClassifier,
    )

    rng = np.random.default_rng(0)
    imgs = [rng.integers(0, 256, size=(40, 48, 3), dtype=np.uint8)
            for _ in range(5)]
    cfg = ImageClassificationConfig(
        resize=36, crop=32, label_map={i: f"class_{i}" for i in range(4)})
    clf = ImageClassifier(model_name="mobilenet-v2", classes=4, config=cfg)
    clf.model.build_params()
    out = clf.predict_image_set(ImageSet.from_arrays(imgs), top_k=3)
    assert len(out) == 5 and len(out[0]) == 3
    assert out[0][0][0].startswith("class_")


def test_classifier_factory_covers_reference_model_set():
    """Every model name in ImageClassificationConfig.scala:31-50 (minus
    the dataset-variant suffixes) builds through ImageClassifier."""
    from analytics_zoo_tpu.models.image.imageclassification import (
        ImageClassifier,
    )
    from analytics_zoo_tpu.models.image.imageclassification.classifier import (
        ImageClassificationConfig,
    )

    reference_models = [
        "alexnet", "alexnet-quantize", "inception-v1", "inception-v3",
        "resnet-50",
        "resnet-50-quantize", "resnet-50-int8", "vgg-16", "vgg-19",
        "densenet-161", "squeezenet", "mobilenet", "mobilenet-v2",
        "mobilenet-v2-quantize",
    ]
    for name in reference_models:
        # alexnet/inception-v3 valid-padding plans need bigger crops
        base = name.removesuffix("-quantize").removesuffix("-int8")
        crop = {"alexnet": 67, "inception-v3": 79}.get(base, 32)
        cfg = ImageClassificationConfig(crop=crop)
        clf = ImageClassifier(model_name=name, classes=4, config=cfg)
        net = clf.build_model()
        assert net is not None, name
