"""CI re-check of the accuracy-parity configs (VERDICT r03 missing #1):
small versions of the ACCURACY_r04.json runs — LeNet on the real sklearn
digits to a convergence bar, and bit-exact checkpoint-resume curve
reproduction (reference resume semantics, TrainImageNet.scala:104-118;
exact iterator state resume is feature/dataset.py's contract)."""

import numpy as np

from tools.accuracy_bench import digits_data, run_lenet


def test_lenet_digits_converges(zoo_ctx, tmp_path):
    hist, acc, _ = run_lenet(epochs=12)
    assert acc >= 0.95, acc
    assert hist[-1] < 0.3 * hist[0]


def test_resume_reproduces_curve_exactly(zoo_ctx, tmp_path):
    full_hist, full_acc, _ = run_lenet(epochs=6)
    res_hist, res_acc, _ = run_lenet(epochs=6,
                                     ckpt_dir=str(tmp_path / "ck"),
                                     stop_at=3)
    tail = full_hist[-len(res_hist):]
    np.testing.assert_allclose(tail, res_hist, atol=1e-5)
    assert abs(full_acc - res_acc) < 1e-6


def test_digits_split_is_real_data():
    (xt, yt), (xv, yv) = digits_data()
    assert xt.shape == (1536, 16, 16, 1) and len(xv) == 261
    # all ten classes present in both splits
    assert set(np.unique(yt)) == set(range(10))
    assert set(np.unique(yv)) == set(range(10))


def test_transformer_char_lm_converges(zoo_ctx):
    """CI re-check of the ACCURACY_r05 transformer artifact path
    (VERDICT r4 next #3): the SAME run() the tool uses — estimator step,
    bf16 params-in-compute, remat, dropout, flash auto-routing — at a
    tiny config; the loss must drop well below the uniform-byte 5.55
    nats within one short epoch."""
    from analytics_zoo_tpu import init_zoo_context
    from tools.transformer_convergence import corpus_bytes, run

    data = corpus_bytes()[:32768]
    try:
        hist, bpc, _ = run(seq=64, blocks=2, hidden=64, heads=2, batch=8,
                           epochs=1, data=data)
    finally:
        # run() switches the global context to bf16 compute; restore the
        # default so fixture-less tests later in the suite keep f32
        init_zoo_context(seed=0)
    assert hist[-1] < 4.0, hist          # uniform = ln(256) = 5.55 nats
    assert bpc < 6.5, bpc                # held-out follows


def test_lenet_augmented_recipe_learns(zoo_ctx):
    """The ≥99% recipe's augmentation leg (short version): augmented
    training must still reach the old bar quickly — guards the affine
    transform from silently corrupting images."""
    hist, acc, _ = run_lenet(epochs=12, augment=True)
    # corrupted augmentation would sit near chance (~0.1); the full
    # 60+15-epoch recipe is the ACCURACY artifact's ≥0.99 run
    assert acc >= 0.9, acc
