"""CI re-check of the accuracy-parity configs (VERDICT r03 missing #1):
small versions of the ACCURACY_r04.json runs — LeNet on the real sklearn
digits to a convergence bar, and bit-exact checkpoint-resume curve
reproduction (reference resume semantics, TrainImageNet.scala:104-118;
exact iterator state resume is feature/dataset.py's contract)."""

import numpy as np

from tools.accuracy_bench import digits_data, run_lenet


def test_lenet_digits_converges(zoo_ctx, tmp_path):
    hist, acc, _ = run_lenet(epochs=12)
    assert acc >= 0.95, acc
    assert hist[-1] < 0.3 * hist[0]


def test_resume_reproduces_curve_exactly(zoo_ctx, tmp_path):
    full_hist, full_acc, _ = run_lenet(epochs=6)
    res_hist, res_acc, _ = run_lenet(epochs=6,
                                     ckpt_dir=str(tmp_path / "ck"),
                                     stop_at=3)
    tail = full_hist[-len(res_hist):]
    np.testing.assert_allclose(tail, res_hist, atol=1e-5)
    assert abs(full_acc - res_acc) < 1e-6


def test_digits_split_is_real_data():
    (xt, yt), (xv, yv) = digits_data()
    assert xt.shape == (1536, 16, 16, 1) and len(xv) == 261
    # all ten classes present in both splits
    assert set(np.unique(yt)) == set(range(10))
    assert set(np.unique(yv)) == set(range(10))
