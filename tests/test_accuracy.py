"""CI re-check of the accuracy-parity configs (VERDICT r03 missing #1):
small versions of the ACCURACY_r04.json runs — LeNet on the real sklearn
digits to a convergence bar, and bit-exact checkpoint-resume curve
reproduction (reference resume semantics, TrainImageNet.scala:104-118;
exact iterator state resume is feature/dataset.py's contract)."""

import os

import numpy as np

from tools.accuracy_bench import digits_data, run_lenet

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def test_lenet_digits_converges(zoo_ctx, tmp_path):
    hist, acc, _ = run_lenet(epochs=12)
    assert acc >= 0.95, acc
    assert hist[-1] < 0.3 * hist[0]


def test_resume_reproduces_curve_exactly(zoo_ctx, tmp_path):
    full_hist, full_acc, _ = run_lenet(epochs=6)
    res_hist, res_acc, _ = run_lenet(epochs=6,
                                     ckpt_dir=str(tmp_path / "ck"),
                                     stop_at=3)
    tail = full_hist[-len(res_hist):]
    np.testing.assert_allclose(tail, res_hist, atol=1e-5)
    assert abs(full_acc - res_acc) < 1e-6


def test_digits_split_is_real_data():
    (xt, yt), (xv, yv) = digits_data()
    assert xt.shape == (1536, 16, 16, 1) and len(xv) == 261
    # all ten classes present in both splits
    assert set(np.unique(yt)) == set(range(10))
    assert set(np.unique(yv)) == set(range(10))


def test_transformer_char_lm_converges():
    """CI re-check of the ACCURACY_r05 transformer artifact path
    (VERDICT r4 next #3): the SAME run() the tool uses — estimator step,
    bf16 params-in-compute, remat, dropout, flash auto-routing — at a
    tiny config; the loss must drop well below the uniform-byte 5.55
    nats within one short epoch.

    Runs in a SUBPROCESS: under full-suite memory/thread pressure the
    XLA CPU runtime intermittently SIGABRTs inside this training loop
    (observed twice, never reproducible standalone in 7 attempts);
    isolation keeps a runtime-level abort from killing the whole suite
    run, and the fresh interpreter also leaves the parent's global
    context untouched (run() switches it to bf16)."""
    import json
    import subprocess
    import sys

    code = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import json, sys
sys.path.insert(0, ".")
from tools.transformer_convergence import corpus_bytes, run
data = corpus_bytes()[:32768]
hist, bpc, _ = run(seq=64, blocks=2, hidden=64, heads=2, batch=8,
                   epochs=1, data=data)
print("RESULT " + json.dumps({"last": float(hist[-1]),
                              "bpc": float(bpc)}))
"""
    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    env.pop("PYTHONPATH", None)   # keep the axon plugin out entirely
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO_ROOT,
                         env=env, capture_output=True, text=True,
                         timeout=900)
    assert out.returncode == 0, (out.stdout[-500:], out.stderr[-1500:])
    line = [ln for ln in out.stdout.splitlines()
            if ln.startswith("RESULT ")][-1]
    r = json.loads(line[len("RESULT "):])
    assert r["last"] < 4.0, r            # uniform = ln(256) = 5.55 nats
    assert r["bpc"] < 6.5, r             # held-out follows


def test_lenet_augmented_recipe_learns(zoo_ctx):
    """The ≥99% recipe's augmentation leg (short version): augmented
    training must still reach the old bar quickly — guards the affine
    transform from silently corrupting images."""
    hist, acc, _ = run_lenet(epochs=12, augment=True)
    # corrupted augmentation would sit near chance (~0.1); the full
    # 60+15-epoch recipe is the ACCURACY artifact's ≥0.99 run
    assert acc >= 0.9, acc
