"""Tier-3 "zoosan" tests: whole-program static concurrency analysis
(callgraph + interprocedural lock-order + guarded-by inference) and the
runtime lockdep sanitizer (``ZOO_SAN=1``).

Static fixtures live in tests/resources/zoosan_fixtures/ — a planted
cross-file ABBA (two modules, opposite nesting order, no single-file
witness), its suppressed variant, a guarded-by runtime violation, the
blocking-under-lock shapes, and a clean (consistently ordered) negative
— mirroring the zoolint fixture convention of positive + suppressed
cases.  The runtime tests install/uninstall the sanitizer in-process
when the session is not already running under ``ZOO_SAN=1``.

CI gates here: ``test_package_lock_graph_acyclic`` (the statically
extracted whole-package lock graph has no cycles) and
``test_package_inference_zero_gaps`` (every lock-guarded attribute is
annotated or justified — 14/14 lock-holding modules covered).  The
companion gate ``test_zoolint.py::test_package_is_clean`` runs the
interprocedural pass over the package as part of the quick tier.
"""

import importlib.util
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
PKG = os.path.join(REPO, "analytics_zoo_tpu")
FIXTURES = os.path.join(REPO, "tests", "resources", "zoosan_fixtures")


def _load_module(relpath, name=None):
    """Import one fixture file (its directory goes on sys.path so flat
    sibling imports like ``from abba_locks import ...`` resolve)."""
    path = os.path.join(FIXTURES, relpath)
    name = name or os.path.splitext(os.path.basename(path))[0]
    sys.path.insert(0, os.path.dirname(path))
    try:
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    finally:
        sys.path.pop(0)
    return mod


def _active(findings):
    return [f for f in findings if not f.suppressed]


# ---------------------------------------------------------------------------
# Callgraph: the linked whole-package view.
# ---------------------------------------------------------------------------


class TestCallGraph:
    @pytest.fixture(scope="class")
    def prog(self):
        from analytics_zoo_tpu.analysis.callgraph import load_program

        return load_program(PKG)

    def test_loads_the_whole_package(self, prog):
        assert len(prog.modules) > 100
        assert len(prog.functions) > 1000

    def test_typed_locks_are_discovered(self, prog):
        broker = ("analytics_zoo_tpu.serving.broker", "InMemoryBroker")
        assert "_cv" in prog.class_locks[broker]
        assert prog.class_locks[broker]["_cv"].factory \
            == "threading.Condition"
        assert prog.class_locks[broker]["_cv"].lock_id \
            == "analytics_zoo_tpu.serving.broker.InMemoryBroker._cv"
        assert "_lock" in prog.class_locks[
            ("analytics_zoo_tpu.metrics.registry", "MetricsRegistry")]
        assert "_LOCK" in prog.module_locks[
            "analytics_zoo_tpu.common.engine"]

    def test_cross_module_call_edge_reaches_foreign_lock(self, prog):
        """InferenceModel._get_compiled compiles under its own lock and
        calls into compile_cache — the lock graph must contain that
        cross-module edge (no single file shows both locks)."""
        from analytics_zoo_tpu.analysis.rules_interproc import (
            build_lock_graph,
        )

        edges = build_lock_graph(prog)
        assert ("analytics_zoo_tpu.pipeline.inference.inference_model"
                ".InferenceModel._lock",
                "analytics_zoo_tpu.common.compile_cache._LOCK") in edges


# ---------------------------------------------------------------------------
# Interprocedural lock order (static half).
# ---------------------------------------------------------------------------


class TestInterprocLockOrder:
    def test_cross_file_abba_detected(self):
        from analytics_zoo_tpu.analysis.callgraph import load_program
        from analytics_zoo_tpu.analysis.rules_interproc import (
            build_lock_graph,
            find_cycles,
            lint_program,
        )

        root = os.path.join(FIXTURES, "abba")
        prog = load_program(root)
        cycles = find_cycles(build_lock_graph(prog))
        assert cycles, "planted cross-file ABBA not found"
        (cycle,) = cycles
        assert {lid.rsplit(".", 1)[1] for lid in set(cycle)} \
            == {"LOCK_A", "LOCK_B"}

        findings = lint_program(root)
        active = _active(findings)
        assert [f.rule for f in active] == ["lock-order-global"]
        (f,) = active
        # both witness sites, one per module, land in the finding
        paths = {s["path"] for s in f.data["sites"]}
        assert any("abba_serving" in p for p in paths)
        assert any("abba_metrics" in p for p in paths)

    def test_suppressed_variant_is_quiet(self):
        from analytics_zoo_tpu.analysis.rules_interproc import lint_program

        findings = lint_program(os.path.join(FIXTURES, "abba_suppressed"))
        assert not _active(findings)
        assert any(f.rule == "lock-order-global" and f.suppressed
                   for f in findings)

    def test_lock_named_locals_do_not_merge(self, tmp_path):
        """A local variable merely NAMED `lock` must not become a
        program-wide node: two unrelated locals in different modules
        nested oppositely around a shared lock are not a cycle."""
        from analytics_zoo_tpu.analysis.callgraph import load_program
        from analytics_zoo_tpu.analysis.rules_interproc import (
            build_lock_graph,
            find_cycles,
        )

        (tmp_path / "shared.py").write_text(
            "import threading\nL = threading.Lock()\n")
        (tmp_path / "a.py").write_text(
            "from shared import L\n"
            "def fa():\n"
            "    lock = object()\n"
            "    with lock:\n"
            "        with L:\n"
            "            pass\n")
        (tmp_path / "b.py").write_text(
            "from shared import L\n"
            "def fb():\n"
            "    lock = object()\n"
            "    with L:\n"
            "        with lock:\n"
            "            pass\n")
        prog = load_program(str(tmp_path), package="p")
        assert find_cycles(build_lock_graph(prog)) == []

    def test_same_named_classes_do_not_share_lock_ids(self, tmp_path):
        """Two classes both named Worker in different modules own
        DIFFERENT `_lock`s — opposite nesting vs a shared module lock
        must not read as a cycle."""
        from analytics_zoo_tpu.analysis.callgraph import load_program
        from analytics_zoo_tpu.analysis.rules_interproc import (
            build_lock_graph,
            find_cycles,
        )

        (tmp_path / "shared.py").write_text(
            "import threading\nL = threading.Lock()\n")
        common = ("import threading\nfrom shared import L\n"
                  "class Worker:\n"
                  "    def __init__(self):\n"
                  "        self._lock = threading.Lock()\n")
        (tmp_path / "a.py").write_text(
            common + "    def go(self):\n"
                     "        with self._lock:\n"
                     "            with L:\n"
                     "                pass\n")
        (tmp_path / "b.py").write_text(
            common + "    def go(self):\n"
                     "        with L:\n"
                     "            with self._lock:\n"
                     "                pass\n")
        prog = load_program(str(tmp_path), package="p")
        edges = build_lock_graph(prog)
        assert find_cycles(edges) == []
        assert ("p.a.Worker._lock", "p.shared.L") in edges
        assert ("p.shared.L", "p.b.Worker._lock") in edges

    def test_clean_fixture_is_quiet(self):
        """The consistently ordered negative contributes nothing, even
        when linted alongside the planted positives."""
        from analytics_zoo_tpu.analysis.rules_interproc import lint_program

        findings = lint_program(FIXTURES, package="zoosan_fixtures")
        clean = [f for f in _active(findings)
                 if f.path.endswith("clean_ordered.py")]
        assert clean == []


# ---------------------------------------------------------------------------
# Guarded-by inference (static half).
# ---------------------------------------------------------------------------


class TestGuardedByInference:
    @pytest.fixture(scope="class")
    def findings(self):
        from analytics_zoo_tpu.analysis.rules_interproc import lint_program

        return lint_program(FIXTURES, package="zoosan_fixtures")

    def _candidates(self, findings, cls):
        return [f for f in _active(findings)
                if f.rule == "guarded-by-candidate"
                and f.data.get("cls") == cls]

    def test_mixed_writes_become_a_candidate(self, findings):
        (f,) = self._candidates(findings, "MixedWrites")
        assert f.data["attribute"] == "_items"
        assert f.data["lock"] == "_lock"
        unlocked = f.data["unlocked_writes"]
        assert len(unlocked) == 1
        assert unlocked[0]["method"] == "MixedWrites.reset"

    def test_private_helper_counts_as_locked(self, findings):
        """Every call site of `_bump_locked` holds the lock — the
        interprocedural fact retires the false unlocked-write."""
        (f,) = self._candidates(findings, "HelperLocked")
        assert f.data["attribute"] == "_count"
        assert f.data["unlocked_writes"] == []

    def test_annotated_class_is_not_a_candidate(self, findings):
        assert self._candidates(findings, "Annotated") == []


# ---------------------------------------------------------------------------
# Package-level CI gates.
# ---------------------------------------------------------------------------


def test_package_lock_graph_acyclic():
    """The statically extracted whole-package lock graph must stay
    acyclic — this is the deadlock-freedom gate for every lock the 14
    lock-holding modules take, including cross-module chains."""
    from analytics_zoo_tpu.analysis.callgraph import load_program
    from analytics_zoo_tpu.analysis.rules_interproc import (
        build_lock_graph,
        find_cycles,
    )

    edges = build_lock_graph(load_program(PKG))
    assert edges, "lock graph unexpectedly empty (extraction broke?)"
    cycles = find_cycles(edges)
    assert cycles == [], f"whole-package lock cycle(s): {cycles}"


def test_package_inference_zero_gaps():
    """Acceptance: the guarded-by inference reports zero remaining
    `guarded-by-candidate` gaps over the package — every lock-guarded
    attribute is annotated (or carries a justified suppression)."""
    from analytics_zoo_tpu.analysis.rules_interproc import lint_program

    gaps = [f for f in _active(lint_program(PKG))
            if f.rule == "guarded-by-candidate"]
    assert gaps == [], "\n".join(
        f"{f.path}:{f.line} {f.message}" for f in gaps)


def test_every_lock_holding_module_is_annotated():
    """14/14: each module that creates a lock carries at least one
    `# guarded-by:` annotation or a justified zoolint suppression."""
    from analytics_zoo_tpu.analysis.astlint import (
        iter_python_files,
        parse_module,
    )

    lockish = ("threading.Lock(", "threading.RLock(",
               "threading.Condition(")
    missing, holders = [], []
    for path in iter_python_files([PKG]):
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        if not any(tok in source for tok in lockish):
            continue
        holders.append(path)
        mod = parse_module(source, path)
        covered = bool(mod.guarded_by_lines) or bool(
            mod.file_suppressions) or bool(mod.suppressions)
        if not covered:
            missing.append(path)
    assert len(holders) >= 14, holders
    assert missing == [], f"lock-holding modules without guarded-by " \
                          f"annotations or suppressions: {missing}"


# ---------------------------------------------------------------------------
# Runtime sanitizer.
# ---------------------------------------------------------------------------


@pytest.fixture()
def san():
    """The sanitizer, installed (reusing the session-wide install when
    the tier runs under ZOO_SAN=1), watching the fixture tree, with
    findings drained on both sides of the test."""
    from analytics_zoo_tpu.analysis import sanitizer

    was_installed = sanitizer.installed()
    if not was_installed:
        sanitizer.install()
    sanitizer.watch_path(FIXTURES)
    sanitizer.drain()
    yield sanitizer
    sanitizer.drain()
    if not was_installed:
        sanitizer.uninstall()


class TestRuntimeLockdep:
    def test_planted_abba_is_caught_with_both_stacks(self, san):
        a = _load_module("abba/abba_serving.py")
        b = _load_module("abba/abba_metrics.py")
        assert a.a_then_b() == "ab"
        assert b.b_then_a() == "ba"
        found = [f for f in san.drain() if f.rule == "san-lock-order"]
        assert len(found) == 1
        (f,) = found
        locks = {c.rsplit(":", 1)[0] for c in f.data["cycle"]}
        assert locks == {os.path.join("abba", "abba_locks.py")}
        # the structured finding carries BOTH acquisition stacks
        assert "abba_metrics" in f.data["this_stack"] \
            or "abba_serving" in f.data["this_stack"]
        assert f.data["reverse_stack"].strip()
        sys.modules.pop("abba_locks", None)

    def test_cross_thread_release_does_not_leak_held(self, san):
        """A Lock acquired on thread A and released on thread B (the
        legal handoff pattern) must not leave a phantom hold on A that
        flags every later sleep/acquire."""
        import threading
        import time

        mod = _load_module("blocking_under_lock.py")
        lock = mod.LOCK  # a sanitized lock from the watched fixture
        assert lock.acquire()
        t = threading.Thread(target=lock.release)
        t.start()
        t.join()
        time.sleep(0.001)  # would be flagged if the hold leaked
        mod.bounded_get_under_lock(__import__("queue").Queue())
        assert [f.rule for f in san.drain()] == []

    def test_consistent_order_is_quiet(self, san):
        clean = _load_module("clean_ordered.py")
        pair = clean.OrderedPair()
        pair.bump()
        pair.nested_consistent()
        clean.also_consistent()
        assert [f.rule for f in san.drain()] == []


class TestRuntimeGuardedBy:
    def test_violation_caught_good_and_suppressed_quiet(self, san):
        mod = _load_module("guarded_violation.py")
        assert san.instrument_module(mod) == 1
        box = mod.GuardedBox()  # __init__ writes are exempt
        box.good_write(1)
        box.lockfree_write(2)  # statically suppressed => runtime quiet
        assert san.findings() == []
        box.bad_write(3)
        found = san.drain()
        assert [f.rule for f in found] == ["san-guarded-by"]
        (f,) = found
        assert f.data["attribute"] == "_state"
        assert f.data["lock"] == "_lock"
        assert "bad_write" in f.data["stack"]

    def test_package_annotation_validated_when_session_sanitized(self):
        """Under a ZOO_SAN=1 session the real broker's Condition is
        wrapped at import — writing its guarded dict without the lock
        must be flagged (the static annotation, proven at runtime)."""
        from analytics_zoo_tpu.analysis import sanitizer

        if not (os.environ.get("ZOO_SAN") == "1"
                and sanitizer.installed()):
            pytest.skip("needs a session-wide ZOO_SAN=1 install")
        import analytics_zoo_tpu.serving.broker as broker_mod

        sanitizer.instrument_module(broker_mod)
        broker = broker_mod.InMemoryBroker()
        assert type(broker._cv._lock).__name__ == "SanRLock"
        sanitizer.drain()
        broker._streams = {}  # naked write to a guarded attribute
        found = [f for f in sanitizer.drain()
                 if f.rule == "san-guarded-by"]
        assert found and found[0].data["attribute"] == "_streams"


class TestRuntimeBlocking:
    def test_sleep_and_unbounded_put_flagged_bounded_get_not(self, san):
        import queue

        mod = _load_module("blocking_under_lock.py")
        q = queue.Queue()
        mod.sleep_under_lock()
        mod.unbounded_put_under_lock(q)
        mod.bounded_get_under_lock(q)
        mod.suppressed_sleep_under_lock()
        found = san.drain()
        calls = sorted(f.data["call"] for f in found)
        assert calls == ["queue.Queue.put(timeout=None)",
                         "time.sleep(0.001)"]
        assert all(f.rule == "san-blocking-under-lock" for f in found)

    def test_held_locks_are_named(self, san):
        mod = _load_module("blocking_under_lock.py")
        mod.sleep_under_lock()
        (f,) = san.drain()
        assert any("blocking_under_lock.py" in lk
                   for lk in f.data["locks"])


class TestZeroCostDisabled:
    def test_threading_lock_identity_when_env_unset(self):
        """Acceptance: with ZOO_SAN unset, importing the package
        patches NOTHING — threading.Lock stays the builtin."""
        env = {k: v for k, v in os.environ.items() if k != "ZOO_SAN"}
        code = (
            "import sys, threading, _thread\n"
            "import analytics_zoo_tpu\n"
            "assert 'analytics_zoo_tpu.analysis.sanitizer' not in "
            "sys.modules  # disabled path imports NO analysis module\n"
            "from analytics_zoo_tpu.analysis import sanitizer\n"
            "assert threading.Lock is _thread.allocate_lock\n"
            "assert threading.RLock is not None\n"
            "assert not sanitizer.installed()\n"
            "import time, queue\n"
            "assert not getattr(time.sleep, '_zoo_san', False)\n"
            "assert not getattr(queue.Queue.put, '_zoo_san', False)\n"
            "print('untouched')\n"
        )
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stderr
        assert "untouched" in out.stdout

    def test_enabled_subprocess_wraps_package_locks(self):
        """The flip side: ZOO_SAN=1 wraps the package's module-level
        locks at import time."""
        env = dict(os.environ, ZOO_SAN="1")
        code = (
            "import analytics_zoo_tpu\n"
            "from analytics_zoo_tpu.common import engine\n"
            "from analytics_zoo_tpu.analysis import sanitizer\n"
            "assert sanitizer.installed()\n"
            "assert type(engine._LOCK).__name__ == 'SanLock', "
            "type(engine._LOCK)\n"
            "print('wrapped')\n"
        )
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stderr
        assert "wrapped" in out.stdout


class TestTelemetryIntegration:
    def test_findings_hit_metrics_and_flight(self, san):
        from analytics_zoo_tpu.metrics import (
            get_flight_recorder,
            get_registry,
        )

        mod = _load_module("blocking_under_lock.py")
        mod.sleep_under_lock()
        assert san.findings()
        reg = get_registry()
        total = 0.0
        for fam in reg.collect():
            if fam.name == "zoo_san_findings_total":
                for labels, child in fam.samples():
                    if labels.get("rule") == "san-blocking-under-lock":
                        total += child.get()
        assert total >= 1
        events = get_flight_recorder().events("san_finding")
        assert any(e["rule"] == "san-blocking-under-lock"
                   for e in events)


# ---------------------------------------------------------------------------
# CLI satellites: --changed, --whole-program, bare-suppression.
# ---------------------------------------------------------------------------


class TestCliSatellites:
    def test_changed_lints_only_modified_files(self, tmp_path,
                                               monkeypatch, capsys):
        from analytics_zoo_tpu.analysis.cli import main

        repo = tmp_path / "repo"
        repo.mkdir()
        monkeypatch.chdir(repo)
        git = ["git", "-c", "user.email=t@t", "-c", "user.name=t"]
        subprocess.run(["git", "init", "-q"], check=True)
        (repo / "clean.py").write_text("x = 1\n")
        (repo / "dirty.py").write_text("x = 1\n")
        subprocess.run([*git, "add", "."], check=True)
        subprocess.run([*git, "commit", "-qm", "seed"], check=True)
        # no origin/main: falls back to the working-tree diff
        (repo / "dirty.py").write_text(
            "try:\n    x = 1\nexcept:\n    pass\n")
        (repo / "fresh.py").write_text("import time\n")  # untracked
        rc = main(["--changed"])
        out = capsys.readouterr().out
        assert rc == 1  # the bare except in dirty.py
        assert "dirty.py" in out
        assert "clean.py" not in out
        # cwd-independence: from a subdirectory the same changes must
        # still be found (a subdir invocation reading as clean would
        # green-light a broken pre-commit)
        sub = repo / "sub"
        sub.mkdir()
        monkeypatch.chdir(sub)
        rc = main(["--changed"])
        out = capsys.readouterr().out
        assert rc == 1 and "dirty.py" in out

    def test_changed_clean_tree_exits_zero(self, tmp_path, monkeypatch,
                                           capsys):
        from analytics_zoo_tpu.analysis.cli import main

        repo = tmp_path / "repo"
        repo.mkdir()
        monkeypatch.chdir(repo)
        subprocess.run(["git", "init", "-q"], check=True)
        rc = main(["--changed"])
        assert rc == 0
        assert "nothing to lint" in capsys.readouterr().out

    def test_whole_program_flag_finds_cross_file_abba(self, capsys):
        from analytics_zoo_tpu.analysis.cli import main

        rc = main(["--whole-program", os.path.join(FIXTURES, "abba")])
        out = capsys.readouterr().out
        assert rc == 1
        assert "lock-order-global" in out

    def test_precommit_script_exists_and_is_executable(self):
        path = os.path.join(REPO, "tools", "precommit.sh")
        assert os.path.exists(path)
        assert os.access(path, os.X_OK)
        with open(path) as f:
            body = f.read()
        assert "--changed" in body and "ZOO_SAN=1" in body

    def test_bare_suppression_is_a_warning(self):
        from analytics_zoo_tpu.analysis import lint_source

        src = ("try:\n"
               "    x = 1\n"
               "except:  # zoolint: disable=bare-except\n"
               "    pass\n")
        findings = lint_source(src)
        assert [f.rule for f in _active(findings)] == ["bare-suppression"]

    def test_justified_suppression_is_quiet(self):
        from analytics_zoo_tpu.analysis import lint_source

        src = ("try:\n"
               "    x = 1\n"
               "except:  # zoolint: disable=bare-except -- probe must\n"
               "    pass\n")
        assert _active(lint_source(src)) == []

    def test_every_package_suppression_is_justified(self):
        """Satellite burn-down: the surviving suppressions all carry a
        `--` justification (bare ones are warnings the clean gate would
        catch; this pins it directly)."""
        from analytics_zoo_tpu.analysis.astlint import (
            iter_python_files,
            parse_module,
        )

        bare = []
        for path in iter_python_files([PKG]):
            with open(path, encoding="utf-8") as fh:
                mod = parse_module(fh.read(), path)
            for line in mod.unjustified_suppressions:
                bare.append(f"{path}:{line}")
        assert bare == [], f"unjustified suppressions: {bare}"


# ---------------------------------------------------------------------------
# HLO satellite: collective + gather/scatter byte accounting.
# ---------------------------------------------------------------------------


class TestHloCollectiveBytes:
    def _two_device_mesh(self):
        import numpy as np
        import jax
        from jax.sharding import Mesh

        return Mesh(np.array(jax.devices()[:2]), ("d",))

    def test_reduce_scatter_bytes_hand_count(self):
        """2-device reduce-scatter of a per-device tensor<4xf32>: the
        FULL 16-byte shard participates even though each device keeps
        8 bytes — hand count pinned."""
        import jax
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        from analytics_zoo_tpu.analysis import analyze_hlo_text

        mesh = self._two_device_mesh()
        fn = shard_map(
            lambda x: jax.lax.psum_scatter(
                x, "d", scatter_dimension=0, tiled=True),
            mesh=mesh, in_specs=P("d"), out_specs=P("d"))
        text = jax.jit(fn).lower(jnp.ones((8,), jnp.float32)).as_text()
        rpt = analyze_hlo_text(text, label="rs")
        assert rpt.collectives == {"reduce_scatter": 1}
        assert rpt.collective_count == 1
        # per-device operand: 8/2 = 4 f32 = 16 bytes (result is 2xf32,
        # 8 bytes — the old result-only accounting undercounted 2x)
        assert rpt.collective_bytes == 16

    def test_all_to_all_and_permute_counted(self):
        import jax
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        from analytics_zoo_tpu.analysis import analyze_hlo_text

        mesh = self._two_device_mesh()
        a2a = shard_map(
            lambda x: jax.lax.all_to_all(
                x, "d", split_axis=1, concat_axis=0, tiled=True),
            mesh=mesh, in_specs=P("d"), out_specs=P("d"))
        rpt = analyze_hlo_text(
            jax.jit(a2a).lower(jnp.ones((4, 4), jnp.float32)).as_text(),
            label="a2a")
        assert rpt.collectives == {"all_to_all": 1}
        assert rpt.collective_bytes == 32  # per-device 2x4 f32

        perm = shard_map(
            lambda x: jax.lax.ppermute(x, "d", perm=[(0, 1), (1, 0)]),
            mesh=mesh, in_specs=P("d"), out_specs=P("d"))
        rpt = analyze_hlo_text(
            jax.jit(perm).lower(jnp.ones((4,), jnp.float32)).as_text(),
            label="perm")
        assert rpt.collectives == {"collective_permute": 1}
        assert rpt.collective_bytes == 8  # per-device 2xf32

    def test_gather_charges_slices_not_the_table(self):
        """An embedding-style x[i] gather reads indices + slices (result
        sized), not the whole table: 4x1 i32 indices (16B) + 2x the
        4x8 f32 result (256B) = 272 — NOT the 512-byte table."""
        from analytics_zoo_tpu.analysis import analyze_hlo_text

        line = ('%6 = "stablehlo.gather"(%arg0, %5) <{slice_sizes = '
                'array<i64: 1, 8>}> : (tensor<16x8xf32>, '
                'tensor<4x1xi32>) -> tensor<4x8xf32>')
        rpt = analyze_hlo_text(line, label="g")
        assert rpt.op_histogram.get("gather") == 1
        assert rpt.bytes_accessed == 16 + 2 * 128

    def test_scatter_charges_updates_not_the_table(self):
        from analytics_zoo_tpu.analysis import analyze_hlo_text

        text = ('%7 = "stablehlo.scatter"(%arg0, %5, %6) <{}> ({\n'
                '^bb0(%a: tensor<f32>, %b: tensor<f32>):\n'
                '  stablehlo.return %b : tensor<f32>\n'
                '}) : (tensor<16x8xf32>, tensor<4x1xi32>, '
                'tensor<4x8xf32>) -> tensor<16x8xf32>')
        rpt = analyze_hlo_text(text, label="s")
        assert rpt.op_histogram.get("scatter") == 1
        # indices (16B) + updates read+written (2*128B); the untouched
        # 16x8 table is aliased, not traffic
        assert rpt.bytes_accessed == 16 + 2 * 128
