"""TensorBoard writer tests — round-trip scalars through the TFRecord/proto
encoder (reference tensorboard/FileWriter.scala role) and the fit() wiring
(Topology.scala setTensorBoard + getTrainSummary)."""

import numpy as np


def test_scalar_roundtrip(tmp_path):
    from analytics_zoo_tpu.tensorboard import TrainSummary

    ts = TrainSummary(str(tmp_path), "app")
    for step in range(5):
        ts.add_scalar("Loss", 1.0 / (step + 1), step + 1)
    ts.add_scalar("Throughput", 1234.5, 5)
    ts.close()

    got = ts.read_scalar("Loss")
    assert [s for s, _, _ in got] == [1, 2, 3, 4, 5]
    np.testing.assert_allclose([v for _, v, _ in got],
                               [1.0, 0.5, 1 / 3, 0.25, 0.2], rtol=1e-6)
    tp = ts.read_scalar("Throughput")
    assert len(tp) == 1 and abs(tp[0][1] - 1234.5) < 1e-3


def test_crc32c_known_vectors():
    from analytics_zoo_tpu.tensorboard.record import crc32c, masked_crc

    # RFC 3720 test vector: 32 zero bytes -> 0x8A9136AA
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"123456789") == 0xE3069283
    assert isinstance(masked_crc(b"abc"), int)


def test_native_crc_matches_python():
    from analytics_zoo_tpu.native import build_native
    from analytics_zoo_tpu.tensorboard.record import _crc32c_py

    lib = build_native()
    if lib is None:
        return  # no compiler in env; fallback covered elsewhere
    data = bytes(range(256)) * 33 + b"tail"
    assert lib.crc32c(data) == _crc32c_py(data)
    # normalize kernel matches numpy
    img = np.random.default_rng(0).integers(0, 255, (4, 8, 8, 3),
                                            dtype=np.uint8)
    mean = np.array([123.0, 117.0, 104.0], np.float32)
    std = np.array([58.4, 57.1, 57.4], np.float32)
    out = lib.normalize_u8(img, mean, std)
    ref = (img.astype(np.float32) - mean) / std
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_fit_writes_tensorboard(zoo_ctx, tmp_path):
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.tensorboard import TrainSummary

    x = np.random.default_rng(0).normal(size=(128, 6)).astype(np.float32)
    y = np.random.default_rng(1).integers(0, 3, size=(128,)).astype(np.int32)
    m = Sequential()
    m.add(Dense(3, activation="softmax", input_shape=(6,)))
    m.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    m.set_tensorboard(str(tmp_path), "run1")
    m.fit(x, y, batch_size=32, nb_epoch=3, validation_data=(x, y))

    ts = TrainSummary.__new__(TrainSummary)
    ts.dir = str(tmp_path / "run1" / "train")
    assert len(ts.read_scalar("Throughput")) == 3
    assert len(ts.read_scalar("Loss")) >= 3
    from analytics_zoo_tpu.tensorboard import ValidationSummary

    vs = ValidationSummary.__new__(ValidationSummary)
    vs.dir = str(tmp_path / "run1" / "validation")
    assert len(vs.read_scalar("accuracy")) == 3


def test_inference_summary_roundtrip(tmp_path):
    """InferenceSummary (reference inference/InferenceSummary.scala):
    serving-side throughput scalars land under <log_dir>/<app>/inference
    and read back via read_scalar — the getScalar API."""
    from analytics_zoo_tpu.tensorboard import InferenceSummary

    s = InferenceSummary(str(tmp_path), "serving-app")
    for step, v in enumerate([10.0, 20.0, 15.0]):
        s.add_scalar("Throughput", v, step)
    s.close()
    assert "inference" in s.dir
    back = s.read_scalar("Throughput")
    assert [(st, v) for st, v, _ in back] == [(0, 10.0), (1, 20.0),
                                             (2, 15.0)]
    # closed writer drops late events instead of raising (serving shutdown
    # race) and reports closed
    assert s.closed
    s.add_scalar("Throughput", 99.0, 3)
    assert len(s.read_scalar("Throughput")) == 3


def test_serving_writes_inference_summary(tmp_path):
    """The serving loop records Throughput to the inference summary dir
    (ClusterServing.scala observability parity)."""
    import numpy as np

    from analytics_zoo_tpu.serving import (
        ClusterServing, ClusterServingHelper, InMemoryBroker, InputQueue,
    )
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, Flatten
    from analytics_zoo_tpu.pipeline.api.keras.topology import Sequential

    m = Sequential()
    m.add(Flatten(input_shape=(2, 2, 1)))
    m.add(Dense(3, activation="softmax"))
    m.build_params()
    mp = str(tmp_path / "model.zoo")
    m.save(mp)
    broker = InMemoryBroker()
    serving = ClusterServing(
        ClusterServingHelper(model_path=mp, batch_size=2, top_n=1,
                             data_shape=(2, 2, 1),
                             log_dir=str(tmp_path / "logs")),
        broker=broker)
    inq = InputQueue(broker=broker)
    for i in range(4):
        inq.enqueue_image(f"u{i}", np.zeros((2, 2, 1), np.float32))
    serving.run(max_records=4)
    scalars = serving.summary.read_scalar("Throughput")
    assert len(scalars) >= 1 and all(v > 0 for _, v, _ in scalars)
