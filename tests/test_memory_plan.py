"""The complete memory plan (ISSUE 14): ZeRO-2/3 as sharding-plan
rules, the remat policy as plan rules resolved at trace time, and
pipeline schedules lowered through the compile choke point.

Acceptance: zero3 holds <= 0.25x replicated-DP per-chip param+opt bytes
at a bit-identical (or recorded-ulp) loss trajectory; a model whose
plan="dp" footprint exceeds the configured HBM budget trains under the
fit(plan="auto") oracle choice; every pipeline schedule compiles
through compile_step/timed_compile with zoo_hlo_* features and a
persistent-cache warm hit from a second process.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _data(n=512, feat=32, classes=10, seed=7):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, feat)).astype(np.float32)
    y = np.argmax(x @ rng.normal(size=(feat, classes)),
                  axis=1).astype(np.int32)
    return x, y


def _model(width=256, feat=32, classes=10):
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    m = Sequential()
    m.add(Dense(width, activation="relu", input_shape=(feat,)))
    m.add(Dense(width, activation="relu"))
    m.add(Dense(classes, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    return m


def _fit(plan, epochs=2, width=256, seed=11):
    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.parallel.plan import per_chip_bytes

    zoo.init_zoo_context(seed=seed, mesh_shape={"data": 8})
    x, y = _data()
    m = _model(width=width)
    m.fit(x, y, batch_size=64, nb_epoch=epochs, plan=plan)
    est = m._estimator
    losses = [h["loss"] for h in est.history]
    chip = per_chip_bytes((m.params, est._opt_state))
    return m, losses, chip


# ---------------------------------------------------------------------------
# ZeRO-2/3 as plan rules
# ---------------------------------------------------------------------------


class TestZeroPlanRules:
    def test_zero2_zero3_rule_tables(self):
        from analytics_zoo_tpu.parallel import plan as zp

        z2, z3 = zp.zero2(), zp.zero3()
        # zero2 = zero1's persistent layout + grads reduce-scattered
        assert not z2.shards_params and z2.shards_opt
        assert z2.grad_rules == ((r".*", P("data")),)
        # zero3 shards everything: params, opt state and the grad tree
        assert z3.shards_params and z3.shards_opt
        assert z3.grad_rules == ((r".*", P("data")),)
        assert zp.zero1().grad_rules is None
        assert zp.resolve_plan("zero2").name == "zero2"
        assert zp.resolve_plan("zero3").name == "zero3"

    def test_cache_key_carries_memory_fields(self):
        from analytics_zoo_tpu.parallel import plan as zp

        keys = {zp.zero1().cache_key(), zp.zero2().cache_key(),
                zp.zero3().cache_key(), zp.fsdp().cache_key(),
                zp.with_remat(zp.fsdp(), "full").cache_key(),
                zp.with_remat(zp.fsdp(), "dots").cache_key()}
        # grad_rules separate zero1/zero2 and fsdp/zero3; remat_rules
        # separate the rematted variants — six distinct programs
        assert len(keys) == 6

    def test_constrain_grads_shards_in_graph(self):
        from analytics_zoo_tpu.parallel import plan as zp

        mesh = zp.build_mesh({"data": 8})
        grads = {"k": jnp.ones((16, 4)), "ragged": jnp.ones((3, 4)),
                 "scalar": jnp.ones(())}
        out = jax.jit(
            lambda g: zp.zero3().constrain_grads(g, mesh))(grads)
        assert out["k"].sharding.spec == P("data")
        # the clamp discipline rides along: indivisible/0-D replicate
        assert out["ragged"].sharding.spec in (P(), P(None))
        # dp (grad_rules=None) is the identity — no constraint op
        same = zp.data_parallel().constrain_grads(grads, mesh)
        assert same is grads


class TestRematRules:
    def test_apply_remat_policies(self):
        from analytics_zoo_tpu.parallel import plan as zp

        def f(x):
            return jnp.sin(x) * x

        x = jnp.linspace(0.0, 1.0, 8)
        assert zp.apply_remat(f, None) is f
        assert zp.apply_remat(f, "none") is f
        for policy in zp.REMAT_POLICIES:
            g = zp.apply_remat(f, policy)
            np.testing.assert_array_equal(np.asarray(g(x)),
                                          np.asarray(f(x)))
            np.testing.assert_allclose(
                np.asarray(jax.grad(lambda v: jnp.sum(g(v)))(x)),
                np.asarray(jax.grad(lambda v: jnp.sum(f(v)))(x)))
        with pytest.raises(ValueError, match="remat policy"):
            zp.apply_remat(f, "not-a-policy")

    def test_resolve_remat_sees_plan_at_trace_time(self):
        """compile_step enters the plan for the duration of tracing, so
        resolve_remat inside the traced body returns the plan's policy;
        outside any plan it returns the caller's default."""
        from analytics_zoo_tpu.parallel import plan as zp

        zp.build_mesh({"data": 8})
        seen = {}

        def step(x):
            seen["policy"] = zp.resolve_remat("blocks", default="flag")
            return x * 2.0

        assert zp.resolve_remat("blocks", default="flag") == "flag"
        planned = zp.compile_step(
            step, zp.with_remat(zp.data_parallel(), "dots"),
            label="remat_probe_step")
        out = planned(jnp.ones(()))
        assert float(out) == 2.0
        assert seen["policy"] == "dots"
        # pattern must match the path: a non-matching rule falls back
        scoped = zp.with_remat(zp.data_parallel(), "full",
                               pattern=r"decoder")
        zp.compile_step(step, scoped,
                        label="remat_probe_scoped_step")(jnp.ones(()))
        assert seen["policy"] == "flag"


# ---------------------------------------------------------------------------
# per-chip memory and trajectory acceptance
# ---------------------------------------------------------------------------


class TestZeroTraining:
    def test_zero3_quarter_memory_at_dp_trajectory(self):
        """The ISSUE 14 pin: zero3 per-chip param+opt bytes <= 0.25x
        replicated DP, loss trajectory bitwise dp's (the gather-on-use
        program computes the same sums in the same order); zero2 holds
        zero1-level persistent state (grads are transient in JAX) with
        the same trajectory."""
        _, dp_losses, dp_chip = _fit("dp")
        _, z3_losses, z3_chip = _fit("zero3")
        _, z2_losses, z2_chip = _fit("zero2")

        assert z3_chip / dp_chip <= 0.25, (z3_chip, dp_chip)
        assert z2_chip / dp_chip <= 0.5, (z2_chip, dp_chip)
        assert z3_losses == dp_losses
        # zero2 groups no reduction differently on this program; any
        # drift would be ulp-level, not a different trajectory
        assert max(abs(a - b)
                   for a, b in zip(z2_losses, dp_losses)) < 1e-6

    def test_zero_mem_gauges_close_the_loop(self):
        """Every planned fit publishes zoo_mem_* gauges: the cost
        model's predict_chip_bytes against the measured placement, with
        small relative error."""
        from analytics_zoo_tpu.metrics import get_registry, snapshot

        _fit("zero3", epochs=1)
        mem = {}
        for s in snapshot(get_registry())["samples"]:
            if s["name"].startswith("zoo_mem_") \
                    and s["labels"].get("label") == "train_step_zero3":
                mem[s["name"]] = s["value"]
        assert mem.get("zoo_mem_predicted_bytes", 0) > 0
        assert mem.get("zoo_mem_live_bytes", 0) > 0
        assert mem["zoo_mem_rel_error"] < 0.05, mem


class TestAutoPlanEscapesOOM:
    def test_model_oom_under_dp_trains_under_auto(self, monkeypatch):
        """A model whose replicated footprint exceeds the configured
        HBM budget: the oracle records dp as infeasible and plan="auto"
        resolves to a sharded (possibly rematted) config that fits —
        and the fit actually trains."""
        import analytics_zoo_tpu as zoo

        # small model: ~20KB params + ~40KB adam state + ~20KB
        # activation estimate; a 15KB budget rules out dp (~80KB) and
        # the zero1/zero2 tiers (replicated params alone exceed it) but
        # admits the param+opt-sharded plans once rematted
        monkeypatch.setenv("ZOO_ORACLE_PEAKS",
                           json.dumps({"hbm_bytes": 15_000}))
        zoo.init_zoo_context(seed=0, mesh_shape={"data": 8})
        x, y = _data(n=128, feat=8, classes=4, seed=0)
        m = _model(width=64, feat=8, classes=4)
        m.fit(x, y, batch_size=32, nb_epoch=2, plan="auto")
        est = m._estimator
        doc = est._auto_plan_record
        by_config = {c["config"]: c for c in doc["candidates"]}
        assert not by_config["plan=dp"]["fits_budget"]
        assert doc["feasible"], doc
        chosen = est._auto_plan
        assert chosen.name.split("+")[0] in ("fsdp", "zero3")
        losses = [h["loss"] for h in est.history]
        assert len(losses) == 2 and np.isfinite(losses).all()
        assert losses[-1] < losses[0]


class TestChoosePlanRematSweep:
    def test_remat_widens_the_feasible_set(self):
        """A budget no un-rematted candidate fits: the sweep finds a
        plan x remat config that does, charges the recompute in
        predicted step time, and records both axes in the doc."""
        from analytics_zoo_tpu.analysis.costmodel import (
            PLATFORM_PEAKS,
            predict_chip_bytes,
        )
        from analytics_zoo_tpu.analysis.oracle import ConfigOracle

        p, o, n, act = 800_000, 1_600_000, 8, 800_000
        oracle = ConfigOracle(peaks=PLATFORM_PEAKS["cpu"])
        # zero3 without remat: (p+o)/n + act = 1.1M; with remat full:
        # (p+o)/n + 0.15*act = 420K — only the rematted tier fits 500K
        assert predict_chip_bytes(p, o, "zero3", n, activation_bytes=act) \
            > 500_000
        assert predict_chip_bytes(p, o, "zero3", n, activation_bytes=act,
                                  remat="full") <= 500_000
        name, doc = oracle.choose_plan(
            p, o, n, hbm_budget=500_000, activation_bytes=act,
            remat_options=(None, "full"))
        assert doc["feasible"]
        assert doc["chosen_remat"] == "full"
        assert doc["chosen_config"].endswith("+remat_full")
        assert name in ("fsdp", "zero3")
        # un-rematted configs are still in the doc, marked infeasible
        assert any(c["remat"] is None and not c["fits_budget"]
                   for c in doc["candidates"])


# ---------------------------------------------------------------------------
# pipeline schedules through the compile choke point
# ---------------------------------------------------------------------------


_PIPE_CHILD = r"""
import json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

import analytics_zoo_tpu as zoo
from analytics_zoo_tpu.metrics import get_registry, snapshot
from analytics_zoo_tpu.parallel.pipeline import (
    gpipe, gpipe_hetero, gpipe_1f1b_grads, gpipe_hetero_1f1b_grads,
)

zoo.init_zoo_context(seed=0, mesh_shape={"data": 2, "pipe": 4},
                     mesh_axes=("data", "pipe"))
rng = np.random.default_rng(0)


def stage(p, a):
    return jnp.tanh(a @ p["w"] + p["b"])


def params(v=1):
    return {"w": rng.normal(0, .5, (4 * v, 8, 8)).astype(np.float32),
            "b": rng.normal(0, .1, (4 * v, 8)).astype(np.float32)}


x = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
y = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))

def loss(a, y_mb):
    return jnp.mean((a - y_mb) ** 2)

# every schedule called EAGERLY so _run_planned owns the choke point
gpipe(stage, params(), x, n_microbatch=8)
gpipe(stage, params(2), x, n_microbatch=8, circular_repeats=2)
edge = [{"w": rng.normal(0, .5, (8, 8)).astype(np.float32)}
        for _ in range(4)]
fns = [lambda e, s, a: jnp.tanh(a @ e["w"])] * 4
gpipe_hetero(fns, edge, {}, x, n_microbatch=8)
gpipe_1f1b_grads(stage, loss, params(), x, y, n_microbatch=8)
gpipe_hetero_1f1b_grads(fns, edge, {}, x, y, loss, n_microbatch=8)

out = {"hits": 0, "misses": 0, "hlo_flops": {}, "compiled": []}
for s in snapshot(get_registry())["samples"]:
    if s["name"] == "zoo_compile_cache_hits_total":
        out["hits"] += s["value"]
    elif s["name"] == "zoo_compile_cache_misses_total":
        out["misses"] += s["value"]
    elif s["name"] == "zoo_hlo_flops":
        out["hlo_flops"][s["labels"]["label"]] = s["value"]
    elif s["name"] == "zoo_compile_seconds":
        out["compiled"].append(s["labels"]["label"])
print("RESULT " + json.dumps(out))
"""

PIPELINE_LABELS = {
    "pipeline_gpipe_step", "pipeline_gpipe_circular_step",
    "pipeline_gpipe_hetero_step", "pipeline_1f1b_step",
    "pipeline_1f1b_hetero_step",
}


def _run_pipe_child(cache_dir):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        ZOO_COMPILE_CACHE=str(cache_dir),
    )
    env.pop("ZOO_SHARDING_PLAN", None)
    env.pop("ZOO_SHARD_OPTIMIZER", None)
    r = subprocess.run([sys.executable, "-c", _PIPE_CHILD], env=env,
                       cwd=REPO, capture_output=True, text=True,
                       timeout=420)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    line = [ln for ln in r.stdout.splitlines()
            if ln.startswith("RESULT ")][-1]
    return json.loads(line[len("RESULT "):])


def test_every_pipeline_schedule_compiles_through_choke_point(tmp_path):
    """GPipe, circular/interleaved, hetero, 1F1B and hetero-1F1B all
    lower through compile_step → timed_compile as pipeline_* plans:
    every schedule label lands in zoo_compile_seconds with nonzero
    zoo_hlo_flops, and a second process over the same ZOO_COMPILE_CACHE
    compiles each as a persistent-cache HIT."""
    cache = tmp_path / "cc"
    cold = _run_pipe_child(cache)
    assert PIPELINE_LABELS <= set(cold["compiled"]), cold["compiled"]
    assert PIPELINE_LABELS <= set(cold["hlo_flops"]), cold["hlo_flops"]
    for label in PIPELINE_LABELS:
        assert cold["hlo_flops"][label] > 0, label
    assert cold["hits"] == 0
    assert cold["misses"] == len(PIPELINE_LABELS)

    warm = _run_pipe_child(cache)
    assert warm["misses"] == 0, warm
    assert warm["hits"] == len(PIPELINE_LABELS)
    assert PIPELINE_LABELS <= set(warm["hlo_flops"])


# ---------------------------------------------------------------------------
# Quick-tier bench guard (bench.py --memory)
# ---------------------------------------------------------------------------


def test_memory_bench_quick_tier(tmp_path):
    """CI guard on the bench itself: zero3 per-chip param+opt bytes <=
    0.25x replicated at a bitwise-equal trajectory, and the plan-rule
    remat leg reproduces the un-remated grads while the HLO features
    show the recompute."""
    sys.path.insert(0, REPO)
    try:
        from bench import memory_bench
    finally:
        sys.path.remove(REPO)
    doc = memory_bench(quick=True, out_path=str(tmp_path / "bench.json"))
    assert doc["value"] <= 0.25, doc["value"]
    assert doc["zero3_trajectory_bitwise_equal"] is True
    assert doc["zero2_trajectory_max_abs_diff"] < 1e-6
    assert doc["ratios"]["zero2"] <= 0.5
    pr = doc["pipeline_remat"]
    assert pr["grad_max_abs_diff"] < 1e-6
    legs = {leg["label"]: leg for leg in pr["legs"]}
    # remat recomputes the forward in the backward: more analytic FLOPs
    assert legs["pipeline_gpipe_remat_full"]["hlo"]["zoo_hlo_flops"] \
        > legs["pipeline_gpipe_noremat"]["hlo"]["zoo_hlo_flops"]
