"""Predictive serving plane (ISSUE 20): the serving roofline + the
bucket-stamped report join, the choose_serving verdict contract with
its logged prediction->outcome pairs, the oracle-seeded scaler prior,
admission accept/shed hysteresis with the typed client reject, the
two-model router, ZOO_SERVING_MODELS parsing, the ZooConfig knobs, and
the --serving-predict bench quick-tier guard."""

import json
import os
import sys
import time

import numpy as np
import pytest

from analytics_zoo_tpu.analysis.costmodel import (
    load_serving_rows,
    predict_serving_seconds,
    resolve_peaks,
)
from analytics_zoo_tpu.analysis.oracle import ConfigOracle
from analytics_zoo_tpu.common.engine import ZooConfig
from analytics_zoo_tpu.serving import (
    InMemoryBroker,
    InputQueue,
    OutputQueue,
    ServingRejected,
    model_stream,
)
from analytics_zoo_tpu.serving.admission import (
    ADMISSION_KEY_PREFIX,
    AdmissionController,
)
from analytics_zoo_tpu.serving.modelspec import (
    ModelSpec,
    format_model_specs,
    parse_model_specs,
)
from analytics_zoo_tpu.serving.scaler import FleetSignals, SloScaler

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_serving_env(monkeypatch):
    """The knobs under test resolve from the env — stay hermetic."""
    for var in ("ZOO_ADMISSION", "ZOO_SERVING_MODELS",
                "ZOO_HLO_REPORT_DIR", "ZOO_ORACLE_PEAKS"):
        monkeypatch.delenv(var, raising=False)


def _cpu_peaks():
    return resolve_peaks("cpu")


def _bucket_feats(bucket, service_ms, peaks=None):
    """Features whose analytic CPU predict time is bucket x service_ms
    (compute-bound: flops sized against the peak table, zero bytes)."""
    peaks = peaks or _cpu_peaks()
    return {"matmul_flops": bucket * service_ms / 1e3 * peaks.flops,
            "bytes_accessed": 0, "collective_bytes": 0, "op_count": 10}


# ---------------------------------------------------------------------------
# the serving roofline
# ---------------------------------------------------------------------------

def test_predict_serving_seconds_overhead_floor_and_monotone():
    """An empty program costs exactly the per-call dispatch overhead
    (serving is k=1 — nothing amortizes it), and more work never
    predicts a FASTER dispatch."""
    peaks = _cpu_peaks()
    floor = predict_serving_seconds({}, peaks=peaks)
    assert floor == pytest.approx(peaks.dispatch_overhead_s)
    small = predict_serving_seconds(_bucket_feats(8, 1.0), peaks=peaks)
    big = predict_serving_seconds(_bucket_feats(16, 1.0), peaks=peaks)
    assert floor < small < big
    # memory term: the roofline takes max(compute, memory) + overhead
    membound = predict_serving_seconds(
        {"matmul_flops": 0, "bytes_accessed": peaks.hbm_bytes_per_s,
         "collective_bytes": 0, "op_count": 1}, peaks=peaks)
    assert membound == pytest.approx(1.0 + peaks.dispatch_overhead_s)


def test_load_serving_rows_bucket_join(tmp_path):
    """Only inference_b* reports load, keyed + sorted by bucket; the
    bucket comes from the stamped meta when present, the label suffix
    otherwise; later files win per label; non-serving labels are not
    serving rows."""
    def write(name, doc):
        with open(tmp_path / name, "w") as f:
            json.dump(doc, f)

    write("hlo-a-1-1.json", {
        "schema": "zoo-hlo-report/2", "label": "inference_b16",
        "bucket": 16, "features": {"matmul_flops": 160}})
    write("hlo-b-1-2.json", {  # no stamped bucket: parsed from label
        "schema": "zoo-hlo-report/2", "label": "inference_b8",
        "features": {"matmul_flops": 1}})
    write("hlo-b-1-3.json", {  # same label, later file: wins
        "schema": "zoo-hlo-report/2", "label": "inference_b8",
        "features": {"matmul_flops": 80}})
    write("hlo-c-1-4.json", {  # training row: not a serving row
        "schema": "zoo-hlo-report/2", "label": "step",
        "features": {"matmul_flops": 7}})

    rows = load_serving_rows(str(tmp_path))
    assert [r["bucket"] for r in rows] == [8, 16]
    assert rows[0]["features"]["matmul_flops"] == 80.0
    assert rows[1]["features"]["matmul_flops"] == 160.0


# ---------------------------------------------------------------------------
# choose_serving
# ---------------------------------------------------------------------------

def test_choose_serving_verdict_contract_and_logging():
    """Per-bucket feasibility against the SLO service slice, replica
    math from the best bucket's derated capacity, the batch budget as
    the leftover slice, and a logged prediction per bucket that
    record_outcome closes with a rel_error."""
    oracle = ConfigOracle(peaks=_cpu_peaks())
    feats = {8: _bucket_feats(8, 4.0), 16: _bucket_feats(16, 4.0)}
    verdict = oracle.choose_serving(
        feats, slo_p99_ms=100.0, offered_rate=300.0, model="m")
    # b8 predicts 32.5ms <= 50ms slice; b16 predicts 64.5ms > 50ms
    assert verdict["pad_buckets"] == [8]
    pred8 = verdict["predicted"]["8"]["predict_seconds"]
    assert pred8 == pytest.approx(0.0325)
    assert not verdict["predicted"]["16"]["feasible"]
    # capacity = 8/0.0325 * 0.6 ~ 147.7 rps -> ceil(300/147.7) = 3
    assert verdict["replicas"] == 3
    assert verdict["batch_budget_ms"] == pytest.approx(
        (0.05 - 0.0325) * 1e3)
    assert verdict["config"] == "serving:m"

    oracle.record_outcome("serving:m:b8", 1.0 / pred8,
                          consumer="serving")
    closed = [r for r in oracle.prediction_log()
              if r["config"] == "serving:m:b8"
              and r.get("rel_error") is not None]
    assert closed and closed[-1]["rel_error"] == pytest.approx(0.0, abs=1e-6)


def test_choose_serving_smallest_bucket_never_drops():
    """An SLO no bucket fits still yields a non-empty pad set (the
    smallest bucket) — serving degrades, it does not refuse."""
    oracle = ConfigOracle(peaks=_cpu_peaks())
    verdict = oracle.choose_serving(
        {8: _bucket_feats(8, 4.0)}, slo_p99_ms=1.0, offered_rate=1.0,
        model="tight")
    assert verdict["pad_buckets"] == [8]
    assert verdict["replicas"] >= 1


# ---------------------------------------------------------------------------
# the oracle-seeded scaler prior
# ---------------------------------------------------------------------------

def test_scaler_prior_seeds_then_reactive_takes_over():
    """A fresh scaler with a prior jumps straight to the oracle target
    on the first (empty) window and never re-applies it — the reactive
    policy owns every later decision."""
    s = SloScaler(slo_p99_ms=400.0, min_replicas=1, max_replicas=4,
                  up_windows=2, prior_target=3)
    assert s.initial_target() == 3
    target, reason = s.decide(1, FleetSignals())
    assert (target, reason) == (3, "oracle_prior")
    # the prior is consumed: an idle window now HOLDS (no re-prime)
    target, reason = s.decide(3, FleetSignals())
    assert target == 3 and reason != "oracle_prior"
    # without a prior the same cold start sits at min_replicas
    cold = SloScaler(slo_p99_ms=400.0, min_replicas=1, max_replicas=4)
    assert cold.initial_target() == 1
    assert cold.decide(1, FleetSignals())[0] == 1


def test_scaler_prior_clamped_to_replica_bounds():
    s = SloScaler(min_replicas=2, max_replicas=4, prior_target=99)
    assert s.initial_target() == 4
    s = SloScaler(min_replicas=2, max_replicas=4, prior_target=1)
    assert s.initial_target() == 2


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def _drain(broker, stream, n):
    ids = [r[0] for r in broker.claim(stream, "t", n, 60_000)]
    broker.release(stream, "t", ids, done=True)


def test_admission_shed_hysteresis_and_typed_reject():
    """Backlog beyond the limit sheds with a drain-sized retry-after;
    the door holds shut (draining) until the backlog falls below the
    resume floor; admit() raises the typed reject; stop() clears the
    published verdict so the stream reads unguarded again."""
    broker = InMemoryBroker()
    stream = model_stream("m")
    ac = AdmissionController(broker, stream=stream, model="m",
                             backlog_limit=4, interval=999.0)
    try:
        assert ac.evaluate()["state"] == "accept"
        ac.admit("ok")  # accept path does not raise

        for i in range(6):
            broker.xadd(stream, {"uri": f"u{i}"})
        verdict = ac.evaluate()
        assert verdict["state"] == "shed" and verdict["reason"] == "backlog"
        assert float(verdict["retry_after_ms"]) >= ac.min_retry_ms
        # published for cross-process clients
        hashed = broker.hgetall(ADMISSION_KEY_PREFIX + stream)
        assert hashed.get("state") == "shed"
        with pytest.raises(ServingRejected) as ei:
            ac.admit("rejected-uri")
        assert ei.value.uri == "rejected-uri"
        assert ei.value.reason == "backlog"
        assert ei.value.retry_after_s > 0

        # hysteresis: 3 outstanding is UNDER the limit but above the
        # resume floor (4 * 0.5 = 2) -> still shut, reason "draining"
        _drain(broker, stream, 3)
        verdict = ac.evaluate()
        assert verdict["state"] == "shed" and verdict["reason"] == "draining"

        _drain(broker, stream, 3)
        assert ac.evaluate()["state"] == "accept"
        ac.admit("ok-again")

        transitions = [(d["state"], d["reason"])
                       for d in ac.decision_log()]
        assert ("shed", "backlog") in transitions
        assert ("accept", "") in transitions
    finally:
        ac.stop()
    assert broker.hgetall(ADMISSION_KEY_PREFIX + stream) == {}


def test_admission_counts_total_outstanding_not_just_unclaimed():
    """The backlog signal is stream xlen — claimed-but-unserved work a
    replica holds still counts (it is sojourn time the client pays),
    so a full claim queue cannot hide an overload from the door."""
    broker = InMemoryBroker()
    stream = model_stream("m")
    ac = AdmissionController(broker, stream=stream, model="m",
                             backlog_limit=4, interval=999.0)
    try:
        for i in range(6):
            broker.xadd(stream, {"uri": f"u{i}"})
        broker.claim(stream, "replica", 6, 60_000)  # all claimed
        assert broker.unclaimed(stream) == 0
        verdict = ac.evaluate()
        assert verdict["state"] == "shed" and verdict["reason"] == "backlog"
    finally:
        ac.stop()


def test_admission_slo_burn_trigger():
    """A firing burn alert among the watched names sheds even with an
    empty stream — the door closes on the early-warning signal."""
    class _Engine:
        def firing(self):
            return [{"slo": "predict_p99", "firing": True}]

    broker = InMemoryBroker()
    ac = AdmissionController(broker, stream=model_stream("m"), model="m",
                             slo_engine=_Engine(), interval=999.0)
    try:
        verdict = ac.evaluate()
        assert verdict["state"] == "shed"
        assert verdict["reason"] == "slo_burn:predict_p99"
    finally:
        ac.stop()


def test_client_enqueue_reads_published_verdict():
    """The cross-process path: InputQueue.enqueue raises the typed
    reject from the published hash BEFORE the record enters the
    stream; an absent hash means every enqueue is accepted."""
    broker = InMemoryBroker()
    stream = model_stream("gated")
    q = InputQueue(broker=broker, model="gated")
    rec = np.zeros((4,), np.float32)
    q.enqueue("open", rec)
    assert broker.xlen(stream) == 1

    broker.hset(ADMISSION_KEY_PREFIX + stream, {
        "state": "shed", "retry_after_ms": "250.0", "reason": "backlog"})
    with pytest.raises(ServingRejected) as ei:
        q.enqueue("shut", rec)
    assert ei.value.retry_after_s == pytest.approx(0.25)
    assert broker.xlen(stream) == 1  # the record never entered

    broker.delete(ADMISSION_KEY_PREFIX + stream)
    q.enqueue("open-again", rec)
    assert broker.xlen(stream) == 2


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------

def test_router_two_models_routed_and_decided():
    """Two specs (given as the raw ZOO_SERVING_MODELS string) get
    their own streams, verdicts, and start/stop decisions; records
    enqueued per model come back per model."""
    from analytics_zoo_tpu.serving.fleet import _SyntheticModel
    from analytics_zoo_tpu.serving.router import ModelRouter

    broker = InMemoryBroker()
    oracle = ConfigOracle(peaks=_cpu_peaks())
    router = ModelRouter(
        broker, "fast=300@60,slow=800",
        model_factory=lambda spec: _SyntheticModel(1.0),
        oracle=oracle,
        features={"fast": {8: _bucket_feats(8, 1.0)},
                  "slow": {8: _bucket_feats(8, 1.0)}},
        max_replicas=2, interval=0.2)
    router.start()
    try:
        assert sorted(router.models()) == ["fast", "slow"]
        for name in ("fast", "slow"):
            v = router.verdict(name)
            assert v["model"] == name and v["replicas"] >= 1
        inq = {n: InputQueue(broker=broker, model=n)
               for n in ("fast", "slow")}
        rec = np.zeros((4,), np.float32)
        want = set()
        for i in range(4):
            for n in ("fast", "slow"):
                uri = f"{n}:{i}"
                inq[n].enqueue(uri, rec)
                want.add(uri)
        outq = OutputQueue(broker=broker)
        got = set()
        deadline = time.time() + 60
        while want - got and time.time() < deadline:
            got.update(outq.dequeue())
            time.sleep(0.02)
        assert want <= got
    finally:
        router.stop()
    actions = [(d["model"], d["action"]) for d in router.decision_log()]
    for name in ("fast", "slow"):
        assert (name, "start") in actions
        assert (name, "stop") in actions


# ---------------------------------------------------------------------------
# spec parsing + the ZooConfig knobs
# ---------------------------------------------------------------------------

def test_model_spec_parse_and_format_round_trip():
    specs = parse_model_specs("resnet=250@120, bert=500")
    assert specs == [ModelSpec("resnet", 250.0, 120.0),
                     ModelSpec("bert", 500.0, 0.0)]
    assert parse_model_specs("") == []
    assert parse_model_specs(
        format_model_specs(specs)) == specs


def test_model_spec_errors_name_the_source():
    for bad in ("resnet", "resnet=", "resnet=abc", "a=0",
                "a=100@-5", "a=100,a=200", "a b=100"):
        with pytest.raises(ValueError, match="ZOO_SERVING_MODELS"):
            parse_model_specs(bad)


def test_zooconfig_serving_knobs_validate_eagerly(monkeypatch):
    """Bad env values fail at ZooConfig construction, naming the
    variable — not at the first routed request."""
    monkeypatch.setenv("ZOO_ADMISSION", "bogus")
    with pytest.raises(ValueError, match="ZOO_ADMISSION"):
        ZooConfig()
    monkeypatch.delenv("ZOO_ADMISSION")

    monkeypatch.setenv("ZOO_SERVING_MODELS", "resnet=nope")
    with pytest.raises(ValueError, match="ZOO_SERVING_MODELS"):
        ZooConfig()
    monkeypatch.delenv("ZOO_SERVING_MODELS")

    monkeypatch.setenv("ZOO_ADMISSION", "1")
    monkeypatch.setenv("ZOO_SERVING_MODELS", "resnet=250@120")
    cfg = ZooConfig()
    assert cfg.admission is True
    assert cfg.serving_models == "resnet=250@120"
    assert ZooConfig(admission=False).admission is False


# ---------------------------------------------------------------------------
# bench quick-tier guard
# ---------------------------------------------------------------------------

def test_serving_predict_bench_quick_tier():
    """CI guard (the --serving-predict bench's priming half): the
    oracle-primed fleet takes the 10x load step with no more hard
    SLO-violation windows than the reactive baseline, and the logged
    per-bucket predictions close within 50% of measured."""
    sys.path.insert(0, REPO_ROOT)
    try:
        from bench import serving_predict_primed_bench
    finally:
        sys.path.pop(0)
    out = serving_predict_primed_bench(quick=True)
    assert out["primed"]["violation_windows"] \
        <= out["reactive"]["violation_windows"], out
    assert out["primed"]["decisions"][0]["reason"] == "oracle_prior"
    assert out["predict_rel_error_by_bucket"], out
    for config, err in out["predict_rel_error_by_bucket"].items():
        assert err <= 0.5, (config, err)
