"""Detection data path: VOC parsing, ROI transforms, SSD training on the
checked-in VOCmini fixture with mAP improving — the end-to-end proof the
reference has via its VOC2007 test resources
(zoo/src/test/resources; pipeline SSDDataSet.scala:38-54)."""

import os

import numpy as np
import pytest

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.feature.image.roi import (
    ImageExpandRoi,
    ImageRandomSampler,
    ImageRoiHFlip,
    ImageRoiNormalize,
    ssd_train_set,
    ssd_val_set,
)
from analytics_zoo_tpu.models.image.objectdetection import (
    ObjectDetector,
    mean_average_precision,
)
from analytics_zoo_tpu.models.image.objectdetection.voc import (
    VOC_CLASSES,
    PascalVoc,
    load_voc_annotation,
)

VOC_ROOT = os.path.join(os.path.dirname(__file__), "resources", "VOCmini")
MINI_CLASSES = ("car", "person", "dog")
MINI_MAP = {c: float(i + 1) for i, c in enumerate(MINI_CLASSES)}


def _record(seed=0, n=2, size=64):
    rng = np.random.default_rng(seed)
    boxes = np.array([[8, 8, 32, 32], [40, 20, 60, 50]], np.float32)[:n]
    return {
        "image": rng.integers(0, 255, size=(size, size, 3)).astype(np.uint8),
        "boxes": boxes,
        "classes": np.arange(1, n + 1, dtype=np.float32),
        "difficult": np.zeros(n, np.float32),
        "_rng": rng,
    }


class TestVocParsing:
    def test_roidb_loads_fixture(self):
        voc = PascalVoc(VOC_ROOT, "2007", "train", class_to_ind=MINI_MAP)
        records = voc.roidb()
        assert len(records) == 16
        r = records[0]
        assert r["image"].dtype == np.uint8 and r["image"].shape == (64, 64, 3)
        assert r["boxes"].shape[1] == 4 and len(r["boxes"]) >= 1
        assert set(np.unique(r["classes"])) <= {1.0, 2.0, 3.0}

    def test_annotation_parse_fields(self):
        path = os.path.join(VOC_ROOT, "VOC2007", "Annotations", "000000.xml")
        ann = load_voc_annotation(path, MINI_MAP)
        assert ann["boxes"].min() >= 1  # VOC pixel coords are 1-based
        assert ann["difficult"].tolist() == [0.0] * len(ann["boxes"])

    def test_default_class_map_matches_reference(self):
        # PascalVoc.scala:80-88: background first, 20 classes, 1-based
        assert VOC_CLASSES[0] == "__background__"
        assert len(VOC_CLASSES) == 21

    def test_missing_devkit_raises(self):
        with pytest.raises(FileNotFoundError):
            PascalVoc("/nonexistent/devkit")


class TestRoiTransforms:
    def test_normalize_to_relative(self):
        rec = ImageRoiNormalize()(_record())
        assert rec["boxes"].max() <= 1.0 and rec["boxes"].min() >= 0.0

    def test_hflip_mirrors_boxes(self):
        rec = ImageRoiNormalize()(_record())
        before = rec["boxes"].copy()
        img_before = rec["image"].copy()
        rec = ImageRoiHFlip(prob=1.0)(rec)
        np.testing.assert_allclose(rec["boxes"][:, 0], 1 - before[:, 2])
        np.testing.assert_allclose(rec["boxes"][:, 2], 1 - before[:, 0])
        np.testing.assert_array_equal(rec["image"], img_before[:, ::-1])

    def test_expand_keeps_box_content(self):
        rec = ImageRoiNormalize()(_record())
        h, w = rec["image"].shape[:2]
        px_before = [rec["image"][int(b[1] * h) + 2, int(b[0] * w) + 2]
                     for b in rec["boxes"]]
        rec = ImageExpandRoi(prob=1.0)(rec)
        nh, nw = rec["image"].shape[:2]
        assert nh >= h and nw >= w
        for b, px in zip(rec["boxes"], px_before):
            np.testing.assert_array_equal(
                rec["image"][int(round(b[1] * nh)) + 2,
                             int(round(b[0] * nw)) + 2], px)
        assert rec["boxes"].max() <= 1.0

    def test_random_sampler_keeps_center_boxes(self):
        rec = ImageRoiNormalize()(_record(seed=3))
        out = ImageRandomSampler()(rec)
        assert out["boxes"].shape[0] <= 2
        assert len(out["classes"]) == len(out["boxes"])
        if len(out["boxes"]):
            assert out["boxes"].min() >= 0 and out["boxes"].max() <= 1

    def test_pipeline_deterministic_per_seed(self):
        voc = PascalVoc(VOC_ROOT, "2007", "train", class_to_ind=MINI_MAP)
        records = voc.roidb()
        fs = ssd_train_set(records, resolution=64, max_boxes=4,
                           label_offset=-1)
        b1 = list(fs.batches(8, seed=7, epoch=1))
        b2 = list(fs.batches(8, seed=7, epoch=1))
        np.testing.assert_array_equal(b1[0]["x"], b2[0]["x"])
        np.testing.assert_array_equal(b1[0]["y"], b2[0]["y"])
        b3 = list(fs.batches(8, seed=7, epoch=2))
        assert not np.array_equal(b1[0]["x"], b3[0]["x"])  # fresh augment

    def test_batch_shapes_and_label_offset(self):
        voc = PascalVoc(VOC_ROOT, "2007", "train", class_to_ind=MINI_MAP)
        fs = ssd_train_set(voc.roidb(), resolution=64, max_boxes=4,
                           label_offset=-1)
        batch = next(iter(fs.batches(8, seed=0, epoch=0)))
        assert batch["x"].shape == (8, 64, 64, 3)
        assert batch["y"].shape == (8, 4, 5)
        labels = batch["y"][..., 4]
        assert set(np.unique(labels)) <= {-1.0, 0.0, 1.0, 2.0}


class TestSSDTrainsOnVocFixture:
    def test_map_improves(self):
        init_zoo_context(seed=0)
        voc_tr = PascalVoc(VOC_ROOT, "2007", "train", class_to_ind=MINI_MAP)
        voc_va = PascalVoc(VOC_ROOT, "2007", "val", class_to_ind=MINI_MAP)
        train = ssd_train_set(voc_tr.roidb(), resolution=64, max_boxes=4,
                              label_offset=-1)
        val = ssd_val_set(voc_va.roidb(), resolution=64, max_boxes=4,
                          label_offset=-1)

        val_batches = list(val.batches(4, shuffle=False, drop_last=False))
        val_x = np.concatenate([b["x"] for b in val_batches])
        gts = []
        for b in val_batches:
            for row in b["y"]:
                real = row[row[:, 4] >= 0]
                gts.append(dict(boxes=real[:, :4], classes=real[:, 4]))

        det = ObjectDetector("ssd-tiny", class_names=MINI_CLASSES)
        from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam

        det.compile(Adam(lr=1e-3))

        def score():
            d = det.predict_image_set(val_x, conf_threshold=0.05)
            return mean_average_precision(d, gts, len(MINI_CLASSES),
                                          iou_threshold=0.3)

        before = score()
        det.model.fit(train, batch_size=8, nb_epoch=40)
        after = score()
        assert after > before, (before, after)
        assert after > 0.2, (before, after)


class TestCocoParsing:
    def _mini_instances(self, tmp_path):
        import json

        from PIL import Image
        rng = np.random.default_rng(0)
        imgs, anns = [], []
        for i in range(3):
            arr = rng.integers(0, 255, size=(32, 48, 3)).astype(np.uint8)
            Image.fromarray(arr).save(tmp_path / f"im{i}.jpg")
            imgs.append({"id": i, "file_name": f"im{i}.jpg",
                         "width": 48, "height": 32})
            anns.append({"id": 10 + i, "image_id": i, "category_id": 3,
                         "bbox": [4, 5, 20, 15], "area": 300,
                         "iscrowd": 0})
        # one degenerate box + one unknown category: must be skipped
        anns.append({"id": 99, "image_id": 0, "category_id": 3,
                     "bbox": [4, 5, 0, 0], "area": 0, "iscrowd": 0})
        anns.append({"id": 98, "image_id": 0, "category_id": 12,
                     "bbox": [1, 1, 5, 5], "area": 25, "iscrowd": 0})
        p = tmp_path / "instances.json"
        with open(p, "w") as f:
            json.dump({"images": imgs, "annotations": anns}, f)
        return str(p)

    def test_instances_json(self, tmp_path):
        from analytics_zoo_tpu.models.image.objectdetection import (
            COCO_CAT_ID_TO_IND,
            COCO_CLASSES,
            Coco,
        )

        path = self._mini_instances(tmp_path)
        recs = Coco(str(tmp_path), instances_json=path).roidb()
        assert len(recs) == 3
        r = recs[0]
        assert r["image"].shape == (32, 48, 3)
        # degenerate + unknown-category annotations skipped
        assert r["boxes"].shape == (1, 4)
        # category_id 3 (car) -> dense index
        assert r["classes"][0] == COCO_CAT_ID_TO_IND[3]
        assert COCO_CLASSES[int(r["classes"][0])] == "car"
        # corners clipped semantics: x2 = x1 + w - 1
        np.testing.assert_allclose(r["boxes"][0], [4, 5, 23, 19])

    def test_devkit_layout(self, tmp_path):
        import json

        from PIL import Image
        rng = np.random.default_rng(1)
        (tmp_path / "ImageSets").mkdir()
        arr = rng.integers(0, 255, size=(20, 20, 3)).astype(np.uint8)
        Image.fromarray(arr).save(tmp_path / "a.jpg")
        with open(tmp_path / "a.json", "w") as f:
            json.dump({"image": {"width": 20, "height": 20},
                       "annotation": [{"bbox": [2, 2, 10, 10], "area": 100,
                                       "category_id": 1}]}, f)
        with open(tmp_path / "ImageSets" / "train.txt", "w") as f:
            f.write("a.jpg a.json\n")
        from analytics_zoo_tpu.models.image.objectdetection import Coco

        recs = Coco(str(tmp_path), "train").roidb()
        assert len(recs) == 1 and recs[0]["boxes"].shape == (1, 4)
        assert recs[0]["classes"][0] == 1.0  # person

    def test_edge_crossing_bbox_clipped_not_shifted(self, tmp_path):
        from analytics_zoo_tpu.models.image.objectdetection.coco import (
            _boxes_from_annotations,
        )

        boxes, classes, _ = _boxes_from_annotations(
            [{"bbox": [-5, 0, 10, 10], "category_id": 1, "area": 100}],
            48.0, 32.0, {1: 1})
        # raw corners: x in [-5, 4]; clipped to [0, 4] — NOT [0, 9]
        np.testing.assert_allclose(boxes[0], [0, 0, 4, 9])
