"""Cluster Serving: broker semantics + end-to-end streaming inference
(reference serving/ClusterServing.scala, pyzoo/zoo/serving/client.py)."""

import json
import time

import numpy as np
import pytest

from analytics_zoo_tpu.serving import (
    ClusterServing, ClusterServingHelper, FileBroker, InMemoryBroker,
    InputQueue, OutputQueue,
)
from analytics_zoo_tpu.serving.client import decode_ndarray, encode_ndarray


@pytest.fixture(params=["memory", "file"])
def broker(request, tmp_path):
    if request.param == "memory":
        return InMemoryBroker()
    return FileBroker(str(tmp_path / "spool"))


def test_broker_stream_roundtrip(broker):
    ids = [broker.xadd("s", {"uri": f"u{i}", "image": str(i)})
           for i in range(5)]
    assert broker.xlen("s") == 5
    recs = broker.xread("s", 3)
    assert [f["uri"] for _, f in recs] == ["u0", "u1", "u2"]
    # read after last_id resumes
    recs2 = broker.xread("s", 10, last_id=recs[-1][0])
    assert [f["uri"] for _, f in recs2] == ["u3", "u4"]
    assert ids == sorted(ids)


def test_broker_trim_and_hash(broker):
    for i in range(6):
        broker.xadd("s", {"i": str(i)})
    broker.xtrim("s", 2)
    assert broker.xlen("s") == 2
    assert [f["i"] for _, f in broker.xread("s", 10)] == ["4", "5"]
    broker.hset("result:a", {"value": "1"})
    broker.hset("result:a", {"extra": "2"})
    assert broker.hgetall("result:a") == {"value": "1", "extra": "2"}
    broker.delete("result:a")
    assert broker.hgetall("result:a") == {}


def test_broker_ack(broker):
    ids = [broker.xadd("s", {"i": str(i)}) for i in range(4)]
    broker.ack("s", ids[1])
    assert broker.xlen("s") == 2
    assert [f["i"] for _, f in broker.xread("s", 10)] == ["2", "3"]


def test_server_acks_consumed_records(tmp_path):
    broker = InMemoryBroker()
    model_path = _tiny_classifier(tmp_path)
    serving = ClusterServing(
        ClusterServingHelper(model_path=model_path, batch_size=4,
                             data_shape=(4, 4, 1),
                             log_dir=str(tmp_path / "logs")),
        broker=broker)
    inq = InputQueue(broker=broker)
    for i in range(6):
        inq.enqueue_image(f"u{i}", np.zeros((4, 4, 1), np.float32))
    serving.run(max_records=6)
    assert broker.xlen("image_stream") == 0  # stream drained, not leaked


def test_ndarray_codec():
    arr = np.random.default_rng(0).normal(size=(3, 4, 2)).astype(np.float32)
    out = decode_ndarray(encode_ndarray(arr))
    np.testing.assert_array_equal(arr, out)
    assert out.dtype == np.float32


def _tiny_classifier(tmp_path):
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, Flatten
    from analytics_zoo_tpu.pipeline.api.keras.topology import Sequential

    m = Sequential()
    m.add(Flatten(input_shape=(4, 4, 1)))
    m.add(Dense(5, activation="softmax"))
    m.build_params()
    path = str(tmp_path / "model.zoo")
    m.save(path)
    return path


def test_end_to_end_serving(tmp_path, broker):
    model_path = _tiny_classifier(tmp_path)
    serving = ClusterServing(
        ClusterServingHelper(model_path=model_path, batch_size=4, top_n=2,
                             data_shape=(4, 4, 1),
                             log_dir=str(tmp_path / "logs")),
        broker=broker)
    inq = InputQueue(broker=broker)
    outq = OutputQueue(broker=broker)
    rng = np.random.default_rng(1)
    for i in range(10):
        inq.enqueue_image(f"img-{i}", rng.normal(
            size=(4, 4, 1)).astype(np.float32))
    served = serving.run(max_records=10)
    assert served == 10
    for i in range(10):
        res = outq.query(f"img-{i}")
        assert res is not None and len(res) == 2  # top-2 [class, prob]
        cls, prob = res[0]
        assert 0 <= cls < 5 and 0.0 <= prob <= 1.0
    assert outq.query("missing") is None


def test_serving_thread_and_bad_records(tmp_path):
    broker = InMemoryBroker()
    model_path = _tiny_classifier(tmp_path)
    serving = ClusterServing(
        ClusterServingHelper(model_path=model_path, batch_size=2,
                             data_shape=(4, 4, 1),
                             log_dir=str(tmp_path / "logs")),
        broker=broker).start(idle_timeout=5.0)
    inq = InputQueue(broker=broker)
    broker.xadd("image_stream", {"uri": "bad", "image": "not-b64!!"})
    inq.enqueue_image("wrong-shape", np.zeros((2, 2, 1), np.float32))
    inq.enqueue_image("ok", np.zeros((4, 4, 1), np.float32))
    outq = OutputQueue(broker=broker)
    deadline = time.time() + 30
    while outq.query("ok") is None and time.time() < deadline:
        time.sleep(0.05)
    serving.stop()
    assert outq.query("ok") is not None
    assert outq.query("bad") is None
    assert outq.query("wrong-shape") is None


def test_yaml_config(tmp_path):
    cfg = tmp_path / "config.yaml"
    cfg.write_text(
        "model:\n  path: /m\nparams:\n  batch_size: 8\n  top_n: 3\n"
        "data:\n  src: memory\n  image_shape: 3,224,224\n")
    h = ClusterServingHelper(str(cfg))
    assert h.model_path == "/m"
    assert h.batch_size == 8
    assert h.top_n == 3
    assert h.data_shape == (3, 224, 224)
    assert h.broker_spec == "memory"

def test_filebroker_memory_ratio_and_server_trim(tmp_path):
    # Tiny capacity so a handful of records exceeds the trim threshold.
    broker = FileBroker(str(tmp_path / "spool"), max_bytes=600)
    broker._RATIO_TTL = 0.0  # the scan cache would hide same-instant adds
    assert broker.memory_ratio() == 0.0
    for i in range(12):
        broker.xadd("image_stream", {"uri": f"u{i}", "image": "x" * 40})
    assert broker.memory_ratio() >= 1.0  # spool is over capacity

    model_path = _tiny_classifier(tmp_path)
    serving = ClusterServing(
        ClusterServingHelper(model_path=model_path, batch_size=4,
                             data_shape=(4, 4, 1),
                             log_dir=str(tmp_path / "logs")),
        broker=broker)
    before = broker.xlen("image_stream")
    serving.step(block_ms=0)  # backpressure path must actually trim
    assert broker.xlen("image_stream") < before
    broker.xtrim("image_stream", 0)
    assert broker.memory_ratio() < 1.0


def test_output_queue_dequeue(tmp_path, broker):
    """OutputQueue.dequeue drains ALL finished results and removes them
    (reference client.py:131) — previously NotImplementedError."""
    model_path = _tiny_classifier(tmp_path)
    serving = ClusterServing(
        ClusterServingHelper(model_path=model_path, batch_size=4, top_n=1,
                             data_shape=(4, 4, 1),
                             log_dir=str(tmp_path / "logs")),
        broker=broker)
    inq = InputQueue(broker=broker)
    outq = OutputQueue(broker=broker)
    rng = np.random.default_rng(0)
    for i in range(6):
        inq.enqueue_image(f"d-{i}", rng.normal(
            size=(4, 4, 1)).astype(np.float32))
    serving.run(max_records=6)
    got = outq.dequeue()
    assert sorted(got) == [f"d-{i}" for i in range(6)]
    assert all("uri" not in str(v) for v in got.values())  # decoded value
    for res in got.values():
        cls, prob = res[0]
        assert 0 <= cls < 5 and 0.0 <= prob <= 1.0
    # removed: a second dequeue is empty and query misses
    assert outq.dequeue() == {}
    assert outq.query("d-0") is None


def test_dequeue_keys_on_original_uri_with_slashes(tmp_path):
    """FileBroker mangles '/' in key FILENAMES; dequeue must still key
    results on the uri the client enqueued (stored in the hash)."""
    broker = FileBroker(str(tmp_path / "spool"))
    model_path = _tiny_classifier(tmp_path)
    serving = ClusterServing(
        ClusterServingHelper(model_path=model_path, batch_size=2, top_n=1,
                             data_shape=(4, 4, 1),
                             log_dir=str(tmp_path / "logs")),
        broker=broker)
    inq = InputQueue(broker=broker)
    outq = OutputQueue(broker=broker)
    uris = ["s3://imgs/cat.jpg", "dir/sub/dog.png"]
    for u in uris:
        inq.enqueue_image(u, np.zeros((4, 4, 1), np.float32))
    serving.run(max_records=2)
    got = outq.dequeue()
    assert sorted(got) == sorted(uris)


# ---------------------------------------------------------------------------
# Pipelined serving (three-stage read/decode -> predict -> write-back)
# ---------------------------------------------------------------------------


class _SlowBroker(InMemoryBroker):
    """xread and hset_many each cost a fixed sleep — the 'stage time'
    knobs for the overlap test — and every write path is counted."""

    def __init__(self, read_s=0.0, write_s=0.0):
        super().__init__()
        self.read_s, self.write_s = read_s, write_s
        self.hset_calls = 0
        self.hset_many_calls = 0

    def xread(self, stream, count, last_id="0", block_ms=0):
        recs = super().xread(stream, count, last_id=last_id, block_ms=0)
        if recs:
            time.sleep(self.read_s)
        return recs

    def hset(self, key, mapping):
        self.hset_calls += 1
        super().hset(key, mapping)

    def hset_many(self, items):
        self.hset_many_calls += 1
        time.sleep(self.write_s)
        with self._cv:
            for key, mapping in items:
                self._hashes.setdefault(key, {}).update(mapping)
            self._cv.notify_all()


class _SlowModel:
    def __init__(self, predict_s):
        self.predict_s = predict_s

    def predict(self, arr):
        time.sleep(self.predict_s)
        return np.tile(np.arange(5, dtype=np.float32), (arr.shape[0], 1))


def test_pipelined_stages_overlap(tmp_path):
    """Acceptance: a full cycle (read+decode+predict+write) completes in
    < 0.8x the sum of its serialized stage times — broker I/O and decode
    overlap device inference."""
    stage_s, batch, n_batches = 0.05, 4, 6
    broker = _SlowBroker(read_s=stage_s, write_s=stage_s)
    inq = InputQueue(broker=broker)
    for i in range(n_batches * batch):
        inq.enqueue(f"u{i}", np.full((3,), i, np.float32))
    serving = ClusterServing(
        ClusterServingHelper(model_path=None, batch_size=batch,
                             log_dir=str(tmp_path / "logs")),
        model=_SlowModel(stage_s), broker=broker)
    t0 = time.perf_counter()
    served = serving.run(max_records=n_batches * batch, idle_timeout=10.0)
    wall = time.perf_counter() - t0
    serialized = n_batches * 3 * stage_s
    assert served == n_batches * batch
    assert wall < 0.8 * serialized, (wall, serialized)
    # every result flushed before run() returned, one broker write per
    # micro-batch, zero per-record writes
    assert len(OutputQueue(broker=broker).dequeue()) == n_batches * batch
    assert broker.hset_many_calls == n_batches
    assert broker.hset_calls == 0


def test_writeback_batched_per_microbatch(tmp_path):
    """Satellite: process_batch (the serial cycle) also writes each
    micro-batch with ONE hset_many round-trip, not per-record hset."""
    broker = _SlowBroker()
    model_path = _tiny_classifier(tmp_path)
    serving = ClusterServing(
        ClusterServingHelper(model_path=model_path, batch_size=8,
                             data_shape=(4, 4, 1),
                             log_dir=str(tmp_path / "logs")),
        broker=broker)
    inq = InputQueue(broker=broker)
    for i in range(8):
        inq.enqueue_image(f"u{i}", np.zeros((4, 4, 1), np.float32))
    n = serving.step()
    assert n == 8
    assert broker.hset_many_calls == 1
    assert broker.hset_calls == 0


def test_hset_many_falls_back_to_hset():
    """A broker that only implements hset still works: the Broker base
    hset_many loops it."""

    class HsetOnlyBroker(InMemoryBroker):
        hset_many = __import__(
            "analytics_zoo_tpu.serving.broker", fromlist=["Broker"]
        ).Broker.hset_many

    broker = HsetOnlyBroker()
    broker.hset_many([("result:a", {"v": "1"}), ("result:b", {"v": "2"})])
    assert broker.hgetall("result:a") == {"v": "1"}
    assert broker.hgetall("result:b") == {"v": "2"}


def test_serial_mode_still_available(tmp_path):
    broker = InMemoryBroker()
    model_path = _tiny_classifier(tmp_path)
    serving = ClusterServing(
        ClusterServingHelper(model_path=model_path, batch_size=4,
                             data_shape=(4, 4, 1),
                             log_dir=str(tmp_path / "logs")),
        broker=broker)
    inq = InputQueue(broker=broker)
    for i in range(4):
        inq.enqueue_image(f"u{i}", np.zeros((4, 4, 1), np.float32))
    served = serving.run(max_records=4, pipelined=False)
    assert served == 4
    assert len(OutputQueue(broker=broker).dequeue()) == 4


def test_pipelined_restartable_after_max_records(tmp_path):
    """max_records/idle exits must leave the server restartable: the
    done-event is local to each run, self._stop only trips on stop()."""
    broker = InMemoryBroker()
    model_path = _tiny_classifier(tmp_path)
    serving = ClusterServing(
        ClusterServingHelper(model_path=model_path, batch_size=4,
                             data_shape=(4, 4, 1),
                             log_dir=str(tmp_path / "logs")),
        broker=broker)
    inq = InputQueue(broker=broker)
    for i in range(4):
        inq.enqueue_image(f"a{i}", np.zeros((4, 4, 1), np.float32))
    assert serving.run(max_records=4) == 4
    for i in range(4):
        inq.enqueue_image(f"b{i}", np.zeros((4, 4, 1), np.float32))
    assert serving.run(max_records=4) == 4
    assert len(OutputQueue(broker=broker).dequeue()) == 8


def test_pipelined_does_not_lose_read_ahead_batches(tmp_path):
    """Records the reader decoded but the loop never predicted must NOT
    be lost on exit: acks happen in the writer after results flush, and
    the read cursor rewinds to the last processed batch."""
    broker = InMemoryBroker()
    model_path = _tiny_classifier(tmp_path)
    serving = ClusterServing(
        ClusterServingHelper(model_path=model_path, batch_size=4,
                             data_shape=(4, 4, 1),
                             log_dir=str(tmp_path / "logs")),
        broker=broker)
    inq = InputQueue(broker=broker)
    for i in range(12):  # 3 batches available, run stops after 1
        inq.enqueue_image(f"u{i}", np.zeros((4, 4, 1), np.float32))
    assert serving.run(max_records=4) == 4
    # the 8 unserved records are still in the stream, and a second run
    # serves exactly them
    assert serving.run(max_records=8) == 8
    assert len(OutputQueue(broker=broker).dequeue()) == 12
    assert broker.xlen("image_stream") == 0  # everything acked in the end


def test_pipelined_idle_writer_stays_healthy(tmp_path):
    """An idle pipelined server must keep /healthz green: reader, loop
    AND writer all beat while there is no traffic."""
    from analytics_zoo_tpu.metrics import get_health

    broker = InMemoryBroker()
    model_path = _tiny_classifier(tmp_path)
    serving = ClusterServing(
        ClusterServingHelper(model_path=model_path, batch_size=4,
                             data_shape=(4, 4, 1),
                             log_dir=str(tmp_path / "logs")),
        broker=broker).start(idle_timeout=30.0)
    try:
        time.sleep(1.5)  # idle, past the writer's 0.5s poll interval
        comps = get_health().status()["components"]
        for name in ("serving_loop", "serving_reader", "serving_writer"):
            assert name in comps, comps
            assert comps[name]["age_seconds"] < 1.0, (name, comps[name])
    finally:
        serving.stop()
