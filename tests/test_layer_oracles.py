"""Independent-oracle tests for the public keras layers that previously
had none (VERDICT r03 weak #5) — torch (CPU) or numpy math is the oracle,
the analogue of the reference's per-layer KerasBaseSpec comparisons
(zoo/src/test/.../keras/layers/*Spec.scala; SURVEY.md §4).  Coverage is
ENFORCED by test_layer_oracle_enforcement.py via tests/oracle_registry.py:
every public layer must appear there with a real test."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from test_layers import apply_layer


def _r(shape, seed=0, scale=1.0):
    return (np.random.default_rng(seed).normal(size=shape) * scale).astype(
        np.float32)


def _nhwc_to_nchw(x):
    return np.moveaxis(x, -1, 1)


def _nchw_to_nhwc(x):
    return np.moveaxis(x, 1, -1)


# ---------------------------------------------------------------------------
# core
# ---------------------------------------------------------------------------


def test_activation():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import Activation

    x = _r((3, 7), 0)
    for name, tfn in [("relu", torch.relu), ("tanh", torch.tanh),
                      ("sigmoid", torch.sigmoid),
                      ("softmax", lambda t: torch.softmax(t, -1))]:
        out, _ = apply_layer(Activation(name), x)
        np.testing.assert_allclose(
            out, tfn(torch.from_numpy(x)).numpy(), rtol=1e-5, atol=1e-6,
            err_msg=name)


def test_dropout():
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dropout

    x = np.ones((64, 64), np.float32)
    layer = Dropout(0.4)
    ev, _ = apply_layer(layer, x, training=False)
    np.testing.assert_array_equal(ev, x)  # inference = identity
    tr, _ = apply_layer(layer, x, training=True, rng=jax.random.PRNGKey(1))
    zeros = float((tr == 0).mean())
    assert abs(zeros - 0.4) < 0.05  # drop rate
    kept = tr[tr != 0]
    np.testing.assert_allclose(kept, 1.0 / 0.6, rtol=1e-5)  # inverted scale


def test_flatten():
    from analytics_zoo_tpu.pipeline.api.keras.layers import Flatten

    x = _r((2, 3, 4, 5), 1)
    out, _ = apply_layer(Flatten(), x)
    np.testing.assert_array_equal(out, x.reshape(2, -1))


def test_reshape():
    from analytics_zoo_tpu.pipeline.api.keras.layers import Reshape

    x = _r((2, 3, 8), 2)
    out, _ = apply_layer(Reshape((4, 6)), x)
    np.testing.assert_array_equal(out, x.reshape(2, 4, 6))
    out, _ = apply_layer(Reshape((-1, 2)), x)
    np.testing.assert_array_equal(out, x.reshape(2, 12, 2))


def test_permute():
    from analytics_zoo_tpu.pipeline.api.keras.layers import Permute

    x = _r((2, 3, 4, 5), 3)
    out, _ = apply_layer(Permute((3, 1, 2)), x)
    np.testing.assert_array_equal(out, np.transpose(x, (0, 3, 1, 2)))


def test_repeat_vector():
    from analytics_zoo_tpu.pipeline.api.keras.layers import RepeatVector

    x = _r((2, 5), 4)
    out, _ = apply_layer(RepeatVector(3), x)
    np.testing.assert_array_equal(out, np.repeat(x[:, None, :], 3, 1))


def test_masking():
    from analytics_zoo_tpu.pipeline.api.keras.layers import Masking

    x = _r((2, 4, 3), 5)
    x[0, 1] = 7.0
    x[1, 3] = 7.0
    out, _ = apply_layer(Masking(7.0), x)
    ref = x.copy()
    ref[0, 1] = 0.0
    ref[1, 3] = 0.0
    np.testing.assert_array_equal(out, ref)


def test_highway():
    from analytics_zoo_tpu.pipeline.api.keras.layers import Highway

    x = _r((4, 6), 6)
    out, params = apply_layer(Highway(activation="tanh"), x)
    h = np.tanh(x @ np.asarray(params["kernel"]) + np.asarray(
        params["bias"]))
    t = 1.0 / (1.0 + np.exp(-(x @ np.asarray(params["gate_kernel"])
                              + np.asarray(params["gate_bias"]))))
    np.testing.assert_allclose(out, t * h + (1 - t) * x, rtol=1e-5,
                               atol=1e-6)


def test_identity_and_input():
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Identity,
        Input,
        InputLayer,
    )

    x = _r((3, 4), 7)
    out, _ = apply_layer(Identity(), x)
    np.testing.assert_array_equal(out, x)
    out, _ = apply_layer(InputLayer(input_shape=(4,)), x)
    np.testing.assert_array_equal(out, x)
    var = Input(shape=(4,))  # graph entry point: symbolic variable
    assert tuple(var.shape)[1:] == (4,)


def test_base_layer_contract():
    """The Layer base class contract: build-once, add_weight -> init_params
    materialization, apply() routing, output-shape inference."""
    from analytics_zoo_tpu.pipeline.api.keras.engine import Layer

    class Affine(Layer):
        def build(self, input_shape):
            self.add_weight("w", (int(input_shape[-1]),), "one")

        def call(self, params, inputs, state=None, training=False,
                 rng=None):
            return inputs * params["w"]

    layer = Affine(input_shape=(5,))
    layer.ensure_built((5,))
    assert layer.built
    params = layer.init_params(jax.random.PRNGKey(0))
    assert params["w"].shape == (5,)
    np.testing.assert_allclose(np.asarray(params["w"]), 1.0)
    x = _r((2, 5), 8)
    out, _ = layer.apply(params, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), x, rtol=1e-6)
    assert layer.compute_output_shape((None, 5)) == (None, 5)


def test_gaussian_noise():
    from analytics_zoo_tpu.pipeline.api.keras.layers import GaussianNoise

    x = np.zeros((200, 200), np.float32)
    layer = GaussianNoise(0.5)
    ev, _ = apply_layer(layer, x, training=False)
    np.testing.assert_array_equal(ev, x)
    tr, _ = apply_layer(layer, x, training=True, rng=jax.random.PRNGKey(2))
    assert abs(float(tr.std()) - 0.5) < 0.01
    assert abs(float(tr.mean())) < 0.01


def test_gaussian_dropout():
    from analytics_zoo_tpu.pipeline.api.keras.layers import GaussianDropout

    x = np.ones((200, 200), np.float32)
    layer = GaussianDropout(0.3)
    ev, _ = apply_layer(layer, x, training=False)
    np.testing.assert_array_equal(ev, x)
    tr, _ = apply_layer(layer, x, training=True, rng=jax.random.PRNGKey(3))
    assert abs(float(tr.mean()) - 1.0) < 0.01  # multiplicative, mean 1
    want_std = np.sqrt(0.3 / 0.7)
    assert abs(float(tr.std()) - want_std) < 0.02


def test_spatial_dropout_1d_2d():
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        SpatialDropout1D,
        SpatialDropout2D,
    )

    x1 = np.ones((8, 16, 32), np.float32)
    tr, _ = apply_layer(SpatialDropout1D(0.5), x1, training=True,
                        rng=jax.random.PRNGKey(4))
    # whole (sample, channel) maps are either all-zero or all-scaled
    per_map = tr.reshape(8, 16, 32)
    for b in range(8):
        for c in range(32):
            col = per_map[b, :, c]
            assert (col == 0).all() or np.allclose(col, 2.0), (b, c)
    x2 = np.ones((4, 5, 6, 8), np.float32)
    tr2, _ = apply_layer(SpatialDropout2D(0.5), x2, training=True,
                         rng=jax.random.PRNGKey(5))
    flat = tr2.reshape(4, -1, 8)
    for b in range(4):
        for c in range(8):
            col = flat[b, :, c]
            assert (col == 0).all() or np.allclose(col, 2.0), (b, c)


# ---------------------------------------------------------------------------
# conv
# ---------------------------------------------------------------------------


def _conv1d_ref(x, params, stride=1, dilation=1, pad=0):
    import torch

    w = np.asarray(params["kernel"])  # (k, in, out)
    conv = torch.nn.Conv1d(w.shape[1], w.shape[2], w.shape[0],
                           stride=stride, dilation=dilation, padding=pad)
    with torch.no_grad():
        conv.weight.copy_(torch.from_numpy(np.transpose(w, (2, 1, 0))))
        conv.bias.copy_(torch.from_numpy(np.asarray(params["bias"])))
        ref = conv(torch.from_numpy(np.transpose(x, (0, 2, 1)))).numpy()
    return np.transpose(ref, (0, 2, 1))


def test_conv1d_vs_torch():
    from analytics_zoo_tpu.pipeline.api.keras.layers import Convolution1D

    x = _r((2, 12, 3), 10)
    out, params = apply_layer(Convolution1D(5, 4, subsample_length=2), x)
    np.testing.assert_allclose(out, _conv1d_ref(x, params, stride=2),
                               rtol=1e-4, atol=1e-5)


def test_atrous_conv1d_vs_torch():
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        AtrousConvolution1D,
    )

    x = _r((2, 16, 3), 11)
    out, params = apply_layer(AtrousConvolution1D(4, 3, atrous_rate=2), x)
    np.testing.assert_allclose(out, _conv1d_ref(x, params, dilation=2),
                               rtol=1e-4, atol=1e-5)


def test_atrous_conv2d_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        AtrousConvolution2D,
    )

    x = _r((2, 10, 10, 3), 12)
    out, params = apply_layer(AtrousConvolution2D(4, 3, 3,
                                                  atrous_rate=(2, 2)), x)
    w = np.asarray(params["kernel"])  # (kh, kw, in, out)
    conv = torch.nn.Conv2d(3, 4, 3, dilation=2)
    with torch.no_grad():
        conv.weight.copy_(torch.from_numpy(np.transpose(w, (3, 2, 0, 1))))
        conv.bias.copy_(torch.from_numpy(np.asarray(params["bias"])))
        ref = conv(torch.from_numpy(_nhwc_to_nchw(x))).numpy()
    np.testing.assert_allclose(out, _nchw_to_nhwc(ref), rtol=1e-4,
                               atol=1e-5)


def test_conv3d_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import Convolution3D

    x = _r((2, 6, 7, 8, 2), 13)
    out, params = apply_layer(
        Convolution3D(3, 3, 3, 3, subsample=(2, 1, 2)), x)
    w = np.asarray(params["kernel"])  # (kd, kh, kw, in, out)
    conv = torch.nn.Conv3d(2, 3, 3, stride=(2, 1, 2))
    with torch.no_grad():
        conv.weight.copy_(torch.from_numpy(
            np.transpose(w, (4, 3, 0, 1, 2))))
        conv.bias.copy_(torch.from_numpy(np.asarray(params["bias"])))
        ref = conv(torch.from_numpy(_nhwc_to_nchw(x))).numpy()
    np.testing.assert_allclose(out, _nchw_to_nhwc(ref), rtol=1e-4,
                               atol=1e-4)


def test_conv2d_transpose_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import Deconvolution2D

    x = _r((2, 5, 5, 3), 14)
    out, params = apply_layer(Deconvolution2D(4, 3, 3, subsample=(2, 2)), x)
    w = np.asarray(params["kernel"])  # (kh, kw, in, out)
    deconv = torch.nn.ConvTranspose2d(3, 4, 3, stride=2)
    with torch.no_grad():
        # lax.conv_transpose keeps forward-conv kernel orientation;
        # torch's transposed conv flips spatially -> flip to align
        deconv.weight.copy_(torch.from_numpy(
            np.transpose(w[::-1, ::-1].copy(), (2, 3, 0, 1))))
        deconv.bias.copy_(torch.from_numpy(np.asarray(params["bias"])))
        ref = deconv(torch.from_numpy(_nhwc_to_nchw(x))).numpy()
    np.testing.assert_allclose(out, _nchw_to_nhwc(ref), rtol=1e-4,
                               atol=1e-5)


def test_separable_conv2d_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        SeparableConvolution2D,
    )

    x = _r((2, 9, 9, 3), 15)
    layer = SeparableConvolution2D(6, 3, 3, depth_multiplier=2)
    out, params = apply_layer(layer, x)
    dw = np.asarray(params["depthwise_kernel"])  # (kh, kw, 1, in*dm)
    pw = np.asarray(params["pointwise_kernel"])  # (1, 1, in*dm, out)
    depth = torch.nn.Conv2d(3, 6, 3, groups=3, bias=False)
    point = torch.nn.Conv2d(6, 6, 1)
    with torch.no_grad():
        # jax depthwise kernel (kh, kw, 1, in*dm) laid out channel-major:
        # output channel c*dm+m <- input channel c
        wd = np.transpose(dw[:, :, 0, :], (2, 0, 1))[:, None, :, :]
        depth.weight.copy_(torch.from_numpy(wd))
        point.weight.copy_(torch.from_numpy(
            np.transpose(pw[0, 0], (1, 0))[:, :, None, None].copy()))
        point.bias.copy_(torch.from_numpy(np.asarray(params["bias"])))
        ref = point(depth(torch.from_numpy(_nhwc_to_nchw(x)))).numpy()
    np.testing.assert_allclose(out, _nchw_to_nhwc(ref), rtol=1e-4,
                               atol=1e-5)


def test_depthwise_conv2d_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        DepthwiseConvolution2D,
    )

    x = _r((2, 9, 9, 3), 16)
    layer = DepthwiseConvolution2D(3, 3, depth_multiplier=2,
                                   subsample=(2, 2))
    _, params = apply_layer(layer, x)
    # non-zero bias so the bias path is actually exercised (the default
    # init is zeros, which would compare vacuously)
    params = dict(params, bias=_r((6,), 17))
    out, _ = apply_layer(layer, x, params=params)
    dw = np.asarray(params["depthwise_kernel"])  # (kh, kw, 1, in*dm)
    depth = torch.nn.Conv2d(3, 6, 3, stride=2, groups=3)
    with torch.no_grad():
        wd = np.transpose(dw[:, :, 0, :], (2, 0, 1))[:, None, :, :]
        depth.weight.copy_(torch.from_numpy(wd))
        depth.bias.copy_(torch.from_numpy(np.asarray(params["bias"])))
        ref = depth(torch.from_numpy(_nhwc_to_nchw(x))).numpy()
    np.testing.assert_allclose(out, _nchw_to_nhwc(ref), rtol=1e-4,
                               atol=1e-5)


def test_locally_connected_1d_vs_manual():
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        LocallyConnected1D,
    )

    x = _r((2, 10, 3), 16)
    layer = LocallyConnected1D(4, 3, subsample_length=2)
    out, params = apply_layer(layer, x)
    k = np.asarray(params["kernel"])   # (out_len, fl*in, nb)
    b = np.asarray(params["bias"])
    out_len = (10 - 3) // 2 + 1
    ref = np.zeros((2, out_len, 4), np.float32)
    for pos in range(out_len):
        patch = x[:, pos * 2:pos * 2 + 3, :].reshape(2, -1)
        ref[:, pos] = patch @ k[pos] + b[pos]
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_cropping():
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Cropping1D,
        Cropping2D,
        Cropping3D,
    )

    x1 = _r((2, 10, 3), 17)
    out, _ = apply_layer(Cropping1D((2, 3)), x1)
    np.testing.assert_array_equal(out, x1[:, 2:-3])
    x2 = _r((2, 8, 9, 3), 18)
    out, _ = apply_layer(Cropping2D(((1, 2), (3, 1))), x2)
    np.testing.assert_array_equal(out, x2[:, 1:-2, 3:-1])
    x3 = _r((2, 6, 7, 8, 2), 19)
    out, _ = apply_layer(Cropping3D(((1, 1), (2, 1), (0, 3))), x3)
    np.testing.assert_array_equal(out, x3[:, 1:-1, 2:-1, 0:-3])


def test_zero_padding():
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        ZeroPadding1D,
        ZeroPadding2D,
        ZeroPadding3D,
    )

    x1 = _r((2, 5, 3), 20)
    out, _ = apply_layer(ZeroPadding1D(2), x1)
    np.testing.assert_array_equal(
        out, np.pad(x1, ((0, 0), (2, 2), (0, 0))))
    x2 = _r((2, 4, 5, 3), 21)
    out, _ = apply_layer(ZeroPadding2D(((1, 2), (0, 3))), x2)
    np.testing.assert_array_equal(
        out, np.pad(x2, ((0, 0), (1, 2), (0, 3), (0, 0))))
    x3 = _r((2, 3, 4, 5, 2), 22)
    out, _ = apply_layer(ZeroPadding3D(1), x3)
    np.testing.assert_array_equal(
        out, np.pad(x3, ((0, 0), (1, 1), (1, 1), (1, 1), (0, 0))))


def test_upsampling_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        UpSampling1D,
        UpSampling2D,
        UpSampling3D,
    )

    x1 = _r((2, 5, 3), 23)
    out, _ = apply_layer(UpSampling1D(3), x1)
    ref = torch.nn.functional.interpolate(
        torch.from_numpy(np.transpose(x1, (0, 2, 1))), scale_factor=3,
        mode="nearest").numpy()
    np.testing.assert_allclose(out, np.transpose(ref, (0, 2, 1)))
    x2 = _r((2, 4, 5, 3), 24)
    out, _ = apply_layer(UpSampling2D((2, 3)), x2)
    ref = torch.nn.functional.interpolate(
        torch.from_numpy(_nhwc_to_nchw(x2)), scale_factor=(2, 3),
        mode="nearest").numpy()
    np.testing.assert_allclose(out, _nchw_to_nhwc(ref))
    x3 = _r((1, 3, 4, 2, 2), 25)
    out, _ = apply_layer(UpSampling3D(2), x3)
    ref = torch.nn.functional.interpolate(
        torch.from_numpy(_nhwc_to_nchw(x3)), scale_factor=2,
        mode="nearest").numpy()
    np.testing.assert_allclose(out, _nchw_to_nhwc(ref))


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------


def test_pooling_1d_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        AveragePooling1D,
        MaxPooling1D,
    )

    x = _r((2, 12, 3), 26)
    xt = torch.from_numpy(np.transpose(x, (0, 2, 1)))
    out, _ = apply_layer(MaxPooling1D(3, stride=2), x)
    ref = torch.nn.functional.max_pool1d(xt, 3, stride=2).numpy()
    np.testing.assert_allclose(out, np.transpose(ref, (0, 2, 1)),
                               rtol=1e-6)
    out, _ = apply_layer(AveragePooling1D(3, stride=2), x)
    ref = torch.nn.functional.avg_pool1d(xt, 3, stride=2).numpy()
    np.testing.assert_allclose(out, np.transpose(ref, (0, 2, 1)),
                               rtol=1e-5, atol=1e-6)


def test_avgpool2d_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        AveragePooling2D,
    )

    x = _r((2, 9, 9, 3), 27)
    out, _ = apply_layer(AveragePooling2D((3, 3), strides=(2, 2)), x)
    ref = torch.nn.functional.avg_pool2d(
        torch.from_numpy(_nhwc_to_nchw(x)), 3, stride=2).numpy()
    np.testing.assert_allclose(out, _nchw_to_nhwc(ref), rtol=1e-5,
                               atol=1e-6)


def test_pooling_3d_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        AveragePooling3D,
        MaxPooling3D,
    )

    x = _r((2, 6, 6, 6, 2), 28)
    xt = torch.from_numpy(_nhwc_to_nchw(x))
    out, _ = apply_layer(MaxPooling3D(2), x)
    ref = torch.nn.functional.max_pool3d(xt, 2).numpy()
    np.testing.assert_allclose(out, _nchw_to_nhwc(ref), rtol=1e-6)
    out, _ = apply_layer(AveragePooling3D(2), x)
    ref = torch.nn.functional.avg_pool3d(xt, 2).numpy()
    np.testing.assert_allclose(out, _nchw_to_nhwc(ref), rtol=1e-5,
                               atol=1e-6)


def test_global_pooling():
    from analytics_zoo_tpu.pipeline.api.keras import layers as L

    x1, x2, x3 = _r((2, 5, 3), 29), _r((2, 4, 5, 3), 30), \
        _r((2, 3, 4, 5, 2), 31)
    for layer, x, ref in [
        (L.GlobalMaxPooling1D(), x1, x1.max(1)),
        (L.GlobalAveragePooling1D(), x1, x1.mean(1)),
        (L.GlobalMaxPooling2D(), x2, x2.max((1, 2))),
        (L.GlobalAveragePooling2D(), x2, x2.mean((1, 2))),
        (L.GlobalMaxPooling3D(), x3, x3.max((1, 2, 3))),
        (L.GlobalAveragePooling3D(), x3, x3.mean((1, 2, 3))),
    ]:
        out, _ = apply_layer(layer, x)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6,
                                   err_msg=type(layer).__name__)


# ---------------------------------------------------------------------------
# recurrent
# ---------------------------------------------------------------------------


def test_gru_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import GRU

    b, t, f, u = 3, 6, 5, 4
    x = _r((b, t, f), 32)
    layer = GRU(u, activation="tanh", inner_activation="sigmoid",
                return_sequences=True)
    out, params = apply_layer(layer, x)

    ref_gru = torch.nn.GRU(f, u, batch_first=True)
    k = np.asarray(params["kernel"])            # (f, 3u) order z|r|h
    rk = np.asarray(params["recurrent_kernel"])  # (u, 3u) order z|r|h
    bias = np.asarray(params["bias"])           # (3u,)  order z|r|h

    def zrh_to_rzn(w):  # (in, 3u) -> torch rows (3u, in) order r|z|n
        z, r, h = np.split(w, 3, axis=-1)
        return np.concatenate([r, z, h], axis=-1).T

    with torch.no_grad():
        ref_gru.weight_ih_l0.copy_(torch.from_numpy(zrh_to_rzn(k).copy()))
        ref_gru.weight_hh_l0.copy_(torch.from_numpy(zrh_to_rzn(rk).copy()))
        # ours adds bias outside the reset gate product (hh = act(xh + b_h
        # + r*hz)); torch puts b_hn INSIDE r*(...) — so all bias goes to
        # b_ih and b_hh stays 0, which makes the two forms identical
        z, r, h = np.split(bias, 3)
        ref_gru.bias_ih_l0.copy_(torch.from_numpy(
            np.concatenate([r, z, h]).copy()))
        ref_gru.bias_hh_l0.zero_()
        ref, _ = ref_gru(torch.from_numpy(x))
    np.testing.assert_allclose(out, ref.numpy(), rtol=1e-4, atol=1e-5)


def test_simple_rnn_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import SimpleRNN

    b, t, f, u = 2, 5, 4, 3
    x = _r((b, t, f), 33)
    layer = SimpleRNN(u, activation="tanh", return_sequences=True)
    out, params = apply_layer(layer, x)
    rnn = torch.nn.RNN(f, u, batch_first=True, nonlinearity="tanh")
    with torch.no_grad():
        rnn.weight_ih_l0.copy_(torch.from_numpy(
            np.asarray(params["kernel"]).T))
        rnn.weight_hh_l0.copy_(torch.from_numpy(
            np.asarray(params["recurrent_kernel"]).T))
        rnn.bias_ih_l0.copy_(torch.from_numpy(np.asarray(params["bias"])))
        rnn.bias_hh_l0.zero_()
        ref, _ = rnn(torch.from_numpy(x))
    np.testing.assert_allclose(out, ref.numpy(), rtol=1e-4, atol=1e-5)


def _np_sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _np_lstm(x, k, rk, b):
    """Numpy LSTM oracle, gate order i,f,g,o; returns (B, T, u) states."""
    bsz, t, _ = x.shape
    u = rk.shape[0]
    h = np.zeros((bsz, u), np.float32)
    c = np.zeros((bsz, u), np.float32)
    seq = []
    for step in range(t):
        z = x[:, step] @ k + h @ rk + b
        i, f, g, o = np.split(z, 4, axis=-1)
        i, f, o = _np_sigmoid(i), _np_sigmoid(f), _np_sigmoid(o)
        g = np.tanh(g)
        c = f * c + i * g
        h = o * np.tanh(c)
        seq.append(h)
    return np.stack(seq, 1)


def test_bidirectional_modes_vs_manual():
    """All four merge modes vs a NUMPY bidirectional LSTM oracle (the
    previous test only checked the concat shape)."""
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        LSTM,
        Bidirectional,
    )

    x = _r((2, 5, 3), 34)
    for mode in ("concat", "sum", "mul", "ave"):
        layer = Bidirectional(
            LSTM(4, activation="tanh", inner_activation="sigmoid",
                 return_sequences=True), merge_mode=mode)
        out, params = apply_layer(layer, x)
        fwd = _np_lstm(x, np.asarray(params["fwd"]["kernel"]),
                       np.asarray(params["fwd"]["recurrent_kernel"]),
                       np.asarray(params["fwd"]["bias"]))
        bwd = _np_lstm(x[:, ::-1], np.asarray(params["bwd"]["kernel"]),
                       np.asarray(params["bwd"]["recurrent_kernel"]),
                       np.asarray(params["bwd"]["bias"]))[:, ::-1]
        ref = {"concat": np.concatenate([fwd, bwd], -1),
               "sum": fwd + bwd, "mul": fwd * bwd,
               "ave": (fwd + bwd) / 2}[mode]
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5,
                                   err_msg=mode)


def test_time_distributed_conv_vs_manual():
    """TimeDistributed over a CONV layer (the previous test only wrapped
    Dense) vs applying the conv per timestep."""
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Convolution2D,
        TimeDistributed,
    )

    x = _r((2, 3, 8, 8, 2), 35)  # (B, T, H, W, C)
    layer = TimeDistributed(Convolution2D(4, 3, 3))
    out, params = apply_layer(layer, x)
    w = np.asarray(params["inner"]["kernel"])
    conv = torch.nn.Conv2d(2, 4, 3)
    with torch.no_grad():
        conv.weight.copy_(torch.from_numpy(np.transpose(w, (3, 2, 0, 1))))
        conv.bias.copy_(torch.from_numpy(
            np.asarray(params["inner"]["bias"])))
        refs = []
        for step in range(3):
            r = conv(torch.from_numpy(_nhwc_to_nchw(x[:, step]))).numpy()
            refs.append(_nchw_to_nhwc(r))
    np.testing.assert_allclose(out, np.stack(refs, 1), rtol=1e-4,
                               atol=1e-5)


def _np_conv(x, w, stride, rank):
    """Tiny VALID/SAME torch conv helper for the ConvLSTM oracles."""
    import torch

    xt = torch.from_numpy(np.moveaxis(x, -1, 1))
    wt = torch.from_numpy(
        np.transpose(w, (rank + 1, rank) + tuple(range(rank))).copy())
    fn = torch.nn.functional.conv2d if rank == 2 \
        else torch.nn.functional.conv3d
    k = w.shape[0]
    pad = k // 2
    out = fn(xt, wt, stride=stride, padding=pad).numpy()
    return np.moveaxis(out, 1, -1)


def _conv_lstm_oracle(x, params, nb_filter, rank):
    b, t = x.shape[:2]
    k = np.asarray(params["kernel"])
    rk = np.asarray(params["recurrent_kernel"])
    bias = np.asarray(params["bias"])
    h = None
    for step in range(t):
        zx = _np_conv(x[:, step], k, 1, rank)
        if h is None:
            h = np.zeros(zx.shape[:-1] + (nb_filter,), np.float32)
            c = np.zeros_like(h)
        z = zx + _np_conv(h, rk, 1, rank) + bias
        i, f, g, o = np.split(z, 4, axis=-1)
        i, f, o = _np_sigmoid(i), _np_sigmoid(f), _np_sigmoid(o)
        g = np.tanh(g)
        c = f * c + i * g
        h = o * np.tanh(c)
    return h


def test_conv_lstm2d_vs_manual():
    from analytics_zoo_tpu.pipeline.api.keras.layers import ConvLSTM2D

    x = _r((2, 3, 6, 6, 2), 36, scale=0.5)
    layer = ConvLSTM2D(3, 3, inner_activation="sigmoid",
                       border_mode="same")
    out, params = apply_layer(layer, x)
    ref = _conv_lstm_oracle(x, params, 3, 2)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_conv_lstm3d_vs_manual():
    from analytics_zoo_tpu.pipeline.api.keras.layers import ConvLSTM3D

    x = _r((1, 2, 4, 4, 4, 2), 37, scale=0.5)
    layer = ConvLSTM3D(2, 3, inner_activation="sigmoid",
                       border_mode="same")
    out, params = apply_layer(layer, x)
    ref = _conv_lstm_oracle(x, params, 2, 3)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# embedding / normalization
# ---------------------------------------------------------------------------


def test_embedding_vs_numpy():
    from analytics_zoo_tpu.pipeline.api.keras.layers import Embedding

    ids = np.array([[1, 4, 2], [0, 3, 3]], np.int32)
    layer = Embedding(5, 6)
    layer.ensure_built((3,))
    params = layer.init_params(jax.random.PRNGKey(0))
    out, _ = layer.apply(params, jnp.asarray(ids))
    table = np.asarray(params["embeddings"])
    np.testing.assert_allclose(np.asarray(out), table[ids], rtol=1e-6)


def test_sparse_embedding():
    """SparseEmbedding: same lookup semantics; gradient touches ONLY the
    looked-up rows (the reference's sparse-gradient contract)."""
    from analytics_zoo_tpu.pipeline.api.keras.layers import SparseEmbedding

    ids = np.array([[1, 3]], np.int32)
    layer = SparseEmbedding(6, 4)
    layer.ensure_built((2,))
    params = layer.init_params(jax.random.PRNGKey(1))
    out, _ = layer.apply(params, jnp.asarray(ids))
    table = np.asarray(params["embeddings"])
    np.testing.assert_allclose(np.asarray(out), table[ids], rtol=1e-6)

    g = jax.grad(lambda p: jnp.sum(
        layer.apply(p, jnp.asarray(ids))[0]))(params)
    ge = np.asarray(g["embeddings"])
    touched = sorted(set(np.nonzero(ge.any(-1))[0].tolist()))
    assert touched == [1, 3]


def test_batchnorm_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        BatchNormalization,
    )

    x = _r((8, 6, 6, 3), 38)
    layer = BatchNormalization(epsilon=1e-3)
    layer.ensure_built((6, 6, 3))
    params = layer.init_params(jax.random.PRNGKey(2))
    state = layer.init_state()
    out, new_state = layer.apply(params, jnp.asarray(x), state=state,
                                 training=True)
    bn = torch.nn.BatchNorm2d(3, eps=1e-3)
    with torch.no_grad():
        bn.weight.copy_(torch.from_numpy(np.asarray(params["gamma"])))
        bn.bias.copy_(torch.from_numpy(np.asarray(params["beta"])))
    bn.train()
    ref = bn(torch.from_numpy(_nhwc_to_nchw(x))).detach().numpy()
    np.testing.assert_allclose(np.asarray(out), _nchw_to_nhwc(ref),
                               rtol=1e-4, atol=1e-4)
    # eval mode with given moving stats
    mm = np.array([0.3, -0.2, 0.1], np.float32)
    mv = np.array([1.5, 0.7, 2.0], np.float32)
    out_e, _ = layer.apply(params, jnp.asarray(x),
                           state={"moving_mean": jnp.asarray(mm),
                                  "moving_var": jnp.asarray(mv)},
                           training=False)
    with torch.no_grad():
        bn.running_mean.copy_(torch.from_numpy(mm))
        bn.running_var.copy_(torch.from_numpy(mv))
    bn.eval()
    ref_e = bn(torch.from_numpy(_nhwc_to_nchw(x))).detach().numpy()
    np.testing.assert_allclose(np.asarray(out_e), _nchw_to_nhwc(ref_e),
                               rtol=1e-4, atol=1e-4)


def test_layernorm_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        LayerNormalization,
    )

    x = _r((4, 7, 10), 39)
    layer = LayerNormalization()
    out, params = apply_layer(layer, x)
    ln = torch.nn.LayerNorm(10, eps=1e-5)
    with torch.no_grad():
        ln.weight.copy_(torch.from_numpy(np.asarray(params["gamma"])))
        ln.bias.copy_(torch.from_numpy(np.asarray(params["beta"])))
        ref = ln(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_within_channel_lrn():
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        WithinChannelLRN2D,
    )

    x = _r((1, 5, 5, 2), 40)
    size, alpha, beta = 3, 1.5, 0.75
    out, _ = apply_layer(WithinChannelLRN2D(size, alpha, beta), x)
    # numpy oracle: SAME sum of squares over a size x size spatial window
    sq = x ** 2
    padded = np.pad(sq, ((0, 0), (1, 1), (1, 1), (0, 0)))
    summed = np.zeros_like(x)
    for i in range(5):
        for j in range(5):
            summed[:, i, j] = padded[:, i:i + 3, j:j + 3].sum((1, 2))
    ref = x / (1.0 + alpha * summed / (size * size)) ** beta
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# merge / advanced activations / tensor ops
# ---------------------------------------------------------------------------


def test_merge_modes_vs_numpy():
    from analytics_zoo_tpu.pipeline.api.keras.layers import Merge

    a, b = _r((3, 5), 41), _r((3, 5), 42)
    ja, jb = jnp.asarray(a), jnp.asarray(b)

    def run(mode, **kw):
        layer = Merge(mode=mode, **kw)
        out = layer.call({}, [ja, jb])
        return np.asarray(out)

    np.testing.assert_allclose(run("sum"), a + b, rtol=1e-6)
    np.testing.assert_allclose(run("mul"), a * b, rtol=1e-6)
    np.testing.assert_allclose(run("max"), np.maximum(a, b), rtol=1e-6)
    np.testing.assert_allclose(run("min"), np.minimum(a, b), rtol=1e-6)
    np.testing.assert_allclose(run("ave"), (a + b) / 2, rtol=1e-6)
    np.testing.assert_allclose(
        run("concat", concat_axis=-1), np.concatenate([a, b], -1))
    np.testing.assert_allclose(
        run("dot"), (a * b).sum(-1, keepdims=True), rtol=1e-5, atol=1e-5)
    an = a / np.linalg.norm(a, axis=-1, keepdims=True)
    bn = b / np.linalg.norm(b, axis=-1, keepdims=True)
    np.testing.assert_allclose(
        run("cosine"), (an * bn).sum(-1, keepdims=True), rtol=1e-4,
        atol=1e-5)


def test_advanced_activations_vs_torch():
    import torch

    from analytics_zoo_tpu.pipeline.api.keras import layers as L

    x = _r((4, 6), 43, scale=2.0)
    xt = torch.from_numpy(x)
    out, _ = apply_layer(L.LeakyReLU(0.2), x)
    np.testing.assert_allclose(
        out, torch.nn.functional.leaky_relu(xt, 0.2).numpy(), rtol=1e-6)
    out, _ = apply_layer(L.ELU(1.3), x)
    np.testing.assert_allclose(
        out, torch.nn.functional.elu(xt, 1.3).numpy(), rtol=1e-5,
        atol=1e-6)
    out, _ = apply_layer(L.ThresholdedReLU(0.7), x)
    np.testing.assert_allclose(
        out, torch.nn.functional.threshold(xt, 0.7, 0.0).numpy(),
        rtol=1e-6)
    out, params = apply_layer(L.PReLU(), x)
    pr = torch.nn.PReLU(6)
    with torch.no_grad():
        pr.weight.copy_(torch.from_numpy(np.asarray(params["alpha"])))
        ref = pr(xt).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-6)
    out, _ = apply_layer(L.Softmax(), x)
    np.testing.assert_allclose(out, torch.softmax(xt, -1).numpy(),
                               rtol=1e-5, atol=1e-6)


def test_srelu_vs_formula():
    from analytics_zoo_tpu.pipeline.api.keras.layers import SReLU

    x = _r((5, 4), 44, scale=2.0)
    out, params = apply_layer(SReLU(), x)
    tl = np.asarray(params["t_left"])
    al = np.asarray(params["a_left"])
    tr = np.asarray(params["t_right"])
    ar = np.asarray(params["a_right"])
    ref = np.where(x < tl, tl + al * (x - tl),
                   np.where(x > tr, tr + ar * (x - tr), x))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_parametric_softplus_vs_formula():
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        ParametricSoftPlus,
    )

    x = _r((3, 4), 45)
    out, params = apply_layer(ParametricSoftPlus(0.3, 2.0), x)
    a = np.asarray(params["alpha"])
    b = np.asarray(params["beta"])
    ref = a * np.log1p(np.exp(b * x))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_mul_and_scale():
    from analytics_zoo_tpu.pipeline.api.keras.layers import Mul

    x = _r((3, 4), 46)
    out, params = apply_layer(Mul(), x)
    np.testing.assert_allclose(out, x * np.asarray(params["weight"]),
                               rtol=1e-6)


def test_shape_edit_ops():
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Expand,
        ExpandDim,
        Squeeze,
    )

    x = _r((2, 1, 4, 1), 47)
    out, _ = apply_layer(Squeeze((1, 3)), x)
    np.testing.assert_array_equal(out, x.squeeze((1, 3)))
    x2 = _r((2, 4), 48)
    out, _ = apply_layer(ExpandDim(1), x2)
    np.testing.assert_array_equal(out, x2[:, None, :])
    x3 = _r((2, 1, 4), 49)
    out, _ = apply_layer(Expand((3, 4)), x3)
    np.testing.assert_array_equal(out, np.broadcast_to(x3, (2, 3, 4)))


def test_max_reduce():
    from analytics_zoo_tpu.pipeline.api.keras.layers import Max

    x = _r((2, 5, 3), 50)
    out, _ = apply_layer(Max(1), x)
    np.testing.assert_allclose(out, x.max(1), rtol=1e-6)
    out, _ = apply_layer(Max(2, keep_dim=True), x)
    np.testing.assert_allclose(out, x.max(2, keepdims=True), rtol=1e-6)
